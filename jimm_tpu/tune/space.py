"""Per-kernel block-size search spaces with static feasibility pruning.

Candidates that cannot lower (tile-alignment) or cannot fit (VMEM) are
pruned *before* anything is measured, so a sweep never wastes reps on a
config Mosaic would reject. The VMEM model for flash attention mirrors
``ops.flash_attention._per_head_vmem_bytes`` — duplicated here (like the
linter's mesh-axis table) so this module never imports jax; a sync test in
`tests/test_tune.py` keeps the two formulas from drifting.
"""

from __future__ import annotations

from typing import Any, Sequence

__all__ = ["FLASH_BLOCKS", "FP8_MATMUL_BLOCK_M", "FP8_MATMUL_BLOCK_N",
           "INT8_FLASH_BLOCKS", "INT8_MATMUL_BLOCK_M",
           "INT8_MATMUL_BLOCK_N", "LN_BLOCK_ROWS", "RETRIEVAL_BLOCK_N",
           "VMEM_BUDGET", "bias_flash_space", "bias_flash_vmem_bytes",
           "flash_space", "flash_vmem_bytes", "fp8_matmul_space",
           "fp8_matmul_vmem_bytes", "int8_flash_bwd_vmem_bytes",
           "int8_flash_space", "int8_flash_vmem_bytes", "int8_matmul_space",
           "int8_matmul_vmem_bytes", "ivf_space", "ivf_vmem_bytes",
           "kernel_space", "ln_space",
           "ln_vmem_bytes", "masked_flash_space", "masked_flash_vmem_bytes",
           "retrieval_space", "retrieval_vmem_bytes", "ring_space",
           "ring_vmem_bytes", "sigmoid_space", "sigmoid_vmem_bytes",
           "tier_space"]

_LANES = 128
_SUBLANES = 8
_INT8_SUBLANES = 32

#: mirrors ops.flash_attention._VMEM_BUDGET (sync-tested)
VMEM_BUDGET = 8 * 1024 * 1024

#: the flash grid tiles Mosaic handles well: lane-aligned powers of two.
#: `_pick_block` in the kernel clamps to the padded sequence, so candidates
#: larger than the (128-padded) sequence are redundant and pruned here.
FLASH_BLOCKS = (128, 256, 512)

#: LN row-block candidates — sublane-aligned, from minimum tile to the
#: point where the (block_rows, features) fp32 working set dominates VMEM
LN_BLOCK_ROWS = (8, 16, 32, 64, 128, 256, 512)

#: corpus-block candidates for the streaming top-k scan — lane-aligned so
#: the (block_n, D) corpus tile and (B, block_n) score tile both land on
#: 128-lane boundaries; larger blocks amortize the per-step top_k merge,
#: smaller ones cap the resident score tile
RETRIEVAL_BLOCK_N = (128, 256, 512, 1024, 2048, 4096)


def _ceil_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def flash_vmem_bytes(block_q: int, block_k: int, d: int) -> int:
    """jax-free mirror of ``_per_head_vmem_bytes`` (see module docstring)."""
    return (
        3 * block_k * d * 2
        + 2 * block_q * d * 2
        + 2 * block_q * _LANES * 4
        + 2 * block_q * d * 4
        + block_q * block_k * 6)


def _attn_space(shapes: Sequence[Sequence[int]], vmem_fn) -> list[dict]:
    """Shared ``{"block_q", "block_k"}`` pruning for the attention family:
    same lane-aligned candidates, variant-specific VMEM formula."""
    q, k = shapes[0], shapes[1]
    sq, sk, d = int(q[-3]), int(k[-3]), int(q[-1])
    out = []
    for bq in FLASH_BLOCKS:
        if bq > _ceil_to(sq, _LANES):
            continue
        for bk in FLASH_BLOCKS:
            if bk > _ceil_to(sk, _LANES):
                continue
            if vmem_fn(bq, bk, d) > VMEM_BUDGET:
                continue
            out.append({"block_q": bq, "block_k": bk})
    return out or [{"block_q": FLASH_BLOCKS[0], "block_k": FLASH_BLOCKS[0]}]


def flash_space(shapes: Sequence[Sequence[int]],
                dtypes: Sequence[Any] = ()) -> list[dict]:
    """Feasible ``{"block_q", "block_k"}`` candidates for q/k/v shapes
    ``(B, S, N, D)`` (or head-flattened ``(BN, S, D)``)."""
    return _attn_space(shapes, flash_vmem_bytes)


def masked_flash_vmem_bytes(block_q: int, block_k: int, d: int) -> int:
    """Softmax flash + the additive key-padding row: one f32 ``(1, bk)``
    mask tile per grid cell (mirrors ``has_mask`` in
    ``_per_head_vmem_bytes``)."""
    return flash_vmem_bytes(block_q, block_k, d) + block_k * 4


def masked_flash_space(shapes: Sequence[Sequence[int]],
                       dtypes: Sequence[Any] = ()) -> list[dict]:
    """Candidates for key-padding-mask flash (NaFlex / MAP pooling)."""
    return _attn_space(shapes, masked_flash_vmem_bytes)


def bias_flash_vmem_bytes(block_q: int, block_k: int, d: int) -> int:
    """Softmax flash + two f32 ``(bq, bk)`` tiles: the resident bias
    in-tile and the dbias scratch/out tile of the backward's accumulation
    kernel (mirrors ``has_bias`` in ``_per_head_vmem_bytes``)."""
    return flash_vmem_bytes(block_q, block_k, d) + 2 * block_q * block_k * 4


def bias_flash_space(shapes: Sequence[Sequence[int]],
                     dtypes: Sequence[Any] = ()) -> list[dict]:
    """Candidates for additive-bias flash (relative-position style)."""
    return _attn_space(shapes, bias_flash_vmem_bytes)


def sigmoid_vmem_bytes(block_q: int, block_k: int, d: int) -> int:
    """Sigmoid attention keeps no online m/l statistics (no row
    normalizer), dropping the two ``(bq, 128)`` f32 stat tiles; the
    optional key-padding row stays in the budget because serving routes
    padded batches through it (mirrors ``kind='sigmoid', has_mask=True``
    in ``_per_head_vmem_bytes``)."""
    return (flash_vmem_bytes(block_q, block_k, d)
            - 2 * block_q * _LANES * 4
            + block_k * 4)


def sigmoid_space(shapes: Sequence[Sequence[int]],
                  dtypes: Sequence[Any] = ()) -> list[dict]:
    """Candidates for sigmoid attention (no-normalizer online loop)."""
    return _attn_space(shapes, sigmoid_vmem_bytes)


def ring_vmem_bytes(block_q: int, block_k: int, d: int) -> int:
    """Per-hop cell of the sequence-parallel ring
    (`parallel/seqpar.py`): each hop IS a masked softmax flash call over
    the local chunk (the traveling key-padding row resident like the
    single-chip masked variant), so the hop's VMEM model is the masked
    formula — the ring adds HBM-resident chunk buffers, not VMEM
    (mirrors ``kind='softmax', has_mask=True`` in
    ``_per_head_vmem_bytes``; sync-tested)."""
    return masked_flash_vmem_bytes(block_q, block_k, d)


def ring_space(shapes: Sequence[Sequence[int]],
               dtypes: Sequence[Any] = ()) -> list[dict]:
    """Feasible ``{"block_q", "block_k"}`` candidates for ONE ring hop.
    ``shapes`` are the per-device LOCAL chunk shapes ``(B, S/p, N, D)`` —
    the key the wrapper resolves under (`seqpar._resolve_ring_blocks`):
    the hop kernel never sees more than a chunk, so candidates larger
    than the 128-padded chunk are redundant exactly like the single-chip
    clamp."""
    return _attn_space(shapes, ring_vmem_bytes)


def ln_vmem_bytes(block_rows: int, features: int) -> int:
    """Coarse upper bound on one LN grid cell's resident fp32 working set:
    x/do/dx tiles plus temporaries at the 128-padded feature width, and the
    two (8, features) partial blocks."""
    fp = _ceil_to(features, _LANES)
    return 6 * block_rows * fp * 4 + 2 * _SUBLANES * fp * 4


def ln_space(shapes: Sequence[Sequence[int]],
             dtypes: Sequence[Any] = ()) -> list[dict]:
    """Feasible ``{"block_rows"}`` candidates for an ``(rows, features)``
    LayerNorm input."""
    rows, features = int(shapes[0][-2]), int(shapes[0][-1])
    out = []
    for br in LN_BLOCK_ROWS:
        if br > _ceil_to(rows, _SUBLANES):
            continue
        if ln_vmem_bytes(br, features) > VMEM_BUDGET:
            continue
        out.append({"block_rows": br})
    return out or [{"block_rows": LN_BLOCK_ROWS[0]}]


def retrieval_vmem_bytes(block_n: int, dim: int, batch: int = 64) -> int:
    """Coarse resident working set of one streaming top-k scan step: the
    f32-upcast corpus block, the query tile, and the (batch, block_n)
    score tile — doubled for the pipeline's in-flight block."""
    fp_d = _ceil_to(dim, _LANES)
    return 2 * (block_n * fp_d * 4 + batch * fp_d * 4
                + batch * block_n * 4)


def retrieval_space(shapes: Sequence[Sequence[int]],
                    dtypes: Sequence[Any] = ()) -> list[dict]:
    """Feasible ``{"block_n"}`` candidates for a top-k workload shaped
    ``[(batch, dim), (n_rows, dim)]``. Blocks past the 128-padded corpus
    are redundant (one padded block already covers every row)."""
    batch, dim = int(shapes[0][-2]), int(shapes[0][-1])
    n_rows = int(shapes[-1][-2])
    out = []
    for bn in RETRIEVAL_BLOCK_N:
        if bn > _ceil_to(max(n_rows, 1), _LANES) and out:
            continue
        if retrieval_vmem_bytes(bn, dim, batch) > VMEM_BUDGET:
            continue
        out.append({"block_n": bn})
    return out or [{"block_n": RETRIEVAL_BLOCK_N[0]}]


def ivf_vmem_bytes(block_n: int, dim: int, batch: int = 64) -> int:
    """Coarse resident working set of one IVF rescore step. Unlike the
    exact scan — one shared block per step — the IVF scan gathers *each
    query its own* candidate block, so the f32-upcast block tile and the
    id row are batch-multiplied: feasible blocks shrink as the query
    bucket grows. Doubled for the pipeline's in-flight gather."""
    fp_d = _ceil_to(dim, _LANES)
    return 2 * batch * (block_n * fp_d * 4   # gathered (B, bn, D) blocks
                        + block_n * 4        # (B, bn) scores
                        + block_n * 4        # (B, bn) row-id gather
                        + fp_d * 4)          # query tile


def ivf_space(shapes: Sequence[Sequence[int]],
              dtypes: Sequence[Any] = ()) -> list[dict]:
    """Feasible ``{"block_n"}`` candidates for an IVF workload shaped
    ``[(batch, dim), (n_rows, dim)]``. Same candidate grid as the exact
    scan; the batch-multiplied VMEM model does the pruning. Smaller blocks
    also waste less rescore work (a cluster pads to whole blocks), so the
    feasibility floor returning the smallest block is the safe default."""
    batch, dim = int(shapes[0][-2]), int(shapes[0][-1])
    n_rows = int(shapes[-1][-2])
    out = []
    for bn in RETRIEVAL_BLOCK_N:
        if bn > _ceil_to(max(n_rows, 1), _LANES) and out:
            continue
        if ivf_vmem_bytes(bn, dim, batch) > VMEM_BUDGET:
            continue
        out.append({"block_n": bn})
    return out or [{"block_n": RETRIEVAL_BLOCK_N[0]}]


def tier_space(shapes: Sequence[Sequence[int]],
               dtypes: Sequence[Any] = ()) -> list[dict]:
    """Feasible ``{"block_n"}`` candidates for the tiered searcher's hot
    scan. The device program is the IVF scan plus a probe-selection
    output (a few KiB — below model resolution), so feasibility is the
    IVF model's; what differs is the *preference*: block_n is also the
    hot arena's allocation quantum, so smaller blocks pack more clusters
    per device budget (see ``tune.api._tier_default``)."""
    return ivf_space(shapes, dtypes)


#: int8 matmul grid tiles: rows align to the int8 32-sublane tile, columns
#: to 128 lanes. The wrapper clamps to the padded M/N, so oversize
#: candidates are pruned here as redundant.
INT8_MATMUL_BLOCK_M = (32, 64, 128, 256, 512)
INT8_MATMUL_BLOCK_N = (128, 256, 512)

#: int8 flash q/k blocks share the f32 kernel's lane-aligned candidates
#: (`_pick_block` clamps to the padded sequence the same way)
INT8_FLASH_BLOCKS = (128, 256, 512)


def int8_matmul_vmem_bytes(block_m: int, block_n: int, k: int) -> int:
    """jax-free mirror of ``ops.int8_matmul._per_cell_vmem_bytes``
    (sync-tested): int8 x/w tiles at 128-padded K, lane-broadcast row
    scales, 1-D column scale + bias, int32 acc + f32 epilogue + out."""
    kp = _ceil_to(k, _LANES)
    return (block_m * kp
            + kp * block_n
            + block_m * _LANES * 4
            + 2 * block_n * 4
            + 3 * block_m * block_n * 4)


def int8_matmul_space(shapes: Sequence[Sequence[int]],
                      dtypes: Sequence[Any] = ()) -> list[dict]:
    """Feasible ``{"block_m", "block_n"}`` candidates for an int8 matmul
    shaped ``[(M, K), (K, N)]``. Blocks past the tile-padded M/N are
    redundant (the wrapper clamps); VMEM-infeasible cells are pruned."""
    m, k = int(shapes[0][-2]), int(shapes[0][-1])
    n = int(shapes[1][-1])
    out = []
    for bm in INT8_MATMUL_BLOCK_M:
        if bm > _ceil_to(m, _INT8_SUBLANES):
            continue
        for bn in INT8_MATMUL_BLOCK_N:
            if bn > _ceil_to(n, _LANES):
                continue
            if int8_matmul_vmem_bytes(bm, bn, k) > VMEM_BUDGET:
                continue
            out.append({"block_m": bm, "block_n": bn})
    return out or [{"block_m": INT8_MATMUL_BLOCK_M[0],
                    "block_n": INT8_MATMUL_BLOCK_N[0]}]


#: fp8 matmul grid tiles: same alignment story as int8 (fp8 Mosaic tiles
#: are (32, 128) too), so the candidate grids coincide
FP8_MATMUL_BLOCK_M = (32, 64, 128, 256, 512)
FP8_MATMUL_BLOCK_N = (128, 256, 512)


def fp8_matmul_vmem_bytes(block_m: int, block_n: int, k: int) -> int:
    """jax-free mirror of ``ops.fp8_matmul._per_cell_vmem_bytes``
    (sync-tested): fp8 a/b tiles at 128-padded K, the lane-broadcast
    per-tensor scale, bias, f32 acc + out."""
    kp = _ceil_to(k, _LANES)
    return (block_m * kp
            + kp * block_n
            + _LANES * 4
            + block_n * 4
            + 2 * block_m * block_n * 4)


def fp8_matmul_space(shapes: Sequence[Sequence[int]],
                     dtypes: Sequence[Any] = ()) -> list[dict]:
    """Feasible ``{"block_m", "block_n"}`` candidates for an fp8 matmul
    shaped ``[(M, K), (K, N)]``. Same pruning story as the int8 space:
    blocks past the tile-padded M/N are redundant (the wrapper clamps),
    VMEM-infeasible cells are dropped."""
    m, k = int(shapes[0][-2]), int(shapes[0][-1])
    n = int(shapes[1][-1])
    out = []
    for bm in FP8_MATMUL_BLOCK_M:
        if bm > _ceil_to(m, _INT8_SUBLANES):
            continue
        for bn in FP8_MATMUL_BLOCK_N:
            if bn > _ceil_to(n, _LANES):
                continue
            if fp8_matmul_vmem_bytes(bm, bn, k) > VMEM_BUDGET:
                continue
            out.append({"block_m": bm, "block_n": bn})
    return out or [{"block_m": FP8_MATMUL_BLOCK_M[0],
                    "block_n": FP8_MATMUL_BLOCK_N[0]}]


def int8_flash_vmem_bytes(block_q: int, block_k: int, d: int) -> int:
    """jax-free mirror of ``ops.flash_attention_int8._per_head_vmem_bytes``
    (sync-tested): int8 q/k at the 128-padded head dim, storage-dtype v and
    out, f32 stats/accumulator, lse-layout scale tiles, and the f32 lse
    out row the backward consumes."""
    dp = _ceil_to(d, _LANES)
    return (block_q * dp + block_k * dp
            + 2 * block_k * d * 2
            + block_q * d * 2
            + 2 * block_q * _LANES * 4
            + block_q * d * 4
            + (block_q + block_k) * 4
            + block_q * 4
            + block_q * block_k * 6)


def int8_flash_bwd_vmem_bytes(block_q: int, block_k: int, d: int) -> int:
    """jax-free mirror of
    ``ops.flash_attention_int8._per_head_bwd_vmem_bytes`` (sync-tested):
    the dq / dkv backward cells' shared upper bound — int8 q/k tiles,
    storage-dtype v/do, scale + lse + delta rows, f32 dq and dk/dv
    scratch, and the recomputed s/p/ds f32 temporaries."""
    dp = _ceil_to(d, _LANES)
    return (block_q * dp + block_k * dp
            + block_k * d * 2 + block_q * d * 2
            + (block_q + block_k) * 4
            + 2 * block_q * 4
            + (block_k * dp + block_k * d) * 4
            + block_q * dp * 4
            + 3 * block_q * block_k * 4)


def int8_flash_space(shapes: Sequence[Sequence[int]],
                     dtypes: Sequence[Any] = ()) -> list[dict]:
    """Feasible ``{"block_q", "block_k"}`` candidates for int8 flash
    attention over q/k/v shapes ``(B, S, N, D)`` (or head-flattened).
    Blocks are shared between forward and backward, so a candidate must
    fit both cells' working sets."""
    q, k = shapes[0], shapes[1]
    sq, sk, d = int(q[-3]), int(k[-3]), int(q[-1])
    out = []
    for bq in INT8_FLASH_BLOCKS:
        if bq > _ceil_to(sq, _LANES):
            continue
        for bk in INT8_FLASH_BLOCKS:
            if bk > _ceil_to(sk, _LANES):
                continue
            if max(int8_flash_vmem_bytes(bq, bk, d),
                   int8_flash_bwd_vmem_bytes(bq, bk, d)) > VMEM_BUDGET:
                continue
            out.append({"block_q": bq, "block_k": bk})
    return out or [{"block_q": INT8_FLASH_BLOCKS[0],
                    "block_k": INT8_FLASH_BLOCKS[0]}]


_SPACES = {"flash_attention": flash_space,
           "flash_attention_masked": masked_flash_space,
           "flash_attention_bias": bias_flash_space,
           "sigmoid_attention": sigmoid_space,
           "layer_norm": ln_space,
           "retrieval_topk": retrieval_space,
           "retrieval_ivf": ivf_space,
           "retrieval_tier": tier_space,
           "int8_matmul": int8_matmul_space,
           "fp8_matmul": fp8_matmul_space,
           "flash_attention_int8": int8_flash_space,
           "ring_attention": ring_space}


def kernel_space(kernel: str, shapes: Sequence[Sequence[int]],
                 dtypes: Sequence[Any] = ()) -> list[dict]:
    """Pruned candidate list for ``kernel`` at the given shapes."""
    try:
        fn = _SPACES[kernel]
    except KeyError:
        raise KeyError(f"no search space for kernel {kernel!r}; "
                       f"known: {sorted(_SPACES)}") from None
    return fn(shapes, dtypes)
