"""Perfetto/Chrome-trace timeline export: journal + serve traces + goodput.

Merges four sources into one ``chrome://tracing`` / Perfetto-loadable
JSON object (the `Trace Event Format`_):

- **journal events** — instant ("i") markers on per-subsystem lanes, or
  complete ("X") spans when the record carries a ``dur_s`` payload field;
  the correlation id becomes the event's ``args.cid`` so an incident's
  chain is searchable in the UI.
- **serve request traces** — the engine's ``recent_traces`` ring: each
  request becomes a stack of queue/pad/device/readback spans on its
  replica's lane, placed backwards from the recorded ``done_mono``.
- **goodput buckets** — a final accounter report rendered as consecutive
  per-bucket spans on a synthetic ``goodput`` lane (relative placement:
  buckets are cumulative ledgers, not intervals, so the lane shows
  proportions, anchored at the trace origin).
- **profiler captures** — committed capture metas from the continuous
  profiling ring (``obs prof``): each capture window becomes an "X" span
  on the ``prof`` lane, carrying its incident cid, so a deep capture sits
  visually under the heal/replan/SLO event that triggered it.

All timestamps share the ``time.monotonic()`` clock the journal and the
serve dispatcher stamp, shifted so the earliest event sits at t=0 (Chrome
trace ``ts``/``dur`` are microseconds).

.. _Trace Event Format:
   https://docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU
"""

from __future__ import annotations

import json
from pathlib import Path

__all__ = [
    "captures_to_trace_events", "export_timeline", "goodput_to_trace_events",
    "journal_to_trace_events", "traces_to_trace_events",
    "validate_chrome_trace", "write_timeline",
]

_PID = 1
_US = 1e6

# journal event-name prefix -> lane (tid) name
_LANES = (
    (("preempt", "grace", "attempt", "restart", "supervise", "checkpoint",
      "mesh", "restore"), "train"),
    (("replica", "heal", "replan", "probe", "revive", "slo"), "serve"),
    (("advisor",), "advisor"),
    (("prof", "hbm"), "prof"),
)


def _lane_for(event: str) -> str:
    for prefixes, lane in _LANES:
        if event.startswith(prefixes):
            return lane
    return "events"


def _args_of(rec: dict) -> dict:
    return {k: v for k, v in rec.items() if k not in ("mono", "seq")}


def journal_to_trace_events(events: list[dict], *,
                            t0: float | None = None) -> list[dict]:
    """Journal records -> trace events. Records without a usable ``mono``
    timestamp (partial/corrupt rows from a truncated attempt) are skipped
    rather than corrupting the timeline."""
    usable = [e for e in events
              if isinstance(e.get("mono"), (int, float))]
    if not usable:
        return []
    if t0 is None:
        t0 = min(e["mono"] for e in usable)
    out = []
    for rec in usable:
        dur_s = rec.get("dur_s")
        base = {
            "name": str(rec.get("event", "event")),
            "pid": _PID,
            "tid": _lane_for(str(rec.get("event", ""))),
            "cat": "journal",
            "args": _args_of(rec),
        }
        if isinstance(dur_s, (int, float)) and dur_s > 0:
            base.update(ph="X",
                        ts=max(0.0, (rec["mono"] - dur_s - t0)) * _US,
                        dur=dur_s * _US)
        else:
            base.update(ph="i", ts=max(0.0, rec["mono"] - t0) * _US,
                        s="p")
        out.append(base)
    return out


_TRACE_PHASES = ("queue_s", "pad_s", "device_s", "readback_s")


def traces_to_trace_events(rows: list[dict], *,
                           t0: float | None = None) -> list[dict]:
    """Serve ``recent_traces`` rows -> per-phase request spans.

    Rows need ``done_mono`` (stamped by the dispatcher) to be placed on the
    shared clock; legacy rows without it are skipped. Phases are laid end to
    end finishing at ``done_mono`` — the dispatcher measures them as
    consecutive stopwatch segments, so that reconstruction is exact up to
    the unmeasured inter-phase glue."""
    usable = [r for r in rows
              if isinstance(r.get("done_mono"), (int, float))]
    if not usable:
        return []
    if t0 is None:
        t0 = min(r["done_mono"] - r.get("total_s", 0.0) for r in usable)
    out = []
    for row in usable:
        tid = f"replica{row.get('replica', '?')}"
        cursor = row["done_mono"] - sum(
            row.get(p, 0.0) or 0.0 for p in _TRACE_PHASES)
        for phase in _TRACE_PHASES:
            dur = float(row.get(phase, 0.0) or 0.0)
            out.append({
                "name": phase[:-2],
                "ph": "X",
                "pid": _PID,
                "tid": tid,
                "cat": "serve",
                "ts": max(0.0, cursor - t0) * _US,
                "dur": dur * _US,
                "args": {"trace_id": row.get("trace_id"),
                         "bucket": row.get("bucket")},
            })
            cursor += dur
    return out


def captures_to_trace_events(metas: list[dict], *,
                             t0: float | None = None) -> list[dict]:
    """Committed capture metas (``list_captures``) -> spans on the ``prof``
    lane. Metas stamp ``start_mono``/``end_mono`` on the same monotonic
    clock the journal uses, so a deep capture lines up under the heal or
    replan that triggered it; ``args.cid`` makes the incident searchable
    from the capture span too."""
    usable = [m for m in metas
              if isinstance(m.get("start_mono"), (int, float))
              and isinstance(m.get("end_mono"), (int, float))]
    if not usable:
        return []
    if t0 is None:
        t0 = min(m["start_mono"] for m in usable)
    out = []
    for m in usable:
        out.append({
            "name": f"capture:{m.get('kind', 'window')}",
            "ph": "X",
            "pid": _PID,
            "tid": "prof",
            "cat": "prof",
            "ts": max(0.0, m["start_mono"] - t0) * _US,
            "dur": max(0.0, m["end_mono"] - m["start_mono"]) * _US,
            "args": {"cid": m.get("cid"), "capture": m.get("name"),
                     "kind": m.get("kind"), "reason": m.get("reason"),
                     "bytes": m.get("bytes"), "step": m.get("step")},
        })
    return out


def goodput_to_trace_events(buckets: dict[str, float], *,
                            t0_us: float = 0.0) -> list[dict]:
    """A ``{bucket: seconds}`` ledger -> consecutive spans on one lane."""
    out = []
    cursor = t0_us
    for bucket, seconds in buckets.items():
        if not isinstance(seconds, (int, float)) or seconds <= 0:
            continue
        out.append({
            "name": bucket, "ph": "X", "pid": _PID, "tid": "goodput",
            "cat": "goodput", "ts": cursor, "dur": seconds * _US,
            "args": {"seconds": seconds},
        })
        cursor += seconds * _US
    return out


def export_timeline(journal_events: list[dict], *,
                    traces: list[dict] = (),
                    captures: list[dict] = (),
                    goodput: dict[str, float] | None = None,
                    meta: dict | None = None) -> dict:
    """Merge all sources into one Chrome trace object.

    Empty inputs are fine — the result is a valid (possibly event-free)
    trace, so exporting a partial or crashed attempt always succeeds."""
    monos = [e["mono"] for e in journal_events
             if isinstance(e.get("mono"), (int, float))]
    monos += [r["done_mono"] - r.get("total_s", 0.0) for r in traces
              if isinstance(r.get("done_mono"), (int, float))]
    monos += [m["start_mono"] for m in captures
              if isinstance(m.get("start_mono"), (int, float))]
    t0 = min(monos) if monos else 0.0
    events = journal_to_trace_events(journal_events, t0=t0)
    events += traces_to_trace_events(list(traces), t0=t0)
    events += captures_to_trace_events(list(captures), t0=t0)
    if goodput:
        events += goodput_to_trace_events(goodput)
    tids = sorted({e["tid"] for e in events})
    metadata = [{"name": "process_name", "ph": "M", "pid": _PID, "tid": 0,
                 "args": {"name": "jimm_tpu flight recorder"}}]
    metadata += [{"name": "thread_name", "ph": "M", "pid": _PID, "tid": tid,
                  "args": {"name": str(tid)}} for tid in tids]
    events.sort(key=lambda e: (e.get("ts", 0.0), e.get("tid", "")))
    return {
        "traceEvents": metadata + events,
        "displayTimeUnit": "ms",
        "otherData": dict(meta or {}, exporter="jimm_tpu.obs.timeline"),
    }


def validate_chrome_trace(trace) -> list[str]:
    """Structural validation against the trace-event schema; returns a list
    of problems (empty == valid). Used by CI so a malformed export fails
    loudly instead of silently refusing to load in the UI."""
    problems: list[str] = []
    if not isinstance(trace, dict):
        return ["trace must be a JSON object"]
    events = trace.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents must be a list"]
    for i, ev in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            problems.append(f"{where}: not an object")
            continue
        if not isinstance(ev.get("name"), str) or not ev.get("name"):
            problems.append(f"{where}: missing/empty name")
        ph = ev.get("ph")
        if ph not in ("X", "B", "E", "i", "I", "M", "C", "b", "e", "n"):
            problems.append(f"{where}: bad phase {ph!r}")
        if ph != "M":
            ts = ev.get("ts")
            if not isinstance(ts, (int, float)) or ts < 0:
                problems.append(f"{where}: bad ts {ts!r}")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(f"{where}: X event with bad dur {dur!r}")
        if "pid" not in ev or "tid" not in ev:
            problems.append(f"{where}: missing pid/tid")
    return problems


def write_timeline(path: str | Path, trace: dict) -> Path:
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(trace, f)
        f.write("\n")
    return path
