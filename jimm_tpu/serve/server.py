"""Stdlib HTTP front end for the inference engine.

``ThreadingHTTPServer`` handler threads bridge into the engine's asyncio
loop with ``run_coroutine_threadsafe`` — the loop does all coalescing and
dispatch; handler threads only parse/serialize JSON and block on their own
request's future. No framework, no new dependencies.

Endpoints::

    GET  /healthz      liveness + queue depth / fill ratio snapshot
    GET  /metrics      Prometheus text exposition (jimm_serve_* series)
    POST /v1/embed     {"image": [[...]]} -> {"features": [...]}; bulk form
                       {"images": [img, ...]} -> {"features": [[...], ...]}
                       (each image submits individually, so the engine
                       coalesces the burst into its warm buckets)
    POST /v1/classify  {"image": ..., "tokens": {label: [ids]}}
                       -> {"scores": {label: p}, "cached": bool}
    POST /v1/search    {"vector": [...]} or {"image": ...} (embedded via
                       the engine first), optional "k" -> {"ids",
                       "scores"} from the named retrieval index

Images ride as nested JSON lists or as ``{"image_b64": base64(raw float32),
"shape": [H, W, C]}`` (the client picks b64 when it can). Typed
:class:`~jimm_tpu.serve.admission.ServeError`\\ s map to their HTTP status
with a machine-readable ``error`` code in the JSON body.
"""

from __future__ import annotations

import asyncio
import base64
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from jimm_tpu.obs.registry import registries as _obs_registries
from jimm_tpu.obs.exporters import render_prometheus_text
from jimm_tpu.obs.spans import new_trace_id
from jimm_tpu.serve.admission import RequestError, ServeError, ServeMetrics
from jimm_tpu.serve.cache import (EmbeddingCache, class_embedding_cache,
                                  prompt_set_key)
from jimm_tpu.serve.engine import InferenceEngine


def request_trace_id(payload: dict) -> str:
    """The request's trace id: inherit the client's ``X-Jimm-Trace-Id``
    (folded into the payload by the handler) when it looks sane, else mint
    one. Wire-supplied ids are untrusted text — bound the length so a
    hostile header can't bloat journal records or the trace ring."""
    tid = payload.get("trace_id")
    if isinstance(tid, str) and 0 < len(tid) <= 64:
        return tid
    return new_trace_id()


def decode_image_payload(payload: dict, *, dtype=np.float32) -> np.ndarray:
    """Pull the image array out of a request body (list or b64 form)."""
    if "image" in payload:
        try:
            return np.asarray(payload["image"], dtype)
        except (TypeError, ValueError) as e:
            raise RequestError(f"bad 'image' payload: {e}") from None
    if "image_b64" in payload:
        if "shape" not in payload:
            raise RequestError("'image_b64' needs 'shape'")
        raw = base64.b64decode(payload["image_b64"])
        wire = np.dtype(payload.get("dtype", "float32"))
        try:
            arr = np.frombuffer(raw, wire).reshape(payload["shape"])
        except ValueError as e:
            raise RequestError(f"bad 'image_b64' payload: {e}") from None
        return arr.astype(dtype, copy=False)
    raise RequestError("request needs 'image' or 'image_b64'")


class ZeroShotService:
    """Zero-shot classification over the engine's image features.

    Class weights come from the embedding cache keyed by (model, token
    rows); on repeat label sets the text tower never runs. The per-request
    work after the engine returns features is one small host matmul.
    """

    def __init__(self, model, *, model_key: str,
                 cache: EmbeddingCache | None = None):
        self.model = model
        self.model_key = model_key
        self.cache = cache if cache is not None else class_embedding_cache()
        self.context_length = model.config.text.context_length
        self._scale = float(np.exp(np.asarray(model.logit_scale[...],
                                              np.float32)))
        bias = getattr(model, "logit_bias", None)
        self._bias = (None if bias is None
                      else float(np.asarray(bias[...], np.float32)))

    def class_weights_blocking(self, table: dict
                               ) -> tuple[list[str], np.ndarray, bool]:
        """(labels, (C, D) unit-norm weights, was_cached). Runs the text
        tower only on a cache miss; call from a handler thread, not the
        event loop."""
        from jimm_tpu.utils.zero_shot import (token_table_rows,
                                              weights_from_rows)
        try:
            labels, rows, owner = token_table_rows(table, self.context_length)
        except (ValueError, TypeError) as e:
            raise RequestError(str(e)) from None
        key = prompt_set_key(self.model_key, np.asarray(rows))
        cached = self.cache.get(key)
        if cached is not None:
            return labels, cached, True
        weights = np.asarray(
            weights_from_rows(self.model, rows, owner, len(labels)),
            np.float32)
        self.cache.put(key, weights)
        return labels, weights, False

    def scores(self, features: np.ndarray, weights: np.ndarray) -> np.ndarray:
        """Calibrated per-class scores from one feature row: softmax over
        labels (CLIP) or per-class sigmoid (SigLIP, has logit_bias)."""
        feat = features.astype(np.float32)
        feat /= np.linalg.norm(feat)
        logits = self._scale * feat @ weights.T
        if self._bias is not None:
            return 1.0 / (1.0 + np.exp(-(logits + self._bias)))
        e = np.exp(logits - logits.max())
        return e / e.sum()


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    # -- plumbing ---------------------------------------------------------

    def log_message(self, fmt, *args):  # noqa: A003 — silence per-request log
        pass

    def _send(self, status: int, body: bytes,
              content_type: str = "application/json",
              extra_headers: dict | None = None) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for name, value in (extra_headers or {}).items():
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, status: int, obj,
                   extra_headers: dict | None = None) -> None:
        self._send(status, json.dumps(obj).encode(),
                   extra_headers=extra_headers)

    def _send_error_obj(self, e: Exception) -> None:
        if isinstance(e, ServeError):
            body = {"error": e.code, "message": str(e)}
            headers = None
            # throttled (429) and shed (503) responses tell the client
            # when to come back; ServeClient feeds this into its backoff
            retry_after = getattr(e, "retry_after_s", None)
            if retry_after is not None:
                body["retry_after_s"] = retry_after
                headers = {"Retry-After": f"{max(retry_after, 0.0):.3f}"}
            self._send_json(e.http_status, body, extra_headers=headers)
        else:
            self.server.app.metrics.inc("errors_total")
            self._send_json(500, {"error": "internal", "message": str(e)})

    def _read_body(self) -> dict:
        length = int(self.headers.get("Content-Length") or 0)
        if length <= 0:
            raise RequestError("empty request body")
        try:
            payload = json.loads(self.rfile.read(length))
        except ValueError as e:
            raise RequestError(f"bad JSON body: {e}") from None
        if not isinstance(payload, dict):
            raise RequestError("request body must be a JSON object")
        return payload

    # -- routes -----------------------------------------------------------

    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        app = self.server.app
        if self.path == "/healthz":
            self._send_json(200, app.healthz())
        elif self.path == "/metrics":
            self._send(200, app.metrics_text().encode(),
                       "text/plain; version=0.0.4")
        elif self.path == "/debug/traces":
            self._send_json(200, app.debug_traces())
        else:
            self._send_json(404, {"error": "not_found",
                                  "message": self.path})

    def do_POST(self) -> None:  # noqa: N802
        app = self.server.app
        try:
            payload = self._read_body()
            # identity/routing headers fold into the payload (an explicit
            # payload field wins) so app.embed()/classify()/search() have
            # one spelling whether called over HTTP or in-process
            tenant = self.headers.get("X-Jimm-Tenant")
            if tenant is not None:
                payload.setdefault("tenant", tenant)
            model = self.headers.get("X-Jimm-Model")
            if model is not None:
                payload.setdefault("model", model)
            # client-minted trace identity: one id threads client retry ->
            # admission -> replica dispatch -> journal/capture, so a slow
            # request is profilable end to end (FastUSP-style multi-level
            # correlation)
            trace_id = self.headers.get("X-Jimm-Trace-Id")
            if trace_id is not None:
                payload.setdefault("trace_id", trace_id)
            if self.path == "/v1/embed":
                out = app.embed(payload)
                # cascade routing metadata travels as response headers so
                # clients bill cost/request without a changed body shape
                cascade_headers = out.pop("_cascade", None)
                self._send_json(200, out, extra_headers=cascade_headers)
            elif self.path == "/v1/classify":
                self._send_json(200, app.classify(payload))
            elif self.path == "/v1/search":
                self._send_json(200, app.search(payload))
            elif self.path == "/admin/revive":
                self._send_json(200, app.revive(payload))
            elif self.path == "/admin/prof/trigger":
                self._send_json(200, app.prof_trigger(payload))
            else:
                self._send_json(404, {"error": "not_found",
                                      "message": self.path})
        except Exception as e:  # noqa: BLE001 — every error gets a response
            self._send_error_obj(e)


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    app: "ServingServer"


class ServingServer:
    """Owns the engine loop thread and the HTTP server thread.

    ``start()`` warm-compiles every bucket, spins up the asyncio loop,
    starts the engine on it, then opens the listening socket — so the first
    client request already hits warm executables.
    """

    def __init__(self, engine: InferenceEngine, *,
                 zero_shot: ZeroShotService | None = None,
                 retrieval=None, pool=None, cascade=None, autoscaler=None,
                 host: str = "127.0.0.1", port: int = 0,
                 request_timeout_s: float = 30.0, warmup: bool = True,
                 metrics_logger=None, metrics_log_every_s: float = 10.0):
        #: optional jimm_tpu.serve.qos.ModelPool for multi-model residency;
        #: ``engine`` must be its default entry (requests naming no model
        #: route there). All pool engines share this server's loop, warmup,
        #: and ServeMetrics.
        self.pool = pool
        if pool is not None and engine is not pool.default:
            raise ValueError("engine must be the pool's default entry")
        #: optional jimm_tpu.serve.cascade.CascadeRouter: single-image
        #: embeds that name no explicit model route through it (cheapest
        #: stage first, calibrated escalation) and carry the routing
        #: metadata back as X-Jimm-Cascade-* response headers
        self.cascade = cascade
        if cascade is not None and pool is None:
            raise ValueError("cascade routing requires a model pool")
        #: optional jimm_tpu.serve.cascade.CascadeAutoscaler, surfaced in
        #: healthz (the control loop itself is driven by the operator
        #: harness, not the HTTP server)
        self.autoscaler = autoscaler
        self.engine = engine
        self.zero_shot = zero_shot
        #: optional jimm_tpu.retrieval.RetrievalService backing /v1/search
        self.retrieval = retrieval
        self.metrics: ServeMetrics = engine.metrics
        if zero_shot is not None:
            self.metrics.bind_gauge("cache_hit_rate",
                                    lambda: zero_shot.cache.hit_rate)
        self.host = host
        self._requested_port = port
        self.request_timeout_s = request_timeout_s
        self._warmup = warmup
        #: train/metrics.py-compatible plumbing: a MetricsLogger (or
        #: anything with .log(step, **metrics)) gets a snapshot every
        #: metrics_log_every_s — same JSONL/TensorBoard sinks training uses
        self.metrics_logger = metrics_logger
        self.metrics_log_every_s = metrics_log_every_s
        self._log_thread: threading.Thread | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._loop_thread: threading.Thread | None = None
        self._httpd: _Server | None = None
        self._http_thread: threading.Thread | None = None

    # -- lifecycle --------------------------------------------------------

    def _engines(self) -> list[InferenceEngine]:
        return self.pool.engines() if self.pool is not None else [self.engine]

    def start(self) -> None:
        if self._loop is not None:
            return
        if self._warmup:
            for engine in self._engines():
                engine.warmup_blocking()
            if self.retrieval is not None:
                self.retrieval.warmup()
        loop = asyncio.new_event_loop()
        started = threading.Event()

        def run() -> None:
            asyncio.set_event_loop(loop)
            loop.call_soon(started.set)
            loop.run_forever()

        self._loop_thread = threading.Thread(target=run, daemon=True,
                                             name="jimm-serve-loop")
        self._loop_thread.start()
        started.wait()
        self._loop = loop
        for engine in self._engines():
            asyncio.run_coroutine_threadsafe(engine.start(),
                                             loop).result(10)
        self._httpd = _Server((self.host, self._requested_port), _Handler)
        self._httpd.app = self
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="jimm-serve-http")
        self._http_thread.start()
        if self.metrics_logger is not None:
            self._log_thread = threading.Thread(
                target=self._metrics_log_loop, daemon=True,
                name="jimm-serve-metrics")
            self._log_thread.start()

    def _metrics_log_loop(self) -> None:
        import time
        step = 0
        while self._httpd is not None:
            time.sleep(self.metrics_log_every_s)
            if self._httpd is None:
                break
            self.metrics_logger.log(step, **self.metrics.snapshot())
            step += 1

    @property
    def port(self) -> int:
        if self._httpd is None:
            raise RuntimeError("server not started")
        return self._httpd.server_address[1]

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._loop is not None:
            for engine in self._engines():
                asyncio.run_coroutine_threadsafe(engine.stop(),
                                                 self._loop).result(10)
            self._loop.call_soon_threadsafe(self._loop.stop)
            if self._loop_thread is not None:
                self._loop_thread.join(timeout=10)
            self._loop.close()
            self._loop = None

    def serve_forever(self) -> None:
        """Block until KeyboardInterrupt (the CLI foreground mode)."""
        assert self._http_thread is not None
        try:
            while self._http_thread.is_alive():
                self._http_thread.join(timeout=1.0)
        except KeyboardInterrupt:
            pass
        finally:
            self.stop()

    # -- request handling (called from HTTP handler threads) --------------

    def _engine_for(self, model: str | None) -> InferenceEngine:
        """Route a request's ``model`` field to its resident engine. With
        no pool the field is ignored (single-model servers predate it)."""
        if self.pool is None:
            return self.engine
        return self.pool.get(model)

    def _submit(self, image: np.ndarray, timeout_s: float | None,
                trace_id: str | None = None, *,
                engine: InferenceEngine | None = None,
                tenant: str | None = None) -> np.ndarray:
        assert self._loop is not None
        engine = engine if engine is not None else self.engine
        future = asyncio.run_coroutine_threadsafe(
            engine.submit(image, timeout_s=timeout_s,
                          trace_id=trace_id, tenant=tenant), self._loop)
        return future.result(timeout=self.request_timeout_s)

    def _submit_many(self, images: list, timeout_s, trace_id: str, *,
                     engine: InferenceEngine | None = None,
                     tenant: str | None = None) -> list[np.ndarray]:
        """Submit a burst of single-item requests at once so the engine's
        batcher coalesces them into its warm buckets — the bulk-embed path
        rides the exact same admission/dispatch machinery as singles."""
        assert self._loop is not None
        engine = engine if engine is not None else self.engine
        futures = [asyncio.run_coroutine_threadsafe(
            engine.submit(image, timeout_s=timeout_s,
                          trace_id=f"{trace_id}.{i}", tenant=tenant),
            self._loop)
            for i, image in enumerate(images)]
        return [f.result(timeout=self.request_timeout_s) for f in futures]

    def _submit_cascade(self, image: np.ndarray, timeout_s: float | None,
                        trace_id: str, tenant: str | None):
        """Route one request through the cascade router on the serving
        loop; returns the full :class:`CascadeResult` (output + routing
        metadata for the response headers)."""
        assert self._loop is not None and self.cascade is not None
        future = asyncio.run_coroutine_threadsafe(
            self.cascade.submit(image, timeout_s=timeout_s,
                                trace_id=trace_id, tenant=tenant),
            self._loop)
        return future.result(timeout=self.request_timeout_s)

    def embed(self, payload: dict) -> dict:
        rid = request_trace_id(payload)
        engine = self._engine_for(payload.get("model"))
        tenant = payload.get("tenant")
        if "images" in payload:
            raw = payload["images"]
            if not isinstance(raw, list) or not raw:
                raise RequestError("'images' must be a non-empty list")
            images = [decode_image_payload(
                item if isinstance(item, dict) else {"image": item},
                dtype=engine.dtype) for item in raw]
            features = self._submit_many(images, payload.get("timeout_s"),
                                         rid, engine=engine, tenant=tenant)
            from jimm_tpu.retrieval.api import retrieval_metrics
            retrieval_metrics()[1].inc(len(images))
            return {"features": [np.asarray(f, np.float32).tolist()
                                 for f in features],
                    "count": len(features), "trace_id": rid}
        image = decode_image_payload(payload, dtype=engine.dtype)
        if self.cascade is not None and payload.get("model") is None:
            result = self._submit_cascade(image, payload.get("timeout_s"),
                                          rid, tenant)
            return {"features": np.asarray(result.output,
                                           np.float32).tolist(),
                    "trace_id": rid,
                    # popped into response headers by the handler, never
                    # serialized into the JSON body
                    "_cascade": result.headers()}
        features = self._submit(image, payload.get("timeout_s"), rid,
                                engine=engine, tenant=tenant)
        return {"features": np.asarray(features, np.float32).tolist(),
                "trace_id": rid}

    def search(self, payload: dict) -> dict:
        """Top-k over the configured retrieval index: a raw query vector
        searches directly; an image embeds through the engine first (same
        buckets, admission, and replica dispatch as ``/v1/embed``)."""
        if self.retrieval is None:
            raise RequestError("this server has no retrieval index "
                               "(start with serve --index)")
        rid = request_trace_id(payload)
        if "vector" in payload:
            try:
                query = np.asarray(payload["vector"], np.float32)
            except (TypeError, ValueError) as e:
                raise RequestError(f"bad 'vector' payload: {e}") from None
        else:
            engine = self._engine_for(payload.get("model"))
            image = decode_image_payload(payload, dtype=engine.dtype)
            query = np.asarray(
                self._submit(image, payload.get("timeout_s"), rid,
                             engine=engine, tenant=payload.get("tenant")),
                np.float32)
        nprobe = payload.get("nprobe")
        if nprobe is not None:
            try:
                nprobe = int(nprobe)
            except (TypeError, ValueError):
                raise RequestError(
                    f"'nprobe' must be an integer; got {nprobe!r}") \
                    from None
        values, ids = self.retrieval.search_blocking(
            query, k=payload.get("k"), nprobe=nprobe)
        # ivf rows can under-fill (probed clusters hold < k rows): the id
        # list is the source of truth, scores truncate to match
        out = {"index": self.retrieval.index.name,
               "k": len(ids[0]), "ids": ids[0],
               "scores": [round(float(v), 6)
                          for v in values[0][:len(ids[0])]],
               "trace_id": rid}
        if self.retrieval.mode in ("ivf", "tiered"):
            out["index_mode"] = self.retrieval.mode
            out["nprobe"] = int(
                self.retrieval.searcher.last_stats.get(
                    "nprobe", self.retrieval.default_nprobe))
        return out

    def classify(self, payload: dict) -> dict:
        if self.zero_shot is None:
            raise RequestError("this server has no zero-shot service "
                               "(started without a text tower)")
        rid = request_trace_id(payload)
        tokens = payload.get("tokens")
        if not isinstance(tokens, dict) or not tokens:
            raise RequestError("classify needs 'tokens': {label: [ids]}")
        labels, weights, cached = \
            self.zero_shot.class_weights_blocking(tokens)
        engine = self._engine_for(payload.get("model"))
        image = decode_image_payload(payload, dtype=engine.dtype)
        features = self._submit(image, payload.get("timeout_s"), rid,
                                engine=engine, tenant=payload.get("tenant"))
        scores = self.zero_shot.scores(np.asarray(features), weights)
        return {"scores": {label: round(float(s), 6)
                           for label, s in zip(labels, scores)},
                "cached": cached,
                "trace_id": rid}

    def revive(self, payload: dict) -> dict:
        """Operator recourse for a watchdog-fenced replica:
        ``POST /admin/revive {"replica": N}`` un-fences lane N with a fresh
        executor and a re-armed restart budget. Without this hook a fence
        is forever — the watchdog never retries a dead lane on its own
        (unless the engine has a self-heal factory installed). A bad index
        or an un-fenced replica is a 400, so drills notice typos."""
        index = payload.get("replica")
        if not isinstance(index, int) or isinstance(index, bool):
            raise RequestError("revive needs 'replica': <int index>")
        engine = self._engine_for(payload.get("model"))
        try:
            # Replica bookkeeping (pool/restarts/dead/incident_cid) is
            # loop-confined state: the watchdog mutates it from loop
            # coroutines, so the admin path must not mutate it from this
            # HTTP handler thread. Hop onto the loop and wait. An
            # unstarted server has no loop yet — spin a disposable one so
            # the mutation still happens on a loop thread and the
            # confinement invariant holds unconditionally.
            loop = self._loop
            if loop is None:
                stats = self._revive_on_disposable_loop(engine, index)
            else:
                future = asyncio.run_coroutine_threadsafe(
                    self._revive_on_loop(engine, index), loop)
                stats = future.result(timeout=30.0)
        except ValueError as e:
            raise RequestError(str(e)) from None
        return {"revived": index, "replica_stats": stats,
                "dead_replicas": engine.dead_replicas()}

    async def _revive_on_loop(self, engine: InferenceEngine,
                              index: int) -> dict:
        return engine.revive(index)

    def _revive_on_disposable_loop(self, engine: InferenceEngine,
                                   index: int) -> dict:
        """Revive on a short-lived loop thread when the server was never
        started. There is no watchdog racing us here, but routing through a
        loop anyway keeps replica state mutated from exactly one kind of
        context, so the discipline is uniform rather than "safe by
        accident"."""
        loop = asyncio.new_event_loop()
        thread = threading.Thread(target=loop.run_forever,
                                  name="jimm-serve-loop", daemon=True)
        thread.start()
        try:
            future = asyncio.run_coroutine_threadsafe(
                self._revive_on_loop(engine, index), loop)
            return future.result(timeout=30.0)
        finally:
            loop.call_soon_threadsafe(loop.stop)
            thread.join(timeout=5.0)
            loop.close()

    def prof_trigger(self, payload: dict) -> dict:
        """``POST /admin/prof/trigger`` — kick a deep profiler capture on a
        caller-supplied incident cid (``jimm-tpu obs prof trigger``). The
        capture manager is process-global (``serve --prof-dir`` or
        ``JIMM_PROF_DIR``); a server without one is a 400, not a silent
        no-op, so drills notice a misconfigured box."""
        from jimm_tpu.obs.prof.capture import get_capture_manager
        mgr = get_capture_manager()
        if mgr is None:
            raise RequestError("this server has no capture manager "
                               "(start with serve --prof-dir, or set "
                               "JIMM_PROF_DIR)")
        cid = payload.get("cid")
        if cid is not None and not isinstance(cid, str):
            raise RequestError("'cid' must be a string")
        reason = payload.get("reason", "admin")
        if not isinstance(reason, str):
            raise RequestError("'reason' must be a string")
        window_s = payload.get("window_s")
        if window_s is not None and not isinstance(window_s, (int, float)):
            raise RequestError("'window_s' must be a number")
        meta = mgr.trigger(cid, reason,
                           window_s=float(window_s) if window_s else None)
        if meta is None:
            return {"triggered": False, "suppressed": True}
        return {"triggered": True, "capture": meta}

    def metrics_text(self) -> str:
        """Unified Prometheus dump for ``/metrics``: this server's
        ``jimm_serve_*`` series (the exact ServeMetrics snapshot names, as
        always) merged with every other namespace published to the obs hub
        (``jimm_train_*`` goodput, ``jimm_spans_*``, ...) — one scrape sees
        the whole process."""
        series: dict = {}
        for prefix, reg in _obs_registries().items():
            if prefix == "jimm_serve":
                continue  # ours comes from self.metrics below, not the hub
            for name, value in reg.snapshot().items():
                series[f"{prefix}_{name}"] = value
        for name, value in self.metrics.snapshot().items():
            series[f"jimm_serve_{name}"] = value
        return render_prometheus_text(series)

    def debug_traces(self) -> dict:
        """The engine's ``recent_traces`` ring (newest last): per-request
        queue/pad/device/readback decomposition with the ``done_mono``
        stamp the timeline exporter joins against. Read by
        ``jimm-tpu obs tail --traces`` and ``obs timeline --traces``."""
        return {"traces": list(self.engine.recent_traces),
                "count": len(self.engine.recent_traces)}

    def healthz(self) -> dict:
        snap = self.metrics.snapshot()
        out = {"status": "ok",
               "buckets": list(self.engine.buckets.sizes),
               "queue_depth": snap["queue_depth"],
               "batch_fill_ratio": snap["batch_fill_ratio"],
               "latency_p50_ms": snap["latency_p50_ms"],
               "latency_p99_ms": snap["latency_p99_ms"],
               "uptime_s": snap["uptime_s"]}
        # per-bucket warm-start provenance (aot/miss/fallback/compile) —
        # "did this process actually start warm?" is a health question
        report = getattr(self.engine, "warmup_report", None)
        if report:
            out["warmup"] = {str(k): v for k, v in sorted(report.items())}
        # topology-planned engines expose per-replica load so "is one
        # replica cold/stuck?" is answerable from a health probe
        if getattr(self.engine, "_multi", False):
            out["replicas"] = self.engine.replica_stats()
            out["replans"] = int(self.metrics.count("replans_total"))
            heal_err = getattr(self.engine, "last_heal_error", None)
            if heal_err:
                out["last_heal_error"] = heal_err
        # a watchdog-fenced replica downgrades the whole probe: the server
        # still answers, but capacity is reduced and an operator should act
        dead = getattr(self.engine, "dead_replicas", lambda: [])()
        if dead:
            out["status"] = "degraded"
            out["dead_replicas"] = dead
        if self.retrieval is not None:
            out["retrieval"] = self.retrieval.describe()
        # the qos/models blocks exist ONLY when a policy / pool is
        # configured: the bare server's healthz shape is byte-compatible
        # with the pre-QoS one (tested in tests/test_qos.py)
        if getattr(self.engine, "qos", None) is not None:
            out["qos"] = self.engine.qos.snapshot()
        if self.pool is not None:
            out["models"] = self.pool.describe()
        # cascade/autoscale blocks follow the same conditional contract:
        # absent unless the server was started with a router / control loop
        if self.cascade is not None:
            out["cascade"] = self.cascade.describe()
        if self.autoscaler is not None:
            out["autoscale"] = self.autoscaler.describe()
        # SLO block only when an SloEngine is attached (same conditional
        # contract as qos/models: the bare server's shape is unchanged).
        # Fast-burning tenants downgrade the probe like a fenced replica:
        # the server answers, but the error budget is being torched.
        slo = getattr(self.engine, "slo", None)
        if slo is not None:
            out["slo"] = slo.snapshot()
            if out["slo"]["fast_burning"] and out["status"] == "ok":
                out["status"] = "degraded"
        return out
