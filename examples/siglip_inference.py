"""SigLIP zero-shot inference (equivalent of the reference's
`examples/siglip_inference.ipynb`): encode images and captions, report
per-pair sigmoid match probabilities.

SigLIP parity notes (SURVEY Appendix A.7-8): captions must be tokenized with
``padding="max_length"`` because the text tower pools the LAST position, and
logits are ``exp(logit_scale) * sim + logit_bias`` squashed with a sigmoid —
probabilities are independent per pair, not a softmax over prompts.

Run:  python examples/siglip_inference.py --checkpoint google/siglip-base-patch16-256 \
          --prompts "a photo of a cat" "a photo of a dog"
"""

from __future__ import annotations

import jimm_tpu.utils.env
jimm_tpu.utils.env.configure_platform()

import argparse

import jax
import jax.numpy as jnp
import numpy as np

from jimm_tpu import SigLIP
from jimm_tpu.parallel import make_mesh
from jimm_tpu.utils import jit_forward


def tokenize(prompts: list[str], checkpoint: str, context: int) -> np.ndarray:
    from transformers import AutoTokenizer
    tok = AutoTokenizer.from_pretrained(checkpoint)
    # padding="max_length" is required for last-token pooling
    out = tok(prompts, padding="max_length", max_length=context,
              return_tensors="np")
    return out["input_ids"].astype(np.int32)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--checkpoint", required=True)
    p.add_argument("--image", default=None,
                   help="npy float32 HWC in [-1,1]; random if omitted")
    p.add_argument("--prompts", nargs="+",
                   default=["a photo of a cat", "a photo of a dog",
                            "a photo of a city street"])
    p.add_argument("--token-file", default=None,
                   help="pre-tokenized prompts, npy int32 [N, S]")
    p.add_argument("--model-axis", type=int, default=1)
    args = p.parse_args()

    mesh = make_mesh({"data": 1, "model": args.model_axis}) \
        if args.model_axis > 1 else None
    model = SigLIP.from_pretrained(args.checkpoint, mesh=mesh,
                                   dtype=jnp.bfloat16)
    size = model.config.vision.image_size

    if args.image:
        image = np.load(args.image).astype(np.float32)[None]
    else:
        image = np.random.RandomState(0).rand(1, size, size, 3).astype(
            np.float32) * 2 - 1
    if args.token_file:
        text = np.load(args.token_file).astype(np.int32)
        labels = [f"caption[{i}]" for i in range(text.shape[0])]
    else:
        text = tokenize(args.prompts, args.checkpoint,
                        model.config.text.context_length)
        labels = args.prompts

    logits = jit_forward(model)(jnp.asarray(image), jnp.asarray(text))
    probs = np.asarray(jax.nn.sigmoid(logits.astype(jnp.float32)))[0]
    for label, prob in sorted(zip(labels, probs), key=lambda t: -t[1]):
        print(f"P(match) = {prob:6.1%}  {label}")


if __name__ == "__main__":
    main()
