"""Microbenchmarks for the SigLIP-B/16-256 training hot path on one chip.

Times each candidate attention implementation (fwd+bwd) at the bench shapes,
and the full train step under each remat policy, to locate where the MFU
gap vs the 50% target comes from. Not part of the test suite — a tuning tool.
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, iters=20, warmup=3):
    # chain iterations through a data dependency and end with a host
    # materialization: on the tunneled TPU platform block_until_ready can
    # return before execution, so independent calls overlap/under-measure
    def chained(args, n):
        def body(args, _):
            out = fn(*args)
            q = args[0] + 1e-6 * out[0].astype(args[0].dtype)
            return (q,) + tuple(args[1:]), None
        args, _ = jax.lax.scan(body, args, None, length=n)
        return args

    chained = jax.jit(chained, static_argnums=1)
    # warm up with the SAME n — static_argnums means a different scan length
    # is a different executable, and compile time would pollute the timing
    float(jnp.sum(chained(args, iters)[0].astype(jnp.float32)))
    t0 = time.perf_counter()
    float(jnp.sum(chained(args, iters)[0].astype(jnp.float32)))
    return (time.perf_counter() - t0) / iters


def bench_attention():
    from jimm_tpu.ops.attention import reference_attention
    from jimm_tpu.ops.flash_attention import flash_attention

    B, S, N, D = 128, 256, 12, 64
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, S, N, D), jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, S, N, D), jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, S, N, D), jnp.bfloat16)

    # fwd+bwd FLOPs for attention proper: fwd 4*B*N*S^2*D, bwd ~2.5x
    flops = 3.5 * 4 * B * N * S * S * D

    def loss_of(attn, **kw):
        def f(q, k, v):
            return jnp.sum(attn(q, k, v, **kw).astype(jnp.float32))
        return jax.jit(jax.grad(f, argnums=(0, 1, 2)))

    impls = {
        # jaxlint: disable=JL009 probing this pinned config IS the experiment
        "flash(bq=128,bk=128)": loss_of(flash_attention, block_q=128,
                                        block_k=128),  # jaxlint: disable=JL009 pinned probe
        "flash(default blocks)": loss_of(flash_attention),
        "xla_dpa": loss_of(
            lambda q, k, v: jax.nn.dot_product_attention(q, k, v)),
        "reference": loss_of(reference_attention),
    }
    for name, fn in impls.items():
        dt = timeit(fn, q, k, v)
        print(f"  attn fwd+bwd {name:24s} {dt*1e3:8.2f} ms  "
              f"{flops/dt/1e12:6.2f} TF/s")


def bench_train_step(remat: str, attn_impl: str, batch: int = 128,
                     ln_impl: str = "xla", unroll: int = 1,
                     fused_qkv: bool = False):
    from flax import nnx

    from jimm_tpu import SigLIP, preset
    from jimm_tpu.train import (OptimizerConfig, make_contrastive_train_step,
                                make_optimizer, mfu)
    from jimm_tpu.train.metrics import train_step_flops

    from jimm_tpu.configs import with_runtime

    cfg = preset("siglip-base-patch16-256")
    do_remat = remat != "none"
    policy = remat if remat in ("dots", "none") else "none"
    if remat == "full":
        policy = "none"
    cfg = with_runtime(cfg, remat=do_remat,
                       remat_policy=policy if do_remat else "none",
                       attn_impl=attn_impl, ln_impl=ln_impl,
                       fused_qkv=fused_qkv, scan_unroll=unroll)
    model = SigLIP(cfg, rngs=nnx.Rngs(0), dtype=jnp.bfloat16,
                   param_dtype=jnp.bfloat16)
    optimizer = make_optimizer(model, OptimizerConfig(learning_rate=1e-3))
    step_fn = make_contrastive_train_step("siglip", donate=True)
    rng = np.random.RandomState(0)
    images = jnp.asarray(rng.randn(batch, 256, 256, 3), jnp.bfloat16)
    text = jnp.asarray(rng.randint(1, cfg.text.vocab_size, size=(batch, 64)),
                       jnp.int32)
    for _ in range(3):
        m = step_fn(model, optimizer, images, text)
    float(m["loss"])
    t0 = time.perf_counter()
    steps = 20
    for _ in range(steps):
        m = step_fn(model, optimizer, images, text)
    float(m["loss"])
    dt = (time.perf_counter() - t0) / steps
    flops = train_step_flops(cfg, batch)
    print(f"  train remat={remat:5s} attn={attn_impl:9s} ln={ln_impl:5s} "
          f"qkv={'fus' if fused_qkv else 'sep'} unroll={unroll:2d} b={batch:4d} "
          f"{dt*1e3:8.2f} ms  {batch/dt:7.1f} img/s  mfu={mfu(flops, dt, 1):.3f}")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--mode", default="all",
                   choices=["all", "attn", "train"])
    p.add_argument("--remat", default=None)
    p.add_argument("--attn", default=None)
    p.add_argument("--batch", type=int, default=128)
    p.add_argument("--ln", default=None)
    p.add_argument("--unroll", type=int, default=1)
    p.add_argument("--fused-qkv", action="store_true")
    args = p.parse_args()
    print("backend:", jax.default_backend(), jax.devices()[0].device_kind)
    if args.mode in ("all", "attn"):
        bench_attention()
    if args.mode in ("all", "train"):
        remats = [args.remat] if args.remat else ["dots", "none", "full"]
        attns = [args.attn] if args.attn else ["flash", "xla"]
        lns = [args.ln] if args.ln else ["xla"]
        for r in remats:
            for a in attns:
                for ln in lns:
                    bench_train_step(r, a, args.batch, ln, args.unroll,
                                     args.fused_qkv)


if __name__ == "__main__":
    main()
