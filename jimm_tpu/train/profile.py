"""Profiling hooks (SURVEY §5 tracing row): `jax.profiler` trace capture
around training steps, viewable in TensorBoard / Perfetto — plus an
offline per-op analyzer so a capture can be read without TensorBoard (the
workflow behind docs/performance.md; `python -m jimm_tpu profile-analyze`)."""

from __future__ import annotations

import collections
import glob
import gzip
import json
import re
from contextlib import contextmanager
from dataclasses import dataclass
from pathlib import Path

import jax


@contextmanager
def trace(log_dir: str | Path, *, host_tracer_level: int = 2):
    """Capture a device+host trace for the enclosed steps::

        with trace("/tmp/profile"):
            for _ in range(5):
                train_step(...)
    """
    Path(log_dir).mkdir(parents=True, exist_ok=True)
    jax.profiler.start_trace(str(log_dir))
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region that shows up in the trace timeline."""
    return jax.profiler.TraceAnnotation(name)


# ---------------------------------------------------------------------------
# Offline trace analysis
# ---------------------------------------------------------------------------

#: container/framework events that would double-count their children
_NON_OP = re.compile(r"^(while\.|jit_|\d+$|SyncOnDone|.*Module)")


@dataclass
class OpStat:
    """One XLA op aggregated across its occurrences in a trace.
    ``bytes_accessed`` is the TOTAL over all occurrences."""

    name: str
    category: str
    total_us: float
    count: int
    bytes_accessed: int
    long_name: str

    @property
    def gbps(self) -> float:
        """Achieved HBM bandwidth (GB/s) — the number that shows whether a
        fusion is bandwidth-bound or stalling."""
        if not self.total_us:
            return 0.0
        return self.bytes_accessed / (self.total_us * 1e-6) / 1e9


def op_stats(log_dir: str | Path, *, device: int | None = 0) -> list[OpStat]:
    """Aggregate device-op self times from the newest ``*.trace.json.gz``
    under ``log_dir`` (written by :func:`trace`). Pure stdlib — no
    TensorBoard required.

    ``device`` picks ONE device pid (default: the first) — under SPMD every
    core runs the same program, and summing across cores would report
    n_devices times the per-step time. ``None`` aggregates all devices."""
    paths = sorted(glob.glob(str(Path(log_dir) / "**" / "*.trace.json.gz"),
                             recursive=True))
    if not paths:
        raise FileNotFoundError(f"no *.trace.json.gz under {log_dir}")
    with gzip.open(paths[-1], "rt") as f:
        events = json.load(f)["traceEvents"]

    pids = {e["pid"]: e["args"].get("name", "")
            for e in events if e.get("ph") == "M"
            and e.get("name") == "process_name"}
    tnames = {(e["pid"], e["tid"]): e["args"].get("name", "")
              for e in events if e.get("ph") == "M"
              and e.get("name") == "thread_name"}
    device_pids = {p for p, n in pids.items() if n.startswith("/device:")}
    if device_pids and device is not None:
        device_pids = {sorted(device_pids)[device]}
    if not device_pids:  # CPU-only capture: ops run inside the host process
        device_pids = set(pids)

    def is_op_lane(lane: str) -> bool:
        # TPU: per-core "XLA Ops" lanes; CPU: tf_XLAEigen/... executor
        # threads. Everything else (python host frames, "Steps", module
        # lanes) would double-count or pollute the aggregation.
        return "XLA Ops" in lane or lane.startswith("tf_XLA")

    have_op_lanes = any(is_op_lane(n) for n in tnames.values())

    agg: dict[str, list] = {}
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in device_pids:
            continue
        lane = tnames.get((e["pid"], e["tid"]), "")
        if have_op_lanes:
            if not is_op_lane(lane):
                continue
        elif lane == "python":
            continue
        if _NON_OP.match(e["name"]):
            continue
        a = e.get("args", {})
        r = agg.setdefault(e["name"], [0.0, 0, 0, "", a.get("hlo_category",
                                                            "?")])
        r[0] += e.get("dur", 0)
        r[1] += 1
        r[2] += int(a.get("bytes_accessed", 0) or 0)
        r[3] = r[3] or a.get("long_name", "")

    stats = [OpStat(name=k, category=v[4], total_us=v[0], count=v[1],
                    bytes_accessed=v[2], long_name=v[3])
             for k, v in agg.items()]
    stats.sort(key=lambda s: -s.total_us)
    return stats


def summarize(stats: list[OpStat], top: int = 25, steps: int = 1) -> str:
    """Human-readable per-op and per-category summary. ``steps`` divides the
    totals so numbers read as per-training-step."""
    total = sum(s.total_us for s in stats)
    by_cat = collections.Counter()
    for s in stats:
        by_cat[s.category] += s.total_us
    lines = [f"device op time: {total / steps / 1e3:.2f} ms/step",
             "by category (ms/step):"]
    for cat, us in by_cat.most_common():
        lines.append(f"  {us / steps / 1e3:9.2f}  {cat}")
    lines.append(f"top {top} ops (ms/step, n/step, MB/occurrence, GB/s):")
    for s in stats[:top]:
        per_occ = s.bytes_accessed / max(s.count, 1)
        lines.append(
            f"  {s.total_us / steps / 1e3:8.2f} n={s.count // steps:4d} "
            f"{per_occ / 1e6:8.1f}MB {s.gbps:6.0f}GB/s  "
            f"{s.name[:44]:44s} {s.long_name[:60]}")
    return "\n".join(lines)
