"""Shared linting machinery: the ``Finding`` record, per-rule suppression
comments, file collection, and the per-file runner.

Layer 1 is pure stdlib ``ast`` — no JAX import happens on the analysis path,
so the AST rules run (and fail) fast in CI even when the accelerator stack is
broken. Layer 2 (``--trace``) lives in :mod:`jimm_tpu.lint.trace` and does
import JAX.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import tokenize

#: severity levels; only "error" findings make the CLI exit non-zero
ERROR = "error"
WARNING = "warning"

#: directory names never walked when collecting files from a directory
#: argument (explicitly-listed files are always linted, which is how the
#: test suite points the linter at the deliberately-broken fixtures)
EXCLUDED_DIRS = frozenset({"__pycache__", "lint_fixtures", ".git",
                           ".venv", "build", "dist"})

SUPPRESS_TAG = "jaxlint:"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""
    rule: str
    severity: str
    path: str
    line: int
    message: str

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.severity}: " \
               f"{self.message}"


@dataclasses.dataclass(frozen=True)
class Directive:
    """One ``# jaxlint: disable=...`` comment: where it sits, which line it
    applies to, which rules it waives, and the trailing justification text
    (empty string = a bare, unjustified disable — JL020)."""
    lineno: int
    col: int
    target: int
    rules: frozenset[str]
    justification: str


def parse_directives(source: str) -> list[Directive]:
    """Every suppression directive in ``source``, in file order.

    ``# jaxlint: disable=JL001`` (comma-separate for several rules) on a code
    line suppresses those rules on that line; on a standalone comment line it
    suppresses them on the next line. ``disable=all`` suppresses every rule.
    Comments are found with ``tokenize`` so strings containing the marker
    don't count. Text after the rule list is the human justification.
    """
    out: list[Directive] = []
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        comments = [(t.start[0], t.start[1], t.string, t.line)
                    for t in tokens if t.type == tokenize.COMMENT]
    except (tokenize.TokenError, SyntaxError, IndentationError):
        return []
    for lineno, col, text, line in comments:
        body = text.lstrip("#").strip()
        if not body.startswith(SUPPRESS_TAG):
            continue
        directive = body[len(SUPPRESS_TAG):].strip()
        if not directive.startswith("disable="):
            continue
        # everything after "disable=" up to the first space is the rule list;
        # the rest of the comment is the human justification
        parts = directive[len("disable="):].split(None, 1)
        rules = parts[0]
        justification = parts[1].strip() if len(parts) > 1 else ""
        ids = frozenset(r.strip() for r in rules.split(",") if r.strip())
        # a comment-only line (any indentation) targets the next line; a
        # trailing comment targets its own
        standalone = not line[:col].strip()
        target = lineno + 1 if standalone else lineno
        out.append(Directive(lineno, col, target, ids, justification))
    return out


def parse_suppressions(source: str) -> dict[int, frozenset[str]]:
    """Map line number -> rule IDs suppressed there (see
    :func:`parse_directives` for the comment grammar)."""
    suppressed: dict[int, set[str]] = {}
    for d in parse_directives(source):
        suppressed.setdefault(d.target, set()).update(d.rules)
    return {ln: frozenset(ids) for ln, ids in suppressed.items()}


def check_bare_suppressions(source: str, path: str) -> list[Finding]:
    """JL020: a ``# jaxlint: disable=...`` with no trailing justification.
    A suppression is a standing exception to a correctness rule; the
    reviewer three PRs later needs the *why* next to the waiver, not in
    the commit that introduced it."""
    findings = []
    for d in parse_directives(source):
        if d.justification:
            continue
        findings.append(Finding(
            "JL020", WARNING, path, d.lineno,
            f"bare suppression of {', '.join(sorted(d.rules))} with no "
            f"justification — append the reason to the comment "
            f"(# jaxlint: disable={','.join(sorted(d.rules))} <why>); "
            f"audit all waivers with `python -m jimm_tpu.lint "
            f"--suppressions`"))
    return findings


def is_suppressed(finding: Finding,
                  suppressions: dict[int, frozenset[str]]) -> bool:
    ids = suppressions.get(finding.line, frozenset())
    return finding.rule in ids or "all" in ids


def collect_files(paths: list[str]) -> list[str]:
    """Expand path arguments into a sorted list of ``.py`` files. Directories
    are walked (skipping :data:`EXCLUDED_DIRS`); explicit file arguments are
    taken verbatim, excluded or not."""
    out: set[str] = set()
    for p in paths:
        if os.path.isfile(p):
            out.add(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames if d not in EXCLUDED_DIRS]
            out.update(os.path.join(dirpath, f) for f in filenames
                       if f.endswith(".py"))
    return sorted(out)


def lint_file(path: str, *, vmem_budget: int | None = None) -> list[Finding]:
    """Run every AST rule over one file; returns unsuppressed findings."""
    from jimm_tpu.lint import rules_ast

    path = str(path)
    try:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    except (OSError, UnicodeDecodeError) as e:
        return [Finding("JL000", ERROR, path, 0, f"unreadable file: {e}")]
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding("JL000", ERROR, path, e.lineno or 0,
                        f"syntax error: {e.msg}")]
    suppressions = parse_suppressions(source)
    findings = rules_ast.run_all(tree, path, vmem_budget=vmem_budget)
    findings += check_bare_suppressions(source, path)
    return [f for f in findings if not is_suppressed(f, suppressions)]


def suppression_audit(paths: list[str]) -> list[tuple[str, int, str, str]]:
    """Every suppression directive under ``paths``:
    (path, line, comma-joined rules, justification) in path order — the
    data behind ``--suppressions``."""
    rows: list[tuple[str, int, str, str]] = []
    for path in collect_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except (OSError, UnicodeDecodeError):
            continue
        for d in parse_directives(source):
            rows.append((path, d.lineno, ",".join(sorted(d.rules)),
                         d.justification))
    return rows


def lint_paths(paths: list[str], *,
               vmem_budget: int | None = None) -> list[Finding]:
    findings: list[Finding] = []
    for path in collect_files(paths):
        findings.extend(lint_file(path, vmem_budget=vmem_budget))
    return findings
