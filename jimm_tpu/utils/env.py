"""Environment plumbing.

This runtime exports ``JAX_PLATFORMS=axon`` globally and the plugin re-merges
it, so the env var alone cannot force a backend. ``configure_platform`` reads
``JIMM_PLATFORM`` (e.g. ``cpu``) and ``JIMM_HOST_DEVICES`` (virtual CPU
device count for mesh testing) and applies them in-process *before* the first
backend use — call it at the top of every script entry point.
"""

from __future__ import annotations

import os


def configure_platform(platform: str | None = None,
                       host_devices: int | None = None) -> None:
    """Apply backend overrides from arguments, falling back to the
    ``JIMM_PLATFORM`` / ``JIMM_HOST_DEVICES`` env vars."""
    # `is None` (not truthiness): an explicit empty/zero argument must be
    # able to override a JIMM_PLATFORM/JIMM_HOST_DEVICES env setting
    plat = os.environ.get("JIMM_PLATFORM") if platform is None else platform
    n = os.environ.get("JIMM_HOST_DEVICES") if host_devices is None else host_devices
    if not plat and not n:
        return
    import jax
    if plat:
        jax.config.update("jax_platforms", plat)
    if n:
        jax.config.update("jax_num_cpu_devices", int(n))
