"""Sharding tests on an 8-device virtual CPU mesh — DP/TP/FSDP correctness
the reference never tested (SURVEY §4: "Multi-node/multi-device behavior is
never tested")."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import nnx
from jax.sharding import PartitionSpec as P

from jimm_tpu import VisionTransformer, ViTConfig, VisionConfig
from jimm_tpu.parallel import (FSDP, FSDP_TP, TENSOR_PARALLEL, create_sharded,
                               make_mesh, shard_batch, use_sharding)


def tiny_cfg(**kw):
    return ViTConfig(vision=VisionConfig(image_size=32, patch_size=16,
                                         width=64, depth=2, num_heads=2,
                                         mlp_dim=128, ln_eps=1e-12, **kw),
                     num_classes=8)


def test_make_mesh_named_axes(eight_devices):
    mesh = make_mesh({"data": 4, "model": 2})
    assert mesh.shape == {"data": 4, "model": 2}
    mesh2 = make_mesh({"data": -1, "model": 2})
    assert mesh2.shape["data"] == 4


def test_constructor_mesh_shards_params(eight_devices):
    mesh = make_mesh({"data": 4, "model": 2})
    model = VisionTransformer(tiny_cfg(), mesh=mesh, rules=TENSOR_PARALLEL)
    kernel = nnx.state(model)["vision"]["encoder"]["blocks"]["mlp"]["fc1"][
        "kernel"].get_value()
    specs = kernel.sharding.spec
    # stacked (layers, embed, mlp): mlp axis -> "model"
    assert specs == jax.sharding.PartitionSpec(None, None, "model")


@pytest.mark.parametrize("rules", [TENSOR_PARALLEL, FSDP, FSDP_TP])
def test_sharded_forward_matches_unsharded(eight_devices, rules, rng):
    img = rng.randn(8, 32, 32, 3).astype(np.float32)
    base = VisionTransformer(tiny_cfg(), rngs=nnx.Rngs(0))
    expected = np.asarray(base(jnp.asarray(img)))

    mesh = make_mesh({"data": 4, "model": 2})
    model = VisionTransformer(tiny_cfg(), rngs=nnx.Rngs(0), mesh=mesh,
                              rules=rules)
    with use_sharding(mesh, rules):
        batch = shard_batch(img, mesh, rules)
        out = nnx.jit(lambda m, x: m(x))(model, batch)
    np.testing.assert_allclose(np.asarray(out), expected, atol=2e-5)


def test_create_sharded_born_sharded(eight_devices):
    mesh = make_mesh({"data": 4, "model": 2})
    model = create_sharded(lambda: VisionTransformer(tiny_cfg(),
                                                     rngs=nnx.Rngs(0)),
                           mesh, FSDP_TP)
    k = nnx.state(model)["vision"]["encoder"]["blocks"]["attn"]["q"][
        "kernel"].get_value()
    assert k.sharding.spec == jax.sharding.PartitionSpec(None, "data", "model")


def test_from_pretrained_with_mesh(eight_devices, tmp_path, rng):
    """Params are placed sharded at load (ref `models/vit.py:237,254`)."""
    from hf_util import save_tiny_vit
    ckpt = save_tiny_vit(tmp_path)
    mesh = make_mesh({"data": 4, "model": 2})
    model = VisionTransformer.from_pretrained(ckpt, mesh=mesh,
                                              rules=TENSOR_PARALLEL)
    k = nnx.state(model)["vision"]["encoder"]["blocks"]["mlp"]["fc1"][
        "kernel"].get_value()
    assert k.sharding.spec == jax.sharding.PartitionSpec(None, None, "model")
    # and the sharded model still matches the unsharded load numerically
    plain = VisionTransformer.from_pretrained(ckpt)
    img = rng.randn(4, 48, 48, 3).astype(np.float32)
    with use_sharding(mesh, TENSOR_PARALLEL):
        out = nnx.jit(lambda m, x: m(x))(model,
                                         shard_batch(img, mesh,
                                                     TENSOR_PARALLEL))
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(plain(jnp.asarray(img))), atol=2e-5)


def test_fsdp_rules_on_text_tower(eight_devices, rng):
    """Regression: FSDP must not map vocab and embed onto the same mesh axis
    (token embedding is ("vocab", "embed"))."""
    from jimm_tpu import CLIP, CLIPConfig, TextConfig
    from jimm_tpu.configs import VisionConfig as VC
    cfg = CLIPConfig(
        vision=VC(image_size=32, patch_size=16, width=64, depth=2, num_heads=2,
                  mlp_dim=128, act="quick_gelu", ln_eps=1e-5, pooling="cls",
                  pre_norm=True, patch_bias=False),
        text=TextConfig(vocab_size=64, context_length=16, width=64, depth=2,
                        num_heads=2, mlp_dim=128),
        projection_dim=32)
    mesh = make_mesh({"data": 4, "model": 2})
    model = CLIP(cfg, rngs=nnx.Rngs(0), mesh=mesh, rules=FSDP)
    emb = nnx.state(model)["text"]["token_embed"]["embedding"].get_value()
    assert emb.sharding.spec == jax.sharding.PartitionSpec(None, "data")


def test_logical_constraint_partial_manual(eight_devices, monkeypatch):
    """Inside shard_map, manual axes are filtered from the constraint spec;
    constraints on still-auto axes of a partially-manual mesh survive
    (round-1 advisor finding: they were dropped wholesale). A spy on
    with_sharding_constraint pins WHAT was constrained — the numerics alone
    pass either way."""
    from jimm_tpu.utils.compat import shard_map

    from jimm_tpu.parallel.sharding import logical_constraint

    applied = []
    real = jax.lax.with_sharding_constraint

    def spy(x, spec):
        applied.append(spec)
        return real(x, spec)

    monkeypatch.setattr(jax.lax, "with_sharding_constraint", spy)

    mesh = make_mesh({"data": 4, "model": 2})
    x = jnp.arange(4 * 8 * 6, dtype=jnp.float32).reshape(4, 8, 6)

    def f_full(x):  # fully manual: must no-op (arrays are local)
        return logical_constraint(x, "batch", "seq", None) * 2

    def f_part(x):  # "data" manual, "model" auto: heads constraint applies
        return logical_constraint(x, "batch", None, "heads") * 2

    with use_sharding(mesh, FSDP_TP):
        y = shard_map(f_full, mesh=mesh, in_specs=P("data"),
                      out_specs=P("data"))(x)
        np.testing.assert_array_equal(np.asarray(y), np.asarray(x) * 2)
        assert applied == []  # fully manual: constraint dropped entirely
        y = shard_map(f_part, mesh=mesh, in_specs=P("data"),
                      out_specs=P("data"), axis_names={"data"})(x)
        np.testing.assert_array_equal(np.asarray(y), np.asarray(x) * 2)
        # manual "data" filtered out of the batch entry; auto "model" kept
        assert applied == [P(None, None, "model")]


@pytest.mark.slow
def test_hybrid_ring_no_involuntary_rematerialization(eight_devices, rng):
    """Regression for VERDICT r2 weak #3: on the hybrid (replica, data,
    model) mesh the FSDP-sharded token-embedding gather produced
    width-sharded activations that XLA could only reshard to the batch
    layout by full replication — the compile log filled with
    "[SPMD] Involuntary full rematerialization". The fix (nn/text.py)
    constrains the table to vocab-only sharding before the lookup.

    XLA emits the warning from C++ on fd 2, so capture the raw file
    descriptor (not sys.stderr) around the compile."""
    import os

    from flax import nnx as _nnx

    from jimm_tpu import SigLIP
    from jimm_tpu.configs import SigLIPConfig, TextConfig
    from jimm_tpu.configs import VisionConfig as VC
    from jimm_tpu.parallel import HYBRID_FSDP_TP
    from jimm_tpu.train import make_contrastive_train_step, make_optimizer
    from jimm_tpu.train.trainer import OptimizerConfig

    cfg = SigLIPConfig(
        vision=VC(image_size=32, patch_size=16, width=64, depth=2,
                  num_heads=2, mlp_dim=128, act="gelu_tanh", pooling="map",
                  remat=True),
        text=TextConfig(vocab_size=64, context_length=8, width=64, depth=2,
                        num_heads=2, mlp_dim=128, act="gelu_tanh",
                        causal=False, pooling="last", proj_bias=True,
                        remat=True),
        projection_dim=64)
    mesh = jax.sharding.Mesh(np.asarray(jax.devices()[:8]).reshape(2, 2, 2),
                             ("replica", "data", "model"))
    model = SigLIP(cfg, rngs=_nnx.Rngs(0), mesh=mesh, rules=HYBRID_FSDP_TP)
    opt = make_optimizer(model, OptimizerConfig(learning_rate=1e-3))
    step = make_contrastive_train_step("siglip_ring", mesh=mesh,
                                       axis_name=("replica", "data"))

    with use_sharding(mesh, HYBRID_FSDP_TP):
        images = shard_batch(rng.randn(8, 32, 32, 3).astype(np.float32),
                             mesh, HYBRID_FSDP_TP)
        text = shard_batch(rng.randint(1, 64, size=(8, 8)), mesh,
                           HYBRID_FSDP_TP)
        # capture into a FILE, not a pipe: if the regression reappears the
        # warnings repeat per HLO op and would fill a 64 KiB pipe buffer,
        # blocking XLA's write() mid-compile and wedging the test
        import tempfile
        with tempfile.TemporaryFile() as cap_file:
            saved = os.dup(2)
            os.dup2(cap_file.fileno(), 2)
            try:
                loss = float(step(model, opt, images, text)["loss"])
            finally:
                os.dup2(saved, 2)
                os.close(saved)
            cap_file.seek(0)
            captured = cap_file.read().decode(errors="replace")
    assert np.isfinite(loss)
    assert "Involuntary full rematerialization" not in captured, captured
