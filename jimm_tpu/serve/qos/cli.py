"""``jimm-tpu qos`` — inspect and validate QoS policy files.

Two verbs, stdlib only (no jax import — this must run on an operator
laptop or in a CI lint job):

- ``ls``       — parse a policy file and print its classes and tenants as
  a table (or ``--json`` for the machine-readable form).
- ``validate`` — parse and exit 0 on a clean policy, 1 with every problem
  listed on a malformed one (the pre-deploy gate).

Wired as a subparser under the main ``jimm-tpu`` CLI (see jimm_tpu/cli.py).
"""

from __future__ import annotations

import argparse
import json
import sys

from jimm_tpu.serve.qos.policy import QosPolicyError, load_policy

__all__ = ["add_qos_parser", "cmd_qos"]


def _fmt(value) -> str:
    return "-" if value is None else f"{value:g}" if isinstance(
        value, float) else str(value)


def _cmd_ls(args) -> int:
    try:
        registry = load_policy(args.policy)
    except QosPolicyError as e:
        print(f"invalid policy {args.policy}: {e}", file=sys.stderr)
        return 1
    desc = registry.describe()
    if args.json:
        print(json.dumps(desc, indent=2, sort_keys=True))
        return 0
    print(f"policy: {args.policy}")
    print("\nclasses (priority order; rank 0 shed last):")
    print(f"  {'name':<16} {'weight':>8} {'rank':>5}")
    for c in desc["classes"]:
        print(f"  {c['name']:<16} {c['weight']:>8g} {c['rank']:>5}")
    print("\ntenants:")
    header = (f"  {'name':<16} {'class':<14} {'rate/s':>8} {'burst':>7} "
              f"{'timeout_s':>10} {'max_queued':>11}")
    print(header)
    rows = desc["tenants"] + [dict(desc["default"],
                                   name=f"({desc['default']['name']})")]
    for t in rows:
        print(f"  {t['name']:<16} {t['klass']:<14} {_fmt(t['rate']):>8} "
              f"{_fmt(t['burst']):>7} {_fmt(t['timeout_s']):>10} "
              f"{_fmt(t['max_queued']):>11}")
    return 0


def _cmd_validate(args) -> int:
    try:
        registry = load_policy(args.policy)
    except QosPolicyError as e:
        print(f"INVALID {args.policy}")
        for problem in str(e).split("; "):
            print(f"  - {problem}")
        return 1
    print(f"OK {args.policy}: {len(registry.classes)} classes, "
          f"{len(registry.tenants)} tenants "
          f"(+ default -> {registry.default.klass!r})")
    return 0


def add_qos_parser(subparsers) -> None:
    """Attach the ``qos`` subcommand tree to the main CLI's subparsers."""
    p = subparsers.add_parser(
        "qos", help="inspect and validate serving QoS policy files")
    p.set_defaults(fn=cmd_qos)
    sub = p.add_subparsers(dest="qos_cmd", required=True)

    pl = sub.add_parser("ls", help="print a policy's classes and tenants")
    pl.add_argument("policy", help="policy file (.json or .toml)")
    pl.add_argument("--json", action="store_true",
                    help="print the parsed policy as JSON")
    pl.set_defaults(qos_func=_cmd_ls)

    pv = sub.add_parser("validate",
                        help="exit 0 iff the policy file is well-formed")
    pv.add_argument("policy", help="policy file (.json or .toml)")
    pv.set_defaults(qos_func=_cmd_validate)


def cmd_qos(args) -> int:
    return args.qos_func(args)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="jimm-tpu-qos")
    sub = parser.add_subparsers(dest="command", required=True)
    add_qos_parser(sub)
    args = parser.parse_args(argv)
    return cmd_qos(args)


if __name__ == "__main__":
    raise SystemExit(main())
