"""Tests for the whole-program layers: the flow graph (``lint.graph``),
the lock-discipline race detector (``lint.concurrency``), the jaxpr
invariant checks (``lint.jaxpr``), and the JL020 suppression meta-rule.

The concurrency fixtures live in ``tests/lint_fixtures/concurrency/`` —
each file pairs a seeded violation with a clean counterpart so every
assertion pins both the detection and the false-positive boundary.
"""

import json
import subprocess
import sys
import time
from pathlib import Path

import pytest

from jimm_tpu.lint import ERROR, WARNING, lint_file
from jimm_tpu.lint.concurrency import (apply_jl014_waivers, jl014_waivers,
                                       run_concurrency_checks)
from jimm_tpu.lint.core import (check_bare_suppressions, collect_files,
                                parse_directives, suppression_audit)
from jimm_tpu.lint.graph import ProjectGraph

FIXTURES = Path(__file__).parent / "lint_fixtures"
CONC = FIXTURES / "concurrency"
REPO = Path(__file__).resolve().parent.parent


def fixture_files(*names):
    return [str(CONC / n) for n in names]


def rules_and_lines(findings):
    return {(f.rule, f.line) for f in findings}


@pytest.fixture(scope="module")
def fixture_graph():
    return ProjectGraph.build(collect_files([str(CONC)]))


@pytest.fixture(scope="module")
def live_graph():
    return ProjectGraph.build(collect_files(
        [str(REPO / "jimm_tpu"), str(REPO / "tests")]))


class TestGraphInference:
    def test_thread_roots_discovered(self, fixture_graph):
        assert fixture_graph.roots_of("RacyCounter._drain_a") \
            == {"thread:_drain_a"}

    def test_http_handler_root_seeded(self, fixture_graph):
        assert fixture_graph.roots_of("FixtureHandler.do_GET") \
            == {"http-handler"}

    def test_roots_propagate_through_calls(self, fixture_graph):
        # _make_fn is only reachable via do_GET -> _respond -> _make_fn,
        # so it inherits the handler root interprocedurally
        assert "http-handler" in fixture_graph.roots_of(
            "FixtureHandler._make_fn")

    def test_caller_guarded_helper_inherits_both_thread_roots(
            self, fixture_graph):
        assert fixture_graph.roots_of("CallerGuardedCounter._bump") \
            == {"thread:_loop_a", "thread:_loop_b"}

    def test_guard_sets_infer_lexical_locks(self, fixture_graph):
        guards = fixture_graph.guard_sets("LockedCounter")
        assert guards.get("hits"), "hits writes should be guarded"
        assert all("_lock" in g for g in guards["hits"])

    def test_entry_guard_inference_covers_callers(self, fixture_graph):
        # CallerGuardedCounter._bump holds no lock lexically, but every
        # caller acquires self._lock first -> entry guards make it safe
        fn = fixture_graph.function("CallerGuardedCounter._bump")
        assert fn is not None
        assert fn.entry_guards, "entry guards should be inferred"

    def test_write_sites_exclude_init(self, fixture_graph):
        sites = fixture_graph.write_sites()
        for (owner, _attr), ws in sites.items():
            assert all(not w.in_init for w in ws), owner


class TestConcurrencyRules:
    def test_jl017_racy_counter(self):
        findings = run_concurrency_checks(fixture_files("racy_counter.py"))
        assert rules_and_lines(findings) == {("JL017", 24)}
        f = findings[0]
        assert f.severity == ERROR
        assert "thread:_drain_a" in f.message
        assert "thread:_drain_b" in f.message

    def test_jl017_silent_on_guarded_and_caller_guarded(self):
        # LockedCounter and CallerGuardedCounter live in the same file as
        # the violation; the single finding above already proves silence,
        # but pin it explicitly on a graph-level query too
        g = ProjectGraph.build(fixture_files("racy_counter.py"))
        findings = run_concurrency_checks(
            fixture_files("racy_counter.py"), graph=g)
        assert not any("LockedCounter" in f.message or
                       "CallerGuarded" in f.message for f in findings)

    def test_jl018_lock_order_cycle(self):
        findings = run_concurrency_checks(fixture_files("lock_cycle.py"))
        assert rules_and_lines(findings) == {("JL018", 21)}
        f = findings[0]
        assert f.severity == ERROR
        assert "_plan_lock" in f.message and "_stats_lock" in f.message

    def test_jl019_blocking_under_lock(self):
        findings = run_concurrency_checks(
            fixture_files("sleep_under_lock.py"))
        assert rules_and_lines(findings) == {
            ("JL019", 18),  # time.sleep under lock
            ("JL019", 23),  # queue.get under lock
            ("JL019", 32),  # queue.get under caller-held (entry) guard
        }

    def test_jl006_interprocedural(self):
        findings = run_concurrency_checks(
            fixture_files("async_device_wait.py"))
        assert rules_and_lines(findings) == {("JL006", 7)}

    def test_jl008_interprocedural(self):
        findings = run_concurrency_checks(fixture_files("handler_jit.py"))
        assert rules_and_lines(findings) == {("JL008", 18)}

    def test_jl023_inline_tier_io_on_request_path(self):
        fx = str(FIXTURES / "retrieval" / "tier" / "streaming_fetch.py")
        findings = run_concurrency_checks([fx])
        assert rules_and_lines(findings) == {
            ("JL023", 29),  # ArtifactStore.get three hops below do_GET
            ("JL023", 33),  # np.load on the do_POST path
        }
        assert all(f.severity == ERROR for f in findings)
        assert "prefetch" in findings[0].message

    def test_jl023_worker_split_and_daemon_io_are_clean(self):
        fx = str(FIXTURES / "retrieval" / "tier" / "streaming_fetch.py")
        findings = run_concurrency_checks([fx])
        assert not any("WorkerFetchHandler" in f.message or
                       "_daemon_cycle" in f.message for f in findings)

    def test_jl014_waived_by_base_class_eviction(self):
        child = CONC / "serve" / "child_table.py"
        per_file = [f for f in lint_file(child) if f.rule == "JL014"]
        assert rules_and_lines(per_file) == {("JL014", 10)}

        g = ProjectGraph.build(collect_files([str(CONC / "serve")]))
        assert any(attr == "_table" for _path, attr in jl014_waivers(g))
        waived = apply_jl014_waivers(list(per_file), g)
        assert waived == []

    def test_zero_false_positives_on_live_tree(self, live_graph):
        files = collect_files([str(REPO / "jimm_tpu"), str(REPO / "tests")])
        findings = run_concurrency_checks(files, graph=live_graph)
        assert findings == [], "\n".join(f.render() for f in findings)

    @pytest.mark.slow
    def test_full_tree_build_within_budget(self):
        # the hard 10 s wall-time gate runs in scripts/lint_bench.py on a
        # quiet runner; in-suite, allow 2x for contention with the rest of
        # the tests so this asserts "same order of magnitude", not luck
        t0 = time.perf_counter()
        files = collect_files([str(REPO / "jimm_tpu"), str(REPO / "tests")])
        g = ProjectGraph.build(files)
        run_concurrency_checks(files, graph=g)
        assert time.perf_counter() - t0 <= 20.0


class TestJl020Suppressions:
    def test_bare_suppression_warns(self, tmp_path):
        src = "import jax\nx = 1  # jaxlint: disable=JL008\n"
        findings = check_bare_suppressions(src, "foo.py")
        assert [(f.rule, f.line, f.severity) for f in findings] == [
            ("JL020", 2, WARNING)]
        assert "JL008" in findings[0].message

    def test_justified_suppression_is_silent(self):
        src = "x = 1  # jaxlint: disable=JL008 one compile per variant\n"
        assert check_bare_suppressions(src, "foo.py") == []

    def test_directive_parse_keeps_justification(self):
        src = ("a = 1  # jaxlint: disable=JL008,JL009 measured, on purpose\n"
               "# jaxlint: disable=JL013\n")
        directives = parse_directives(src)
        assert directives[0].rules == frozenset({"JL008", "JL009"})
        assert directives[0].justification == "measured, on purpose"
        assert directives[1].justification == ""
        # a full-line directive targets the NEXT line
        assert directives[1].target == 3

    def test_indented_standalone_directive_targets_next_line(self):
        # a comment-only line inside a block is still standalone, even
        # though its column is nonzero
        src = ("def f():\n"
               "    # jaxlint: disable=JL009 pinned probe config\n"
               "    g(block_q=128)\n")
        (d,) = parse_directives(src)
        assert d.target == 3
        assert d.justification == "pinned probe config"

    def test_audit_table_covers_tree(self):
        rows = suppression_audit([str(REPO / "jimm_tpu"),
                                  str(REPO / "scripts")])
        assert rows, "the tree has known, justified suppressions"
        bare = [r for r in rows if not r[3]]
        assert bare == [], f"bare suppressions in tree: {bare}"

    @pytest.mark.slow
    def test_shipped_tree_has_no_jl020(self):
        from jimm_tpu.lint import lint_paths
        findings = [f for f in lint_paths([str(REPO / "jimm_tpu")])
                    if f.rule == "JL020"]
        assert findings == [], "\n".join(f.render() for f in findings)


class TestJaxprLayer:
    def test_live_entries_match_goldens(self):
        from jimm_tpu.lint.jaxpr import run_jaxpr_checks
        findings = run_jaxpr_checks()
        assert findings == [], "\n".join(f.render() for f in findings)

    def test_jlt104_promotion_drift(self):
        import jax.numpy as jnp

        from jimm_tpu.lint.jaxpr import run_jaxpr_checks

        def bad_promo():
            def f(x):
                return x.astype(jnp.float32) * 2
            return f, (jnp.zeros((4,), jnp.int8),)

        findings = run_jaxpr_checks(
            entry_points={"bad_promo": bad_promo},
            goldens={"bad_promo": {"f32_promotions": 0,
                                   "collectives": {}}})
        assert [f.rule for f in findings] == ["JLT104"]
        assert findings[0].severity == ERROR
        assert findings[0].path == "<jaxpr:bad_promo>"

    def test_jlt105_baked_constant(self):
        import numpy as np

        import jax.numpy as jnp

        from jimm_tpu.lint.jaxpr import run_jaxpr_checks

        def bad_const():
            baked = jnp.asarray(np.ones((64, 64), np.float32))

            def f(x):
                return x + baked
            return f, (jnp.zeros((64, 64), jnp.float32),)

        findings = run_jaxpr_checks(
            entry_points={"bad_const": bad_const},
            goldens={"bad_const": {"f32_promotions": 99,
                                   "collectives": {}}})
        assert [f.rule for f in findings] == ["JLT105"]
        assert "16384 bytes" in findings[0].message

    def test_jlt106_collective_drift_and_missing_golden(self):
        import jax
        import jax.numpy as jnp

        from jimm_tpu.lint.jaxpr import run_jaxpr_checks

        def with_sum():
            def f(x):
                # jnp.sum has no collective; drift comes from the golden
                return jnp.sum(x)
            return f, (jnp.zeros((4,), jnp.float32),)

        # golden expects one psum -> observing zero is ERROR drift
        drift = run_jaxpr_checks(
            entry_points={"e": with_sum},
            goldens={"e": {"f32_promotions": 9,
                           "collectives": {"psum2": 1}}})
        assert [(f.rule, f.severity) for f in drift] == [("JLT106", ERROR)]

        # no golden at all -> WARNING nudging a goldens update
        missing = run_jaxpr_checks(entry_points={"e": with_sum}, goldens={})
        assert [(f.rule, f.severity) for f in missing] == [
            ("JLT106", WARNING)]
        assert "--update-goldens" in missing[0].message

    def test_broken_entry_becomes_jlt000(self):
        from jimm_tpu.lint.jaxpr import run_jaxpr_checks

        def broken():
            raise ValueError("fixture boom")

        findings = run_jaxpr_checks(entry_points={"broken": broken},
                                    goldens={})
        assert [(f.rule, f.severity) for f in findings] == [
            ("JLT000", ERROR)]
        assert "fixture boom" in findings[0].message

    def test_goldens_file_is_committed_and_complete(self):
        from jimm_tpu.lint.jaxpr import ENTRY_POINTS, GOLDENS_PATH
        goldens = json.loads(GOLDENS_PATH.read_text())
        assert set(goldens) == set(ENTRY_POINTS)
        assert goldens["data_parallel_psum"]["collectives"] == {"psum2": 1}


class TestCliIntegration:
    def run_cli(self, *args):
        return subprocess.run(
            [sys.executable, "-m", "jimm_tpu.lint", *args],
            capture_output=True, text=True, cwd=REPO,
        )

    def test_concurrency_flag_finds_fixture_race(self):
        proc = self.run_cli(str(CONC / "racy_counter.py"),
                            "--concurrency", "--json")
        assert proc.returncode == 1
        report = json.loads(proc.stdout)
        assert [(f["rule"], f["line"]) for f in report] == [("JL017", 24)]

    def test_sarif_export(self, tmp_path):
        out = tmp_path / "lint.sarif"
        proc = self.run_cli(str(CONC / "lock_cycle.py"), "--concurrency",
                            "--sarif", str(out))
        assert proc.returncode == 1
        sarif = json.loads(out.read_text())
        assert sarif["version"] == "2.1.0"
        run = sarif["runs"][0]
        assert run["tool"]["driver"]["name"] == "jaxlint"
        results = run["results"]
        assert [r["ruleId"] for r in results] == ["JL018"]
        assert results[0]["level"] == "error"
        loc = results[0]["locations"][0]["physicalLocation"]
        assert loc["region"]["startLine"] == 21

    def test_suppressions_flag_exits_zero(self):
        proc = self.run_cli("jimm_tpu", "--suppressions")
        assert proc.returncode == 0
        assert "directive(s)" in proc.stdout


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-v"]))
