"""Tests for the jimm_tpu.lint static analyzer (Layer 1 + CLI).

The fixtures under tests/lint_fixtures/ are excluded from normal lint walks
(see EXCLUDED_DIRS) and only linted when named explicitly, so the shipped
tree stays clean while each rule keeps a living positive example.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from jimm_tpu.lint import ERROR, lint_file, lint_paths
from jimm_tpu.lint.rules_ast import CANONICAL_MESH_AXES

FIXTURES = Path(__file__).parent / "lint_fixtures"
REPO = Path(__file__).resolve().parent.parent


def findings_for(name):
    return lint_file(FIXTURES / name)


def rules_and_lines(findings):
    return {(f.rule, f.line) for f in findings}


class TestRuleFixtures:
    def test_jl001_unguarded_version_gated_config(self):
        findings = findings_for("bad_config_gate.py")
        assert rules_and_lines(findings) == {("JL001", 6)}
        assert findings[0].severity == ERROR
        assert "jax_num_cpu_devices" in findings[0].message

    def test_jl002_host_sync_in_jit(self):
        findings = findings_for("bad_host_sync.py")
        assert rules_and_lines(findings) == {
            ("JL002", 9),   # float() on traced value
            ("JL002", 10),  # np.asarray on traced value
            ("JL002", 11),  # Python `if` on traced value
            ("JL002", 13),  # .item()
        }

    def test_jl003_missing_donation(self):
        findings = findings_for("bad_donation.py")
        assert rules_and_lines(findings) == {
            ("JL003", 8),   # optimizer-carrying nnx.jit without donate_argnums
            ("JL003", 15),  # builder call without donate=
        }

    def test_jl004_non_canonical_partition_spec(self):
        findings = findings_for("bad_partition_spec.py")
        assert rules_and_lines(findings) == {("JL004", 9)}
        assert "'batch'" in findings[0].message

    def test_jl005_pallas_tiling_and_vmem(self):
        findings = findings_for("bad_pallas.py")
        assert rules_and_lines(findings) == {
            ("JL005", 11),  # lane dim 100 not %128
            ("JL005", 12),  # sublane dim 12 not %8
            ("JL005", 13),  # VMEM scratch over budget
        }

    def test_jl005_budget_is_configurable(self):
        findings = lint_file(FIXTURES / "bad_pallas.py",
                             vmem_budget=256 * 1024 * 1024)
        # with a 256 MiB budget the 64 MiB scratch is fine; tiling still fires
        assert rules_and_lines(findings) == {("JL005", 11), ("JL005", 12)}

    def test_jl006_async_host_sync_in_serve(self):
        findings = findings_for("serve/bad_async_sync.py")
        assert rules_and_lines(findings) == {
            ("JL006", 8),   # np.asarray on the event loop
            ("JL006", 10),  # .block_until_ready() on the event loop
            ("JL006", 11),  # .item() on the event loop
        }
        assert all(f.severity == ERROR for f in findings)
        # sync helpers and executor lambdas in the same file stay clean
        assert not any(f.line > 11 for f in findings)

    def test_jl006_scoped_to_serve_paths(self):
        # the identical source outside a serve/ path segment is not JL006's
        # business (general async code may sync freely)
        import ast

        from jimm_tpu.lint.rules_ast import check_async_host_sync
        src = (FIXTURES / "serve" / "bad_async_sync.py").read_text()
        tree = ast.parse(src)
        assert check_async_host_sync(tree, "jimm_tpu/train/loop.py") == []
        assert check_async_host_sync(tree, "jimm_tpu/serve/engine.py") != []

    def test_jl007_bare_print_in_library_code(self):
        findings = findings_for("jimm_tpu/bad_print.py")
        # line 10 fires; the suppressed print on 15 and the logger call on
        # 20 stay clean
        assert rules_and_lines(findings) == {("JL007", 10)}
        assert findings[0].severity == ERROR
        assert "obs" in findings[0].message

    def test_jl007_scoped_to_library_paths(self):
        import ast

        from jimm_tpu.lint.rules_ast import check_bare_print
        src = (FIXTURES / "jimm_tpu" / "bad_print.py").read_text()
        tree = ast.parse(src)
        # CLI entry points, scripts, and tests are print's legitimate home
        assert check_bare_print(tree, "jimm_tpu/cli.py") == []
        assert check_bare_print(tree, "jimm_tpu/obs/cli.py") == []
        assert check_bare_print(tree, "jimm_tpu/__main__.py") == []
        assert check_bare_print(tree, "jimm_tpu/launch.py") == []
        assert check_bare_print(tree, "scripts/serve_bench.py") == []
        assert check_bare_print(tree, "tests/test_obs.py") == []
        # library modules are not
        assert check_bare_print(tree, "jimm_tpu/train/metrics.py") != []
        assert check_bare_print(tree, "jimm_tpu/serve/engine.py") != []

    def test_jl008_jit_in_loop(self):
        findings = findings_for("bad_jit_in_loop.py")
        assert rules_and_lines(findings) == {
            ("JL008", 10),  # jax.jit call in for body
            ("JL008", 13),  # nnx.jit call in while body
            ("JL008", 17),  # jit-decorated def in loop body
        }
        assert all(f.severity == ERROR for f in findings)
        assert any("AOT" in f.message for f in findings)
        # hoisted_ok (jit once, reuse) and the suppressed site stay clean

    def test_jl008_handler_and_test_scoping(self):
        import ast

        from jimm_tpu.lint.rules_ast import check_jit_in_loop
        from jimm_tpu.lint.rules_ast import _annotate_parents
        src = (
            "import jax\n"
            "class H:\n"
            "    def do_GET(self):\n"
            "        f = jax.jit(lambda x: x)\n"
            "async def handle(req):\n"
            "    g = jax.jit(lambda x: x)\n"
        )
        tree = ast.parse(src)
        _annotate_parents(tree)
        # do_GET fires anywhere; the async def only in serving code
        lib = check_jit_in_loop(tree, "jimm_tpu/train/loop.py")
        assert {(f.rule, f.line) for f in lib} == {("JL008", 4)}
        serve = check_jit_in_loop(tree, "jimm_tpu/serve/server.py")
        assert {(f.rule, f.line) for f in serve} == {("JL008", 4),
                                                    ("JL008", 6)}
        # tests construct jits per-case on purpose
        assert check_jit_in_loop(tree, "tests/test_serve.py") == []

    def test_jl009_block_size_literal(self):
        findings = findings_for("bad_block_literal.py")
        assert rules_and_lines(findings) == {
            ("JL009", 8),   # block_q=128
            ("JL009", 9),   # block_k=256
            ("JL009", 12),  # block_rows=64
            ("JL009", 27),  # flash_attention_masked block_q=128 — the rule
            ("JL009", 28),  # keys on kwarg names, so variants are covered
        }
        assert all(f.severity == ERROR for f in findings)
        assert any("best_config" in f.message for f in findings)
        # the suppressed pin, the named-constant kwarg, the def-site
        # default, and block_rows=None all stay clean

    def test_jl009_ops_tune_and_test_paths_exempt(self):
        import ast

        from jimm_tpu.lint.rules_ast import check_block_size_literal
        src = "flash_attention(q, k, v, block_q=128)\n"
        tree = ast.parse(src)
        assert check_block_size_literal(tree, "jimm_tpu/serve/engine.py")
        # ops defaults and the tuner's bench closures are the mechanism
        assert check_block_size_literal(
            tree, "jimm_tpu/ops/flash_attention.py") == []
        assert check_block_size_literal(tree, "jimm_tpu/tune/api.py") == []
        # tests pin blocks to exercise specific configs on purpose
        assert check_block_size_literal(tree, "tests/test_ops.py") == []

    def test_jl010_unplaced_device_put(self):
        findings = findings_for("serve/bad_device_put.py")
        assert rules_and_lines(findings) == {
            ("JL010", 7),   # jax.device_put(np.asarray(...)) — no placement
            ("JL010", 8),   # jax.device_put(padded) — no placement
        }
        assert all(f.severity == ERROR for f in findings)
        assert any("NamedSharding" in f.message for f in findings)
        # explicit positional/keyword placements and the suppressed put
        # (lines 10-14) stay clean

    def test_jl010_scoped_to_serve_and_parallel_paths(self):
        import ast

        from jimm_tpu.lint.rules_ast import check_device_put_placement
        src = "import jax\nx = jax.device_put(batch)\n"
        tree = ast.parse(src)
        assert check_device_put_placement(
            tree, "jimm_tpu/serve/topology.py") != []
        assert check_device_put_placement(
            tree, "jimm_tpu/parallel/sharding.py") != []
        # elsewhere the default device IS the contract (single-device code)
        assert check_device_put_placement(
            tree, "jimm_tpu/data/pipeline.py") == []
        assert check_device_put_placement(
            tree, "jimm_tpu/weights/loader.py") == []

    def test_jl011_host_sort(self):
        findings = findings_for("retrieval/host_sort.py")
        assert rules_and_lines(findings) == {
            ("JL011", 8),   # np.argsort over host copy of device scores
            ("JL011", 9),   # np.sort
            ("JL011", 10),  # jnp.argsort
            ("JL011", 11),  # sorted() over array-derived data
        }
        assert all(f.severity == ERROR for f in findings)
        assert any("lax.top_k" in f.message for f in findings)
        # np.lexsort over bounded candidates, sorted() on plain python
        # data, and the suppressed deliberate sort (lines 16-25) stay clean

    def test_jl011_ivf_merge_fixture(self):
        findings = findings_for("retrieval/ann_merge.py")
        assert rules_and_lines(findings) == {
            ("JL011", 9),   # np.argsort over probed candidate scores
            ("JL011", 10),  # sorted() over array-derived candidates
        }
        assert all(f.severity == ERROR for f in findings)
        # the lexsort-based bounded merge (merge_probed_candidates_ok)
        # stays clean — it is the idiom ivf.py actually uses

    def test_jl011_scoped_to_serve_and_retrieval_paths(self):
        import ast

        from jimm_tpu.lint.rules_ast import check_host_sort
        src = "import numpy as np\norder = np.argsort(-scores)\n"
        tree = ast.parse(src)
        assert check_host_sort(tree, "jimm_tpu/serve/server.py") != []
        assert check_host_sort(tree, "jimm_tpu/retrieval/topk.py") != []
        # retrieval/ann/ is covered by construction: the path test is
        # "retrieval" anywhere in the parts, so the new subpackage (and
        # any future one) inherits the rule without a lint change
        assert check_host_sort(
            tree, "jimm_tpu/retrieval/ann/ivf.py") != []
        assert check_host_sort(
            tree, "jimm_tpu/retrieval/ann/kmeans.py") != []
        # elsewhere a host sort is unexceptional (CLI display, training
        # eval), and test oracles *should* argsort
        assert check_host_sort(tree, "jimm_tpu/cli.py") == []
        assert check_host_sort(tree, "jimm_tpu/train/loop.py") == []
        assert check_host_sort(tree, "tests/test_retrieval.py") == []

    def test_jl012_quant_upcast(self):
        findings = findings_for("ops/int8_bad_upcast.py")
        assert rules_and_lines(findings) == {
            ("JL012", 7),   # bare .astype(jnp.float32) on the accumulator
            ("JL012", 8),   # jax.lax.convert_element_type(..., jnp.float32)
            ("JL012", 9),   # string dtype spelling .astype("float32")
        }
        assert all(f.severity == ERROR for f in findings)
        assert any("_dequant" in f.message for f in findings)
        # the _dequant/quantize_rows sanctioned sites, the bf16 epilogue,
        # and the suppressed deliberate upcast (lines 13-29) stay clean

    def test_jl012_scoped_to_quant_ops_paths(self):
        import ast

        from jimm_tpu.lint.rules_ast import check_quant_upcast
        src = "y = acc.astype(jnp.float32)\n"
        tree = ast.parse(src)
        assert check_quant_upcast(tree, "jimm_tpu/ops/int8_matmul.py") != []
        assert check_quant_upcast(
            tree, "jimm_tpu/ops/flash_attention_int8.py") != []
        assert check_quant_upcast(tree, "jimm_tpu/quant/__init__.py") != []
        # non-quantized ops and the rest of the tree upcast freely (f32 IS
        # their compute dtype), and tests compare against f32 on purpose
        assert check_quant_upcast(
            tree, "jimm_tpu/ops/flash_attention.py") == []
        assert check_quant_upcast(tree, "jimm_tpu/ops/layer_norm.py") == []
        assert check_quant_upcast(tree, "jimm_tpu/train/loop.py") == []
        assert check_quant_upcast(tree, "tests/test_int8_ops.py") == []

    def test_jl013_swallowed_exception(self):
        findings = findings_for("serve/bad_swallow.py")
        assert rules_and_lines(findings) == {
            ("JL013", 7),   # except Exception: pass
            ("JL013", 14),  # bare except: pass
        }
        assert all(f.severity == ERROR for f in findings)
        assert any("supervisor" in f.message for f in findings)
        # the narrow OSError swallow, the justified suppression, and the
        # handler that acts on the failure (lines 18-39) stay clean

    def test_jl013_scoped_to_resilience_critical_paths(self):
        import ast

        from jimm_tpu.lint.rules_ast import check_swallowed_exception
        src = "try:\n    f()\nexcept Exception:\n    pass\n"
        tree = ast.parse(src)
        assert check_swallowed_exception(
            tree, "jimm_tpu/serve/engine.py") != []
        assert check_swallowed_exception(
            tree, "jimm_tpu/train/checkpoint.py") != []
        assert check_swallowed_exception(
            tree, "jimm_tpu/resilience/supervisor.py") != []
        # the rest of the tree (and all tests) may use best-effort
        # swallows without a justification comment
        assert check_swallowed_exception(
            tree, "jimm_tpu/weights/resolve.py") == []
        assert check_swallowed_exception(
            tree, "jimm_tpu/obs/registry.py") == []
        assert check_swallowed_exception(
            tree, "tests/test_serve.py") == []
        assert check_swallowed_exception(
            tree, "jimm_tpu/serve/test_helpers.py") == []

    def test_jl014_unbounded_tenant_table(self):
        findings = findings_for("serve/bad_tenant_growth.py")
        assert rules_and_lines(findings) == {
            ("JL014", 12),  # self.per_tenant[tenant_id] = ..., no eviction
            ("JL014", 16),  # .setdefault(tenant_id, ...), same hole
        }
        assert all(f.severity == ERROR for f in findings)
        assert any("adversary" in f.message for f in findings)
        # the evicting router, the config-keyed ledger, the bounded LRU,
        # and the justified suppression (lines 20-59) stay clean

    def test_jl014_scoped_to_serve_library_paths(self):
        import ast

        from jimm_tpu.lint.rules_ast import (_annotate_parents,
                                             check_unbounded_tenant_table)
        src = ("class T:\n"
               "    def on_request(self, tenant):\n"
               "        self.seen[tenant] = 1\n")
        tree = ast.parse(src)
        _annotate_parents(tree)
        assert check_unbounded_tenant_table(
            tree, "jimm_tpu/serve/qos/scheduler.py") != []
        assert check_unbounded_tenant_table(
            tree, "jimm_tpu/serve/server.py") != []
        # non-serving code tracks what it likes, and tests build ad-hoc
        # tables on purpose
        assert check_unbounded_tenant_table(
            tree, "jimm_tpu/train/loop.py") == []
        assert check_unbounded_tenant_table(
            tree, "jimm_tpu/obs/registry.py") == []
        assert check_unbounded_tenant_table(
            tree, "tests/test_serve.py") == []
        assert check_unbounded_tenant_table(
            tree, "jimm_tpu/serve/test_helpers.py") == []

    def test_jl015_journal_bypass(self):
        findings = findings_for("resilience/bad_event_print.py")
        assert rules_and_lines(findings) == {
            ("JL015", 8),   # print(json.dumps(...))
            ("JL015", 12),  # "..." + json.dumps(...) concat
            ("JL015", 16),  # f-string interpolating json.dumps(...)
        }
        assert all(f.severity == ERROR for f in findings)
        assert any("flight-recorder" in f.message for f in findings)
        # the justified ready-line and the journal emitter (lines 19-28)
        # stay clean

    def test_jl015_scoped_to_resilience_paths_not_cli(self):
        import ast

        from jimm_tpu.lint.rules_ast import check_journal_bypass
        src = "import json\nprint(json.dumps({'a': 1}))\n"
        tree = ast.parse(src)
        assert check_journal_bypass(
            tree, "jimm_tpu/resilience/supervisor.py") != []
        assert check_journal_bypass(
            tree, "jimm_tpu/serve/engine.py") != []
        assert check_journal_bypass(
            tree, "jimm_tpu/train/loop.py") != []
        # CLI entry points keep their sanctioned parseable ready-lines,
        # tests print what they like, and the rest of the tree is JL007's
        # jurisdiction
        assert check_journal_bypass(tree, "jimm_tpu/cli.py") == []
        assert check_journal_bypass(tree, "jimm_tpu/launch.py") == []
        assert check_journal_bypass(tree, "tests/test_serve.py") == []
        assert check_journal_bypass(
            tree, "jimm_tpu/obs/registry.py") == []

    def test_jl016_bare_lowp_cast(self):
        findings = findings_for("ops/lowp_bad_cast.py")
        assert rules_and_lines(findings) == {
            ("JL016", 7),   # bare .astype(jnp.float8_e4m3fn)
            ("JL016", 8),   # jax.lax.convert_element_type(..., e5m2)
            ("JL016", 9),   # string dtype spelling .astype("int8")
        }
        assert all(f.severity == ERROR for f in findings)
        assert any("quantize_tensor" in f.message for f in findings)
        # the quantize/scale sanctioned sites, the expression-derived
        # dtype, and the suppressed deliberate cast (lines 13-28) stay
        # clean

    def test_jl016_scoped_to_ops_and_train_paths(self):
        import ast

        from jimm_tpu.lint.rules_ast import check_bare_lowp_cast
        src = "y = x.astype(jnp.float8_e4m3fn)\n"
        tree = ast.parse(src)
        assert check_bare_lowp_cast(
            tree, "jimm_tpu/ops/fp8_matmul.py") != []
        assert check_bare_lowp_cast(
            tree, "jimm_tpu/train/trainer.py") != []
        # checkpoint rewrite code stores int8 as a format, not a numerics
        # decision; tests compare against raw casts on purpose
        assert check_bare_lowp_cast(
            tree, "jimm_tpu/weights/quantize.py") == []
        assert check_bare_lowp_cast(tree, "tests/test_fp8_ops.py") == []
        # the quantizer's own cast is sanctioned by its enclosing name
        from jimm_tpu.lint.rules_ast import _annotate_parents
        src_ok = ("def quantize_rows(x, s):\n"
                  "    return (x / s).astype(jnp.int8)\n")
        tree_ok = ast.parse(src_ok)
        _annotate_parents(tree_ok)
        assert check_bare_lowp_cast(
            tree_ok, "jimm_tpu/ops/int8_matmul.py") == []

    def test_jl021_cascade_threshold_literals(self):
        findings = findings_for("serve/cascade/bad_threshold.py")
        assert rules_and_lines(findings) == {
            ("JL021", 4),   # def route(..., escalation_threshold=0.95)
            ("JL021", 6),   # confidence >= 0.92
            ("JL021", 14),  # self.confidence_floor = 0.9
            ("JL021", 15),  # self.margin_threshold: float = -0.05
            ("JL021", 18),  # make_router(..., threshold=0.88)
        }
        assert all(f.severity == ERROR for f in findings)
        assert any("cascade calibrate" in f.message for f in findings)
        # loading calibration.threshold, round(confidence, 6), and the
        # variable-vs-variable comparison (lines 24-31) stay clean

    def test_jl021_scoped_to_cascade_outside_calibrate(self):
        import ast

        from jimm_tpu.lint.rules_ast import check_cascade_thresholds
        src = "threshold = 0.92\n"
        tree = ast.parse(src)
        assert check_cascade_thresholds(
            tree, "jimm_tpu/serve/cascade/router.py") != []
        assert check_cascade_thresholds(
            tree, "jimm_tpu/serve/cascade/autoscale.py") != []
        # the fitter is the one place thresholds legitimately live
        assert check_cascade_thresholds(
            tree, "jimm_tpu/serve/cascade/calibrate.py") == []
        # outside the cascade package the marks mean nothing
        assert check_cascade_thresholds(
            tree, "jimm_tpu/serve/engine.py") == []
        assert check_cascade_thresholds(
            tree, "jimm_tpu/retrieval/cascade.py") == []
        assert check_cascade_thresholds(
            tree, "tests/test_cascade.py") == []

    def test_jl022_profiler_bypass(self):
        findings = findings_for("train/bad_profiler.py")
        assert rules_and_lines(findings) == {
            ("JL022", 9),   # jax.profiler.start_trace(log_dir)
            ("JL022", 12),  # jax.profiler.stop_trace()
            ("JL022", 16),  # start_trace(log_dir) — from-import spelling
            ("JL022", 18),  # stop_trace()
        }
        assert all(f.severity == ERROR for f in findings)
        assert any("profiler_session" in f.message for f in findings)
        # the disabled direct call, the profiler_session route, and the
        # session-agnostic TraceAnnotation all stay clean

    def test_jl022_scoped_to_outside_obs_prof(self):
        import ast

        from jimm_tpu.lint.rules_ast import check_profiler_bypass
        src = "import jax\njax.profiler.start_trace('/tmp/x')\n"
        tree = ast.parse(src)
        assert check_profiler_bypass(
            tree, "jimm_tpu/train/profile.py") != []
        assert check_profiler_bypass(
            tree, "jimm_tpu/serve/engine.py") != []
        # the sanctioned session owner and tests are exempt
        assert check_profiler_bypass(
            tree, "jimm_tpu/obs/prof/capture.py") == []
        assert check_profiler_bypass(
            tree, "tests/test_profile.py") == []

    def test_jl024_seqpar_discipline(self):
        findings = findings_for("parallel/seqpar_bad.py")
        assert rules_and_lines(findings) == {
            ("JL024", 15),  # all_gather — from-import spelling
            ("JL024", 19),  # jax.lax.all_gather on the KV chunk
            ("JL024", 24),  # dense (S, S) score einsum outside a hop fn
        }
        assert all(f.severity == ERROR for f in findings)
        assert any("ppermute" in f.message for f in findings)
        # the per-hop tile (_hop_scores_ok), the sanctioned ppermute, the
        # projection einsum, and the justified mask gather all stay clean

    def test_jl024_scoped_to_seqpar_modules(self):
        import ast

        from jimm_tpu.lint.rules_ast import check_seqpar_discipline
        src = "import jax\nx = jax.lax.all_gather(k, 'seq')\n"
        tree = ast.parse(src)
        assert check_seqpar_discipline(
            tree, "jimm_tpu/parallel/seqpar.py") != []
        # the zigzag ring module and the ring losses gather on purpose
        # (loss terms, not KV) — only seqpar* carries the contract
        assert check_seqpar_discipline(
            tree, "jimm_tpu/parallel/ring_attention.py") == []
        assert check_seqpar_discipline(
            tree, "jimm_tpu/train/losses.py") == []
        assert check_seqpar_discipline(
            tree, "tests/test_seqpar.py") == []

    def test_jl024_pv_contraction_not_score_shaped(self):
        from jimm_tpu.lint.rules_ast import _einsum_is_dense_scores
        assert _einsum_is_dense_scores("bqnd,bknd->bnqk")
        assert _einsum_is_dense_scores("bqd,bkd->bqk")
        # p @ V, grad contractions, and projections are contractions over
        # one of the two sequence axes — not materialized scores
        assert not _einsum_is_dense_scores("bnqk,bknd->bqnd")
        assert not _einsum_is_dense_scores("bnqk,bqnd->bknd")
        assert not _einsum_is_dense_scores("bsnd,ndh->bsh")

    def test_clean_counterexamples_and_suppression(self):
        # guarded config, canonical specs, static branches, and both
        # same-line and next-line `# jaxlint: disable=` forms: no findings
        assert findings_for("clean.py") == []

    def test_jl002_alias_of_static_metadata_not_tainted(self, tmp_path):
        # regression: `dtype = x.dtype` then branching on `dtype` used to
        # taint the alias and flag a perfectly static branch
        src = (
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    dtype = x.dtype\n"
            "    if dtype == 'int8':\n"
            "        x = x + 1\n"
            "    y = x * 2\n"
            "    if y > 0:\n"          # line 8: genuinely traced branch
            "        x = x - 1\n"
            "    return x\n"
        )
        p = tmp_path / "alias.py"
        p.write_text(src)
        assert rules_and_lines(lint_file(p)) == {("JL002", 8)}


class TestTreeInvariants:
    def test_canonical_axes_match_mesh_module(self):
        from jimm_tpu.parallel.mesh import MESH_AXES
        assert CANONICAL_MESH_AXES == frozenset(MESH_AXES)

    def test_fixtures_excluded_from_directory_walks(self):
        findings = lint_paths([str(FIXTURES.parent)])
        assert not any("lint_fixtures" in f.path for f in findings)

    def test_shipped_tree_is_clean(self):
        findings = [f for f in lint_paths([str(REPO / "jimm_tpu")])
                    if f.severity == ERROR]
        assert findings == [], "\n".join(f.render() for f in findings)


class TestCli:
    def run_cli(self, *args):
        return subprocess.run(
            [sys.executable, "-m", "jimm_tpu.lint", *args],
            capture_output=True, text=True, cwd=REPO,
        )

    def test_broken_fixture_fails_with_json_report(self):
        proc = self.run_cli(str(FIXTURES / "bad_partition_spec.py"), "--json")
        assert proc.returncode == 1
        report = json.loads(proc.stdout)
        assert [(f["rule"], f["line"]) for f in report] == [("JL004", 9)]
        assert report[0]["path"].endswith("bad_partition_spec.py")
        assert report[0]["severity"] == "error"

    def test_clean_fixture_exits_zero(self):
        proc = self.run_cli(str(FIXTURES / "clean.py"), "--json")
        assert proc.returncode == 0
        assert json.loads(proc.stdout) == []


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-v"]))
