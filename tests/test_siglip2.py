"""SigLIP2 parity vs the HF ``Siglip2Model`` oracle (capability anchor:
ref `README.md:13-14` "SigLIP v1 and v2, any non-NaFlex variant" — which the
reference asserts but never tests; transformers ships a *distinct*
``Siglip2Model`` class whose checkpoints differ from Siglip's).

Checkpoint-format deltas covered here: NaFlex Linear patch embedding
(out, p*p*3) instead of Conv2d OIHW, and a ``num_patches``-sized position
table. The oracle is driven at the fixed square resolution (spatial shape ==
native grid), where NaFlex packing reduces to v1 semantics.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from jimm_tpu import SigLIP

from hf_util import (sample_image, sample_text, save_tiny_siglip2,
                     siglip2_pixel_inputs)


@pytest.fixture(scope="module")
def ckpt(tmp_path_factory):
    return save_tiny_siglip2(tmp_path_factory.mktemp("siglip2"))


@pytest.fixture(scope="module")
def oracle(ckpt):
    from transformers import Siglip2Model
    return Siglip2Model.from_pretrained(ckpt).eval()


def test_vision_tower_parity(ckpt, oracle, rng):
    """MAP-head pooled output vs the Siglip2 pooler (three-stage parity,
    stage 1 — ref `tests/test_siglip.py:36` shape)."""
    import torch
    model = SigLIP.from_pretrained(ckpt)
    img = sample_image(rng)
    inputs = siglip2_pixel_inputs(img)
    with torch.no_grad():
        # the vision submodule names the mask `attention_mask` (the
        # top-level Siglip2Model calls it `pixel_attention_mask`)
        ref = oracle.vision_model(
            pixel_values=inputs["pixel_values"],
            attention_mask=inputs["pixel_attention_mask"],
            spatial_shapes=inputs["spatial_shapes"]).pooler_output.numpy()
    np.testing.assert_allclose(np.asarray(model.encode_image(jnp.asarray(img))),
                               ref, atol=1e-4)


def test_text_tower_parity(ckpt, oracle, rng):
    import torch
    model = SigLIP.from_pretrained(ckpt)
    txt = sample_text(rng)
    with torch.no_grad():
        ref = oracle.get_text_features(torch.tensor(txt)).numpy()
    np.testing.assert_allclose(np.asarray(model.encode_text(jnp.asarray(txt))),
                               ref, atol=1e-4)


def test_logits_parity(ckpt, oracle, rng):
    import torch
    model = SigLIP.from_pretrained(ckpt)
    img, txt = sample_image(rng), sample_text(rng)
    ours = np.asarray(model(jnp.asarray(img), jnp.asarray(txt)))
    with torch.no_grad():
        theirs = oracle(input_ids=torch.tensor(txt),
                        **siglip2_pixel_inputs(img)).logits_per_image.numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-4)


def test_num_patches_table_resamples_to_grid(tmp_path, rng):
    """A v2 table sized by a LARGER num_patches than the load grid (the
    NaFlex maximum) is bilinearly resampled at load instead of erroring."""
    ckpt = save_tiny_siglip2(tmp_path, num_patches=16)  # 4x4 table
    model = SigLIP.from_pretrained(ckpt)
    # no image_size in v2 configs: inferred from the table (4*16 = 64px)
    assert model.config.vision.image_size == 64
    # and an explicit lower resolution forces the 4x4 -> 2x2 resample
    small = SigLIP.from_pretrained(ckpt, image_size=32)
    out = small(jnp.asarray(sample_image(rng)),
                jnp.asarray(sample_text(rng)))
    assert out.shape == (2, 2) and np.isfinite(np.asarray(out)).all()


def test_shape_inference_without_config(ckpt, tmp_path, rng):
    """Config-free load: patch size inferred from the 2-D Linear weight."""
    import os
    import shutil
    d = tmp_path / "noconfig"
    d.mkdir()
    shutil.copy(os.path.join(ckpt, "model.safetensors"), d)
    model = SigLIP.from_pretrained(str(d / "model.safetensors"))
    assert model.config.vision.patch_size == 16
    assert model.config.vision.pooling == "map"
    out = model(jnp.asarray(sample_image(rng)), jnp.asarray(sample_text(rng)))
    assert out.shape == (2, 2)


def test_save_pretrained_flavors(ckpt, tmp_path):
    """A Siglip2-origin model round-trips natively by default (flavor
    matches the source checkpoint — `tests/test_export.py` proves
    Siglip2Model reloads it); the explicit v1 downgrade warns (ADVICE r3
    #1: the patch embed becomes Conv2d OIHW, Siglip2Model cannot reload)
    but stays a valid v1 export."""
    model = SigLIP.from_pretrained(ckpt)
    assert model._hf_source_flavor == "siglip2"
    model.save_pretrained(tmp_path / "native")  # no warning
    again = SigLIP.from_pretrained(str(tmp_path / "native"))
    assert again._hf_source_flavor == "siglip2"
    with pytest.warns(UserWarning, match="SiglipModel"):
        model.save_pretrained(tmp_path / "v1", flavor="siglip")
    again = SigLIP.from_pretrained(str(tmp_path / "v1"))
    assert again._hf_source_flavor == "siglip"
