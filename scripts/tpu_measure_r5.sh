#!/bin/bash
# Round-5 resident TPU measurement watcher (VERDICT r4 item 1).
#
# Runs from round start. Probes the axon tunnel every 120 s; whenever it is
# up, runs the next not-yet-done phase of the measurement queue. Each phase
# is marked done in $STATE so a tunnel drop mid-queue resumes at the next
# phase in the next window. Every JSON line a tool prints is appended to
# the committed /root/repo/MEASUREMENTS.jsonl, tagged with timestamp, phase,
# attempt number and the attempt's exit code (so superseded partial results
# from a timed-out attempt are distinguishable from the final ones).
#
# Queue order (VERDICT r4 item 1): lever sweep -> adopt into defaults ->
# bench.py -> ViT-L/16-384 train MFU -> compiled-flash parity -> vmem probe
# -> inference bench -> attn crossover -> long-context. New-in-r5 phases are
# gated on their script existing so the watcher can run before they land.
#
# The single chip must never be shared between processes: all TPU work
# (this watcher and any interactive run) must hold flock on $LOCK.
set -u
cd /root/repo
LOG=/tmp/measure_r5.log
LOCK=/tmp/tpu.lock
STATE=/tmp/measure_r5_state
MAX_TRIES=12   # per NO-PROGRESS phase attempt; an attempt that lands at
               # least one new measurement refunds its try (see run_phase),
               # so a flaky tunnel can't walk a resumable phase to gave_up
               # while every window still moves the grid forward
LOCK_BUSY=200  # flock -E code: lock held elsewhere — not the phase's fault
mkdir -p "$STATE"
exec >> "$LOG" 2>&1

probe() {
  # -w: a hung lock holder (tunnel-blocked interactive run) must read as
  # "tunnel down", not block the watcher forever
  flock -w 60 "$LOCK" timeout 90 python -c "
import jax
x = (jax.numpy.ones((256,256)) @ jax.numpy.ones((256,256)))
assert float(x[0,0]) == 256.0" 2>/dev/null
}

persist() {  # persist <phase> <logfile> <attempt> <rc>
  python - "$1" "$2" "$3" "$4" <<'EOF'
import json, sys, time
phase, path, attempt, rc = sys.argv[1:5]
out = open("/root/repo/MEASUREMENTS.jsonl", "a")
for line in open(path, errors="replace"):
    line = line.strip()
    if not (line.startswith("{") and line.endswith("}")):
        continue
    try:
        rec = json.loads(line)
    except Exception:
        continue
    # skip-resume notices carry no measurement — persisting one per variant
    # per window bloats the ledger without adding a datapoint
    if set(rec) - {"variant", "model", "metric", "case"} == {"skipped"}:
        continue
    rec = {"ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
           "phase": phase, "attempt": int(attempt), "rc": int(rc), **rec}
    out.write(json.dumps(rec) + "\n")
out.close()
EOF
}

bench_clean() {  # did the bench phase log produce a real TPU datapoint?
  python - "$1" <<'EOF'
import json, sys
# bench.py's contract: the LAST parseable result line is authoritative — a
# datapoint-first emission ("mfu_crosscheck": "pending") is superseded by
# the final line, which may have withheld the metric (value 0 + mfu_error)
last = None
for line in open(sys.argv[1], errors="replace"):
    line = line.strip()
    if not line.startswith("{"):
        continue
    try:
        rec = json.loads(line)
    except Exception:
        continue
    if "metric" in rec:
        last = rec
ok = (last is not None and "error" not in last and "mfu_error" not in last
      and last.get("value", 0) > 0 and "cpu" not in last["metric"])
sys.exit(0 if ok else 1)
EOF
}

run_phase() {  # run_phase <name> <timeout_s> <cmd...>; bench needs a clean rec
  local name=$1 tmo=$2; shift 2
  [ -e "$STATE/$name.done" ] || [ -e "$STATE/$name.gave_up" ] && return 0
  local tries
  tries=$(cat "$STATE/$name.tries" 2>/dev/null || echo 0)
  if [ "$tries" -ge "$MAX_TRIES" ]; then
    echo "=== phase $name gave up after $tries tries ==="
    touch "$STATE/$name.gave_up"
    return 0
  fi
  echo $((tries + 1)) > "$STATE/$name.tries"
  echo "=== phase $name attempt $((tries + 1)) start $(date -u +%H:%M:%S) ==="
  local plog="$STATE/$name.log"
  if [ "$name" = goldens ]; then
    # goldens is pure network egress, no chip use — holding the exclusive
    # TPU lock for a 30-min download (x5 retries when egress is blocked)
    # would starve the probe loop and any interactive run
    timeout "$tmo" "$@" > "$plog" 2>&1
  else
    flock -w 120 -E "$LOCK_BUSY" "$LOCK" timeout "$tmo" "$@" > "$plog" 2>&1
  fi
  local rc=$?
  if [ $rc -eq "$LOCK_BUSY" ]; then
    # ADVICE r4: lock contention means the workload never ran — refund the
    # attempt so contention can't walk a phase to gave_up
    echo "$tries" > "$STATE/$name.tries"
    echo "=== phase $name lock busy (attempt refunded) $(date -u +%H:%M:%S) ==="
    sleep 120
    return 1
  fi
  cat "$plog"
  persist "$name" "$plog" "$((tries + 1))" "$rc"
  # a failed attempt that still landed a measurement (sweep variants before
  # a mid-grid hang) is progress, not a strike — refund the try so the
  # skip-resume logic gets as many windows as the grid needs. ONLY the
  # resumable sweep phases: for bench/vit_train a printed record + nonzero
  # exit would repeat identically every window (no skip-resume there), so
  # refunding would starve the queue behind a permanently-failing phase.
  if { [ "$name" = sweep ] || [ "$name" = vit_sweep ]; } \
      && [ $rc -ne 0 ] && grep -q '"mfu"' "$plog" 2>/dev/null; then
    echo "$tries" > "$STATE/$name.tries"
    echo "=== phase $name failed but made progress (try refunded) ==="
  fi
  local ok=$rc
  # bench.py exits 0 on every failure path by design — require a clean
  # TPU record before declaring the metric-of-record phases done
  if { [ "$name" = bench ] || [ "$name" = vit_train ] \
      || [ "$name" = bench_adopted ]; } && [ $rc -eq 0 ] \
      && ! bench_clean "$plog"; then
    ok=99
  fi
  if [ $ok -eq 0 ]; then
    touch "$STATE/$name.done"
    echo "=== phase $name DONE $(date -u +%H:%M:%S) ==="
  else
    echo "=== phase $name rc=$rc ok=$ok (retry later) $(date -u +%H:%M:%S) ==="
    # backoff so a fast-failing phase can't hot-loop probe/rerun on 1 core
    sleep 120
    return 1
  fi
}

adopt_refresh() {  # adopt_refresh <phase> <preset-args...>
  # Adoption is cheap CPU work off MEASUREMENTS.jsonl — run it whenever the
  # phase has NEW records, not only after the full grid completes, so a
  # window that measured a better config benefits the very next bench run
  # even if the sweep never finishes (windows are scarce).
  local phase=$1; shift
  local n last
  # grep -c already prints 0 on no match, so `|| echo 0` used to yield the
  # two-line "0\n0", making the -gt below an invalid integer test that
  # passed by accident — strip to digits and default empty to 0
  n=$(grep -c "\"phase\": \"$phase\"" /root/repo/MEASUREMENTS.jsonl \
      2>/dev/null || true)
  n=${n//[^0-9]/}; n=${n:-0}
  last=$(cat "$STATE/adopt_$phase.count" 2>/dev/null || true)
  last=${last//[^0-9]/}; last=${last:-0}
  [ "$n" -gt "$last" ] || return 0
  if env JIMM_PLATFORM=cpu timeout 300 \
      python -m scripts.adopt_sweep --phase "$phase" "$@" --apply; then
    echo "$n" > "$STATE/adopt_$phase.count"
    echo "=== adopt($phase) refreshed at $n records $(date -u +%H:%M:%S) ==="
  else
    echo "=== adopt($phase) refresh failed (rc=$?) $(date -u +%H:%M:%S) ==="
  fi
}

bench_adopted_phase() {
  # Re-measure the benchmark of record whenever the ADOPTED CONFIG CHANGES
  # (hash-keyed, not once-ever): a later window's better sweep result gets
  # its own bench datapoint. Tries reset when the config changes.
  [ -f jimm_tpu/adopted_runtime.json ] || return 0
  local cur prev
  cur=$(sha256sum jimm_tpu/adopted_runtime.json | cut -d' ' -f1)
  prev=$(cat "$STATE/bench_adopted.cfg" 2>/dev/null || echo none)
  if [ "$cur" != "$prev" ]; then
    rm -f "$STATE/bench_adopted.done" "$STATE/bench_adopted.gave_up" \
          "$STATE/bench_adopted.tries"
    echo "$cur" > "$STATE/bench_adopted.cfg"
  fi
  run_phase bench_adopted 950 env BENCH_TIMEOUT_S=900 python bench.py
}

echo "watcher r5 started $(date -u +%F' '%H:%M:%S) head=$(git rev-parse --short HEAD)"
i=0
while true; do
  i=$((i+1))
  if ! probe; then
    echo "probe $i: tunnel down $(date -u +%H:%M:%S)"
    sleep 120
    continue
  fi
  echo "probe $i: TPU ALIVE $(date -u +%H:%M:%S)"
  # Windows are scarce (r5: one 19-min window in the first 3 h) — spend
  # them on the metrics of record FIRST. bench's builtin defaults equal
  # the measured-best known config (remat=dots, unroll 12, 44.6%), so
  # running it before the sweep completes loses nothing; vit_train is
  # metric of record #2 and has never had a datapoint.
  run_phase bench       950 env BENCH_TIMEOUT_S=900 python bench.py || continue
  if [ -f scripts/vit_train_bench.py ]; then
    run_phase vit_train 950 env BENCH_TIMEOUT_S=900 python -m scripts.vit_train_bench || continue
  fi
  # lever grid: per-variant watchdog + skip-resume; partial JSON lines are
  # persisted even on timeout, and .jax_cache makes a retry's compiles cheap
  if ! run_phase sweep 4500 python -m scripts.bench_sweep --steps 30; then
    adopt_refresh sweep --preset siglip-base-patch16-256
    continue
  fi
  adopt_refresh sweep --preset siglip-base-patch16-256
  bench_adopted_phase || continue
  if [ -f scripts/flash_compiled_check.py ]; then
    # 15 compiled cases (12 flash + 3 fused-LN) x fwd+bwd+oracle compiles:
    # a cold cache needs well over the old 900 s
    run_phase flashchk 1800 python -m scripts.flash_compiled_check || continue
  fi
  # per-op attribution at HEAD, at the adopted (measured-best) config —
  # the committed evidence for "50% reached or the gap is explained"
  run_phase profile     900 python -m scripts.profile_step --adopted || continue
  run_phase vmem        600 python -m scripts.vmem_probe || continue
  run_phase inference   900 python -m scripts.inference_bench || continue
  run_phase crossover   900 python -m scripts.attn_crossover --causal || continue
  run_phase longctx     900 python -m scripts.longcontext_bench --bwd || continue
  run_phase longctx_c   900 python -m scripts.longcontext_bench --bwd --causal || continue
  # metric-of-record #2 tuning: the ViT-L lever grid, adopted under its own
  # preset key (rides the same fidelity filters)
  if ! run_phase vit_sweep 3600 python -m scripts.bench_sweep --model vit_l16_384 --steps 30; then
    adopt_refresh vit_sweep --preset vit-large-patch16-384
    continue
  fi
  adopt_refresh vit_sweep --preset vit-large-patch16-384
  if [ -f scripts/dump_goldens.py ]; then
    # needs network egress, not the chip; a blocked attempt still leaves
    # tests/goldens/ATTEMPTS.log evidence (VERDICT r4 item 4)
    run_phase goldens  1800 python -m scripts.dump_goldens --all || continue
  fi
  echo "=== queue complete $(date -u +%H:%M:%S); idle-probing every 10 min ==="
  touch "$STATE/queue_complete"
  sleep 600
done
