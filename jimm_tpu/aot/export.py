"""Serialize/deserialize compiled serve forwards via ``jax.export``, and
wire the JAX persistent compilation cache for train steps.

What an artifact holds: the StableHLO module ``jax.export`` produces for
``model.<method>`` traced at one (bucket, *item_shape) input — with the
model's *parameters as call arguments*, not baked-in constants. Loading an
artifact therefore skips the expensive half of cold start (Python trace +
jaxpr lowering of the whole model) and works for any checkpoint of the
same architecture; the live model supplies the parameter leaves at call
time. Exotic-dtype state leaves (PRNG keys — not serializable as call
arguments by the export flatbuffer schema) are closed over as trace-time
constants instead; they are bytes-tiny and inert in eval forwards.

The second lever is the XLA-level persistent compilation cache
(:func:`enable_persistent_cache`): with it, even the backend compile of a
deserialized module is a disk hit on restart. The two compose — artifact
store above (trace+lower), jax cache below (XLA optimize+codegen).
"""

from __future__ import annotations

from typing import Any, Callable

__all__ = ["enable_persistent_cache", "load_serve_forward",
           "serialize_serve_forward"]


def _partition_state(model):
    """Split a live nnx model into (merge recipe, plain array leaves).

    Returns ``(rebuild, arg_leaves, arg_specs)`` where ``rebuild(leaves)``
    reconstitutes the module inside a trace, ``arg_leaves`` are the
    plain-dtype state arrays (exported as call arguments, in deterministic
    tree-flatten order), and extended-dtype leaves (PRNG keys) are captured
    by ``rebuild`` as constants.
    """
    import jax
    from flax import nnx

    graphdef, state = nnx.split(model)
    leaves, treedef = jax.tree.flatten(state)

    def _plain(leaf) -> bool:
        try:
            return not jax.dtypes.issubdtype(leaf.dtype, jax.dtypes.extended)
        except (TypeError, AttributeError):
            return True

    arg_idx = [i for i, leaf in enumerate(leaves) if _plain(leaf)]
    consts = {i: leaf for i, leaf in enumerate(leaves) if not _plain(leaf)}
    arg_leaves = [leaves[i] for i in arg_idx]
    arg_specs = [jax.ShapeDtypeStruct(leaf.shape, leaf.dtype,
                                      sharding=_named_sharding(leaf))
                 for leaf in arg_leaves]

    def rebuild(current_arg_leaves):
        merged = dict(zip(arg_idx, current_arg_leaves))
        merged.update(consts)
        ordered = [merged[i] for i in range(len(leaves))]
        return nnx.merge(graphdef, jax.tree.unflatten(treedef, ordered))

    return rebuild, arg_leaves, arg_specs


def _named_sharding(leaf):
    """The leaf's ``NamedSharding``, or None for single-device placements.

    Sharded-model exports must record the parameter layout: the StableHLO
    then carries logical HloShardings, so a program exported from one
    replica's submesh deserializes onto any same-shape submesh (the outer
    jit recompiles XLA for the actual devices; only the mesh *shape* is
    pinned, which the AOT key already fingerprints). Single-device leaves
    export unsharded, byte-identical to the pre-topology artifacts."""
    from jax.sharding import NamedSharding
    sharding = getattr(leaf, "sharding", None)
    return sharding if isinstance(sharding, NamedSharding) else None


def serialize_serve_forward(model, method: str, batch: int,
                            item_shape: tuple[int, ...],
                            in_dtype: Any,
                            x_sharding: Any = None) -> bytes:
    """Trace + export ``model.<method>`` at one padded-bucket shape and
    return the serialized artifact bytes. This is the expensive call the
    store exists to amortize — it runs once per (architecture, bucket) in
    ``aot warmup`` or on a write-through miss, never on the request path.

    Parameter shardings are read off the live model's leaves (a sharded
    replica model exports a sharded program); ``x_sharding`` optionally
    pins the batch input's ``NamedSharding`` to match the engine's single
    sharded ``device_put`` per micro-batch."""
    import jax
    from jax import export as jax_export

    rebuild, _arg_leaves, arg_specs = _partition_state(model)

    def fwd(param_leaves, x):
        return getattr(rebuild(param_leaves), method)(x)

    x_spec = jax.ShapeDtypeStruct((int(batch), *item_shape), in_dtype,
                                  sharding=x_sharding)
    exported = jax_export.export(jax.jit(fwd))(arg_specs, x_spec)
    return exported.serialize()


def load_serve_forward(payload: bytes, model,
                       method: str) -> Callable[[Any], Any]:
    """Deserialize an artifact against a live model; returns a callable
    over one padded batch. Raises on any incompatibility (arity/shape/dtype
    drift, calling-convention version skew) — the caller treats that as a
    fallback-to-fresh-compile signal, so a wrong program can never serve.

    The returned callable never re-traces the model's Python: the jit wraps
    ``Exported.call`` (a single StableHLO invocation), so the engine's
    compile-count gauge stays at zero on a fully warm store.
    """
    import jax
    from jax import export as jax_export

    exported = jax_export.deserialize(bytearray(payload))
    rebuild, arg_leaves, arg_specs = _partition_state(model)
    n_expected = len(arg_specs) + 1
    flat_avals = jax.tree.flatten(exported.in_avals)[0] \
        if hasattr(exported, "in_avals") else []
    if flat_avals and len(flat_avals) != len(arg_specs) + 1:
        raise ValueError(
            f"artifact expects {len(flat_avals)} input leaves, live model "
            f"provides {n_expected} — architecture drift")
    call = jax.jit(exported.call)
    # params go up front once; device-resident leaves are passed by
    # reference each call (no copy)
    params = list(arg_leaves)

    def forward(x):
        return call(params, x)

    return forward


def enable_persistent_cache(cache_dir: str) -> bool:
    """Point jax's persistent compilation cache at ``cache_dir`` so repeat
    XLA compiles (train steps across restarts, deserialized serve modules)
    are disk hits. Thresholds drop to zero: on the cold-start path even a
    sub-second compile is worth persisting. Returns False (without raising)
    on jax lines that lack the knobs — the caller keeps working uncached."""
    import jax
    try:
        jax.config.update("jax_compilation_cache_dir", str(cache_dir))
    except (AttributeError, ValueError):
        return False
    for knob, value in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                        ("jax_persistent_cache_min_entry_size_bytes", 0)):
        try:
            jax.config.update(knob, value)
        except (AttributeError, ValueError):
            pass  # threshold knobs are best-effort; the dir is what matters
    return True
