"""Cascade router: hit the cheapest resident model, escalate on doubt.

A :class:`CascadeRouter` fronts an ordered list of pool engines — cheapest
dtype first (int8/fp8 twin), widest last — and routes each request through
them as a confidence cascade:

1. Submit to the cheapest stage's engine (normal admission: the request
   counter and the tenant's token bucket are charged exactly once, here).
2. Score the output through the stage's **calibrated** confidence signal
   (:class:`~jimm_tpu.serve.cascade.calibrate.CascadeCalibration` —
   temperature-scaled logit margin; thresholds come from content-addressed
   store artifacts, never from code: lint JL021).
3. Accept, or escalate to the next stage via ``engine.submit(...,
   escalated=True)`` — the re-submit bypasses admission double-billing but
   still honors the physical queue bound.

Every hop is journaled on one correlation id (``cascade_request`` →
``cascade_escalated``* → ``cascade_routed``) so ``obs timeline`` shows a
request's whole path, and escalations run under a ``cascade_escalate``
span for the latency decomposition. An optional ``agreement_fn`` cross-
checks a confident cheap answer against embedding-neighbor agreement from
the retrieval index (run off-loop; it touches host index structures).
"""

from __future__ import annotations

import asyncio
import dataclasses
from typing import Callable, Sequence

from jimm_tpu.obs.journal import get_journal, new_correlation_id
from jimm_tpu.obs.spans import new_trace_id, span
from jimm_tpu.serve.admission import ServeMetrics

#: response headers the server attaches and the client parses back
CASCADE_HEADER_MODELS = "X-Jimm-Cascade-Models"
CASCADE_HEADER_MODEL = "X-Jimm-Cascade-Model"
CASCADE_HEADER_CONFIDENCE = "X-Jimm-Cascade-Confidence"


@dataclasses.dataclass(frozen=True)
class CascadeStage:
    """One rung of the ladder: a pool model plus the calibration that
    decides whether its answers are trustworthy. The terminal (widest)
    stage carries ``calibration=None`` — it always accepts."""

    name: str
    engine: object
    calibration: object = None


@dataclasses.dataclass(frozen=True)
class CascadeResult:
    """What the router hands back: the accepted output plus the routing
    metadata the server exposes as response headers."""

    output: object
    model: str
    models_tried: tuple[str, ...]
    confidence: float | None
    escalations: int
    cid: str
    trace_id: str

    def headers(self) -> dict[str, str]:
        """The cascade response headers (server side; the client parses
        the same names back into :class:`~jimm_tpu.serve.client
        .CascadeInfo`)."""
        out = {CASCADE_HEADER_MODELS: ",".join(self.models_tried),
               CASCADE_HEADER_MODEL: self.model}
        if self.confidence is not None:
            out[CASCADE_HEADER_CONFIDENCE] = f"{self.confidence:.6f}"
        return out


class CascadeRouter:
    """Routes requests through calibrated stages, cheapest first.

    ``score_fn`` maps an engine output row to the score row the
    calibration thresholds (e.g. a fixed zero-shot projection of the
    embedding); identity when omitted. ``agreement_fn`` +
    ``agreement_floor`` optionally cross-check accepted cheap answers
    with embedding-neighbor agreement — both must be given together, and
    the floor, like every threshold, belongs in operator config or a
    calibration artifact, not code.
    """

    def __init__(self, stages: Sequence[CascadeStage], *,
                 metrics: ServeMetrics | None = None,
                 score_fn: Callable | None = None,
                 agreement_fn: Callable | None = None,
                 agreement_floor: float | None = None):
        stages = list(stages)
        if not stages:
            raise ValueError("cascade needs at least one stage")
        names = [s.name for s in stages]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate stage names {names}")
        for s in stages[:-1]:
            if s.calibration is None:
                raise ValueError(
                    f"non-terminal stage {s.name!r} has no calibration; "
                    "only the widest stage may accept unconditionally")
        if (agreement_fn is None) != (agreement_floor is None):
            raise ValueError("agreement_fn and agreement_floor must be "
                             "given together")
        self.stages = stages
        self.metrics = metrics or getattr(stages[0].engine, "metrics",
                                          None) or ServeMetrics()
        self.score_fn = score_fn
        self.agreement_fn = agreement_fn
        self.agreement_floor = agreement_floor
        self.metrics.inc("cascade_requests_total", 0)
        self.metrics.inc("cascade_escalations_total", 0)
        for s in stages:
            self.metrics.inc(f"cascade_{s.name}_accepted_total", 0)
        self.metrics.bind_gauge("cascade_escalation_rate",
                                lambda: round(self.escalation_rate, 4))

    @classmethod
    def from_pool(cls, pool, order: Sequence[str],
                  calibrations: dict, **kwargs) -> "CascadeRouter":
        """Build stages from pool model names, cheapest → widest.
        ``calibrations`` maps every non-terminal name to its
        :class:`CascadeCalibration`."""
        order = list(order)
        missing = [n for n in order[:-1] if n not in calibrations]
        if missing:
            raise ValueError(f"no calibration for cascade stages {missing}")
        stages = [CascadeStage(name=n, engine=pool.get(n),
                               calibration=calibrations.get(n))
                  for n in order]
        kwargs.setdefault("metrics", pool.metrics)
        return cls(stages, **kwargs)

    # -- routing -----------------------------------------------------------

    async def submit(self, item, timeout_s: float | None = None,
                     trace_id: str | None = None,
                     tenant: str | None = None) -> CascadeResult:
        """Route one request through the cascade. Raises whatever the
        stage engines raise (throttle/shed/deadline are not swallowed —
        an escalation that can't be admitted fails the request)."""
        cid = new_correlation_id()
        tid = trace_id or new_trace_id()
        self.metrics.inc("cascade_requests_total")
        journal = get_journal()
        journal.emit("cascade_request", cid=cid, trace_id=tid,
                     stage=self.stages[0].name, tenant=tenant)
        loop = asyncio.get_running_loop()
        tried: list[str] = []
        confidence: float | None = None
        last = len(self.stages) - 1
        for i, stage in enumerate(self.stages):
            if i == 0:
                out = await stage.engine.submit(item, timeout_s, tid, tenant)
            else:
                with span("cascade_escalate"):
                    out = await stage.engine.submit(item, timeout_s, tid,
                                                    tenant, escalated=True)
            tried.append(stage.name)
            if stage.calibration is None:
                confidence = None  # terminal stage: accepted by fiat
                accept = True
            else:
                scores = self.score_fn(out) if self.score_fn else out
                accept, confidence = stage.calibration.accepts(scores)
                if accept and self.agreement_fn is not None and i < last:
                    agreement = await loop.run_in_executor(
                        None, self.agreement_fn, out)
                    if agreement < self.agreement_floor:
                        accept = False
                        journal.emit("cascade_crosscheck_failed", cid=cid,
                                     stage=stage.name,
                                     agreement=round(float(agreement), 6),
                                     floor=self.agreement_floor)
            if accept:
                self.metrics.inc(f"cascade_{stage.name}_accepted_total")
                journal.emit("cascade_routed", cid=cid, trace_id=tid,
                             model=stage.name, escalations=i,
                             models_tried=tried,
                             confidence=None if confidence is None
                             else round(confidence, 6))
                return CascadeResult(
                    output=out, model=stage.name, models_tried=tuple(tried),
                    confidence=confidence, escalations=i, cid=cid,
                    trace_id=tid)
            self.metrics.inc("cascade_escalations_total")
            journal.emit("cascade_escalated", cid=cid, trace_id=tid,
                         stage_from=stage.name,
                         stage_to=self.stages[i + 1].name,
                         confidence=round(confidence, 6))
        raise AssertionError("unreachable: terminal stage always accepts")

    # -- introspection -----------------------------------------------------

    @property
    def escalation_rate(self) -> float:
        total = self.metrics.count("cascade_requests_total")
        if not total:
            return 0.0
        return self.metrics.count("cascade_escalations_total") / total

    def describe(self) -> dict:
        """The healthz ``cascade`` block: stage ladder, calibration
        provenance, live escalation counters."""
        stages = []
        for s in self.stages:
            entry: dict = {"model": s.name,
                           "accepted": self.metrics.count(
                               f"cascade_{s.name}_accepted_total")}
            if s.calibration is not None:
                entry["calibration"] = {
                    "fingerprint": s.calibration.fingerprint,
                    "threshold": s.calibration.threshold,
                    "temperature": s.calibration.temperature,
                    "measured_disagreement":
                        s.calibration.measured_disagreement,
                }
            stages.append(entry)
        return {
            "stages": stages,
            "requests": self.metrics.count("cascade_requests_total"),
            "escalations": self.metrics.count("cascade_escalations_total"),
            "escalation_rate": round(self.escalation_rate, 4),
            "crosscheck": self.agreement_fn is not None,
        }
