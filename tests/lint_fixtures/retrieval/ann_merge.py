"""JL011 fixture: the IVF merge-path temptation — candidate lists come
back per probe, and the easy (wrong) move is a host argsort over them."""
import numpy as np


def merge_probed_candidates(cand_vals_dev, cand_idx_dev):
    vals = np.asarray(cand_vals_dev)       # host copy of probe rescores
    idx = np.asarray(cand_idx_dev)
    order = np.argsort(-vals, axis=-1)     # JL011: full argsort on host
    ranked = sorted(vals.ravel())          # JL011: sorted() on array data
    return np.take_along_axis(idx, order, axis=-1), ranked


def merge_probed_candidates_ok(cand_vals_dev, cand_idx_dev, k):
    vals = np.asarray(cand_vals_dev)
    idx = np.asarray(cand_idx_dev)
    # ok: lexsort over the bounded nprobe*k candidate fan-in is the
    # sanctioned final merge (score desc, global index asc)
    sort_i = np.where(idx < 0, np.iinfo(np.int64).max, idx)
    order = np.lexsort((sort_i, -vals), axis=-1)[:, :k]
    return (np.take_along_axis(vals, order, axis=1),
            np.take_along_axis(idx, order, axis=1))
