"""CLI smoke tests (`python -m jimm_tpu ...`), in-process via `cli.main`.

The reference has no CLI at all (SURVEY §5 config row); ours must at least
list presets, train offline on synthetic data with checkpoint/resume, and
inspect safetensors files.
"""

import json

import numpy as np
import pytest

from jimm_tpu.cli import main
from jimm_tpu.weights.safetensors_io import save_file


def test_presets_lists_all(capsys):
    assert main(["presets"]) == 0
    out = capsys.readouterr().out
    for name in ("vit-base-patch16-224", "clip-vit-large-patch14",
                 "siglip-so400m-patch14-384", "siglip2-large-patch16-512"):
        assert name in out


def test_train_tiny_vit(tmp_path, capsys):
    metrics = tmp_path / "metrics.jsonl"
    assert main(["train", "--preset", "vit-base-patch16-224", "--tiny",
                 "--steps", "3", "--batch-size", "8",
                 "--metrics-file", str(metrics)]) == 0
    records = [json.loads(line) for line in metrics.read_text().splitlines()]
    assert len(records) == 3
    assert all(np.isfinite(r["loss"]) for r in records)


def test_train_resume(tmp_path):
    args = ["train", "--preset", "vit-base-patch16-224", "--tiny",
            "--batch-size", "8", "--ckpt-dir", str(tmp_path / "ckpt"),
            "--save-every", "1", "--log-every", "0"]
    assert main(args + ["--steps", "2"]) == 0
    metrics = tmp_path / "metrics.jsonl"
    assert main(args + ["--steps", "4", "--resume",
                        "--metrics-file", str(metrics)]) == 0
    records = [json.loads(line) for line in metrics.read_text().splitlines()]
    # resumed at step 2: only steps 2 and 3 ran in the second invocation
    assert [r["step"] for r in records] == [2, 3]


@pytest.mark.slow
def test_train_sharded_ring_loss(tmp_path, eight_devices, capsys):
    assert main(["train", "--preset", "siglip-base-patch16-256", "--tiny",
                 "--steps", "2", "--batch-size", "8",
                 "--mesh", "data=4,model=2", "--rules", "fsdp_tp",
                 "--loss", "siglip_ring", "--log-every", "1"]) == 0
    assert "loss=" in capsys.readouterr().out


def test_inspect(tmp_path, capsys):
    path = tmp_path / "m.safetensors"
    save_file({"w": np.zeros((3, 5), np.float32)}, path)
    assert main(["inspect", str(path)]) == 0
    out = capsys.readouterr().out
    assert "w" in out and "(3, 5)" in out


def test_bench_forward_tiny(capsys):
    assert main(["bench-forward", "--preset", "siglip-base-patch16-256",
                 "--tiny", "--batch-size", "4", "--steps", "2"]) == 0
    assert "images/sec" in capsys.readouterr().out


def test_train_profile_capture(tmp_path, capsys):
    assert main(["train", "--preset", "vit-base-patch16-224", "--tiny",
                 "--steps", "6", "--batch-size", "8", "--log-every", "0",
                 "--profile-dir", str(tmp_path / "prof")]) == 0
    assert "profile trace written" in capsys.readouterr().out
    assert (tmp_path / "prof" / "plugins" / "profile").is_dir()
