"""jimm_tpu.aot — persistent ahead-of-time compile-artifact store.

Cold start is the serve engine's last uninstrumented cost: every process
restart re-traces and re-compiles every shape bucket before the first
request can be answered. This package closes that gap with two layers:

1. **Artifact store** (:mod:`keys` / :mod:`store` / :mod:`export`):
   serve forwards are exported to StableHLO via ``jax.export``, keyed by
   a byte-stable fingerprint over everything that shaped the program
   (config hash, bucket, dtypes, mesh, backend, jax versions, donation),
   and kept in a content-addressed on-disk store with atomic writes,
   integrity hashes, LRU eviction, and quarantine-on-mismatch.
2. **JAX persistent compilation cache**
   (:func:`~jimm_tpu.aot.export.enable_persistent_cache`): backend
   compiles — train steps, and the XLA half of deserialized serve
   modules — become disk hits across restarts.

:class:`~jimm_tpu.aot.warmup.AotForward` is the serve-side entry point:
a drop-in for ``counting_forward`` that consults the store per bucket
(``jimm_aot_hit_total``), write-throughs on a miss
(``jimm_aot_miss_total``), and degrades to a fresh jit on any bad
artifact (``jimm_aot_fallback_total``) — never a wrong answer, never a
crash. ``jimm-tpu aot warmup|ls|gc|verify`` manages stores offline.
"""

from jimm_tpu.aot.keys import (AOT_FORMAT_VERSION, AotKey, canonical_json,
                               config_hash, donation_signature,
                               serve_forward_key)
from jimm_tpu.aot.store import DEFAULT_MAX_BYTES, ArtifactStore, StoreEntry

__all__ = [
    "AOT_FORMAT_VERSION",
    "AotKey",
    "ArtifactStore",
    "DEFAULT_MAX_BYTES",
    "StoreEntry",
    "canonical_json",
    "config_hash",
    "donation_signature",
    "serve_forward_key",
]


def __getattr__(name):  # lazy: keep `import jimm_tpu.aot` jax-free
    if name in ("AotForward", "aot_metrics", "warmup_store"):
        from jimm_tpu.aot import warmup
        return getattr(warmup, name)
    if name in ("enable_persistent_cache", "load_serve_forward",
                "serialize_serve_forward"):
        from jimm_tpu.aot import export
        return getattr(export, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
