from jimm_tpu.data.pipeline import PrefetchIterator
from jimm_tpu.data.synthetic import blob_classification, contrastive_pairs

__all__ = ["PrefetchIterator", "blob_classification", "contrastive_pairs"]
