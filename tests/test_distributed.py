"""Two-process `jax.distributed` smoke (VERDICT r2 weak #7: every
multi-device test ran in one process; `initialize_distributed` was never
exercised even at 2 local processes).

Spawns two real OS processes forming a local CPU cluster: asserts cluster
formation, global mesh construction over non-addressable devices, a
cross-process psum, and a process_allgather — the primitives multi-host
training rests on (SURVEY §2.3 "collective communication backend" row).
"""

import socket
import subprocess
import sys

import pytest

WORKER = r"""
import sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 2)

addr, pid = sys.argv[1], int(sys.argv[2])
from jimm_tpu.parallel import initialize_distributed, make_mesh
initialize_distributed(coordinator_address=addr, num_processes=2,
                       process_id=pid)
assert jax.process_count() == 2, jax.process_count()
assert jax.process_index() == pid
assert jax.device_count() == 4, jax.device_count()       # 2 global x 2 local
assert jax.local_device_count() == 2

# double-init must be a no-op (initialize_distributed's contract)
initialize_distributed(coordinator_address=addr, num_processes=2,
                       process_id=pid)

import jax.numpy as jnp
from jax import shard_map
from jax.experimental import multihost_utils
from jax.sharding import PartitionSpec as P

# cross-process allgather: one value per process, ordered by process id
got = multihost_utils.process_allgather(jnp.float32(pid + 1))
assert got.tolist() == [1.0, 2.0], got

# global mesh over all 4 devices (2 of them non-addressable here) + psum
mesh = make_mesh({"data": -1})
assert dict(mesh.shape) == {"data": 4}
fn = shard_map(lambda x: jax.lax.psum(x, "data"), mesh=mesh,
               in_specs=P(), out_specs=P())
out = jax.jit(fn)(np.float32(1.0))
assert float(out) == 4.0, float(out)
print(f"WORKER_OK {pid}")
"""


@pytest.mark.slow
def test_two_process_cluster(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    addr = f"127.0.0.1:{port}"
    procs = [subprocess.Popen(
        [sys.executable, "-c", WORKER, addr, str(pid)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=600)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (rc, out, err) in enumerate(outs):
        assert rc == 0, f"worker {pid} rc={rc}\nstdout:{out}\nstderr:{err[-2000:]}"
        assert f"WORKER_OK {pid}" in out
