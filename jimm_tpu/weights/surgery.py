"""Checkpoint surgery: adapt pretrained weights to a different architecture
shape at load time.

The reference can only instantiate a checkpoint at its native resolution
(image size is read from, or inferred from, the position-embedding table —
ref `models/vit.py:144-164`). Standard ViT practice is to fine-tune at a
higher resolution by interpolating the 2-D grid of position embeddings;
`from_pretrained(..., image_size=...)` does that here.

Interpolation is bilinear via the framework's own host-side resizer
(`jimm_tpu.data.preprocess.resize_bilinear` — native C++ when built, numpy
otherwise): pure host work, no device/backend touch during weight loading.
"""

from __future__ import annotations

import numpy as np

from jimm_tpu.data.preprocess import resize_bilinear


def interpolate_pos_embed(pos: np.ndarray, new_grid: int, *,
                          n_prefix: int = 0) -> np.ndarray:
    """Resample a ViT position-embedding table to a new square grid.

    - ``pos``: ``(P, H)`` or ``(1, P, H)`` with ``P = n_prefix + g*g``
      (``n_prefix`` class/register tokens first, then the row-major grid).
    - ``new_grid``: target side length; output has ``n_prefix + new_grid^2``
      positions, same rank and dtype as the input.
    """
    arr = np.asarray(pos)
    squeeze = arr.ndim == 2
    if squeeze:
        arr = arr[None]
    if arr.ndim != 3:
        raise ValueError(f"pos embed must be (P, H) or (1, P, H), "
                         f"got {arr.shape}")
    n_grid = arr.shape[1] - n_prefix
    old_grid = int(round(n_grid ** 0.5))
    if old_grid * old_grid != n_grid:
        raise ValueError(f"{n_grid} grid positions is not a square grid")
    prefix = arr[:, :n_prefix]
    if old_grid == new_grid:
        out = arr
    else:
        grid = arr[:, n_prefix:].reshape(old_grid, old_grid, -1)
        resized = resize_bilinear(grid[None].astype(np.float32),
                                  (new_grid, new_grid))[0]
        resized = resized.reshape(1, new_grid * new_grid, -1)
        out = np.concatenate([prefix.astype(np.float32),
                              resized], axis=1).astype(arr.dtype)
    return out[0] if squeeze else out


def resize_checkpoint_pos_embed(weights: dict, key: str, *, patch_size: int,
                                image_size: int, n_prefix: int) -> dict:
    """Copy ``weights`` with ``weights[key]`` resampled for ``image_size``.
    Validates divisibility by ``patch_size``."""
    if image_size % patch_size:
        raise ValueError(f"image_size {image_size} is not a multiple of "
                         f"patch_size {patch_size}")
    out = dict(weights)
    out[key] = interpolate_pos_embed(weights[key],
                                     image_size // patch_size,
                                     n_prefix=n_prefix)
    return out


def apply_image_size(weights: dict, cfg, image_size: int | None, *,
                     key: str, n_prefix: int):
    """``from_pretrained(..., image_size=...)`` entry point: returns
    ``(weights, cfg)`` adapted to the requested resolution (no-op when it
    already matches). ``key`` is the family's HF pos-embed tensor name and
    ``n_prefix`` its class/register-token count (0 for SigLIP's MAP grid)."""
    if not image_size or image_size == cfg.vision.image_size:
        return weights, cfg
    import dataclasses
    weights = resize_checkpoint_pos_embed(
        weights, key, patch_size=cfg.vision.patch_size,
        image_size=image_size, n_prefix=n_prefix)
    cfg = dataclasses.replace(cfg, vision=dataclasses.replace(
        cfg.vision, image_size=image_size))
    return weights, cfg
