"""Pallas TPU flash attention: a templated kernel family, not one kernel.

The tiling / online-normalizer / custom-VJP scaffolding is shared; a
:class:`VariantSpec` (score transform + normalizer kind, mask source, bias
source) instantiates the members:

- ``flash_attention``        — softmax, optional causal (the original).
- ``flash_attention_lse``    — softmax returning per-row logsumexp (the
  ring-attention building block).
- ``flash_attention_masked`` — softmax with a per-sample ``(B, Sk)``
  key-padding mask, streamed as additive f32 rows. Unblocks NaFlex and MAP
  pooling on the flash path (`nn/vision.py::forward_naflex`).
- ``flash_attention_bias``   — softmax with an additive bias broadcastable
  to ``(N, Sq, Sk)`` (relative-position style), fwd + bwd including dbias
  via a dedicated batch-innermost accumulation kernel.
- ``sigmoid_attention``      — elementwise ``sigmoid(s + logit_bias)``
  scores, NO row normalizer ("Theory, Analysis, and Best Practices for
  Sigmoid Self-Attention"): the online loop drops the m/l statistics
  entirely, and the backward needs no lse/delta.

Kernel structure (all variants): the kv loop is a GRID dimension, not an
in-kernel loop over a resident copy — each (head-block, q-block, kv-block)
grid cell sees one (block_q, d) q tile and one (block_k, d) k/v tile, so
VMEM holds a single working set while Mosaic's grid pipeline streams the
next kv block from HBM in parallel with compute. Softmax variants keep the
flash-attention recurrence in VMEM scratch ((block_q, 128) lane-broadcast
m/l, fp32 accumulator); the sigmoid variant keeps only the accumulator.
HBM traffic is O(S*D) and VMEM is O(block^2).

The backward recomputes attention blockwise (from the saved logsumexp for
softmax kinds; from scratch for sigmoid) — dq kernel plus dk/dv kernel in
the flash-attention-2 arrangement, and for the bias variant a third kernel
whose grid runs batch innermost to accumulate dbias across samples.

Numerical contract: softmax variants match
`jimm_tpu.ops.attention.reference_attention` (fp32 softmax einsum) to
~1e-5 in f32; the sigmoid variant matches
`reference_sigmoid_attention`. Tested in interpret mode on CPU and
compiled on TPU (`tests/test_flash_variants.py`,
`scripts/flash_compiled_check.py`).

Masking uses a large negative constant (not -inf) so padded/fully-masked
rows degrade to garbage-but-finite values — no NaNs reach the gradient.
Contract for the masked softmax variants: a query row whose keys are ALL
masked produces finite garbage output, and contributes exactly zero
gradient as long as its output cotangent is zero — consumers must mask
such rows downstream (NaFlex's MAP pooling does). The sigmoid variant has
no such row: zero valid keys simply yields a zero output row.

Head dims that are not one of the tested MXU tiles (64/128/256) are
zero-padded to the next tile inside the wrappers (the padded lanes
contribute 0 to every dot product and are sliced off the outputs), so the
dispatch layer no longer falls back to XLA on e.g. d=80 towers — see the
crossover note in docs/performance.md.
"""

from __future__ import annotations

import math
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
#: default per-grid-cell tile extents. 512 amortizes grid-step overhead
#: (measured ~2x faster than 128 at seq 256-1k on v5e) while the fp32
#:  (block_q, block_k) logits tile stays ~1MB — far under VMEM; _prologue
#: clamps to the padded sequence so short sequences use one tile.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
_LANES = 128  # scratch m/l are lane-broadcast for Mosaic-friendly layout

#: head-dim tiles the kernels are tuned for; other dims zero-pad up
_HEAD_TILES = (64, 128, 256)


class VariantSpec(NamedTuple):
    """Static template parameters for one family member (hashable — rides
    through ``custom_vjp`` nondiff args and ``partial`` into the kernels).

    - ``kind``: ``"softmax"`` (online max/sum recurrence, lse residual) or
      ``"sigmoid"`` (elementwise transform, accumulate-only loop).
    - ``has_mask``: stream per-sample additive key-padding rows
      ``(BN, 1, Sk)`` (0 keep / NEG_INF drop) into every score tile.
    - ``has_bias``: stream additive ``(N, Sq, Sk)`` f32 bias tiles into
      every score tile; the backward gains a dbias kernel.
    """

    kind: str = "softmax"
    has_mask: bool = False
    has_bias: bool = False


_SOFTMAX = VariantSpec()


def _ceil_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _bcast_lanes(x: jax.Array) -> jax.Array:
    """(n,) -> (n, 128) with every lane equal."""
    return jnp.broadcast_to(x[:, None], (x.shape[0], _LANES))


def _from_lanes(x: jax.Array) -> jax.Array:
    """(n, 128) all-lanes-equal -> (n,). max is exact on equal lanes."""
    return jnp.max(x, axis=1)


def _scores(q, k, sm_scale, mask_row, bias_tile, pos_mask):
    """One head's fp32 score tile: dot, scale, additive mask/bias, then the
    positional (padding/causal) mask. q/k stay in their storage dtype
    (bf16) so the MXU runs at full bf16 rate with fp32 accumulation; the
    softmax scale is applied to the fp32 logits AFTER the dot (pre-scaling
    q in bf16 would round)."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * sm_scale
    if bias_tile is not None:
        s = s + bias_tile
    if mask_row is not None:
        s = s + mask_row
    return jnp.where(pos_mask, s, NEG_INF)


# ---------------------------------------------------------------------------
# Forward kernel (template)
# ---------------------------------------------------------------------------

def _fwd_kernel(*refs, sk_real: int, block_k: int, causal: bool,
                sm_scale: float, logit_bias: float, n_k: int,
                spec: VariantSpec):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    softmax = spec.kind == "softmax"
    it = iter(refs)
    q_ref, k_ref, v_ref = next(it), next(it), next(it)
    mask_ref = next(it) if spec.has_mask else None
    bias_ref = next(it) if spec.has_bias else None
    o_ref = next(it)
    lse_ref = next(it) if softmax else None
    m_scr = next(it) if softmax else None
    l_scr = next(it) if softmax else None
    acc_scr = next(it)
    hb, bq, d = q_ref.shape

    @pl.when(kj == 0)
    def _init():
        if softmax:
            m_scr[...] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
            l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    def compute():
        # position mask is head-independent: build once, reuse per head
        k_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        pos = k_pos < sk_real
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            pos = pos & (k_pos <= q_pos)
        # static loop over the hb heads resident in this grid cell — one
        # cell amortizes grid-step overhead over hb MXU calls (the d=64
        # per-head matmuls are too small to hide it one at a time)
        for h in range(hb):
            v = v_ref[h]
            s = _scores(q_ref[h], k_ref[h], sm_scale,
                        mask_ref[h] if spec.has_mask else None,
                        bias_ref[h] if spec.has_bias else None, pos)
            if softmax:
                m_prev = _from_lanes(m_scr[h])
                l_prev = _from_lanes(l_scr[h])
                m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
                p = jnp.exp(s - m_new[:, None])
                corr = jnp.exp(m_prev - m_new)
                l_new = l_prev * corr + jnp.sum(p, axis=1)
                acc_scr[h] = acc_scr[h] * corr[:, None] + jax.lax.dot_general(
                    p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                m_scr[h] = _bcast_lanes(m_new)
                l_scr[h] = _bcast_lanes(l_new)
            else:
                # no normalizer, no running statistics: each kv block's
                # sigmoid scores contribute independently to the sum
                p = jax.nn.sigmoid(s + logit_bias)
                acc_scr[h] += jax.lax.dot_general(
                    p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)

    if causal:
        # kv blocks strictly above the diagonal contribute nothing: the
        # block is needed iff its first key position <= the block's last
        # query position. Their DMA is elided too: the host-side index map
        # clamps skipped cells to the last needed block, so Mosaic's
        # pipeline sees a repeated index and issues no copy.
        pl.when(kj * block_k <= (qi + 1) * bq - 1)(compute)
        last_j = jnp.minimum(n_k - 1, ((qi + 1) * bq - 1) // block_k)
    else:
        compute()
        last_j = n_k - 1

    @pl.when(kj == last_j)
    def _finalize():
        for h in range(hb):
            if softmax:
                m = _from_lanes(m_scr[h])
                l = _from_lanes(l_scr[h])
                l_safe = jnp.where(l == 0.0, 1.0, l)
                o_ref[h] = (acc_scr[h] / l_safe[:, None]).astype(o_ref.dtype)
                lse_ref[h, 0, :] = m + jnp.log(l_safe)
            else:
                o_ref[h] = acc_scr[h].astype(o_ref.dtype)


# ---------------------------------------------------------------------------
# Backward kernels (templates)
# ---------------------------------------------------------------------------

def _ds_tile(spec, s, do, v, lse, delta, logit_bias):
    """Shared backward score-gradient: recompute p from the fp32 score
    tile, then ``ds`` (unscaled — the chain-rule sm_scale lands at the
    dq/dk finalize, and dbias takes ds as-is). Returns (p, ds)."""
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    if spec.kind == "softmax":
        p = jnp.exp(s - lse[:, None])
        ds = p * (dp - delta[:, None])
    else:
        p = jax.nn.sigmoid(s + logit_bias)
        ds = p * (1.0 - p) * dp
    return p, ds


def _bwd_dq_kernel(*refs, sk_real: int, block_k: int, causal: bool,
                   sm_scale: float, logit_bias: float, n_k: int,
                   spec: VariantSpec):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    softmax = spec.kind == "softmax"
    it = iter(refs)
    q_ref, k_ref, v_ref = next(it), next(it), next(it)
    mask_ref = next(it) if spec.has_mask else None
    bias_ref = next(it) if spec.has_bias else None
    do_ref = next(it)
    lse_ref = next(it) if softmax else None
    delta_ref = next(it) if softmax else None
    dq_ref = next(it)
    dq_scr = next(it)
    hb, bq, d = q_ref.shape

    @pl.when(kj == 0)
    def _init():
        dq_scr[...] = jnp.zeros(dq_scr.shape, jnp.float32)

    def compute():
        k_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        pos = k_pos < sk_real
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            pos = pos & (k_pos <= q_pos)
        for h in range(hb):
            k = k_ref[h]
            s = _scores(q_ref[h], k, sm_scale,
                        mask_ref[h] if spec.has_mask else None,
                        bias_ref[h] if spec.has_bias else None, pos)
            _, ds = _ds_tile(spec, s, do_ref[h], v_ref[h],
                             lse_ref[h, 0, :] if softmax else None,
                             delta_ref[h, 0, :] if softmax else None,
                             logit_bias)
            dq_scr[h] += jax.lax.dot_general(
                ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    if causal:
        pl.when(kj * block_k <= (qi + 1) * bq - 1)(compute)
    else:
        compute()

    @pl.when(kj == n_k - 1)
    def _finalize():
        dq_ref[...] = (dq_scr[...] * sm_scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, sq_real: int, block_q: int, causal: bool,
                    sm_scale: float, logit_bias: float, n_q: int,
                    spec: VariantSpec):
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    softmax = spec.kind == "softmax"
    it = iter(refs)
    q_ref, k_ref, v_ref = next(it), next(it), next(it)
    mask_ref = next(it) if spec.has_mask else None
    bias_ref = next(it) if spec.has_bias else None
    do_ref = next(it)
    lse_ref = next(it) if softmax else None
    delta_ref = next(it) if softmax else None
    dk_ref = next(it)
    dv_ref = next(it)
    dk_scr = next(it)
    dv_scr = next(it)
    hb, bk, d = k_ref.shape

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[...] = jnp.zeros(dv_scr.shape, jnp.float32)

    def compute():
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, bk), 0)
        pos = q_pos < sq_real
        if causal:
            k_pos = kj * bk + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 1)
            pos = pos & (k_pos <= q_pos)
        for h in range(hb):
            q = q_ref[h]
            do = do_ref[h]
            s = _scores(q, k_ref[h], sm_scale,
                        mask_ref[h] if spec.has_mask else None,
                        bias_ref[h] if spec.has_bias else None, pos)
            p, ds = _ds_tile(spec, s, do, v_ref[h],
                             lse_ref[h, 0, :] if softmax else None,
                             delta_ref[h, 0, :] if softmax else None,
                             logit_bias)
            # dv's MXU input is a rounded copy; ds keeps the fp32 p
            # (matching the dq kernel) so dk isn't computed from a
            # double-rounded p
            dv_scr[h] += jax.lax.dot_general(
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dk_scr[h] += jax.lax.dot_general(
                ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    if causal:
        # q blocks whose last row is left of this kv block never land
        pl.when((qi + 1) * block_q - 1 >= kj * bk)(compute)
    else:
        compute()

    @pl.when(qi == n_q - 1)
    def _finalize():
        # ds was accumulated unscaled; the chain-rule sm_scale lands here
        dk_ref[...] = (dk_scr[...] * sm_scale).astype(dk_ref.dtype)
        dv_ref[...] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_dbias_kernel(*refs, sq_real: int, sk_real: int, block_q: int,
                      block_k: int, causal: bool, sm_scale: float,
                      logit_bias: float, n_b: int, spec: VariantSpec):
    """dbias for the bias variant: grid (N/hb, n_q, n_k, B) with batch
    INNERMOST ("arbitrary"), so one (head-block, q-block, k-block) bias
    tile stays resident while per-sample ds tiles accumulate in scratch;
    the result is written once at the last batch step. dbias is exactly
    ``ds`` (no sm_scale — bias adds to the scaled logits)."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    bi = pl.program_id(3)
    softmax = spec.kind == "softmax"
    it = iter(refs)
    q_ref, k_ref, v_ref = next(it), next(it), next(it)
    mask_ref = next(it) if spec.has_mask else None
    bias_ref = next(it)
    do_ref = next(it)
    lse_ref = next(it) if softmax else None
    delta_ref = next(it) if softmax else None
    db_ref = next(it)
    db_scr = next(it)
    hb, bq, d = q_ref.shape

    @pl.when(bi == 0)
    def _init():
        db_scr[...] = jnp.zeros(db_scr.shape, jnp.float32)

    k_pos = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (bq, block_k), 1)
    pos = k_pos < sk_real
    if causal:
        q_pos = qi * bq + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 0)
        pos = pos & (k_pos <= q_pos)
    for h in range(hb):
        s = _scores(q_ref[h], k_ref[h], sm_scale,
                    mask_ref[h] if spec.has_mask else None,
                    bias_ref[h], pos)
        _, ds = _ds_tile(spec, s, do_ref[h], v_ref[h],
                         lse_ref[h, 0, :] if softmax else None,
                         delta_ref[h, 0, :] if softmax else None,
                         logit_bias)
        db_scr[h] += ds

    @pl.when(bi == n_b - 1)
    def _finalize():
        db_ref[...] = db_scr[...]


# ---------------------------------------------------------------------------
# Host-side wrappers
# ---------------------------------------------------------------------------

def _flatten_heads(x: jax.Array) -> jax.Array:
    b, s, n, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * n, s, d)


def _unflatten_heads(x: jax.Array, b: int, n: int) -> jax.Array:
    bn, s, d = x.shape
    return x.reshape(b, n, s, d).transpose(0, 2, 1, 3)


def _pad_seq(x: jax.Array, target: int) -> jax.Array:
    pad = target - x.shape[1]
    if pad == 0:
        return x
    return jnp.pad(x, ((0, 0), (0, pad), (0, 0)))


def _pad_last(x: jax.Array, target: int) -> jax.Array:
    pad = target - x.shape[-1]
    if pad == 0:
        return x
    return jnp.pad(x, ((0, 0), (0, 0), (0, pad)))


def _head_pad_target(d: int) -> int:
    """Next supported head tile >= d (64/128/256), or the 128-padded width
    past 256. Zero-padded lanes contribute 0 to q·k and produce output
    columns the wrappers slice off, so ANY head dim runs on the flash path
    (the dispatch allowlist used to punt d=80-style towers to XLA)."""
    for t in _HEAD_TILES:
        if d <= t:
            return t
    return _ceil_to(d, _LANES)


def _interpret() -> bool:
    # looked up per call (NOT cached): scripts may configure the platform
    # after an earlier flash-attention touch, and a cached answer would
    # silently run the kernel interpreted on TPU (or compiled on CPU)
    return jax.default_backend() != "tpu"


from jimm_tpu.utils.compat import pallas_tpu_compiler_params

_SEMANTICS = pallas_tpu_compiler_params(
    dimension_semantics=("parallel", "parallel", "arbitrary"))
#: the dbias grid: batch innermost so the bias tile accumulates in scratch
_SEMANTICS4 = pallas_tpu_compiler_params(
    dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"))


def _causal_kv_index(block_q: int, block_k: int, n_k: int):
    """kv-block index map for causal grids ordered (heads, q, kv): blocks
    strictly above the diagonal (kernel skips them via ``pl.when``) are
    clamped to the q row's last needed block, so the pipeline sees the same
    index twice and elides the HBM->VMEM copy (VERDICT r2 weak #4 — the
    skipped blocks' DMAs used to run anyway)."""
    def idx(h, i, j):
        jmax = jnp.minimum(n_k - 1, ((i + 1) * block_q - 1) // block_k)
        return (h, jnp.minimum(j, jmax), 0)
    return idx


def _causal_q_index(block_q: int, block_k: int, lse_layout: bool = False):
    """q-side index maps for the causal dk/dv grid ordered (heads, kv, q):
    q blocks entirely left of the diagonal are clamped up to the kv row's
    first needed block — same DMA-eliding trick as `_causal_kv_index`."""
    def idx(h, j, i):
        imin = (j * block_k) // block_q
        i = jnp.maximum(i, imin)
        return (h, 0, i) if lse_layout else (h, i, 0)
    return idx


def _mask_fwd_index(block_q: int, block_k: int, n_k: int, causal: bool):
    """Additive-mask rows live in lse layout (heads, 1, Sk); clamp the kv
    index exactly like `_causal_kv_index` so skipped cells elide DMAs."""
    if not causal:
        return lambda h, i, j: (h, 0, j)

    def idx(h, i, j):
        jmax = jnp.minimum(n_k - 1, ((i + 1) * block_q - 1) // block_k)
        return (h, 0, jnp.minimum(j, jmax))
    return idx


def _bias_fwd_index(block_q: int, block_k: int, n_k: int, n_hb: int,
                    causal: bool):
    """Bias tiles are per-HEAD (no batch dim): flattened head-block h of
    the (B*N)-row grid maps to bias head-block ``h % (N/hb)``."""
    if not causal:
        return lambda h, i, j: (h % n_hb, i, j)

    def idx(h, i, j):
        jmax = jnp.minimum(n_k - 1, ((i + 1) * block_q - 1) // block_k)
        return (h % n_hb, i, jnp.minimum(j, jmax))
    return idx


def _bias_dkv_index(block_q: int, block_k: int, n_hb: int, causal: bool):
    if not causal:
        return lambda h, j, i: (h % n_hb, i, j)

    def idx(h, j, i):
        i = jnp.maximum(i, (j * block_k) // block_q)
        return (h % n_hb, i, j)
    return idx


#: VMEM budget for one grid cell's resident tiles (of ~16MB/core), leaving
#: room for Mosaic's input double-buffering and intermediates
_VMEM_BUDGET = 8 * 1024 * 1024


def _per_head_vmem_bytes(block_q: int, block_k: int, d: int, *,
                         kind: str = "softmax", has_mask: bool = False,
                         has_bias: bool = False) -> int:
    """Estimated resident VMEM per head in one grid cell — the model behind
    `_pick_hb`, exposed for `scripts/vmem_probe.py` to validate against
    Mosaic's compile-time accounting (one shared formula, no drift). The
    per-variant terms are mirrored jax-free in `tune/space.py`
    (sync-tested in tests/test_tune.py)."""
    n = (3 * block_k * d * 2            # k/v in + one of q/do
         + 2 * block_q * d * 2          # q tile + bf16 out tile
         + 2 * block_q * d * 4          # fp32 accumulators
         + block_q * block_k * 6)       # s fp32 + p bf16 intermediate
    if kind == "softmax":
        n += 2 * block_q * _LANES * 4   # m/l stats scratch (sigmoid: none)
    if has_mask:
        n += block_k * 4                # additive key-padding row
    if has_bias:
        n += 2 * block_q * block_k * 4  # bias in-tile + dbias scratch/out
    return n


def _pick_hb(bn: int, block_q: int, block_k: int, d: int,
             spec: VariantSpec = _SOFTMAX, n_heads: int | None = None) -> int:
    """Heads per grid cell: the per-head (S, 64) matmuls are too small to
    hide the ~us grid-step sequencing cost, so each cell processes `hb`
    heads back to back (measured ~2x on ViT-shape attention on v5e). The
    bias variant additionally needs hb | N so a head block never straddles
    two samples' rows (its bias index map divides by N/hb)."""
    per_head = _per_head_vmem_bytes(block_q, block_k, d, kind=spec.kind,
                                    has_mask=spec.has_mask,
                                    has_bias=spec.has_bias)
    for hb in (8, 4, 2):
        if bn % hb:
            continue
        if spec.has_bias and (n_heads or bn) % hb:
            continue
        if hb * per_head <= _VMEM_BUDGET:
            return hb
    return 1


def _fwd_pallas(q3, k3, v3, maskadd, bias, causal, spec, sm_scale,
                logit_bias, block_q, block_k):
    """Assemble and run the forward pallas_call for any variant. Returns
    (o_padded, lse_padded_or_None)."""
    softmax = spec.kind == "softmax"
    bn, sq, d = q3.shape
    sk = k3.shape[1]
    sq_p, sk_p = _ceil_to(sq, block_q), _ceil_to(sk, block_k)
    qp, kp, vp = (_pad_seq(q3, sq_p), _pad_seq(k3, sk_p), _pad_seq(v3, sk_p))
    n_q, n_k = sq_p // block_q, sk_p // block_k
    n_heads = bias.shape[0] if spec.has_bias else bn
    hb = _pick_hb(bn, block_q, block_k, d, spec, n_heads)
    kernel = partial(_fwd_kernel, sk_real=sk, block_k=block_k, causal=causal,
                     sm_scale=sm_scale, logit_bias=logit_bias, n_k=n_k,
                     spec=spec)
    kv_idx = (_causal_kv_index(block_q, block_k, n_k) if causal
              else (lambda h, i, j: (h, j, 0)))
    inputs = [qp, kp, vp]
    in_specs = [
        pl.BlockSpec((hb, block_q, d), lambda h, i, j: (h, i, 0)),
        pl.BlockSpec((hb, block_k, d), kv_idx),
        pl.BlockSpec((hb, block_k, d), kv_idx),
    ]
    if spec.has_mask:
        inputs.append(jnp.pad(maskadd, ((0, 0), (0, 0), (0, sk_p - sk))))
        in_specs.append(pl.BlockSpec(
            (hb, 1, block_k), _mask_fwd_index(block_q, block_k, n_k, causal)))
    if spec.has_bias:
        inputs.append(jnp.pad(bias, ((0, 0), (0, sq_p - sq),
                                     (0, sk_p - sk))))
        in_specs.append(pl.BlockSpec(
            (hb, block_q, block_k),
            _bias_fwd_index(block_q, block_k, n_k, n_heads // hb, causal)))
    out_specs = [pl.BlockSpec((hb, block_q, d), lambda h, i, j: (h, i, 0))]
    out_shape = [jax.ShapeDtypeStruct((bn, sq_p, d), q3.dtype)]
    scratch = [pltpu.VMEM((hb, block_q, d), jnp.float32)]
    if softmax:
        out_specs.append(pl.BlockSpec((hb, 1, block_q),
                                      lambda h, i, j: (h, 0, i)))
        out_shape.append(jax.ShapeDtypeStruct((bn, 1, sq_p), jnp.float32))
        scratch = [pltpu.VMEM((hb, block_q, _LANES), jnp.float32),
                   pltpu.VMEM((hb, block_q, _LANES), jnp.float32)] + scratch
    outs = pl.pallas_call(
        kernel,
        grid=(bn // hb, n_q, n_k),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        compiler_params=_SEMANTICS,
        interpret=_interpret(),
    )(*inputs)
    return outs[0], (outs[1] if softmax else None)


def _flash_fwd_impl(q3, k3, v3, maskadd, bias, causal, spec, sm_scale,
                    logit_bias, block_q, block_k):
    sq = q3.shape[1]
    o, lse = _fwd_pallas(q3, k3, v3, maskadd, bias, causal, spec, sm_scale,
                         logit_bias, block_q, block_k)
    # the names make o/lse saveable by remat policies (`"dots"` in
    # `Transformer._remat_policy` saves them): jax.checkpoint traces through
    # custom_vjp fwd rules, and without a saveable mark the whole forward
    # kernel would re-run inside the backward pass of a remat'd layer
    o = checkpoint_name(o[:, :sq], "flash_o")
    if lse is not None:
        lse = checkpoint_name(lse[:, 0, :sq], "flash_lse")
    return o, (q3, k3, v3, maskadd, bias, o, lse)


@partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash(q3, k3, v3, maskadd, bias, causal, spec, sm_scale, logit_bias,
           block_q, block_k):
    o, _ = _flash_fwd_impl(q3, k3, v3, maskadd, bias, causal, spec,
                           sm_scale, logit_bias, block_q, block_k)
    return o


def _flash_fwd(q3, k3, v3, maskadd, bias, causal, spec, sm_scale,
               logit_bias, block_q, block_k):
    return _flash_fwd_impl(q3, k3, v3, maskadd, bias, causal, spec,
                           sm_scale, logit_bias, block_q, block_k)


def _flash_bwd(causal, spec, sm_scale, logit_bias, block_q, block_k, res,
               do, dlse=None):
    softmax = spec.kind == "softmax"
    q3, k3, v3, maskadd, bias, o, lse = res
    bn, sq, d = q3.shape
    sk = k3.shape[1]
    sq_p, sk_p = _ceil_to(sq, block_q), _ceil_to(sk, block_k)
    n_q, n_k = sq_p // block_q, sk_p // block_k
    qp, dop = _pad_seq(q3, sq_p), _pad_seq(do, sq_p)
    kp, vp = _pad_seq(k3, sk_p), _pad_seq(v3, sk_p)
    n_heads = bias.shape[0] if spec.has_bias else bn
    hb = _pick_hb(bn, block_q, block_k, d, spec, n_heads)
    n_hb = n_heads // hb

    mp = (jnp.pad(maskadd, ((0, 0), (0, 0), (0, sk_p - sk)))
          if spec.has_mask else None)
    bp = (jnp.pad(bias, ((0, 0), (0, sq_p - sq), (0, sk_p - sk)))
          if spec.has_bias else None)
    stats = []
    if softmax:
        delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=-1)
        if dlse is not None:
            # An lse cotangent folds exactly into delta: the lse output adds
            # dlse_i * p_ij to ds_ij, and the kernels compute
            # ds = p * (dp - delta), so delta -= dlse covers it for free.
            delta = delta - dlse.astype(jnp.float32)
        lse_p = jnp.pad(lse, ((0, 0), (0, sq_p - lse.shape[1])))[:, None]
        delta_p = jnp.pad(delta, ((0, 0), (0, sq_p - delta.shape[1])))[:, None]
        stats = [lse_p, delta_p]

    # ---- dq ---------------------------------------------------------------
    kv_idx = (_causal_kv_index(block_q, block_k, n_k) if causal
              else (lambda h, i, j: (h, j, 0)))
    q_spec = pl.BlockSpec((hb, block_q, d), lambda h, i, j: (h, i, 0))
    stat_spec = pl.BlockSpec((hb, 1, block_q), lambda h, i, j: (h, 0, i))
    dq_inputs = [qp, kp, vp]
    dq_specs = [q_spec, pl.BlockSpec((hb, block_k, d), kv_idx),
                pl.BlockSpec((hb, block_k, d), kv_idx)]
    if spec.has_mask:
        dq_inputs.append(mp)
        dq_specs.append(pl.BlockSpec(
            (hb, 1, block_k), _mask_fwd_index(block_q, block_k, n_k, causal)))
    if spec.has_bias:
        dq_inputs.append(bp)
        dq_specs.append(pl.BlockSpec(
            (hb, block_q, block_k),
            _bias_fwd_index(block_q, block_k, n_k, n_hb, causal)))
    dq_inputs.append(dop)
    dq_specs.append(q_spec)
    if softmax:
        dq_inputs += stats
        dq_specs += [stat_spec, stat_spec]
    dq = pl.pallas_call(
        partial(_bwd_dq_kernel, sk_real=sk, block_k=block_k, causal=causal,
                sm_scale=sm_scale, logit_bias=logit_bias, n_k=n_k, spec=spec),
        grid=(bn // hb, n_q, n_k),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((hb, block_q, d), lambda h, i, j: (h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bn, sq_p, d), q3.dtype),
        scratch_shapes=[pltpu.VMEM((hb, block_q, d), jnp.float32)],
        compiler_params=_SEMANTICS,
        interpret=_interpret(),
    )(*dq_inputs)[:, :sq]

    # ---- dk / dv ----------------------------------------------------------
    q_idx = (_causal_q_index(block_q, block_k) if causal
             else (lambda h, j, i: (h, i, 0)))
    stat_idx = (_causal_q_index(block_q, block_k, lse_layout=True) if causal
                else (lambda h, j, i: (h, 0, i)))
    kv_spec = pl.BlockSpec((hb, block_k, d), lambda h, j, i: (h, j, 0))
    dkv_inputs = [qp, kp, vp]
    dkv_specs = [pl.BlockSpec((hb, block_q, d), q_idx), kv_spec, kv_spec]
    if spec.has_mask:
        dkv_inputs.append(mp)
        dkv_specs.append(pl.BlockSpec((hb, 1, block_k),
                                      lambda h, j, i: (h, 0, j)))
    if spec.has_bias:
        dkv_inputs.append(bp)
        dkv_specs.append(pl.BlockSpec(
            (hb, block_q, block_k),
            _bias_dkv_index(block_q, block_k, n_hb, causal)))
    dkv_inputs.append(dop)
    dkv_specs.append(pl.BlockSpec((hb, block_q, d), q_idx))
    if softmax:
        dkv_inputs += stats
        dkv_specs += [pl.BlockSpec((hb, 1, block_q), stat_idx),
                      pl.BlockSpec((hb, 1, block_q), stat_idx)]
    dk, dv = pl.pallas_call(
        partial(_bwd_dkv_kernel, sq_real=sq, block_q=block_q, causal=causal,
                sm_scale=sm_scale, logit_bias=logit_bias, n_q=n_q, spec=spec),
        grid=(bn // hb, n_k, n_q),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((hb, block_k, d), lambda h, j, i: (h, j, 0)),
            pl.BlockSpec((hb, block_k, d), lambda h, j, i: (h, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bn, sk_p, d), q3.dtype),
            jax.ShapeDtypeStruct((bn, sk_p, d), q3.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((hb, block_k, d), jnp.float32),
            pltpu.VMEM((hb, block_k, d), jnp.float32),
        ],
        compiler_params=_SEMANTICS,
        interpret=_interpret(),
    )(*dkv_inputs)

    # ---- dbias ------------------------------------------------------------
    dbias = None
    if spec.has_bias:
        n_b = bn // n_heads
        q_idx4 = lambda h, i, j, b: (b * n_hb + h, i, 0)      # noqa: E731
        kv_idx4 = lambda h, i, j, b: (b * n_hb + h, j, 0)     # noqa: E731
        stat_idx4 = lambda h, i, j, b: (b * n_hb + h, 0, i)   # noqa: E731
        db_inputs = [qp, kp, vp]
        db_specs = [pl.BlockSpec((hb, block_q, d), q_idx4),
                    pl.BlockSpec((hb, block_k, d), kv_idx4),
                    pl.BlockSpec((hb, block_k, d), kv_idx4)]
        if spec.has_mask:
            db_inputs.append(mp)
            db_specs.append(pl.BlockSpec(
                (hb, 1, block_k), lambda h, i, j, b: (b * n_hb + h, 0, j)))
        db_inputs.append(bp)
        db_specs.append(pl.BlockSpec((hb, block_q, block_k),
                                     lambda h, i, j, b: (h, i, j)))
        db_inputs.append(dop)
        db_specs.append(pl.BlockSpec((hb, block_q, d), q_idx4))
        if softmax:
            db_inputs += stats
            db_specs += [pl.BlockSpec((hb, 1, block_q), stat_idx4),
                         pl.BlockSpec((hb, 1, block_q), stat_idx4)]
        dbias = pl.pallas_call(
            partial(_bwd_dbias_kernel, sq_real=sq, sk_real=sk,
                    block_q=block_q, block_k=block_k, causal=causal,
                    sm_scale=sm_scale, logit_bias=logit_bias, n_b=n_b,
                    spec=spec),
            grid=(n_hb, n_q, n_k, n_b),
            in_specs=db_specs,
            out_specs=pl.BlockSpec((hb, block_q, block_k),
                                   lambda h, i, j, b: (h, i, j)),
            out_shape=jax.ShapeDtypeStruct((n_heads, sq_p, sk_p),
                                           jnp.float32),
            scratch_shapes=[pltpu.VMEM((hb, block_q, block_k), jnp.float32)],
            compiler_params=_SEMANTICS4,
            interpret=_interpret(),
        )(*db_inputs)[:, :sq, :sk]

    # the mask is non-learnable by contract (it is expanded from a boolean
    # key-padding mask host-side); its zero cotangent dead-ends in the
    # wrapper's jnp.where over constants
    dmask = jnp.zeros_like(maskadd) if spec.has_mask else None
    return dq, dk[:, :sk], dv[:, :sk], dmask, dbias


def _flash_vjp_bwd(causal, spec, sm_scale, logit_bias, block_q, block_k,
                   res, do):
    return _flash_bwd(causal, spec, sm_scale, logit_bias, block_q, block_k,
                      res, do)


_flash.defvjp(_flash_fwd, _flash_vjp_bwd)


def _pick_block(seq: int, requested: int) -> int:
    """Largest block (<= requested) that minimizes padded-sequence length:
    dead-tile work grows with ceil_to(seq, block)^2, so e.g. seq 577 takes
    block 128 (pad to 640) over 512 (pad to 1024), while exact multiples
    keep the biggest tile. Always a multiple of 128: the (hb, 1, block)
    lse/delta blocks put the block extent in the LANE dimension, where
    Mosaic requires a 128 multiple — a sub-128 request would lower on some
    toolchains only by luck of the block==array escape hatch."""
    best = None
    for b in (512, 256, 128):
        if b > requested:
            continue
        padded = _ceil_to(seq, b)
        if best is None or padded < best[0]:
            best = (padded, b)
    return best[1] if best else _LANES


def _resolve_blocks(q, k, v, block_q, block_k,
                    kernel: str = "flash_attention"):
    """Trace-time (host-side) block resolution through the tune cache:
    ``None`` means "tuned value if the persistent cache has one for these
    shapes/dtypes, else the shipped default" — lookup only, never a
    measurement (docs/tuning.md). Explicit ints win, so the tuner's own
    bench closures cannot recurse. Each family member looks up under its
    own kernel name (its VMEM footprint, and therefore its feasible and
    optimal blocks, differ)."""
    if block_q is not None and block_k is not None:
        return int(block_q), int(block_k)
    from jimm_tpu.tune import best_config
    cfg = best_config(kernel, (q.shape, k.shape, v.shape),
                      (q.dtype, k.dtype, v.dtype),
                      default={"block_q": DEFAULT_BLOCK_Q,
                               "block_k": DEFAULT_BLOCK_K})
    return (int(block_q if block_q is not None else cfg["block_q"]),
            int(block_k if block_k is not None else cfg["block_k"]))


def _prologue(q, k, v, block_q, block_k, kernel: str = "flash_attention"):
    """Shared head-flattening + scale/block selection for every entry
    point. Pads off-tile head dims up (scale still uses the REAL d)."""
    d = q.shape[-1]
    sm_scale = 1.0 / (d ** 0.5)
    block_q, block_k = _resolve_blocks(q, k, v, block_q, block_k,
                                       kernel=kernel)
    block_q = min(_pick_block(q.shape[1], block_q),
                  _ceil_to(q.shape[1], 128))
    block_k = min(_pick_block(k.shape[1], block_k),
                  _ceil_to(k.shape[1], 128))
    q3, k3, v3 = map(_flatten_heads, (q, k, v))
    dp = _head_pad_target(d)
    if dp != d:
        q3, k3, v3 = (_pad_last(x, dp) for x in (q3, k3, v3))
    return q3, k3, v3, sm_scale, block_q, block_k


def _canon_mask(mask: jax.Array, b: int, sk: int) -> jax.Array:
    """Accept ``(B, Sk)`` or the dispatch convention ``(B, 1, 1, Sk)``
    (bool/int, True = attend); return ``(B, Sk)`` bool."""
    if mask.ndim == 4:
        if mask.shape[1] != 1 or mask.shape[2] != 1:
            raise ValueError(
                "masked flash attention supports KEY-PADDING masks only "
                f"((B, Sk) or (B, 1, 1, Sk)); got {mask.shape} — arbitrary "
                "(B, N, Sq, Sk) masks need impl='xla'")
        mask = mask[:, 0, 0, :]
    if mask.shape != (b, sk):
        raise ValueError(f"key-padding mask shape {mask.shape} does not "
                         f"match (B, Sk)=({b}, {sk})")
    return mask != 0


def _expand_mask(mask: jax.Array, n: int) -> jax.Array:
    """(B, Sk) bool -> (B*N, 1, Sk) additive f32 rows (0 keep / NEG_INF
    drop), replicated per head in `_flatten_heads` row order."""
    b, sk = mask.shape
    add = jnp.where(mask, 0.0, NEG_INF).astype(jnp.float32)
    return jnp.broadcast_to(add[:, None, None, :],
                            (b, n, 1, sk)).reshape(b * n, 1, sk)


def _canon_bias(bias: jax.Array, n: int, sq: int, sk: int) -> jax.Array:
    """Broadcast an additive bias to per-head ``(N, Sq, Sk)`` f32 (grads
    flow back through the broadcast to the caller's shape)."""
    return jnp.broadcast_to(bias.astype(jnp.float32), (n, sq, sk))


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    is_causal: bool = False,
                    block_q: int | None = None,
                    block_k: int | None = None) -> jax.Array:
    """Flash attention over ``(B, S, N, D)`` q/k/v. Scale is 1/sqrt(D) like
    `jax.nn.dot_product_attention`. Runs the Pallas interpreter off-TPU so
    CPU tests exercise the same code path. Block sizes default to the tune
    cache's answer for these shapes (falling back to ``DEFAULT_BLOCK_*``)."""
    b, _, n, d = q.shape
    q3, k3, v3, sm_scale, block_q, block_k = _prologue(q, k, v, block_q,
                                                       block_k)
    o = _flash(q3, k3, v3, None, None, is_causal, _SOFTMAX, sm_scale, 0.0,
               block_q, block_k)
    return _unflatten_heads(o, b, n)[..., :d]


def flash_attention_masked(q: jax.Array, k: jax.Array, v: jax.Array,
                           mask: jax.Array, *,
                           is_causal: bool = False,
                           block_q: int | None = None,
                           block_k: int | None = None) -> jax.Array:
    """Flash attention with a per-sample key-padding mask (the NaFlex /
    MAP-pooling case): ``mask`` is ``(B, Sk)`` or ``(B, 1, 1, Sk)``
    bool/int, True = attend. Masked keys receive exactly zero attention
    and zero gradient. Rows with NO valid key produce finite garbage (see
    module docstring) — mask them downstream, as NaFlex pooling does."""
    b, _, n, d = q.shape
    sk = k.shape[1]
    maskadd = _expand_mask(_canon_mask(mask, b, sk), n)
    q3, k3, v3, sm_scale, block_q, block_k = _prologue(
        q, k, v, block_q, block_k, kernel="flash_attention_masked")
    spec = VariantSpec(kind="softmax", has_mask=True)
    o = _flash(q3, k3, v3, maskadd, None, is_causal, spec, sm_scale, 0.0,
               block_q, block_k)
    return _unflatten_heads(o, b, n)[..., :d]


def flash_attention_bias(q: jax.Array, k: jax.Array, v: jax.Array,
                         bias: jax.Array, *,
                         is_causal: bool = False,
                         block_q: int | None = None,
                         block_k: int | None = None) -> jax.Array:
    """Flash attention with an additive logits bias broadcastable to
    ``(N, Sq, Sk)`` (relative-position style; shared across the batch).
    Differentiable in ``bias`` — the backward runs a dedicated
    batch-innermost accumulation kernel, never materializing
    ``(B, N, Sq, Sk)``."""
    b, sq, n, d = q.shape
    sk = k.shape[1]
    bias3 = _canon_bias(bias, n, sq, sk)
    q3, k3, v3, sm_scale, block_q, block_k = _prologue(
        q, k, v, block_q, block_k, kernel="flash_attention_bias")
    spec = VariantSpec(kind="softmax", has_bias=True)
    o = _flash(q3, k3, v3, None, bias3, is_causal, spec, sm_scale, 0.0,
               block_q, block_k)
    return _unflatten_heads(o, b, n)[..., :d]


def sigmoid_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                      is_causal: bool = False,
                      mask: jax.Array | None = None,
                      logit_bias: float | None = None,
                      block_q: int | None = None,
                      block_k: int | None = None) -> jax.Array:
    """Sigmoid attention: ``o = sigmoid(q k^T / sqrt(D) + logit_bias) v``
    — no row normalizer, so the online loop keeps no statistics and the
    backward needs no lse/delta. ``logit_bias`` defaults to ``-log(Sk)``
    (the sigmoid-attention paper's initialization, which matches softmax's
    1/Sk row mass at init). Optional key-padding ``mask`` as in
    `flash_attention_masked`; masked (and fully-masked) rows are exactly
    zero here — sigmoid(NEG_INF) underflows to 0, no garbage rows."""
    b, _, n, d = q.shape
    sk = k.shape[1]
    if logit_bias is None:
        logit_bias = -math.log(max(sk, 1))
    spec = VariantSpec(kind="sigmoid", has_mask=mask is not None)
    maskadd = (_expand_mask(_canon_mask(mask, b, sk), n)
               if mask is not None else None)
    q3, k3, v3, sm_scale, block_q, block_k = _prologue(
        q, k, v, block_q, block_k, kernel="sigmoid_attention")
    o = _flash(q3, k3, v3, maskadd, None, is_causal, spec, sm_scale,
               float(logit_bias), block_q, block_k)
    return _unflatten_heads(o, b, n)[..., :d]


# ---------------------------------------------------------------------------
# (o, lse) variant — building block for cross-chip ring attention
# ---------------------------------------------------------------------------

@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_lse(q3, k3, v3, causal, sm_scale, block_q, block_k):
    o, res = _flash_fwd_impl(q3, k3, v3, None, None, causal, _SOFTMAX,
                             sm_scale, 0.0, block_q, block_k)
    return o, res[6]


def _flash_lse_fwd(q3, k3, v3, causal, sm_scale, block_q, block_k):
    o, res = _flash_fwd_impl(q3, k3, v3, None, None, causal, _SOFTMAX,
                             sm_scale, 0.0, block_q, block_k)
    return (o, res[6]), res


def _flash_lse_bwd(causal, sm_scale, block_q, block_k, res, cts):
    do, dlse = cts
    # The lse cotangent is exact and free: it folds into the delta term of
    # the standard flash backward (see _flash_bwd) — no extra passes, no
    # materialized attention matrix.
    dq, dk, dv, _, _ = _flash_bwd(causal, _SOFTMAX, sm_scale, 0.0, block_q,
                                  block_k, res, do, dlse)
    return dq, dk, dv


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def flash_attention_lse(q: jax.Array, k: jax.Array, v: jax.Array, *,
                        is_causal: bool = False,
                        block_q: int | None = None,
                        block_k: int | None = None
                        ) -> tuple[jax.Array, jax.Array]:
    """Like `flash_attention` but also returns the per-row logsumexp
    ``(B, N, S)`` so partial results over kv chunks can be merged exactly
    (the ring-attention combine)."""
    b, sq, n, d = q.shape
    q3, k3, v3, sm_scale, block_q, block_k = _prologue(q, k, v, block_q,
                                                       block_k)
    o3, lse3 = _flash_lse(q3, k3, v3, is_causal, sm_scale, block_q, block_k)
    return _unflatten_heads(o3, b, n)[..., :d], lse3.reshape(b, n, sq)


# ---------------------------------------------------------------------------
# External-residual hop entry points — the sequence-parallel ring
# (`jimm_tpu/parallel/seqpar.py`) drives the SAME kernels per KV hop
# ---------------------------------------------------------------------------

def ring_hop_fwd(q3, k3, v3, maskadd, spec, sm_scale, logit_bias,
                 block_q, block_k):
    """One ring-hop forward in flattened-heads ``(B*N, S, D)`` space:
    returns ``(o, lse)`` for the hop's local (q × visiting-KV) product
    (``lse`` is None for the sigmoid kind, which keeps no normalizer).
    The caller owns the cross-hop merge and differentiation — this is a
    plain function, not a custom_vjp."""
    o, res = _flash_fwd_impl(q3, k3, v3, maskadd, None, False, spec,
                             sm_scale, logit_bias, block_q, block_k)
    return o, res[6]


def ring_hop_bwd(q3, k3, v3, maskadd, o3, lse3, do3, spec, sm_scale,
                 logit_bias, block_q, block_k):
    """One ring-hop backward against GLOBAL residuals: ``o3``/``lse3`` are
    the fully-merged output and logsumexp (all chunks folded), so the
    kernels' ``p = exp(s - lse)`` and ``delta = rowsum(do·o)`` are the
    global row statistics and the per-hop dq/dk/dv are exact partial
    gradients — summing them over hops reproduces the unsharded backward.
    (Sigmoid ignores o3/lse3: no normalizer, no delta.)"""
    dq, dk, dv, _, _ = _flash_bwd(False, spec, sm_scale, logit_bias,
                                  block_q, block_k,
                                  (q3, k3, v3, maskadd, None, o3, lse3), do3)
    return dq, dk, dv
