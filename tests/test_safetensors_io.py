"""Pure-numpy safetensors reader/writer roundtrip + interop with the
upstream Rust wheel."""

import ml_dtypes
import numpy as np
import pytest

from jimm_tpu.weights.safetensors_io import load_file, save_file


@pytest.fixture
def tensors(rng):
    return {
        "a.weight": rng.randn(4, 8).astype(np.float32),
        "a.bias": rng.randn(8).astype(np.float16),
        "b.scale": rng.randn(3, 3, 2).astype(np.float64),
        "b.bf16": rng.randn(5, 7).astype(np.float32).astype(ml_dtypes.bfloat16),
        "ids": np.arange(12, dtype=np.int64).reshape(3, 4),
        "flag": np.array([True, False]),
    }


def test_roundtrip(tensors, tmp_path):
    path = tmp_path / "t.safetensors"
    save_file(tensors, path)
    loaded = load_file(path)
    assert set(loaded) == set(tensors)
    for k in tensors:
        assert loaded[k].dtype == tensors[k].dtype
        np.testing.assert_array_equal(loaded[k], tensors[k])


def test_reads_upstream_wheel_output(tensors, tmp_path):
    st = pytest.importorskip("safetensors.numpy")
    path = tmp_path / "up.safetensors"
    upstream = {k: v for k, v in tensors.items()
                if v.dtype != ml_dtypes.bfloat16}
    st.save_file(upstream, str(path))
    loaded = load_file(path)
    for k in upstream:
        np.testing.assert_array_equal(loaded[k], upstream[k])


def test_upstream_wheel_reads_our_output(tensors, tmp_path):
    st = pytest.importorskip("safetensors.numpy")
    path = tmp_path / "ours.safetensors"
    ours = {k: v for k, v in tensors.items()
            if v.dtype != ml_dtypes.bfloat16}
    save_file(ours, path, metadata={"format": "jimm_tpu"})
    loaded = st.load_file(str(path))
    for k in ours:
        np.testing.assert_array_equal(loaded[k], ours[k])


def test_metadata_ignored_on_load(tmp_path, rng):
    path = tmp_path / "m.safetensors"
    save_file({"x": rng.randn(2).astype(np.float32)}, path,
              metadata={"origin": "test"})
    assert set(load_file(path)) == {"x"}
