"""pytorch_model.bin loading without torch in the import graph.

Capability parity with the reference's `use_pytorch=True` path
(ref `common/utils.py:55-71`, SURVEY §2.4 "both formats"), implemented by the
stdlib-only unpickler in `jimm_tpu/weights/torch_pickle.py`. Torch appears
here only as the oracle that writes the files.
"""

import pickle

import jax.numpy as jnp
import numpy as np
import pytest

from jimm_tpu import VisionTransformer
from jimm_tpu.weights import torch_pickle

from hf_util import sample_image, save_tiny_vit


def test_dtype_roundtrip(tmp_path):
    import torch
    tensors = {
        "f32": torch.randn(3, 4),
        "f64": torch.randn(2, 2, dtype=torch.float64),
        "f16": torch.randn(5).half(),
        "bf16": torch.randn(4, 4).bfloat16(),
        "i64": torch.arange(6).reshape(2, 3),
        "i32": torch.arange(4, dtype=torch.int32),
        "u8": torch.arange(10, dtype=torch.uint8),
        "bool": torch.tensor([True, False, True]),
        "scalar": torch.tensor(2.5),
        # non-contiguous view: strides must be honored
        "noncontig": torch.randn(6, 8).t(),
        # two tensors sharing one storage with different offsets
        "slice": torch.arange(20, dtype=torch.float32)[5:15],
    }
    torch.save(tensors, tmp_path / "t.bin")
    loaded = torch_pickle.load_file(tmp_path / "t.bin")
    assert set(loaded) == set(tensors)
    for k, v in tensors.items():
        ref = (v.float().numpy() if v.dtype == torch.bfloat16
               else v.numpy())
        got = loaded[k]
        assert tuple(got.shape) == tuple(v.shape), k
        np.testing.assert_array_equal(
            got.astype(np.float32) if k == "bf16" else got, ref, err_msg=k)


def test_state_dict_save_with_metadata(tmp_path):
    """`torch.save(module.state_dict())` writes an OrderedDict carrying a
    `_metadata` instance attribute — the most common .bin layout in the
    wild; must load."""
    import torch
    lin = torch.nn.Linear(4, 3)
    torch.save(lin.state_dict(), tmp_path / "sd.bin")
    loaded = torch_pickle.load_file(tmp_path / "sd.bin")
    assert set(loaded) == {"weight", "bias"}
    np.testing.assert_array_equal(loaded["weight"],
                                  lin.weight.detach().numpy())


def test_oob_view_rejected():
    """A corrupt stream whose tensor view exceeds its storage must raise,
    not silently read out of bounds via as_strided."""
    storage = torch_pickle._LazyStorage(
        lambda: np.arange(4, dtype=np.float32).tobytes(),
        np.dtype(np.float32))
    with pytest.raises(ValueError, match="exceeds storage"):
        torch_pickle._rebuild_tensor_v2(storage, 0, (1048576,), (1,))
    with pytest.raises(ValueError, match="negative"):
        torch_pickle._rebuild_tensor_v2(storage, 3, (4,), (-1,))
    with pytest.raises(ValueError, match="offset"):
        torch_pickle._rebuild_tensor_v2(storage, 9, (1,), (1,))
    # a valid strided view at the very edge still works
    out = torch_pickle._rebuild_tensor_v2(storage, 0, (2, 2), (2, 1))
    np.testing.assert_array_equal(out, [[0, 1], [2, 3]])


def test_non_torch_zip_rejected(tmp_path):
    import zipfile
    with zipfile.ZipFile(tmp_path / "x.bin", "w") as zf:
        zf.writestr("something.txt", "hello")
    with pytest.raises(ValueError, match="not a torch checkpoint"):
        torch_pickle.load_file(tmp_path / "x.bin")


def test_sharded_bin_dir(tmp_path, rng):
    """Sharded pytorch_model.bin.index.json checkpoints load, including via
    the no-safetensors fallback with use_pytorch=False."""
    import json as _json
    import torch
    from transformers import ViTForImageClassification
    safedir = save_tiny_vit(tmp_path / "safe")
    hf = ViTForImageClassification.from_pretrained(safedir)
    sd = {k: v for k, v in hf.state_dict().items()}
    keys = sorted(sd)
    half = len(keys) // 2
    d = tmp_path / "sharded"
    d.mkdir()
    shards = {"pytorch_model-00001-of-00002.bin": keys[:half],
              "pytorch_model-00002-of-00002.bin": keys[half:]}
    weight_map = {}
    for shard, ks in shards.items():
        torch.save({k: sd[k] for k in ks}, d / shard)
        weight_map.update({k: shard for k in ks})
    (d / "pytorch_model.bin.index.json").write_text(
        _json.dumps({"weight_map": weight_map}))
    import shutil
    shutil.copy(f"{safedir}/config.json", d / "config.json")

    img = jnp.asarray(sample_image(rng, size=48))
    ref = VisionTransformer.from_pretrained(safedir)
    for flag in (True, False):
        model = VisionTransformer.from_pretrained(str(d), use_pytorch=flag)
        np.testing.assert_allclose(np.asarray(model(img)),
                                   np.asarray(ref(img)), atol=1e-6)


def test_rejects_arbitrary_globals(tmp_path):
    """The whitelist unpickler must refuse non-tensor pickles (safer than
    pre-2.6 torch.load)."""
    import torch

    class Evil:
        def __reduce__(self):
            return (print, ("pwned",))

    torch.save({"w": torch.randn(2), "e": Evil()}, tmp_path / "evil.bin")
    with pytest.raises(pickle.UnpicklingError, match="whitelist"):
        torch_pickle.load_file(tmp_path / "evil.bin")


@pytest.fixture(scope="module")
def vit_bin_ckpt(tmp_path_factory):
    """A tiny HF ViT checkpoint saved in the torch .bin format only."""
    import torch  # noqa: F401
    from transformers import ViTForImageClassification
    safedir = save_tiny_vit(tmp_path_factory.mktemp("vit_safe"))
    bindir = tmp_path_factory.mktemp("vit_bin")
    hf = ViTForImageClassification.from_pretrained(safedir)
    hf.save_pretrained(bindir, safe_serialization=False)
    assert (bindir / "pytorch_model.bin").is_file()
    assert not (bindir / "model.safetensors").exists()
    return safedir, str(bindir)


def test_vit_from_pytorch_bin_matches_safetensors(vit_bin_ckpt, rng):
    safedir, bindir = vit_bin_ckpt
    ref = VisionTransformer.from_pretrained(safedir)
    model = VisionTransformer.from_pretrained(bindir, use_pytorch=True)
    img = jnp.asarray(sample_image(rng, size=48))
    np.testing.assert_allclose(np.asarray(model(img)),
                               np.asarray(ref(img)), atol=1e-6)


def test_dir_falls_back_to_bin_without_flag(vit_bin_ckpt, rng):
    """A directory holding only pytorch_model.bin loads even with
    use_pytorch=False (no safetensors to prefer)."""
    _, bindir = vit_bin_ckpt
    model = VisionTransformer.from_pretrained(bindir)
    out = model(jnp.asarray(sample_image(rng, size=48)))
    assert out.shape == (2, 7)


def test_bare_bin_file_path(vit_bin_ckpt, rng):
    """Loading a bare .bin file path works, with sibling config discovery."""
    _, bindir = vit_bin_ckpt
    model = VisionTransformer.from_pretrained(bindir + "/pytorch_model.bin")
    out = model(jnp.asarray(sample_image(rng, size=48)))
    assert out.shape == (2, 7)
