"""Cold-tier IO engine: disk spill + journaled, double-buffered fetch.

Cold clusters live as content-addressed segments on the aot
:class:`~jimm_tpu.aot.store.ArtifactStore` (same atomic-install /
quarantine / LRU discipline as compiled programs). The engine owns one
daemon worker thread: request threads never touch disk — they enqueue a
:meth:`prefetch` right after the device-side probe names the clusters,
run the host-side ADC shortlist while the worker streams bytes in, and
only then :meth:`collect` the staged rows. When the scan genuinely
outruns the disk, the wait is timed under a ``tier_stall`` span (→
``jimm_spans_tier_stall_seconds`` on the timeline) and counted on
``jimm_tier_stalls_total`` — stalls are a first-class signal, not a
silent latency tax. Every transfer is journaled (``tier_spill`` /
``tier_fetch`` / ``tier_fetch_failed``) on the caller's correlation id.

A corrupt or truncated cold segment is quarantined and the fetch fails
loudly; the searcher degrades that query's candidates rather than
serving rows it cannot trust.
"""

from __future__ import annotations

import hashlib
import json
import queue
import threading
import time

import numpy as np

from jimm_tpu.obs import get_journal, get_registry, span

__all__ = ["TIER_FORMAT_VERSION", "TierIoEngine", "decode_cluster",
           "encode_cluster"]

#: bump when the cold-segment framing changes — old artifacts quarantine
TIER_FORMAT_VERSION = 1

#: an honest upper bound for one cluster fetch; a disk this slow is an
#: incident, not a stall
_COLLECT_TIMEOUT_S = 60.0


def encode_cluster(cluster: int, row_ids: np.ndarray,
                   rows: np.ndarray) -> bytes:
    """Frame one cluster's full-precision rows as a cold segment:
    header JSON line, then row ids (int64), then rows (float32)."""
    row_ids = np.ascontiguousarray(row_ids, np.int64)
    rows = np.ascontiguousarray(rows, np.float32)
    if rows.ndim != 2 or len(row_ids) != len(rows):
        raise ValueError(f"rows {rows.shape} / row_ids {row_ids.shape} "
                         f"mismatch")
    header = {"tier_format": TIER_FORMAT_VERSION, "cluster": int(cluster),
              "rows": int(len(rows)), "dim": int(rows.shape[1])}
    return json.dumps(header, sort_keys=True,
                      separators=(",", ":")).encode() + b"\n" + \
        row_ids.tobytes() + rows.tobytes()


def decode_cluster(payload: bytes) -> tuple[int, np.ndarray, np.ndarray]:
    """Inverse of :func:`encode_cluster` → ``(cluster, row_ids, rows)``;
    raises ValueError on bad framing (callers quarantine)."""
    head, sep, body = payload.partition(b"\n")
    if not sep:
        raise ValueError("cold segment has no header line")
    try:
        header = json.loads(head)
    except ValueError as e:
        raise ValueError(f"bad cold-segment header: {e}") from None
    if header.get("tier_format") != TIER_FORMAT_VERSION:
        raise ValueError(f"tier_format {header.get('tier_format')!r} != "
                         f"{TIER_FORMAT_VERSION}")
    n, dim = int(header["rows"]), int(header["dim"])
    ids_bytes = n * 8
    if len(body) != ids_bytes + n * dim * 4:
        raise ValueError(f"cold segment body is {len(body)} bytes, header "
                         f"promises {ids_bytes + n * dim * 4}")
    row_ids = np.frombuffer(body[:ids_bytes], np.int64).copy()
    rows = np.frombuffer(body[ids_bytes:], np.float32).reshape(n, dim)
    return int(header["cluster"]), row_ids, rows.copy()


class _Staged:
    __slots__ = ("ready", "row_ids", "rows", "error", "waiters")

    def __init__(self):
        self.ready = threading.Event()
        self.row_ids = None
        self.rows = None
        self.error: str | None = None
        #: concurrent searches waiting on this fetch — the entry is
        #: consumed only when the LAST waiter collects, so two request
        #: threads deduping onto one disk read both get the rows
        self.waiters = 0


class TierIoEngine:
    """Spill clusters to the artifact store; stream them back on demand.

    One daemon worker drains the fetch queue so disk latency overlaps
    the host-side ADC pass (FastUSP's overlap-transfer-behind-compute,
    one level up the hierarchy). ``prefetch`` and ``collect`` are safe
    from any thread; the staging table is guarded by its own lock and
    no lock is ever held across disk IO or an event wait.
    """

    def __init__(self, artifacts, *, label: str = "index"):
        self.artifacts = artifacts
        self.label = str(label)
        self._queue: queue.Queue = queue.Queue()
        self._lock = threading.Lock()
        self._staged: dict[int, _Staged] = {}
        reg = get_registry("jimm_tier")
        self._m_spills = reg.counter("jimm_tier_spills_total")
        self._m_fetches = reg.counter("jimm_tier_cold_fetches_total")
        self._m_fetch_bytes = reg.counter("jimm_tier_cold_fetch_bytes_total")
        self._m_failed = reg.counter("jimm_tier_fetch_failures_total")
        self._m_stalls = reg.counter("jimm_tier_stalls_total")
        self._worker = threading.Thread(target=self._drain,
                                        name=f"tier-io-{self.label}",
                                        daemon=True)
        self._worker.start()

    # -- spill ------------------------------------------------------------

    def spill(self, cluster: int, row_ids: np.ndarray, rows: np.ndarray,
              *, cid: str | None = None) -> str:
        """Write one cluster cold; returns its artifact fingerprint.

        Content-addressed: re-spilling identical rows is a no-op put, and
        a re-tiered layout never aliases a stale segment.
        """
        payload = encode_cluster(cluster, row_ids, rows)
        digest = hashlib.sha256(payload).hexdigest()[:16]
        fp = f"tier-{self.label}-c{int(cluster)}-{digest}"
        if not self.artifacts.contains(fp):
            self.artifacts.put(fp, payload, {
                "kind": "tier_cluster", "cluster": int(cluster),
                "rows": int(len(rows)), "label": self.label,
                "tier_format": TIER_FORMAT_VERSION})
        self._m_spills.inc()
        get_journal().emit("tier_spill", cid=cid, cluster=int(cluster),
                           bytes=len(payload), fingerprint=fp)
        return fp

    # -- fetch ------------------------------------------------------------

    def prefetch(self, cluster: int, fingerprint: str,
                 *, cid: str | None = None) -> None:
        """Enqueue a cold fetch. Dedups onto an already-staged or
        in-flight entry — but every call registers a waiter, so each
        matching :meth:`collect` (one per prefetch, from any thread)
        gets the rows off the single disk read."""
        with self._lock:
            entry = self._staged.get(cluster)
            if entry is not None:
                entry.waiters += 1
                return
            entry = _Staged()
            entry.waiters = 1
            self._staged[cluster] = entry
        self._queue.put((int(cluster), fingerprint, cid,
                         time.monotonic()))

    def collect(self, cluster: int,
                *, timeout_s: float = _COLLECT_TIMEOUT_S
                ) -> tuple[np.ndarray, np.ndarray]:
        """Staged ``(row_ids, rows)`` for a prefetched cluster; blocks
        (timed as a stall) only when the fetch has not landed yet. The
        last waiter consumes the entry — staging stays bounded by the
        probe width times the concurrent request fan-in."""
        with self._lock:
            entry = self._staged.get(cluster)
        if entry is None:
            raise KeyError(f"cluster {cluster} was never prefetched")
        if not entry.ready.is_set():
            self._m_stalls.inc()
            with span("tier_stall"):
                ok = entry.ready.wait(timeout_s)
            if not ok:
                self._release(cluster, entry)
                raise TimeoutError(f"cold fetch of cluster {cluster} "
                                   f"exceeded {timeout_s:.0f}s")
        self._release(cluster, entry)
        if entry.error is not None:
            raise RuntimeError(f"cold fetch of cluster {cluster} failed: "
                               f"{entry.error}")
        return entry.row_ids, entry.rows

    def _release(self, cluster: int, entry: _Staged) -> None:
        with self._lock:
            entry.waiters -= 1
            if entry.waiters <= 0 and self._staged.get(cluster) is entry:
                del self._staged[cluster]

    def pending(self) -> int:
        with self._lock:
            return len(self._staged)

    def close(self) -> None:
        self._queue.put(None)
        self._worker.join(timeout=5.0)

    # -- worker -----------------------------------------------------------

    def _drain(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                return
            cluster, fp, cid, t_enq = item
            t0 = time.monotonic()
            err = None
            row_ids = rows = None
            try:
                payload = self.artifacts.get(fp)
                if payload is None:
                    err = f"artifact {fp} missing"
                else:
                    got, row_ids, rows = decode_cluster(payload)
                    if got != cluster:
                        raise ValueError(f"segment names cluster {got}")
            except ValueError as e:
                self.artifacts.quarantine(fp, f"tier decode: {e}")
                err = str(e)
            except Exception as e:  # noqa: BLE001 — a dead worker would
                err = str(e)        # strand every future collect

            with self._lock:
                entry = self._staged.get(cluster)
            if entry is None:          # consumed by a timed-out collect
                continue
            dur = time.monotonic() - t0
            if err is None:
                entry.row_ids, entry.rows = row_ids, rows
                self._m_fetches.inc()
                self._m_fetch_bytes.inc(rows.nbytes + row_ids.nbytes)
                get_journal().emit("tier_fetch", cid=cid,
                                   cluster=cluster, tier="cold",
                                   bytes=int(rows.nbytes), dur_s=dur,
                                   queued_s=t0 - t_enq)
            else:
                entry.error = err
                self._m_failed.inc()
                get_journal().emit("tier_fetch_failed", cid=cid,
                                   cluster=cluster, fingerprint=fp,
                                   error=err)
            entry.ready.set()
