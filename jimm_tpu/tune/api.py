"""`best_config` lookup + the offline tuning driver.

The contract the ops hot path relies on:

- **Never tune in the hot path.** `best_config` is called at trace time
  from `ops/flash_attention.py` / `ops/layer_norm.py`; it does a memo/store
  lookup and otherwise returns the kernel's safe default. Measurement only
  happens when the operator opted in — ``JIMM_TUNE=1`` in the environment,
  or an explicit offline ``jimm-tpu tune run`` / `tune_kernel` call.
- **Every outcome is counted**: ``jimm_tune_hit_total`` /
  ``jimm_tune_miss_total`` / ``jimm_tune_fallback_total`` (observability.md
  lists the series), so a fleet silently running on fallback defaults shows
  up on the first metrics dump.

The process-wide cache defaults to ``JIMM_TUNE_CACHE`` or
``~/.cache/jimm_tpu/tune``; ``serve --tune-cache`` / ``bench.py
--tune-cache`` repoint it via `configure`.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable, Mapping, Sequence

from jimm_tpu import obs
from jimm_tpu.tune.cache import TuneCache, TuneKey, tune_key
from jimm_tpu.tune.measure import measure
from jimm_tpu.tune.space import (bias_flash_space, flash_space,
                                 fp8_matmul_space, int8_flash_space,
                                 int8_matmul_space, ivf_space, ln_space,
                                 masked_flash_space, retrieval_space,
                                 ring_space, sigmoid_space, tier_space)

__all__ = ["KERNELS", "KernelSpec", "best_config", "configure", "get_cache",
           "tune_kernel"]

Shapes = Sequence[Sequence[int]]
Dtypes = Sequence[Any]


# ---------------------------------------------------------------------------
# kernel registry
# ---------------------------------------------------------------------------

def _flash_default(shapes: Shapes, dtypes: Dtypes) -> dict:
    from jimm_tpu.ops.flash_attention import DEFAULT_BLOCK_K, DEFAULT_BLOCK_Q
    return {"block_q": DEFAULT_BLOCK_Q, "block_k": DEFAULT_BLOCK_K}


def _flash_bench(shapes: Shapes, dtypes: Dtypes,
                 config: Mapping[str, int]) -> Callable[[], Any]:
    """Timed closure: flash fwd+bwd at the candidate blocks (training is the
    sweep's consumer; a fwd-only winner that loses the backward would be a
    false economy). Explicit block kwargs bypass the tuner — no recursion."""
    import jax
    import jax.numpy as jnp

    from jimm_tpu.ops.flash_attention import flash_attention
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    dt = jnp.dtype(dtypes[0]) if dtypes else jnp.float32
    q = jax.random.normal(kq, tuple(shapes[0]), dt)
    k = jax.random.normal(kk, tuple(shapes[1]), dt)
    v = jax.random.normal(kv, tuple(shapes[2]), dt)
    bq, bk = int(config["block_q"]), int(config["block_k"])

    def loss(q, k, v):
        o = flash_attention(q, k, v, block_q=bq, block_k=bk)
        return jnp.sum(o.astype(jnp.float32))

    step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    return lambda: step(q, k, v)


def _attn_qkv(shapes: Shapes, dtypes: Dtypes):
    import jax
    import jax.numpy as jnp
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    dt = jnp.dtype(dtypes[0]) if dtypes else jnp.float32
    return (jax.random.normal(kq, tuple(shapes[0]), dt),
            jax.random.normal(kk, tuple(shapes[1]), dt),
            jax.random.normal(kv, tuple(shapes[2]), dt))


def _masked_flash_bench(shapes: Shapes, dtypes: Dtypes,
                        config: Mapping[str, int]) -> Callable[[], Any]:
    """Timed closure: masked flash fwd+bwd with a NaFlex-shaped key-padding
    mask (~25% padded keys, every row keeps its first key). Explicit block
    kwargs bypass the tuner — no recursion."""
    import jax
    import jax.numpy as jnp

    from jimm_tpu.ops.flash_attention import flash_attention_masked
    q, k, v = _attn_qkv(shapes, dtypes)
    b, sk = q.shape[0], k.shape[1]
    mask = (jax.random.uniform(jax.random.PRNGKey(1), (b, sk)) > 0.25)
    mask = mask.at[:, 0].set(True)
    bq, bk = int(config["block_q"]), int(config["block_k"])

    def loss(q, k, v):
        o = flash_attention_masked(q, k, v, mask, block_q=bq, block_k=bk)
        return jnp.sum(o.astype(jnp.float32))

    step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    return lambda: step(q, k, v)


def _ring_bench(shapes: Shapes, dtypes: Dtypes,
                config: Mapping[str, int]) -> Callable[[], Any]:
    """Timed closure for the sequence-parallel ring's per-hop kernel.
    ``shapes`` are the LOCAL chunk shapes ``(B, S/p, N, D)`` — the blocks
    only govern the per-hop flash call (`seqpar.ring_hop_fwd`/`_bwd`,
    which is the masked single-chip product over one chunk), so benching
    masked flash at chunk shape measures exactly what the config
    controls; the ppermute schedule is block-independent. Explicit block
    kwargs bypass the tuner — no recursion."""
    import jax
    import jax.numpy as jnp

    from jimm_tpu.ops.flash_attention import flash_attention_masked
    q, k, v = _attn_qkv(shapes, dtypes)
    b, sk = q.shape[0], k.shape[1]
    # the ring's traveling mask rows look like NaFlex padding per chunk
    mask = (jax.random.uniform(jax.random.PRNGKey(1), (b, sk)) > 0.25)
    mask = mask.at[:, 0].set(True)
    bq, bk = int(config["block_q"]), int(config["block_k"])

    def loss(q, k, v):
        o = flash_attention_masked(q, k, v, mask, block_q=bq, block_k=bk)
        return jnp.sum(o.astype(jnp.float32))

    step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    return lambda: step(q, k, v)


def _bias_flash_bench(shapes: Shapes, dtypes: Dtypes,
                      config: Mapping[str, int]) -> Callable[[], Any]:
    """Timed closure: bias flash fwd+bwd including the dbias accumulation
    kernel (the variant's distinguishing cost). Explicit block kwargs
    bypass the tuner — no recursion."""
    import jax
    import jax.numpy as jnp

    from jimm_tpu.ops.flash_attention import flash_attention_bias
    q, k, v = _attn_qkv(shapes, dtypes)
    sq, sk, n = q.shape[1], k.shape[1], q.shape[2]
    bias = jax.random.normal(jax.random.PRNGKey(1), (n, sq, sk),
                             jnp.float32)
    bq, bk = int(config["block_q"]), int(config["block_k"])

    def loss(q, k, v, bias):
        o = flash_attention_bias(q, k, v, bias, block_q=bq, block_k=bk)
        return jnp.sum(o.astype(jnp.float32))

    step = jax.jit(jax.grad(loss, argnums=(0, 1, 2, 3)))
    return lambda: step(q, k, v, bias)


def _sigmoid_bench(shapes: Shapes, dtypes: Dtypes,
                   config: Mapping[str, int]) -> Callable[[], Any]:
    """Timed closure: sigmoid attention fwd+bwd (training is the consumer
    — the variant exists for SigLIP-style towers). Explicit block kwargs
    bypass the tuner — no recursion."""
    import jax
    import jax.numpy as jnp

    from jimm_tpu.ops.flash_attention import sigmoid_attention
    q, k, v = _attn_qkv(shapes, dtypes)
    bq, bk = int(config["block_q"]), int(config["block_k"])

    def loss(q, k, v):
        o = sigmoid_attention(q, k, v, block_q=bq, block_k=bk)
        return jnp.sum(o.astype(jnp.float32))

    step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    return lambda: step(q, k, v)


def _ln_default(shapes: Shapes, dtypes: Dtypes) -> dict:
    from jimm_tpu.ops.layer_norm import DEFAULT_BLOCK_ROWS
    return {"block_rows": DEFAULT_BLOCK_ROWS}


def _ln_bench(shapes: Shapes, dtypes: Dtypes,
              config: Mapping[str, int]) -> Callable[[], Any]:
    """Timed closure: fused LN fwd+bwd (the backward is the kernel's whole
    reason to exist — see docs/performance.md)."""
    import jax
    import jax.numpy as jnp

    from jimm_tpu.ops.layer_norm import layer_norm
    rows, feat = (int(d) for d in shapes[0][-2:])
    dt = jnp.dtype(dtypes[0]) if dtypes else jnp.float32
    x = jax.random.normal(jax.random.PRNGKey(0), (rows, feat), dt)
    scale = jnp.ones((feat,), jnp.float32)
    bias = jnp.zeros((feat,), jnp.float32)
    br = int(config["block_rows"])

    def loss(x, scale, bias):
        o = layer_norm(x, scale, bias, 1e-6, br)
        return jnp.sum(o.astype(jnp.float32))

    step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    return lambda: step(x, scale, bias)


def _retrieval_default(shapes: Shapes, dtypes: Dtypes) -> dict:
    from jimm_tpu.retrieval.topk import DEFAULT_BLOCK_N
    candidates = retrieval_space(shapes, dtypes)
    feasible = {c["block_n"] for c in candidates}
    return {"block_n": (DEFAULT_BLOCK_N if DEFAULT_BLOCK_N in feasible
                        else max(feasible))}


def _retrieval_bench(shapes: Shapes, dtypes: Dtypes,
                     config: Mapping[str, int]) -> Callable[[], Any]:
    """Timed closure: one streaming top-k pass at the candidate block over
    a synthetic normalized corpus shaped like the live one. Explicit
    block_n bypasses the tuner — no recursion."""
    import jax
    import numpy as np

    from jimm_tpu.retrieval.topk import corpus_layout, make_topk_fn
    batch, dim = int(shapes[0][-2]), int(shapes[0][-1])
    n_rows = int(shapes[-1][-2])
    dt = np.dtype(dtypes[-1]) if dtypes else np.dtype(np.float32)
    rng = np.random.default_rng(0)
    corpus = np.asarray(rng.standard_normal((n_rows, dim),
                                            dtype=np.float32), dt)
    queries = rng.standard_normal((batch, dim), dtype=np.float32)
    blocks, offsets, valid = corpus_layout(
        corpus, block_n=int(config["block_n"]))
    step = jax.jit(make_topk_fn(10))
    valid = np.int32(valid)
    return lambda: step(blocks, offsets, valid, queries)


def _ivf_default(shapes: Shapes, dtypes: Dtypes) -> dict:
    # the feasible set already accounts for the batch-multiplied gather;
    # prefer the largest feasible block up to the exact kernel's default
    # (fewer scan steps, less per-block top_k overhead)
    from jimm_tpu.retrieval.topk import DEFAULT_BLOCK_N
    feasible = {c["block_n"] for c in ivf_space(shapes, dtypes)}
    capped = {b for b in feasible if b <= DEFAULT_BLOCK_N}
    return {"block_n": max(capped) if capped else min(feasible)}


def _ivf_bench(shapes: Shapes, dtypes: Dtypes,
               config: Mapping[str, int]) -> Callable[[], Any]:
    """Timed closure: one fused IVF pass (coarse scan + probe + rescore)
    at the candidate block over a synthetic clustered corpus shaped like
    the live one. Explicit block_n bypasses the tuner — no recursion."""
    import jax
    import numpy as np

    from jimm_tpu.retrieval.ann.ivf import cluster_layout, make_ivf_fn
    from jimm_tpu.retrieval.ann.kmeans import (assign_clusters,
                                               clustered_rows)
    batch, dim = int(shapes[0][-2]), int(shapes[0][-1])
    n_rows = int(shapes[-1][-2])
    dt = np.dtype(dtypes[-1]) if dtypes else np.dtype(np.float32)
    clusters = max(1, min(64, n_rows // 64))
    rows, cents = clustered_rows(n_rows, dim, clusters, seed=0)
    corpus = np.asarray(rows, dt)
    assign = assign_clusters(rows, cents)
    blocks, rids, cl_start, cl_count = cluster_layout(
        corpus, assign, clusters, block_n=int(config["block_n"]))
    nprobe_max = max(1, min(8, clusters))
    max_bpc = max(1, int(cl_count.max(initial=0)))
    rng = np.random.default_rng(1)
    queries = rng.standard_normal((batch, dim), dtype=np.float32)
    step = jax.jit(make_ivf_fn(10, nprobe_max, max_bpc))
    live_c = np.int32(clusters)
    nprobe = np.int32(nprobe_max)
    return lambda: step(blocks, rids, np.asarray(cents, np.float32),
                        cl_start, cl_count, live_c, nprobe, queries)


def _tier_default(shapes: Shapes, dtypes: Dtypes) -> dict:
    # opposite preference to _ivf_default: block_n is also the hot
    # arena's allocation quantum, and a small corpus-per-cluster means a
    # large block mostly buys padding — pick the *smallest* feasible
    # block at or above the lane width so the budget packs more clusters
    feasible = {c["block_n"] for c in tier_space(shapes, dtypes)}
    return {"block_n": min(feasible)}


def _tier_bench(shapes: Shapes, dtypes: Dtypes,
                config: Mapping[str, int]) -> Callable[[], Any]:
    """Timed closure: one hot-arena tier pass (coarse scan + probe +
    rescore + probe-selection output) at the candidate block over a
    synthetic clustered corpus. Explicit block_n bypasses the tuner —
    no recursion."""
    import jax
    import numpy as np

    from jimm_tpu.retrieval.ann.ivf import cluster_layout
    from jimm_tpu.retrieval.ann.kmeans import (assign_clusters,
                                               clustered_rows)
    from jimm_tpu.retrieval.tier.engine import make_tier_fn
    batch, dim = int(shapes[0][-2]), int(shapes[0][-1])
    n_rows = int(shapes[-1][-2])
    dt = np.dtype(dtypes[-1]) if dtypes else np.dtype(np.float32)
    clusters = max(1, min(64, n_rows // 64))
    rows, cents = clustered_rows(n_rows, dim, clusters, seed=0)
    corpus = np.asarray(rows, dt)
    assign = assign_clusters(rows, cents)
    blocks, rids, cl_start, cl_count = cluster_layout(
        corpus, assign, clusters, block_n=int(config["block_n"]))
    nprobe_max = max(1, min(8, clusters))
    max_bpc = max(1, int(cl_count.max(initial=0)))
    rng = np.random.default_rng(1)
    queries = rng.standard_normal((batch, dim), dtype=np.float32)
    step = jax.jit(make_tier_fn(10, nprobe_max, max_bpc))
    live_c = np.int32(clusters)
    nprobe = np.int32(nprobe_max)
    return lambda: step(blocks, rids, np.asarray(cents, np.float32),
                        cl_start, cl_count, live_c, nprobe, queries)


def _int8_matmul_default(shapes: Shapes, dtypes: Dtypes) -> dict:
    from jimm_tpu.ops.int8_matmul import DEFAULT_BLOCK_M, DEFAULT_BLOCK_N
    return {"block_m": DEFAULT_BLOCK_M, "block_n": DEFAULT_BLOCK_N}


def _int8_matmul_bench(shapes: Shapes, dtypes: Dtypes,
                       config: Mapping[str, int]) -> Callable[[], Any]:
    """Timed closure: the fused dequantizing matmul (forward only — it is a
    serving kernel). Explicit block kwargs bypass the tuner — no
    recursion."""
    import jax
    import jax.numpy as jnp

    from jimm_tpu.ops.int8_matmul import int8_matmul
    m, k = (int(d) for d in shapes[0][-2:])
    n = int(shapes[1][-1])
    kx, kw = jax.random.split(jax.random.PRNGKey(0))
    x_q = jax.random.randint(kx, (m, k), -127, 128, jnp.int8)
    w_q = jax.random.randint(kw, (k, n), -127, 128, jnp.int8)
    x_s = jnp.full((m,), 0.01, jnp.float32)
    w_s = jnp.full((n,), 0.01, jnp.float32)
    bias = jnp.zeros((n,), jnp.float32)
    bm, bn = int(config["block_m"]), int(config["block_n"])

    step = jax.jit(lambda xq, xs, wq, ws, b: int8_matmul(
        xq, xs, wq, ws, b, activation="gelu", block_m=bm, block_n=bn))
    return lambda: step(x_q, x_s, w_q, w_s, bias)


def _int8_flash_default(shapes: Shapes, dtypes: Dtypes) -> dict:
    from jimm_tpu.ops.flash_attention_int8 import (DEFAULT_BLOCK_K,
                                                   DEFAULT_BLOCK_Q)
    return {"block_q": DEFAULT_BLOCK_Q, "block_k": DEFAULT_BLOCK_K}


def _int8_flash_bench(shapes: Shapes, dtypes: Dtypes,
                      config: Mapping[str, int]) -> Callable[[], Any]:
    """Timed closure: int8 flash fwd+bwd at the candidate blocks (since
    the int8_qk training policy landed the backward, training is a
    consumer too — a fwd-only winner that loses the backward would be a
    false economy). Explicit block kwargs bypass the tuner — no
    recursion."""
    import jax
    import jax.numpy as jnp

    from jimm_tpu.ops.flash_attention_int8 import flash_attention_int8
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    dt = jnp.dtype(dtypes[0]) if dtypes else jnp.float32
    q = jax.random.normal(kq, tuple(shapes[0]), dt)
    k = jax.random.normal(kk, tuple(shapes[1]), dt)
    v = jax.random.normal(kv, tuple(shapes[2]), dt)
    bq, bk = int(config["block_q"]), int(config["block_k"])

    def loss(q, k, v):
        o = flash_attention_int8(q, k, v, block_q=bq, block_k=bk)
        return jnp.sum(o.astype(jnp.float32))

    step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    return lambda: step(q, k, v)


def _fp8_matmul_default(shapes: Shapes, dtypes: Dtypes) -> dict:
    from jimm_tpu.ops.fp8_matmul import DEFAULT_BLOCK_M, DEFAULT_BLOCK_N
    return {"block_m": DEFAULT_BLOCK_M, "block_n": DEFAULT_BLOCK_N}


def _fp8_matmul_bench(shapes: Shapes, dtypes: Dtypes,
                      config: Mapping[str, int]) -> Callable[[], Any]:
    """Timed closure: fp8 matmul fwd+bwd (training is the kernel's whole
    consumer — the backward's two e5m2 contractions dominate). Explicit
    block kwargs bypass the tuner — no recursion."""
    import jax
    import jax.numpy as jnp

    from jimm_tpu.ops.fp8_matmul import fp8_matmul
    m, k = (int(d) for d in shapes[0][-2:])
    n = int(shapes[1][-1])
    kx, kw = jax.random.split(jax.random.PRNGKey(0))
    x = jax.random.normal(kx, (m, k), jnp.float32)
    w = jax.random.normal(kw, (k, n), jnp.float32)
    bias = jnp.zeros((n,), jnp.float32)
    bm, bn = int(config["block_m"]), int(config["block_n"])

    def loss(x, w, bias):
        y = fp8_matmul(x, w, bias, block_m=bm, block_n=bn)
        return jnp.sum(y)

    step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    return lambda: step(x, w, bias)


@dataclasses.dataclass(frozen=True)
class KernelSpec:
    """One tunable kernel: identity, search space, fallback, and bench."""

    version: int  # bump with the kernel implementation — stale configs miss
    space: Callable[[Shapes, Dtypes], list[dict]]
    default: Callable[[Shapes, Dtypes], dict]
    bench: Callable[[Shapes, Dtypes, Mapping[str, int]], Callable[[], Any]]


KERNELS: dict[str, KernelSpec] = {
    "flash_attention": KernelSpec(version=1, space=flash_space,
                                  default=_flash_default,
                                  bench=_flash_bench),
    "flash_attention_masked": KernelSpec(version=1,
                                         space=masked_flash_space,
                                         default=_flash_default,
                                         bench=_masked_flash_bench),
    "flash_attention_bias": KernelSpec(version=1, space=bias_flash_space,
                                       default=_flash_default,
                                       bench=_bias_flash_bench),
    "sigmoid_attention": KernelSpec(version=1, space=sigmoid_space,
                                    default=_flash_default,
                                    bench=_sigmoid_bench),
    "layer_norm": KernelSpec(version=1, space=ln_space,
                             default=_ln_default, bench=_ln_bench),
    "retrieval_topk": KernelSpec(version=1, space=retrieval_space,
                                 default=_retrieval_default,
                                 bench=_retrieval_bench),
    "retrieval_ivf": KernelSpec(version=1, space=ivf_space,
                                default=_ivf_default,
                                bench=_ivf_bench),
    "retrieval_tier": KernelSpec(version=1, space=tier_space,
                                 default=_tier_default,
                                 bench=_tier_bench),
    "int8_matmul": KernelSpec(version=1, space=int8_matmul_space,
                              default=_int8_matmul_default,
                              bench=_int8_matmul_bench),
    # version 2: the backward landed (lse output changed the fwd cell's
    # working set; blocks must now fit the dq/dkv cells too)
    "flash_attention_int8": KernelSpec(version=2, space=int8_flash_space,
                                       default=_int8_flash_default,
                                       bench=_int8_flash_bench),
    "fp8_matmul": KernelSpec(version=1, space=fp8_matmul_space,
                             default=_fp8_matmul_default,
                             bench=_fp8_matmul_bench),
    # keyed on the per-device LOCAL chunk shapes (B, S/p, N, D) — see
    # parallel/seqpar.py::_resolve_ring_blocks
    "ring_attention": KernelSpec(version=1, space=ring_space,
                                 default=_flash_default,
                                 bench=_ring_bench),
}


# ---------------------------------------------------------------------------
# process-wide cache
# ---------------------------------------------------------------------------

_cache: TuneCache | None = None


def get_cache() -> TuneCache:
    global _cache
    if _cache is None:
        _cache = TuneCache()
    return _cache


def configure(root: str | os.PathLike | None) -> TuneCache:
    """Point the process-wide tune cache at ``root`` (``serve --tune-cache``
    and ``bench.py --tune-cache`` call this before any kernel traces)."""
    global _cache
    _cache = TuneCache(root)
    return _cache


# ---------------------------------------------------------------------------
# lookup (hot path) and tuning (offline)
# ---------------------------------------------------------------------------

def _key_for(kernel: str, shapes: Shapes, dtypes: Dtypes) -> TuneKey:
    spec = KERNELS[kernel]
    return tune_key(kernel, shapes=shapes, dtypes=dtypes,
                    kernel_version=spec.version)


def best_config(kernel: str, shapes: Shapes, dtypes: Dtypes, *,
                default: Mapping[str, int] | None = None,
                cache: TuneCache | None = None) -> dict:
    """The tuned config for ``kernel`` at these shapes, else a safe default.

    Lookup-only unless ``JIMM_TUNE=1``: called host-side at trace time, so
    a cold cache costs one file probe per newly traced shape and a warm one
    costs a dict probe.
    """
    spec = KERNELS[kernel]
    key = _key_for(kernel, shapes, dtypes)
    cache = cache or get_cache()
    registry = obs.get_registry("jimm_tune")
    record = cache.get(key)
    if record is not None:
        registry.counter("hit_total").inc()
        return dict(record["config"])
    registry.counter("miss_total").inc()
    if os.environ.get("JIMM_TUNE") == "1":
        return dict(tune_kernel(kernel, shapes, dtypes,
                                cache=cache)["config"])
    registry.counter("fallback_total").inc()
    return dict(default) if default is not None else spec.default(shapes,
                                                                  dtypes)


def tune_kernel(kernel: str, shapes: Shapes, dtypes: Dtypes, *,
                cache: TuneCache | None = None, reps: int | None = None,
                candidates: Sequence[Mapping[str, int]] | None = None
                ) -> dict:
    """Measure every feasible candidate, persist the winner, return
    ``{"config", "time_s", "candidates", "fingerprint", "trials"}``."""
    spec = KERNELS[kernel]
    key = _key_for(kernel, shapes, dtypes)
    cache = cache or get_cache()
    cands = list(candidates) if candidates is not None \
        else spec.space(shapes, dtypes)
    trials = []
    for config in cands:
        fn = spec.bench(shapes, dtypes, config)
        trials.append({"config": dict(config),
                       "time_s": measure(fn, reps=reps, kernel=kernel)})
    best = min(trials, key=lambda t: t["time_s"])
    fingerprint = cache.put(key, best["config"],
                            metrics={"time_s": best["time_s"],
                                     "trials": trials})
    return {"config": dict(best["config"]), "time_s": best["time_s"],
            "candidates": len(trials), "fingerprint": fingerprint,
            "trials": trials}
