"""Find the flash-vs-XLA attention crossover sequence length on this chip.

Times fwd+bwd at fixed B*N*S (constant work per config would need B to
shrink as S grows; we instead keep total tokens constant) and prints TF/s,
informing the `impl="auto"` dispatch rule in `jimm_tpu.ops.attention`.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, iters=10):
    def chained(args, n):
        def body(args, _):
            out = fn(*args)
            q = args[0] + 1e-6 * out[0].astype(args[0].dtype)
            return (q,) + tuple(args[1:]), None
        args, _ = jax.lax.scan(body, args, None, length=n)
        return args

    chained = jax.jit(chained, static_argnums=1)
    float(jnp.sum(chained(args, iters)[0].astype(jnp.float32)))
    t0 = time.perf_counter()
    float(jnp.sum(chained(args, iters)[0].astype(jnp.float32)))
    return (time.perf_counter() - t0) / iters


def main():
    import argparse
    from functools import partial

    from jimm_tpu.ops.flash_attention import flash_attention

    p = argparse.ArgumentParser()
    p.add_argument("--causal", action="store_true",
                   help="also time causal flash: with skipped kv blocks "
                        "eliding both compute AND their DMA, causal should "
                        "approach half the non-causal time at long seq")
    args = p.parse_args()

    from scripts._watchdog import hard_watchdog

    print("backend:", jax.default_backend(), jax.devices()[0].device_kind)
    rng = np.random.RandomState(0)
    N, D = 12, 64
    total_tokens = 128 * 256  # constant B*S
    for S in (64, 128, 256, 512, 1024, 2048, 4096, 8192):
        B = max(1, total_tokens // S)

        def _hang(S=S):
            # bound a tunnel hang to one sequence length, with evidence
            print(f"  S={S}: case watchdog after 300s (tunnel hang?)",
                  flush=True)

        disarm = hard_watchdog(300, 21, _hang)
        q = jnp.asarray(rng.randn(B, S, N, D), jnp.bfloat16)
        k = jnp.asarray(rng.randn(B, S, N, D), jnp.bfloat16)
        v = jnp.asarray(rng.randn(B, S, N, D), jnp.bfloat16)
        flops = 3.5 * 4 * B * N * S * S * D

        def loss_of(attn):
            def f(q, k, v):
                return jnp.sum(attn(q, k, v).astype(jnp.float32))
            return jax.jit(jax.grad(f, argnums=(0, 1, 2)))

        tf = timeit(loss_of(flash_attention), q, k, v)
        tx = timeit(loss_of(
            lambda q, k, v: jax.nn.dot_product_attention(q, k, v)), q, k, v)
        win = "flash" if tf < tx else "xla"
        causal_col = ""
        if args.causal:
            tc = timeit(loss_of(partial(flash_attention, is_causal=True)),
                        q, k, v)
            causal_col = (f"  causal {tc*1e3:8.2f} ms "
                          f"({tc/tf:4.2f}x of full)")
        print(f"  S={S:5d} B={B:4d}: flash {tf*1e3:8.2f} ms "
              f"({flops/tf/1e12:6.2f} TF/s)  xla {tx*1e3:8.2f} ms "
              f"({flops/tx/1e12:6.2f} TF/s)  -> {win}{causal_col}")
        disarm()


if __name__ == "__main__":
    main()
