"""SigLIP dual-tower model (v1 and v2, non-NaFlex variants).

Capability parity with `src/jimm/models/siglip.py:15-385`: MAP-pooled vision
tower (post-norm, gelu_tanh, eps 1e-6), bidirectional text tower with
last-token pooling and *biased* text projection, ``logit_scale`` and
``logit_bias``; HF checkpoint loading incl. the fused torch
``in_proj_weight`` q/k/v split for the MAP head (ref `siglip.py:352-363`).
Unlike the reference, ``intermediate_size`` is read from config, so
So400m-class checkpoints (non-4x MLP) load (SURVEY §2.4).

``Siglip2Model``-flavored checkpoints (ref `README.md:13-14` "any non-NaFlex
variant") load through the same mapping: they differ only in the vision
embeddings — a NaFlex Linear patch embedding (handled by ``T.patch``) and a
``num_patches``-sized position table (grid-resampled to the fixed-resolution
grid at load). Parity vs the HF ``Siglip2Model`` oracle is tested in
`tests/test_siglip2.py`.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax import nnx

from jimm_tpu.configs import act_to_hf, normalize_act, with_runtime, SigLIPConfig, TextConfig, VisionConfig
from jimm_tpu.nn.text import TextTower
from jimm_tpu.nn.vision import VisionTower
from jimm_tpu.parallel.sharding import (ShardingRules, TENSOR_PARALLEL,
                                        logical, shard_model)
from jimm_tpu.weights.loader import (M, T, apply_mapping,
                                    layer_orders)
from jimm_tpu.weights.resolve import resolve_checkpoint


class SigLIP(nnx.Module):
    def __init__(self, config: SigLIPConfig | None = None, *,
                 rngs: nnx.Rngs | None = None,
                 mesh: jax.sharding.Mesh | None = None,
                 rules: ShardingRules | str = TENSOR_PARALLEL,
                 dtype=None, param_dtype=jnp.float32):
        cfg = config or SigLIPConfig()
        self.config = cfg
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        self.vision = VisionTower(cfg.vision, rngs, dtype=dtype,
                                  param_dtype=param_dtype)
        self.text = TextTower(cfg.text, rngs, dtype=dtype,
                              param_dtype=param_dtype)
        # biased projection to the shared embedding dim (ref siglip.py:111-119)
        self.text_projection = nnx.Linear(
            cfg.text.width, cfg.projection_dim, use_bias=True, dtype=dtype,
            param_dtype=param_dtype,
            kernel_init=logical(nnx.initializers.xavier_uniform(),
                                "embed", "proj"),
            bias_init=logical(nnx.initializers.zeros_init(), "proj"),
            rngs=rngs)
        self.logit_scale = nnx.Param(jnp.asarray(cfg.logit_scale_init,
                                                 dtype=param_dtype))
        self.logit_bias = nnx.Param(jnp.asarray(cfg.logit_bias_init,
                                                dtype=param_dtype))
        if mesh is not None:
            shard_model(self, mesh, rules)

    def encode_image(self, images: jax.Array) -> jax.Array:
        """(B, H, W, C) -> unnormalized (B, width): the MAP-head output is the
        image feature — no separate visual projection (ref siglip.py:140-149)."""
        return self.vision(images)

    def encode_image_naflex(self, patches: jax.Array,
                            spatial_shapes: jax.Array,
                            mask: jax.Array) -> jax.Array:
        """NaFlex variable-resolution image encoding — BEYOND the reference,
        whose SigLIP2 support stops at "any non-NaFlex variant"
        (ref `README.md:13-14`). Takes HF-processor-style inputs: flattened
        ``(B, S, p*p*C)`` patches, per-sample ``(B, 2)`` (h, w) grids, and a
        ``(B, S)`` padding mask (see `jimm_tpu.data.naflex.patchify_naflex`
        to produce them from raw images). Parity vs the HF ``Siglip2Model``
        NaFlex oracle is tested in `tests/test_naflex.py`."""
        return self.vision.forward_naflex(patches, spatial_shapes, mask)

    def logits_naflex(self, patches: jax.Array, spatial_shapes: jax.Array,
                      mask: jax.Array, text: jax.Array) -> jax.Array:
        """``__call__`` semantics over NaFlex image inputs."""
        return self._logits(
            self.encode_image_naflex(patches, spatial_shapes, mask),
            self.encode_text(text))

    def _logits(self, img: jax.Array, txt: jax.Array) -> jax.Array:
        """Shared logit head: L2-normalize, scale, bias
        (ref `siglip.py:161-170`)."""
        img = img / jnp.linalg.norm(img, axis=-1, keepdims=True)
        txt = txt / jnp.linalg.norm(txt, axis=-1, keepdims=True)
        scale = jnp.exp(self.logit_scale[...])
        return scale * img @ txt.T + self.logit_bias[...]  # logits_per_image

    def encode_text(self, text: jax.Array) -> jax.Array:
        """(B, S) -> unnormalized (B, projection_dim); pools the LAST position
        (requires max-length padding) then biased projection
        (ref `siglip.py:151-152`)."""
        hidden = self.text(text)
        return self.text_projection(self.text.pool(hidden, text))

    def __call__(self, images: jax.Array, text: jax.Array) -> jax.Array:
        return self._logits(self.encode_image(images),
                            self.encode_text(text))

    # ------------------------------------------------------------------
    # Checkpoint loading
    # ------------------------------------------------------------------

    @staticmethod
    def config_from_hf(config: dict[str, Any] | None,
                       weights: dict[str, np.ndarray]) -> SigLIPConfig:
        w = weights
        # shape inference first (the reference is nearly config-free:
        # ref siglip.py:193-207); config fills gaps when present
        v_width = w["vision_model.post_layernorm.weight"].shape[0]
        t_width = w["text_model.final_layer_norm.weight"].shape[0]
        v_depth = 1 + max(int(k.split(".")[3]) for k in w
                          if k.startswith("vision_model.encoder.layers."))
        t_depth = 1 + max(int(k.split(".")[3]) for k in w
                          if k.startswith("text_model.encoder.layers."))
        vc = (config or {}).get("vision_config", {})
        tc = (config or {}).get("text_config", {})
        pe = w["vision_model.embeddings.patch_embedding.weight"]
        if pe.ndim == 4:  # SigLIP v1: Conv2d OIHW
            patch = pe.shape[-1]
        else:  # SigLIP2: NaFlex Linear (out, p*p*3) — ref `README.md:13-14`
            patch = vc.get("patch_size", int(round((pe.shape[-1] // 3) ** 0.5)))
        n_pos = w["vision_model.embeddings.position_embedding.weight"].shape[0]
        vocab, _ = w["text_model.embeddings.token_embedding.weight"].shape
        ctx = w["text_model.embeddings.position_embedding.weight"].shape[0]
        # SigLIP2 vision configs carry num_patches instead of image_size;
        # the fallback (square grid of the position table) covers them
        image = vc.get("image_size", int(round(n_pos ** 0.5)) * patch)
        vision = VisionConfig(
            image_size=image, patch_size=patch, width=v_width, depth=v_depth,
            num_heads=vc.get("num_attention_heads", max(1, v_width // 64)),
            mlp_dim=w["vision_model.encoder.layers.0.mlp.fc1.weight"].shape[0],
            act=normalize_act(vc.get("hidden_act"), "gelu_tanh"),
            ln_eps=vc.get("layer_norm_eps", 1e-6),
            pooling="map", pre_norm=False, patch_bias=True)
        text = TextConfig(
            vocab_size=vocab, context_length=ctx, width=t_width, depth=t_depth,
            num_heads=tc.get("num_attention_heads", max(1, t_width // 64)),
            mlp_dim=w["text_model.encoder.layers.0.mlp.fc1.weight"].shape[0],
            act=normalize_act(tc.get("hidden_act"), "gelu_tanh"),
            ln_eps=tc.get("layer_norm_eps", 1e-6),
            causal=False, pooling="last", proj_bias=True)
        proj = w["text_model.head.weight"].shape[0]
        return SigLIPConfig(vision=vision, text=text, projection_dim=proj)

    @staticmethod
    def hf_mapping(cfg: SigLIPConfig) -> list[M]:
        def tower(dst_prefix: str, src_prefix: str) -> list[M]:
            p = src_prefix + "encoder.layers.{i}."
            d = dst_prefix + "encoder.blocks."
            return [
                M(d + "ln1.scale", p + "layer_norm1.weight"),
                M(d + "ln1.bias", p + "layer_norm1.bias"),
                M(d + "attn.q.kernel", p + "self_attn.q_proj.weight", T.linear),
                M(d + "attn.q.bias", p + "self_attn.q_proj.bias"),
                M(d + "attn.k.kernel", p + "self_attn.k_proj.weight", T.linear),
                M(d + "attn.k.bias", p + "self_attn.k_proj.bias"),
                M(d + "attn.v.kernel", p + "self_attn.v_proj.weight", T.linear),
                M(d + "attn.v.bias", p + "self_attn.v_proj.bias"),
                M(d + "attn.out.kernel", p + "self_attn.out_proj.weight",
                  T.linear),
                M(d + "attn.out.bias", p + "self_attn.out_proj.bias"),
                M(d + "ln2.scale", p + "layer_norm2.weight"),
                M(d + "ln2.bias", p + "layer_norm2.bias"),
                M(d + "mlp.fc1.kernel", p + "mlp.fc1.weight", T.linear),
                M(d + "mlp.fc1.bias", p + "mlp.fc1.bias"),
                M(d + "mlp.fc2.kernel", p + "mlp.fc2.weight", T.linear),
                M(d + "mlp.fc2.bias", p + "mlp.fc2.bias"),
            ]

        h = "vision_model.head."
        return [
            M("vision.pos_embed",
              "vision_model.embeddings.position_embedding.weight",
              T.unsqueeze),
            M("vision.patch_embed.conv.kernel",
              "vision_model.embeddings.patch_embedding.weight", T.patch),
            M("vision.patch_embed.conv.bias",
              "vision_model.embeddings.patch_embedding.bias"),
            M("vision.ln_post.scale", "vision_model.post_layernorm.weight"),
            M("vision.ln_post.bias", "vision_model.post_layernorm.bias"),
            # MAP pooling head; torch fuses q/k/v into in_proj_* — split into
            # thirds (ref siglip.py:352-363)
            M("vision.head.probe", h + "probe"),
            M("vision.head.attn.q.kernel", h + "attention.in_proj_weight",
              T.chunk(3, 0, T.linear)),
            M("vision.head.attn.k.kernel", h + "attention.in_proj_weight",
              T.chunk(3, 1, T.linear)),
            M("vision.head.attn.v.kernel", h + "attention.in_proj_weight",
              T.chunk(3, 2, T.linear)),
            M("vision.head.attn.q.bias", h + "attention.in_proj_bias",
              T.chunk(3, 0)),
            M("vision.head.attn.k.bias", h + "attention.in_proj_bias",
              T.chunk(3, 1)),
            M("vision.head.attn.v.bias", h + "attention.in_proj_bias",
              T.chunk(3, 2)),
            M("vision.head.attn.out.kernel", h + "attention.out_proj.weight",
              T.linear),
            M("vision.head.attn.out.bias", h + "attention.out_proj.bias"),
            M("vision.head.ln.scale", h + "layernorm.weight"),
            M("vision.head.ln.bias", h + "layernorm.bias"),
            M("vision.head.mlp.fc1.kernel", h + "mlp.fc1.weight", T.linear),
            M("vision.head.mlp.fc1.bias", h + "mlp.fc1.bias"),
            M("vision.head.mlp.fc2.kernel", h + "mlp.fc2.weight", T.linear),
            M("vision.head.mlp.fc2.bias", h + "mlp.fc2.bias"),
            M("text.token_embed.embedding",
              "text_model.embeddings.token_embedding.weight"),
            M("text.pos_embed",
              "text_model.embeddings.position_embedding.weight"),
            M("text.ln_final.scale", "text_model.final_layer_norm.weight"),
            M("text.ln_final.bias", "text_model.final_layer_norm.bias"),
            M("text_projection.kernel", "text_model.head.weight", T.linear),
            M("text_projection.bias", "text_model.head.bias"),
            M("logit_scale", "logit_scale", T.scalar_1d),
            M("logit_bias", "logit_bias", T.scalar_1d),
            *tower("vision.", "vision_model."),
            *tower("text.", "text_model."),
        ]

    @classmethod
    def from_pretrained(cls, name_or_path: str, *,
                        mesh: jax.sharding.Mesh | None = None,
                        rules: ShardingRules | str = TENSOR_PARALLEL,
                        dtype=None, use_pytorch: bool = False,
                        runtime: dict | None = None,
                        image_size: int | None = None
                        ) -> "SigLIP":
        weights, config = resolve_checkpoint(name_or_path,
                                             use_pytorch=use_pytorch)
        cfg = cls.config_from_hf(config, weights)
        if runtime:
            # execution-strategy overrides a checkpoint cannot know
            # (remat/pipeline/attn_impl/... — configs.RUNTIME_FIELDS)
            cfg = with_runtime(cfg, **runtime)
        # higher-res fine-tune: bilinear pos-embed grid resample
        from jimm_tpu.weights.surgery import (apply_image_size,
                                              resize_checkpoint_pos_embed)
        pos_key = "vision_model.embeddings.position_embedding.weight"
        orig_pos_n = weights[pos_key].shape[0]
        weights, cfg = apply_image_size(
            weights, cfg, image_size,
            key=pos_key, n_prefix=0)  # MAP pooling: pure grid, no class token
        # SigLIP2 position tables are sized by num_patches (the NaFlex
        # maximum), which can differ from the fixed-resolution grid; resample
        # like the HF runtime's resize_positional_embeddings does (bilinear)
        grid = cfg.vision.image_size // cfg.vision.patch_size
        if weights[pos_key].shape[0] != grid * grid:
            weights = resize_checkpoint_pos_embed(
                weights, pos_key, patch_size=cfg.vision.patch_size,
                image_size=cfg.vision.image_size, n_prefix=0)
        param_dtype = dtype if dtype is not None else jnp.float32
        model = cls(cfg, mesh=mesh, rules=rules, dtype=dtype,
                    param_dtype=param_dtype)
        apply_mapping(model, weights, cls.hf_mapping(cfg),
                      num_layers=cfg.vision.depth,
                      num_layers_by_prefix={"text.": cfg.text.depth},
                      param_dtype=param_dtype, layer_order=layer_orders(cfg))
        # remember the source flavor: a SigLIP2 (NaFlex Linear patch embed)
        # origin changes what save_pretrained can round-trip
        pe = weights["vision_model.embeddings.patch_embedding.weight"]
        model._hf_source_flavor = "siglip2" if pe.ndim == 2 else "siglip"
        # the NaFlex path resamples the position table per sample FROM the
        # stored table; if load-time surgery already interpolated it away
        # from the checkpoint's native grid, a second resample would diverge
        # from the HF oracle — forward_naflex refuses in that case
        model.vision._pos_table_resampled = (
            weights[pos_key].shape[0] != orig_pos_n)
        return model

    # ------------------------------------------------------------------
    # Checkpoint saving (HF-interoperable; absent from the reference)
    # ------------------------------------------------------------------

    def hf_config(self) -> dict:
        cfg = self.config
        vision = {
            "hidden_size": cfg.vision.width,
            "num_hidden_layers": cfg.vision.depth,
            "num_attention_heads": cfg.vision.num_heads,
            "intermediate_size": cfg.vision.mlp_dim,
            "image_size": cfg.vision.image_size,
            "patch_size": cfg.vision.patch_size,
            "hidden_act": act_to_hf(cfg.vision.act),
            "layer_norm_eps": cfg.vision.ln_eps,
        }
        text = {
            "hidden_size": cfg.text.width,
            "num_hidden_layers": cfg.text.depth,
            "num_attention_heads": cfg.text.num_heads,
            "intermediate_size": cfg.text.mlp_dim,
            "vocab_size": cfg.text.vocab_size,
            "max_position_embeddings": cfg.text.context_length,
            "hidden_act": act_to_hf(cfg.text.act),
            "layer_norm_eps": cfg.text.ln_eps,
        }
        return {
            "architectures": ["SiglipModel"],
            "model_type": "siglip",
            
            "vision_config": vision, "text_config": text,
        }

    def save_pretrained(self, save_dir, *, flavor: str | None = None) -> None:
        """Export an HF-compatible checkpoint.

        ``flavor``: ``"siglip"`` (v1: Conv2d OIHW patch embed, ``SiglipModel``
        reloads it), ``"siglip2"`` (NaFlex Linear patch embed +
        ``num_patches`` position table, ``Siglip2Model`` reloads it), or
        ``None`` = match the checkpoint the model was loaded from (v1 for
        fresh models). The reference has no save path at all (SURVEY §5)."""
        if flavor is None:
            flavor = getattr(self, "_hf_source_flavor", None) or "siglip"
        if flavor not in ("siglip", "siglip2"):
            raise ValueError(f"unknown export flavor {flavor!r}")
        from jimm_tpu.weights.export import save_pretrained
        if flavor == "siglip":
            if getattr(self, "_hf_source_flavor", None) == "siglip2":
                import warnings
                warnings.warn(
                    "exporting a Siglip2-origin model in SiglipModel (v1) "
                    "format — the NaFlex Linear patch embed becomes Conv2d "
                    "OIHW. Reload with SiglipModel / SigLIP.from_pretrained "
                    "(or pass flavor='siglip2' for a Siglip2Model-loadable "
                    "export).", stacklevel=2)
            save_pretrained(self, save_dir)
            return
        self._save_pretrained_siglip2(save_dir)

    def _save_pretrained_siglip2(self, save_dir) -> None:
        """Siglip2-native export: the shared export pipeline with two hooks —
        the patch embedding re-flattened to the NaFlex Linear ``(D, p*p*C)``
        layout ((row, col, chan) input order — inverse of
        `weights/loader._patch_linear_to_hwio`) and a ``siglip2`` config
        carrying ``num_patches``."""
        from jimm_tpu.weights.export import save_pretrained

        def state_hook(state: dict) -> dict:
            pe_key = "vision_model.embeddings.patch_embedding.weight"
            pe = state[pe_key]  # v1 inverse transform wrote Conv2d OIHW
            d_out, c, p, _ = pe.shape
            state[pe_key] = np.ascontiguousarray(
                pe.transpose(0, 2, 3, 1).reshape(d_out, p * p * c))
            return state

        def config_hook(cfg: dict) -> dict:
            cfg["architectures"] = ["Siglip2Model"]
            cfg["model_type"] = "siglip2"
            cfg["vision_config"]["num_patches"] = \
                self.config.vision.num_patches
            return cfg

        save_pretrained(self, save_dir, state_hook=state_hook,
                        config_hook=config_hook)
