"""JL012 fixture: silent f32 upcasts in quantized ops code."""
import jax
import jax.numpy as jnp


def int8_forward(acc, x_q, w_q, scales):
    y = acc.astype(jnp.float32)                        # JL012: bare upcast
    xf = jax.lax.convert_element_type(x_q, jnp.float32)  # JL012: CET upcast
    wf = w_q.astype("float32")                         # JL012: string dtype
    return y + xf @ wf * scales


def _dequant(acc, x_scale, w_scale):
    # ok: the sanctioned rescale site — enclosing name says dequant
    return acc.astype(jnp.float32) * x_scale[:, None] * w_scale[None, :]


def quantize_rows(x):
    # ok: quantization itself computes scales in f32 by definition
    scale = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1) / 127.0
    return jnp.round(x / scale[:, None]).astype(jnp.int8), scale


def epilogue_cast(acc):
    # ok: bf16 epilogues are mixed-precision policy, not a silent f32 demotion
    half = acc.astype(jnp.bfloat16)
    # ok: a justified deliberate upcast
    debug = acc.astype(jnp.float32)  # jaxlint: disable=JL012 parity probe
    return half, debug
