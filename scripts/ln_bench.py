"""Fused Pallas LayerNorm vs XLA LayerNorm, fwd+bwd, train-step shapes."""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from jimm_tpu.ops.layer_norm import layer_norm


def timeit(fn, *args, iters=50):
    def chained(args, n):
        def body(args, _):
            out = fn(*args)
            x = args[0] + 1e-6 * out[0].astype(args[0].dtype)
            return (x,) + tuple(args[1:]), None
        args, _ = jax.lax.scan(body, args, None, length=n)
        return args

    chained = jax.jit(chained, static_argnums=1)
    float(jnp.sum(chained(args, iters)[0].astype(jnp.float32)))
    t0 = time.perf_counter()
    float(jnp.sum(chained(args, iters)[0].astype(jnp.float32)))
    return (time.perf_counter() - t0) / iters


def main():
    print("backend:", jax.default_backend())
    rng = np.random.RandomState(0)
    R, F = 128 * 256, 768  # vision tower LN shape at bench batch
    x = jnp.asarray(rng.randn(R, F), jnp.bfloat16)
    s = jnp.asarray(rng.randn(F), jnp.bfloat16)
    b = jnp.asarray(rng.randn(F), jnp.bfloat16)
    eps = 1e-6

    def xla_ln(x, s, b):
        xf = x.astype(jnp.float32)
        mu = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.mean((xf - mu) ** 2, axis=-1, keepdims=True)
        return ((xf - mu) * jax.lax.rsqrt(var + eps) * s + b).astype(x.dtype)

    nbytes = (R * F * 2) * 4  # read x + write y, fwd+bwd ballpark
    for name, f in (("xla", xla_ln),
                    ("fused", lambda x, s, b: layer_norm(x, s, b, eps))):
        # one compile per benchmarked variant, by design
        g = jax.jit(jax.grad(  # jaxlint: disable=JL008 one compile/variant
            lambda x, s, b: jnp.sum(f(x, s, b).astype(jnp.float32) ** 2),
            argnums=(0, 1, 2)))
        dt = timeit(g, x, s, b)
        print(f"  ln fwd+bwd {name:6s} {dt*1e3:7.3f} ms  "
              f"~{nbytes/dt/1e9:5.0f} GB/s eff")


if __name__ == "__main__":
    main()
