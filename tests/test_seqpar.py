"""Tests for the sequence-parallel mesh axis: ring + Ulysses attention
(`jimm_tpu.parallel.seqpar`), the topology/tune/obs wiring around it, and
the temporal presets that motivate it.

Parity discipline mirrors the flash-attention suite: f32 allclose against
the reference oracles, bf16 by cosine (>= 0.999). The einsum hops run
everywhere; the per-hop Pallas flash hops run in interpret mode and are
marked slow.
"""

import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jimm_tpu.obs.baseline import row_key
from jimm_tpu.ops.attention import (dot_product_attention,
                                    reference_attention,
                                    reference_sigmoid_attention)
from jimm_tpu.parallel.mesh import make_mesh
from jimm_tpu.parallel.seqpar import (plan_seq_parallel, ring_attention_sp,
                                      seq_parallel_attention,
                                      seqpar_comm_bytes)
from jimm_tpu.parallel.sharding import PRESET_RULES, use_sharding
from jimm_tpu.serve.topology import TopologyPlan, plan_topology


def _devices(n):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} devices, have {len(devs)}")
    return devs[:n]


def _seq_mesh(p):
    return make_mesh({"seq": p}, devices=_devices(p))


def _qkv(b, s, n, d, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (b, s, n, d), dtype) for k in ks)


def _cosine(a, b):
    a = np.asarray(a, np.float64).ravel()
    b = np.asarray(b, np.float64).ravel()
    return float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-30))


def _ref(q, k, v, mask=None, kind="softmax", is_causal=False):
    if kind == "sigmoid":
        return reference_sigmoid_attention(q, k, v, mask=mask)
    m4 = None if mask is None else (mask != 0)[:, None, None, :]
    return reference_attention(q, k, v, mask=m4, is_causal=is_causal)


# ---------------------------------------------------------------------------
# Planner
# ---------------------------------------------------------------------------

class TestPlanner:
    def test_ulysses_iff_divisible_and_cheaper(self):
        # heads % p != 0 -> ring, always
        assert plan_seq_parallel(6, 4) == "ring"
        # divisible but p == 2: ring (comm tie, ring overlaps hops)
        assert plan_seq_parallel(8, 2) == "ring"
        # divisible and p > 2: head scatter moves fewer bytes
        assert plan_seq_parallel(8, 4) == "ulysses"
        assert plan_seq_parallel(16, 8) == "ulysses"

    def test_forced_plans_validate(self):
        assert plan_seq_parallel(6, 4, plan="ring") == "ring"
        with pytest.raises(ValueError, match="divisible"):
            plan_seq_parallel(6, 4, plan="ulysses")
        with pytest.raises(ValueError, match="unknown"):
            plan_seq_parallel(8, 4, plan="zigzag")

    def test_comm_bytes_formulas(self):
        b, s, n, d, p = 2, 256, 8, 64, 4
        local = (s // p) * n * d * 2 * b
        assert seqpar_comm_bytes(b, s, n, d, p) == 2 * (p - 1) * local
        assert seqpar_comm_bytes(b, s, n, d, p, masked=True) == \
            2 * (p - 1) * local + (p - 1) * b * (s // p) * 4
        assert seqpar_comm_bytes(b, s, n, d, p, plan="ulysses") == \
            4 * local * (p - 1) // p
        # the auto rule's premise: ulysses strictly cheaper for p > 2
        assert seqpar_comm_bytes(b, s, n, d, 4, plan="ulysses") < \
            seqpar_comm_bytes(b, s, n, d, 4)
        with pytest.raises(ValueError):
            seqpar_comm_bytes(b, s, n, d, p, plan="nope")


# ---------------------------------------------------------------------------
# Ring parity — einsum hops, f32
# ---------------------------------------------------------------------------

class TestRingParityF32:
    TOL = 2e-5

    @pytest.fixture()
    def mesh(self):
        return _seq_mesh(4)

    @pytest.fixture()
    def qkv(self):
        return _qkv(2, 64, 6, 16)

    @pytest.fixture()
    def mask(self):
        m = jax.random.bernoulli(jax.random.PRNGKey(9), 0.8, (2, 64))
        return m.at[:, 0].set(True)

    def test_softmax_forward(self, mesh, qkv):
        q, k, v = qkv
        o = ring_attention_sp(q, k, v, mesh=mesh, impl="einsum")
        np.testing.assert_allclose(o, _ref(q, k, v), atol=self.TOL)

    def test_masked_forward(self, mesh, qkv, mask):
        q, k, v = qkv
        o = ring_attention_sp(q, k, v, mask=mask, mesh=mesh, impl="einsum")
        np.testing.assert_allclose(o, _ref(q, k, v, mask=mask),
                                   atol=self.TOL)

    def test_masked_accepts_4d_key_padding(self, mesh, qkv, mask):
        q, k, v = qkv
        o = ring_attention_sp(q, k, v, mask=mask[:, None, None, :],
                              mesh=mesh, impl="einsum")
        np.testing.assert_allclose(o, _ref(q, k, v, mask=mask),
                                   atol=self.TOL)

    def test_sigmoid_forward(self, mesh, qkv, mask):
        q, k, v = qkv
        o = ring_attention_sp(q, k, v, kind="sigmoid", mask=mask, mesh=mesh,
                              impl="einsum")
        np.testing.assert_allclose(o, _ref(q, k, v, mask=mask,
                                           kind="sigmoid"), atol=self.TOL)

    def test_causal_forward(self, mesh, qkv):
        q, k, v = qkv
        o = ring_attention_sp(q, k, v, is_causal=True, mesh=mesh,
                              impl="einsum")
        np.testing.assert_allclose(o, _ref(q, k, v, is_causal=True),
                                   atol=self.TOL)

    @pytest.mark.parametrize("kw", [
        {}, {"masked": True}, {"kind": "sigmoid", "masked": True},
    ], ids=["softmax", "masked", "sigmoid"])
    def test_grads_match_reference(self, mesh, qkv, mask, kw):
        q, k, v = qkv
        m = mask if kw.get("masked") else None
        kind = kw.get("kind", "softmax")

        def ring_loss(q, k, v):
            o = ring_attention_sp(q, k, v, mask=m, kind=kind, mesh=mesh,
                                  impl="einsum")
            return jnp.sum(jnp.sin(o))

        def ref_loss(q, k, v):
            return jnp.sum(jnp.sin(_ref(q, k, v, mask=m, kind=kind)))

        got = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", got, want):
            np.testing.assert_allclose(a, b, atol=1e-4,
                                       err_msg=f"d{name} ({kind})")

    def test_rejects_indivisible_sequence(self, mesh):
        q, k, v = _qkv(1, 66, 4, 8)
        with pytest.raises(ValueError, match="not divisible"):
            ring_attention_sp(q, k, v, mesh=mesh)

    def test_rejects_dense_mask(self, mesh, qkv):
        q, k, v = qkv
        dense = jnp.ones((2, 1, 64, 64), bool)
        with pytest.raises(ValueError, match="KEY-PADDING"):
            ring_attention_sp(q, k, v, mask=dense, mesh=mesh)


# ---------------------------------------------------------------------------
# Ring parity — bf16 by cosine
# ---------------------------------------------------------------------------

class TestRingParityBf16:
    COS = 0.999

    @pytest.mark.parametrize("kw", [
        {}, {"masked": True}, {"kind": "sigmoid", "masked": True},
    ], ids=["softmax", "masked", "sigmoid"])
    def test_forward_and_grads_cosine(self, kw):
        mesh = _seq_mesh(4)
        q, k, v = _qkv(2, 64, 4, 16, dtype=jnp.bfloat16)
        mask = (jax.random.bernoulli(jax.random.PRNGKey(9), 0.8, (2, 64))
                .at[:, 0].set(True)) if kw.get("masked") else None
        kind = kw.get("kind", "softmax")
        o = ring_attention_sp(q, k, v, mask=mask, kind=kind, mesh=mesh,
                              impl="einsum")
        want = _ref(q.astype(jnp.float32), k.astype(jnp.float32),
                    v.astype(jnp.float32), mask=mask, kind=kind)
        assert o.dtype == jnp.bfloat16
        assert _cosine(o.astype(jnp.float32), want) >= self.COS

        def ring_loss(q, k, v):
            return jnp.sum(jnp.sin(ring_attention_sp(
                q, k, v, mask=mask, kind=kind, mesh=mesh,
                impl="einsum").astype(jnp.float32)))

        def ref_loss(q, k, v):
            return jnp.sum(jnp.sin(_ref(q, k, v, mask=mask, kind=kind)))

        got = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
        want_g = jax.grad(ref_loss, argnums=(0, 1, 2))(
            q.astype(jnp.float32), k.astype(jnp.float32),
            v.astype(jnp.float32))
        for name, a, b in zip("qkv", got, want_g):
            assert _cosine(a, b) >= self.COS, f"d{name} ({kind})"


# ---------------------------------------------------------------------------
# Mask placement across ring shards; NaFlex-style odd lengths
# ---------------------------------------------------------------------------

class TestMaskPlacement:
    """The traveling mask rows must be exact no matter where the padding
    falls relative to the ring's shard boundaries (S=64, p=4 -> shard
    boundaries at 16/32/48)."""

    def _check(self, keep_slices, s=64, p=4):
        mesh = _seq_mesh(p)
        q, k, v = _qkv(2, s, 4, 16, seed=3)
        keep = np.ones((2, s), bool)
        for sl in keep_slices:
            keep[:, sl] = False
        mask = jnp.asarray(keep)
        o = ring_attention_sp(q, k, v, mask=mask, mesh=mesh, impl="einsum")
        np.testing.assert_allclose(o, _ref(q, k, v, mask=mask), atol=2e-5)

    def test_padding_inside_one_shard(self):
        # dropped keys 20..27 sit strictly inside shard 1 (16..31)
        self._check([slice(20, 28)])

    def test_padding_straddles_shard_boundary(self):
        # dropped keys 44..51 cross the shard 2 -> 3 boundary at 48
        self._check([slice(44, 52)])

    def test_whole_shard_masked_out(self):
        # shard 2 (32..47) contributes nothing; its hop must be a no-op
        self._check([slice(32, 48)])

    def test_trailing_naflex_padding(self):
        self._check([slice(50, 64)])

    @pytest.mark.parametrize("s_real", [257, 577])
    def test_odd_lengths_pad_to_ring(self, s_real):
        """NaFlex workflow for ring-indivisible sequences: pad to the next
        multiple of the axis, mask the tail, compare the real rows against
        the unsharded masked oracle at the padded length."""
        p = 4
        s_pad = -(-s_real // p) * p
        mesh = _seq_mesh(p)
        q, k, v = _qkv(1, s_pad, 2, 16, seed=s_real)
        keep = np.zeros((1, s_pad), bool)
        keep[:, :s_real] = True
        mask = jnp.asarray(keep)
        o = ring_attention_sp(q, k, v, mask=mask, mesh=mesh, impl="einsum")
        want = _ref(q, k, v, mask=mask)
        np.testing.assert_allclose(np.asarray(o)[:, :s_real],
                                   np.asarray(want)[:, :s_real], atol=2e-5)


# ---------------------------------------------------------------------------
# Ulysses head scatter
# ---------------------------------------------------------------------------

class TestUlysses:
    def test_masked_parity_exact(self):
        mesh = _seq_mesh(4)
        q, k, v = _qkv(2, 64, 8, 16, seed=5)
        mask = (jax.random.bernoulli(jax.random.PRNGKey(9), 0.8, (2, 64))
                .at[:, 0].set(True))
        o = seq_parallel_attention(q, k, v, mask=mask, mesh=mesh,
                                   plan="ulysses", impl="einsum")
        np.testing.assert_allclose(o, _ref(q, k, v, mask=mask), atol=2e-5)

    def test_auto_plan_picks_ulysses_when_divisible(self):
        mesh = _seq_mesh(4)
        q, k, v = _qkv(2, 64, 8, 16, seed=5)
        mask = jnp.ones((2, 64), bool)
        got = seq_parallel_attention(q, k, v, mask=mask, kind="sigmoid",
                                     mesh=mesh, plan="auto", impl="einsum")
        np.testing.assert_allclose(
            got, _ref(q, k, v, mask=mask, kind="sigmoid"), atol=2e-5)

    def test_auto_plan_falls_back_to_ring(self):
        # 6 heads % 4 != 0: the planner must choose ring, and still be exact
        mesh = _seq_mesh(4)
        q, k, v = _qkv(2, 64, 6, 16, seed=7)
        o = seq_parallel_attention(q, k, v, mesh=mesh, plan="auto",
                                   impl="einsum")
        np.testing.assert_allclose(o, _ref(q, k, v), atol=2e-5)


# ---------------------------------------------------------------------------
# dot_product_attention routing
# ---------------------------------------------------------------------------

class TestAttentionRouting:
    def _inputs(self, s=64, n=4):
        ks = jax.random.split(jax.random.PRNGKey(2), 3)
        return tuple(jax.random.normal(k, (2, s, n, 16), jnp.float32)
                     for k in ks)

    def test_auto_routes_under_ambient_seq_mesh(self):
        q, k, v = self._inputs()
        mesh = _seq_mesh(4)
        want = dot_product_attention(q, k, v, impl="xla")
        with use_sharding(mesh, PRESET_RULES["sp"]):
            got = dot_product_attention(q, k, v)
        np.testing.assert_allclose(got, want, atol=2e-5)

    def test_explicit_ring_and_ulysses_impls(self):
        q, k, v = self._inputs()
        mesh = _seq_mesh(4)
        want = dot_product_attention(q, k, v, impl="xla")
        with use_sharding(mesh, PRESET_RULES["sp"]):
            ring = dot_product_attention(q, k, v, impl="ring")
            uly = dot_product_attention(q, k, v, impl="ulysses")
        np.testing.assert_allclose(ring, want, atol=2e-5)
        np.testing.assert_allclose(uly, want, atol=2e-5)

    def test_indivisible_sequence_falls_through(self):
        # the MAP pool's 1-row probe (and any S % p != 0) must not try to
        # ring-shard — it silently stays on the single-chip path
        q, _, _ = self._inputs()
        kv = jax.random.normal(jax.random.PRNGKey(3), (2, 63, 4, 16))
        probe = jax.random.normal(jax.random.PRNGKey(4), (2, 1, 4, 16))
        mesh = _seq_mesh(4)
        want = dot_product_attention(probe, kv, kv, impl="xla")
        with use_sharding(mesh, PRESET_RULES["sp"]):
            got = dot_product_attention(probe, kv, kv)
        np.testing.assert_allclose(got, want, atol=2e-5)

    def test_no_mesh_means_single_chip(self):
        q, k, v = self._inputs()
        got = dot_product_attention(q, k, v)
        np.testing.assert_allclose(got, dot_product_attention(q, k, v,
                                                              impl="xla"),
                                   atol=2e-5)

    def test_explicit_impl_without_seq_axis_raises(self):
        q, k, v = self._inputs()
        with pytest.raises(ValueError):
            dot_product_attention(q, k, v, impl="ring")


# ---------------------------------------------------------------------------
# Per-hop flash hops (interpret mode — slow)
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestRingFlashHops:
    @pytest.mark.parametrize("kw", [
        {}, {"masked": True}, {"kind": "sigmoid", "masked": True},
    ], ids=["softmax", "masked", "sigmoid"])
    def test_flash_forward_and_grads(self, kw):
        mesh = _seq_mesh(2)
        q, k, v = _qkv(1, 64, 2, 64)
        mask = (jax.random.bernoulli(jax.random.PRNGKey(9), 0.8, (1, 64))
                .at[:, 0].set(True)) if kw.get("masked") else None
        kind = kw.get("kind", "softmax")
        o = ring_attention_sp(q, k, v, mask=mask, kind=kind, mesh=mesh,
                              impl="flash")
        np.testing.assert_allclose(o, _ref(q, k, v, mask=mask, kind=kind),
                                   atol=2e-4)

        def ring_loss(q, k, v):
            return jnp.sum(jnp.sin(ring_attention_sp(
                q, k, v, mask=mask, kind=kind, mesh=mesh, impl="flash")))

        def ref_loss(q, k, v):
            return jnp.sum(jnp.sin(_ref(q, k, v, mask=mask, kind=kind)))

        got = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", got, want):
            np.testing.assert_allclose(a, b, atol=5e-4,
                                       err_msg=f"flash d{name} ({kind})")

    def test_flash_causal_rejected(self):
        mesh = _seq_mesh(2)
        q, k, v = _qkv(1, 64, 2, 64)
        with pytest.raises(ValueError, match="non-causal"):
            ring_attention_sp(q, k, v, is_causal=True, mesh=mesh,
                              impl="flash")


# ---------------------------------------------------------------------------
# Topology: the third mesh axis
# ---------------------------------------------------------------------------

class TestTopologySeqAxis:
    def test_plan_carries_seq_parallel(self):
        devs = _devices(8)
        plan = plan_topology(2, 1, 4, devices=devs)
        assert plan.seq_parallel == 4
        assert plan.devices_used == 8
        assert len(plan.device_groups) == 2
        assert all(len(g) == 4 for g in plan.device_groups)
        assert plan.describe()["seq_parallel"] == 4
        for mesh in plan.meshes():
            assert dict(mesh.shape)["seq"] == 4

    def test_seq1_collapses_to_legacy_plan(self):
        """Degenerate seq=1 must be byte-identical to the two-axis world:
        same groups, same describe, same mesh axis names — which is what
        keeps AOT fingerprints shared with pre-seq artifacts."""
        devs = _devices(8)
        legacy = plan_topology(2, 2, devices=devs)
        degenerate = plan_topology(2, 2, 1, devices=devs)
        assert degenerate == legacy
        assert degenerate.describe() == legacy.describe()
        for a, b in zip(degenerate.meshes(), legacy.meshes()):
            assert a.shape == b.shape
            assert a.axis_names == b.axis_names
            assert "seq" not in a.axis_names

    def test_default_seq_parallel_is_one(self):
        plan = plan_topology(devices=_devices(1))
        assert plan.seq_parallel == 1
        assert plan.is_trivial
        assert not plan_topology(1, 1, 2, devices=_devices(2)).is_trivial

    def test_infeasible_error_enumerates_splits(self):
        devs = _devices(8)
        with pytest.raises(ValueError) as e:
            plan_topology(3, 3, 1, devices=devs)
        msg = str(e.value)
        assert "feasible splits" in msg
        # every (data, model, seq) factorization of 8 shows up
        assert "data=2 model=2 seq=2" in msg
        assert "data=1 model=1 seq=8" in msg
        assert "data=8 model=1 seq=1" in msg
        assert str(3 * 3 * 1) in msg
        assert "xla_force_host_platform_device_count" in msg

    def test_mesh_group_is_model_times_seq(self):
        devs = _devices(8)
        plan = plan_topology(2, 2, 2, devices=devs)
        assert all(len(g) == 4 for g in plan.device_groups)
        for mesh in plan.meshes():
            shape = dict(mesh.shape)
            assert shape.get("model") == 2 and shape.get("seq") == 2


# ---------------------------------------------------------------------------
# Tune registration
# ---------------------------------------------------------------------------

class TestRingTune:
    def test_ring_kernel_registered(self):
        from jimm_tpu.tune.api import KERNELS
        from jimm_tpu.tune.space import ring_space
        spec = KERNELS["ring_attention"]
        assert spec.space is ring_space

    def test_ring_vmem_model_syncs_with_kernel(self):
        """The ring hop runs the masked-flash kernel on local chunks, so its
        VMEM model must track the kernel's own estimate exactly — the same
        sync discipline as every other tuned kernel."""
        import jimm_tpu.ops.flash_attention as fa
        from jimm_tpu.tune.space import ring_vmem_bytes
        for bq in (128, 256):
            for bk in (128, 256, 512):
                for d in (64, 128, 256):
                    assert ring_vmem_bytes(bq, bk, d) == \
                        fa._per_head_vmem_bytes(bq, bk, d, has_mask=True)

    def test_ring_space_keys_on_local_chunks(self):
        from jimm_tpu.tune.space import VMEM_BUDGET, ring_space, \
            ring_vmem_bytes
        local = (4, 512, 8, 64)  # (B, S/p, N, D)
        cands = ring_space((local, local, local))
        assert cands, "no feasible ring hop configs for a 512-token chunk"
        for c in cands:
            assert ring_vmem_bytes(c["block_q"], c["block_k"], 64) \
                <= VMEM_BUDGET

    def test_best_config_resolves_ring_default(self):
        from jimm_tpu.tune import best_config
        cfg = best_config("ring_attention",
                          ((2, 64, 4, 16),) * 3,
                          (jnp.float32,) * 3,
                          default={"block_q": 128, "block_k": 512})
        assert cfg == {"block_q": 128, "block_k": 512}


# ---------------------------------------------------------------------------
# Baseline keys segment on sequence identity
# ---------------------------------------------------------------------------

class TestBaselineSeqKeys:
    BASE = {"phase": "serve_bench", "backend": "cpu", "preset": "p"}

    def test_legacy_rows_keep_their_keys(self):
        assert row_key(self.BASE) == "serve_bench/cpu/p"

    def test_seq_len_segments(self):
        assert row_key({**self.BASE, "seq_len": 1568}) == \
            "serve_bench/cpu/p/seq1568"

    def test_seq_parallel_segments_only_above_one(self):
        rec = {**self.BASE, "seq_len": 1568, "seq_parallel": 4}
        assert row_key(rec) == "serve_bench/cpu/p/seq1568/sp4"
        # a stamped-but-degenerate run keeps the single-chip key
        rec["seq_parallel"] = 1
        assert row_key(rec) == "serve_bench/cpu/p/seq1568"

    def test_ring_run_never_gates_against_single_chip_baseline(self):
        single = row_key({**self.BASE, "seq_len": 196, "seq_parallel": 1})
        ring = row_key({**self.BASE, "seq_len": 196, "seq_parallel": 8})
        assert single != ring


# ---------------------------------------------------------------------------
# Temporal presets
# ---------------------------------------------------------------------------

class TestTemporalPreset:
    def test_presets_exist_and_flatten_frames(self):
        from jimm_tpu.configs import preset
        cfg = preset("vit-temporal-small-patch16-224-f8")
        v = cfg.vision
        assert v.num_frames == 8
        grid = v.image_size // v.patch_size
        # MAP pooling: no CLS token, so T * grid^2 divides any even ring
        assert v.pooling == "map"
        assert v.num_patches == 8 * grid * grid
        assert v.seq_len == v.num_patches
        assert v.seq_len % 8 == 0

    def test_tower_forward_on_clips(self):
        from flax import nnx

        from jimm_tpu.cli import _tiny_override
        from jimm_tpu.configs import preset
        from jimm_tpu.nn.vision import VisionTower
        cfg = _tiny_override(preset("vit-temporal-small-patch16-224-f8"))
        v = cfg.vision
        tower = VisionTower(v, rngs=nnx.Rngs(0))
        clips = jnp.zeros((2, v.num_frames, v.image_size, v.image_size, 3))
        out = tower(clips)
        assert out.shape == (2, v.width)

    def test_tower_rejects_wrong_frame_count(self):
        from flax import nnx

        from jimm_tpu.cli import _tiny_override
        from jimm_tpu.configs import preset
        from jimm_tpu.nn.vision import VisionTower
        cfg = _tiny_override(preset("vit-temporal-small-patch16-224-f8"))
        v = cfg.vision
        tower = VisionTower(v, rngs=nnx.Rngs(0))
        with pytest.raises(ValueError, match="temporal tower expects"):
            tower(jnp.zeros((2, 4, v.image_size, v.image_size, 3)))
        with pytest.raises(ValueError, match="temporal tower expects"):
            tower(jnp.zeros((2, v.image_size, v.image_size, 3)))

    def test_synthetic_clips(self):
        from jimm_tpu.data.synthetic import blob_classification
        imgs, labels = next(blob_classification(4, image_size=16,
                                                num_frames=8))
        assert imgs.shape == (4, 8, 16, 16, 3)
        assert labels.shape == (4,)
        # num_frames=1 keeps the legacy stream byte for byte
        legacy, _ = next(blob_classification(4, image_size=16))
        tagged, _ = next(blob_classification(4, image_size=16, num_frames=1))
        np.testing.assert_array_equal(legacy, tagged)


# ---------------------------------------------------------------------------
# Observability: permuted-bytes accounting
# ---------------------------------------------------------------------------

class TestRingObservability:
    def test_bytes_permuted_counter_accounts_the_plan(self):
        from jimm_tpu.obs.registry import get_registry
        counter = get_registry("jimm_ring").counter(
            "jimm_ring_bytes_permuted_total")
        mesh = _seq_mesh(4)
        q, k, v = _qkv(2, 64, 6, 16)
        before = counter.value
        ring_attention_sp(q, k, v, mesh=mesh, impl="einsum")
        expect = seqpar_comm_bytes(2, 64, 6, 16, 4, itemsize=4) * 4
        assert counter.value - before == expect
