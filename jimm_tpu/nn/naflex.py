"""NaFlex (native-flexible-resolution) vision input support for SigLIP2.

The reference supports "SigLIP v1 and v2, any non-NaFlex variant"
(ref `README.md:13-14`) — NaFlex is its stated limitation. This module goes
beyond that: variable-aspect, variable-resolution batches processed the way
HF's ``Siglip2Model`` NaFlex path does (pre-patchified inputs + per-sample
spatial shapes + padding mask), but designed for XLA: everything is
shape-static, the per-sample bilinear position-embedding resize is expressed
as one einsum over interpolation-weight matrices instead of a Python loop of
dynamic-shape ``F.interpolate`` calls (HF
`modeling_siglip2.py` ``Siglip2VisionEmbeddings.resize_positional_embeddings``
loops over the batch on the host — untraceable and TPU-hostile).

Semantics matched exactly (oracle-tested in `tests/test_naflex.py`):
``torch.nn.functional.interpolate(mode="bilinear", align_corners=False,
antialias=True)`` — the triangle filter with support scaled by the
downsampling factor, evaluated per axis; for upscaling it degenerates to
standard edge-clamped bilinear.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _axis_weights(idx: jax.Array, n_out: jax.Array, n_in: int) -> jax.Array:
    """Antialiased-bilinear interpolation weights for sampling a length
    ``n_in`` (static) source axis at output indices ``idx`` of a length
    ``n_out`` (dynamic, per sample) target axis.

    For each output index i: source center ``src = (i + 0.5) * s - 0.5``
    with ``s = n_in / n_out``; triangle filter of half-width
    ``max(1, s)`` (antialias widens the kernel only when downsampling),
    normalized over the in-range taps — which also reproduces torch's
    edge-clamping for plain bilinear upsampling.
    """
    scale = n_in / n_out.astype(jnp.float32)
    src = (idx.astype(jnp.float32) + 0.5) * scale - 0.5
    support = jnp.maximum(scale, 1.0)
    taps = jnp.arange(n_in, dtype=jnp.float32)
    w = jnp.maximum(0.0, 1.0 - jnp.abs(taps[None, :] - src[:, None]) / support)
    # out-of-grid rows (padded tokens whose row/col lies past the sample's
    # h*w) can have an all-zero tap window; the epsilon turns the 0/0 into
    # an all-zero weight row (finite!) instead of NaN, which would otherwise
    # poison masked attention through 0 * NaN
    return w / jnp.maximum(w.sum(axis=-1, keepdims=True), 1e-9)


def naflex_position_embedding(table: jax.Array, spatial_shapes: jax.Array,
                              seq_len: int) -> jax.Array:
    """Sample a ``(H0, W0, D)`` learned position table at every token of
    every sample's ``(h, w)`` grid: token ``t`` of sample ``b`` lives at
    row ``t // w_b``, col ``t % w_b`` and gets the antialiased-bilinear
    resample of the table at that position — equivalent to resizing the
    table to ``(h_b, w_b)`` and flattening, with no dynamic shapes.

    Args:
        table: ``(H0, W0, D)`` position-embedding grid (static shape).
        spatial_shapes: ``(B, 2)`` int32 per-sample (height, width) in
            patches; ``h * w <= seq_len`` for real tokens.
        seq_len: static padded token count of the batch.

    Returns:
        ``(B, seq_len, D)``; rows past ``h * w`` are zero (they are padding
        and must be masked out of attention anyway).
    """
    h0, w0, _ = table.shape
    t = jnp.arange(seq_len)

    def per_sample(shape: jax.Array) -> jax.Array:
        h, w = shape[0], shape[1]
        row = t // jnp.maximum(w, 1)
        col = t % jnp.maximum(w, 1)
        wr = _axis_weights(row, h, h0)              # (S, H0)
        wc = _axis_weights(col, w, w0)              # (S, W0)
        return jnp.einsum("sj,jkd,sk->sd", wr, table.astype(jnp.float32), wc)

    return jax.vmap(per_sample)(spatial_shapes)
