"""Checkpoint resolution: local safetensors file/dir or HF hub repo id.

Preserves the reference's user-visible loading contract minus torch
(SURVEY §7.1.1): local `.safetensors` file with sibling/parent `config.json`
discovery (ref `common/utils.py:77-86`), local directory, or HF hub repo-id
(ref `common/utils.py:74-99`). Adds sharded-checkpoint support
(`model.safetensors.index.json`), which the reference lacks.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Any

import numpy as np

from jimm_tpu.weights.safetensors_io import load_file


def _load_config(path: Path) -> dict[str, Any] | None:
    if path.is_file():
        with open(path) as f:
            return json.load(f)
    return None


def _from_dir(d: Path) -> tuple[dict[str, np.ndarray], dict | None]:
    config = _load_config(d / "config.json")
    index = d / "model.safetensors.index.json"
    if index.is_file():
        with open(index) as f:
            weight_map: dict[str, str] = json.load(f)["weight_map"]
        weights: dict[str, np.ndarray] = {}
        for shard in sorted(set(weight_map.values())):
            weights.update(load_file(d / shard))
        return weights, config
    single = d / "model.safetensors"
    if single.is_file():
        return load_file(single), config
    candidates = sorted(d.glob("*.safetensors"))
    if candidates:
        weights = {}
        for c in candidates:
            weights.update(load_file(c))
        return weights, config
    raise FileNotFoundError(f"no .safetensors weights under {d}")


def _from_file(p: Path) -> tuple[dict[str, np.ndarray], dict | None]:
    weights = load_file(p)
    # config discovery: sibling config.json, else parent of a `model/` dir
    # (ref common/utils.py:77-86)
    config = _load_config(p.parent / "config.json")
    if config is None and p.parent.name == "model":
        config = _load_config(p.parent.parent / "config.json")
    return weights, config


def _from_hub(repo_id: str) -> tuple[dict[str, np.ndarray], dict | None]:
    try:
        from huggingface_hub import hf_hub_download
    except ImportError as e:  # pragma: no cover
        raise FileNotFoundError(
            f"{repo_id!r} is not a local path and huggingface_hub is "
            "unavailable") from e
    weights: dict[str, np.ndarray] = {}
    try:
        # sharded checkpoints first (large models), then the single file
        try:
            index_path = hf_hub_download(repo_id,
                                         "model.safetensors.index.json")
            with open(index_path) as f:
                weight_map: dict[str, str] = json.load(f)["weight_map"]
            for shard in sorted(set(weight_map.values())):
                weights.update(load_file(hf_hub_download(repo_id, shard)))
        except Exception:
            weights = load_file(hf_hub_download(repo_id, "model.safetensors"))
    except Exception as e:
        raise FileNotFoundError(
            f"could not fetch {repo_id!r} from the HF hub (offline?): {e}"
        ) from e
    try:
        config_path = hf_hub_download(repo_id, "config.json")
        config = _load_config(Path(config_path))
    except Exception:
        config = None
    return weights, config


def resolve_checkpoint(name_or_path: str | os.PathLike
                       ) -> tuple[dict[str, np.ndarray], dict | None]:
    """Return ``(flat hf tensor dict, hf config dict | None)``."""
    p = Path(name_or_path).expanduser()
    if p.is_dir():
        return _from_dir(p)
    if p.is_file():
        return _from_file(p)
    name = str(name_or_path)
    if name.startswith((".", "/", "~")) or name.count("/") != 1:
        # filesystem-looking, but nothing there — don't confuse with a repo id
        raise FileNotFoundError(f"no checkpoint file or directory at {name!r}")
    return _from_hub(name)
