"""Layer-2 checks (``--trace``): lower registered model entry points on tiny
shapes — no real execution, only ``jit(...).lower()`` (plus XLA compilation
for the FSDP check, still host-side) — and assert TPU-correctness properties
on the emitted program text:

- **JLT101** donation materialized: a donated train step's StableHLO carries
  ``tf.aliasing_output`` on the model/optimizer state inputs. Donation that
  silently fails to alias (dtype/layout mismatch, struct change) doubles HBM
  on the hot path without any runtime error.
- **JLT102** no full-parameter all-gather under FSDP: the compiled module
  must not gather an entire stacked parameter onto every device — good FSDP
  lowering moves per-layer slices (or uses reduce-scatter/all-reduce).
- **JLT103** stable program across the declared batch buckets: the op
  histogram of the lowered module must be identical for every batch size in
  :data:`BATCH_BUCKETS`, otherwise each bucket compiles a structurally
  different program (cache-key churn and recompiles at runtime).

Tiny configs keep tracing cheap (~seconds); the properties they certify are
shape-independent program structure, not numerics.
"""

from __future__ import annotations

import re

from jimm_tpu.lint.core import ERROR, WARNING, Finding

#: batch sizes the data pipeline is allowed to present to a jitted step;
#: JLT103 asserts one program structure covers them all
BATCH_BUCKETS = (2, 4)

_TINY_VISION = dict(image_size=16, patch_size=8, width=32, depth=2,
                    num_heads=2, mlp_dim=64)

_ALLGATHER_RE = re.compile(
    r"=\s*([a-z]+[0-9]+)\[([0-9,]*)\][^=]*\ball-gather")

_DTYPE_BYTES = {"f64": 8, "s64": 8, "u64": 8, "f32": 4, "s32": 4, "u32": 4,
                "bf16": 2, "f16": 2, "s16": 2, "u16": 2, "s8": 1, "u8": 1,
                "pred": 1}


def _tiny_vit():
    from flax import nnx

    from jimm_tpu import VisionTransformer, ViTConfig, VisionConfig
    cfg = ViTConfig(vision=VisionConfig(**_TINY_VISION), num_classes=4)
    return VisionTransformer(cfg, rngs=nnx.Rngs(0))


def _tiny_siglip():
    from flax import nnx

    from jimm_tpu import SigLIP, SigLIPConfig, TextConfig, VisionConfig
    cfg = SigLIPConfig(
        vision=VisionConfig(**_TINY_VISION),
        text=TextConfig(vocab_size=64, context_length=8, width=32, depth=2,
                        num_heads=2, mlp_dim=64, causal=False,
                        pooling="last", proj_bias=True),
        projection_dim=32)
    return SigLIP(cfg, rngs=nnx.Rngs(0))


def _vit_batch(batch: int):
    import jax.numpy as jnp
    images = jnp.zeros((batch, 16, 16, 3), jnp.float32)
    labels = jnp.zeros((batch,), jnp.int32)
    return images, labels


def _siglip_batch(batch: int):
    import jax.numpy as jnp
    images = jnp.zeros((batch, 16, 16, 3), jnp.float32)
    text = jnp.zeros((batch, 8), jnp.int32)
    return images, text


def _vit_step_body(model, optimizer, images, labels):
    import optax
    from flax import nnx

    from jimm_tpu.utils.compat import optimizer_update

    def loss_fn(model):
        logits = model(images)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()

    loss, grads = nnx.value_and_grad(loss_fn)(model)
    optimizer_update(optimizer, model, grads)
    return loss


def _siglip_step_body(model, optimizer, images, text):
    from flax import nnx

    from jimm_tpu.train import contrastive_loss_fn
    from jimm_tpu.utils.compat import optimizer_update

    def loss_fn(model):
        return contrastive_loss_fn(model, images, text, kind="siglip")

    loss, grads = nnx.value_and_grad(loss_fn)(model)
    optimizer_update(optimizer, model, grads)
    return loss


#: registered entry points: name -> (model builder, batch builder, step body,
#: forward fn)
ENTRY_POINTS = {
    "vit_classifier": (_tiny_vit, _vit_batch, _vit_step_body,
                       lambda m, b: m(b[0])),
    "siglip_contrastive": (_tiny_siglip, _siglip_batch, _siglip_step_body,
                           lambda m, b: m.encode_image(b[0])),
}


def _trace_path(entry: str) -> str:
    return f"<trace:{entry}>"


# ---------------------------------------------------------------------------
# JLT101 — donation must materialize as input/output aliasing
# ---------------------------------------------------------------------------

def _check_donation(entry: str, build_model, build_batch,
                    step_body) -> list[Finding]:
    import jax
    from flax import nnx

    from jimm_tpu.train import OptimizerConfig, make_optimizer

    model = build_model()
    optimizer = make_optimizer(model, OptimizerConfig())
    graphdef, state = nnx.split((model, optimizer))
    batch = build_batch(BATCH_BUCKETS[0])

    def pure_step(state, *batch):
        model, optimizer = nnx.merge(graphdef, state)
        loss = step_body(model, optimizer, *batch)
        return nnx.state((model, optimizer)), loss

    lowered = jax.jit(pure_step, donate_argnums=(0,)).lower(state, *batch)
    text = lowered.as_text()
    if "tf.aliasing_output" not in text:
        return [Finding(
            "JLT101", ERROR, _trace_path(entry), 0,
            "donate_argnums on the train-step state produced no "
            "tf.aliasing_output attribute in the lowered StableHLO — "
            "donation is silently not materializing, params/m/v will "
            "double-buffer in HBM")]
    return []


# ---------------------------------------------------------------------------
# JLT102 — no full-parameter all-gather under FSDP
# ---------------------------------------------------------------------------

def _check_fsdp_allgather(entry: str, build_model, build_batch,
                          forward) -> list[Finding]:
    import jax
    import jax.numpy as jnp
    from flax import nnx

    from jimm_tpu.parallel import FSDP, create_sharded, make_mesh, \
        use_sharding

    ndev = len(jax.devices())
    if ndev < 2:
        return [Finding(
            "JLT102", WARNING, _trace_path(entry), 0,
            f"skipped: FSDP all-gather check needs >= 2 devices, "
            f"have {ndev}")]
    mesh = make_mesh({"data": ndev})
    with use_sharding(mesh, FSDP):
        model = create_sharded(build_model, mesh, FSDP)
        graphdef, state = nnx.split(model)
        batch = build_batch(BATCH_BUCKETS[0])

        def fwd(state, batch):
            model = nnx.merge(graphdef, state)
            return forward(model, batch)

        compiled = jax.jit(fwd).lower(state, batch).compile()
    text = compiled.as_text()

    # threshold: the largest single (stacked) parameter's full byte size —
    # per-layer FSDP gathers move 1/depth of it, a "full parameter" gather
    # moves at least all of it
    # shape/dtype arithmetic instead of .nbytes: abstract arrays (lazy
    # sharded init) raise NotImplementedError on the property
    def leaf_nbytes(leaf):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            return 0
        elems = 1
        for d in shape:
            elems *= int(d)
        try:
            itemsize = jnp.dtype(dtype).itemsize
        except TypeError:
            itemsize = 4
        return elems * itemsize

    largest = max(
        leaf_nbytes(leaf)
        for leaf in jax.tree_util.tree_leaves(state))
    findings = []
    for dtype, dims in _ALLGATHER_RE.findall(text):
        elems = 1
        for d in dims.split(","):
            if d:
                elems *= int(d)
        nbytes = elems * _DTYPE_BYTES.get(dtype, 4)
        if nbytes >= largest:
            findings.append(Finding(
                "JLT102", ERROR, _trace_path(entry), 0,
                f"compiled FSDP forward all-gathers {nbytes} bytes "
                f"({dtype}[{dims}]) >= largest stacked parameter "
                f"({largest} bytes) — a full-parameter gather defeats "
                f"FSDP's memory scaling"))
    return findings


# ---------------------------------------------------------------------------
# JLT103 — one program structure across batch buckets
# ---------------------------------------------------------------------------

_OP_RE = re.compile(r"\bstablehlo\.[a-z_]+")


def _op_histogram(text: str) -> dict[str, int]:
    hist: dict[str, int] = {}
    for op in _OP_RE.findall(text):
        hist[op] = hist.get(op, 0) + 1
    return hist


def _check_bucket_stability(entry: str, build_model, build_batch,
                            forward) -> list[Finding]:
    import jax
    from flax import nnx

    model = build_model()
    graphdef, state = nnx.split(model)

    def fwd(state, batch):
        model = nnx.merge(graphdef, state)
        return forward(model, batch)

    jitted = jax.jit(fwd)
    hists = {}
    for batch in BATCH_BUCKETS:
        text = jitted.lower(state, build_batch(batch)).as_text()
        hists[batch] = _op_histogram(text)
    base_batch = BATCH_BUCKETS[0]
    base = hists[base_batch]
    findings = []
    for batch, hist in hists.items():
        if hist != base:
            diff = {op for op in set(base) | set(hist)
                    if base.get(op, 0) != hist.get(op, 0)}
            findings.append(Finding(
                "JLT103", ERROR, _trace_path(entry), 0,
                f"lowered program structure differs between batch "
                f"{base_batch} and batch {batch} (ops: {sorted(diff)}) — "
                f"each bucket will compile a different program "
                f"(cache-key churn, runtime recompiles)"))
    return findings


# ---------------------------------------------------------------------------

def run_trace_checks() -> list[Finding]:
    """Run every trace check over every registered entry point. Exceptions
    inside a check become JLT000 error findings — a broken lowering path is
    itself a finding, not a linter crash."""
    from jimm_tpu.utils.env import set_host_device_count

    # must land before the XLA backend initializes; harmless no-op after
    try:
        set_host_device_count(8)
    except RuntimeError:
        pass

    findings: list[Finding] = []
    for entry, (build_model, build_batch, step_body,
                forward) in ENTRY_POINTS.items():
        for check in (
                lambda: _check_donation(entry, build_model, build_batch,
                                        step_body),
                lambda: _check_fsdp_allgather(entry, build_model,
                                              build_batch, forward),
                lambda: _check_bucket_stability(entry, build_model,
                                                build_batch, forward)):
            try:
                findings.extend(check())
            except Exception as e:  # noqa: BLE001 — surface, don't crash
                findings.append(Finding(
                    "JLT000", ERROR, _trace_path(entry), 0,
                    f"trace check raised {type(e).__name__}: {e}"))
    return findings
