"""Environment plumbing.

This runtime exports ``JAX_PLATFORMS=axon`` globally and the plugin re-merges
it, so the env var alone cannot force a backend. ``configure_platform`` reads
``JIMM_PLATFORM`` (e.g. ``cpu``) and ``JIMM_HOST_DEVICES`` (virtual CPU
device count for mesh testing) and applies them in-process *before* the first
backend use — call it at the top of every script entry point.
"""

from __future__ import annotations

import os


#: fields a caller set EXPLICITLY (argument, not env) in this process —
#: later env-fallback calls (e.g. initialize_distributed's bootstrap) must
#: not clobber them with JIMM_* values
_explicit: set[str] = set()


def set_host_device_count(n: int) -> None:
    """Request ``n`` virtual host (CPU) devices, portably across JAX
    versions. The ``jax_num_cpu_devices`` config key exists only on
    JAX >= 0.5; older versions fall back to the XLA flag, which takes
    effect only if set before the backend first initializes. Raises
    ``RuntimeError`` (from jax) if the backend is already up on >= 0.5."""
    import jax
    try:
        jax.config.update("jax_num_cpu_devices", int(n))
    except AttributeError:
        import re
        flags = os.environ.get("XLA_FLAGS", "")
        flag = f"--xla_force_host_platform_device_count={int(n)}"
        if "--xla_force_host_platform_device_count" in flags:
            # replace, don't skip: a stale value (e.g. inherited through the
            # environment from a parent process) must not win over this call
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", flag, flags)
        else:
            flags = f"{flags} {flag}".strip()
        os.environ["XLA_FLAGS"] = flags


def configure_platform(platform: str | None = None,
                       host_devices: int | None = None) -> None:
    """Apply backend overrides from arguments, falling back to the
    ``JIMM_PLATFORM`` / ``JIMM_HOST_DEVICES`` env vars. Explicit arguments
    win over env for the rest of the process: a bare re-invocation never
    overrides what a caller set by hand."""
    # `is None` (not truthiness): an explicit empty/zero argument must be
    # able to override a JIMM_PLATFORM/JIMM_HOST_DEVICES env setting
    if platform is not None:
        _explicit.add("platform")
    if host_devices is not None:
        _explicit.add("host_devices")
    plat = os.environ.get("JIMM_PLATFORM") if platform is None else platform
    n = os.environ.get("JIMM_HOST_DEVICES") if host_devices is None else host_devices
    if platform is None and "platform" in _explicit:
        plat = None
    if host_devices is None and "host_devices" in _explicit:
        n = None
    if not plat and not n:
        return
    import jax
    if plat:
        jax.config.update("jax_platforms", plat)
    if n:
        set_host_device_count(int(n))
