"""SigLIP contrastive training with the ring all-gather sigmoid loss.

The north-star entry point (`BASELINE.json`): dual-tower SigLIP trained with
the chunked ring sigmoid loss over the data-parallel mesh axis, FSDP+TP
parameter sharding, Pallas flash attention in the towers, bf16 params,
prefetched input pipeline, MFU logging, orbax checkpointing. The reference
has no contrastive training at all.

Run (single host / CPU mesh):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/siglip_training.py --steps 50 --batch-size 64
"""

from __future__ import annotations

import jimm_tpu.utils.env
jimm_tpu.utils.env.configure_platform()

import argparse
import dataclasses

import jax
import jax.numpy as jnp
from flax import nnx

from jimm_tpu import SigLIP, preset
from jimm_tpu.configs import SigLIPConfig, TextConfig, VisionConfig
from jimm_tpu.data import PrefetchIterator, contrastive_pairs
from jimm_tpu.parallel import PRESET_RULES, make_mesh, use_sharding
from jimm_tpu.train import (CheckpointManager, MetricsLogger, OptimizerConfig,
                            StepTimer, make_contrastive_train_step,
                            make_optimizer)


def tiny_config(image_size: int, remat: bool) -> SigLIPConfig:
    return SigLIPConfig(
        vision=VisionConfig(image_size=image_size, patch_size=16, width=128,
                            depth=4, num_heads=2, mlp_dim=256, act="gelu_tanh",
                            pooling="map", remat=remat),
        text=TextConfig(vocab_size=64, context_length=8, width=128, depth=4,
                        num_heads=2, mlp_dim=256, act="gelu_tanh",
                        causal=False, pooling="last", proj_bias=True),
        projection_dim=128)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--preset", default=None,
                   help="e.g. siglip-base-patch16-256 (default: tiny demo)")
    p.add_argument("--rules", default="fsdp_tp", choices=sorted(PRESET_RULES))
    p.add_argument("--model-axis", type=int, default=1)
    p.add_argument("--loss", default="siglip_ring",
                   choices=["siglip_ring", "siglip", "clip"])
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--bf16", action="store_true")
    p.add_argument("--remat", action="store_true")
    p.add_argument("--ckpt-dir", default=None)
    p.add_argument("--log", default=None)
    args = p.parse_args()

    mesh = make_mesh({"data": -1, "model": args.model_axis})
    rules = PRESET_RULES[args.rules]
    print(f"mesh {dict(mesh.shape)} rules {args.rules} loss {args.loss}")

    if args.preset:
        cfg = preset(args.preset)
        if args.remat:
            cfg = dataclasses.replace(
                cfg,
                vision=dataclasses.replace(cfg.vision, remat=True),
                text=dataclasses.replace(cfg.text, remat=True))
    else:
        cfg = tiny_config(32, args.remat)
    dtype = jnp.bfloat16 if args.bf16 else None
    param_dtype = jnp.bfloat16 if args.bf16 else jnp.float32
    model = SigLIP(cfg, rngs=nnx.Rngs(0), mesh=mesh, rules=rules,
                   dtype=dtype, param_dtype=param_dtype)
    optimizer = make_optimizer(model, OptimizerConfig(
        learning_rate=args.lr, warmup_steps=10, total_steps=args.steps))
    train_step = make_contrastive_train_step(args.loss, mesh=mesh,
                                             donate=True)
    logger = MetricsLogger(path=args.log, print_every=5)
    ckpt = CheckpointManager(args.ckpt_dir) if args.ckpt_dir else None

    data = PrefetchIterator(
        contrastive_pairs(args.batch_size, image_size=cfg.vision.image_size,
                          vocab_size=cfg.text.vocab_size,
                          seq_len=cfg.text.context_length),
        mesh=mesh, rules=rules)
    timer = StepTimer()

    with use_sharding(mesh, rules):
        for step, (images, text) in zip(range(args.steps), data):
            if args.bf16:
                images = jax.tree.map(
                    lambda x: x.astype(jnp.bfloat16)
                    if x.dtype == jnp.float32 else x, images)
            timer.start()
            metrics = train_step(model, optimizer, images, text)
            dt = timer.stop(metrics["loss"])
            logger.log(step, loss=metrics["loss"],
                       images_per_sec=args.batch_size / dt)
            if ckpt and step and step % 100 == 0:
                ckpt.save(step, model, optimizer)
    if ckpt:
        ckpt.save(args.steps, model, optimizer, force=True)
        ckpt.wait()
        ckpt.close()
    data.close()
    logger.close()
    print(f"final loss {float(metrics['loss']):.4f}")


if __name__ == "__main__":
    main()
