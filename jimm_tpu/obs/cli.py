"""``jimm-tpu obs`` — tail, snapshot, and diff metric dumps.

Three verbs over the exporter formats (stdlib only, no jax import):

- ``snapshot`` — fetch a ``/metrics`` endpoint (or read a saved dump) and
  print it as a console table, JSON, or raw Prometheus text; ``-o`` saves
  the parsed snapshot as JSON for a later ``diff``.
- ``tail``     — follow a MEASUREMENTS.jsonl-style ledger (``tail -f`` with
  JSON pretty-keys), or poll a ``/metrics`` URL and print only the series
  that changed between polls.
- ``diff``     — structural diff of two dumps (JSON snapshot or Prometheus
  text, auto-detected): added / removed / changed with deltas.

Wired as a subparser under the main ``jimm-tpu`` CLI (see jimm_tpu/cli.py).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request

from jimm_tpu.obs.exporters import (console_table, diff_snapshots,
                                    parse_prometheus_text)

__all__ = ["add_obs_parser", "cmd_obs"]


def _load_dump(source: str, timeout_s: float = 10.0) -> dict[str, float]:
    """Read a metrics dump from a URL, JSON file, or Prometheus text file."""
    if source.startswith(("http://", "https://")):
        with urllib.request.urlopen(source, timeout=timeout_s) as resp:
            text = resp.read().decode("utf-8")
    else:
        with open(source) as f:
            text = f.read()
    text = text.strip()
    if text.startswith("{"):
        data = json.loads(text)
        return {k: v for k, v in data.items()
                if isinstance(v, (int, float))}
    return parse_prometheus_text(text)


def _cmd_snapshot(args) -> int:
    series = _load_dump(args.source)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(series, f, indent=2, sort_keys=True)
            f.write("\n")
    if args.json:
        print(json.dumps(series, indent=2, sort_keys=True))
    else:
        print(console_table(series, title=f"metrics: {args.source}"),
              end="")
    return 0


def _tail_jsonl(path: str, follow: bool) -> int:
    with open(path) as f:
        while True:
            line = f.readline()
            if line:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                ts = rec.pop("ts", "")
                phase = rec.pop("phase", "")
                keys = ", ".join(f"{k}={v}" for k, v in sorted(rec.items()))
                print(f"{ts} [{phase}] {keys}", flush=True)
            elif follow:
                time.sleep(0.5)
            else:
                return 0


def _tail_url(url: str, interval_s: float) -> int:
    prev: dict[str, float] = {}
    while True:
        try:
            cur = _load_dump(url)
        except OSError as e:
            print(f"# fetch failed: {e}", file=sys.stderr, flush=True)
            time.sleep(interval_s)
            continue
        changes = diff_snapshots(prev, cur)
        stamp = time.strftime("%H:%M:%S")
        for name, value in sorted(changes["added"].items()):
            print(f"{stamp} {name} = {value}", flush=True)
        for name, d in sorted(changes["changed"].items()):
            print(f"{stamp} {name} = {d['after']} ({d['delta']:+g})",
                  flush=True)
        prev = cur
        time.sleep(interval_s)


def _cmd_tail(args) -> int:
    if args.source.startswith(("http://", "https://")):
        try:
            return _tail_url(args.source, args.interval)
        except KeyboardInterrupt:
            return 0
    try:
        return _tail_jsonl(args.source, follow=args.follow)
    except KeyboardInterrupt:
        return 0


def _cmd_diff(args) -> int:
    before = _load_dump(args.before)
    after = _load_dump(args.after)
    d = diff_snapshots(before, after)
    if args.json:
        print(json.dumps(d, indent=2, sort_keys=True))
    else:
        for name, value in sorted(d["added"].items()):
            print(f"+ {name} = {value}")
        for name, value in sorted(d["removed"].items()):
            print(f"- {name} = {value}")
        for name, c in sorted(d["changed"].items()):
            print(f"~ {name}: {c['before']} -> {c['after']} "
                  f"({c['delta']:+g})")
        if not (d["added"] or d["removed"] or d["changed"]):
            print("(no differences)")
    return 1 if (d["added"] or d["removed"] or d["changed"]) else 0


def add_obs_parser(subparsers) -> None:
    """Attach the ``obs`` subcommand tree to the main CLI's subparsers."""
    p = subparsers.add_parser(
        "obs", help="tail, snapshot, and diff metric dumps")
    p.set_defaults(fn=cmd_obs)
    sub = p.add_subparsers(dest="obs_cmd", required=True)

    ps = sub.add_parser("snapshot",
                        help="fetch/read a metrics dump and print it")
    ps.add_argument("source",
                    help="/metrics URL, JSON snapshot, or Prometheus "
                         "text file")
    ps.add_argument("--json", action="store_true",
                    help="print as JSON instead of a table")
    ps.add_argument("-o", "--out", default=None,
                    help="also save the parsed snapshot as JSON")
    ps.set_defaults(obs_func=_cmd_snapshot)

    pt = sub.add_parser("tail",
                        help="follow a metrics JSONL ledger or poll a "
                             "/metrics URL")
    pt.add_argument("source", help="JSONL path or /metrics URL")
    pt.add_argument("-f", "--follow", action="store_true",
                    help="keep following a JSONL file (tail -f)")
    pt.add_argument("--interval", type=float, default=2.0,
                    help="poll interval for URLs (seconds)")
    pt.set_defaults(obs_func=_cmd_tail)

    pd = sub.add_parser("diff", help="diff two metric dumps")
    pd.add_argument("before")
    pd.add_argument("after")
    pd.add_argument("--json", action="store_true")
    pd.set_defaults(obs_func=_cmd_diff)


def cmd_obs(args) -> int:
    return args.obs_func(args)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="jimm-tpu-obs")
    sub = parser.add_subparsers(dest="command", required=True)
    add_obs_parser(sub)
    args = parser.parse_args(argv)
    return cmd_obs(args)


if __name__ == "__main__":
    raise SystemExit(main())
