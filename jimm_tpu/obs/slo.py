"""SLO objectives and multi-window burn rates over serve traffic.

An :class:`SloObjective` states what "good" means for a tenant: an
availability target (fraction of requests that must succeed) and an
optional latency target (a success slower than ``latency_ms`` still counts
against the SLO). The :class:`SloEngine` consumes one observation per
finished request (ok/failed + latency) and maintains, per tenant,
per-second traffic buckets from which it computes **burn rates** over two
windows:

    burn_rate(window) = bad_fraction(window) / error_budget

where ``error_budget = 1 - availability``. A burn rate of 1.0 means the
budget is being spent exactly as provisioned; 14.4 (the classic fast-page
threshold for a 99.9% objective) means the monthly budget would be gone in
~2 days. Zero-traffic windows burn nothing (rate 0.0) — no traffic, no
spend.

:meth:`SloEngine.fast_burning` implements the standard multi-window guard:
a tenant is fast-burning only when **both** the fast window exceeds the
page threshold **and** the slow window is itself burning (>= 1.0), so a
single failed request after an idle stretch can't page. The serve engine
feeds this into the self-heal escalation path and the journal.

Metrics are published as a ``jimm_slo`` registry (``jimm_slo_*`` series in
the unified snapshot and the serving ``/metrics`` dump): per tenant,
``{tenant}_good_total`` / ``{tenant}_bad_total`` counters and
``{tenant}_fast_burn_rate`` / ``{tenant}_slow_burn_rate`` gauges. Tenant
cardinality is bounded by the policy file: only tenants with declared
objectives get series; unknown tenants fold into ``default``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass

from jimm_tpu.obs.registry import MetricRegistry, publish

__all__ = ["SloEngine", "SloObjective", "DEFAULT_FAST_WINDOW_S",
           "DEFAULT_SLOW_WINDOW_S", "DEFAULT_FAST_BURN_THRESHOLD"]

DEFAULT_FAST_WINDOW_S = 60.0
DEFAULT_SLOW_WINDOW_S = 600.0
DEFAULT_FAST_BURN_THRESHOLD = 14.4


@dataclass(frozen=True)
class SloObjective:
    """What "good" means for one tenant."""

    availability: float = 0.999        # target good-fraction, in (0, 1)
    latency_ms: float | None = None    # slower-than-this successes are bad

    def __post_init__(self):
        if not 0.0 < self.availability < 1.0:
            raise ValueError(
                f"availability must be in (0, 1), got {self.availability}")
        if self.latency_ms is not None and self.latency_ms <= 0:
            raise ValueError(
                f"latency_ms must be positive, got {self.latency_ms}")

    @property
    def error_budget(self) -> float:
        return 1.0 - self.availability

    @classmethod
    def from_dict(cls, data: dict) -> "SloObjective":
        unknown = set(data) - {"availability", "latency_ms"}
        if unknown:
            raise ValueError(f"unknown SLO objective keys: {sorted(unknown)}")
        kw = {}
        if "availability" in data:
            kw["availability"] = float(data["availability"])
        if "latency_ms" in data and data["latency_ms"] is not None:
            kw["latency_ms"] = float(data["latency_ms"])
        return cls(**kw)

    def describe(self) -> dict:
        out: dict = {"availability": self.availability}
        if self.latency_ms is not None:
            out["latency_ms"] = self.latency_ms
        return out


class _Tracker:
    """Per-second (sec, good, bad) buckets for one tenant, bounded by the
    longest window we will ever ask about."""

    def __init__(self, horizon_s: float):
        self._buckets: deque[list] = deque(maxlen=int(horizon_s) + 2)
        self.good_total = 0
        self.bad_total = 0

    def observe(self, ok: bool, now: float) -> None:
        sec = int(now)
        if not self._buckets or self._buckets[-1][0] != sec:
            self._buckets.append([sec, 0, 0])
        self._buckets[-1][1 if ok else 2] += 1
        if ok:
            self.good_total += 1
        else:
            self.bad_total += 1

    def window_counts(self, window_s: float, now: float) -> tuple[int, int]:
        lo = now - window_s
        good = bad = 0
        for sec, g, b in self._buckets:
            if sec >= lo:
                good += g
                bad += b
        return good, bad


class SloEngine:
    """Burn-rate accounting for a set of per-tenant objectives."""

    def __init__(self, objectives: dict[str, SloObjective] | None = None, *,
                 fast_window_s: float = DEFAULT_FAST_WINDOW_S,
                 slow_window_s: float = DEFAULT_SLOW_WINDOW_S,
                 fast_burn_threshold: float = DEFAULT_FAST_BURN_THRESHOLD,
                 registry: MetricRegistry | None = None,
                 clock=time.monotonic):
        objectives = dict(objectives or {})
        objectives.setdefault("default", SloObjective())
        self.objectives = objectives
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.fast_burn_threshold = float(fast_burn_threshold)
        self._clock = clock
        self._lock = threading.Lock()
        self._trackers = {name: _Tracker(self.slow_window_s)
                          for name in objectives}
        self._listeners: list = []
        self._burning: set[str] = set()
        if registry is None:
            registry = MetricRegistry("jimm_slo")
            publish(registry)
        self.registry = registry
        self._counters = {}
        for name in objectives:
            self._counters[name] = (
                registry.counter(f"{name}_good_total"),
                registry.counter(f"{name}_bad_total"))
            registry.gauge(f"{name}_fast_burn_rate",
                           lambda t=name: self.burn_rate(
                               t, self.fast_window_s))
            registry.gauge(f"{name}_slow_burn_rate",
                           lambda t=name: self.burn_rate(
                               t, self.slow_window_s))

    @classmethod
    def from_objective_dicts(cls, slo: dict[str, dict],
                             **kwargs) -> "SloEngine":
        """Build from a parsed policy-file ``slo`` section
        (``{tenant: {availability, latency_ms}}``)."""
        return cls({name: SloObjective.from_dict(spec)
                    for name, spec in slo.items()}, **kwargs)

    def _resolve(self, tenant: str | None) -> str:
        return tenant if tenant in self._trackers else "default"

    # -- write -------------------------------------------------------------

    def observe(self, tenant: str | None, ok: bool,
                latency_s: float | None = None) -> bool:
        """Account one finished request; returns whether it counted as good
        (a success slower than the tenant's latency target does not)."""
        name = self._resolve(tenant)
        obj = self.objectives[name]
        good = bool(ok)
        if good and obj.latency_ms is not None and latency_s is not None:
            good = latency_s * 1000.0 <= obj.latency_ms
        now = self._clock()
        with self._lock:
            self._trackers[name].observe(good, now)
        self._counters[name][0 if good else 1].inc()
        if self._listeners:
            self._notify_transitions()
        return good

    # -- burn-rate consumers ------------------------------------------------

    def add_listener(self, fn) -> None:
        """Register a fast-burn *transition* consumer:
        ``fn(tenant, entered, fast_rate, slow_rate)`` fires once when a
        tenant enters fast burn (``entered=True``) and once when it exits
        (``entered=False``). Transitions are evaluated on observations —
        an idle tenant's exit is reported with its next request, which is
        exactly when a consumer could act on it anyway. This is the hook
        the cascade autoscaler hangs capacity decisions on."""
        self._listeners.append(fn)

    def _notify_transitions(self) -> None:
        burning = set(self.fast_burning())
        entered = burning - self._burning
        exited = self._burning - burning
        if not entered and not exited:
            return
        self._burning = burning
        for name in sorted(entered | exited):
            fast = self.burn_rate(name, self.fast_window_s)
            slow = self.burn_rate(name, self.slow_window_s)
            for fn in list(self._listeners):
                try:
                    fn(name, name in entered, fast, slow)
                except Exception:  # noqa: BLE001 — a consumer bug must not fail request accounting; surfaced as a counted error
                    self.registry.counter("listener_errors_total").inc()

    # -- read --------------------------------------------------------------

    def burn_rate(self, tenant: str | None, window_s: float) -> float:
        """bad_fraction(window) / error_budget; 0.0 at zero traffic."""
        name = self._resolve(tenant)
        now = self._clock()
        with self._lock:
            good, bad = self._trackers[name].window_counts(window_s, now)
        total = good + bad
        if total == 0:
            return 0.0
        return (bad / total) / self.objectives[name].error_budget

    def fast_burning(self) -> list[str]:
        """Tenants burning budget fast enough to page: fast-window burn
        over the threshold AND slow-window burn >= 1.0 (multi-window
        guard against blips)."""
        out = []
        for name in self.objectives:
            if (self.burn_rate(name, self.fast_window_s)
                    >= self.fast_burn_threshold
                    and self.burn_rate(name, self.slow_window_s) >= 1.0):
                out.append(name)
        return out

    def snapshot(self) -> dict:
        """The ``/healthz`` block: per-tenant objectives, counts, and both
        burn rates."""
        tenants = {}
        for name, obj in self.objectives.items():
            tr = self._trackers[name]
            tenants[name] = {
                "objective": obj.describe(),
                "good_total": tr.good_total,
                "bad_total": tr.bad_total,
                "fast_burn_rate": round(
                    self.burn_rate(name, self.fast_window_s), 4),
                "slow_burn_rate": round(
                    self.burn_rate(name, self.slow_window_s), 4),
            }
        burning = self.fast_burning()
        return {
            "fast_window_s": self.fast_window_s,
            "slow_window_s": self.slow_window_s,
            "fast_burn_threshold": self.fast_burn_threshold,
            "fast_burning": burning,
            "tenants": tenants,
        }
