"""Content-addressed on-disk store for AOT compile artifacts.

Layout (one directory per fingerprint, two-level fan-out)::

    <root>/
      objects/<fp[:2]>/<fp>/artifact.bin   # the serialized executable
      objects/<fp[:2]>/<fp>/meta.json      # integrity hash + provenance
      quarantine/<fp>-<n>/                 # entries that failed validation

Durability and safety rules:

- **Atomic writes**: payload and meta land in a temp directory that is
  ``os.replace``d into place, so a crashed writer can never leave a
  half-entry a reader would trust.
- **Integrity**: ``meta.json`` records the payload's SHA-256; ``get``
  re-hashes on every read. A mismatch (bit rot, truncation, concurrent
  clobber) quarantines the entry and returns ``None`` — the caller falls
  back to a fresh compile, never to a corrupt executable.
- **Version discipline**: entries whose recorded jax/jaxlib/format version
  disagrees with the running process are quarantined the same way. (The
  fingerprint already folds versions in, so this only triggers on doctored
  or hand-copied stores — but a wrong executable is the one failure mode
  this subsystem must never have.)
- **LRU eviction**: ``artifact.bin``'s mtime is touched on every hit;
  ``gc`` (also run after every ``put``) drops least-recently-used entries
  until the store fits ``max_bytes``.

No jax import anywhere: ``jimm-tpu aot ls``/``gc``/``verify`` stay
pure-host tools, like the obs CLI.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import shutil
import tempfile
import time
from pathlib import Path

from jimm_tpu.aot.keys import AOT_FORMAT_VERSION

__all__ = ["ArtifactStore", "StoreEntry", "DEFAULT_MAX_BYTES"]

#: default size cap; override per-store or with JIMM_AOT_MAX_BYTES
DEFAULT_MAX_BYTES = 2 * 1024 ** 3

_ARTIFACT = "artifact.bin"
_META = "meta.json"


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


@dataclasses.dataclass(frozen=True)
class StoreEntry:
    """One validated (or at least readable) store entry, for ``ls``."""

    fingerprint: str
    path: Path
    size: int
    created: float
    last_used: float
    meta: dict

    def to_row(self) -> dict:
        return {"fingerprint": self.fingerprint, "size": self.size,
                "created": self.created, "last_used": self.last_used,
                **{k: self.meta.get(k) for k in
                   ("label", "bucket", "method", "backend", "jax")}}


class ArtifactStore:
    """See module docstring. All methods are safe to call concurrently from
    multiple processes sharing one root: writes are atomic renames, reads
    re-validate, and losers of a put race simply overwrite with identical
    content (same fingerprint => same bytes)."""

    def __init__(self, root: str | os.PathLike,
                 max_bytes: int | None = None):
        self.root = Path(root).expanduser()
        env_cap = os.environ.get("JIMM_AOT_MAX_BYTES")
        self.max_bytes = (int(max_bytes) if max_bytes is not None
                          else int(env_cap) if env_cap
                          else DEFAULT_MAX_BYTES)
        (self.root / "objects").mkdir(parents=True, exist_ok=True)

    # -- paths ------------------------------------------------------------

    def entry_dir(self, fingerprint: str) -> Path:
        return self.root / "objects" / fingerprint[:2] / fingerprint

    @property
    def quarantine_dir(self) -> Path:
        return self.root / "quarantine"

    # -- write ------------------------------------------------------------

    def put(self, fingerprint: str, payload: bytes,
            meta: dict | None = None) -> Path:
        """Atomically install ``payload`` under ``fingerprint``; returns the
        entry directory. Runs LRU gc afterwards so the store never stays
        over its cap for longer than one put."""
        entry = self.entry_dir(fingerprint)
        entry.parent.mkdir(parents=True, exist_ok=True)
        record = {
            "fingerprint": fingerprint,
            "sha256": _sha256(payload),
            "size": len(payload),
            "created": time.time(),
            "format_version": AOT_FORMAT_VERSION,
            **(meta or {}),
        }
        tmp = Path(tempfile.mkdtemp(prefix=".put-", dir=entry.parent))
        try:
            (tmp / _ARTIFACT).write_bytes(payload)
            (tmp / _META).write_text(json.dumps(record, indent=1,
                                                sort_keys=True))
            if entry.exists():
                # same fingerprint => same content; replace wholesale so a
                # reader never sees a mixed old/new pair
                old = entry.with_name(entry.name + ".old")
                if old.exists():
                    shutil.rmtree(old, ignore_errors=True)
                os.replace(entry, old)
                os.replace(tmp, entry)
                shutil.rmtree(old, ignore_errors=True)
            else:
                os.replace(tmp, entry)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self.gc()
        return entry

    # -- read -------------------------------------------------------------

    def get(self, fingerprint: str, *,
            expect_versions: dict | None = None) -> bytes | None:
        """Validated payload for ``fingerprint``, or ``None``.

        ``None`` means either a clean miss (no entry) or a failed entry —
        failed entries (unreadable meta, hash mismatch, format/version
        mismatch against ``expect_versions``) are moved to quarantine so
        the next lookup is a clean miss. Hits touch the artifact mtime for
        LRU ordering. Use :meth:`contains` to distinguish miss from hit
        without paying the hash."""
        entry = self.entry_dir(fingerprint)
        if not (entry / _ARTIFACT).is_file():
            return None
        reason = None
        payload = None
        try:
            meta = json.loads((entry / _META).read_text())
            payload = (entry / _ARTIFACT).read_bytes()
        except (OSError, ValueError) as e:
            reason = f"unreadable entry: {e}"
        else:
            if meta.get("format_version") != AOT_FORMAT_VERSION:
                reason = (f"format_version {meta.get('format_version')!r} "
                          f"!= {AOT_FORMAT_VERSION}")
            elif _sha256(payload) != meta.get("sha256"):
                reason = "payload sha256 mismatch (corrupt artifact)"
            elif expect_versions:
                for field, expected in expect_versions.items():
                    got = meta.get(field)
                    if got is not None and got != expected:
                        reason = (f"{field} mismatch: entry has {got!r}, "
                                  f"runtime is {expected!r}")
                        break
        if reason is not None:
            self.quarantine(fingerprint, reason)
            return None
        try:
            os.utime(entry / _ARTIFACT)  # LRU touch
        except OSError:
            pass
        return payload

    def contains(self, fingerprint: str) -> bool:
        return (self.entry_dir(fingerprint) / _ARTIFACT).is_file()

    def entries(self) -> list[StoreEntry]:
        out = []
        objects = self.root / "objects"
        for meta_path in sorted(objects.glob(f"??/*/{_META}")):
            entry = meta_path.parent
            try:
                meta = json.loads(meta_path.read_text())
                st = (entry / _ARTIFACT).stat()
            except (OSError, ValueError):
                continue  # half-entry mid-replace or foreign junk; skip
            out.append(StoreEntry(
                fingerprint=entry.name, path=entry, size=st.st_size,
                created=float(meta.get("created", st.st_mtime)),
                last_used=st.st_mtime, meta=meta))
        return out

    @property
    def total_bytes(self) -> int:
        return sum(e.size for e in self.entries())

    # -- maintenance ------------------------------------------------------

    def quarantine(self, fingerprint: str, reason: str) -> Path | None:
        """Move a bad entry aside (never delete — a human may want the
        evidence) and record why. Idempotent under races."""
        entry = self.entry_dir(fingerprint)
        if not entry.exists():
            return None
        self.quarantine_dir.mkdir(parents=True, exist_ok=True)
        for n in range(1000):
            dest = self.quarantine_dir / (f"{fingerprint}-{n}" if n
                                          else fingerprint)
            if not dest.exists():
                break
        try:
            os.replace(entry, dest)
        except OSError:
            return None  # another process got there first
        try:
            (dest / "reason.txt").write_text(reason + "\n")
        except OSError:
            pass
        return dest

    def gc(self, max_bytes: int | None = None) -> list[str]:
        """Evict least-recently-used entries until the store fits the cap.
        Returns evicted fingerprints (oldest first)."""
        cap = self.max_bytes if max_bytes is None else int(max_bytes)
        entries = sorted(self.entries(), key=lambda e: e.last_used)
        total = sum(e.size for e in entries)
        evicted: list[str] = []
        for e in entries:
            if total <= cap:
                break
            shutil.rmtree(e.path, ignore_errors=True)
            total -= e.size
            evicted.append(e.fingerprint)
        return evicted

    def verify(self) -> list[dict]:
        """Re-hash every entry; quarantine failures. Returns one problem
        record per bad entry (empty list == healthy store)."""
        problems = []
        for e in self.entries():
            reason = None
            try:
                payload = (e.path / _ARTIFACT).read_bytes()
            except OSError as exc:
                reason = f"unreadable artifact: {exc}"
            else:
                if e.meta.get("format_version") != AOT_FORMAT_VERSION:
                    reason = (f"format_version "
                              f"{e.meta.get('format_version')!r} != "
                              f"{AOT_FORMAT_VERSION}")
                elif _sha256(payload) != e.meta.get("sha256"):
                    reason = "payload sha256 mismatch"
            if reason is not None:
                self.quarantine(e.fingerprint, reason)
                problems.append({"fingerprint": e.fingerprint,
                                 "reason": reason})
        return problems
