"""JL003 fixtures: optimizer-carrying jit without donation (line 8) and a
train-step builder call without an explicit donate= (line 15)."""

from flax import nnx


@nnx.jit
def train_step(model, optimizer, images, labels):  # line 8: JL003
    del images, labels
    return model, optimizer


def build():
    from jimm_tpu.train import make_contrastive_train_step
    return make_contrastive_train_step("siglip")  # line 15: JL003
