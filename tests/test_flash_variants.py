"""The templated flash-attention family vs XLA einsum oracles.

Three variants share the softmax kernel's tiling/online-normalizer/
custom-vjp scaffolding (`jimm_tpu/ops/flash_attention.py`):

- masked  — per-sample ``(B, Sk)`` key-padding masks (NaFlex, MAP pooling)
- bias    — additive ``(N, Sq, Sk)`` logits bias, differentiable in bias
- sigmoid — no row normalizer (per the sigmoid-attention paper)

Parity runs in Pallas interpret mode on CPU at the ISSUE's seq matrix
(1 / 5 / 257 / 577, f32 + bf16); the TPU cross-lowering tests mirror the
LayerNorm odd-shapes matrix. Block sizes resolve through
``tune.best_config`` on every call here (no explicit block kwargs)."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jimm_tpu.ops.attention import (dot_product_attention,
                                    reference_attention,
                                    reference_sigmoid_attention)
from jimm_tpu.ops.flash_attention import (flash_attention_bias,
                                          flash_attention_masked,
                                          sigmoid_attention)

#: the ISSUE's parity matrix: degenerate single-token, tiny odd, and the
#: ViT-shaped odd lengths that need sequence padding (257 = 16x16 + cls,
#: 577 = 24x24 + cls)
SEQ_LENS = (1, 5, 257, 577)

slow = pytest.mark.slow

#: interpret-mode Pallas is slow on CPU, and tier-1 shares an 870 s budget
#: with the whole suite — so tier-1 keeps one representative of every
#: distinct code path (f32 allclose at tiny/odd/padded lengths, bf16
#: cosine at the padded multi-block lengths) and the redundant corners of
#: the matrix run under ``-m slow``.
FWD_CASES = [
    pytest.param(np.float32, 1, marks=slow),
    (np.float32, 5),
    pytest.param(np.float32, 257, marks=slow),
    (np.float32, 577),
    pytest.param(jnp.bfloat16, 1, marks=slow),
    pytest.param(jnp.bfloat16, 5, marks=slow),
    pytest.param(jnp.bfloat16, 257, marks=slow),
    (jnp.bfloat16, 577),
]

#: 257 is the strongest backward case (odd length -> padded q/k blocks,
#: multi-block online accumulation); the rest of the lengths re-prove the
#: same padding logic the forward matrix already covers
GRAD_SEQS = [pytest.param(1, marks=slow), pytest.param(5, marks=slow),
             257, pytest.param(577, marks=slow)]


def qkv(rng, b=2, s=256, n=2, d=64, dtype=np.float32):
    return tuple(jnp.asarray(rng.randn(b, s, n, d).astype(np.float32) * 0.5,
                             dtype) for _ in range(3))


def key_mask(rng, b, s):
    """Random key-padding mask with >= 1 valid key per sample (an all-
    masked row's forward output is garbage by contract — see the kernel
    module docstring — and is exercised separately below)."""
    m = rng.rand(b, s) > 0.3
    m[:, 0] = True
    return jnp.asarray(m)


def cosine(a, b):
    a = np.asarray(a, np.float64).ravel()
    b = np.asarray(b, np.float64).ravel()
    denom = np.linalg.norm(a) * np.linalg.norm(b)
    return float(a @ b / denom) if denom else 1.0


def ref_bias_attention(q, k, v, bias, *, is_causal=False):
    return reference_attention(q, k, v, is_causal=is_causal,
                               bias=bias[None])


# ---------------------------------------------------------------------------
# forward parity: f32 allclose, bf16 cosine (acceptance criteria)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dtype,s", FWD_CASES)
def test_masked_forward_parity(rng, s, dtype):
    q, k, v = qkv(rng, s=s, dtype=dtype)
    mask = key_mask(rng, 2, s)
    out = flash_attention_masked(q, k, v, mask)
    ref = reference_attention(q, k, v, mask=mask[:, None, None, :])
    assert out.dtype == q.dtype
    if dtype == np.float32:
        np.testing.assert_allclose(out, ref, atol=3e-5)
    else:
        assert cosine(out, ref) >= 0.999


@pytest.mark.parametrize("dtype,s", FWD_CASES)
def test_bias_forward_parity(rng, s, dtype):
    q, k, v = qkv(rng, s=s, dtype=dtype)
    bias = jnp.asarray(rng.randn(2, s, s).astype(np.float32) * 0.3)
    out = flash_attention_bias(q, k, v, bias)
    ref = ref_bias_attention(q, k, v, bias)
    if dtype == np.float32:
        np.testing.assert_allclose(out, ref, atol=3e-5)
    else:
        assert cosine(out, ref) >= 0.999


@pytest.mark.parametrize("dtype,s", FWD_CASES)
def test_sigmoid_forward_parity(rng, s, dtype):
    q, k, v = qkv(rng, s=s, dtype=dtype)
    out = sigmoid_attention(q, k, v)
    ref = reference_sigmoid_attention(q, k, v)
    if dtype == np.float32:
        np.testing.assert_allclose(out, ref, atol=3e-5)
    else:
        assert cosine(out, ref) >= 0.999


@pytest.mark.parametrize("s", [pytest.param(5, marks=slow), 257])
def test_masked_causal_forward(rng, s):
    q, k, v = qkv(rng, s=s)
    mask = key_mask(rng, 2, s)
    out = flash_attention_masked(q, k, v, mask, is_causal=True)
    ref = reference_attention(q, k, v, is_causal=True,
                              mask=mask[:, None, None, :])
    np.testing.assert_allclose(out, ref, atol=3e-5)


@pytest.mark.parametrize("s", [pytest.param(5, marks=slow), 257])
def test_sigmoid_masked_causal_forward(rng, s):
    q, k, v = qkv(rng, s=s)
    mask = key_mask(rng, 2, s)
    out = sigmoid_attention(q, k, v, mask=mask, is_causal=True)
    ref = reference_sigmoid_attention(q, k, v, mask=mask, is_causal=True)
    np.testing.assert_allclose(out, ref, atol=3e-5)


def test_sigmoid_default_logit_bias_is_log_sk(rng):
    """The paper's init: logit_bias = -log(Sk) matches softmax's 1/Sk row
    mass at uniform scores."""
    q, k, v = qkv(rng, s=64)
    np.testing.assert_allclose(
        sigmoid_attention(q, k, v),
        np.asarray(reference_sigmoid_attention(
            q, k, v, logit_bias=-math.log(64))), atol=3e-5)


def test_sigmoid_fully_masked_rows_are_exactly_zero(rng):
    """sigmoid(NEG_INF) underflows to 0 — unlike softmax-masked, a row with
    no valid key yields exactly zero output, no garbage."""
    q, k, v = qkv(rng, s=16)
    mask = np.ones((2, 16), bool)
    mask[1, :] = False
    out = np.asarray(sigmoid_attention(q, k, v, mask=jnp.asarray(mask)))
    assert np.all(out[1] == 0.0)
    assert np.any(out[0] != 0.0)


def test_masked_fully_masked_rows_zero_grad_when_downstream_masks(rng):
    """The NaFlex contract: garbage rows are fine iff downstream masking
    zeroes their cotangent — then NO gradient flows through them."""
    q, k, v = qkv(rng, s=8)
    mask = np.ones((2, 8), bool)
    mask[1, 4:] = False  # sample 1: keys 4..7 padded

    def loss(q, k, v):
        o = flash_attention_masked(q, k, v, jnp.asarray(mask))
        # downstream masking, as MAP pooling / NaFlex do
        return jnp.sum((o * jnp.asarray(mask)[:, :, None, None]) ** 2)

    dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    # padded queries get zero dq; padded keys get zero dk/dv
    assert np.all(np.asarray(dq)[1, 4:] == 0.0)
    assert np.all(np.asarray(dk)[1, 4:] == 0.0)
    assert np.all(np.asarray(dv)[1, 4:] == 0.0)
    assert np.any(np.asarray(dq)[0] != 0.0)


# ---------------------------------------------------------------------------
# backward parity
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("s", GRAD_SEQS)
def test_masked_grad_parity(rng, s):
    q, k, v = qkv(rng, s=s)
    mask = key_mask(rng, 2, s)

    def flash_loss(q, k, v):
        return jnp.sum(flash_attention_masked(q, k, v, mask) ** 2)

    def ref_loss(q, k, v):
        return jnp.sum(reference_attention(
            q, k, v, mask=mask[:, None, None, :]) ** 2)

    gf = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, ("dq", "dk", "dv")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, err_msg=name)


@pytest.mark.parametrize("s", GRAD_SEQS)
def test_bias_grad_parity(rng, s):
    """dbias runs the dedicated batch-innermost accumulation kernel — the
    variant's whole point is differentiability in the bias without a dense
    (B, N, Sq, Sk) tensor."""
    q, k, v = qkv(rng, s=s)
    bias = jnp.asarray(rng.randn(2, s, s).astype(np.float32) * 0.3)

    def flash_loss(q, k, v, bias):
        return jnp.sum(flash_attention_bias(q, k, v, bias) ** 2)

    def ref_loss(q, k, v, bias):
        return jnp.sum(ref_bias_attention(q, k, v, bias) ** 2)

    gf = jax.grad(flash_loss, argnums=(0, 1, 2, 3))(q, k, v, bias)
    gr = jax.grad(ref_loss, argnums=(0, 1, 2, 3))(q, k, v, bias)
    for a, b, name in zip(gf, gr, ("dq", "dk", "dv", "dbias")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, err_msg=name)


@pytest.mark.parametrize("s", GRAD_SEQS)
def test_sigmoid_grad_parity(rng, s):
    q, k, v = qkv(rng, s=s)

    def flash_loss(q, k, v):
        return jnp.sum(sigmoid_attention(q, k, v) ** 2)

    def ref_loss(q, k, v):
        return jnp.sum(reference_sigmoid_attention(q, k, v) ** 2)

    gf = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, ("dq", "dk", "dv")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, err_msg=name)


def test_bias_grad_flows_through_broadcast(rng):
    """A (Sq, Sk) bias (head-shared) must receive the head-summed
    gradient — grads flow back through the broadcast."""
    q, k, v = qkv(rng, s=8)
    bias2 = jnp.asarray(rng.randn(8, 8).astype(np.float32) * 0.3)

    def flash_loss(bias):
        return jnp.sum(flash_attention_bias(q, k, v, bias) ** 2)

    def ref_loss(bias):
        return jnp.sum(reference_attention(
            q, k, v, bias=bias[None, None]) ** 2)

    np.testing.assert_allclose(
        np.asarray(jax.grad(flash_loss)(bias2)),
        np.asarray(jax.grad(ref_loss)(bias2)), atol=5e-4)


# ---------------------------------------------------------------------------
# dispatch (ops/attention.py)
# ---------------------------------------------------------------------------

class TestDispatch:
    def test_flash_impl_routes_key_padding_mask(self, rng):
        """impl='flash' + key-padding mask runs the masked variant instead
        of raising (the old hard rejection)."""
        q, k, v = qkv(rng, s=64)
        mask = key_mask(rng, 2, 64)
        out = dot_product_attention(q, k, v, mask=mask[:, None, None, :],
                                    impl="flash")
        ref = reference_attention(q, k, v, mask=mask[:, None, None, :])
        np.testing.assert_allclose(out, ref, atol=3e-5)

    @slow
    def test_flash_masked_impl(self, rng):
        """Same route as test_flash_impl_routes_key_padding_mask, spelled
        explicitly."""
        q, k, v = qkv(rng, s=64)
        mask = key_mask(rng, 2, 64)
        out = dot_product_attention(q, k, v, mask=mask[:, None, None, :],
                                    impl="flash_masked")
        ref = reference_attention(q, k, v, mask=mask[:, None, None, :])
        np.testing.assert_allclose(out, ref, atol=3e-5)

    def test_flash_bias_impl(self, rng):
        q, k, v = qkv(rng, s=64)
        bias = jnp.asarray(rng.randn(2, 64, 64).astype(np.float32) * 0.3)
        out = dot_product_attention(q, k, v, bias=bias, impl="flash_bias")
        np.testing.assert_allclose(out, ref_bias_attention(q, k, v, bias),
                                   atol=3e-5)

    def test_sigmoid_impl(self, rng):
        q, k, v = qkv(rng, s=64)
        out = dot_product_attention(q, k, v, impl="sigmoid")
        np.testing.assert_allclose(out, reference_sigmoid_attention(q, k, v),
                                   atol=3e-5)

    def test_xla_accepts_bias(self, rng):
        q, k, v = qkv(rng, s=32)
        bias = jnp.asarray(rng.randn(2, 32, 32).astype(np.float32) * 0.3)
        out = dot_product_attention(q, k, v, bias=bias[None], impl="xla")
        np.testing.assert_allclose(out, ref_bias_attention(q, k, v, bias),
                                   atol=2e-5)

    def test_arbitrary_mask_on_flash_names_xla(self, rng):
        q, k, v = qkv(rng, s=16)
        full = jnp.ones((2, 2, 16, 16), bool)
        with pytest.raises(ValueError, match="key-padding masks only"):
            dot_product_attention(q, k, v, mask=full, impl="flash")

    def test_ring_masked_needs_mesh_dense_mask_names_xla(self, rng):
        # key-padding masks are now first-class on the seqpar ring — but
        # an explicit impl="ring" still demands a seq mesh to run on
        q, k, v = qkv(rng, s=16)
        mask = jnp.ones((2, 1, 1, 16), bool)
        with pytest.raises(ValueError, match="mesh"):
            dot_product_attention(q, k, v, mask=mask, impl="ring")
        # arbitrary dense masks stay rejected, pointing at impl="xla"
        full = jnp.ones((2, 2, 16, 16), bool)
        with pytest.raises(ValueError, match="xla"):
            dot_product_attention(q, k, v, mask=full, impl="ring")

    def test_flash_masked_requires_mask(self, rng):
        q, k, v = qkv(rng, s=16)
        with pytest.raises(ValueError, match="requires a key-padding"):
            dot_product_attention(q, k, v, impl="flash_masked")

    def test_flash_bias_requires_bias(self, rng):
        q, k, v = qkv(rng, s=16)
        with pytest.raises(ValueError, match="requires a bias"):
            dot_product_attention(q, k, v, impl="flash_bias")


# ---------------------------------------------------------------------------
# TPU cross-lowering (mirrors the LayerNorm odd-shapes matrix)
# ---------------------------------------------------------------------------

#: (dtype, batch, seq, heads, head_dim) — odd seq lengths that need block
#: padding, plus off-tile head dims the wrapper lane-pads (80 -> 128);
#: tier-1 keeps the multi-block f32 case and the padded-head-dim bf16 case
#: per variant, the rest of the matrix runs under ``-m slow``
LOWER_CASES = [
    pytest.param("float32", 1, 5, 2, 64, marks=slow),
    ("float32", 2, 257, 2, 64),
    pytest.param("float32", 2, 577, 2, 80, marks=slow),
    pytest.param("bfloat16", 1, 5, 2, 64, marks=slow),
    pytest.param("bfloat16", 2, 257, 2, 64, marks=slow),
    ("bfloat16", 2, 577, 2, 80),
]


def _lower_grad_for_tpu(flash_loss, *args):
    fn = jax.jit(jax.grad(flash_loss, argnums=tuple(range(len(args)))))
    fn.trace(*args).lower(lowering_platforms=("tpu",))  # must not raise


@pytest.mark.parametrize("dtype,b,s,n,d", LOWER_CASES)
def test_masked_lowers_for_tpu(b, s, n, d, dtype):
    dt = jnp.dtype(dtype)
    qs = jax.ShapeDtypeStruct((b, s, n, d), dt)
    mask = jnp.ones((b, s), bool)

    def loss(q, k, v):
        o = flash_attention_masked(q, k, v, mask)
        return jnp.sum(o.astype(jnp.float32))

    _lower_grad_for_tpu(loss, qs, qs, qs)


@pytest.mark.parametrize("dtype,b,s,n,d", LOWER_CASES)
def test_bias_lowers_for_tpu(b, s, n, d, dtype):
    dt = jnp.dtype(dtype)
    qs = jax.ShapeDtypeStruct((b, s, n, d), dt)
    bs = jax.ShapeDtypeStruct((n, s, s), jnp.float32)

    def loss(q, k, v, bias):
        o = flash_attention_bias(q, k, v, bias)
        return jnp.sum(o.astype(jnp.float32))

    _lower_grad_for_tpu(loss, qs, qs, qs, bs)


@pytest.mark.parametrize("dtype,b,s,n,d", LOWER_CASES)
def test_sigmoid_lowers_for_tpu(b, s, n, d, dtype):
    dt = jnp.dtype(dtype)
    qs = jax.ShapeDtypeStruct((b, s, n, d), dt)

    def loss(q, k, v):
        o = sigmoid_attention(q, k, v)
        return jnp.sum(o.astype(jnp.float32))

    _lower_grad_for_tpu(loss, qs, qs, qs)


# ---------------------------------------------------------------------------
# NaFlex acceptance: flash-masked forward with zero dense score tensors
# ---------------------------------------------------------------------------

def _tiny_naflex_tower(attn_impl):
    from flax import nnx

    from jimm_tpu.configs import VisionConfig
    from jimm_tpu.nn.vision import VisionTower
    cfg = VisionConfig(image_size=16, patch_size=4, width=16, depth=2,
                       num_heads=2, mlp_dim=32, pooling="map",
                       pre_norm=False, attn_impl=attn_impl)
    return VisionTower(cfg, nnx.Rngs(0))


def test_forward_naflex_flash_masked_no_dense_scores():
    """The acceptance criterion: forward_naflex on the masked flash variant
    lowers for TPU with NO dense (B, N, S, S) score materialization — the
    lowered program must not contain an SxS-shaped tensor."""
    tower = _tiny_naflex_tower("flash_masked")
    S = 347  # distinctive odd length: "347x347" can't appear by accident
    patches = jax.ShapeDtypeStruct((2, S, 4 * 4 * 3), jnp.float32)
    shapes = jnp.asarray([[13, 17], [9, 11]], jnp.int32)
    mask = np.zeros((2, S), bool)
    mask[0, :13 * 17] = True
    mask[1, :9 * 11] = True
    mask = jnp.asarray(mask)

    from flax import nnx
    graphdef, state = nnx.split(tower)

    @jax.jit
    def fwd(state, p):
        return nnx.merge(graphdef, state).forward_naflex(p, shapes, mask)

    lowered = fwd.trace(state, patches).lower(lowering_platforms=("tpu",))
    txt = lowered.as_text()
    assert f"{S}x{S}" not in txt, \
        "dense (.., S, S) attention scores were materialized"


def test_forward_naflex_flash_masked_matches_dense(rng):
    """Flash-vs-dense oracle on an odd-grid NaFlex batch (padded rows
    all-masked): two towers built from the same seed are weight-identical,
    so the only difference is the attention kernel."""
    dense = _tiny_naflex_tower("xla")
    flash = _tiny_naflex_tower("flash_masked")
    S = 36
    patches = np.zeros((2, S, 4 * 4 * 3), np.float32)
    patches[0, :5 * 7] = rng.randn(35, 48).astype(np.float32)
    patches[1, :3 * 11] = rng.randn(33, 48).astype(np.float32)
    shapes = jnp.asarray([[5, 7], [3, 11]], jnp.int32)
    mask = np.zeros((2, S), bool)
    mask[0, :35] = True
    mask[1, :33] = True
    out_dense = dense.forward_naflex(jnp.asarray(patches), shapes,
                                     jnp.asarray(mask))
    out_flash = flash.forward_naflex(jnp.asarray(patches), shapes,
                                     jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(out_flash),
                               np.asarray(out_dense), atol=2e-4)
