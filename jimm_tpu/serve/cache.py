"""LRU cache for text-tower class/prompt embeddings.

Zero-shot classification against a fixed label set pays the text tower once
per *label set*, not once per request: the ensemble classifier weights from
``utils/zero_shot.py`` depend only on (model, tokenized prompts). Keying a
small LRU on exactly that tuple lets repeat label sets skip the text encoder
entirely — the inference hot path stays the single ``(B, D) @ (D, C)``
matmul. Values are host ``np.ndarray``s (not device buffers) so a cache full
of stale label sets never pins HBM.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Callable

import numpy as np


def prompt_set_key(model_key: str, rows) -> str:
    """Stable cache key for a prompt set under one model.

    ``model_key`` names the weights (checkpoint path / preset + dtype);
    ``rows`` is the ``(N, L)`` int token matrix — its bytes subsume both the
    tokenizer (same text, different tokenizer => different ids) and the
    prompt set itself.
    """
    rows = np.ascontiguousarray(np.asarray(rows, np.int64))
    h = hashlib.sha256()
    h.update(model_key.encode())
    h.update(str(rows.shape).encode())
    h.update(rows.tobytes())
    return h.hexdigest()


class EmbeddingCache:
    """Thread-safe LRU mapping prompt-set keys to embedding matrices.

    Hit/miss/eviction counters feed the serve metrics (`cache_hit_rate` in
    ``/metrics``); ``get_or_build`` is the only API the hot path needs.
    """

    def __init__(self, capacity: int = 32):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._data: OrderedDict[str, np.ndarray] = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._data)

    def get(self, key: str) -> np.ndarray | None:
        with self._lock:
            value = self._data.get(key)
            if value is None:
                self.misses += 1
                return None
            self._data.move_to_end(key)
            self.hits += 1
            return value

    def put(self, key: str, value: np.ndarray) -> None:
        with self._lock:
            if key in self._data:
                self._data.move_to_end(key)
            self._data[key] = value
            while len(self._data) > self.capacity:
                self._data.popitem(last=False)
                self.evictions += 1

    def get_or_build(self, key: str,
                     builder: Callable[[], np.ndarray]) -> np.ndarray:
        """Return the cached value, building (and inserting) it on a miss.
        The builder runs outside the lock — a slow text-tower encode must
        not serialize unrelated lookups."""
        value = self.get(key)
        if value is not None:
            return value
        value = np.asarray(builder())
        self.put(key, value)
        return value

    @property
    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def stats(self) -> dict:
        return {"cache_entries": len(self._data), "cache_hits": self.hits,
                "cache_misses": self.misses, "cache_evictions": self.evictions,
                "cache_hit_rate": round(self.hit_rate, 4)}


#: process-wide default cache for class embeddings, shared by the CLI
#: `classify` command (repeat invocations in one process reuse weights) and
#: the serving stack's zero-shot endpoint
_DEFAULT_CACHE: EmbeddingCache | None = None
_DEFAULT_LOCK = threading.Lock()


def class_embedding_cache() -> EmbeddingCache:
    global _DEFAULT_CACHE
    with _DEFAULT_LOCK:
        if _DEFAULT_CACHE is None:
            _DEFAULT_CACHE = EmbeddingCache(capacity=32)
        return _DEFAULT_CACHE
