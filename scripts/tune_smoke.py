"""CI tier-1 smoke for the persistent kernel autotuner.

Three invariants, asserted end to end on CPU (interpret-mode Pallas):

1. **Cold tune**: ``jimm-tpu tune run`` core (`tune_kernel`) measures the
   layer_norm candidate space at a small shape and persists the winner in
   a tmp cache — at least one measurement, a config on disk. The same
   cold→warm pair then covers one attention-family variant
   (``flash_attention_masked``, fwd+bwd through its own kernels) end to
   end, so a variant registration that breaks keying or benching fails CI.
2. **Warm process**: a SECOND subprocess resolves the same (kernel, shape,
   dtype) through ``best_config`` against that cache and must report a pure
   hit — ``jimm_tune_hit_total == 1`` and ``jimm_tune_measure_total == 0``
   (zero re-measurements; the cross-process key-stability contract).
3. **Host-only CLI**: ``jimm-tpu tune ls`` lists the cache without
   importing jax (asserted via ``sys.modules`` in the subprocess).

Exits nonzero (with a JSON error line) on any violation.

Usage:
    JAX_PLATFORMS=cpu python -m scripts.tune_smoke
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

SHAPES = ((32, 128),)
DTYPES = ("float32",)

#: small enough that interpret-mode fwd+bwd benching of the one feasible
#: candidate (seq 64 -> a single 128 block) stays a few seconds
MASKED_SHAPES = ((1, 64, 2, 64),) * 3
MASKED_DTYPES = ("float32",) * 3


def fail(msg: str) -> int:
    print(json.dumps({"metric": "tune_smoke", "value": 0.0, "error": msg}),
          flush=True)
    return 1


def run(code: str, root: str) -> dict:
    env = dict(os.environ, JIMM_TUNE_CACHE=root, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(f"subprocess failed: {proc.stderr[-1500:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


COLD_TMPL = """
import json
from jimm_tpu import obs
from jimm_tpu.tune import tune_kernel
report = tune_kernel(%r, %r, %r)
snap = obs.get_registry("jimm_tune").snapshot()
print(json.dumps({"config": report["config"],
                  "candidates": report["candidates"],
                  "fingerprint": report["fingerprint"],
                  "measures": snap.get("measure_total", 0)}))
"""

WARM_TMPL = """
import json
from jimm_tpu import obs
from jimm_tpu.tune import best_config
cfg = best_config(%r, %r, %r)
snap = obs.get_registry("jimm_tune").snapshot()
print(json.dumps({"config": cfg,
                  "hits": snap.get("hit_total", 0),
                  "misses": snap.get("miss_total", 0),
                  "measures": snap.get("measure_total", 0)}))
"""

COLD = COLD_TMPL % ("layer_norm", SHAPES, DTYPES)
WARM = WARM_TMPL % ("layer_norm", SHAPES, DTYPES)
COLD_MASKED = COLD_TMPL % ("flash_attention_masked", MASKED_SHAPES,
                           MASKED_DTYPES)
WARM_MASKED = WARM_TMPL % ("flash_attention_masked", MASKED_SHAPES,
                           MASKED_DTYPES)

LS = """
import json, sys
from jimm_tpu.tune.cli import main
rc = main(["tune", "ls"])
print(json.dumps({"rc": rc, "jax_imported": "jax" in sys.modules}))
"""


def main() -> int:
    with tempfile.TemporaryDirectory(prefix="jimm-tune-smoke-") as root:
        # --- cold: measure + persist --------------------------------------
        cold = run(COLD, root)
        if cold["measures"] < 1 or cold["candidates"] < 1:
            return fail(f"cold tune measured nothing: {cold}")
        if "block_rows" not in cold["config"]:
            return fail(f"cold tune returned no block_rows: {cold}")

        # --- warm: fresh process, pure cache hit, zero measurements -------
        warm = run(WARM, root)
        if warm["config"] != cold["config"]:
            return fail(f"warm lookup config {warm['config']} != tuned "
                        f"{cold['config']} (key instability across "
                        f"processes?)")
        if warm["hits"] != 1 or warm["misses"] != 0:
            return fail(f"warm lookup was not a pure hit: {warm}")
        if warm["measures"] != 0:
            return fail(f"warm lookup re-measured {warm['measures']} "
                        f"times; the hot path must be lookup-only")

        # --- attention-variant kernel: cold tune -> warm pure hit ---------
        vcold = run(COLD_MASKED, root)
        if vcold["measures"] < 1 or vcold["candidates"] < 1:
            return fail(f"masked-flash cold tune measured nothing: {vcold}")
        if "block_q" not in vcold["config"] \
                or "block_k" not in vcold["config"]:
            return fail(f"masked-flash tune returned no blocks: {vcold}")
        vwarm = run(WARM_MASKED, root)
        if vwarm["config"] != vcold["config"]:
            return fail(f"masked-flash warm config {vwarm['config']} != "
                        f"tuned {vcold['config']}")
        if vwarm["hits"] != 1 or vwarm["misses"] != 0 \
                or vwarm["measures"] != 0:
            return fail(f"masked-flash warm lookup was not a pure hit: "
                        f"{vwarm}")

        # --- tune ls stays jax-free ---------------------------------------
        ls = run(LS, root)
        if ls["rc"] != 0:
            return fail(f"`tune ls` exited {ls['rc']}")
        if ls["jax_imported"]:
            return fail("`tune ls` imported jax on the host-only path")

        print(json.dumps({"metric": "tune_smoke", "value": 1.0,
                          "config": cold["config"],
                          "candidates": cold["candidates"],
                          "cold_measures": cold["measures"],
                          "warm_measures": warm["measures"],
                          "variant_config": vcold["config"],
                          "variant_warm_measures": vwarm["measures"]}),
              flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
