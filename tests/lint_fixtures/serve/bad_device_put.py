"""JL010 fixture: unplaced device_put in sharding-sensitive serve code."""
import jax
import numpy as np


def forward_batch(padded, batch_sharding):
    x = jax.device_put(np.asarray(padded))    # JL010: lands on device 0
    y = jax.device_put(padded)                # JL010: same, bare alias form
    # ok: explicit placements, positional and keyword
    a = jax.device_put(padded, batch_sharding)
    b = jax.device_put(padded, sharding=batch_sharding)
    c = jax.device_put(padded, device=jax.devices()[0])
    # ok: a justified default placement
    d = jax.device_put(padded)  # jaxlint: disable=JL010 placement asserted by caller
    return x, y, a, b, c, d
