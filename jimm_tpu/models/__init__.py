"""Public model API (parity with ref `src/jimm/models/__init__.py:1-9`)."""

from jimm_tpu.models.clip import CLIP
from jimm_tpu.models.siglip import SigLIP
from jimm_tpu.models.vit import VisionTransformer

__all__ = ["VisionTransformer", "CLIP", "SigLIP"]
