"""NaFlex (variable-resolution SigLIP2) vs the HF ``Siglip2Model`` oracle.

The reference supports "SigLIP v1 and v2, any non-NaFlex variant"
(ref `README.md:13-14`) — the NaFlex path here is beyond-reference
capability, so parity is anchored directly to HF torch semantics:
- position-table resize == ``F.interpolate(bilinear, align_corners=False,
  antialias=True)`` (exact filter math, not a lookalike),
- full vision-tower + logits parity on a mixed-resolution padded batch,
- host-side patchify == HF ``Siglip2ImageProcessor`` grid/rounding rules.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from hf_util import save_tiny_siglip2


def _torch_resize_table(table: np.ndarray, h: int, w: int) -> np.ndarray:
    import torch
    import torch.nn.functional as F
    t = torch.tensor(table).permute(2, 0, 1).unsqueeze(0)
    out = F.interpolate(t, size=(h, w), mode="bilinear",
                        align_corners=False, antialias=True)
    return out[0].permute(1, 2, 0).reshape(h * w, -1).numpy()


@pytest.mark.parametrize("hw", [(16, 16), (8, 32), (3, 5), (20, 10), (1, 64)])
def test_position_embedding_matches_torch_interpolate(rng, hw):
    from jimm_tpu.nn.naflex import naflex_position_embedding
    h, w = hw
    table = rng.randn(16, 16, 8).astype(np.float32)
    seq = h * w
    ours = np.asarray(naflex_position_embedding(
        jnp.asarray(table), jnp.asarray([[h, w]], jnp.int32), seq))[0]
    ref = _torch_resize_table(table, h, w)
    np.testing.assert_allclose(ours, ref, atol=1e-5)


def _mixed_batch(rng, patch=16, max_patches=4):
    """Two samples: a full 2x2 grid and a padded 1x2 grid."""
    full = rng.randn(4, patch * patch * 3).astype(np.float32)
    half = rng.randn(2, patch * patch * 3).astype(np.float32)
    patches = np.zeros((2, max_patches, patch * patch * 3), np.float32)
    patches[0] = full
    patches[1, :2] = half
    shapes = np.asarray([[2, 2], [1, 2]], np.int32)
    mask = np.asarray([[1, 1, 1, 1], [1, 1, 0, 0]], bool)
    return patches, shapes, mask


def test_naflex_matches_hf_siglip2_oracle(rng, tmp_path):
    import torch
    from transformers import Siglip2Model

    d = save_tiny_siglip2(tmp_path / "ckpt")
    hf = Siglip2Model.from_pretrained(d).eval()

    from jimm_tpu import SigLIP
    model = SigLIP.from_pretrained(d)

    patches, shapes, mask = _mixed_batch(rng)
    with torch.no_grad():
        ref_img = hf.get_image_features(
            pixel_values=torch.tensor(patches),
            pixel_attention_mask=torch.tensor(mask.astype(np.int64)),
            spatial_shapes=torch.tensor(shapes.astype(np.int64))).numpy()
    ours_img = np.asarray(model.encode_image_naflex(
        jnp.asarray(patches), jnp.asarray(shapes), jnp.asarray(mask)))
    np.testing.assert_allclose(ours_img, ref_img, atol=2e-4)

    # full contrastive logits over the NaFlex image batch
    txt = rng.randint(1, 90, size=(2, 8)).astype(np.int64)
    with torch.no_grad():
        ref_logits = hf(input_ids=torch.tensor(txt),
                        pixel_values=torch.tensor(patches),
                        pixel_attention_mask=torch.tensor(
                            mask.astype(np.int64)),
                        spatial_shapes=torch.tensor(shapes.astype(np.int64)),
                        ).logits_per_image.numpy()
    ours_logits = np.asarray(model.logits_naflex(
        jnp.asarray(patches), jnp.asarray(shapes), jnp.asarray(mask),
        jnp.asarray(txt, jnp.int32)))
    np.testing.assert_allclose(ours_logits, ref_logits, atol=2e-3)


def test_padding_values_cannot_leak(rng, tmp_path):
    from jimm_tpu import SigLIP
    d = save_tiny_siglip2(tmp_path / "ckpt")
    model = SigLIP.from_pretrained(d)
    patches, shapes, mask = _mixed_batch(rng)
    base = np.asarray(model.encode_image_naflex(
        jnp.asarray(patches), jnp.asarray(shapes), jnp.asarray(mask)))
    poisoned = patches.copy()
    poisoned[1, 2:] = 1e4  # garbage in the masked pad region
    out = np.asarray(model.encode_image_naflex(
        jnp.asarray(poisoned), jnp.asarray(shapes), jnp.asarray(mask)))
    np.testing.assert_allclose(out, base, atol=1e-5)
    assert np.isfinite(out).all()


def test_uniform_grid_matches_v1_path(rng, tmp_path):
    """At a sample's native square grid with no padding, the NaFlex path
    must reproduce the fixed-resolution encode_image exactly (the pos-table
    resize is the identity there)."""
    from jimm_tpu import SigLIP
    from jimm_tpu.data.naflex import image_to_patches
    d = save_tiny_siglip2(tmp_path / "ckpt")
    model = SigLIP.from_pretrained(d)
    images = rng.randn(2, 32, 32, 3).astype(np.float32)
    v1 = np.asarray(model.encode_image(jnp.asarray(images)))
    patches = np.stack([image_to_patches(im, 16) for im in images])
    shapes = np.asarray([[2, 2]] * 2, np.int32)
    mask = np.ones((2, 4), bool)
    ours = np.asarray(model.encode_image_naflex(
        jnp.asarray(patches), jnp.asarray(shapes), jnp.asarray(mask)))
    np.testing.assert_allclose(ours, v1, atol=1e-4)


@pytest.mark.parametrize("size", [(37, 211), (1024, 64), (16, 16), (999, 3)])
def test_target_size_matches_hf_processor(size):
    from transformers.models.siglip2.image_processing_siglip2 import (
        get_image_size_for_max_num_patches)

    from jimm_tpu.data.naflex import target_size_for_max_patches
    ours = target_size_for_max_patches(size[0], size[1], 16, 256)
    ref = get_image_size_for_max_num_patches(size[0], size[1], 16, 256)
    assert ours == tuple(ref)


def test_patch_layout_matches_hf_processor(rng):
    from transformers.models.siglip2.image_processing_siglip2 import (
        convert_image_to_patches)

    from jimm_tpu.data.naflex import image_to_patches
    im = rng.randn(48, 32, 3).astype(np.float32)
    np.testing.assert_array_equal(image_to_patches(im, 16),
                                  convert_image_to_patches(im, 16))


def test_patchify_naflex_end_to_end(rng):
    from jimm_tpu.data.naflex import patchify_naflex
    images = [rng.randn(40, 80, 3).astype(np.float32),
              rng.randn(64, 64, 3).astype(np.float32)]
    patches, shapes, mask = patchify_naflex(images, patch_size=16,
                                            max_num_patches=16)
    assert patches.shape == (2, 16, 16 * 16 * 3)
    assert mask.shape == (2, 16)
    for i in range(2):
        n = int(shapes[i, 0] * shapes[i, 1])
        assert n <= 16
        assert mask[i, :n].all() and not mask[i, n:].any()
        assert (patches[i, n:] == 0).all()


def test_refuses_naflex_after_load_time_pos_resample(rng, tmp_path):
    """An image_size override interpolates the stored table at load; a second
    per-sample resample would diverge from the checkpoint, so the NaFlex
    path must refuse rather than silently double-resample."""
    from jimm_tpu import SigLIP
    d = save_tiny_siglip2(tmp_path / "ckpt")
    model = SigLIP.from_pretrained(d, image_size=64)  # native is 32
    patches, shapes, mask = _mixed_batch(rng)
    with pytest.raises(ValueError, match="native image_size"):
        model.encode_image_naflex(jnp.asarray(patches), jnp.asarray(shapes),
                                  jnp.asarray(mask))


def test_naflex_contrastive_training_step(rng, tmp_path):
    """The shared loss dispatch accepts a NaFlex triple for images: the
    masked path is trainable (finite grads, loss moves) and at a uniform
    unpadded grid its loss equals the fixed-resolution path's exactly."""
    from flax import nnx

    from jimm_tpu import SigLIP
    from jimm_tpu.data.naflex import image_to_patches
    from jimm_tpu.train import (OptimizerConfig, make_contrastive_train_step,
                                make_optimizer)
    d = save_tiny_siglip2(tmp_path / "ckpt")
    model = SigLIP.from_pretrained(d)
    opt = make_optimizer(model, OptimizerConfig(learning_rate=1e-3))
    step = make_contrastive_train_step("siglip")
    txt = jnp.asarray(rng.randint(1, 90, size=(2, 8)), jnp.int32)

    images = rng.randn(2, 32, 32, 3).astype(np.float32)
    patches = np.stack([image_to_patches(im, 16) for im in images])
    nf = (jnp.asarray(patches), jnp.asarray([[2, 2]] * 2, jnp.int32),
          jnp.ones((2, 4), bool))
    from jimm_tpu.train.trainer import contrastive_loss_fn
    l_nf = float(contrastive_loss_fn(model, nf, txt, kind="siglip"))
    l_v1 = float(contrastive_loss_fn(model, jnp.asarray(images), txt,
                                     kind="siglip"))
    np.testing.assert_allclose(l_nf, l_v1, rtol=1e-5)

    # padded mixed-resolution batch trains: loss decreases over a few steps
    p, s, m = _mixed_batch(rng)
    nf = (jnp.asarray(p), jnp.asarray(s), jnp.asarray(m))
    losses = [float(step(model, opt, nf, txt)["loss"]) for _ in range(5)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_naflex_file_dataset_to_train_step(rng, tmp_path):
    """tfrecords of mixed-size images -> naflex_image_text_batches ->
    contrastive train step: the full file-to-gradient NaFlex loop."""
    from jimm_tpu import SigLIP
    from jimm_tpu.data.records import (naflex_image_text_batches,
                                       write_image_text_records)
    from jimm_tpu.train import (OptimizerConfig,
                                make_contrastive_train_step, make_optimizer)
    pairs = []
    for i, (h, w) in enumerate([(16, 48), (32, 32), (48, 16), (16, 16)]):
        img = rng.randint(0, 255, size=(h, w, 3)).astype(np.uint8)
        pairs.append((img, [i + 1, i + 2]))
    write_image_text_records(tmp_path / "d.tfrecord", pairs, encoding="raw")

    batches = naflex_image_text_batches(
        str(tmp_path / "d.tfrecord"), 2, patch_size=16, max_num_patches=4,
        seq_len=8, repeat=False, shuffle_buffer=0)
    d = save_tiny_siglip2(tmp_path / "ckpt")
    model = SigLIP.from_pretrained(d)
    opt = make_optimizer(model, OptimizerConfig(learning_rate=1e-3))
    step = make_contrastive_train_step("siglip")
    seen = 0
    shapes_seen = set()
    for (patches, shapes, mask), tokens in batches:
        assert patches.shape[1:] == (4, 16 * 16 * 3)
        assert mask.shape[1] == 4
        shapes_seen.update(map(tuple, shapes.tolist()))
        out = step(model, opt,
                   (jnp.asarray(patches), jnp.asarray(shapes),
                    jnp.asarray(mask)), jnp.asarray(tokens))
        assert np.isfinite(float(out["loss"]))
        seen += len(tokens)
    assert seen == 4
    # aspect ratios survived: wide (16x48 -> 1x3), square (scaled up to the
    # budget, 2x2), and tall (3x1) grids all appear
    assert shapes_seen == {(1, 3), (2, 2), (3, 1)}


def test_cli_train_naflex_synthetic(tmp_path):
    """`train --naflex`: variable-resolution contrastive training from the
    CLI, synthetic mixed-aspect data, ring loss over an FSDP+TP mesh."""
    from jimm_tpu.cli import main
    rc = main(["train", "--preset", "siglip2-base-patch16-256", "--tiny",
               "--naflex", "--steps", "3", "--batch-size", "8",
               "--platform", "cpu", "--host-devices", "8",
               "--mesh", "data=4,model=2", "--rules", "fsdp_tp",
               "--loss", "siglip_ring",
               "--metrics-file", str(tmp_path / "m.jsonl")])
    assert rc == 0
    import json as _json
    lines = [_json.loads(line)
             for line in open(tmp_path / "m.jsonl").read().splitlines()]
    assert len(lines) == 3
    assert all(np.isfinite(rec["loss"]) for rec in lines)


def test_cli_train_naflex_rejects_vit():
    from jimm_tpu.cli import main
    with pytest.raises(SystemExit, match="siglip"):
        main(["train", "--preset", "vit-base-patch16-224", "--tiny",
              "--naflex", "--steps", "1", "--platform", "cpu"])
