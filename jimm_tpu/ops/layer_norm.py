"""Pallas TPU fused LayerNorm (forward + custom-VJP backward).

XLA's LayerNorm backward materializes several row-stat intermediates and ran
at ~340 GB/s in the SigLIP train-step profile (vs ~800 GB/s streaming ops —
see docs/performance.md). This kernel computes dx and the dscale/dbias
row-partials in ONE pass over (rows, features) tiles: each tensor is read
exactly once.

Shape robustness: every BlockSpec dimension is a multiple of the Mosaic
tile (sublanes x 128 lanes) — features are zero-padded up to the lane
multiple with the statistics masked to the real width, rows are padded to a
sublane-aligned block multiple, and the per-row mean/rstd are stored
lane-broadcast like the flash-attention stats. Nothing relies on the
"block equals array" escape hatch, which older kernels leaned on and which
stricter Mosaic versions reject (the recorded ``ln=fused`` sweep failures
on siglip_b16_256 in MEASUREMENTS.jsonl).

The row-block size resolves through `jimm_tpu.tune.best_config` when not
given explicitly: a tuned value if the persistent cache has one for this
(shape, dtype, backend), else ``DEFAULT_BLOCK_ROWS`` — lookup only, never
a measurement (docs/tuning.md).

Semantics match ``flax.nnx.LayerNorm`` (biased variance over the feature
axis, fp32 statistics, ``(x - mean) * rsqrt(var + eps) * scale + bias``),
verified to ~1e-5 in `tests/test_layer_norm.py` — including feature dims
not divisible by 128 and row counts not divisible by 8. Off-TPU the
kernels run in the Pallas interpreter so CPU tests exercise the same code
path.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

DEFAULT_BLOCK_ROWS = 256
_LANES = 128
_SUBLANES = 8


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _ceil_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _fwd_kernel(x_ref, g_ref, b_ref, o_ref, mu_ref, rstd_ref, *, eps: float,
                f_real: int):
    x = x_ref[...].astype(jnp.float32)              # (br, fp), tail cols 0
    fp = x.shape[1]
    # padded feature columns arrive zeroed from the host, so the raw sum is
    # already exact; the centered tail (0 - mu) must be masked before the
    # variance or every pad lane would contribute mu^2
    mu = jnp.sum(x, axis=1) / f_real
    xc = x - mu[:, None]
    if f_real != fp:
        in_f = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1) < f_real
        xc = jnp.where(in_f, xc, 0.0)
    var = jnp.sum(xc * xc, axis=1) / f_real
    rstd = jax.lax.rsqrt(var + eps)
    xhat = xc * rstd[:, None]
    g = g_ref[...].astype(jnp.float32)
    b = b_ref[...].astype(jnp.float32)
    o_ref[...] = (xhat * g[None, :] + b[None, :]).astype(o_ref.dtype)
    # stats are lane-broadcast (like flash attention's m/l) so their blocks
    # are full Mosaic tiles instead of (br, 1) lane slivers
    mu_ref[...] = jnp.broadcast_to(mu[:, None], mu_ref.shape)
    rstd_ref[...] = jnp.broadcast_to(rstd[:, None], rstd_ref.shape)


def _bwd_kernel(x_ref, g_ref, mu_ref, rstd_ref, do_ref, dx_ref, dg_ref,
                db_ref, *, f_real: int):
    x = x_ref[...].astype(jnp.float32)
    do = do_ref[...].astype(jnp.float32)            # tail cols/rows 0
    # all lanes equal -> max is an exact lane collapse
    mu = jnp.max(mu_ref[...], axis=1, keepdims=True)
    rstd = jnp.max(rstd_ref[...], axis=1, keepdims=True)
    xhat = (x - mu) * rstd
    if f_real != x.shape[1]:
        # pad cols hold x=0 so xhat=-mu*rstd there; zero them so the m2
        # moment and the dscale partial only see real features (do is
        # already zero in the tail, belt and suspenders for m2's product)
        in_f = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1) < f_real
        xhat = jnp.where(in_f, xhat, 0.0)
    g = g_ref[...].astype(jnp.float32)
    dy = do * g[None, :]
    m1 = jnp.sum(dy, axis=1, keepdims=True) / f_real
    m2 = jnp.sum(dy * xhat, axis=1, keepdims=True) / f_real
    dx_ref[...] = (rstd * (dy - m1 - xhat * m2)).astype(dx_ref.dtype)
    # dscale/dbias accumulate into ONE (8, fp) block revisited by every grid
    # step (TPU grids run sequentially, so read-modify-write is ordered).
    # Mosaic requires the sublane dim divisible by 8, so the partial lives
    # in row 0 of an 8-row block; the wrapper sums the zero rows away.
    @pl.when(pl.program_id(0) == 0)
    def _init():
        dg_ref[...] = jnp.zeros_like(dg_ref)
        db_ref[...] = jnp.zeros_like(db_ref)

    row0 = jax.lax.broadcasted_iota(jnp.int32, (_SUBLANES, 1), 0) == 0
    dg_ref[...] += jnp.where(row0, jnp.sum(do * xhat, axis=0)[None, :], 0.0)
    db_ref[...] += jnp.where(row0, jnp.sum(do, axis=0)[None, :], 0.0)


def _pad2(x: jax.Array, rows: int, cols: int) -> jax.Array:
    pr, pc = rows - x.shape[0], cols - x.shape[1]
    return x if pr == 0 and pc == 0 else jnp.pad(x, ((0, pr), (0, pc)))


def _pad1(v: jax.Array, cols: int) -> jax.Array:
    pc = cols - v.shape[0]
    return v if pc == 0 else jnp.pad(v, ((0, pc),))


def _sublanes(*dtypes) -> int:
    """Row-block alignment: 16 when any 16-bit operand is in play (bf16
    Mosaic tiles are (16, 128)), else the fp32 minimum of 8."""
    if any(jnp.dtype(d).itemsize == 2 for d in dtypes):
        return 16
    return _SUBLANES


def _rows_blocks(n_rows: int, block_rows: int,
                 sublanes: int = _SUBLANES) -> tuple[int, int, int]:
    """(block_rows, n_blocks, padded_rows): the row block is clamped to the
    (sublane-aligned) row count and rounded UP to a sublane multiple, and
    odd row counts are PADDED to a block multiple (padded rows normalize
    garbage-but-finite values the wrappers slice off; zero-padded ``do``
    rows contribute nothing to the dscale/dbias partial sums) rather than
    shrinking the tile — a (1, F) tile per row would be orders of magnitude
    slower."""
    br = min(block_rows, _ceil_to(n_rows, sublanes))
    br = max(sublanes, _ceil_to(br, sublanes))
    padded = _ceil_to(n_rows, br)
    return br, padded // br, padded


def _resolve_block_rows(shape: tuple[int, ...], dtype,
                        block_rows: int | None) -> int:
    """Trace-time (host-side) block resolution through the tune cache —
    lookup only, never a measurement. Explicit ``block_rows`` wins (the
    tuner's own bench closures pass it, so tuning cannot recurse)."""
    if block_rows is not None:
        return int(block_rows)
    from jimm_tpu.tune import best_config
    cfg = best_config("layer_norm", (tuple(shape),), (dtype,),
                      default={"block_rows": DEFAULT_BLOCK_ROWS})
    return int(cfg["block_rows"])


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def layer_norm(x: jax.Array, scale: jax.Array, bias: jax.Array,
               eps: float = 1e-6,
               block_rows: int | None = None) -> jax.Array:
    """Fused LayerNorm over the last axis of ``(rows, features)`` input."""
    o, _ = _ln_fwd(x, scale, bias, eps, block_rows)
    return o


def _ln_fwd_impl(x, scale, bias, eps, block_rows):
    r, f = x.shape
    br = _resolve_block_rows((r, f), x.dtype, block_rows)
    br, n_b, rp = _rows_blocks(r, br, _sublanes(x.dtype))
    fp = _ceil_to(f, _LANES)
    o, mu, rstd = pl.pallas_call(
        partial(_fwd_kernel, eps=eps, f_real=f),
        grid=(n_b,),
        in_specs=[
            pl.BlockSpec((br, fp), lambda i: (i, 0)),
            pl.BlockSpec((fp,), lambda i: (0,)),
            pl.BlockSpec((fp,), lambda i: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((br, fp), lambda i: (i, 0)),
            pl.BlockSpec((br, _LANES), lambda i: (i, 0)),
            pl.BlockSpec((br, _LANES), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rp, fp), x.dtype),
            jax.ShapeDtypeStruct((rp, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((rp, _LANES), jnp.float32),
        ],
        interpret=_interpret(),
    )(_pad2(x, rp, fp), _pad1(scale, fp), _pad1(bias, fp))
    # stats residuals saved as (r,) — one lane of the broadcast, unpadded
    return o[:r, :f], (x, scale, mu[:r, 0], rstd[:r, 0])


def _ln_fwd(x, scale, bias, eps, block_rows):
    return _ln_fwd_impl(x, scale, bias, eps, block_rows)


def _ln_bwd(eps, block_rows, res, do):
    x, scale, mu, rstd = res
    r, f = x.shape
    br = _resolve_block_rows((r, f), x.dtype, block_rows)
    br, n_b, rp = _rows_blocks(r, br, _sublanes(x.dtype, do.dtype))
    fp = _ceil_to(f, _LANES)
    # zero-padded do rows/cols zero their dscale/dbias contributions; padded
    # dx rows/cols are garbage-but-finite and sliced off
    stats = (rp, _LANES)
    dx, dg_part, db_part = pl.pallas_call(
        partial(_bwd_kernel, f_real=f),
        grid=(n_b,),
        in_specs=[
            pl.BlockSpec((br, fp), lambda i: (i, 0)),
            pl.BlockSpec((fp,), lambda i: (0,)),
            pl.BlockSpec((br, _LANES), lambda i: (i, 0)),
            pl.BlockSpec((br, _LANES), lambda i: (i, 0)),
            pl.BlockSpec((br, fp), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, fp), lambda i: (i, 0)),
            pl.BlockSpec((_SUBLANES, fp), lambda i: (0, 0)),
            pl.BlockSpec((_SUBLANES, fp), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rp, fp), x.dtype),
            jax.ShapeDtypeStruct((_SUBLANES, fp), jnp.float32),
            jax.ShapeDtypeStruct((_SUBLANES, fp), jnp.float32),
        ],
        interpret=_interpret(),
    )(_pad2(x, rp, fp), _pad1(scale, fp),
      _pad2(jnp.broadcast_to(mu[:, None], (r, _LANES)), *stats),
      _pad2(jnp.broadcast_to(rstd[:, None], (r, _LANES)), *stats),
      _pad2(do, rp, fp))
    dg = jnp.sum(dg_part, axis=0)[:f].astype(scale.dtype)
    db = jnp.sum(db_part, axis=0)[:f].astype(scale.dtype)
    return dx[:r, :f], dg, db


layer_norm.defvjp(_ln_fwd, _ln_bwd)
