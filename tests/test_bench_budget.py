"""Unit tests for bench.py's parent-side budget and JSON-line logic.

These never touch a backend: they exercise the outage-proofing math that
decides whether the driver artifact gets a datapoint (VERDICT r3 item 3).
"""

import importlib.util
import json
import pathlib
import sys


def _load_bench():
    path = pathlib.Path(__file__).resolve().parent.parent / "bench.py"
    spec = importlib.util.spec_from_file_location("bench_mod", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


bench = _load_bench()


def _args(**kw):
    argv = []
    for k, v in kw.items():
        argv += [f"--{k.replace('_', '-')}", str(v)]
    return bench.parse_args(argv, validate=False)


def test_default_budget_reserves_cpu_smoke(monkeypatch):
    monkeypatch.delenv("BENCH_TIMEOUT_S", raising=False)
    attempt, total = bench.resolve_budget(_args())
    # one attempt + the CPU fallback must both fit inside the total window
    assert attempt + bench.CPU_SMOKE_RESERVE + 5 <= total
    assert attempt >= 300  # the TPU attempt still gets a real window


def test_env_total_budget_caps_attempt(monkeypatch):
    monkeypatch.setenv("BENCH_TIMEOUT_S", "900")
    attempt, total = bench.resolve_budget(_args())
    assert total == 900
    assert attempt == 420  # the r3 default per-attempt cap
    monkeypatch.setenv("BENCH_TIMEOUT_S", "200")
    attempt, total = bench.resolve_budget(_args())
    # a small driver window shrinks the attempt, never overruns
    assert attempt + bench.CPU_SMOKE_RESERVE + 5 <= 200


def test_reserve_covers_documented_smoke_minimum():
    # the smoke needs ~90 s; the reserve must cover that plus the attempt's
    # -5 margin and the smoke's own -10 timeout margin (double-hang path)
    assert bench.CPU_SMOKE_RESERVE >= 90 + 5 + 10


def test_explicit_timeout_still_leaves_reserve(monkeypatch):
    monkeypatch.delenv("BENCH_TIMEOUT_S", raising=False)
    attempt, total = bench.resolve_budget(_args(timeout=60))
    assert attempt == 60
    assert total >= 60 + bench.CPU_SMOKE_RESERVE


def test_find_json_line_requires_metric_schema():
    out = "\n".join([
        "some log line",
        json.dumps({"not": "the schema"}),
        "42",
        json.dumps({"metric": "m", "value": 1.0}),
        "trailing noise",
    ])
    line = bench.find_json_line(out)
    assert json.loads(line)["metric"] == "m"
    assert bench.find_json_line("no json here\n17\n[1,2]") is None
