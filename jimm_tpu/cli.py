"""Command-line interface (SURVEY §5 config row: the reference has no CLI or
flag system at all — hyperparameters live in module constants,
ref `examples/vit_training.py:18-29`).

Subcommands::

    python -m jimm_tpu presets                      # list named model presets
    python -m jimm_tpu train --preset ... --steps N # training (synthetic or --data)
    python -m jimm_tpu classify IMG --ckpt ...      # zero-shot classification
    python -m jimm_tpu evaluate --data ...          # accuracy / retrieval metrics
    python -m jimm_tpu prepare-data SRC OUT         # raw images -> tfrecord shards
    python -m jimm_tpu export SRC OUT               # HF checkpoint -> safetensors dir
    python -m jimm_tpu export-run OUT --ckpt-dir D  # training run -> HF safetensors
    python -m jimm_tpu inspect FILE.safetensors     # tensor names/shapes/dtypes
    python -m jimm_tpu bench-forward --preset ...   # jitted forward throughput
    python -m jimm_tpu profile-analyze DIR          # per-op trace summary
    python -m jimm_tpu build-native                 # compile the C++ preprocessing lib
    python -m jimm_tpu obs snapshot URL|FILE        # print/save a unified metric dump
    python -m jimm_tpu obs tail URL|JSONL           # follow metrics live
    python -m jimm_tpu obs diff BEFORE AFTER        # structural metric diff

`train` runs entirely offline on procedural data (`jimm_tpu.data.synthetic`)
so it works with zero network on CPU or TPU, and exercises the real stack:
mesh + sharding rules, jitted step, checkpoint/resume, metrics JSONL.
"""

from __future__ import annotations

import argparse
import dataclasses
import sys
from typing import Any


def _configure_backend(args: argparse.Namespace) -> None:
    import os

    import jimm_tpu.utils.env as env
    env.configure_platform(platform=getattr(args, "platform", None),
                           host_devices=getattr(args, "host_devices", None))
    # join the cluster before any backend use when (a) running under
    # `python -m jimm_tpu.launch` (or a hand-exported process group), or
    # (b) the environment looks like a multi-host TPU pod — skipping init
    # there would silently train an independent copy per host. The pod
    # path uses jax's argless auto-detect (metadata server), whose failure
    # mode on a NON-pod TPU host is a hang — so markers that single-host
    # environments also set must not trigger it (ADVICE r4):
    # TPU_WORKER_HOSTNAMES counts only with >1 hosts (single-host VMs set it
    # to one name), TPU_WORKER_ID alone never counts, and an explicit
    # non-TPU --platform skips cluster join entirely.
    if os.environ.get("JIMM_NUM_PROCESSES"):
        # explicit opt-in (launcher or hand-exported group): always honored,
        # on any platform — this path never touches the TPU metadata server
        from jimm_tpu.parallel import initialize_distributed
        initialize_distributed()
        return
    if getattr(args, "platform", None) not in (None, "tpu"):
        return  # explicit non-TPU platform: never probe the TPU runtime
    hostnames = [h for h in
                 os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",") if h]
    pod_markers = ("CLOUD_TPU_TASK_ID", "MEGASCALE_COORDINATOR_ADDRESS")
    if any(m in os.environ for m in pod_markers) or len(hostnames) > 1:
        from jimm_tpu.parallel import initialize_distributed
        initialize_distributed()


def _configure_journal(args: argparse.Namespace) -> None:
    """Point the process-wide flight-recorder journal at ``--journal PATH``
    (no flag: in-memory ring only, or the JIMM_JOURNAL env default)."""
    if getattr(args, "journal", None):
        from jimm_tpu.obs.journal import configure_journal
        configure_journal(args.journal)


def _parse_mesh(spec: str | None, max_devices: int | None = None):
    """``"data=4,model=2"`` -> Mesh (None -> no mesh: replicated 1-device).

    ``max_devices`` restricts the mesh to the first N visible devices —
    the elastic-restart path: a shrunk attempt plans its mesh over the
    surviving subset while the process still sees the full virtual device
    list (``make_mesh`` requires the axis product to equal the device
    count, so the subset must be explicit)."""
    if not spec:
        return None
    from jimm_tpu.parallel import make_mesh
    axes = {}
    for part in spec.split(","):
        name, _, size = part.partition("=")
        axes[name.strip()] = int(size)
    devices = None
    if max_devices is not None:
        import jax
        visible = jax.devices()
        if not 1 <= max_devices <= len(visible):
            raise SystemExit(f"--max-devices {max_devices} out of range "
                             f"(1..{len(visible)} visible)")
        devices = visible[:max_devices]
    return make_mesh(axes, devices=devices)


def _family(preset_name: str) -> str:
    for fam in ("vit", "clip", "siglip"):
        if preset_name.startswith(fam):
            return fam
    raise SystemExit(f"cannot infer model family from preset {preset_name!r}")


def _model_cls(fam: str):
    from jimm_tpu import CLIP, SigLIP, VisionTransformer
    return {"vit": VisionTransformer, "clip": CLIP, "siglip": SigLIP}[fam]


def _replace_towers(cfg: Any, **fields: Any) -> Any:
    """dataclasses.replace the same fields in the vision (and, if present,
    text) tower config."""
    cfg = dataclasses.replace(
        cfg, vision=dataclasses.replace(cfg.vision, **fields))
    if hasattr(cfg, "text"):
        cfg = dataclasses.replace(
            cfg, text=dataclasses.replace(cfg.text, **fields))
    return cfg


def _norm_for(fam: str) -> dict:
    """Family-correct file-pipeline normalization (HF processor
    conventions): CLIP's mean/std; ViT/SigLIP use the 0.5 defaults. Shared
    by train and evaluate so both see the same pixels."""
    if fam == "clip":
        from jimm_tpu.data.preprocess import CLIP_MEAN, CLIP_STD
        return {"mean": CLIP_MEAN, "std": CLIP_STD}
    return {}


def _is_tar_data(data: str) -> bool:
    """Route --data to the webdataset loader when it names tar shards
    (compressed .tar.gz/.tar.zst included)."""
    from pathlib import Path
    p = Path(data)
    if p.is_dir():
        return (not any(p.glob("*.tfrecord*"))) and any(p.glob("*.tar*"))
    return ".tar" in p.name


def _dataset_classes(data: str) -> list[str] | None:
    """Ordered class names from the classes.json prepare-data writes next
    to the shards (index == label id) — resolved by the container's own
    path rules (tfrecord or tar), so every --data form (dir, glob, file)
    works for both formats."""
    import json
    from pathlib import Path

    if _is_tar_data(data):
        from jimm_tpu.data.webdataset import resolve_tar_paths as resolve
    else:
        from jimm_tpu.data.records import resolve_paths as resolve
    try:
        cj = Path(resolve(data)[0]).parent / "classes.json"
    except FileNotFoundError:
        return None  # the loader itself will raise with the right message
    if cj.is_file():
        return list(json.loads(cj.read_text()))
    return None


def _num_classes_from_data(data: str) -> int | None:
    classes = _dataset_classes(data)
    if classes is not None:
        print(f"num_classes={len(classes)} from classes.json")
        return len(classes)
    return None


def _swap_classifier(model, n_target: int, *, dtype, seed: int,
                     mesh=None, rules=None) -> None:
    """Replace a ViT's classification head with a fresh ``n_target``-wide
    zero-init Linear (the standard fine-tune head swap). Shared by train
    and evaluate so both rebuild the same architecture around an orbax
    checkpoint."""
    import dataclasses as _dc

    from flax import nnx

    from jimm_tpu.parallel.sharding import logical, shard_model
    cfg = model.config
    model.classifier = nnx.Linear(
        cfg.vision.width, n_target, dtype=dtype, param_dtype=dtype,
        kernel_init=logical(nnx.initializers.zeros_init(),
                            "embed", "classes"),
        bias_init=logical(nnx.initializers.zeros_init(), "classes"),
        rngs=nnx.Rngs(seed))
    model.config = _dc.replace(cfg, num_classes=n_target,
                               do_classification=True)
    if mesh is not None:
        shard_model(model, mesh, rules)


def _fit_head(model, n: int | None, *, dtype, seed: int = 0,
              mesh=None, rules=None) -> None:
    """Make a loaded ViT's classifier match the task: swap in a fresh
    ``n``-wide head when the count differs (or the checkpoint is headless),
    error when headless with no count known. One decision shared by train,
    evaluate, and export-run — they must rebuild identical architectures."""
    cfg = model.config
    if n and (not cfg.do_classification or n != cfg.num_classes):
        _swap_classifier(model, n, dtype=dtype, seed=seed, mesh=mesh,
                         rules=rules)
        print(f"fresh classifier head: {n} classes")
    elif not cfg.do_classification:
        raise SystemExit("checkpoint has no classifier head; pass "
                         "--num-classes (or put classes.json next to "
                         "--data)")


def _restore_run(args: argparse.Namespace):
    """Rebuild the architecture a training run used (--preset [+--tiny] or
    --from-pretrained [+--image-size], with the vit head swap) and restore
    its orbax checkpoint over it. Shared by `evaluate` and `export-run` —
    they must reconstruct the exact same model to load the weights."""
    import jax.numpy as jnp
    from flax import nnx

    from jimm_tpu import preset

    fam = _family(args.preset)
    dtype = jnp.bfloat16 if args.bf16 else jnp.float32
    data = getattr(args, "data", None)
    n = (args.num_classes or (_num_classes_from_data(data) if data else None)
         if fam == "vit" else None)
    if args.from_pretrained:
        if args.tiny:
            raise SystemExit("--tiny conflicts with --from-pretrained "
                             "(the checkpoint defines the architecture)")
        # the training run was `train --from-pretrained X`: rebuild the
        # same architecture (incl. head swap) before restoring over it
        model = _model_cls(fam).from_pretrained(
            args.from_pretrained, dtype=dtype, image_size=args.image_size)
        if fam == "vit":
            _fit_head(model, n, dtype=dtype)
    else:
        cfg = preset(args.preset)
        if args.tiny:
            cfg = _tiny_override(cfg)
        if n:
            # must match the classifier head the training run used
            cfg = dataclasses.replace(cfg, num_classes=n)
        model = _model_cls(fam)(cfg, rngs=nnx.Rngs(0), dtype=dtype,
                                param_dtype=dtype)
    from jimm_tpu.train import CheckpointManager
    step = CheckpointManager(args.ckpt_dir).restore(model)
    print(f"restored step {step} from {args.ckpt_dir}")
    return fam, model


def _tiny_override(cfg: Any) -> Any:
    """Shrink any preset to CPU-demo size, keeping its architecture class."""
    from jimm_tpu.configs import CLIPConfig, SigLIPConfig, ViTConfig

    # depth 4 (not 2) so tiny runs can still exercise pipeline stages x
    # virtual-chunk splits (depth % (stages * virtual) == 0 for 2x2)
    def shrink_vision(v):
        return dataclasses.replace(v, image_size=32, patch_size=16, width=64,
                                   depth=4, num_heads=2, mlp_dim=128)

    def shrink_text(t):
        return dataclasses.replace(t, vocab_size=64, context_length=8,
                                   width=64, depth=4, num_heads=2, mlp_dim=128)

    if isinstance(cfg, ViTConfig):
        return dataclasses.replace(cfg, vision=shrink_vision(cfg.vision))
    if isinstance(cfg, (CLIPConfig, SigLIPConfig)):
        return dataclasses.replace(cfg, vision=shrink_vision(cfg.vision),
                                   text=shrink_text(cfg.text),
                                   projection_dim=64)
    raise TypeError(type(cfg))


def _serve_dtype(args: argparse.Namespace) -> str:
    """Resolve the serving precision from --dtype / the legacy --bf16."""
    if getattr(args, "bf16", False) and args.dtype not in (None, "bf16"):
        raise SystemExit(f"--bf16 conflicts with --dtype {args.dtype}")
    return args.dtype or ("bf16" if getattr(args, "bf16", False) else "f32")


# ---------------------------------------------------------------------------
# Subcommands
# ---------------------------------------------------------------------------

def cmd_presets(args: argparse.Namespace) -> int:
    _configure_backend(args)
    import math

    from flax import nnx

    from jimm_tpu.configs import PRESETS

    def params_m(name: str, cfg: Any) -> str:
        # abstract construction: shapes only, nothing allocated
        model = nnx.eval_shape(
            lambda: _model_cls(_family(name))(cfg, rngs=nnx.Rngs(0)))
        n = sum(math.prod(v.shape)
                for _, v in nnx.to_flat_state(nnx.state(model, nnx.Param)))
        return f"{n / 1e6:8.1f}M"

    for name, cfg in PRESETS.items():
        v = cfg.vision
        extra = ""
        if hasattr(cfg, "text"):
            extra = (f" text(width={cfg.text.width} depth={cfg.text.depth} "
                     f"vocab={cfg.text.vocab_size})")
        print(f"{name:32s} {params_m(name, cfg)} "
              f"vision(width={v.width} depth={v.depth} "
              f"img={v.image_size} patch={v.patch_size}){extra}")
    return 0


def _batch_fingerprint(batch) -> int:
    """48-bit content hash of a batch's host bytes.

    Small enough to round-trip float64 metrics paths (JSONL, registry)
    exactly — equal fingerprints at equal steps between a resumed run and
    an uninterrupted control is the zero-replay/zero-skip resume proof.
    Pulls the batch to host, so it is opt-in (--batch-fingerprint)."""
    import hashlib

    import jax
    import numpy as np
    h = hashlib.sha1()
    for leaf in jax.tree.leaves(batch):
        h.update(np.asarray(leaf).tobytes())
    return int(h.hexdigest()[:12], 16)


def cmd_train(args: argparse.Namespace) -> int:
    _configure_backend(args)
    _configure_journal(args)
    if args.compilation_cache_dir:
        # persistent XLA compile cache: restarted runs (preemption,
        # resume, sweep retries) skip straight past the train-step compile
        from jimm_tpu.aot.export import enable_persistent_cache
        enable_persistent_cache(args.compilation_cache_dir)
    import jax.numpy as jnp
    import numpy as np
    from flax import nnx

    from jimm_tpu import preset
    from jimm_tpu.data import (PrefetchIterator, blob_classification,
                               contrastive_pairs)
    from jimm_tpu.parallel import PRESET_RULES, shard_batch, use_sharding
    from jimm_tpu.train import (CheckpointManager, MetricsLogger,
                                OptimizerConfig, StepTimer,
                                make_classifier_train_step,
                                make_contrastive_train_step, make_optimizer)

    fam = _family(args.preset)
    if args.naflex and fam != "siglip":
        raise SystemExit("--naflex trains SigLIP2-style models; "
                         "use a siglip preset")
    cfg = preset(args.preset)
    if args.tiny:
        if args.from_pretrained:
            # --tiny shrinks the PRESET; with --from-pretrained the
            # architecture comes from the checkpoint, so the flag would be
            # silently ignored — refuse the contradiction
            raise SystemExit("--tiny conflicts with --from-pretrained "
                             "(the checkpoint defines the architecture)")
        cfg = _tiny_override(cfg)

    # execution-strategy overrides, built ONCE: the preset path applies
    # them to cfg, the fine-tune path passes them to from_pretrained
    rt: dict[str, Any] = {}
    if args.attn_impl:
        rt["attn_impl"] = args.attn_impl
    if args.remat:
        from jimm_tpu.configs import parse_remat
        try:
            rt.update(parse_remat(args.remat))
        except ValueError as e:
            raise SystemExit(f"--remat: {e}")
    if args.ln_impl:
        rt["ln_impl"] = args.ln_impl
    if args.fused_qkv:
        rt["fused_qkv"] = True
    if args.precision:
        rt["precision"] = args.precision
    mesh = _parse_mesh(args.mesh, max_devices=args.max_devices)
    pp_extra = {}
    if args.pipeline_virtual > 1:
        if args.rules != "pp":
            raise SystemExit("--pipeline-virtual needs --rules pp")
        # bake circular placement into storage when the stage count is
        # known from --mesh (avoids a per-step cross-stage all-to-all)
        stages = dict(mesh.shape).get("stage", 0) if mesh is not None else 0
        pp_extra = dict(pp_virtual=args.pipeline_virtual, pp_stages=stages)
    if args.pipeline_microbatches:
        if args.pipeline_microbatches < 1:
            raise SystemExit("--pipeline-microbatches must be >= 1")
        if args.rules != "pp":
            raise SystemExit("--pipeline-microbatches needs --rules pp "
                             "(layers sharded over the 'stage' mesh axis)")
        rt.update(pipeline=True, **pp_extra,
                  pp_microbatches=args.pipeline_microbatches)
    elif args.rules == "pp":
        # --rules pp without the flag: default to the config's microbatch
        # count rather than silently running the unpipelined scan with
        # stage-sharded params (correct but all-gathers every layer)
        rt.update(pipeline=True, **pp_extra)
    # fill knobs the user left unset from the measured-best adopted runtime
    # (`scripts/adopt_sweep.py --apply`, jimm_tpu/adopted_runtime.json);
    # explicit flags above always win, the TPU-measured choices are not
    # imposed on other backends, and the adoption only holds for the exact
    # architecture it was measured on — a --tiny shrink or a checkpoint of
    # unknown shape must not inherit e.g. a flash kernel choice or an
    # unroll that its shapes never validated
    import jax as _jax
    if (_jax.default_backend() == "tpu" and not args.tiny
            and not args.from_pretrained):
        from jimm_tpu.configs import adopted_runtime
        for k, v in adopted_runtime(args.preset).items():
            rt.setdefault(k, v)
    if args.scan_unroll >= 1:  # any explicit value wins, including 1
        rt["scan_unroll"] = args.scan_unroll
    elif args.scan_unroll == 0 and not args.from_pretrained:
        # auto: full unroll on TPU, resolved against the preset's depth
        # (a checkpoint's depth is unknown here — explicit unrolls only);
        # an adopted, measured unroll above outranks this heuristic
        if _jax.default_backend() == "tpu":
            rt.setdefault("scan_unroll", cfg.vision.depth)
    if rt and not args.from_pretrained:
        cfg = _replace_towers(cfg, **rt)
    def _validate_pp(cfg_obj) -> None:
        # fail bad pipeline configs before any compile, with the exact
        # message the shard_map trace would produce minutes in — preset
        # path pre-model, fine-tune path right after the checkpoint load
        if args.rules != "pp":
            return
        from jimm_tpu.configs import validate_pipeline
        mesh_shape = dict(mesh.shape) if mesh is not None else {}
        data_axis = mesh_shape.get("data", 1)
        if args.batch_size % data_axis:
            # floor division below would validate a WRONG local batch and
            # let a config pass (or fail confusingly) that the real
            # shard-map trace rejects minutes later (ADVICE r4)
            raise SystemExit(f"--batch-size {args.batch_size} is not "
                             f"divisible by the data mesh axis ({data_axis})")
        local_batch = args.batch_size // data_axis
        try:
            for tname in ("vision", "text"):
                tower = getattr(cfg_obj, tname, None)
                if tower is not None:
                    validate_pipeline(tower,
                                      n_stages=mesh_shape.get("stage", 0),
                                      local_batch=local_batch,
                                      tower_name=tname)
        except ValueError as e:
            raise SystemExit(f"pipeline config: {e}")

    if not args.from_pretrained:
        _validate_pp(cfg)
    n_classes = None
    if fam == "vit":
        n_classes = args.num_classes or (
            _num_classes_from_data(args.data) if args.data else None)
        if n_classes is None and not args.data:
            n_classes = 4  # synthetic classes
        if n_classes:
            cfg = dataclasses.replace(cfg, num_classes=n_classes)

    rules = PRESET_RULES[args.rules] if args.rules else (
        PRESET_RULES["dp"] if mesh is not None else None)
    dtype = jnp.bfloat16 if args.bf16 else jnp.float32

    if args.from_pretrained:
        # fine-tune: architecture from the checkpoint, execution strategy
        # from the SAME rt dict the preset path applies (built above)
        try:
            model = _model_cls(fam).from_pretrained(
                args.from_pretrained, mesh=mesh,
                rules=rules if rules is not None else "replicated",
                dtype=dtype, runtime=rt or None, image_size=args.image_size)
        except ValueError as e:
            # a checkpoint depth incompatible with the stage/virtual layout
            # raises during construction (interleaved placement is baked
            # into storage) — give it the same fast, clean exit as the
            # parse-time checks; any OTHER load error keeps its traceback
            if (args.rules == "pp" and "divisible" in str(e)
                    and "stage" in str(e)):
                raise SystemExit(f"pipeline config: {e}")
            raise
        if fam == "vit":
            _fit_head(model, n_classes, dtype=dtype, seed=args.seed,
                      mesh=mesh, rules=rules)
        cfg = model.config
        _validate_pp(cfg)
    else:
        model = _model_cls(fam)(cfg, rngs=nnx.Rngs(args.seed), mesh=mesh,
                                rules=rules, dtype=dtype, param_dtype=dtype)
    # low-precision training surgery, BEFORE the optimizer is built: the
    # optimizer tracks nnx.Param state, and the fp8 wrapper shares the
    # Linear's kernel/bias Params (amax histories are plain Variables, so
    # they never enter optimizer state)
    precision = getattr(cfg.vision, "precision", "bf16")
    if precision != "bf16":
        from jimm_tpu.quant.policy import apply_precision_policy
        n_lowp = apply_precision_policy(model, precision)
        print(f"precision policy {precision}: {n_lowp} modules rewritten")
    # --moment-dtype wins over the legacy --bf16-momentum sugar
    moment_dtype = ({"f32": "float32", "bf16": "bfloat16"}[args.moment_dtype]
                    if args.moment_dtype
                    else ("bfloat16" if args.bf16_momentum else None))
    optimizer = make_optimizer(model, OptimizerConfig(
        learning_rate=args.lr, weight_decay=args.weight_decay,
        warmup_steps=args.warmup_steps, total_steps=args.steps,
        moment_dtype=moment_dtype))

    import jax

    # deterministic fault drill: --fake-failure-at-step N is historical
    # sugar for the crash@N entry of the general --inject-faults plan
    fault_spec = args.inject_faults or ""
    if args.fake_failure_at_step is not None:
        crash = f"crash@{args.fake_failure_at_step}"
        fault_spec = f"{fault_spec},{crash}" if fault_spec else crash
    fault_plan = None
    if fault_spec:
        from jimm_tpu.resilience import FaultPlan
        try:
            fault_plan = FaultPlan.parse(fault_spec)
        except ValueError as e:
            raise SystemExit(f"--inject-faults: {e}")
        if fault_plan.needs("corrupt") and not args.ckpt_dir:
            raise SystemExit("--inject-faults: corrupt@STEP needs --ckpt-dir")
    if args.preemption_save and not args.ckpt_dir:
        raise SystemExit("--preemption-save needs --ckpt-dir")

    # mesh= records the topology each save was sharded over and counts a
    # topology change when a restore crosses mesh shapes (elastic restarts)
    ckpt = CheckpointManager(args.ckpt_dir, save_interval_steps=args.save_every,
                             mesh=mesh) \
        if args.ckpt_dir else None
    start_step = 0
    if ckpt is not None and args.resume:
        try:
            start_step = ckpt.restore(model, optimizer) + 1
            print(f"resumed from step {start_step - 1}")
        except FileNotFoundError:
            pass

    # deterministic resume: resumed step N sees the same batch it would have
    # in the uninterrupted run. File pipelines fast-forward the raw example
    # stream (no image decode); synthetic generators just skip batches.
    data_kw = dict(shard_index=jax.process_index(),
                   shard_count=jax.process_count(),
                   shuffle_buffer=args.shuffle_buffer, seed=args.seed,
                   skip_examples=start_step * args.batch_size,
                   **_norm_for(fam))

    grain_stream = None  # consumed-state tracker for exact checkpoint/resume

    def _grain_data(task: str):
        nonlocal grain_stream
        if _is_tar_data(args.data):
            raise SystemExit("--loader grain reads tfrecord shards; tar "
                             "(webdataset) data uses --loader records")
        import base64

        from jimm_tpu.data.grain_pipeline import (CheckpointableGrainStream,
                                                  make_grain_loader)
        extra = ({"seq_len": cfg.text.context_length}
                 if task == "contrastive" else {})
        loader = make_grain_loader(
            args.data, args.batch_size, task=task,
            image_size=cfg.vision.image_size, seed=args.seed,
            worker_count=args.data_workers,
            shard_index=jax.process_index(),
            shard_count=jax.process_count(), **_norm_for(fam), **extra)
        grain_iter = iter(loader)
        saved = (ckpt.last_restored_extra.get("grain_state")
                 if ckpt is not None else None)
        if start_step and saved:
            # exact position from the checkpoint — no decode replay, no
            # skipped batches: the saved state is the one captured with the
            # last batch the train loop actually consumed (see
            # CheckpointableGrainStream), so resume lands on the very next
            # batch even though PrefetchIterator had read ahead.
            grain_iter.set_state(base64.b64decode(saved))
        else:
            for _ in range(start_step):  # pre-grain_state checkpoint:
                next(grain_iter)         # replay (decodes) to position
        grain_stream = CheckpointableGrainStream(grain_iter)
        return grain_stream.batches()

    if fam == "vit":
        step_fn = make_classifier_train_step(donate=True)
        if args.data and args.loader == "grain":
            data = _grain_data("classification")
        elif args.data:
            if _is_tar_data(args.data):
                from jimm_tpu.data.webdataset import (
                    wds_classification_batches as classification_batches)
            else:
                from jimm_tpu.data.records import classification_batches
            data = classification_batches(
                args.data, args.batch_size,
                image_size=cfg.vision.image_size, **data_kw)
        else:
            # temporal towers train on synthetic (B, T, H, W, C) clips;
            # the file loaders stay image-only for now
            data = blob_classification(args.batch_size,
                                       image_size=cfg.vision.image_size,
                                       num_classes=cfg.num_classes,
                                       seed=args.seed,
                                       num_frames=cfg.vision.num_frames)
    else:
        # ring losses shard the batch over the "data" axis — on a mesh
        # without one (e.g. model-only TP) the dense loss is the default
        ring_ok = mesh is not None and ("data" in mesh.shape
                                        or mesh.shape.get("seq", 1) > 1)
        if fam == "clip":
            loss_kind = args.loss or ("clip_ring" if ring_ok else "clip")
        else:
            loss_kind = args.loss or ("siglip_ring" if ring_ok
                                      else "siglip")
        # a seq axis joins the pair-dimension ring: the contrastive batch
        # shards over ("data", "seq") combined, so sequence-parallel
        # meshes spend every chip on the pairwise loss too
        loss_axis = "data"
        if (loss_kind.endswith("_ring") and mesh is not None
                and mesh.shape.get("seq", 1) > 1):
            loss_axis = tuple(a for a in ("data", "seq")
                              if a in mesh.shape)
        step_fn = make_contrastive_train_step(loss_kind, mesh=mesh,
                                              axis_name=loss_axis,
                                              donate=True)
        if rules is not None and isinstance(loss_axis, tuple):
            # batches land sharded over both pair axes (the loss's
            # shard_map in_specs expect it)
            rules = dataclasses.replace(rules, batch=loss_axis)
        if args.naflex:
            # variable-resolution SigLIP2 training (beyond the reference)
            if fam != "siglip":
                raise SystemExit("--naflex trains SigLIP2-style models; "
                                 "use a siglip preset")
            if args.rules == "pp":
                raise SystemExit("--naflex needs attention masks, which the "
                                 "pipelined path does not support yet")
            if args.data and (args.loader == "grain"
                              or _is_tar_data(args.data)):
                raise SystemExit("--naflex reads tfrecord shards (records "
                                 "loader) or synthetic data")
            naflex_kw = dict(patch_size=cfg.vision.patch_size,
                             max_num_patches=cfg.vision.num_patches,
                             seq_len=cfg.text.context_length)
            if args.data:
                from jimm_tpu.data.records import naflex_image_text_batches
                data = naflex_image_text_batches(
                    args.data, args.batch_size, **naflex_kw, **data_kw)
            else:
                from jimm_tpu.data.synthetic import naflex_contrastive_pairs
                data = naflex_contrastive_pairs(
                    args.batch_size, **naflex_kw,
                    vocab_size=cfg.text.vocab_size, seed=args.seed)
        elif args.data and args.loader == "grain":
            data = _grain_data("contrastive")
        elif args.data:
            if _is_tar_data(args.data):
                from jimm_tpu.data.webdataset import (
                    wds_image_text_batches as image_text_batches)
            else:
                from jimm_tpu.data.records import image_text_batches
            data = image_text_batches(
                args.data, args.batch_size,
                image_size=cfg.vision.image_size,
                seq_len=cfg.text.context_length, **data_kw)
        else:
            data = contrastive_pairs(args.batch_size,
                                     image_size=cfg.vision.image_size,
                                     vocab_size=cfg.text.vocab_size,
                                     seq_len=cfg.text.context_length,
                                     seed=args.seed)
    if not args.data:
        for _ in range(start_step):
            next(data)

    from jimm_tpu import obs
    logger = MetricsLogger(path=args.metrics_file, print_every=args.log_every,
                           tensorboard_dir=args.tensorboard_dir,
                           registry=obs.get_registry("jimm_train"))
    timer = StepTimer()
    # goodput ledger: every loop region below runs under a measure() bucket,
    # so the end-of-run report decomposes wall time into
    # compile/data_wait/step/checkpoint/host_sync/other
    acct = obs.GoodputAccounter()
    profiler_ctx = None
    # continuous profiling ring: a bounded on-disk rotation of short
    # step-window captures, plus anomaly-triggered deep captures (installed
    # process-globally so resilience paths can maybe_trigger() into it)
    prof_ring = None
    if args.prof_ring:
        from jimm_tpu.obs.prof.capture import configure_capture
        prof_ring = configure_capture(
            args.prof_ring, max_ring_bytes=args.prof_ring_bytes,
            every_steps=args.prof_every, window_steps=args.prof_window)

    # preemption guard: SIGTERM sets a flag the loop polls; the handler
    # turns it into a grace-window async save + resumable PreemptedError
    guard = None
    preempt = None
    if args.preemption_save:
        from jimm_tpu.resilience import PreemptionGuard, PreemptionHandler
        guard = PreemptionGuard().install()
        preempt = PreemptionHandler(guard, ckpt,
                                    grace_steps=args.grace_steps,
                                    accounter=acct)

    def place(batch):
        if mesh is None:
            # tree-map: a NaFlex batch nests the image triple inside
            import jax as _jax
            return _jax.tree.map(jnp.asarray, batch)
        return shard_batch(batch, mesh, rules)

    data = PrefetchIterator(data, mesh=mesh, rules=rules) \
        if mesh is not None else map(place, data)
    if grain_stream is not None:
        # advance consumed_state batch-by-batch on THIS (consumer) side of
        # the prefetch queue, so checkpoints record the trained-on position
        data = grain_stream.track(data)

    # profile steps start+2..start+4 (past compile), falling back to the
    # whole run when it is shorter than that
    profile_start = min(start_step + 2, max(args.steps - 1, start_step))
    profile_stop = min(start_step + 4, args.steps - 1)
    dt = None
    try:
        with use_sharding(mesh, rules):
            for step in range(start_step, args.steps):
                if prof_ring is not None:
                    prof_ring.on_step(step)
                if args.profile_dir and step == profile_start:
                    if prof_ring is not None:
                        # one profiler session at a time: a live ring window
                        # would deadlock the blocking one-shot trace below
                        prof_ring.flush()
                    from jimm_tpu.train.profile import trace
                    profiler_ctx = trace(args.profile_dir)
                    profiler_ctx.__enter__()
                with acct.measure("data_wait"):
                    batch = next(data)
                # hash before step_fn runs: donated buffers die with the step
                fp = (_batch_fingerprint(batch)
                      if args.batch_fingerprint else None)
                # the first step traces + compiles under the same call; it
                # lands in the "compile" bucket, steady-state in "step"
                # (timer.stop's device_get sync keeps device time in-bucket)
                with acct.measure("compile" if step == start_step
                                  else "step"):
                    timer.start()
                    metrics = step_fn(model, optimizer, *batch)
                    dt = timer.stop(metrics["loss"])
                if profiler_ctx is not None and step == profile_stop:
                    profiler_ctx.__exit__(None, None, None)
                    profiler_ctx = None
                    print(f"profile trace written to {args.profile_dir}")
                with acct.measure("host_sync"):
                    host_metrics = {k: float(v) for k, v in metrics.items()}
                    if fp is not None:
                        host_metrics["batch_fingerprint"] = fp
                    logger.log(step, step_time_s=dt, **host_metrics)
                extra = None
                if ckpt is not None and grain_stream is not None:
                    import base64
                    extra = {"grain_state": base64.b64encode(
                        grain_stream.consumed_state).decode("ascii")}
                saved_now = False
                if ckpt is not None and (preempt is None
                                         or not preempt.draining):
                    # while the grace save drains, later per-step saves are
                    # pointless — nothing after it survives the restart
                    with acct.measure("checkpoint"):
                        saved_now = ckpt.save(step, model, optimizer,
                                              extra=extra)
                if fault_plan is not None:
                    # drill events for this step (stall/corrupt/preempt/
                    # crash); a preempt's SIGTERM lands before the guard
                    # check below, same as a real maintenance signal
                    fault_plan.fire(step, ckpt=ckpt)
                if preempt is not None:
                    preempt.after_step(step, model, optimizer, extra=extra,
                                       already_saved=saved_now)
    finally:
        if guard is not None:
            guard.uninstall()
        if profiler_ctx is not None:
            # crash mid-profile: still flush what was captured
            profiler_ctx.__exit__(None, None, None)
            print(f"profile trace written to {args.profile_dir}")
        if prof_ring is not None:
            # commit a half-open window so the newest capture survives a
            # crash — the whole point of a flight-recorder ring
            prof_ring.close()
        # a mid-run crash must not strand buffered TensorBoard events (the
        # EventFileWriter queue flushes on close, not per event)
        logger.close()
    if ckpt is not None:
        ckpt.wait()
        ckpt.close()
    import json as _json

    from jimm_tpu.train.metrics import mfu as _mfu, train_step_flops
    achieved_mfu = (None if dt is None
                    else _mfu(train_step_flops(cfg, args.batch_size), dt))
    # precision + moment_dtype ride the goodput line so measurement
    # consumers (lowp_train_smoke, window_report) can attribute MFU/img/s
    # deltas to the policy that produced them
    print("goodput: " + _json.dumps({
        **acct.report(mfu=achieved_mfu),
        "precision": precision,
        "moment_dtype": moment_dtype or "param",
    }))
    return 0


def _argv_flag_value(argv: list[str], flag: str, default):
    """Last occurrence wins, mirroring argparse."""
    value = default
    for i, tok in enumerate(argv):
        if tok == flag and i + 1 < len(argv):
            value = argv[i + 1]
        elif tok.startswith(flag + "="):
            value = tok.split("=", 1)[1]
    return value


def cmd_supervise(args: argparse.Namespace) -> int:
    """Run ``train`` as restartable attempts.

    A preemption (PreemptedError out of the grace-window save) or worker
    death restarts the command with ``--resume`` after a bounded jittered
    backoff, up to ``--max-restarts`` times, then gives up loudly.
    In-process — one interpreter, one metric registry — so
    ``jimm_train_restarts_total`` and the lost-work goodput bucket
    accumulate across attempts; ``launch.py --restarts`` applies the same
    policy at process-group granularity.

    ``--elastic`` replans the mesh before every attempt from the devices
    still available (``--shrink-plan`` shrinks the budget between attempts
    for drills), so a restart that lost hosts restores its checkpoint onto
    the smaller mesh (resharding-on-restore) instead of crashing on the old
    shape. ``--adapt`` runs a :class:`~jimm_tpu.resilience.GoodputAdvisor`
    over the per-attempt goodput breakdown and carries its bounded knob
    decisions (checkpoint cadence, grace steps, scan unroll) into the next
    attempt's flags. Without these flags, behavior is byte-identical to the
    static supervise loop."""
    from jimm_tpu.resilience import BackoffPolicy, GiveUpError, Supervisor
    _configure_journal(args)
    cmd = list(args.train_args or [])
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd or cmd[0] != "train":
        raise SystemExit("supervise wraps the train subcommand: "
                         "jimm-tpu supervise [options] -- train ...")
    if "--ckpt-dir" not in cmd:
        raise SystemExit("supervise needs --ckpt-dir in the train command "
                         "(restarts resume from checkpoints)")
    if "--preemption-save" not in cmd:
        cmd.append("--preemption-save")
    shrink_plan = None
    if args.shrink_plan:
        if not args.elastic:
            raise SystemExit("--shrink-plan is an --elastic drill knob")
        try:
            shrink_plan = [int(x) for x in args.shrink_plan.split(",")]
        except ValueError:
            raise SystemExit(f"--shrink-plan {args.shrink_plan!r}: expected "
                             "comma-separated device counts, e.g. 8,4")
        if any(n < 1 for n in shrink_plan):
            raise SystemExit("--shrink-plan device counts must be >= 1")
    advisor = None
    if args.adapt:
        from jimm_tpu.resilience import GoodputAdvisor

        # seed the knobs from the train command itself (which already
        # folded in any adopted_runtime pick): adopted-plus-adapted
        advisor = GoodputAdvisor(knobs={
            "save_every": int(_argv_flag_value(cmd, "--save-every", 50)),
            "grace_steps": int(_argv_flag_value(cmd, "--grace-steps", 1)),
            "scan_unroll": int(_argv_flag_value(cmd, "--scan-unroll", 0)),
        })
    sup = Supervisor(max_restarts=args.max_restarts,
                     backoff=BackoffPolicy(base_s=args.backoff_base_s,
                                           max_s=args.backoff_max_s,
                                           jitter=0.5, seed=args.seed))
    # elastic state threaded through attempts: the previous attempt's mesh
    # width (to count replans) and the goodput counter values already
    # booked (to hand the advisor per-attempt deltas)
    elastic_state: dict[str, Any] = {"last_k": None, "booked": {}}

    def _observe_goodput(attempt_i: int, t0: float) -> None:
        from jimm_tpu import obs
        snap = obs.snapshot()
        prefix = "jimm_train_goodput_"
        deltas = {}
        for key, value in snap.items():
            if key.startswith(prefix) and key.endswith("_seconds_total"):
                bucket = key[len(prefix):-len("_seconds_total")]
                deltas[bucket] = value - elastic_state["booked"].get(key, 0.0)
                elastic_state["booked"][key] = value
        import time as _time
        advisor.observe(attempt_i, _time.monotonic() - t0, deltas)

    def attempt(i: int, resume: bool) -> int:
        argv = list(cmd)
        if resume and "--resume" not in argv:
            argv.append("--resume")
        if args.elastic:
            import jax
            avail = len(jax.devices())
            if shrink_plan is not None:
                avail = min(avail,
                            shrink_plan[min(i, len(shrink_plan) - 1)])
            from jimm_tpu.resilience import plan_data_axis
            batch = int(_argv_flag_value(argv, "--batch-size", 32))
            k = plan_data_axis(avail, batch)
            # appended AFTER the user's flags: argparse last-wins makes the
            # replanned mesh effective without rewriting their command
            argv += ["--mesh", f"data={k}", "--rules", "dp",
                     "--max-devices", str(k)]
            if (elastic_state["last_k"] is not None
                    and k != elastic_state["last_k"]):
                from jimm_tpu.obs import get_registry
                from jimm_tpu.obs.journal import get_journal
                get_registry("jimm_train").counter(
                    "topology_changes_total").inc()
                # runs inside the supervisor's correlate(incident) scope,
                # so the replan joins the preemption/crash chain ambiently
                get_journal().emit("mesh_replanned", attempt=i + 1,
                                   data_from=elastic_state["last_k"],
                                   data_to=k, devices=avail)
                print(f"[supervise] attempt {i + 1}: replanned mesh "
                      f"data={elastic_state['last_k']} -> data={k} "
                      f"({avail} devices available)")
            elastic_state["last_k"] = k
        if advisor is not None:
            argv += advisor.argv_overrides()
        import time as _time
        t0 = _time.monotonic()
        try:
            ns = build_parser().parse_args(argv)
            return ns.fn(ns)
        finally:
            if advisor is not None:
                _observe_goodput(i, t0)

    try:
        rc = sup.run(attempt)
    except GiveUpError as e:
        print(f"supervise: {e}", file=sys.stderr)
        return 1
    # one parseable line with the resilience counters, so external drills
    # (scripts/resilience_smoke.py, CI) can assert on them cross-process
    import json as _json

    from jimm_tpu import obs
    snap = obs.snapshot()
    keys = ("jimm_train_restarts_total", "jimm_train_preemptions_total",
            "jimm_train_checkpoint_quarantined_total",
            "jimm_train_goodput_lost_work_seconds_total",
            "jimm_train_goodput_preemption_save_seconds_total")
    if args.elastic:
        keys += ("jimm_train_topology_changes_total",
                 "jimm_train_checkpoint_topology_changes_total")
    if advisor is not None:
        keys += ("jimm_train_goodput_advisor_decisions_total",)
    print("resilience: "
          + _json.dumps({k: snap.get(k, 0.0) for k in keys}))
    return rc


def cmd_evaluate(args: argparse.Namespace) -> int:
    """Evaluate a model over a file dataset (single non-repeating pass).

    - vit: top-1 accuracy over labeled records
    - clip/siglip: in-batch retrieval R@1, image->text and text->image
      (diagonal is the positive pair, as in contrastive training)

    Weights: ``--ckpt`` (HF checkpoint: local safetensors file/dir or hub
    id) or ``--preset`` + ``--ckpt-dir`` (orbax training checkpoint).
    Prints one JSON line.
    """
    _configure_backend(args)
    import json

    import jax.numpy as jnp
    import numpy as np
    from flax import nnx

    from jimm_tpu import preset
    from jimm_tpu.utils import jit_forward

    if args.ckpt:
        if not (args.model or args.preset):
            raise SystemExit("--ckpt needs --model (or --preset to infer "
                             "the family)")
        fam = args.model or _family(args.preset)
        model = _model_cls(fam).from_pretrained(
            args.ckpt, dtype=jnp.bfloat16 if args.bf16 else None)
        cfg = model.config
    else:
        if not (args.preset and args.ckpt_dir):
            raise SystemExit("need --ckpt, or --preset with --ckpt-dir")
        fam, model = _restore_run(args)
        cfg = model.config

    # family-correct normalization, SAME helper as cmd_train's loaders —
    # eval must see the pixels training saw; square resize is the shared
    # file-pipeline convention (classify's center-crop is for wild images)
    norm = _norm_for(fam)

    if args.naflex and (fam == "vit" or args.zero_shot):
        raise SystemExit("--naflex applies to clip/siglip retrieval "
                         "evaluation (not vit accuracy or --zero-shot)")
    fwd = jit_forward(model)
    n = 0
    if args.zero_shot:
        if fam == "vit":
            raise SystemExit("--zero-shot needs a contrastive model "
                             "(clip/siglip); vit evaluates accuracy "
                             "directly")
        metrics, n = _zero_shot_eval(args, model, cfg, norm)
    elif fam == "vit":
        if _is_tar_data(args.data):
            from jimm_tpu.data.webdataset import (
                wds_classification_batches as classification_batches)
        else:
            from jimm_tpu.data.records import classification_batches
        correct = 0
        for images, labels in classification_batches(
                args.data, args.batch_size, image_size=cfg.vision.image_size,
                repeat=False, shuffle_buffer=0, drop_remainder=False):
            pred = np.asarray(jnp.argmax(fwd(jnp.asarray(images)), axis=-1))
            correct += int((pred == labels).sum())
            n += len(labels)
        if not n:
            raise SystemExit(f"no examples in {args.data}")
        metrics = {"top1_accuracy": round(correct / n, 4)}
    else:
        if args.naflex:
            # variable-resolution retrieval: aspect-preserving NaFlex
            # batches + masked logits instead of the square resize
            if fam != "siglip":
                raise SystemExit("--naflex evaluates SigLIP2-style models; "
                                 "use --model siglip")
            if _is_tar_data(args.data):
                raise SystemExit("--naflex reads tfrecord shards")
            from jimm_tpu.data.records import naflex_image_text_batches

            def batches():
                return naflex_image_text_batches(
                    args.data, args.batch_size,
                    patch_size=cfg.vision.patch_size,
                    max_num_patches=cfg.vision.num_patches,
                    seq_len=cfg.text.context_length, repeat=False,
                    shuffle_buffer=0, drop_remainder=False, **norm)

            logits_fn = nnx.jit(
                lambda m, im, tok: m.logits_naflex(*im, tok))
        else:
            if _is_tar_data(args.data):
                from jimm_tpu.data.webdataset import (
                    wds_image_text_batches as image_text_batches)
            else:
                from jimm_tpu.data.records import image_text_batches

            def batches():
                return image_text_batches(
                    args.data, args.batch_size,
                    image_size=cfg.vision.image_size,
                    seq_len=cfg.text.context_length, repeat=False,
                    shuffle_buffer=0, drop_remainder=False, **norm)

            logits_fn = nnx.jit(lambda m, im, tok: m(im, tok))
        i2t = t2i = 0
        for images, tokens in batches():
            if args.naflex:
                images = tuple(jnp.asarray(a) for a in images)
            else:
                images = jnp.asarray(images)
            logits = np.asarray(
                logits_fn(model, images, jnp.asarray(tokens)), np.float32)
            diag = np.arange(len(logits))
            i2t += int((logits.argmax(axis=1) == diag).sum())
            t2i += int((logits.argmax(axis=0) == diag).sum())
            n += len(logits)
        if not n:
            raise SystemExit(f"no examples in {args.data}")
        metrics = {"retrieval_r1_image_to_text": round(i2t / n, 4),
                   "retrieval_r1_text_to_image": round(t2i / n, 4)}
    print(json.dumps({"examples": n, "batch_size": args.batch_size,
                      **metrics}))
    return 0


def _zero_shot_eval(args: argparse.Namespace, model, cfg, norm
                    ) -> tuple[dict, int]:
    """Zero-shot classification accuracy (the CLIP-paper benchmark flow)
    over *classification* records: ensemble classifier weights from a
    tokens file, then one image-encoder pass + a (B, D) @ (D, C) matmul
    per batch — no text tower in the loop.

    ``--zero-shot tokens.json``: ``{label: [ids]}`` or
    ``{label: [[ids], [ids], ...]}`` (multiple prompt templates per class,
    ensemble-averaged). Class order follows the dataset's own
    ``classes.json`` when present (index == label id), else the file's
    insertion order.
    """
    import json

    import jax.numpy as jnp
    import numpy as np
    from flax import nnx

    from jimm_tpu.data.records import pad_tokens
    from jimm_tpu.utils.zero_shot import zero_shot_logits_from_features

    table = json.loads(open(args.zero_shot).read())
    labels = _dataset_classes(args.data) or list(table)
    missing = [label for label in labels if label not in table]
    if missing:
        raise SystemExit(f"--zero-shot file lacks tokens for classes "
                         f"{missing[:5]} (dataset classes.json order)")
    rows, owner = [], []
    for ci, label in enumerate(labels):
        entry = table[label]
        per_class = entry if entry and isinstance(entry[0], list) else [entry]
        for r in per_class:
            if len(r) > cfg.text.context_length:
                raise SystemExit(
                    f"tokens for {label!r} are {len(r)} ids but the "
                    f"checkpoint's context_length is "
                    f"{cfg.text.context_length}; re-tokenize to fit")
            rows.append(pad_tokens(r, cfg.text.context_length))
            owner.append(ci)
    emb = np.array(model.encode_text(jnp.asarray(np.stack(rows))),
                   np.float32)  # copy: jax buffers are read-only
    emb /= np.linalg.norm(emb, axis=-1, keepdims=True)
    owner_arr = np.asarray(owner)
    weights = np.stack([emb[owner_arr == ci].mean(axis=0)
                        for ci in range(len(labels))])
    weights /= np.linalg.norm(weights, axis=-1, keepdims=True)
    weights = jnp.asarray(weights)

    if _is_tar_data(args.data):
        from jimm_tpu.data.webdataset import (
            wds_classification_batches as classification_batches)
    else:
        from jimm_tpu.data.records import classification_batches
    encode = nnx.jit(lambda m, im: m.encode_image(im))
    correct = n = 0
    for images, y in classification_batches(
            args.data, args.batch_size, image_size=cfg.vision.image_size,
            repeat=False, shuffle_buffer=0, drop_remainder=False, **norm):
        feats = encode(model, jnp.asarray(images))
        logits = np.asarray(
            zero_shot_logits_from_features(model, feats, weights),
            np.float32)
        correct += int((logits.argmax(axis=1) == y).sum())
        n += len(y)
    if not n:
        raise SystemExit(f"no examples in {args.data}")
    return {"zero_shot_top1": round(correct / n, 4),
            "classes": len(labels),
            "prompts": len(rows)}, n


def cmd_export_run(args: argparse.Namespace) -> int:
    """Export a TRAINING RUN (orbax checkpoint) as an HF-interoperable
    safetensors directory — the fine-tune → share loop: the output loads in
    `transformers` and back through `from_pretrained`. (`export` converts
    HF checkpoints; this converts this framework's own runs.)"""
    _configure_backend(args)
    _, model = _restore_run(args)
    _model_save(model, args)
    print(f"exported {args.ckpt_dir} -> {args.out}")
    return 0


def cmd_prepare_data(args: argparse.Namespace) -> int:
    """Build tfrecord shards (the format `--data` consumes) from raw files.

    - ``--task classification``: SRC/<class_name>/*.{jpg,jpeg,png} — labels
      are sorted class-directory indices; writes ``classes.json`` alongside
      the shards.
    - ``--task contrastive``: SRC holds the images; ``--captions`` is a TSV
      of ``relative/path<TAB>caption``. Captions that are whitespace-
      separated integers are taken as pre-tokenized ids; otherwise
      ``--tokenizer`` names a HuggingFace tokenizer (needs the optional
      ``transformers`` install — tokenization is offline-optional tooling,
      never a runtime dependency).
    """
    import json
    import re
    from pathlib import Path

    from jimm_tpu.data.tfrecord import TFRecordWriter, encode_example

    src, out = Path(args.src), Path(args.out)
    out.mkdir(parents=True, exist_ok=True)
    stale = sorted(out.glob("part-*.tfrecord"))
    if stale:
        # the readers glob the whole dir: leftover higher-numbered shards
        # from a previous run would silently mix into the dataset
        raise SystemExit(f"{out} already holds {len(stale)} shard(s) "
                         f"({stale[0].name}..); remove them or use a fresh "
                         "output directory")
    exts = {".jpg", ".jpeg", ".png"}
    _INT = re.compile(r"^-?\d+$")

    class ShardWriter:
        """Rotates part-NNNNN.tfrecord files every --shard-size examples."""

        def __init__(self):
            self.n_in_shard = 0
            self.shards = 0
            self.total = 0
            self._w = None

        def write(self, payload: bytes) -> None:
            if self._w is None or self.n_in_shard >= args.shard_size:
                self.close()
                self._w = TFRecordWriter(
                    out / f"part-{self.shards:05d}.tfrecord")
                self.shards += 1
                self.n_in_shard = 0
            self._w.write(payload)
            self.n_in_shard += 1
            self.total += 1

        def close(self) -> None:
            if self._w is not None:
                self._w.close()
                self._w = None

    writer = ShardWriter()
    classes: dict[str, int] = {}
    try:
        if args.task == "classification":
            names = sorted(d.name for d in src.iterdir() if d.is_dir())
            if not names:
                raise SystemExit(f"no class directories under {src}")
            classes = {name: i for i, name in enumerate(names)}
            for name, label in classes.items():
                for img in sorted((src / name).iterdir()):
                    if img.suffix.lower() not in exts or not img.is_file():
                        continue
                    writer.write(encode_example({"image": img.read_bytes(),
                                                 "label": label}))
        else:  # contrastive
            if not args.captions:
                raise SystemExit("--task contrastive needs --captions TSV")
            tok = None
            for ln, line in enumerate(
                    Path(args.captions).read_text().splitlines(), 1):
                if not line.strip():
                    continue
                rel, _, caption = line.partition("\t")
                parts = caption.split()
                if not parts:
                    raise SystemExit(f"{args.captions}:{ln}: no caption "
                                     f"after TAB (line {line[:60]!r})")
                if all(_INT.match(p) for p in parts):
                    ids = [int(p) for p in parts]  # pre-tokenized
                else:
                    if tok is None:
                        if not args.tokenizer:
                            raise SystemExit(
                                f"{args.captions}:{ln}: text caption needs "
                                "--tokenizer (HF name/path)")
                        from transformers import AutoTokenizer  # opt tooling
                        tok = AutoTokenizer.from_pretrained(args.tokenizer)
                    ids = tok(caption)["input_ids"]
                if len(ids) > args.seq_len:
                    # keep the FINAL token when truncating: CLIP pools the
                    # text tower at the EOT position (argmax of ids), and a
                    # plain tail-chop would drop it — `classify` refuses
                    # exactly this silent truncation (see its context-length
                    # guard); the training-data writer must not do it either
                    ids = list(ids[:args.seq_len - 1]) + [ids[-1]]
                writer.write(encode_example(
                    {"image": (src / rel).read_bytes(),
                     "tokens": ids}))
    finally:
        writer.close()  # flush the open shard even on a mid-run error
    if not writer.total:
        raise SystemExit(f"no examples found under {src}")
    if classes:
        # written last: a failed run must not leave a plausible-looking
        # classes.json next to no (or partial) shards
        (out / "classes.json").write_text(json.dumps(classes, indent=2))
    print(f"wrote {writer.total} examples in {writer.shards} shard(s) "
          f"to {out}")
    return 0


def cmd_classify(args: argparse.Namespace) -> int:
    """Zero-shot image classification with CLIP/SigLIP (the reference's
    `examples/clip_inference.py` flow as a command).

    Label prompts come from ``--labels`` (tokenized via ``--tokenizer``, an
    optional HF tokenizer — tooling only, never a runtime dependency) or
    from ``--tokens-file`` (JSON ``{label: [token ids]}``, fully offline).
    """
    _configure_backend(args)
    import json

    import jax.numpy as jnp
    import numpy as np

    from jimm_tpu.data.preprocess import (CLIP_MEAN, CLIP_STD, SIGLIP_MEAN,
                                          SIGLIP_STD, preprocess_batch)
    from jimm_tpu.data.records import decode_image, pad_tokens
    from jimm_tpu.serve.cache import class_embedding_cache, prompt_set_key
    from jimm_tpu.utils.zero_shot import (weights_from_rows,
                                          zero_shot_logits_from_features)

    model_cls = _model_cls(args.model)
    dtype = jnp.bfloat16 if args.bf16 else jnp.float32
    model = model_cls.from_pretrained(args.ckpt, dtype=dtype)
    cfg = model.config

    if args.tokens_file:
        if args.ensemble:
            raise SystemExit("--ensemble builds prompts from templates; it "
                             "needs --labels (+ a tokenizer), not "
                             "--tokens-file")
        table = json.loads(open(args.tokens_file).read())
        labels = list(table)
        rows = [table[k] for k in labels]
        for k, r in table.items():
            if len(r) > cfg.text.context_length:
                # silent truncation could drop the EOT token CLIP pools at
                raise SystemExit(
                    f"tokens for {k!r} are {len(r)} ids but the checkpoint's "
                    f"context_length is {cfg.text.context_length}; "
                    "re-tokenize to fit")
    else:
        if not args.labels:
            raise SystemExit("need --labels (with --tokenizer or a CLIP "
                             "checkpoint dir holding vocab.json/merges.txt), "
                             "or --tokens-file")
        labels = [s.strip() for s in args.labels.split(",") if s.strip()]
        template = args.template or "a photo of a {}"
        if args.ensemble:
            # CLIP-paper recipe: average each class over prompt templates
            # (normalize, mean, renormalize); an explicit --template
            # supplies the set ("|"-separated; a single entry works), else
            # the builtin 7-template subset
            from jimm_tpu.utils.zero_shot import TEMPLATES, expand_templates
            templates = (tuple(t for t in args.template.split("|") if t)
                         if args.template else TEMPLATES)
            prompts = expand_templates(labels, templates)
        else:
            prompts = [template.format(label) for label in labels]
        rows = None
        if not args.tokenizer and args.model == "clip":
            # zero-dependency path: every HF CLIP checkpoint ships its BPE
            # vocab; use the built-in tokenizer when the files are local
            from pathlib import Path

            from jimm_tpu.data.clip_tokenizer import CLIPTokenizer
            p = Path(args.ckpt)
            d = p if p.is_dir() else p.parent
            if (d / "vocab.json").is_file() and (d / "merges.txt").is_file():
                rows = CLIPTokenizer.from_dir(d)(
                    prompts, context_length=cfg.text.context_length)
        if rows is None:
            if not args.tokenizer:
                raise SystemExit(
                    "no vocab.json/merges.txt next to the checkpoint; pass "
                    "--tokenizer (HF name/path) or --tokens-file")
            from transformers import AutoTokenizer  # optional tooling
            tok = AutoTokenizer.from_pretrained(args.tokenizer)
            rows = tok(prompts, padding="max_length", truncation=True,
                       max_length=cfg.text.context_length)["input_ids"]
    text = jnp.asarray(np.stack(
        [pad_tokens(r, cfg.text.context_length) for r in rows]))

    # class weights go through the serving embedding cache, keyed on
    # (checkpoint, family, dtype, token rows): repeat classify calls in one
    # process — and the `jimm-tpu serve` endpoint — skip the text tower.
    # Non-ensemble is the one-row-per-class special case of the same
    # normalize/mean/renormalize math, so every path shares one matmul form.
    if args.ensemble:
        n_templates = text.shape[0] // len(labels)
        owner = [i // n_templates for i in range(text.shape[0])]
    else:
        owner = list(range(len(labels)))
    model_key = (f"{args.model}:{args.ckpt}:"
                 f"{'bf16' if args.bf16 else 'f32'}")
    if args.index:
        # persistent tier: the retrieval store's prompt cache survives
        # process restarts, so repeat CLI invocations skip the text tower
        # entirely (same get_or_build surface as the in-process cache)
        from jimm_tpu.retrieval import VectorStore
        cache = VectorStore(args.index).prompt_cache()
    else:
        cache = class_embedding_cache()
    weights = cache.get_or_build(
        prompt_set_key(model_key, np.asarray(text)),
        lambda: np.asarray(
            weights_from_rows(model, text, owner, len(labels)), np.float32))

    with open(args.image, "rb") as f:
        img = decode_image(f.read())
    mean, std = ((CLIP_MEAN, CLIP_STD) if args.model == "clip"
                 else (SIGLIP_MEAN, SIGLIP_STD))
    if args.naflex:
        # variable-resolution path: aspect-preserving patch grid + mask
        # instead of the square resize (SigLIP2 NaFlex; beyond the
        # reference's non-NaFlex-only support)
        if args.model != "siglip":
            raise SystemExit("--naflex is a SigLIP2 feature; use "
                             "--model siglip")
        from jimm_tpu.data.naflex import patchify_naflex
        from jimm_tpu.data.preprocess import to_float_normalized
        im = to_float_normalized(img[None], mean, std)[0]
        patches, shapes, mask = patchify_naflex(
            [im], patch_size=cfg.vision.patch_size,
            max_num_patches=cfg.vision.num_patches)
        feats = model.encode_image_naflex(
            jnp.asarray(patches, dtype), jnp.asarray(shapes),
            jnp.asarray(mask))
    else:
        # CLIP checkpoints are trained with shortest-side resize + center
        # crop; SigLIP's processor resizes straight to the square
        batch = preprocess_batch(img[None],
                                 image_size=cfg.vision.image_size,
                                 mean=mean, std=std,
                                 crop=args.model == "clip")
        feats = model.encode_image(jnp.asarray(batch, dtype))
    logits = np.asarray(zero_shot_logits_from_features(
        model, feats, jnp.asarray(weights)), np.float32)[0]
    if args.model == "siglip":
        scores = 1.0 / (1.0 + np.exp(-logits))  # per-pair sigmoid
    else:
        e = np.exp(logits - logits.max())
        scores = e / e.sum()
    for i in np.argsort(-scores):
        print(f"{scores[i]:8.4f}  {labels[i]}")
    return 0


def _model_save(model, args: argparse.Namespace) -> None:
    """Model-method export (flavor-aware for SigLIP): --flavor picks the
    HF format for SigLIP2-origin checkpoints; default matches the source."""
    flavor = getattr(args, "flavor", "auto")
    if flavor != "auto" and not hasattr(model, "_save_pretrained_siglip2"):
        raise SystemExit("--flavor applies to SigLIP models only")
    if flavor == "auto":
        model.save_pretrained(args.out)
    else:
        model.save_pretrained(args.out, flavor=flavor)


def cmd_export(args: argparse.Namespace) -> int:
    _configure_backend(args)
    import jax.numpy as jnp

    dtype = jnp.bfloat16 if args.bf16 else jnp.float32
    model = _model_cls(args.model).from_pretrained(args.src, dtype=dtype)
    _model_save(model, args)
    print(f"exported {args.src} -> {args.out}")
    return 0


def cmd_inspect(args: argparse.Namespace) -> int:
    import math

    from jimm_tpu.weights.safetensors_io import read_header
    header, _ = read_header(args.file)
    total = 0
    for name, meta in sorted(header.items()):
        if name == "__metadata__":
            continue
        shape, dtype = meta["shape"], meta["dtype"]
        total += math.prod(int(s) for s in shape)
        print(f"{name:60s} {dtype:10s} {tuple(shape)}")
    print(f"-- {total / 1e6:.1f}M parameters")
    return 0


def _positive_int(v: str) -> int:
    n = int(v)
    if n < 1:
        raise argparse.ArgumentTypeError(f"must be >= 1, got {n}")
    return n


def cmd_profile_analyze(args: argparse.Namespace) -> int:
    """Offline per-op summary of a jax.profiler capture (no TensorBoard)."""
    from jimm_tpu.train.profile import op_stats, summarize
    device = None if args.device < 0 else args.device
    print(summarize(op_stats(args.dir, device=device), top=args.top,
                    steps=args.steps))
    return 0


def cmd_build_native(args: argparse.Namespace) -> int:
    """Compile the native host-preprocessing library (g++, no deps)."""
    import pathlib
    import subprocess
    native_dir = pathlib.Path(__file__).resolve().parents[1] / "native"
    rc = subprocess.call(["make", "-C", str(native_dir)])
    if rc == 0:
        from jimm_tpu.data.preprocess import _load_library
        ok = _load_library() is not None
        print("native preprocessing library built and loadable"
              if ok else "built, but failed to load")
        return 0 if ok else 1
    return rc


def cmd_bench_forward(args: argparse.Namespace) -> int:
    _configure_backend(args)
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    from flax import nnx

    from jimm_tpu import preset
    from jimm_tpu.utils import jit_forward

    fam = _family(args.preset)
    cfg = preset(args.preset)
    if args.tiny:
        cfg = _tiny_override(cfg)
    dtype = jnp.bfloat16 if args.bf16 else jnp.float32
    model = _model_cls(fam)(cfg, rngs=nnx.Rngs(0), dtype=dtype, param_dtype=dtype)
    fwd = jit_forward(model)

    rng = np.random.RandomState(0)
    images = jnp.asarray(rng.randn(args.batch_size, cfg.vision.image_size,
                                   cfg.vision.image_size, 3), dtype)
    inputs = (images,)
    if fam in ("clip", "siglip"):
        text = jnp.asarray(rng.randint(1, cfg.text.vocab_size,
                                       size=(args.batch_size,
                                             cfg.text.context_length)),
                           jnp.int32)
        inputs = (images, text)

    out = fwd(*inputs)
    jax.device_get(out)
    t0 = time.perf_counter()
    for _ in range(args.steps):
        out = fwd(*inputs)
    jax.device_get(out)
    dt = (time.perf_counter() - t0) / args.steps
    print(f"{args.preset}: {args.batch_size / dt:.1f} images/sec "
          f"({dt * 1e3:.2f} ms/batch of {args.batch_size})")
    return 0


def _parse_pool_model(spec: str) -> tuple[str, str, str]:
    """Parse one ``--pool-model NAME=PRESET[@DTYPE]`` spec into
    ``(name, preset, dtype)``. DTYPE defaults to f32."""
    name, sep, rest = spec.partition("=")
    if not sep or not name or not rest:
        raise SystemExit(f"--pool-model {spec!r}: expected "
                         "NAME=PRESET[@DTYPE]")
    if name == "default":
        raise SystemExit("--pool-model: 'default' names the primary model; "
                         "pick another name")
    preset_name, _, dtype = rest.partition("@")
    dtype = dtype or "f32"
    if dtype not in ("f32", "bf16", "int8"):
        raise SystemExit(f"--pool-model {spec!r}: dtype must be "
                         "f32|bf16|int8")
    return name, preset_name, dtype


def cmd_serve(args: argparse.Namespace) -> int:
    """HTTP micro-batching inference server (see docs/serving.md).

    Loads a checkpoint (or random-initializes a preset — wiring and latency
    smoke tests without weights), warm-compiles every batch bucket, then
    serves ``/v1/embed`` and ``/v1/classify`` with bounded-queue admission
    control. ``/healthz`` and ``/metrics`` report engine state.
    """
    _configure_backend(args)
    _configure_journal(args)
    import json
    import time

    import jax.numpy as jnp
    from flax import nnx

    from jimm_tpu import preset
    from jimm_tpu.serve import (AdmissionPolicy, BucketTable, InferenceEngine,
                                ServingServer, ZeroShotService,
                                counting_forward, default_buckets)

    if args.tune_cache:
        # point kernel block resolution at an offline-tuned cache BEFORE any
        # trace: ops consult tune.best_config at trace time (lookup only —
        # serving never measures; populate with `jimm-tpu tune`)
        from jimm_tpu.tune import configure as tune_configure
        tune_configure(args.tune_cache)

    serve_dtype = _serve_dtype(args)
    # int8 builds/loads the model in f32, then quantizes in place below
    dtype = jnp.bfloat16 if serve_dtype == "bf16" else jnp.float32
    if args.ckpt:
        fam = args.model or (_family(args.preset) if args.preset else None)
        if fam is None:
            raise SystemExit("--ckpt needs --model (or --preset) to pick "
                             "the model family")
        model = _model_cls(fam).from_pretrained(args.ckpt, dtype=dtype)
        model_key = f"{fam}:{args.ckpt}"
    elif args.preset:
        fam = _family(args.preset)
        cfg = preset(args.preset)
        if args.tiny:
            cfg = _tiny_override(cfg)
        model = _model_cls(fam)(cfg, rngs=nnx.Rngs(0), dtype=dtype,
                                param_dtype=dtype)
        model_key = f"{fam}:{args.preset}" + (":tiny" if args.tiny else "")
    else:
        raise SystemExit("need --ckpt (with --model) or --preset")
    model_key += ":" + serve_dtype
    if serve_dtype == "int8":
        if args.model_parallel > 1:
            raise SystemExit("--dtype int8 does not support "
                             "--model-parallel > 1 yet (QuantLinear params "
                             "carry no logical sharding axes); use data "
                             "replicas")
        # in-place Linear -> QuantLinear surgery BEFORE any forward is
        # built, so the warm compiles (and AOT fingerprints, via the
        # aggregate param_dtype) see the quantized model
        from jimm_tpu.quant import quantize_model
        quantize_model(model)

    method = "encode_image" if fam in ("clip", "siglip") else "__call__"
    size = model.config.vision.image_size
    store = None
    if args.aot_store:
        # store-first warm start: buckets precompiled by `jimm-tpu aot
        # warmup` deserialize instead of compiling; anything else compiles
        # fresh and is written through for the next restart
        from jimm_tpu.aot import ArtifactStore
        store = ArtifactStore(args.aot_store)
    from jimm_tpu.serve.topology import build_replica_forwards, plan_topology
    plan = plan_topology(args.replicas, args.model_parallel,
                         getattr(args, "seq_parallel", 1))

    def _build_forward(mdl, mdl_method, mdl_size, key):
        if not plan.is_trivial:
            # multi-chip serving: N replica groups of (data=1, model=k)
            # submeshes, each with its own sharded param copy + warm
            # forward, load-balanced behind the one admission queue
            return build_replica_forwards(
                mdl, plan, method=mdl_method,
                item_shape=(mdl_size, mdl_size, 3), store=store, label=key)
        if store is not None:
            from jimm_tpu.aot.warmup import AotForward
            fwd = AotForward(mdl, method=mdl_method,
                             item_shape=(mdl_size, mdl_size, 3),
                             store=store, label=key)
            return fwd, fwd.trace_count
        return counting_forward(mdl, mdl_method)

    forward, trace_count = _build_forward(model, method, size, model_key)
    _bucket_dtypes = {"f32": "float32", "bf16": "bfloat16", "int8": "int8"}
    bucket_dtype = _bucket_dtypes[serve_dtype]
    buckets = (BucketTable(tuple(int(s) for s in args.buckets.split(",")),
                           dtype=bucket_dtype)
               if args.buckets else default_buckets(dtype=bucket_dtype))
    policy = AdmissionPolicy(max_queue=args.queue_size,
                             default_timeout_s=args.timeout_s,
                             shed_fraction=args.shed_fraction)
    qos = None
    if args.qos_policy:
        # tenant-aware admission + weighted-fair scheduling; without the
        # flag `qos` stays None and every serve path below is byte-
        # identical to the policy-free server
        from jimm_tpu.serve.qos import QosScheduler, load_policy
        qos = QosScheduler(load_policy(args.qos_policy))
    engine = InferenceEngine(forward, item_shape=(size, size, 3),
                             buckets=buckets,
                             max_delay_ms=args.max_delay_ms, policy=policy,
                             trace_count=trace_count, qos=qos)
    if qos is not None and qos.registry.slo:
        # the policy's slo section -> per-tenant burn-rate tracking; a
        # fast burn escalates into the self-heal path and flips /healthz
        from jimm_tpu.obs.slo import SloEngine
        engine.attach_slo(SloEngine.from_objective_dicts(qos.registry.slo))
    if args.self_heal:
        if plan.is_trivial:
            raise SystemExit("--self-heal needs a replica topology "
                             "(--replicas/--model-parallel > 1): a single "
                             "lane has nothing to replan around")
        # watchdog escalation: fence -> probe/revive -> rebuild the full
        # replica set from the AOT store and replan around the dead lane.
        # The factory reuses _build_forward, so a warm store means the
        # rebuild deserializes executables — zero fresh traces.
        engine.set_heal(
            lambda: _build_forward(model, method, size, model_key))
    pool = None
    pool_traces = []
    pool_models = [model]
    if args.pool_model:
        # multi-model residency: each extra model gets its own warm engine
        # (own buckets + own AOT fingerprint via its model_key, so the
        # f32/int8 twins never adopt each other's executables) behind the
        # same metrics surface and QoS scheduler; requests route with the
        # `model=` field / X-Jimm-Model header
        from jimm_tpu.serve.qos import ModelPool
        engines = {"default": engine}
        for spec in args.pool_model:
            pname, ppreset, pdtype = _parse_pool_model(spec)
            if pname in engines:
                raise SystemExit(f"--pool-model: duplicate name {pname!r}")
            pfam = _family(ppreset)
            pcfg = preset(ppreset)
            if args.tiny:
                pcfg = _tiny_override(pcfg)
            pjdtype = jnp.bfloat16 if pdtype == "bf16" else jnp.float32
            pmodel = _model_cls(pfam)(pcfg, rngs=nnx.Rngs(0), dtype=pjdtype,
                                      param_dtype=pjdtype)
            pkey = (f"{pfam}:{ppreset}" + (":tiny" if args.tiny else "")
                    + ":" + pdtype)
            if pdtype == "int8":
                if args.model_parallel > 1:
                    raise SystemExit(
                        f"--pool-model {pname}: int8 does not support "
                        "--model-parallel > 1 (same constraint as --dtype "
                        "int8); use data replicas")
                from jimm_tpu.quant import quantize_model
                quantize_model(pmodel)
            pmethod = ("encode_image" if pfam in ("clip", "siglip")
                       else "__call__")
            psize = pmodel.config.vision.image_size
            pforward, ptrace = _build_forward(pmodel, pmethod, psize, pkey)
            pengine = InferenceEngine(
                pforward, item_shape=(psize, psize, 3),
                buckets=BucketTable(buckets.sizes,
                                    dtype=_bucket_dtypes[pdtype]),
                max_delay_ms=args.max_delay_ms, policy=policy,
                metrics=engine.metrics, qos=qos)
            # per-model compile gauge (the bare `compile_count` gauge stays
            # the default model's, bound above via trace_count=)
            engine.metrics.bind_gauge(f"model_{pname}_compile_count", ptrace)
            pool_traces.append(ptrace)
            pool_models.append(pmodel)
            engines[pname] = pengine
        pool = ModelPool(engines, default="default")
        # every extra engine's __init__ re-bound queue_depth_now to its own
        # queue (latest wins); restore it to the default model's
        engine.metrics.bind_gauge(
            "queue_depth_now",
            lambda e=engine: (float(e._queue.qsize())
                              if e._queue is not None else 0.0))
    zero_shot = (ZeroShotService(model, model_key=model_key)
                 if fam in ("clip", "siglip") else None)
    retrieval = None
    index_daemon = None
    if args.index:
        if not args.index_store:
            raise SystemExit("--index needs --index-store (the vector "
                             "store root)")
        # /v1/search: load the named index snapshot and build its warm
        # searcher over the same topology (and AOT store) as the engine
        from jimm_tpu.retrieval import RetrievalService, VectorStore
        vstore = VectorStore(args.index_store)
        retrieval = RetrievalService.from_store(
            vstore, args.index, k=args.search_k, plan=plan,
            aot_store=store, mode=args.index_mode, nprobe=args.nprobe,
            nprobe_max=args.nprobe_max,
            device_budget_bytes=(args.tier_device_budget_mb << 20
                                 if args.tier_device_budget_mb is not None
                                 else None),
            host_budget_bytes=(args.tier_host_budget_mb << 20
                               if args.tier_host_budget_mb is not None
                               else None))
        if args.tier_daemon_interval is not None:
            if args.index_mode != "tiered":
                raise SystemExit("--tier-daemon-interval needs "
                                 "--index-mode tiered")
            from jimm_tpu.retrieval.tier import IndexDaemon
            index_daemon = IndexDaemon(vstore, args.index,
                                       retrieval.searcher)
            index_daemon.start(args.tier_daemon_interval)
    elif args.index_store:
        raise SystemExit("--index-store needs --index (the index name)")
    logger = None
    if args.metrics_file:
        from jimm_tpu.train.metrics import MetricsLogger
        logger = MetricsLogger(path=args.metrics_file,
                               print_every=10 ** 9)  # JSONL only, no console
    monitor = None
    if args.prof_dir:
        # continuous profiling + HBM watchdog: the capture manager is
        # process-global so heal/replan/SLO-burn paths (and POST
        # /admin/prof/trigger) deep-capture onto their incident cids
        from jimm_tpu.obs.prof.capture import configure_capture
        from jimm_tpu.obs.prof.memory import MemoryMonitor
        configure_capture(args.prof_dir)
        monitor = MemoryMonitor()

        def _model_pool_bytes() -> float:
            import jax
            total = 0.0
            for m in pool_models:
                for leaf in jax.tree_util.tree_leaves(nnx.state(m)):
                    total += float(getattr(leaf, "nbytes", 0) or 0)
            return total

        monitor.register_subsystem("model_pool", _model_pool_bytes)
        monitor.register_subsystem(
            "serve_buffers", lambda: float(engine._traces_bytes))
        if retrieval is not None:
            info = retrieval.describe()
            if info["mode"] == "tiered":
                # tiered residency: report the (flat) hot-arena bytes,
                # not the corpus size the budget exists to decouple from
                monitor.register_subsystem(
                    "retrieval_index",
                    lambda s=retrieval.searcher: float(s.resident_bytes()))
            else:
                monitor.register_subsystem(
                    "retrieval_index",
                    lambda r=info["rows"], d=info["dim"]: float(r * d * 4))
        monitor.start()
    server = ServingServer(engine, zero_shot=zero_shot,
                           retrieval=retrieval, host=args.host,
                           port=args.port, metrics_logger=logger,
                           metrics_log_every_s=args.metrics_every_s,
                           pool=pool)
    t0 = time.monotonic()
    server.start()
    ready = {"status": "serving", "host": args.host,
             "port": server.port, "model": model_key,
             "buckets": list(buckets.sizes), "dtype": buckets.dtype,
             "warmup_s": round(time.monotonic() - t0, 3),
             "compile_count": trace_count() + sum(t() for t in pool_traces)}
    if qos is not None:
        ready["qos"] = {"policy": args.qos_policy,
                        "classes": list(qos.registry.class_order),
                        "tenants": sorted(qos.registry.tenants)}
        if qos.registry.slo:
            ready["qos"]["slo"] = sorted(qos.registry.slo)
    if pool is not None:
        ready["models"] = pool.describe()
    if not plan.is_trivial:
        ready["topology"] = plan.describe()
    if args.aot_store:
        ready["aot"] = {str(k): v["source"]
                        for k, v in sorted(engine.warmup_report.items())}
    if retrieval is not None:
        info = retrieval.describe()
        ready["retrieval"] = {"index": info["index"], "rows": info["rows"],
                              "dim": info["dim"], "k": info["k"],
                              "block_n": info["block_n"],
                              "partitions": info["partitions"],
                              "mode": info["mode"]}
        if info["mode"] in ("ivf", "tiered"):
            ready["retrieval"]["nprobe"] = info["nprobe"]
            ready["retrieval"]["nprobe_max"] = info["nprobe_max"]
            ready["retrieval"]["clusters"] = info["clusters"]
        if info["mode"] == "tiered":
            ready["retrieval"]["resident_bytes"] = info["resident_bytes"]
            ready["retrieval"]["tiers"] = info["tiers"]
            if index_daemon is not None:
                ready["retrieval"]["daemon"] = index_daemon.describe()
        if args.aot_store:
            ready["retrieval"]["aot"] = {
                str(b): s for b, s in sorted(
                    retrieval.searcher.warmup_report.items())}
    print(json.dumps(ready), flush=True)
    try:
        if args.max_seconds:
            time.sleep(args.max_seconds)
            server.stop()
        else:
            server.serve_forever()
    finally:
        if index_daemon is not None:
            index_daemon.stop()
        if monitor is not None:
            monitor.stop()
        if args.prof_dir:
            from jimm_tpu.obs.prof.capture import get_capture_manager
            mgr = get_capture_manager()
            if mgr is not None:
                mgr.flush()
    return 0


# ---------------------------------------------------------------------------
# Parser
# ---------------------------------------------------------------------------

def _add_backend_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument("--platform", choices=["cpu", "tpu"], default=None,
                   help="force a JAX backend (default: environment)")
    p.add_argument("--host-devices", type=int, default=None,
                   help="virtual CPU device count (for mesh testing)")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="jimm_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("presets", help="list named model presets")
    _add_backend_flags(sp)
    sp.set_defaults(fn=cmd_presets)

    sp = sub.add_parser("train", help="train on synthetic data (offline)")
    sp.add_argument("--preset", required=True)
    sp.add_argument("--tiny", action="store_true",
                    help="shrink the preset to CPU-demo size")
    sp.add_argument("--from-pretrained", default=None,
                    help="fine-tune from an HF checkpoint (local file/dir "
                         "or hub id); --preset then only names the family")
    sp.add_argument("--image-size", type=int, default=None,
                    help="with --from-pretrained: load at a different "
                         "resolution (pos-embed grid interpolation)")
    sp.add_argument("--steps", type=int, default=100)
    sp.add_argument("--batch-size", type=int, default=32)
    sp.add_argument("--data", default=None,
                    help="tfrecord file/dir/glob with image+label (vit) or "
                         "image+tokens (clip/siglip) examples; default: "
                         "procedural synthetic data")
    sp.add_argument("--shuffle-buffer", type=int, default=256,
                    help="example shuffle-buffer size for --data "
                         "(records loader)")
    sp.add_argument("--loader", default="records",
                    choices=["records", "grain"],
                    help="--data pipeline: 'records' (generator, buffer "
                         "shuffle) or 'grain' (parallel workers, global "
                         "shuffle, checkpointable iteration)")
    sp.add_argument("--data-workers", type=int, default=0,
                    help="grain loader subprocess count (0 = in-process)")
    sp.add_argument("--num-classes", type=int, default=None,
                    help="override classifier width (vit + --data)")
    sp.add_argument("--lr", type=float, default=1e-3)
    sp.add_argument("--weight-decay", type=float, default=1e-4)
    sp.add_argument("--warmup-steps", type=int, default=0)
    sp.add_argument("--seed", type=int, default=0)
    sp.add_argument("--bf16", action="store_true")
    sp.add_argument("--compilation-cache-dir", default=None,
                    help="persist XLA compiles to this dir (jax "
                         "compilation cache) so restarted runs skip the "
                         "train-step compile")
    sp.add_argument("--mesh", default=None,
                    help='e.g. "data=4,model=2" or "data=2,model=1,seq=4" '
                         '(a seq axis turns on sequence-parallel attention '
                         'and joins the ring losses; default: no mesh)')
    sp.add_argument("--max-devices", type=int, default=None,
                    help="build the mesh over only the first N visible "
                         "devices (elastic restarts: a shrunk attempt plans "
                         "over the surviving subset and restore reshards "
                         "the checkpoint onto it)")
    sp.add_argument("--rules", default=None,
                    choices=["replicated", "dp", "tp", "fsdp",
                             "fsdp_tp", "sp", "fsdp_sp", "pp"],
                    help="sharding rules preset (requires --mesh)")
    sp.add_argument("--loss", default=None,
                    choices=["clip", "clip_ring", "siglip", "siglip_ring"])
    sp.add_argument("--naflex", action="store_true",
                    help="variable-resolution SigLIP2 training: NaFlex "
                         "(patches, shapes, mask) batches from tfrecords "
                         "(or synthetic mixed-aspect data) instead of "
                         "square images")
    sp.add_argument("--attn-impl", default=None,
                    choices=["auto", "xla", "flash", "flash_int8", "ring",
                             "ulysses", "saveable"],
                    help="attention kernel for both towers "
                         "(ring/ulysses = sequence-parallel over a seq mesh "
                         "axis: ppermute kv ring vs all-to-all head "
                         "redistribution; "
                         "flash_int8 = int8-QK flash, fwd+bwd; "
                         "saveable = checkpoint-named probs for --remat "
                         "dots+attn)")
    sp.add_argument("--precision", default=None,
                    choices=["bf16", "fp8_hybrid", "int8_qk"],
                    help="training precision policy: bf16 (as built), "
                         "fp8_hybrid (eligible Linears matmul in e4m3 fwd / "
                         "e5m2 grad with delayed per-tensor scaling), "
                         "int8_qk (attention via the int8-QK flash kernel)")
    sp.add_argument("--remat", default=None,
                    help="activation remat in the layer scan: none (off), "
                         "full (recompute all), or dots with +ln/+act/+attn "
                         "suffixes (save matmul [+layernorm][+activation]"
                         "[+attention-prob] outputs)")
    sp.add_argument("--ln-impl", default=None, choices=["xla", "fused"],
                    help="LayerNorm kernel (fused = one-pass Pallas)")
    sp.add_argument("--fused-qkv", action="store_true",
                    help="q/k/v as one (H, 3H) matmul")
    sp.add_argument("--bf16-momentum", action="store_true",
                    help="keep Adam's first moment in bfloat16 (halves that "
                         "buffer's HBM footprint and traffic)")
    sp.add_argument("--moment-dtype", default=None, choices=["f32", "bf16"],
                    help="Adam first-moment dtype (OptimizerConfig."
                         "moment_dtype); wins over --bf16-momentum and is "
                         "stamped on the goodput line")
    sp.add_argument("--pipeline-microbatches", type=int, default=0,
                    help="enable pipeline parallelism with N microbatches "
                         "(needs a 'stage' mesh axis and --rules pp)")
    sp.add_argument("--pipeline-virtual", type=int, default=1,
                    help="interleaved PP: virtual chunks per stage "
                         "(circular placement; shrinks the bubble ~Vx)")
    sp.add_argument("--scan-unroll", type=int, default=0,
                    help="layer-scan unroll factor (0 = auto: full unroll "
                         "on TPU for better XLA scheduling, 1 on CPU)")
    sp.add_argument("--ckpt-dir", default=None)
    sp.add_argument("--resume", action="store_true")
    sp.add_argument("--fake-failure-at-step", type=int, default=None,
                    help="failure drill: crash after checkpointing this step "
                         "(recover with --resume); sugar for "
                         "--inject-faults crash@STEP")
    sp.add_argument("--inject-faults", default=None,
                    help="deterministic fault drill plan: comma-separated "
                         "kind@STEP entries — preempt@N (SIGTERM to self), "
                         "crash@N (hard failure after N's checkpoint), "
                         "stall@N:SECONDS (slow-host sleep), corrupt@N "
                         "(garbage the newest committed checkpoint)")
    sp.add_argument("--preemption-save", action="store_true",
                    help="catch SIGTERM and spend the grace window on an "
                         "async checkpoint save overlapping the next "
                         "--grace-steps steps, then exit resumable "
                         "(needs --ckpt-dir)")
    sp.add_argument("--grace-steps", type=int, default=1,
                    help="training steps to overlap with the preemption "
                         "save before exiting (0 = save and exit at once)")
    sp.add_argument("--batch-fingerprint", action="store_true",
                    help="log a content hash of every consumed batch to the "
                         "metrics stream (proves zero-replay/zero-skip "
                         "resume; pulls each batch to host)")
    sp.add_argument("--save-every", type=int, default=50)
    sp.add_argument("--log-every", type=int, default=10)
    sp.add_argument("--metrics-file", default=None,
                    help="JSONL metrics output path")
    sp.add_argument("--tensorboard-dir", default=None,
                    help="write TensorBoard scalar events here")
    sp.add_argument("--profile-dir", default=None,
                    help="capture a jax.profiler trace of steps 2-4 here")
    sp.add_argument("--prof-ring", default=None, metavar="DIR",
                    help="continuous profiling: keep a bounded on-disk "
                         "ring of short step-window captures here, and "
                         "accept anomaly-triggered deep captures "
                         "(jimm-tpu obs prof ls/show/diff)")
    sp.add_argument("--prof-every", type=int, default=200,
                    help="capture a ring window every N steps")
    sp.add_argument("--prof-window", type=int, default=2,
                    help="steps per ring window capture")
    sp.add_argument("--prof-ring-bytes", type=int, default=64 << 20,
                    help="ring byte budget; oldest captures evicted")
    sp.add_argument("--journal", default=None, metavar="FILE",
                    help="persist flight-recorder events (preemption, "
                         "checkpoint, reshard) to this rotating JSONL "
                         "journal")
    _add_backend_flags(sp)
    sp.set_defaults(fn=cmd_train)

    sp = sub.add_parser("supervise",
                        help="run train as restartable attempts "
                             "(preemption/crash -> backoff -> --resume)")
    sp.add_argument("--max-restarts", type=int, default=3,
                    help="restarts before giving up")
    sp.add_argument("--backoff-base-s", type=float, default=1.0)
    sp.add_argument("--backoff-max-s", type=float, default=30.0)
    sp.add_argument("--seed", type=int, default=None,
                    help="seed the restart-backoff jitter "
                         "(reproducible drills)")
    sp.add_argument("--elastic", action="store_true",
                    help="replan the mesh from surviving devices before "
                         "every attempt (--mesh data=K --max-devices K "
                         "appended to the train command); restore reshards "
                         "the checkpoint onto the new shape")
    sp.add_argument("--shrink-plan", default=None,
                    help="elastic drill: comma-separated device budgets per "
                         "attempt, e.g. 8,4 = first attempt sees 8 devices, "
                         "every later attempt 4 (simulates losing hosts)")
    sp.add_argument("--adapt", action="store_true",
                    help="run the GoodputAdvisor over per-attempt goodput "
                         "breakdowns and carry its bounded knob decisions "
                         "(--save-every/--grace-steps/--scan-unroll) into "
                         "the next attempt")
    sp.add_argument("--journal", default=None, metavar="FILE",
                    help="persist flight-recorder events (attempts, "
                         "restarts, replans, advisor decisions) to this "
                         "rotating JSONL journal — one correlated incident "
                         "chain per failure")
    sp.add_argument("train_args", nargs=argparse.REMAINDER,
                    help="-- train --preset ... --ckpt-dir ...")
    sp.set_defaults(fn=cmd_supervise)

    sp = sub.add_parser("evaluate",
                        help="accuracy / retrieval metrics over a dataset")
    sp.add_argument("--data", required=True,
                    help="tfrecord file/dir/glob (single pass, no repeat)")
    sp.add_argument("--batch-size", type=int, default=32)
    sp.add_argument("--ckpt", default=None,
                    help="HF checkpoint (local file/dir or hub id)")
    sp.add_argument("--model", default=None,
                    choices=["vit", "clip", "siglip"],
                    help="model family for --ckpt (else from --preset name)")
    sp.add_argument("--preset", default=None)
    sp.add_argument("--tiny", action="store_true")
    sp.add_argument("--ckpt-dir", default=None,
                    help="orbax training checkpoint (with --preset)")
    sp.add_argument("--from-pretrained", default=None,
                    help="with --ckpt-dir: the HF checkpoint the training "
                         "run fine-tuned from (rebuilds that architecture)")
    sp.add_argument("--zero-shot", default=None, metavar="TOKENS_JSON",
                    help="zero-shot classification accuracy over labeled "
                         "records (clip/siglip): {label: [ids]} or "
                         "{label: [[ids], ...]} for prompt ensembles; "
                         "class order from the dataset's classes.json")
    sp.add_argument("--naflex", action="store_true",
                    help="SigLIP2 retrieval over NaFlex variable-resolution "
                         "batches (aspect-preserving) instead of the square "
                         "resize")
    sp.add_argument("--image-size", type=int, default=None,
                    help="with --from-pretrained: the --image-size the "
                         "training run used")
    sp.add_argument("--num-classes", type=int, default=None,
                    help="classifier width of the trained head (vit + "
                         "--ckpt-dir; default: classes.json next to --data)")
    sp.add_argument("--bf16", action="store_true")
    _add_backend_flags(sp)
    sp.set_defaults(fn=cmd_evaluate)

    sp = sub.add_parser("classify",
                        help="zero-shot image classification (CLIP/SigLIP)")
    sp.add_argument("image", help="image file (PNG/JPEG)")
    sp.add_argument("--ckpt", required=True,
                    help="checkpoint: local safetensors file/dir or HF repo")
    sp.add_argument("--model", default="clip", choices=["clip", "siglip"])
    sp.add_argument("--labels", default=None,
                    help='comma-separated label names, e.g. "cat,dog"')
    sp.add_argument("--template", default=None,
                    help="prompt template applied to each label (default "
                         "'a photo of a {}'); with --ensemble, a "
                         "\"|\"-separated template set")
    sp.add_argument("--tokenizer", default=None,
                    help="HF tokenizer for --labels (optional tooling)")
    sp.add_argument("--tokens-file", default=None,
                    help="JSON {label: [token ids]} — offline alternative "
                         "to --tokenizer")
    sp.add_argument("--ensemble", action="store_true",
                    help="prompt-template ensemble per class (the CLIP-"
                         "paper recipe): normalize/mean/renormalize text "
                         "embeddings over templates; --template with "
                         "\"|\"-separated entries overrides the builtin set")
    sp.add_argument("--naflex", action="store_true",
                    help="SigLIP2 NaFlex path: keep the image's aspect "
                         "ratio (variable-resolution patches + mask) "
                         "instead of squashing to the square")
    sp.add_argument("--index", default=None, metavar="STORE",
                    help="retrieval vector-store root to persist class "
                         "embeddings in: repeat invocations (across "
                         "processes) skip the text tower")
    sp.add_argument("--bf16", action="store_true")
    _add_backend_flags(sp)
    sp.set_defaults(fn=cmd_classify)

    sp = sub.add_parser("prepare-data",
                        help="build tfrecord shards from raw image files")
    sp.add_argument("src", help="source directory (class dirs, or images)")
    sp.add_argument("out", help="output directory for part-*.tfrecord")
    sp.add_argument("--task", default="classification",
                    choices=["classification", "contrastive"])
    sp.add_argument("--captions", default=None,
                    help="TSV: relative/path<TAB>caption (contrastive)")
    sp.add_argument("--tokenizer", default=None,
                    help="HF tokenizer for text captions (optional tooling; "
                         "integer captions are used as pre-tokenized ids)")
    sp.add_argument("--seq-len", type=int, default=64,
                    help="truncate token ids to this length")
    sp.add_argument("--shard-size", type=int, default=1000,
                    help="examples per tfrecord shard")
    sp.set_defaults(fn=cmd_prepare_data)

    sp = sub.add_parser("export",
                        help="load a checkpoint and save as HF safetensors")
    sp.add_argument("src", help="HF repo id, local file, or local dir")
    sp.add_argument("out", help="output directory")
    sp.add_argument("--model", required=True, choices=["vit", "clip", "siglip"])
    sp.add_argument("--flavor", default="auto",
                    choices=["auto", "siglip", "siglip2"],
                    help="SigLIP export format: auto = match the source "
                         "checkpoint (Siglip2-origin stays Siglip2Model-"
                         "loadable); siglip forces the v1 layout")
    sp.add_argument("--bf16", action="store_true")
    _add_backend_flags(sp)
    sp.set_defaults(fn=cmd_export)

    sp = sub.add_parser("export-run",
                        help="export a training run (orbax) as HF safetensors")
    sp.add_argument("out", help="output directory")
    sp.add_argument("--ckpt-dir", required=True,
                    help="orbax checkpoint directory of the run")
    sp.add_argument("--preset", required=True,
                    help="preset the run trained (or its family, with "
                         "--from-pretrained)")
    sp.add_argument("--flavor", default="auto",
                    choices=["auto", "siglip", "siglip2"],
                    help="SigLIP export format (see `export --flavor`)")
    sp.add_argument("--tiny", action="store_true")
    sp.add_argument("--from-pretrained", default=None,
                    help="HF checkpoint the run fine-tuned from")
    sp.add_argument("--image-size", type=int, default=None)
    sp.add_argument("--num-classes", type=int, default=None)
    sp.add_argument("--bf16", action="store_true")
    _add_backend_flags(sp)
    sp.set_defaults(fn=cmd_export_run)

    sp = sub.add_parser("inspect", help="list tensors in a safetensors file")
    sp.add_argument("file")
    sp.set_defaults(fn=cmd_inspect)

    sp = sub.add_parser("profile-analyze",
                        help="per-op summary of a jax.profiler trace dir")
    sp.add_argument("dir", help="--profile-dir of a train run")
    sp.add_argument("--top", type=int, default=25)
    sp.add_argument("--steps", type=_positive_int, default=1,
                    help="steps captured, to report per-step numbers")
    sp.add_argument("--device", type=int, default=0,
                    help="device index to report (-1 = sum across devices)")
    sp.set_defaults(fn=cmd_profile_analyze)

    sp = sub.add_parser("build-native",
                        help="compile native/libjimm_preprocess.so")
    sp.set_defaults(fn=cmd_build_native)

    sp = sub.add_parser("serve",
                        help="HTTP micro-batching inference server")
    sp.add_argument("--ckpt", default=None,
                    help="checkpoint: local safetensors file/dir or HF repo")
    sp.add_argument("--model", default=None,
                    choices=["vit", "clip", "siglip"],
                    help="model family of --ckpt")
    sp.add_argument("--preset", default=None,
                    help="random-init a preset instead of --ckpt (wiring/"
                         "latency smoke tests)")
    sp.add_argument("--tiny", action="store_true")
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--port", type=int, default=8000,
                    help="listening port (0 = pick a free one)")
    sp.add_argument("--buckets", default=None,
                    help="comma-separated batch buckets to warm-compile, "
                         'e.g. "1,4,16,64" (default: platform table)')
    sp.add_argument("--max-delay-ms", type=float, default=5.0,
                    help="micro-batch coalescing window")
    sp.add_argument("--replicas", type=int, default=1,
                    help="independent serving replicas to partition the "
                         "visible devices into; micro-batches are load-"
                         "balanced across them (1 = classic single-device "
                         "serve)")
    sp.add_argument("--model-parallel", type=int, default=1,
                    help="devices per replica: each forward's params are "
                         "tensor-parallel over a (data=1, model=k) submesh "
                         "(big towers that don't fit one chip)")
    sp.add_argument("--seq-parallel", type=int, default=1,
                    help="sequence-parallel ways per replica: the submesh "
                         "grows a seq axis and attention runs ring/ulysses "
                         "across it (sequences too long for one chip; "
                         "composes with --model-parallel)")
    sp.add_argument("--self-heal", action="store_true",
                    help="escalate a watchdog fence: probe the fenced "
                         "replica (transient fault -> revive in place), "
                         "else rebuild the replica set from the AOT store "
                         "and replan around it live (zero fresh traces "
                         "when the store is warm)")
    sp.add_argument("--queue-size", type=int, default=256,
                    help="admission bound; requests past it get a 503 "
                         "queue_full")
    sp.add_argument("--timeout-s", type=float, default=5.0,
                    help="default per-request deadline")
    sp.add_argument("--shed-fraction", type=float, default=0.5,
                    help="queue fill fraction past which the batcher stops "
                         "waiting for stragglers")
    sp.add_argument("--max-seconds", type=float, default=None,
                    help="serve this long then exit (scripted smoke runs; "
                         "default: until Ctrl-C)")
    sp.add_argument("--metrics-file", default=None,
                    help="append metric snapshots as JSONL "
                         "(train/metrics.py format)")
    sp.add_argument("--metrics-every-s", type=float, default=10.0)
    sp.add_argument("--prof-dir", default=None, metavar="DIR",
                    help="continuous profiling + HBM watchdog: keep the "
                         "anomaly-triggered capture ring here (heal/replan/"
                         "SLO-burn incidents and POST /admin/prof/trigger "
                         "deep-capture onto their cids) and sample "
                         "jimm_hbm_* device-memory gauges")
    sp.add_argument("--bf16", action="store_true",
                    help="legacy spelling of --dtype bf16")
    sp.add_argument("--dtype", choices=["f32", "bf16", "int8"], default=None,
                    help="serving precision (default f32). int8 quantizes "
                         "the weights in place at startup (symmetric "
                         "per-channel) and dispatches the fused Pallas "
                         "int8 matmul path — docs/quantization.md")
    sp.add_argument("--aot-store", default=None,
                    help="consult this AOT artifact store before any "
                         "fresh compile (populate with `jimm-tpu aot "
                         "warmup`); misses are written through")
    sp.add_argument("--tune-cache", default=None,
                    help="resolve Pallas kernel block sizes from this "
                         "tuned-config cache (populate with `jimm-tpu "
                         "tune`); lookup only — misses fall back to safe "
                         "defaults, serving never measures")
    sp.add_argument("--index-store", default=None,
                    help="vector store root holding retrieval indexes "
                         "(populate with `jimm-tpu index build/add`); "
                         "enables /v1/search")
    sp.add_argument("--index", default=None,
                    help="index name inside --index-store to serve")
    sp.add_argument("--search-k", type=int, default=10,
                    help="compiled top-k carry width; /v1/search requests "
                         "may ask for any k up to this")
    sp.add_argument("--index-mode", default="exact",
                    choices=["exact", "ivf", "tiered"],
                    help="retrieval mode: exact streaming top-k, "
                         "two-stage IVF over the index's trained codebook "
                         "(train with `jimm-tpu index train-centroids`), "
                         "or tiered — IVF under an explicit device byte "
                         "budget with warm/cold spill to host RAM and the "
                         "store's artifact dir (docs/retrieval.md)")
    sp.add_argument("--nprobe", type=int, default=None,
                    help="ivf/tiered mode: default clusters probed per "
                         "query (requests may override up to --nprobe-max; "
                         "default: min(8, --nprobe-max))")
    sp.add_argument("--nprobe-max", type=int, default=32,
                    help="ivf/tiered mode: compiled probe-width ceiling — "
                         "any nprobe up to this reuses one program (a "
                         "runtime scalar, never a recompile)")
    sp.add_argument("--tier-device-budget-mb", type=int, default=None,
                    help="tiered mode: hot-arena HBM budget in MiB "
                         "(default 64); device-resident bytes stay flat "
                         "at this cap however large the corpus grows")
    sp.add_argument("--tier-host-budget-mb", type=int, default=None,
                    help="tiered mode: host-RAM budget for warm "
                         "full-precision rows; clusters past it spill to "
                         "disk segments (default: unbounded host)")
    sp.add_argument("--tier-daemon-interval", type=float, default=None,
                    metavar="SECONDS",
                    help="tiered mode: start the autonomous IndexDaemon "
                         "(retrain/build-ivf/compact/re-tier on staleness "
                         "and access drift) at this tick interval")
    sp.add_argument("--qos-policy", default=None, metavar="FILE",
                    help="tenant QoS policy (JSON/TOML): priority classes, "
                         "per-tenant token-bucket rate limits, and queue "
                         "quotas; enables weighted-fair scheduling and "
                         "class-ordered shedding (docs/qos.md). Without it "
                         "serving is byte-identical to the policy-free "
                         "server")
    sp.add_argument("--pool-model", action="append", default=None,
                    metavar="NAME=PRESET[@DTYPE]",
                    help="additional resident model (repeatable): random-"
                         "init PRESET at DTYPE (f32|bf16|int8, default "
                         "f32), warm its own engine + AOT fingerprint, and "
                         "route requests naming model=NAME to it; inherits "
                         "--tiny/--buckets/--aot-store")
    sp.add_argument("--journal", default=None, metavar="FILE",
                    help="persist flight-recorder events (replica faults, "
                         "fences, heals, replans, SLO burns) to this "
                         "rotating JSONL journal")
    _add_backend_flags(sp)
    sp.set_defaults(fn=cmd_serve)

    sp = sub.add_parser("bench-forward", help="jitted forward throughput")
    sp.add_argument("--preset", required=True)
    sp.add_argument("--tiny", action="store_true")
    sp.add_argument("--batch-size", type=int, default=32)
    sp.add_argument("--steps", type=int, default=20)
    sp.add_argument("--bf16", action="store_true")
    _add_backend_flags(sp)
    sp.set_defaults(fn=cmd_bench_forward)

    # jimm-tpu obs {snapshot,tail,diff} — pure-host metric tooling (no jax)
    from jimm_tpu.obs.cli import add_obs_parser
    add_obs_parser(sub)

    # jimm-tpu aot {warmup,ls,gc,verify} — AOT compile-artifact store
    from jimm_tpu.aot.cli import add_aot_parser
    add_aot_parser(sub)

    # jimm-tpu tune {run,ls} — persistent Pallas kernel autotuner
    from jimm_tpu.tune.cli import add_tune_parser
    add_tune_parser(sub)

    # jimm-tpu index {build,add,ls,verify,compact} — retrieval stores (no jax)
    from jimm_tpu.retrieval.cli import add_index_parser
    add_index_parser(sub)

    # jimm-tpu qos {ls,validate} — tenant QoS policy tooling (no jax)
    from jimm_tpu.serve.qos.cli import add_qos_parser
    add_qos_parser(sub)

    # jimm-tpu cascade {calibrate,ls} — cascade calibration tooling (no jax)
    from jimm_tpu.serve.cascade.cli import add_cascade_parser
    add_cascade_parser(sub)

    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    from jimm_tpu.resilience import PreemptedError
    try:
        return args.fn(args)
    except PreemptedError as e:
        # bare `train` hit by SIGTERM: state is saved; exit clean and
        # resumable instead of with a traceback (75 = EX_TEMPFAIL)
        print(str(e), file=sys.stderr)
        return 75


if __name__ == "__main__":
    sys.exit(main())
