"""SigLIP parity tests (reference anchor: `tests/test_siglip.py`, atol 1e-2 —
we hold ~1e-5), incl. the fused MAP-head in_proj split and non-4x MLP."""

import jax.numpy as jnp
import numpy as np
import pytest

from jimm_tpu import SigLIP

from hf_util import sample_image, sample_text, save_tiny_siglip, torch_image


@pytest.fixture(scope="module")
def siglip_ckpt(tmp_path_factory):
    return save_tiny_siglip(tmp_path_factory.mktemp("siglip"))


@pytest.fixture(scope="module")
def oracle(siglip_ckpt):
    from transformers import SiglipModel
    return SiglipModel.from_pretrained(siglip_ckpt).eval()


def test_vision_tower_parity(siglip_ckpt, oracle, rng):
    """MAP-head pooled output vs HF pooler (ref test_siglip.py:36)."""
    import torch
    model = SigLIP.from_pretrained(siglip_ckpt)
    img = sample_image(rng)
    with torch.no_grad():
        ref = oracle.vision_model(torch_image(img)).pooler_output.numpy()
    np.testing.assert_allclose(np.asarray(model.encode_image(jnp.asarray(img))),
                               ref, atol=1e-4)


def test_text_tower_parity(siglip_ckpt, oracle, rng):
    """Last-token pooled + projected text features (ref test_siglip.py:43-52)."""
    import torch
    model = SigLIP.from_pretrained(siglip_ckpt)
    txt = sample_text(rng)
    with torch.no_grad():
        ref = oracle.get_text_features(torch.tensor(txt)).numpy()
    np.testing.assert_allclose(np.asarray(model.encode_text(jnp.asarray(txt))),
                               ref, atol=1e-4)


def test_logits_parity(siglip_ckpt, oracle, rng):
    import torch
    model = SigLIP.from_pretrained(siglip_ckpt)
    img, txt = sample_image(rng), sample_text(rng)
    ours = np.asarray(model(jnp.asarray(img), jnp.asarray(txt)))
    with torch.no_grad():
        theirs = oracle(input_ids=torch.tensor(txt),
                        pixel_values=torch_image(img)).logits_per_image.numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-4)


def test_non_4x_mlp_loads(siglip_ckpt):
    """The tiny oracle uses a 2x text MLP — the reference hardcodes 4x and
    cannot load such checkpoints (SURVEY §2.4); we must."""
    model = SigLIP.from_pretrained(siglip_ckpt)
    assert model.config.text.mlp_dim == 2 * model.config.text.width


def test_shape_inference_without_config(siglip_ckpt, tmp_path, rng):
    import os, shutil
    d = tmp_path / "noconfig"
    d.mkdir()
    shutil.copy(os.path.join(siglip_ckpt, "model.safetensors"), d)
    model = SigLIP.from_pretrained(str(d / "model.safetensors"))
    assert model.config.vision.pooling == "map"
    out = model(jnp.asarray(sample_image(rng)), jnp.asarray(sample_text(rng)))
    assert out.shape == (2, 2)
