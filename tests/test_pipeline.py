"""Pipeline parallelism vs unsharded oracle: functional core, model-level
integration, PP x DP composition, and training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import nnx

from jimm_tpu.configs import TransformerConfig
from jimm_tpu.nn.transformer import Transformer
from jimm_tpu.utils import compat
from jimm_tpu.parallel import PIPELINE, make_mesh, use_sharding
from jimm_tpu.parallel.pipeline import pipeline_forward


@pytest.fixture(scope="module")
def pp_mesh(eight_devices):
    return make_mesh({"data": 2, "stage": 4})


def test_functional_core_matches_sequential(rng, pp_mesh):
    L, H, B = 8, 16, 16
    w = jnp.asarray(rng.randn(L, H, H).astype(np.float32) * 0.2)
    x = jnp.asarray(rng.randn(B, H).astype(np.float32))

    def ref(w, x):
        for i in range(L):
            x = jnp.tanh(x @ w[i])
        return x

    def stage_apply(w_local, xm, tick):
        return jax.lax.scan(lambda h, wi: (jnp.tanh(h @ wi), None),
                            xm, w_local)[0]

    with compat.set_mesh(pp_mesh):
        out = pipeline_forward(stage_apply, w, x, n_microbatches=4,
                               batch_axis="data")
        gp = jax.grad(lambda w: (pipeline_forward(
            stage_apply, w, x, n_microbatches=4,
            batch_axis="data") ** 2).mean())(w)
    np.testing.assert_allclose(out, ref(w, x), atol=1e-5)
    gr = jax.grad(lambda w: (ref(w, x) ** 2).mean())(w)
    np.testing.assert_allclose(gp, gr, atol=1e-5)


@pytest.mark.parametrize("n_virtual,n_micro", [(2, 4), (2, 8), (4, 4)])
def test_functional_core_interleaved_matches_sequential(rng, pp_mesh,
                                                        n_virtual, n_micro):
    """Circular-placement (interleaved) schedule == plain sequential stack,
    values and gradients, across virtual-chunk/microbatch shapes."""
    from jimm_tpu.parallel.pipeline import circular_layer_order
    S, L, H, B = 4, 16, 16, 16
    w = jnp.asarray(rng.randn(L, H, H).astype(np.float32) * 0.2)
    x = jnp.asarray(rng.randn(B, H).astype(np.float32))

    def ref(w, x):
        for i in range(L):
            x = jnp.tanh(x @ w[i])
        return x

    def stage_apply(w_local, xm, tick):
        return jax.lax.scan(lambda h, wi: (jnp.tanh(h @ wi), None),
                            xm, w_local)[0]

    order = circular_layer_order(L, S, n_virtual)

    def run(w):
        return pipeline_forward(stage_apply, w[order], x,
                                n_microbatches=n_micro, n_virtual=n_virtual,
                                batch_axis="data")

    with compat.set_mesh(pp_mesh):
        out = run(w)
        gp = jax.grad(lambda w: (run(w) ** 2).mean())(w)
    np.testing.assert_allclose(out, ref(w, x), atol=1e-5)
    gr = jax.grad(lambda w: (ref(w, x) ** 2).mean())(w)
    np.testing.assert_allclose(gp, gr, atol=1e-5)


def _towers(pipeline: bool, **kw):
    kw.setdefault("pp_microbatches", 2)
    cfg = TransformerConfig(width=32, depth=8, num_heads=2, mlp_dim=64,
                            pipeline=pipeline, **kw)
    return Transformer(cfg, nnx.Rngs(0))


def test_transformer_interleaved_matches_plain(rng, pp_mesh):
    """pp_virtual=2 over 4 stages: circular placement at the module level."""
    x = jnp.asarray(rng.randn(8, 12, 32).astype(np.float32))
    ref = np.asarray(_towers(False)(x))
    pp = _towers(True, pp_virtual=2, pp_microbatches=4)
    with use_sharding(pp_mesh, PIPELINE):
        out = np.asarray(pp(x))
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_transformer_prebaked_placement_matches_plain(rng, pp_mesh):
    """cfg.pp_stages bakes circular placement into storage at construction
    (no per-step cross-stage all-to-all); semantics must be unchanged."""
    x = jnp.asarray(rng.randn(8, 12, 32).astype(np.float32))
    ref = np.asarray(_towers(False)(x))
    pp = _towers(True, pp_virtual=2, pp_microbatches=4, pp_stages=4)
    with use_sharding(pp_mesh, PIPELINE):
        out = np.asarray(pp(x))
    np.testing.assert_allclose(out, ref, atol=1e-5)
    # a mesh whose stage count contradicts the baked placement must raise
    bad = make_mesh({"data": 4, "stage": 2})
    with use_sharding(bad, PIPELINE), pytest.raises(ValueError,
                                                    match="pp_stages"):
        pp(x)


def test_prebaked_placement_checkpoint_roundtrip(rng, tmp_path, pp_mesh):
    """Canonical HF checkpoint -> permuted (pp_stages) storage via the
    loader's layer_order -> identical forward -> canonical re-export."""
    from transformers import SiglipConfig, SiglipModel

    from jimm_tpu import SigLIP
    from jimm_tpu.weights.export import save_pretrained

    tower = dict(hidden_size=64, intermediate_size=128, num_hidden_layers=8,
                 num_attention_heads=2, image_size=32, patch_size=16)
    hf = SiglipConfig(vision_config=dict(tower),
                      text_config=dict(hidden_size=64, intermediate_size=128,
                                       num_hidden_layers=8,
                                       num_attention_heads=2))
    SiglipModel(hf).eval().save_pretrained(tmp_path / "src",
                                           safe_serialization=True)

    plain = SigLIP.from_pretrained(str(tmp_path / "src"))
    piped = SigLIP.from_pretrained(
        str(tmp_path / "src"), mesh=pp_mesh, rules=PIPELINE,
        runtime=dict(pipeline=True, pp_virtual=2, pp_stages=4,
                     pp_microbatches=4))

    img = jnp.asarray(rng.randn(8, 32, 32, 3).astype(np.float32))
    txt = jnp.asarray(rng.randint(1, 99, size=(8, 16)), jnp.int32)
    ref = np.asarray(plain(img, txt))
    with use_sharding(pp_mesh, PIPELINE):
        out = np.asarray(piped(img, txt))
    np.testing.assert_allclose(out, ref, atol=2e-4)

    # export from permuted storage must be canonical again
    save_pretrained(piped, tmp_path / "out")
    again = SigLIP.from_pretrained(str(tmp_path / "out"))
    np.testing.assert_allclose(np.asarray(again(img, txt)), ref, atol=2e-4)


def test_transformer_pipeline_dropout(rng, pp_mesh):
    """Active dropout in the pipelined path: fresh masks per microbatch and
    per step (VERDICT r1: PP was eval-biased)."""
    x = jnp.asarray(rng.randn(8, 12, 32).astype(np.float32))
    cfg = TransformerConfig(width=32, depth=8, num_heads=2, mlp_dim=64,
                            dropout=0.5, pipeline=True, pp_microbatches=2)
    pp = Transformer(cfg, nnx.Rngs(0))
    pp.blocks.dropout.deterministic = False
    with use_sharding(pp_mesh, PIPELINE):
        a = np.asarray(pp(x))
        b = np.asarray(pp(x))
    # dropout is active (output differs from eval) and re-randomizes per call
    pp.blocks.dropout.deterministic = True
    with use_sharding(pp_mesh, PIPELINE):
        ev = np.asarray(pp(x))
    assert np.abs(a - ev).max() > 1e-3
    assert np.abs(a - b).max() > 1e-3, "masks must differ across steps"
    assert np.isfinite(a).all() and np.isfinite(b).all()
    # microbatches must not share masks: batch rows land in different
    # microbatches, so per-row deviation from eval must not be identical
    dev = np.abs(a - ev).mean(axis=(1, 2))
    assert dev.std() > 1e-5


def test_transformer_pipeline_matches_plain(rng, pp_mesh):
    x = jnp.asarray(rng.randn(8, 12, 32).astype(np.float32))
    ref = np.asarray(_towers(False)(x))
    pp = _towers(True)
    with use_sharding(pp_mesh, PIPELINE):
        out = np.asarray(pp(x))
    np.testing.assert_allclose(out, ref, atol=1e-5)


@pytest.mark.slow
def test_transformer_pipeline_gradients_match(rng, pp_mesh):
    x = jnp.asarray(rng.randn(8, 12, 32).astype(np.float32))

    def loss(m):
        return (m(x) ** 2).mean()

    g_plain = nnx.grad(loss)(_towers(False))
    pp = _towers(True)
    with use_sharding(pp_mesh, PIPELINE):
        g_pp = nnx.grad(loss)(pp)
    for (kp, vp), (kq, vq) in zip(
            nnx.to_flat_state(nnx.state(g_plain, nnx.Param)),
            nnx.to_flat_state(nnx.state(g_pp, nnx.Param))):
        np.testing.assert_allclose(np.asarray(vq.get_value()),
                                   np.asarray(vp.get_value()),
                                   atol=1e-5, err_msg=str(kp))


def test_transformer_pipeline_with_remat(rng, pp_mesh):
    x = jnp.asarray(rng.randn(8, 12, 32).astype(np.float32))
    cfg = TransformerConfig(width=32, depth=8, num_heads=2, mlp_dim=64,
                            pipeline=True, pp_microbatches=4, remat=True,
                            remat_policy="dots")
    pp = Transformer(cfg, nnx.Rngs(0))
    ref = np.asarray(_towers(False)(x))
    with use_sharding(pp_mesh, PIPELINE):
        out = np.asarray(pp(x))
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_pipeline_requires_stage_axis(rng, eight_devices):
    x = jnp.asarray(rng.randn(8, 12, 32).astype(np.float32))
    pp = _towers(True)
    mesh = make_mesh({"data": 8})
    with use_sharding(mesh, PIPELINE):
        with pytest.raises(ValueError, match="stage"):
            pp(x)


@pytest.mark.slow
def test_pipelined_vit_training_step(rng, pp_mesh):
    """End-to-end: a pipelined ViT classifier trains (loss decreases)."""
    from jimm_tpu import VisionTransformer, ViTConfig, VisionConfig
    from jimm_tpu.parallel import shard_batch
    from jimm_tpu.train import (OptimizerConfig, make_classifier_train_step,
                                make_optimizer)

    cfg = ViTConfig(
        vision=VisionConfig(image_size=16, patch_size=8, width=32, depth=8,
                            num_heads=2, mlp_dim=64, ln_eps=1e-12,
                            pipeline=True, pp_microbatches=2),
        num_classes=4)
    model = VisionTransformer(cfg, rngs=nnx.Rngs(0), mesh=pp_mesh,
                              rules=PIPELINE)
    opt = make_optimizer(model, OptimizerConfig(learning_rate=1e-2))
    step = make_classifier_train_step()
    with use_sharding(pp_mesh, PIPELINE):
        images = shard_batch(rng.randn(16, 16, 16, 3).astype(np.float32),
                             pp_mesh, PIPELINE)
        labels = shard_batch(rng.randint(0, 4, size=(16,)), pp_mesh, PIPELINE)
        losses = [float(step(model, opt, images, labels)["loss"])
                  for _ in range(8)]
    assert losses[-1] < losses[0]


# ---------------------------------------------------------------------------
# Parse-time constraint validation (VERDICT r3 weak #6: these used to
# surface only inside the shard_map trace, minutes into a compile)
# ---------------------------------------------------------------------------

def test_validate_pipeline_catches_all_constraints():
    import dataclasses

    from jimm_tpu.configs import VisionConfig, validate_pipeline

    tower = VisionConfig(image_size=16, patch_size=8, width=32, depth=8,
                         num_heads=2, mlp_dim=64, pipeline=True,
                         pp_microbatches=4, pp_virtual=2, pp_stages=4)
    validate_pipeline(tower, n_stages=4, local_batch=8)  # valid: no raise

    cases = [
        (dict(pp_microbatches=0), dict(n_stages=4), "n_microbatches"),
        (dict(), dict(n_stages=0), "'stage' axis"),
        (dict(), dict(n_stages=3), "not divisible by 3 stages"),
        (dict(pp_stages=2), dict(n_stages=4), "pp_stages=2"),
        (dict(pp_microbatches=3, pp_virtual=2, pp_stages=2),
         dict(n_stages=2, local_batch=3), "microbatches 3 divisible"),
        (dict(pp_virtual=1), dict(n_stages=4, local_batch=6),
         "local batch 6"),
    ]
    for tower_kw, call_kw, match in cases:
        bad = dataclasses.replace(tower, **tower_kw)
        with pytest.raises(ValueError, match=match):
            validate_pipeline(bad, **call_kw)

    # a non-pipelined tower never raises, whatever the mesh looks like
    off = dataclasses.replace(tower, pipeline=False)
    validate_pipeline(off, n_stages=0, local_batch=3)


def test_cli_rejects_bad_pipeline_config_at_parse_time(eight_devices):
    from jimm_tpu.cli import main

    with pytest.raises(SystemExit, match="microbatches 3 divisible"):
        main(["train", "--preset", "siglip-base-patch16-256", "--tiny",
              "--steps", "1", "--batch-size", "8",
              "--mesh", "data=4,stage=2", "--rules", "pp",
              "--pipeline-microbatches", "3", "--pipeline-virtual", "2"])
    with pytest.raises(SystemExit, match="local batch 3 not divisible"):
        main(["train", "--preset", "siglip-base-patch16-256", "--tiny",
              "--steps", "1", "--batch-size", "6",
              "--mesh", "data=2,stage=4", "--rules", "pp",
              "--pipeline-microbatches", "4"])
