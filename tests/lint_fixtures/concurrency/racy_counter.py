"""JL017 seed: one attribute written from two thread entry points with no
consistent guard — and the two shapes that must stay clean (a fully locked
twin, and a helper guarded only at its call sites)."""

import threading


class RacyCounter:
    """`hits` is written by both worker threads with no lock: JL017."""

    def __init__(self):
        self.hits = 0
        self._threads = []

    def start(self):
        self._threads = [
            threading.Thread(target=self._drain_a, daemon=True),
            threading.Thread(target=self._drain_b, daemon=True),
        ]
        for t in self._threads:
            t.start()

    def _drain_a(self):
        self.hits += 1  # root thread:_drain_a, unguarded

    def _drain_b(self):
        self.hits += 1  # root thread:_drain_b, unguarded


class LockedCounter:
    """Same shape, every write under one lock: clean."""

    def __init__(self):
        self.hits = 0
        self._lock = threading.Lock()

    def start(self):
        threading.Thread(target=self._drain_a, daemon=True).start()
        threading.Thread(target=self._drain_b, daemon=True).start()

    def _drain_a(self):
        with self._lock:
            self.hits += 1

    def _drain_b(self):
        with self._lock:
            self.hits += 1


class CallerGuardedCounter:
    """The write sits in a helper with no lexical lock, but every direct
    caller holds the lock — entry-guard inference must keep this clean."""

    def __init__(self):
        self.hits = 0
        self._lock = threading.Lock()

    def start(self):
        threading.Thread(target=self._loop_a, daemon=True).start()
        threading.Thread(target=self._loop_b, daemon=True).start()

    def _loop_a(self):
        with self._lock:
            self._bump()

    def _loop_b(self):
        with self._lock:
            self._bump()

    def _bump(self):
        self.hits += 1  # guarded at every entry: no JL017
