"""Checkpoint resolution: local safetensors/pytorch file/dir or HF hub repo id.

Preserves the reference's full user-visible loading contract
(SURVEY §2.4 "both formats"): local `.safetensors` or `pytorch_model.bin`
file with sibling/parent `config.json` discovery (ref `common/utils.py:77-86`),
local directory, or HF hub repo-id (ref `common/utils.py:55-99`) — but with
zero torch in the import graph: `.bin` files are read by the stdlib-only
unpickler in :mod:`jimm_tpu.weights.torch_pickle`. Adds sharded-checkpoint
support (`*.index.json`), which the reference lacks.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Any

import numpy as np

from jimm_tpu.weights import torch_pickle
from jimm_tpu.weights.safetensors_io import load_file

_TORCH_SUFFIXES = (".bin", ".pt", ".pth")


def _load_config(path: Path) -> dict[str, Any] | None:
    if path.is_file():
        with open(path) as f:
            return json.load(f)
    return None


def _sharded(d: Path, index: Path, loader) -> dict[str, np.ndarray]:
    with open(index) as f:
        weight_map: dict[str, str] = json.load(f)["weight_map"]
    weights: dict[str, np.ndarray] = {}
    for shard in sorted(set(weight_map.values())):
        weights.update(loader(d / shard))
    return weights


def _from_dir(d: Path, use_pytorch: bool = False
              ) -> tuple[dict[str, np.ndarray], dict | None]:
    config = _load_config(d / "config.json")
    if use_pytorch:
        index = d / "pytorch_model.bin.index.json"
        if index.is_file():
            return _sharded(d, index, torch_pickle.load_file), config
        single = d / "pytorch_model.bin"
        if single.is_file():
            return torch_pickle.load_file(single), config
        raise FileNotFoundError(f"no pytorch_model.bin under {d}")
    index = d / "model.safetensors.index.json"
    if index.is_file():
        return _sharded(d, index, load_file), config
    single = d / "model.safetensors"
    if single.is_file():
        return load_file(single), config
    candidates = sorted(d.glob("*.safetensors"))
    if candidates:
        weights: dict[str, np.ndarray] = {}
        for c in candidates:
            weights.update(load_file(c))
        return weights, config
    # fall back to the torch format when no safetensors exist at all
    bin_index = d / "pytorch_model.bin.index.json"
    if bin_index.is_file():
        return _sharded(d, bin_index, torch_pickle.load_file), config
    if (d / "pytorch_model.bin").is_file():
        return torch_pickle.load_file(d / "pytorch_model.bin"), config
    raise FileNotFoundError(f"no .safetensors or pytorch_model.bin "
                            f"weights under {d}")


def _from_file(p: Path) -> tuple[dict[str, np.ndarray], dict | None]:
    if p.suffix in _TORCH_SUFFIXES:
        weights = torch_pickle.load_file(p)
    else:
        weights = load_file(p)
    # config discovery: sibling config.json, else parent of a `model/` dir
    # (ref common/utils.py:77-86)
    config = _load_config(p.parent / "config.json")
    if config is None and p.parent.name == "model":
        config = _load_config(p.parent.parent / "config.json")
    return weights, config


# not-found family: a definitive "this file isn't in the repo" answer used
# as control flow (sharded-vs-single probing) — retrying these would turn
# every fallback probe into retries * backoff of dead waiting
_NO_RETRY_ERRORS = ("EntryNotFoundError", "RepositoryNotFoundError",
                    "RevisionNotFoundError", "GatedRepoError",
                    "FileNotFoundError")


def _retryable(exc: BaseException) -> bool:
    return not any(cls.__name__ in _NO_RETRY_ERRORS
                   for cls in type(exc).__mro__)


def _hub_download_with_retry(hf_hub_download, repo_id: str, filename: str,
                             *, retries: int | None = None,
                             backoff_s: float | None = None,
                             sleep=None) -> str:
    """``hf_hub_download`` with bounded retry + local-cache last resort.

    Transient failures (timeouts, 5xx, resets) get ``retries`` attempts
    with exponential backoff; not-found errors propagate immediately (they
    are sharded-vs-single control flow, not flakiness). When the network
    never recovers, one final ``local_files_only=True`` attempt serves a
    previously-cached copy — so a blipping link can't kill an `aot warmup`
    or a train start whose weights are already on disk.
    """
    import time as _time

    from jimm_tpu.resilience import BackoffPolicy
    if retries is None:
        retries = int(os.environ.get("JIMM_HUB_RETRIES", "3"))
    if backoff_s is None:
        backoff_s = float(os.environ.get("JIMM_HUB_BACKOFF_S", "0.5"))
    sleep = sleep or _time.sleep
    # jitter=0: the historical exact exponential delays (base * 2**attempt)
    backoff = BackoffPolicy(retries=max(1, retries), base_s=backoff_s)
    last: BaseException | None = None
    for attempt in range(backoff.retries):
        try:
            return hf_hub_download(repo_id, filename)
        except Exception as e:
            if not _retryable(e):
                raise
            last = e
            if attempt + 1 < backoff.retries:
                sleep(backoff.delay(attempt))
    try:
        return hf_hub_download(repo_id, filename, local_files_only=True)
    except Exception:
        raise last  # the transient error, not the unhelpful cache miss


def _from_hub(repo_id: str, use_pytorch: bool = False
              ) -> tuple[dict[str, np.ndarray], dict | None]:
    try:
        from huggingface_hub import hf_hub_download
    except ImportError as e:  # pragma: no cover
        raise FileNotFoundError(
            f"{repo_id!r} is not a local path and huggingface_hub is "
            "unavailable") from e

    def download(filename: str) -> str:
        return _hub_download_with_retry(hf_hub_download, repo_id, filename)

    def fetch(single: str, loader) -> dict[str, np.ndarray]:
        # sharded checkpoints first (large models), then the single file
        try:
            index_path = download(single + ".index.json")
            with open(index_path) as f:
                weight_map: dict[str, str] = json.load(f)["weight_map"]
            out: dict[str, np.ndarray] = {}
            for shard in sorted(set(weight_map.values())):
                out.update(loader(download(shard)))
            return out
        except Exception:
            return loader(download(single))

    formats = [("model.safetensors", load_file),
               ("pytorch_model.bin", torch_pickle.load_file)]
    if use_pytorch:
        formats.reverse()
    try:
        try:
            weights = fetch(*formats[0])
        except Exception:
            weights = fetch(*formats[1])  # repo hosts only the other format
    except Exception as e:
        raise FileNotFoundError(
            f"could not fetch {repo_id!r} from the HF hub "
            f"(offline, or repo has neither format?): {e}") from e
    try:
        config_path = download("config.json")
        config = _load_config(Path(config_path))
    except Exception:
        config = None
    return weights, config


def resolve_checkpoint(name_or_path: str | os.PathLike, *,
                       use_pytorch: bool = False
                       ) -> tuple[dict[str, np.ndarray], dict | None]:
    """Return ``(flat hf tensor dict, hf config dict | None)``.

    ``use_pytorch=True`` prefers the ``pytorch_model.bin`` format (ref
    `common/utils.py:55-71`) — read torch-free by
    :mod:`~jimm_tpu.weights.torch_pickle`.
    """
    p = Path(name_or_path).expanduser()
    if p.is_dir():
        return _from_dir(p, use_pytorch)
    if p.is_file():
        return _from_file(p)
    name = str(name_or_path)
    if name.startswith((".", "/", "~")) or name.count("/") != 1:
        # filesystem-looking, but nothing there — don't confuse with a repo id
        raise FileNotFoundError(f"no checkpoint file or directory at {name!r}")
    return _from_hub(name, use_pytorch)
