"""JL009 fixture: hardcoded block kwargs (lines 8, 12, 27-28; the rule keys
on the kwarg name, covering every attention-family variant), a suppressed
deliberate pin (line 16), and non-literal kwargs (fine: lines 20, 24)."""

from jimm_tpu.ops import flash_attention, flash_attention_masked, layer_norm


out = flash_attention(q, k, v, block_q=128,  # line 8: JL009
                      block_k=256)  # line 9: JL009


y = layer_norm(x, g, b, block_rows=64)  # line 12: JL009


# a justified pin survives: probing this exact config is the point
z = layer_norm(x, g, b, block_rows=64)  # jaxlint: disable=JL009 tuned offline


BLOCK = 128
tuned = flash_attention(q, k, v, block_q=BLOCK)  # named constant: no finding


def wrapper(block_rows=256):  # def-site default: no finding
    return layer_norm(x, g, b, block_rows=None)  # None: no finding


w = flash_attention_masked(q, k, v, m, block_q=128,  # line 27: JL009
                           block_k=128)  # line 28: JL009
