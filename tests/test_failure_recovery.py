"""Failure-recovery drill (SURVEY §5 failure-detection row, VERDICT r1 #7):
train with checkpointing, inject a mid-run crash, restore into FRESH model
and optimizer objects, and assert step and loss continuity with an
uninterrupted run of the same schedule."""

import json

import numpy as np
import pytest

from jimm_tpu.cli import main


def read_metrics(path):
    with open(path) as f:
        return {rec["step"]: rec for rec in map(json.loads, f)}


def test_cli_fake_failure_then_resume(tmp_path):
    """The CLI drill: crash after checkpointing step 2, resume, finish; the
    resumed losses must match an uninterrupted control run step-for-step."""
    common = ["train", "--preset", "vit-base-patch16-224", "--tiny",
              "--batch-size", "4", "--steps", "6", "--save-every", "1",
              "--log-every", "0", "--seed", "7"]

    control = tmp_path / "control.jsonl"
    assert main(common + ["--metrics-file", str(control)]) == 0

    ckpt = tmp_path / "ckpt"
    crashed = tmp_path / "crashed.jsonl"
    with pytest.raises(RuntimeError, match="injected failure at step 2"):
        main(common + ["--ckpt-dir", str(ckpt),
                       "--metrics-file", str(crashed),
                       "--fake-failure-at-step", "2"])
    assert set(read_metrics(crashed)) == {0, 1, 2}

    resumed = tmp_path / "resumed.jsonl"
    assert main(common + ["--ckpt-dir", str(ckpt), "--resume",
                          "--metrics-file", str(resumed)]) == 0
    res = read_metrics(resumed)
    assert set(res) == {3, 4, 5}, "resume must continue at step 3"

    ctl = read_metrics(control)
    for step in (3, 4, 5):
        np.testing.assert_allclose(
            res[step]["loss"], ctl[step]["loss"], rtol=2e-4,
            err_msg=f"loss diverged from uninterrupted run at step {step}")


def test_restore_after_mesh_shrink_continues(tmp_path, eight_devices):
    """Resharding-on-restore drill: save on a data=8 mesh, restore onto
    data=4 (half the devices, as after losing hosts). The resumed run must
    continue step-for-step — same losses AND same batch content hashes as
    an uninterrupted 8-device control — and the restore must count exactly
    one topology change."""
    from jimm_tpu import obs

    common = ["train", "--preset", "vit-base-patch16-224", "--tiny",
              "--batch-size", "8", "--steps", "6", "--save-every", "1",
              "--log-every", "0", "--seed", "7", "--batch-fingerprint",
              "--rules", "dp"]

    control = tmp_path / "control.jsonl"
    assert main(common + ["--mesh", "data=8",
                          "--metrics-file", str(control)]) == 0

    ckpt = tmp_path / "ckpt"
    crashed = tmp_path / "crashed.jsonl"
    with pytest.raises(RuntimeError, match="injected failure at step 2"):
        main(common + ["--mesh", "data=8", "--ckpt-dir", str(ckpt),
                       "--metrics-file", str(crashed),
                       "--fake-failure-at-step", "2"])

    before = obs.snapshot().get(
        "jimm_train_checkpoint_topology_changes_total", 0)
    resumed = tmp_path / "resumed.jsonl"
    assert main(common + ["--mesh", "data=4", "--max-devices", "4",
                          "--ckpt-dir", str(ckpt), "--resume",
                          "--metrics-file", str(resumed)]) == 0
    after = obs.snapshot().get(
        "jimm_train_checkpoint_topology_changes_total", 0)
    assert after == before + 1, \
        "restore across mesh shapes must count a topology change"

    res, ctl = read_metrics(resumed), read_metrics(control)
    assert set(res) == {3, 4, 5}, "resume must continue at step 3"
    for step in (3, 4, 5):
        np.testing.assert_allclose(
            res[step]["loss"], ctl[step]["loss"], rtol=2e-4,
            err_msg=f"loss diverged after mesh shrink at step {step}")
        # content hash of the consumed batch: equality proves the shrunk
        # run consumed byte-identical global batches (no replay, no skip)
        assert res[step]["batch_fingerprint"] == \
            ctl[step]["batch_fingerprint"], \
            f"batch content diverged after mesh shrink at step {step}"


def test_resume_without_checkpoint_starts_fresh(tmp_path):
    """--resume with an empty checkpoint dir is a cold start, not an error."""
    metrics = tmp_path / "m.jsonl"
    assert main(["train", "--preset", "vit-base-patch16-224", "--tiny",
                 "--batch-size", "4", "--steps", "2", "--log-every", "0",
                 "--ckpt-dir", str(tmp_path / "empty"), "--resume",
                 "--metrics-file", str(metrics)]) == 0
    assert set(read_metrics(metrics)) == {0, 1}
