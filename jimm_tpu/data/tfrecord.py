"""Zero-dependency TFRecord + ``tf.train.Example`` codec.

The reference's only real-data path is a tfds MNIST download inside an
example script (ref `examples/vit_training.py:205-212`). Here the on-disk
format is first-class library code with NO tensorflow/protobuf imports: the
TFRecord framing (length / masked-CRC32C / payload) and the three-field
``Example`` proto are simple enough to read and write directly, which keeps
the training-image pipeline importable on a bare TPU host. CRC32C uses the
native C++ library (`native/preprocess.cpp: jimm_crc32c`) when built, with a
table-driven python fallback.

Format compatibility is pinned by tests that cross-read/-write against real
``tensorflow`` (`tests/test_tfrecord.py`).

TFRecord framing (per record):
  uint64le  length
  uint32le  masked_crc32c(length bytes)
  bytes     payload
  uint32le  masked_crc32c(payload)

``Example`` wire format (the subset every TF data tool emits):
  Example   { Features features = 1; }
  Features  { map<string, Feature> feature = 1; }
  Feature   { oneof { BytesList = 1; FloatList = 2; Int64List = 3; } }
  BytesList { repeated bytes value = 1; }
  FloatList { repeated float value = 1 [packed]; }
  Int64List { repeated int64 value = 1 [packed]; }
"""

from __future__ import annotations

import ctypes
import struct
from pathlib import Path
from typing import Any, BinaryIO, Iterable, Iterator

import numpy as np

# ---------------------------------------------------------------------------
# CRC32C
# ---------------------------------------------------------------------------

_CRC_TABLE: np.ndarray | None = None


def _crc_table() -> np.ndarray:
    global _CRC_TABLE
    if _CRC_TABLE is None:
        t = np.empty(256, np.uint32)
        for i in range(256):
            c = np.uint32(i)
            for _ in range(8):
                c = np.uint32(0x82F63B78) ^ (c >> np.uint32(1)) \
                    if c & np.uint32(1) else c >> np.uint32(1)
            t[i] = c
        _CRC_TABLE = t
    return _CRC_TABLE


def _crc32c_py(data: bytes) -> int:
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = int(table[(crc ^ b) & 0xFF]) ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _native_crc():
    from jimm_tpu.data.preprocess import _LIB
    if _LIB is None or not hasattr(_LIB, "jimm_crc32c"):
        return None
    _LIB.jimm_crc32c.restype = ctypes.c_uint32
    _LIB.jimm_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    return _LIB.jimm_crc32c


_NATIVE_CRC = _native_crc()


def crc32c(data: bytes) -> int:
    """CRC32C (Castagnoli) — native C++ when available, python fallback."""
    if _NATIVE_CRC is not None:
        return _NATIVE_CRC(data, len(data))
    return _crc32c_py(data)


def masked_crc32c(data: bytes) -> int:
    """TFRecord's masked CRC: rotate right by 15 and add a constant."""
    crc = crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# TFRecord framing
# ---------------------------------------------------------------------------

class TFRecordWriter:
    def __init__(self, path: str | Path):
        self._f: BinaryIO = open(path, "wb")

    def write(self, record: bytes) -> None:
        length = struct.pack("<Q", len(record))
        self._f.write(length)
        self._f.write(struct.pack("<I", masked_crc32c(length)))
        self._f.write(record)
        self._f.write(struct.pack("<I", masked_crc32c(record)))

    def close(self) -> None:
        self._f.close()

    def __enter__(self) -> "TFRecordWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def write_tfrecord(path: str | Path, records: Iterable[bytes]) -> int:
    with TFRecordWriter(path) as w:
        n = 0
        for rec in records:
            w.write(rec)
            n += 1
    return n


def read_tfrecord(path: str | Path, *, verify: bool = True
                  ) -> Iterator[bytes]:
    """Yield raw record payloads; ``verify`` checks both framing CRCs."""
    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if not header:
                return
            if len(header) < 12:
                raise ValueError(f"{path}: truncated record header")
            (length,), (len_crc,) = (struct.unpack("<Q", header[:8]),
                                     struct.unpack("<I", header[8:]))
            if verify and masked_crc32c(header[:8]) != len_crc:
                raise ValueError(f"{path}: corrupt length crc")
            data = f.read(length)
            if len(data) < length:
                raise ValueError(f"{path}: truncated record body")
            crc_bytes = f.read(4)
            if len(crc_bytes) < 4:
                raise ValueError(f"{path}: truncated record crc")
            (data_crc,) = struct.unpack("<I", crc_bytes)
            if verify and masked_crc32c(data) != data_crc:
                raise ValueError(f"{path}: corrupt record crc")
            yield data


# ---------------------------------------------------------------------------
# Minimal protobuf wire helpers
# ---------------------------------------------------------------------------

def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _tag(field: int, wire: int) -> bytes:
    return _varint(field << 3 | wire)


def _len_delim(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


def _iter_fields(buf: bytes) -> Iterator[tuple[int, int, Any]]:
    """Yield (field_number, wire_type, value) over a serialized message."""
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:  # varint
            val, pos = _read_varint(buf, pos)
        elif wire == 1:  # 64-bit
            val, pos = buf[pos:pos + 8], pos + 8
        elif wire == 2:  # length-delimited
            n, pos = _read_varint(buf, pos)
            val, pos = buf[pos:pos + n], pos + n
        elif wire == 5:  # 32-bit
            val, pos = buf[pos:pos + 4], pos + 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


# ---------------------------------------------------------------------------
# tf.train.Example encode / decode
# ---------------------------------------------------------------------------

def _zigzag_int64(n: int) -> int:
    return n & 0xFFFFFFFFFFFFFFFF  # plain int64 varint (two's complement)


def encode_example(features: dict[str, Any]) -> bytes:
    """dict -> serialized ``tf.train.Example``. Value types: ``bytes``/``str``
    (or list thereof) -> BytesList; ints -> Int64List; floats -> FloatList."""
    feat_entries = []
    for name, value in features.items():
        if isinstance(value, (bytes, str, int, float, np.integer, np.floating)):
            value = [value]
        value = list(value)
        if not value:
            raise ValueError(f"feature {name!r} is empty")
        first = value[0]
        if isinstance(first, (bytes, str)):
            payload = b"".join(
                _len_delim(1, v.encode() if isinstance(v, str) else v)
                for v in value)
            feature = _len_delim(1, payload)  # BytesList
        elif isinstance(first, (int, np.integer)):
            packed = b"".join(_varint(_zigzag_int64(int(v))) for v in value)
            feature = _len_delim(3, _len_delim(1, packed))  # Int64List packed
        elif isinstance(first, (float, np.floating)):
            packed = np.asarray(value, "<f4").tobytes()
            feature = _len_delim(2, _len_delim(1, packed))  # FloatList packed
        else:
            raise TypeError(f"feature {name!r}: {type(first)}")
        entry = _len_delim(1, name.encode()) + _len_delim(2, feature)
        feat_entries.append(_len_delim(1, entry))  # map entry
    features_msg = b"".join(feat_entries)
    return _len_delim(1, features_msg)  # Example.features


def _decode_feature(buf: bytes) -> list:
    for field, _, val in _iter_fields(buf):
        if field == 1:  # BytesList
            return [v for f, _, v in _iter_fields(val) if f == 1]
        if field == 2:  # FloatList
            out: list = []
            for f, wire, v in _iter_fields(val):
                if f != 1:
                    continue
                if wire == 2:  # packed
                    out.extend(np.frombuffer(v, "<f4").tolist())
                else:  # unpacked 32-bit
                    out.append(struct.unpack("<f", v)[0])
            return out
        if field == 3:  # Int64List
            out = []
            for f, wire, v in _iter_fields(val):
                if f != 1:
                    continue
                if wire == 2:  # packed varints
                    pos = 0
                    while pos < len(v):
                        n, pos = _read_varint(v, pos)
                        out.append(n - (1 << 64) if n >= 1 << 63 else n)
                else:
                    out.append(v - (1 << 64) if v >= 1 << 63 else v)
            return out
    return []


def decode_example(buf: bytes) -> dict[str, list]:
    """Serialized ``tf.train.Example`` -> ``{name: list-of-values}``."""
    out: dict[str, list] = {}
    for field, _, features_msg in _iter_fields(buf):
        if field != 1:
            continue
        for f, _, entry in _iter_fields(features_msg):
            if f != 1:
                continue
            name, feature = "", b""
            for ef, _, ev in _iter_fields(entry):
                if ef == 1:
                    name = ev.decode()
                elif ef == 2:
                    feature = ev
            out[name] = _decode_feature(feature)
    return out
