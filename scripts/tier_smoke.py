"""CI tier-1 smoke for tiered billion-row-pattern retrieval
(docs/retrieval.md, "Tiered residency & PQ").

Forces 8 virtual CPU devices and proves the three-tier serving path end
to end in one process, at CI scale:

1. **Store past the budget**: a tmp :class:`VectorStore` gets 6k
   clustered rows (1.5 MiB) over a 32-centroid codebook; the tiered
   searcher is pinned to an 8-block (256 KiB) device arena and a 2 MiB
   host budget, so the corpus spans hot, warm AND cold from the first
   generation.
2. **Growth, flat residency**: three add/refresh rounds grow the corpus
   10x (60k rows, ~60x the device budget). After every round the
   ``jimm_tier_device_resident_bytes`` gauge must read EXACTLY its
   warmup value — growth repacks the fixed arena, never grows it — and
   the trace count must not move (repack, not retrace).
3. **Recall through the cascade**: top-10 at the smoke ``nprobe`` vs
   the exact NumPy oracle over 128 mixture queries, compared on id
   strings (build_ivf reorders rows) — recall@10 >= 0.95 after the
   PQ-coarse probe + exact rescore.
4. **Daemon cycle on one cid**: 10x growth leaves the codebook stale;
   one :class:`IndexDaemon` step must decide ``retrain``, retrain +
   rebuild + re-tier, and leave the whole cycle — decision, apply, and
   the installed plan — on ONE correlation id in the journal.
5. **Live /v1/search**: a closed client loop against a real
   :class:`ServingServer` over the grown index — every request
   answered, zero post-warmup recompiles, gauge still flat.

Exits nonzero (with a JSON error line) on any violation.

Usage:
    JAX_PLATFORMS=cpu python -m scripts.tier_smoke
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
from concurrent.futures import ThreadPoolExecutor

ROWS_BASE = 6_000
GROWTH_ROUNDS = 3
ROWS_PER_ROUND = 18_000          # 6k + 3*18k = 60k = 10x the base
DIM = 64
CENTERS = 32                     # mixture components in the corpus
CLUSTERS = 32                    # trained codebook size
K = 10
BLOCK_N = 128
ARENA_BLOCKS = 8                 # 8 * 128 * 64 * 4 B = 256 KiB device
HOST_BUDGET = 1 << 20            # 1 MiB host — the tail goes cold
NPROBE_SMOKE = 8
NPROBE_MAX = 32
RECALL_QUERIES = 128
RECALL_FLOOR = 0.95
CLIENTS = 16
PER_CLIENT = 2
DAEMON_CID = "tier-smoke-drill"


def fail(msg: str) -> int:
    print(json.dumps({"metric": "tier_smoke", "value": 0.0,
                      "error": msg}), flush=True)
    return 1


def main() -> int:
    # must land before jax initializes its backends
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")

    import numpy as np
    from flax import nnx

    from jimm_tpu import CLIP, preset
    from jimm_tpu.aot import ArtifactStore
    from jimm_tpu.cli import _tiny_override
    from jimm_tpu.obs import get_journal, get_registry
    from jimm_tpu.retrieval import (IndexDaemon, RetrievalService,
                                    VectorStore)
    from jimm_tpu.retrieval.ann import clustered_rows, train_centroids
    from jimm_tpu.serve import (BucketTable, InferenceEngine, ServeClient,
                                ServingServer, counting_forward)

    total = ROWS_BASE + GROWTH_ROUNDS * ROWS_PER_ROUND
    corpus, centers = clustered_rows(total, DIM, CENTERS, seed=3)
    queries, _ = clustered_rows(RECALL_QUERIES, DIM, CENTERS, seed=11,
                                center_mat=centers)
    ids = [f"doc{i:05d}" for i in range(total)]
    buckets = (8,)
    device_budget = ARENA_BLOCKS * BLOCK_N * DIM * 4

    def oracle_ids(loaded, q):
        scores = q @ loaded.matrix_f32().T
        order = np.argsort(-scores, axis=1, kind="stable")[:, :K]
        return [{loaded.ids[j] for j in row} for row in order]

    def recall_now(service, vstore) -> float:
        loaded = vstore.load("corpus")
        want = oracle_ids(loaded, queries)
        hits = 0
        for start in range(0, RECALL_QUERIES, buckets[-1]):
            batch = queries[start:start + buckets[-1]]
            _vals, id_rows = service.search_blocking(batch)
            for qi, row in enumerate(id_rows):
                hits += len(set(row) & want[start + qi])
        return hits / (RECALL_QUERIES * K)

    def resident_gauge() -> float:
        return get_registry("jimm_tier").snapshot()[
            "jimm_tier_device_resident_bytes"]

    with tempfile.TemporaryDirectory(prefix="jimm-tier-smoke-") as root:
        vstore = VectorStore(os.path.join(root, "index"))
        vstore.create("corpus", DIM)
        vstore.add("corpus", ids[:ROWS_BASE], corpus[:ROWS_BASE])
        codebook = train_centroids(corpus[:ROWS_BASE], CLUSTERS, seed=0)
        vstore.set_codebook("corpus", codebook, trained_rows=ROWS_BASE)
        vstore.build_ivf("corpus")
        store = ArtifactStore(os.path.join(root, "aot"))

        service = RetrievalService.from_store(
            vstore, "corpus", k=K, buckets=buckets, block_n=BLOCK_N,
            aot_store=store, mode="tiered", nprobe=NPROBE_SMOKE,
            nprobe_max=NPROBE_MAX, device_budget_bytes=device_budget,
            host_budget_bytes=HOST_BUDGET)
        searcher = service.searcher
        service.warmup()

        tiers = searcher.tier_plan().describe()
        if not (tiers["hot_clusters"] and tiers["warm_clusters"]
                and tiers["cold_clusters"]):
            return fail(f"base corpus must span all three tiers under a "
                        f"{device_budget}-byte arena; plan={tiers}")
        resident0 = searcher.resident_bytes()
        # the arena obeys the budget; ids/centroids/cluster tables ride
        # on top but are fixed-size — allow them, flatness catches leaks
        if resident0 > device_budget + (128 << 10):
            return fail(f"device-resident {resident0} B far exceeds the "
                        f"{device_budget} B arena budget at warmup")
        traces0 = service.trace_count()

        # --- growth: 10x past the device budget, gauge-flat --------------
        for r in range(GROWTH_ROUNDS):
            lo = ROWS_BASE + r * ROWS_PER_ROUND
            vstore.add("corpus", ids[lo:lo + ROWS_PER_ROUND],
                       corpus[lo:lo + ROWS_PER_ROUND])
            searcher.refresh(vstore.load("corpus"),
                             assign=vstore.load_assignments("corpus"))
            service.search_blocking(queries[:buckets[-1]])
            gauge = resident_gauge()
            if gauge != resident0:
                return fail(f"growth round {r}: device-resident gauge "
                            f"moved {resident0} -> {gauge} B; the arena "
                            f"must be fixed")
        if service.trace_count() != traces0:
            return fail(f"growth retraced "
                        f"{service.trace_count() - traces0}x — a refresh "
                        f"must repack, never retrace")

        # --- recall@10 through PQ-coarse + exact rescore ------------------
        recall = recall_now(service, vstore)
        if recall < RECALL_FLOOR:
            return fail(f"recall@{K} = {recall:.4f} < {RECALL_FLOOR} at "
                        f"nprobe={NPROBE_SMOKE} over {total} rows")

        # --- daemon: stale codebook -> retrain cycle on one cid -----------
        daemon = IndexDaemon(vstore, "corpus", searcher, window=1,
                             cooldown=0, cid=DAEMON_CID, seed=0)
        staleness = daemon.sample()["staleness"]
        decision = daemon.step()
        if decision is None or decision["action"] != "retrain":
            return fail(f"10x growth (staleness={staleness:.2f}) must "
                        f"trip a retrain; decision={decision}")
        chain = [e["event"] for e in get_journal().chain(DAEMON_CID)]
        for ev in ("tier_daemon_decision", "tier_daemon_applied",
                   "tier_plan"):
            if ev not in chain:
                return fail(f"daemon cycle not fully journaled on "
                            f"{DAEMON_CID!r}: missing {ev} in {chain}")
        if vstore.ann_status("corpus")["staleness"] != 0.0:
            return fail("retrain did not clear staleness")
        if resident_gauge() != resident0:
            return fail("retrain/re-tier moved the device-resident gauge")
        recall_post = recall_now(service, vstore)
        if recall_post < RECALL_FLOOR:
            return fail(f"post-retrain recall@{K} = {recall_post:.4f} < "
                        f"{RECALL_FLOOR}")

        # --- live /v1/search over the grown index -------------------------
        cfg = _tiny_override(preset("clip-vit-base-patch16"))
        model = CLIP(cfg, rngs=nnx.Rngs(0))
        size = cfg.vision.image_size
        forward, traces = counting_forward(model, "encode_image")
        engine = InferenceEngine(forward, item_shape=(size, size, 3),
                                 buckets=BucketTable((1,)),
                                 max_delay_ms=2.0, trace_count=traces)
        server = ServingServer(engine, retrieval=service, port=0)
        server.start()
        try:
            topk_traces = service.trace_count()

            def one_client(seed: int) -> int:
                client = ServeClient(port=server.port, timeout_s=60.0)
                try:
                    done = 0
                    for j in range(PER_CLIENT):
                        q = queries[(seed * PER_CLIENT + j)
                                    % RECALL_QUERIES]
                        out = client.search(vector=q, k=K)
                        if len(out["ids"]) == K:
                            done += 1
                    return done
                finally:
                    client.close()

            with ThreadPoolExecutor(max_workers=CLIENTS) as pool:
                answered = sum(pool.map(one_client, range(CLIENTS)))
            if answered != CLIENTS * PER_CLIENT:
                return fail(f"only {answered}/{CLIENTS * PER_CLIENT} "
                            f"searches answered")
            delta = service.trace_count() - topk_traces
            if delta:
                return fail(f"live serving retraced {delta}x post-warmup")
            if resident_gauge() != resident0:
                return fail("serving load moved the device-resident gauge")
        finally:
            server.stop()
            searcher.close()

        stats = searcher.tier_stats()
        print(json.dumps({
            "metric": "tier_smoke", "value": 1.0,
            "rows": total, "dim": DIM, "clusters": CLUSTERS, "k": K,
            "block_n": BLOCK_N, "nprobe": NPROBE_SMOKE,
            "device_budget_bytes": device_budget,
            "device_resident_bytes": resident0,
            "corpus_bytes": total * DIM * 4,
            "recall_at_10": round(recall, 4),
            "recall_post_retrain": round(recall_post, 4),
            "staleness_at_decision": round(staleness, 4),
            "daemon_chain": sorted(set(chain)),
            "tiers": {key: stats[key] for key in
                      ("hot_clusters", "warm_clusters", "cold_clusters")},
            "pq_bytes_per_row": stats["pq_bytes_per_row"],
            "searches": answered,
        }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
