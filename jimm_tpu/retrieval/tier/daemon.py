"""Autonomous index maintenance: the tier twin of the CascadeAutoscaler.

An :class:`IndexDaemon` closes the loop the CLI's manual ``index
retrain`` / ``build-ivf`` / ``compact`` workflow leaves open: it samples
the store's ``ann_status`` staleness advice plus the live searcher's
access statistics, keeps a sliding window of samples, and — after a
cooldown, past explicit trip points — makes exactly ONE decision per
tick:

- ``retrain``  — staleness past the store's retrain threshold: train a
  fresh codebook (same cluster count, so the compiled programs never
  retrace), persist it, rebuild cluster runs, refresh the searcher;
- ``build_ivf`` — segments merely lack cluster runs: re-cluster them
  against the current codebook;
- ``compact``  — the tombstone share crossed ``compact_high``: fold live
  rows and refresh so reclaimed rows leave every tier;
- ``retier``   — the access-EMA-optimal hot set drifted from the
  installed one by more than ``retier_high``: rebuild residency so the
  working set is the device-resident set.

Bounded (one decision per tick, cooldown between decisions), hysteretic
(each trip point is well above the post-action value of its own signal,
so an action cannot immediately re-trip itself), and audited: every
decision and application is journaled (``tier_daemon_decision`` /
``tier_daemon_applied``) on the daemon's root correlation id — the same
cid the refresh's ``tier_plan`` event and any resulting ``tier_spill``
transfers carry, so ``jimm-tpu journal correlate`` shows one whole
retrain/re-tier cycle as one chain. ``jimm_tier_daemon_decisions_total``
is pre-created at 0 so "the loop ran and did nothing" is visible,
distinct from "the loop never ran".
"""

from __future__ import annotations

import threading
import time
from collections import deque

from jimm_tpu.obs import get_journal, get_registry, new_correlation_id
from jimm_tpu.retrieval.store import ANN_STALENESS_RETRAIN

__all__ = ["IndexDaemon"]


class IndexDaemon:
    """Background maintenance for one named index.

    Args:
        store: the :class:`~jimm_tpu.retrieval.store.VectorStore`.
        name: the index to maintain.
        searcher: optionally a live
            :class:`~jimm_tpu.retrieval.tier.engine.TieredSearcher` —
            refreshed after every action so serving follows the store;
            without one the daemon still maintains the store itself.
        retrain_high: staleness trip point (default: the store's own
            retrain threshold).
        compact_high: tombstone-share trip point.
        retier_high: hot-set drift trip point (symmetric-difference
            fraction of the installed hot set).
        window / cooldown: hysteresis, measured in ticks.
    """

    def __init__(self, store, name: str, searcher=None, *,
                 retrain_high: float = ANN_STALENESS_RETRAIN,
                 compact_high: float = 0.25, retier_high: float = 0.25,
                 window: int = 3, cooldown: int = 2,
                 cid: str | None = None, seed: int = 0,
                 clock=time.monotonic):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if min(retrain_high, compact_high, retier_high) <= 0:
            raise ValueError("trip points must be positive")
        self.store = store
        self.name = str(name)
        self.searcher = searcher
        self.retrain_high = float(retrain_high)
        self.compact_high = float(compact_high)
        self.retier_high = float(retier_high)
        self.window = int(window)
        self.cooldown = max(0, int(cooldown))
        self.cid = cid or new_correlation_id()
        self.seed = int(seed)
        self.clock = clock
        self.decisions: list[dict] = []
        self._samples: deque[dict] = deque(maxlen=self.window)
        self._cooldown_lock = threading.Lock()
        self._since_decision = self.cooldown  # first full window may act
        self._tick = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._m_decisions = get_registry("jimm_tier").counter(
            "jimm_tier_daemon_decisions_total")
        self._m_decisions.inc(0)

    # -- sensing -----------------------------------------------------------

    def sample(self) -> dict:
        """One sensor reading: store staleness/advice + tombstone share
        + (with a live searcher) hot-set drift vs the access EMA."""
        status = self.store.ann_status(self.name) or {}
        man = self.store.manifest(self.name)
        dead = len(man.get("tombstones", []))
        live = int(status.get("live_rows", 0))
        out = {"staleness": float(status.get("staleness", 0.0)),
               "advice": status.get("advice", "ok"),
               "tombstone_frac": dead / max(live + dead, 1),
               "live": live}
        if self.searcher is not None:
            installed = set(self.searcher.tier_plan().hot)
            proposed = set(self.searcher.propose_plan().hot)
            out["hot_drift"] = (len(installed ^ proposed)
                               / max(len(installed), 1))
        else:
            out["hot_drift"] = 0.0
        return out

    # -- deciding ----------------------------------------------------------

    def tick(self) -> dict | None:
        """Sample, window, and decide. Returns the decision (not yet
        applied — run it through :meth:`apply`) or None."""
        self._tick += 1
        self._samples.append(self.sample())
        if len(self._samples) < self.window:
            return None
        with self._cooldown_lock:
            if self._since_decision < self.cooldown:
                self._since_decision += 1
                return None
        decision = self._decide()
        if decision is None:
            with self._cooldown_lock:
                self._since_decision += 1
            return None
        self._record(decision)
        return decision

    def _mean(self, name: str) -> float:
        return sum(s[name] for s in self._samples) / len(self._samples)

    def _decide(self) -> dict | None:
        staleness = self._mean("staleness")
        tombs = self._mean("tombstone_frac")
        drift = self._mean("hot_drift")
        advice = self._samples[-1]["advice"]
        window = {"staleness": round(staleness, 4),
                  "tombstone_frac": round(tombs, 4),
                  "hot_drift": round(drift, 4), "advice": advice,
                  "ticks": self._tick}
        # priority order: correctness-of-routing first (a stale codebook
        # degrades recall everywhere), storage health second, placement
        # last — and exactly one action per tick
        if staleness >= self.retrain_high:
            return {"action": "retrain", "window": window,
                    "reason": f"staleness {staleness:.3f} >= "
                              f"{self.retrain_high} across the window: "
                              "retrain codebook + rebuild runs"}
        if advice == "build-ivf":
            return {"action": "build_ivf", "window": window,
                    "reason": "segments lack cluster runs: re-cluster "
                              "against the current codebook"}
        if tombs >= self.compact_high:
            return {"action": "compact", "window": window,
                    "reason": f"tombstone share {tombs:.3f} >= "
                              f"{self.compact_high}: fold live rows"}
        if self.searcher is not None and drift >= self.retier_high:
            return {"action": "retier", "window": window,
                    "reason": f"hot-set drift {drift:.3f} >= "
                              f"{self.retier_high}: re-tier to the "
                              "access working set"}
        return None

    def _record(self, decision: dict) -> None:
        self.decisions.append(decision)
        with self._cooldown_lock:
            self._since_decision = 0
        self._m_decisions.inc()
        get_journal().emit("tier_daemon_decision", cid=self.cid,
                           index=self.name, **decision)

    # -- acting ------------------------------------------------------------

    def apply(self, decision: dict) -> None:
        """Execute one decision synchronously on the daemon thread (the
        store does the disk work; the searcher refresh swaps residency
        without a retrace). Journals ``tier_daemon_applied`` with the
        action report on the root cid."""
        t0 = time.perf_counter()
        action = decision["action"]
        report: dict = {}
        if action == "retrain":
            from jimm_tpu.retrieval.ann.kmeans import train_centroids
            loaded = self.store.load(self.name)
            cb = self.store.codebook(self.name)
            n_clusters = (self.searcher.n_clusters
                          if self.searcher is not None
                          else int(cb[0].shape[0]))
            cents = train_centroids(loaded.matrix_f32(), n_clusters,
                                    seed=self.seed)
            self.store.set_codebook(self.name, cents, seed=self.seed)
            report = self.store.build_ivf(self.name)
            self._refresh(centroids=cents)
        elif action == "build_ivf":
            report = self.store.build_ivf(self.name)
            self._refresh()
        elif action == "compact":
            report = self.store.compact(self.name)
            self._refresh()
        elif action == "retier":
            plan = self.searcher.refresh(cid=self.cid)
            report = plan.describe()
        else:
            raise ValueError(f"unknown action {action!r}")
        get_journal().emit("tier_daemon_applied", cid=self.cid,
                           index=self.name, action=action,
                           dur_s=round(time.perf_counter() - t0, 6),
                           **{k: v for k, v in report.items()
                              if isinstance(v, (int, float, str))})

    def _refresh(self, centroids=None) -> None:
        """Reload the index (tombstone-filtered, fresh assignments) into
        the live searcher so every tier follows the store's live set."""
        if self.searcher is None:
            return
        loaded = self.store.load(self.name)
        assign = self.store.load_assignments(self.name)
        self.searcher.refresh(loaded, assign=assign,
                              centroids=centroids, cid=self.cid)

    def step(self) -> dict | None:
        """tick() + apply() — the body of the control loop."""
        decision = self.tick()
        if decision is not None:
            self.apply(decision)
        return decision

    # -- lifecycle ---------------------------------------------------------

    def start(self, interval_s: float = 30.0) -> None:
        """Run :meth:`step` every ``interval_s`` on a daemon thread."""
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    self.step()
                except Exception as e:  # noqa: BLE001 — a failed cycle
                    # must not kill the loop; journal it and keep going
                    get_journal().emit("tier_daemon_error", cid=self.cid,
                                       index=self.name, error=str(e))
                if self._stop.wait(interval_s):
                    return

        self._thread = threading.Thread(
            target=loop, name=f"index-daemon-{self.name}", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5.0)

    # -- introspection -----------------------------------------------------

    def describe(self) -> dict:
        """The healthz ``index_daemon`` block."""
        return {
            "cid": self.cid,
            "index": self.name,
            "retrain_high": self.retrain_high,
            "compact_high": self.compact_high,
            "retier_high": self.retier_high,
            "window": self.window,
            "cooldown": self.cooldown,
            "running": self._thread is not None,
            "decisions": len(self.decisions),
            "last_decision": self.decisions[-1] if self.decisions
            else None,
        }
