"""NaFlex variable-resolution SigLIP2 inference.

Runs a batch of images with DIFFERENT sizes and aspect ratios through one
jitted forward — no per-resolution recompiles, no squashing to a fixed
square. Each image keeps its aspect ratio: it is resized to the largest
patch-divisible grid within the token budget, patchified, and padded; the
model masks the padding and resamples its position table per sample inside
the jit (see `jimm_tpu/nn/naflex.py`).

The reference framework supports "any non-NaFlex variant" only
(ref `README.md:13-14`) — this path is jimm_tpu-specific capability.

Usage:
    python examples/naflex_inference.py [hub-id-or-local-dir]
"""

import sys

import jax.numpy as jnp
import numpy as np
from flax import nnx

from jimm_tpu import SigLIP
from jimm_tpu.data import patchify_naflex, to_float_normalized


def main() -> None:
    repo = sys.argv[1] if len(sys.argv) > 1 else "google/siglip2-base-patch16-256"
    model = SigLIP.from_pretrained(repo, dtype=jnp.bfloat16)
    patch = model.config.vision.patch_size
    budget = model.config.vision.num_patches

    # three images, three different shapes — one batch, one compile
    rng = np.random.RandomState(0)
    images = [to_float_normalized(rng.rand(1, h, w, 3).astype(np.float32))[0]
              for h, w in ((480, 640), (768, 256), (224, 224))]
    patches, shapes, mask = patchify_naflex(images, patch_size=patch,
                                            max_num_patches=budget)

    @nnx.jit  # NOT bare jax.jit: the scanned layer stack is module state
    def embed(model, p, s, m):
        feats = model.encode_image_naflex(p, s, m)
        return feats / jnp.linalg.norm(feats, axis=-1, keepdims=True)

    feats = embed(model, jnp.asarray(patches), jnp.asarray(shapes),
                  jnp.asarray(mask))
    print("grids:", shapes.tolist())
    print("embeddings:", feats.shape, "cosine(img0, img1) =",
          float(feats[0] @ feats[1]))


if __name__ == "__main__":
    main()
