"""Persistent, fingerprint-keyed store of autotuning results.

A tuned block config is reusable only when everything that shaped the
measurement matches: the kernel (name + implementation version), the
operand shapes and dtypes, the backend the timing ran on, and the jax
version that lowered the kernel. All of it folds into one SHA-256
fingerprint over canonical JSON — the same byte-stable discipline as
`jimm_tpu/aot/keys.py`, whose `canonical_json` this module reuses — and
the record lands in a `jimm_tpu.aot.store.ArtifactStore` (atomic writes,
per-read integrity hash, quarantine on corruption, LRU gc) holding a small
JSON payload instead of a serialized executable.

No jax import at module level: ``jimm-tpu tune ls`` stays a pure host
tool, and `tune_key` only touches jax to *default* the backend/version
fields when they are not passed explicitly.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from pathlib import Path
from typing import Any, Mapping, Sequence

from jimm_tpu.aot.keys import canonical_json
from jimm_tpu.aot.store import ArtifactStore

__all__ = ["TUNE_FORMAT_VERSION", "TuneCache", "TuneKey", "default_root",
           "tune_key"]

#: bump when the record payload layout changes — old entries then read as
#: misses (different fingerprint) instead of deserializing garbage
TUNE_FORMAT_VERSION = 1

#: override with JIMM_TUNE_CACHE, `tune.configure(root)`, or the CLI --store
DEFAULT_CACHE_ROOT = "~/.cache/jimm_tpu/tune"


def default_root() -> str:
    return os.environ.get("JIMM_TUNE_CACHE", DEFAULT_CACHE_ROOT)


def _dtype_name(d: Any) -> str:
    """Canonical dtype string without importing jax: accepts 'bfloat16',
    np.float32, jnp.bfloat16, or any dtype-like with a ``.name``."""
    if isinstance(d, str):
        return d
    name = getattr(d, "name", None)
    if isinstance(name, str):
        return name
    import numpy as np
    return str(np.dtype(d).name)


@dataclasses.dataclass(frozen=True)
class TuneKey:
    """Every field that must match for a tuned config to be reusable."""

    kernel: str
    kernel_version: int
    shapes: tuple[tuple[int, ...], ...]
    dtypes: tuple[str, ...]
    backend: str
    jax_version: str
    format_version: int = TUNE_FORMAT_VERSION

    def fingerprint(self) -> str:
        """Hex SHA-256 over the canonical JSON of all fields — byte-stable
        across processes (`tests/test_tune.py` pins cross-process
        stability the same way the AOT keys are golden-tested)."""
        return hashlib.sha256(
            canonical_json(dataclasses.asdict(self)).encode()).hexdigest()

    def describe(self) -> dict:
        """Human-facing metadata recorded in the store entry."""
        return {"kernel": self.kernel,
                "kernel_version": self.kernel_version,
                "shapes": [list(s) for s in self.shapes],
                "dtypes": list(self.dtypes),
                "backend": self.backend,
                "jax": self.jax_version}


def tune_key(kernel: str, *, shapes: Sequence[Sequence[int]],
             dtypes: Sequence[Any], kernel_version: int,
             backend: str | None = None,
             jax_version: str | None = None) -> TuneKey:
    """Build the key for one (kernel, shapes, dtypes) tuning point.

    Backend/version default from the running jax, but accept explicit
    values so keys can be computed (and tested) without a backend.
    """
    if backend is None or jax_version is None:
        import jax
        backend = backend or jax.default_backend()
        jax_version = jax_version or jax.__version__
    return TuneKey(
        kernel=str(kernel),
        kernel_version=int(kernel_version),
        shapes=tuple(tuple(int(d) for d in s) for s in shapes),
        dtypes=tuple(_dtype_name(d) for d in dtypes),
        backend=str(backend),
        jax_version=str(jax_version),
    )


class TuneCache:
    """Tuned-config records on top of an `ArtifactStore`.

    Hits are memoized in-process so the trace-time `best_config` lookup in
    the ops hot path costs one dict probe after the first resolution of a
    shape. Misses are NOT memoized: an offline ``jimm-tpu tune`` run (or
    another replica) may populate the store between traces, and the next
    lookup should see it.
    """

    def __init__(self, root: str | os.PathLike | None = None,
                 max_bytes: int | None = None):
        self.store = ArtifactStore(Path(root or default_root()).expanduser(),
                                   max_bytes=max_bytes)
        self._memo: dict[str, dict] = {}

    @property
    def root(self) -> Path:
        return self.store.root

    def get(self, key: TuneKey) -> dict | None:
        """The stored record ``{"config": ..., "metrics": ...}`` or None."""
        fp = key.fingerprint()
        rec = self._memo.get(fp)
        if rec is not None:
            return rec
        payload = self.store.get(fp)
        if payload is None:
            return None
        try:
            rec = json.loads(payload.decode())
        except (UnicodeDecodeError, ValueError):
            self.store.quarantine(fp, "undecodable tune record")
            return None
        if not isinstance(rec, dict) or not isinstance(rec.get("config"),
                                                       dict):
            self.store.quarantine(fp, "tune record missing config mapping")
            return None
        self._memo[fp] = rec
        return rec

    def put(self, key: TuneKey, config: Mapping[str, Any],
            metrics: Mapping[str, Any] | None = None) -> str:
        """Persist the winning ``config`` (plus measurement provenance);
        returns the fingerprint."""
        fp = key.fingerprint()
        rec = {"config": dict(config), "metrics": dict(metrics or {}),
               "key": key.describe()}
        self.store.put(fp, canonical_json(rec).encode(),
                       meta={"label": f"tune:{key.kernel}",
                             **key.describe()})
        self._memo[fp] = rec
        return fp

    def entries(self):
        return self.store.entries()
