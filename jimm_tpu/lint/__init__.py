"""``jimm_tpu.lint`` — TPU-correctness static analyzer.

Layer 1 (always on) is pure-``ast`` rules JL001–JL006 over the source tree;
layer 2 (``--trace``) lowers registered model entry points and asserts
program-text properties JLT101–JLT103. See ``docs/static_analysis.md`` for
the rule catalog and suppression syntax (``# jaxlint: disable=<rule>``).
"""

from jimm_tpu.lint.core import ERROR, WARNING, Finding, lint_file, lint_paths

__all__ = ["ERROR", "WARNING", "Finding", "lint_file", "lint_paths"]
