"""Cross-file JL014 waiver base: the eviction policy lives HERE, in the
base class — a per-file scan of the subclass can't see it."""


class BoundedTable:
    def __init__(self, cap: int = 64):
        self._table: dict = {}
        self._cap = cap

    def _evict_if_full(self):
        while len(self._table) > self._cap:
            self._table.pop(next(iter(self._table)))
