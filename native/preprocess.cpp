// Native host-side image preprocessing for the input pipeline.
//
// The TPU compute path is jax/XLA; the host-side runtime around it is native
// (the reference has no native code at all — SURVEY §2.2 — its input path is
// single-threaded numpy, ref `examples/vit_training.py:45-57`). This library
// does the per-batch CPU work that would otherwise serialize with dispatch:
// uint8 -> float32 conversion, mean/std normalization, bilinear resize, and
// center crop, multithreaded over the batch dimension.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image). All
// arrays are C-contiguous NHWC.
//
// Build: make -C native   ->  native/libjimm_preprocess.so

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <functional>
#include <thread>
#include <vector>

namespace {

// Run fn(b) for b in [0, batch) over a small thread pool.
void parallel_for_batch(int64_t batch, int threads,
                        const std::function<void(int64_t)>& fn) {
  if (threads <= 1 || batch <= 1) {
    for (int64_t b = 0; b < batch; ++b) fn(b);
    return;
  }
  int n = std::min<int64_t>(threads, batch);
  std::vector<std::thread> pool;
  pool.reserve(n);
  std::atomic<int64_t> next{0};
  for (int t = 0; t < n; ++t) {
    pool.emplace_back([&] {
      for (int64_t b = next.fetch_add(1); b < batch; b = next.fetch_add(1))
        fn(b);
    });
  }
  for (auto& th : pool) th.join();
}

inline float lerp(float a, float b, float w) { return a + (b - a) * w; }

// CRC32C (Castagnoli, reflected poly 0x82F63B78), slice-by-8: the checksum
// of the TFRecord framing format. Software table version — portable, and at
// ~1-2 GB/s far from the input-pipeline bottleneck.
struct Crc32cTables {
  uint32_t t[8][256];
  Crc32cTables() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k) c = (c & 1) ? 0x82F63B78u ^ (c >> 1) : c >> 1;
      t[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; ++i)
      for (int s = 1; s < 8; ++s)
        t[s][i] = (t[s - 1][i] >> 8) ^ t[0][t[s - 1][i] & 0xFF];
  }
};
const Crc32cTables kCrc;

uint32_t crc32c_impl(const uint8_t* p, int64_t n, uint32_t crc) {
  crc = ~crc;
  while (n >= 8) {
    uint64_t word;
    std::memcpy(&word, p, 8);
    crc ^= static_cast<uint32_t>(word);
    uint32_t hi = static_cast<uint32_t>(word >> 32);
    crc = kCrc.t[7][crc & 0xFF] ^ kCrc.t[6][(crc >> 8) & 0xFF] ^
          kCrc.t[5][(crc >> 16) & 0xFF] ^ kCrc.t[4][crc >> 24] ^
          kCrc.t[3][hi & 0xFF] ^ kCrc.t[2][(hi >> 8) & 0xFF] ^
          kCrc.t[1][(hi >> 16) & 0xFF] ^ kCrc.t[0][hi >> 24];
    p += 8;
    n -= 8;
  }
  while (n-- > 0) crc = kCrc.t[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

// Bilinear sample of one output row for all channels.
void resize_row(const float* src, int64_t sh, int64_t sw, int64_t c,
                float* dst, int64_t dw, float sy, float scale_x) {
  int64_t y0 = static_cast<int64_t>(sy);
  y0 = std::min(y0, sh - 1);
  int64_t y1 = std::min(y0 + 1, sh - 1);
  float wy = sy - static_cast<float>(y0);
  const float* row0 = src + y0 * sw * c;
  const float* row1 = src + y1 * sw * c;
  for (int64_t x = 0; x < dw; ++x) {
    float sx = (static_cast<float>(x) + 0.5f) * scale_x - 0.5f;
    sx = std::max(sx, 0.0f);
    int64_t x0 = static_cast<int64_t>(sx);
    x0 = std::min(x0, sw - 1);
    int64_t x1 = std::min(x0 + 1, sw - 1);
    float wx = sx - static_cast<float>(x0);
    for (int64_t k = 0; k < c; ++k) {
      float top = lerp(row0[x0 * c + k], row0[x1 * c + k], wx);
      float bot = lerp(row1[x0 * c + k], row1[x1 * c + k], wx);
      dst[x * c + k] = lerp(top, bot, wy);
    }
  }
}

}  // namespace

extern "C" {

// uint8 [B,H,W,C] -> float32 [B,H,W,C], out = (in*(1/255) - mean[c]) / std[c]
void jimm_u8_to_f32_normalize(const uint8_t* in, float* out, int64_t batch,
                              int64_t h, int64_t w, int64_t c,
                              const float* mean, const float* std_,
                              int threads) {
  const int64_t plane = h * w * c;
  std::vector<float> inv_std(c), off(c);
  for (int64_t k = 0; k < c; ++k) {
    inv_std[k] = 1.0f / std_[k];
    off[k] = mean[k];
  }
  parallel_for_batch(batch, threads, [&](int64_t b) {
    const uint8_t* src = in + b * plane;
    float* dst = out + b * plane;
    constexpr float kInv255 = 1.0f / 255.0f;
    for (int64_t i = 0; i < plane; ++i) {
      int64_t k = i % c;
      dst[i] = (static_cast<float>(src[i]) * kInv255 - off[k]) * inv_std[k];
    }
  });
}

// float32 [B,H,W,C] in-place channel normalization: (x - mean[c]) / std[c]
void jimm_f32_normalize(float* data, int64_t batch, int64_t h, int64_t w,
                        int64_t c, const float* mean, const float* std_,
                        int threads) {
  const int64_t plane = h * w * c;
  std::vector<float> inv_std(c);
  for (int64_t k = 0; k < c; ++k) inv_std[k] = 1.0f / std_[k];
  parallel_for_batch(batch, threads, [&](int64_t b) {
    float* p = data + b * plane;
    for (int64_t i = 0; i < plane; ++i) {
      int64_t k = i % c;
      p[i] = (p[i] - mean[k]) * inv_std[k];
    }
  });
}

// Bilinear resize float32 [B,sh,sw,C] -> [B,dh,dw,C] (half-pixel centers,
// matching PIL/TF "align_corners=False" semantics).
void jimm_resize_bilinear_f32(const float* in, float* out, int64_t batch,
                              int64_t sh, int64_t sw, int64_t dh, int64_t dw,
                              int64_t c, int threads) {
  const float scale_y = static_cast<float>(sh) / static_cast<float>(dh);
  const float scale_x = static_cast<float>(sw) / static_cast<float>(dw);
  parallel_for_batch(batch, threads, [&](int64_t b) {
    const float* src = in + b * sh * sw * c;
    float* dst = out + b * dh * dw * c;
    for (int64_t y = 0; y < dh; ++y) {
      float sy = (static_cast<float>(y) + 0.5f) * scale_y - 0.5f;
      sy = std::max(sy, 0.0f);
      resize_row(src, sh, sw, c, dst + y * dw * c, dw, sy, scale_x);
    }
  });
}

// Center crop float32 [B,H,W,C] -> [B,ch,cw,C]
void jimm_center_crop_f32(const float* in, float* out, int64_t batch,
                          int64_t h, int64_t w, int64_t ch, int64_t cw,
                          int64_t c, int threads) {
  const int64_t y0 = (h - ch) / 2;
  const int64_t x0 = (w - cw) / 2;
  parallel_for_batch(batch, threads, [&](int64_t b) {
    const float* src = in + (b * h * w + y0 * w + x0) * c;
    float* dst = out + b * ch * cw * c;
    for (int64_t y = 0; y < ch; ++y)
      std::memcpy(dst + y * cw * c, src + y * w * c,
                  sizeof(float) * cw * c);
  });
}

// CRC32C of a byte buffer (TFRecord framing checksum).
uint32_t jimm_crc32c(const uint8_t* data, int64_t n) {
  return crc32c_impl(data, n, 0);
}

}  // extern "C"
