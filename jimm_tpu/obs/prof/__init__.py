"""jimm_tpu.obs.prof — continuous profiling + HBM observability.

Three pieces (see docs/observability.md "Profiling & memory"):

- :mod:`~jimm_tpu.obs.prof.capture` — the windowed ``jax.profiler``
  capture manager: an always-on bounded-overhead ring of recent
  step-window captures plus ``trigger(cid)`` deep captures correlated on
  flight-recorder cids (``prof_capture_started/committed`` journal
  events). The ONLY sanctioned home of ``start_trace``/``stop_trace``
  (lint JL022).
- :mod:`~jimm_tpu.obs.prof.memory` — per-device HBM gauges
  (``jimm_hbm_*``), per-subsystem byte attribution, and the
  ``hbm_leak_suspected`` monotonic-growth watchdog.
- :mod:`~jimm_tpu.obs.prof.opstats` — jax-free parsing of committed
  captures into top-k per-op tables and a direction-aware diff (the
  ``obs prof ls/show/diff`` CLI).
"""

from jimm_tpu.obs.prof.capture import (CaptureManager, configure_capture,
                                       get_capture_manager, list_captures,
                                       maybe_trigger, profiler_session,
                                       reset_capture)
from jimm_tpu.obs.prof.memory import MemoryMonitor, device_memory_rows
from jimm_tpu.obs.prof.opstats import (aggregate_ops, diff_ops, op_table,
                                       render_diff, render_table, top_ops)

__all__ = [
    "CaptureManager", "MemoryMonitor", "aggregate_ops", "configure_capture",
    "device_memory_rows", "diff_ops", "get_capture_manager",
    "list_captures", "maybe_trigger", "op_table", "profiler_session",
    "render_diff", "render_table", "reset_capture", "top_ops",
]
