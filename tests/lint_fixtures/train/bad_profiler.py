"""JL022 fixture: direct jax.profiler session control outside obs/prof —
racing the continuous capture ring for the process's ONE profiler session."""

import jax
from jax.profiler import start_trace, stop_trace


def profile_a_few_steps(step_fn, log_dir):
    jax.profiler.start_trace(log_dir)       # JL022: attribute spelling
    for _ in range(3):
        step_fn()
    jax.profiler.stop_trace()               # JL022: same hole on the way out


def profile_imported(step_fn, log_dir):
    start_trace(log_dir)                    # JL022: from-import spelling
    step_fn()
    stop_trace()                            # JL022


def sanctioned_direct(log_dir):
    # ok: justified direct session (a standalone harness with no ring)
    jax.profiler.start_trace(log_dir)  # jaxlint: disable=JL022 ringless one-off harness


def sanctioned_session(step_fn, log_dir):
    # ok: the ring's session lock serializes this against window captures
    from jimm_tpu.obs.prof.capture import profiler_session
    with profiler_session(log_dir):
        step_fn()


def annotations_stay_legal(name):
    # ok: TraceAnnotation is session-agnostic — no session claimed
    return jax.profiler.TraceAnnotation(name)
