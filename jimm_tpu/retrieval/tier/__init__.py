"""Tiered residency for billion-row retrieval: HBM / host RAM / disk.

The subsystem that makes corpus size independent of device memory. Hot
clusters stay device-resident in a fixed budgeted arena, warm clusters
in host RAM, cold clusters on the aot artifact store; the device-side
coarse probe names which clusters a query touches and only those stream
up the hierarchy, double-buffered behind the PQ asymmetric-distance
pass (:mod:`~jimm_tpu.retrieval.tier.engine`). The
:class:`~jimm_tpu.retrieval.tier.daemon.IndexDaemon` keeps the whole
arrangement healthy autonomously — retrain, rebuild, compact, re-tier —
journaled on one correlation id per cycle.

Importing this package never imports jax (the CLI and the daemon's
store-only mode stay accelerator-free); the device programs materialize
lazily inside :class:`TieredSearcher`.
"""

from jimm_tpu.retrieval.tier.daemon import IndexDaemon
from jimm_tpu.retrieval.tier.engine import (DEFAULT_DEVICE_BUDGET_MB,
                                            TieredSearcher,
                                            make_rescore_fn, make_tier_fn)
from jimm_tpu.retrieval.tier.io import (TIER_FORMAT_VERSION, TierIoEngine,
                                        decode_cluster, encode_cluster)
from jimm_tpu.retrieval.tier.pq import (PQ_FORMAT_VERSION, PqCodec,
                                        adc_scores, decode_pq, encode_pq,
                                        encode_rows, query_luts, train_pq)
from jimm_tpu.retrieval.tier.residency import (AccessStats, TierPlan,
                                               plan_tiers)

__all__ = [
    "AccessStats",
    "DEFAULT_DEVICE_BUDGET_MB",
    "IndexDaemon",
    "PQ_FORMAT_VERSION",
    "PqCodec",
    "TIER_FORMAT_VERSION",
    "TierIoEngine",
    "TierPlan",
    "TieredSearcher",
    "adc_scores",
    "decode_cluster",
    "decode_pq",
    "encode_cluster",
    "encode_pq",
    "encode_rows",
    "make_rescore_fn",
    "make_tier_fn",
    "plan_tiers",
    "query_luts",
    "train_pq",
]
