"""JL019 seed: blocking calls while holding a threading lock — lexically,
and through a helper whose every caller holds the lock. The clean twins
block only after releasing."""

import queue
import threading
import time


class SleepyWorker:
    def __init__(self):
        self._lock = threading.Lock()
        self._q: queue.Queue = queue.Queue()
        self.last = None

    def poll_bad(self):
        with self._lock:
            time.sleep(0.5)  # JL019: every contender stalls half a second
            self.last = "polled"

    def drain_bad(self):
        with self._lock:
            self.last = self._q.get()  # JL019: queue wait under the lock

    def drain_via_helper(self):
        with self._lock:
            self._take_one()

    def _take_one(self):
        # no lexical lock here, but the only caller holds it: JL019 via
        # entry-guard inference
        item = self._q.get()
        self.last = item

    def poll_ok(self):
        time.sleep(0.5)  # not holding anything: clean
        with self._lock:
            self.last = "polled"

    def drain_ok(self):
        item = self._q.get()  # wait first, then lock: clean
        with self._lock:
            self.last = item
