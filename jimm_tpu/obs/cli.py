"""``jimm-tpu obs`` — tail, snapshot, diff, timeline, regress, and prof.

Six verbs over the exporter formats (stdlib only, no jax import):

- ``snapshot`` — fetch a ``/metrics`` endpoint (or read a saved dump) and
  print it as a console table, JSON, or raw Prometheus text; ``-o`` saves
  the parsed snapshot as JSON for a later ``diff``.
- ``tail``     — follow a MEASUREMENTS.jsonl-style ledger (``tail -f`` with
  JSON pretty-keys), or poll a ``/metrics`` URL and print only the series
  that changed between polls; ``--traces`` polls a serving server's
  ``/debug/traces`` ring and prints each request's phase decomposition.
- ``diff``     — structural diff of two dumps (JSON snapshot or Prometheus
  text, auto-detected): added / removed / changed with deltas.
- ``timeline`` — merge a flight-recorder journal (plus optional serve
  traces and a goodput report) into Chrome trace-event JSON loadable in
  Perfetto / ``chrome://tracing``.
- ``regress``  — gate fresh MEASUREMENTS.jsonl rows against adopted
  per-(workload,backend,preset) baselines; fallback rows are excluded
  from comparison and ``--adopt`` records new baselines.
- ``prof``     — the continuous-profiling ring: ``ls`` committed captures,
  ``show`` a per-op table, ``diff`` two captures direction-aware (exit 1
  on regression), and ``trigger`` a deep capture on a running server.
  ``ls``/``show``/``diff`` stay jax-free so they run on a dev box against
  artifacts rsynced off the TPU host.

Wired as a subparser under the main ``jimm-tpu`` CLI (see jimm_tpu/cli.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request

from jimm_tpu.obs.exporters import (console_table, diff_snapshots,
                                    parse_prometheus_text)

__all__ = ["add_obs_parser", "cmd_obs"]


def _load_dump(source: str, timeout_s: float = 10.0) -> dict[str, float]:
    """Read a metrics dump from a URL, JSON file, or Prometheus text file."""
    if source.startswith(("http://", "https://")):
        with urllib.request.urlopen(source, timeout=timeout_s) as resp:
            text = resp.read().decode("utf-8")
    else:
        with open(source) as f:
            text = f.read()
    text = text.strip()
    if text.startswith("{"):
        data = json.loads(text)
        return {k: v for k, v in data.items()
                if isinstance(v, (int, float))}
    return parse_prometheus_text(text)


def _cmd_snapshot(args) -> int:
    series = _load_dump(args.source)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(series, f, indent=2, sort_keys=True)
            f.write("\n")
    if args.json:
        print(json.dumps(series, indent=2, sort_keys=True))
    else:
        print(console_table(series, title=f"metrics: {args.source}"),
              end="")
    return 0


def _follow_lines(path: str, *, follow: bool, poll_s: float = 0.5,
                  sleep=time.sleep, should_stop=None):
    """Yield lines from ``path``, surviving journal-style rotation.

    The flight-recorder journal rotates by renaming the live file aside
    and recreating the path; a follower holding the old descriptor then
    reads EOF forever. So at EOF we re-stat the *path*: a changed inode
    (or a file shorter than our read position — truncate-in-place
    rotation) means a new file is live, and we reopen from its top.
    ``sleep``/``should_stop`` are injectable so the rotation regression
    test can drive the loop without wall-clock waits."""
    f = open(path)
    try:
        ino = os.fstat(f.fileno()).st_ino
        while True:
            line = f.readline()
            if line:
                yield line
                continue
            if not follow:
                return
            try:
                st = os.stat(path)
            except OSError:
                st = None  # mid-rotation window; poll again
            if st is not None and (st.st_ino != ino
                                   or st.st_size < f.tell()):
                f.close()
                f = open(path)
                ino = os.fstat(f.fileno()).st_ino
                continue
            if should_stop is not None and should_stop():
                return
            sleep(poll_s)
    finally:
        f.close()


def _tail_jsonl(path: str, follow: bool, *, sleep=time.sleep,
                should_stop=None, out=None) -> int:
    out = out if out is not None else sys.stdout
    for line in _follow_lines(path, follow=follow, sleep=sleep,
                              should_stop=should_stop):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        ts = rec.pop("ts", "")
        phase = rec.pop("phase", "")
        keys = ", ".join(f"{k}={v}" for k, v in sorted(rec.items()))
        print(f"{ts} [{phase}] {keys}", file=out, flush=True)
    return 0


def _tail_url(url: str, interval_s: float) -> int:
    prev: dict[str, float] = {}
    while True:
        try:
            cur = _load_dump(url)
        except OSError as e:
            print(f"# fetch failed: {e}", file=sys.stderr, flush=True)
            time.sleep(interval_s)
            continue
        changes = diff_snapshots(prev, cur)
        stamp = time.strftime("%H:%M:%S")
        for name, value in sorted(changes["added"].items()):
            print(f"{stamp} {name} = {value}", flush=True)
        for name, d in sorted(changes["changed"].items()):
            print(f"{stamp} {name} = {d['after']} ({d['delta']:+g})",
                  flush=True)
        prev = cur
        time.sleep(interval_s)


def _trace_line(row: dict) -> str:
    phases = " ".join(
        f"{p[:-2]}={row.get(p, 0.0) * 1e3:.2f}ms"
        for p in ("queue_s", "pad_s", "device_s", "readback_s")
        if isinstance(row.get(p), (int, float)))
    total = row.get("total_s")
    total_txt = f" total={total * 1e3:.2f}ms" \
        if isinstance(total, (int, float)) else ""
    return (f"{row.get('trace_id', '?')} replica={row.get('replica', '?')} "
            f"bucket={row.get('bucket', '?')} {phases}{total_txt}")


def _load_trace_rows(source: str) -> list[dict]:
    """Rows from a ``/debug/traces`` endpoint or a saved JSON dump."""
    if source.startswith(("http://", "https://")):
        url = source if source.endswith("/debug/traces") \
            else source.rstrip("/") + "/debug/traces"
        with urllib.request.urlopen(url, timeout=10.0) as resp:
            data = json.loads(resp.read().decode("utf-8"))
    else:
        with open(source) as f:
            data = json.load(f)
    if isinstance(data, dict):
        data = data.get("traces", [])
    return [r for r in data if isinstance(r, dict)]


def _tail_traces(source: str, interval_s: float, follow: bool) -> int:
    seen: set = set()
    while True:
        try:
            rows = _load_trace_rows(source)
        except OSError as e:
            print(f"# fetch failed: {e}", file=sys.stderr, flush=True)
            rows = []
        for row in rows:
            tid = row.get("trace_id")
            if tid in seen:
                continue
            seen.add(tid)
            print(_trace_line(row), flush=True)
        if len(seen) > 4096:  # ring is small; cap the dedup set anyway
            seen = set(r.get("trace_id") for r in rows)
        if not follow and not source.startswith(("http://", "https://")):
            return 0
        time.sleep(interval_s)


def _cmd_tail(args) -> int:
    if args.traces:
        try:
            return _tail_traces(args.source, args.interval, args.follow)
        except KeyboardInterrupt:
            return 0
    if args.source.startswith(("http://", "https://")):
        try:
            return _tail_url(args.source, args.interval)
        except KeyboardInterrupt:
            return 0
    try:
        return _tail_jsonl(args.source, follow=args.follow)
    except KeyboardInterrupt:
        return 0


def _cmd_diff(args) -> int:
    before = _load_dump(args.before)
    after = _load_dump(args.after)
    d = diff_snapshots(before, after)
    if args.json:
        print(json.dumps(d, indent=2, sort_keys=True))
    else:
        for name, value in sorted(d["added"].items()):
            print(f"+ {name} = {value}")
        for name, value in sorted(d["removed"].items()):
            print(f"- {name} = {value}")
        for name, c in sorted(d["changed"].items()):
            print(f"~ {name}: {c['before']} -> {c['after']} "
                  f"({c['delta']:+g})")
        if not (d["added"] or d["removed"] or d["changed"]):
            print("(no differences)")
    return 1 if (d["added"] or d["removed"] or d["changed"]) else 0


def _cmd_timeline(args) -> int:
    from jimm_tpu.obs.journal import read_events
    from jimm_tpu.obs.timeline import (export_timeline,
                                       validate_chrome_trace,
                                       write_timeline)

    events = read_events(args.journal)
    traces = _load_trace_rows(args.traces) if args.traces else []
    captures = []
    if args.prof:
        from jimm_tpu.obs.prof.capture import list_captures
        captures = list_captures(args.prof)
    goodput = None
    if args.goodput:
        with open(args.goodput) as f:
            report = json.load(f)
        # accept either a raw {bucket: seconds} map or a goodput report
        # with {bucket}_s keys
        goodput = {k[:-2]: v for k, v in report.items()
                   if k.endswith("_s") and isinstance(v, (int, float))} \
            or {k: v for k, v in report.items()
                if isinstance(v, (int, float))}
    trace = export_timeline(events, traces=traces, captures=captures,
                            goodput=goodput,
                            meta={"journal": str(args.journal)})
    problems = validate_chrome_trace(trace)
    if problems:
        for p in problems:
            print(f"invalid trace: {p}", file=sys.stderr)
        return 1
    out = args.out or "timeline.json"
    write_timeline(out, trace)
    n = sum(1 for e in trace["traceEvents"] if e.get("ph") != "M")
    print(f"wrote {out}: {n} events from {len(events)} journal records"
          f" + {len(traces)} serve traces + {len(captures)} captures"
          f" (open in Perfetto or chrome://tracing)")
    return 0


def _cmd_prof_ls(args) -> int:
    from jimm_tpu.obs.prof.capture import list_captures
    metas = list_captures(args.dir)
    if args.json:
        print(json.dumps([{k: v for k, v in m.items() if k != "path"}
                          for m in metas], indent=2))
        return 0
    if not metas:
        print(f"(no committed captures under {args.dir})")
        return 0
    print(f"{'capture':<24} {'kind':<7} {'dur':>8} {'bytes':>10} "
          f"{'step':>7}  cid / reason")
    for m in metas:
        dur = m.get("dur_s")
        dur_txt = f"{dur:.3f}s" if isinstance(dur, (int, float)) else "?"
        step = m.get("step")
        tail = " ".join(str(x) for x in (m.get("cid"), m.get("reason"))
                        if x is not None)
        print(f"{m.get('name', '?'):<24} {m.get('kind', '?'):<7} "
              f"{dur_txt:>8} {m.get('bytes', 0):>10} "
              f"{step if step is not None else '-':>7}  {tail}")
    return 0


def _cmd_prof_show(args) -> int:
    from jimm_tpu.obs.prof.opstats import op_table, render_table
    rows = op_table(args.capture, device=args.device)
    print(render_table(rows, top=args.top))
    return 0


def _cmd_prof_diff(args) -> int:
    from jimm_tpu.obs.prof.opstats import diff_ops, op_table, render_diff
    before = op_table(args.before, device=args.device)
    after = op_table(args.after, device=args.device)
    d = diff_ops(before, after, threshold=args.threshold, top=args.top)
    if args.json:
        print(json.dumps(d, indent=2))
    else:
        print(render_diff(d))
    return 1 if d["verdict"] == "regression" else 0


def _cmd_prof_trigger(args) -> int:
    url = args.url.rstrip("/") + "/admin/prof/trigger"
    payload: dict = {"reason": args.reason}
    if args.cid:
        payload["cid"] = args.cid
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode("utf-8"),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=15.0) as resp:
            body = json.loads(resp.read().decode("utf-8"))
    except OSError as e:
        print(f"trigger failed: {e}", file=sys.stderr)
        return 1
    print(json.dumps(body, indent=2))
    return 0 if body.get("triggered") else 1


def _cmd_regress(args) -> int:
    from jimm_tpu.obs.baseline import (BaselineStore, check_rows, is_fallback,
                                       summarize)

    rows = []
    with open(args.measurements) as f:
        for line in f:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                rows.append(rec)
    store = BaselineStore(args.baselines)
    if args.adopt:
        adopted = store.adopt_rows(rows, note=args.note)
        store.save()
        print(f"adopted {len(adopted)} baseline(s) into {args.baselines}")
        for name in adopted:
            print(f"  + {name}")
        return 0
    verdicts = check_rows(store, rows, threshold=args.threshold)
    counts = summarize(verdicts)
    if args.json:
        print(json.dumps({"verdicts": verdicts, "summary": counts},
                         indent=2))
    else:
        for v in verdicts:
            if v["status"] == "fallback_excluded":
                print(f"! {v['key']}: fallback row excluded from gating")
            elif v["status"] == "no_baseline":
                print(f"? {v['key']} {v['metric']}={v['fresh']} "
                      f"(no baseline; run with --adopt)")
            else:
                mark = {"ok": "=", "improved": "+",
                        "regression": "REGRESSION"}[v["status"]]
                print(f"{mark} {v['key']} {v['metric']}: {v['fresh']} vs "
                      f"baseline {v['baseline']} "
                      f"({v['delta_frac']:+.1%})")
        print(f"summary: {counts['ok']} ok, {counts['improved']} improved, "
              f"{counts['regression']} regression(s), "
              f"{counts['no_baseline']} unbaselined, "
              f"{counts['fallback_excluded']} fallback-excluded "
              f"(threshold {args.threshold:.0%})")
    if counts["regression"]:
        return 1
    if args.fail_on_fallback and counts["fallback_excluded"]:
        n_real = sum(1 for r in rows if not is_fallback(r))
        print(f"fallback rows present ({counts['fallback_excluded']}) with "
              f"--fail-on-fallback ({n_real} real rows)", file=sys.stderr)
        return 1
    return 0


def add_obs_parser(subparsers) -> None:
    """Attach the ``obs`` subcommand tree to the main CLI's subparsers."""
    p = subparsers.add_parser(
        "obs", help="tail, snapshot, and diff metric dumps")
    p.set_defaults(fn=cmd_obs)
    sub = p.add_subparsers(dest="obs_cmd", required=True)

    ps = sub.add_parser("snapshot",
                        help="fetch/read a metrics dump and print it")
    ps.add_argument("source",
                    help="/metrics URL, JSON snapshot, or Prometheus "
                         "text file")
    ps.add_argument("--json", action="store_true",
                    help="print as JSON instead of a table")
    ps.add_argument("-o", "--out", default=None,
                    help="also save the parsed snapshot as JSON")
    ps.set_defaults(obs_func=_cmd_snapshot)

    pt = sub.add_parser("tail",
                        help="follow a metrics JSONL ledger or poll a "
                             "/metrics URL")
    pt.add_argument("source", help="JSONL path or /metrics URL")
    pt.add_argument("-f", "--follow", action="store_true",
                    help="keep following a JSONL file (tail -f)")
    pt.add_argument("--interval", type=float, default=2.0,
                    help="poll interval for URLs (seconds)")
    pt.add_argument("--traces", action="store_true",
                    help="tail the serve request-trace ring "
                         "(/debug/traces) instead of metric series")
    pt.set_defaults(obs_func=_cmd_tail)

    pd = sub.add_parser("diff", help="diff two metric dumps")
    pd.add_argument("before")
    pd.add_argument("after")
    pd.add_argument("--json", action="store_true")
    pd.set_defaults(obs_func=_cmd_diff)

    px = sub.add_parser(
        "timeline",
        help="export a flight-recorder journal as Chrome trace JSON")
    px.add_argument("journal", help="journal.jsonl path (rotated segments "
                                    "are merged automatically)")
    px.add_argument("-o", "--out", default=None,
                    help="output path (default timeline.json)")
    px.add_argument("--traces", default=None,
                    help="serve traces: /debug/traces URL or saved JSON")
    px.add_argument("--goodput", default=None,
                    help="goodput report JSON to render as a bucket lane")
    px.add_argument("--prof", default=None,
                    help="capture ring dir: render committed profiler "
                         "captures as spans on a 'prof' lane")
    px.set_defaults(obs_func=_cmd_timeline)

    pp = sub.add_parser(
        "prof", help="list, analyze, and trigger profiler captures")
    psub = pp.add_subparsers(dest="prof_cmd", required=True)

    pls = psub.add_parser("ls", help="list committed captures in a ring dir")
    pls.add_argument("dir", nargs="?", default=".",
                     help="capture ring directory (default .)")
    pls.add_argument("--json", action="store_true")
    pls.set_defaults(obs_func=_cmd_prof_ls)

    psh = psub.add_parser(
        "show", help="per-op time/bytes table for one capture (jax-free)")
    psh.add_argument("capture",
                     help="capture dir (or any dir/file holding a "
                          "*.trace.json.gz)")
    psh.add_argument("--top", type=int, default=20)
    psh.add_argument("--device", type=int, default=0,
                     help="device pid to aggregate (default first)")
    psh.set_defaults(obs_func=_cmd_prof_show)

    pdf = psub.add_parser(
        "diff", help="direction-aware per-op diff of two captures; "
                     "exit 1 on regression")
    pdf.add_argument("before")
    pdf.add_argument("after")
    pdf.add_argument("--top", type=int, default=20)
    pdf.add_argument("--threshold", type=float, default=0.10,
                     help="per-op fractional slowdown that counts as a "
                          "regression (0.10 = 10%%)")
    pdf.add_argument("--device", type=int, default=0)
    pdf.add_argument("--json", action="store_true")
    pdf.set_defaults(obs_func=_cmd_prof_diff)

    ptr = psub.add_parser(
        "trigger", help="ask a serving server for a deep capture "
                        "(POST /admin/prof/trigger)")
    ptr.add_argument("url", help="server base URL, e.g. http://host:8000")
    ptr.add_argument("--cid", default=None,
                     help="incident correlation id to tag the capture with")
    ptr.add_argument("--reason", default="manual")
    ptr.set_defaults(obs_func=_cmd_prof_trigger)

    pr = sub.add_parser(
        "regress",
        help="gate MEASUREMENTS.jsonl rows against adopted baselines")
    pr.add_argument("--measurements", default="MEASUREMENTS.jsonl")
    pr.add_argument("--baselines", default="BASELINES.json")
    pr.add_argument("--threshold", type=float, default=0.20,
                    help="max tolerated fractional regression (0.20 = 20%%)")
    pr.add_argument("--adopt", action="store_true",
                    help="adopt the rows' metrics as new baselines "
                         "instead of gating")
    pr.add_argument("--note", default=None,
                    help="provenance note stored with adopted baselines")
    pr.add_argument("--fail-on-fallback", action="store_true",
                    help="exit nonzero when fallback rows are present")
    pr.add_argument("--json", action="store_true")
    pr.set_defaults(obs_func=_cmd_regress)


def cmd_obs(args) -> int:
    return args.obs_func(args)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="jimm-tpu-obs")
    sub = parser.add_subparsers(dest="command", required=True)
    add_obs_parser(sub)
    args = parser.parse_args(argv)
    return cmd_obs(args)


if __name__ == "__main__":
    raise SystemExit(main())
