"""Driver entry points must stay green: single-chip compile check and the
multi-chip dry run the driver executes with virtual devices."""

import sys

import jax
import pytest


def _load_graft():
    sys.path.insert(0, "/root/repo")
    import __graft_entry__
    return __graft_entry__


@pytest.mark.slow
def test_dryrun_multichip_8(eight_devices):
    g = _load_graft()
    g.dryrun_multichip(8)


def test_entry_is_jittable():
    g = _load_graft()
    fn, args = g.entry()
    out = jax.eval_shape(fn, *args)  # abstract trace = compile-check, fast
    assert out.shape == (4, 4)


def test_debug_utils():
    import jax.numpy as jnp
    from jimm_tpu.utils.debug import assert_finite, checked

    assert_finite({"a": jnp.ones(3)})
    with pytest.raises(FloatingPointError):
        assert_finite({"a": jnp.array([1.0, jnp.nan])})

    def div(x):
        return 1.0 / x

    assert float(checked(div)(jnp.asarray(2.0))) == 0.5
    with pytest.raises(Exception):
        checked(div)(jnp.asarray(0.0))
