"""Transformer encoder stack, TPU-first.

Differences from the reference (`src/jimm/common/transformer.py`):

- Layers are *stacked* (one set of parameters with a leading ``layers`` dim,
  built via ``nnx.vmap``) and the forward is a ``jax.lax.scan`` via
  ``nnx.scan`` — constant compile time in depth and a clean FSDP unit,
  instead of the reference's python-unrolled ``nnx.Sequential``
  (ref `common/transformer.py:171-188`).
- Attention is a swappable functional kernel (`jimm_tpu/ops/attention.py`)
  over explicit ``(B, S, N, D)`` tensors with plain ``(H, H)`` projection
  kernels, not ``nnx.MultiHeadAttention``'s ``(H, N, D)`` layout — simpler
  checkpoint mapping and a direct hand-off to Pallas flash attention.
- Sharding comes from logical axis names resolved by a rules table
  (`jimm_tpu/parallel/sharding.py`), not per-callsite PartitionSpecs.

Parity-preserved semantics (SURVEY Appendix A):
- pre-LN residual order ``x + attn(ln1(x))``; ``x + mlp(ln2(x))``
  (ref `common/transformer.py:130-131`).
- causal masking equivalent to the reference's sliced float ``tril`` mask
  (ref `common/transformer.py:125-129`, `models/clip.py:62`).
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from flax import nnx

import jimm_tpu.utils.compat  # noqa: F401  (nnx backfills: to_flat_state, set_value)
from jax.ad_checkpoint import checkpoint_name

from jimm_tpu.configs import TransformerConfig
from jimm_tpu.ops.activations import get_activation
from jimm_tpu.ops.attention import dot_product_attention
from jimm_tpu.parallel.sharding import logical, logical_constraint

Dtype = jnp.dtype | None


def _linear(din: int, dout: int, names: tuple, rngs: nnx.Rngs, *,
            use_bias: bool = True, dtype: Dtype, param_dtype) -> nnx.Linear:
    return nnx.Linear(
        din, dout, use_bias=use_bias, dtype=dtype, param_dtype=param_dtype,
        kernel_init=logical(nnx.initializers.xavier_uniform(), *names),
        bias_init=logical(nnx.initializers.zeros_init(), names[-1]),
        rngs=rngs)


def _layernorm(dim: int, eps: float, rngs: nnx.Rngs, *, dtype: Dtype,
               param_dtype, impl: str = "xla") -> nnx.Module:
    if impl == "fused":
        from jimm_tpu.nn.norm import FusedLayerNorm
        return FusedLayerNorm(dim, epsilon=eps, dtype=dtype,
                              param_dtype=param_dtype, rngs=rngs)
    return nnx.LayerNorm(
        dim, epsilon=eps, dtype=dtype, param_dtype=param_dtype,
        scale_init=logical(nnx.initializers.ones_init(), "embed"),
        bias_init=logical(nnx.initializers.zeros_init(), "embed"),
        rngs=rngs)


class Attention(nnx.Module):
    """Multi-head attention with (H, H) q/k/v/out kernels; supports
    self-attention and cross-attention (MAP pooling probe).

    ``fused_qkv`` computes the three projections as one ``(H, 3H)`` matmul
    by concatenating the kernels at call time — parameters (and therefore
    checkpoints) stay separate, the concat is tiny next to the matmul, and
    gradients flow back through the slice."""

    def __init__(self, width: int, num_heads: int, rngs: nnx.Rngs, *,
                 is_causal: bool = False, impl: str = "auto",
                 fused_qkv: bool = False,
                 dtype: Dtype = None, param_dtype=jnp.float32):
        if width % num_heads:
            raise ValueError(f"width {width} not divisible by heads {num_heads}")
        self.num_heads = num_heads
        self.head_dim = width // num_heads
        self.is_causal = is_causal
        self.impl = impl
        self.fused_qkv = fused_qkv
        self.dtype = dtype
        lin = partial(_linear, dtype=dtype, param_dtype=param_dtype)
        self.q = lin(width, width, ("embed", "heads"), rngs)
        self.k = lin(width, width, ("embed", "heads"), rngs)
        self.v = lin(width, width, ("embed", "heads"), rngs)
        self.out = lin(width, width, ("heads", "embed"), rngs)

    def _project_qkv(self, x: jax.Array) -> tuple[jax.Array, ...]:
        w = jnp.concatenate([self.q.kernel[...], self.k.kernel[...],
                             self.v.kernel[...]], axis=1)
        b = jnp.concatenate([self.q.bias[...], self.k.bias[...],
                             self.v.bias[...]])
        dtype = self.dtype or x.dtype
        qkv = x.astype(dtype) @ w.astype(dtype) + b.astype(dtype)
        return tuple(jnp.split(qkv, 3, axis=-1))

    def __call__(self, x: jax.Array, kv: jax.Array | None = None,
                 mask: jax.Array | None = None) -> jax.Array:
        B, Sq, _ = x.shape
        if kv is None and self.fused_qkv:
            q, k, v = self._project_qkv(x)
            Sk = Sq
        else:
            kv = x if kv is None else kv
            Sk = kv.shape[1]
            q, k, v = self.q(x), self.k(kv), self.v(kv)
        q = q.reshape(B, Sq, self.num_heads, self.head_dim)
        k = k.reshape(B, Sk, self.num_heads, self.head_dim)
        v = v.reshape(B, Sk, self.num_heads, self.head_dim)
        o = dot_product_attention(q, k, v, is_causal=self.is_causal,
                                  mask=mask, impl=self.impl)
        return self.out(o.reshape(B, Sq, self.num_heads * self.head_dim))


class Mlp(nnx.Module):
    def __init__(self, width: int, mlp_dim: int, act: str, rngs: nnx.Rngs, *,
                 dtype: Dtype = None, param_dtype=jnp.float32):
        lin = partial(_linear, dtype=dtype, param_dtype=param_dtype)
        self.fc1 = lin(width, mlp_dim, ("embed", "mlp"), rngs)
        self.fc2 = lin(mlp_dim, width, ("mlp", "embed"), rngs)
        self.act: Callable = get_activation(act)

    def __call__(self, x: jax.Array) -> jax.Array:
        # name is free (identity) unless a "+act" remat policy saves it
        return self.fc2(checkpoint_name(self.act(self.fc1(x)), "act_out"))


#: dropout-stream draws per Block.__call__ (attn residual + mlp residual);
#: the pipelined path strides its pinned RngCounts by this
_BLOCK_DROPOUT_DRAWS = 2


class Block(nnx.Module):
    """Pre-LN residual block (ref `common/transformer.py:116-132`)."""

    def __init__(self, cfg: TransformerConfig, rngs: nnx.Rngs, *,
                 dtype: Dtype = None, param_dtype=jnp.float32):
        self.ln1 = _layernorm(cfg.width, cfg.ln_eps, rngs, dtype=dtype,
                              param_dtype=param_dtype, impl=cfg.ln_impl)
        self.attn = Attention(cfg.width, cfg.num_heads, rngs,
                              is_causal=cfg.causal, impl=cfg.attn_impl,
                              fused_qkv=cfg.fused_qkv,
                              dtype=dtype, param_dtype=param_dtype)
        self.ln2 = _layernorm(cfg.width, cfg.ln_eps, rngs, dtype=dtype,
                              param_dtype=param_dtype, impl=cfg.ln_impl)
        self.mlp = Mlp(cfg.width, cfg.mlp_dim, cfg.act, rngs, dtype=dtype,
                       param_dtype=param_dtype)
        self.dropout = nnx.Dropout(cfg.dropout, rngs=rngs)

    def __call__(self, x: jax.Array,
                 mask: jax.Array | None = None) -> jax.Array:
        # ln outputs carry a checkpoint name so "+ln" remat policies can keep
        # them (skipping the LN recompute in the backward); plain identity
        # under every other policy
        x = x + self.dropout(self.attn(checkpoint_name(self.ln1(x), "ln_out"),
                                       mask=mask))
        x = x + self.dropout(self.mlp(checkpoint_name(self.ln2(x), "ln_out")))
        return logical_constraint(x, "batch", "seq", None)


class Transformer(nnx.Module):
    """Depth-stacked encoder, scanned over the ``layers`` axis."""

    def __init__(self, cfg: TransformerConfig, rngs: nnx.Rngs, *,
                 dtype: Dtype = None, param_dtype=jnp.float32):
        self.cfg = cfg

        @nnx.split_rngs(splits=cfg.depth)
        @nnx.vmap(in_axes=0, out_axes=0,
                  transform_metadata={nnx.PARTITION_NAME: "layers"})
        def create_block(rngs: nnx.Rngs) -> Block:
            return Block(cfg, rngs, dtype=dtype, param_dtype=param_dtype)

        # the clone keeps the blocks' captured RngState from aliasing the
        # caller's rngs (flax 0.10 vmap broadcasts it by reference), so the
        # stacking fixup below cannot corrupt sibling modules' streams
        self.blocks = create_block(nnx.clone(rngs))
        from jimm_tpu.utils.compat import ensure_stacked_rng_state
        ensure_stacked_rng_state(self.blocks, cfg.depth)
        if cfg.pipeline and cfg.pp_virtual > 1 and cfg.pp_stages:
            # circular placement is baked into STORAGE order once at
            # construction (stored row j = canonical layer order[j]), so the
            # pipelined forward needs no per-step cross-stage all-to-all;
            # loaders/exporters reorder at their stacking edge to match
            from jimm_tpu.parallel.pipeline import circular_layer_order
            order = circular_layer_order(cfg.depth, cfg.pp_stages,
                                         cfg.pp_virtual)
            state = nnx.state(self.blocks)
            nnx.update(self.blocks,
                       jax.tree.map(lambda p: p[order], state))
        if cfg.pipeline and cfg.dropout > 0.0:
            # persistent schedule-tick counter: offsets the per-tick dropout
            # rng folding so masks differ across training steps (pipelined
            # path only — rng mutations inside shard_map don't propagate)
            self.pp_tick = nnx.Variable(jnp.zeros((), jnp.uint32))

    def _remat_policy(self):
        # "dots" keeps weight-matmul outputs (NOT the batched qk/pv dots —
        # saving S^2 attention probabilities is pure HBM waste) plus the
        # flash kernel's o/lse residuals, so the backward recomputes only
        # elementwise ops; "none" is classic full rematerialization.
        # "+ln" / "+act" additionally keep the LayerNorm / MLP-activation
        # outputs — a bit more HBM for one less elementwise recompute pass
        # each (the step is bandwidth-bound; see docs/performance.md).
        from jimm_tpu.configs import remat_policy_parts
        policy = self.cfg.remat_policy
        if policy == "none":
            return None
        parts = remat_policy_parts(policy)
        names = ["flash_o", "flash_lse"]
        if "ln" in parts:
            names.append("ln_out")
        if "act" in parts:
            names.append("act_out")
        if "attn" in parts:
            # only the "saveable" attention impl emits this name — with any
            # other impl the save-list entry matches nothing and the run
            # silently measures plain "dots"
            if self.cfg.attn_impl != "saveable":
                raise ValueError(
                    f"remat_policy {policy!r} saves attention probabilities, "
                    f"but attn_impl={self.cfg.attn_impl!r} never emits them; "
                    "use attn_impl='saveable'")
            names.append("attn_probs")
        return jax.checkpoint_policies.save_from_both_policies(
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            jax.checkpoint_policies.save_only_these_names(*names))

    def _apply_stack(self, blocks: Block, x: jax.Array,
                     mask: jax.Array | None = None) -> jax.Array:
        """Scan ``x`` through a stacked block module (all layers or one
        pipeline stage's local slice). ``mask`` (bool, broadcastable to
        (B, N, Sq, Sk)) rides into every layer as a closure capture — it is
        layer-invariant, so it is not a scan carry."""
        def body(block: Block, x: jax.Array) -> jax.Array:
            return block(x, mask=mask)

        if self.cfg.remat:
            body = nnx.remat(body, policy=self._remat_policy())
        scan = nnx.scan(body, in_axes=(0, nnx.Carry), out_axes=nnx.Carry,
                        unroll=self.cfg.scan_unroll,
                        transform_metadata={nnx.PARTITION_NAME: "layers"})
        return scan(blocks, x)

    def __call__(self, x: jax.Array,
                 mask: jax.Array | None = None) -> jax.Array:
        if not self.cfg.pipeline:
            return self._apply_stack(self.blocks, x, mask)
        if mask is not None:
            raise ValueError(
                "attention masks are not supported on the pipelined path "
                "yet (the stage loop has no mask plumbing); use "
                "pipeline=False — the non-pipelined path runs key-padding "
                "masks on the flash kernel (impl='flash_masked' / 'auto')")

        from jimm_tpu.parallel.pipeline import (circular_layer_order,
                                                pipeline_forward)
        from jimm_tpu.parallel.sharding import current_rules

        from jimm_tpu.configs import validate_pipeline

        from jimm_tpu.utils.compat import get_abstract_mesh
        mesh = get_abstract_mesh()
        n_stage = (dict(mesh.shape).get("stage", 0)
                   if mesh is not None else 0)
        # shared checks (stage axis present, depth divisibility, pp_stages
        # match) — identical function and messages as the parse-time path
        validate_pipeline(self.cfg, n_stages=n_stage)
        n_virtual = self.cfg.pp_virtual
        rules = current_rules()
        batch_axis = rules.batch if rules is not None else None
        if isinstance(batch_axis, str) and batch_axis not in mesh.shape:
            batch_axis = None
        graphdef, state = nnx.split(self.blocks)
        if n_virtual > 1 and self.cfg.pp_stages != n_stage:
            # a truthy-but-mismatched pp_stages was already rejected by
            # validate_pipeline above; pp_stages unknown at construction:
            # fall back to permuting per call — correct, but a cross-stage
            # all-to-all each step; set cfg.pp_stages to bake the placement
            # into storage instead
            order = circular_layer_order(self.cfg.depth, n_stage, n_virtual)
            state = jax.tree.map(lambda p: p[order], state)

        dropout_active = (self.cfg.dropout > 0.0
                          and not self.blocks.dropout.deterministic)
        tick_offset = 0
        if dropout_active:
            # rng mutations inside shard_map/scan are discarded, so dropout
            # draws fold the schedule tick into each layer's OWN key via the
            # RngCount slot; the persistent step counter advances the offset
            # so masks differ across training steps too.
            from jimm_tpu.parallel.pipeline import num_ticks
            t_total = num_ticks(self.cfg.pp_microbatches, n_stage, n_virtual)
            # .value, not [...]: flax 0.10 __setitem__ writes through to the
            # (immutable) jax array instead of replacing the variable's value
            tick_offset = self.pp_tick.value
            self.pp_tick.value = tick_offset + jnp.uint32(t_total)

        def stage_apply(state_chunk, xm, tick):
            # plain lax.scan + per-layer merge (nnx.scan can't consume
            # modules whose arrays were introduced at the enclosing
            # shard_map trace level)
            def body(h, layer_state):
                if dropout_active:
                    # a Block consumes _BLOCK_DROPOUT_DRAWS counts per call,
                    # so stride the pinned count — otherwise tick t's last
                    # draw equals tick t+1's first and masks repeat shifted
                    layer_state = _set_rng_counts(
                        layer_state, tick * _BLOCK_DROPOUT_DRAWS)
                return nnx.merge(graphdef, layer_state)(h), None

            if self.cfg.remat:
                body = jax.checkpoint(body, policy=self._remat_policy())
            out, _ = jax.lax.scan(body, xm, state_chunk,
                                  unroll=self.cfg.scan_unroll)
            return out

        return pipeline_forward(stage_apply, state, x,
                                n_microbatches=self.cfg.pp_microbatches,
                                n_virtual=n_virtual,
                                batch_axis=batch_axis,
                                tick_offset=tick_offset)


def _is_rng_count(leaf) -> bool:
    # flat-state leaves are Variables on flax >= 0.12 but VariableStates
    # (carrying the Variable class in .type) on 0.10
    if isinstance(leaf, nnx.RngCount):
        return True
    t = getattr(leaf, "type", None)
    return isinstance(t, type) and issubclass(t, nnx.RngCount)


def _set_rng_counts(state, value) -> nnx.State:
    """Functionally pin every RngCount in ``state`` to ``value`` — each
    (layer key, tick) pair then draws a unique, deterministic dropout mask."""
    flat = nnx.to_flat_state(state)
    new = [(p, l.replace(jnp.asarray(value, jnp.uint32))
            if _is_rng_count(l) else l) for p, l in flat]
    return nnx.from_flat_state(new)
