"""Flash-attention kernel vs fp32 einsum oracle (SURVEY §4 implication (d)),
in Pallas interpret mode on CPU (compiled path exercised by bench on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jimm_tpu.ops.attention import reference_attention
from jimm_tpu.ops.flash_attention import flash_attention


def qkv(rng, b=2, s=256, n=2, d=64, dtype=np.float32):
    return tuple(jnp.asarray(rng.randn(b, s, n, d).astype(dtype) * 0.5)
                 for _ in range(3))


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_reference(rng, causal):
    q, k, v = qkv(rng)
    out = flash_attention(q, k, v, is_causal=causal)
    ref = reference_attention(q, k, v, is_causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5)


def test_forward_unaligned_seq(rng):
    """Sequence lengths that need padding (ViT: 197, 257, 577 tokens)."""
    q, k, v = qkv(rng, s=197)
    out = flash_attention(q, k, v)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_reference(rng, causal):
    q, k, v = qkv(rng, s=128, n=1)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, is_causal=causal) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, is_causal=causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gr):
        np.testing.assert_allclose(a, b, atol=5e-4, err_msg=f"d{name}")


def test_gradients_unaligned_seq(rng):
    q, k, v = qkv(rng, s=197, n=1)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gr):
        np.testing.assert_allclose(a, b, atol=5e-4, err_msg=f"d{name}")


@pytest.mark.parametrize("s", [1, 5, 257])
def test_odd_seq_fwd_bwd(rng, s):
    """Sequence lengths far off the tile grid (single token, tiny crops,
    ViT-odd 257): fwd and grads through the padded+masked kernels."""
    q, k, v = qkv(rng, b=1, s=s, n=1)
    np.testing.assert_allclose(flash_attention(q, k, v),
                               reference_attention(q, k, v), atol=2e-5)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gr):
        np.testing.assert_allclose(a, b, atol=5e-4, err_msg=f"d{name}")


@pytest.mark.parametrize("s", [5, 257])
@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_odd_seq_lowers_for_tpu(s, dtype):
    """Odd sequence lengths must pass the Mosaic divisibility checks for
    fwd AND bwd (AOT cross-lowering runs them on CPU) — no reliance on the
    block==array escape hatch."""
    dt = jnp.dtype(dtype)
    spec = jax.ShapeDtypeStruct((1, s, 2, 64), dt)

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v).astype(jnp.float32))

    fn = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    fn.trace(spec, spec, spec).lower(lowering_platforms=("tpu",))


def test_bf16_inputs(rng):
    q, k, v = qkv(rng, dtype=np.float32)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    out = flash_attention(qb, kb, vb)
    assert out.dtype == jnp.bfloat16
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(out.astype(np.float32), ref, atol=2e-2)


@pytest.mark.parametrize("causal", [False, True])
def test_full_head_block_grid(rng, causal):
    """bn divisible by 8 -> _pick_hb selects 8 heads per grid cell; values
    AND gradients must match the oracle through the blocked indexing."""
    from jimm_tpu.ops.flash_attention import _pick_hb
    q, k, v = qkv(rng, b=4, s=128, n=4)
    assert _pick_hb(16, 128, 128, 64) == 8

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, is_causal=causal) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, is_causal=causal) ** 2)

    np.testing.assert_allclose(flash_attention(q, k, v, is_causal=causal),
                               reference_attention(q, k, v, is_causal=causal),
                               atol=2e-5)
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(a, b, atol=5e-4, err_msg=f"d{name}")


@pytest.mark.slow
def test_long_sequence_streams(rng):
    """seq 2048 with 512-blocks: 4x4 kv grid per cell — the K/V tiles
    stream block by block (the long-context configuration, scaled down to
    interpreter speed)."""
    q, k, v = qkv(rng, b=1, s=2048, n=1)
    out = flash_attention(q, k, v)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-5)
