"""Goodput accounting: classify training wall time into named buckets.

"Goodput" here is the fraction of wall-clock time the accelerator spends on
actual training steps, as opposed to compiling, waiting for data, writing
checkpoints, or syncing scalars back to the host. The accounter is a small
stopwatch ledger: wrap each region of the training loop in
``acct.measure("bucket")`` and ask for a :meth:`report` at the end — the
residual (startup code, python glue) is attributed to ``other`` so the
buckets always sum to exactly the wall time.

Buckets (the fixed vocabulary the docs and CI smoke assert on):

- ``compile``    — first-step tracing/compilation (and explicit AOT compiles)
- ``data_wait``  — blocked on the input pipeline (``next(iterator)``)
- ``step``       — dispatched training step incl. the device sync that
                   realizes the loss on host
- ``checkpoint`` — orbax save/restore
- ``host_sync``  — metric logging, console/JSONL writes
- ``preemption_save`` — SIGTERM grace-window save (initiate + final flush)
- ``lost_work``  — wall time a preemption/restart discarded (grace-window
                   steps whose results are thrown away, work since the
                   last committed checkpoint on a crash)
- ``replan``     — live topology replans: mesh re-planning between
                   supervised attempts, serve-engine replica swaps
- ``heal``       — self-heal wall time: probe + rebuild around a fenced
                   replica (the replan it triggers books separately)
- ``other``      — residual wall time not covered by a measure() region

MFU-adjusted goodput = goodput × MFU: the fraction of *peak hardware* FLOPs
the whole loop achieves, not just the step function — the number that tells
you whether to optimize the kernel or the pipeline around it.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager

from jimm_tpu.obs.registry import MetricRegistry, enabled, get_registry

__all__ = ["BUCKETS", "GoodputAccounter"]

BUCKETS = ("compile", "data_wait", "step", "checkpoint", "host_sync",
           "preemption_save", "lost_work", "replan", "heal")


class GoodputAccounter:
    """Wall-time ledger over the fixed bucket vocabulary.

    Also mirrors per-bucket cumulative seconds into the ``jimm_train``
    registry as ``goodput_{bucket}_seconds_total`` counters plus a
    ``goodput_ratio`` gauge, so the unified snapshot carries the breakdown
    without a separate report call.
    """

    def __init__(self, registry: MetricRegistry | None = None):
        self._lock = threading.Lock()
        self._seconds = {name: 0.0 for name in BUCKETS}
        self._t_start = time.monotonic()
        self.registry = registry if registry is not None \
            else get_registry("jimm_train")
        self._counters = {
            name: self.registry.counter(f"goodput_{name}_seconds_total")
            for name in BUCKETS}
        self.registry.gauge("goodput_ratio", self.goodput)
        self.registry.gauge("goodput_wall_s", self.wall_s)

    @contextmanager
    def measure(self, bucket: str):
        """Attribute the wrapped region's wall time to ``bucket``."""
        if bucket not in self._seconds:
            raise KeyError(f"unknown goodput bucket {bucket!r}; "
                           f"expected one of {BUCKETS}")
        if not enabled():
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self._seconds[bucket] += dt
            self._counters[bucket].inc(dt)

    def add(self, bucket: str, seconds: float) -> None:
        """Attribute already-measured time (e.g. a StepTimer reading)."""
        if bucket not in self._seconds:
            raise KeyError(f"unknown goodput bucket {bucket!r}")
        with self._lock:
            self._seconds[bucket] += seconds
        self._counters[bucket].inc(seconds)

    # -- read -------------------------------------------------------------

    def wall_s(self) -> float:
        return time.monotonic() - self._t_start

    def seconds(self, wall: float | None = None) -> dict[str, float]:
        with self._lock:
            out = dict(self._seconds)
        # Residual: wall time no measure() region claimed. Clamped at 0 so
        # overlapping regions (a bug, but survivable) can't go negative.
        if wall is None:
            wall = self.wall_s()
        out["other"] = max(0.0, wall - sum(out.values()))
        return out

    def goodput(self) -> float:
        """step-time / wall-time, in [0, 1]."""
        wall = self.wall_s()
        if wall <= 0:
            return 0.0
        with self._lock:
            step = self._seconds["step"]
        return min(1.0, step / wall)

    def report(self, mfu: float | None = None) -> dict[str, float]:
        """Flat report: per-bucket seconds + fractions (summing to 1.0 by
        construction), goodput, and MFU-adjusted goodput when an MFU is
        supplied."""
        wall = self.wall_s()
        secs = self.seconds(wall)
        out: dict[str, float] = {"wall_s": round(wall, 4)}
        for name, s in secs.items():
            out[f"{name}_s"] = round(s, 4)
            out[f"{name}_frac"] = round(s / wall, 4) if wall > 0 else 0.0
        # derive goodput from the same wall sample as the fracs instead of
        # calling goodput() (which resamples the clock): every field in one
        # report must describe the same instant, or goodput and
        # mfu_adjusted_goodput drift apart whenever the scheduler preempts
        # between reads
        g = min(1.0, secs["step"] / wall) if wall > 0 else 0.0
        out["goodput"] = round(g, 4)
        if mfu is not None:
            out["mfu"] = round(mfu, 4)
            out["mfu_adjusted_goodput"] = round(g * mfu, 4)
        return out
