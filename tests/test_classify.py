"""`classify` CLI: zero-shot classification, offline via --tokens-file."""

import json

import numpy as np
import pytest
from PIL import Image

from jimm_tpu.cli import main

from hf_util import save_tiny_clip, save_tiny_siglip


@pytest.fixture()
def image_file(tmp_path, rng):
    p = tmp_path / "img.png"
    Image.fromarray(rng.randint(0, 255, size=(24, 24, 3))
                    .astype(np.uint8)).save(p)
    return str(p)


def test_classify_clip(tmp_path, image_file, capsys):
    ckpt = save_tiny_clip(tmp_path / "ckpt")
    tokens = tmp_path / "tokens.json"
    # EOT (max vocab id in the tiny config) present per row: CLIP pools there
    tokens.write_text(json.dumps({"cat": [1, 5, 63], "dog": [2, 6, 63]}))
    rc = main(["classify", image_file, "--ckpt", str(ckpt), "--model", "clip",
               "--tokens-file", str(tokens), "--platform", "cpu"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 2
    scores = [float(line.split()[0]) for line in out]
    assert abs(sum(scores) - 1.0) < 1e-3  # softmax over labels
    assert {line.split()[1] for line in out} == {"cat", "dog"}


def test_classify_reuses_cached_class_embeddings(tmp_path, image_file,
                                                 capsys):
    """Repeat invocations in one process hit the serve embedding cache:
    the text tower runs once per (checkpoint, label set), not per call."""
    from jimm_tpu.serve.cache import class_embedding_cache

    ckpt = save_tiny_clip(tmp_path / "ckpt")
    tokens = tmp_path / "tokens.json"
    tokens.write_text(json.dumps({"owl": [3, 7, 63], "jay": [4, 8, 63]}))
    cache = class_embedding_cache()
    hits0, misses0 = cache.hits, cache.misses
    argv = ["classify", image_file, "--ckpt", str(ckpt), "--model", "clip",
            "--tokens-file", str(tokens), "--platform", "cpu"]
    assert main(argv) == 0
    first = capsys.readouterr().out
    assert cache.misses - misses0 == 1  # cold: built and inserted
    assert main(argv) == 0
    assert capsys.readouterr().out == first  # cached weights, same scores
    assert cache.hits - hits0 >= 1  # warm: text tower skipped


def test_classify_siglip(tmp_path, image_file, capsys):
    ckpt = save_tiny_siglip(tmp_path / "ckpt")
    tokens = tmp_path / "tokens.json"
    tokens.write_text(json.dumps({"ant": [1, 2], "bee": [3, 4],
                                  "fly": [5, 6]}))
    rc = main(["classify", image_file, "--ckpt", str(ckpt),
               "--model", "siglip", "--tokens-file", str(tokens),
               "--platform", "cpu"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 3
    for line in out:  # sigmoid scores, each in (0, 1)
        assert 0.0 < float(line.split()[0]) < 1.0


def test_classify_rejects_overlong_tokens(tmp_path, image_file):
    ckpt = save_tiny_clip(tmp_path / "ckpt")
    tokens = tmp_path / "tokens.json"
    tokens.write_text(json.dumps({"cat": list(range(1, 40))}))  # ctx is 8
    with pytest.raises(SystemExit, match="context_length"):
        main(["classify", image_file, "--ckpt", str(ckpt), "--model", "clip",
              "--tokens-file", str(tokens), "--platform", "cpu"])


def test_classify_builtin_clip_tokenizer(tmp_path, image_file, capsys):
    """A CLIP checkpoint dir with vocab.json/merges.txt needs no
    --tokenizer and no --tokens-file: the built-in BPE handles --labels."""
    import json as _json

    from jimm_tpu.data.clip_tokenizer import bytes_to_unicode

    alphabet = list(bytes_to_unicode().values())
    merges = [("c", "a"), ("ca", "t</w>"), ("d", "o"), ("do", "g</w>")]
    vocab_tokens = (alphabet + [c + "</w>" for c in alphabet]
                    + ["".join(m) for m in merges]
                    + ["<|startoftext|>", "<|endoftext|>"])
    # model vocab must cover the BPE table (incl. EOT as the max id)
    ckpt = save_tiny_clip(tmp_path / "ckpt", vocab_size=len(vocab_tokens))
    (tmp_path / "ckpt" / "vocab.json").write_text(_json.dumps(
        {tok: i for i, tok in enumerate(vocab_tokens)}))
    (tmp_path / "ckpt" / "merges.txt").write_text(
        "#version: 0.2\n" + "\n".join(f"{a} {b}" for a, b in merges) + "\n")
    rc = main(["classify", image_file, "--ckpt", str(ckpt), "--model", "clip",
               "--labels", "cat,dog", "--platform", "cpu"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert {line.split()[1] for line in out} == {"cat", "dog"}


def test_classify_needs_token_source(tmp_path, image_file):
    ckpt = save_tiny_clip(tmp_path / "ckpt")
    with pytest.raises(SystemExit, match="tokens-file"):
        main(["classify", image_file, "--ckpt", str(ckpt),
              "--platform", "cpu"])


def test_classify_siglip2_naflex(tmp_path, rng, capsys):
    """--naflex: aspect-preserving variable-resolution zero-shot on a
    SigLIP2 checkpoint — a non-square image maps to a non-square grid."""
    from hf_util import save_tiny_siglip2
    p = tmp_path / "wide.png"
    Image.fromarray(rng.randint(0, 255, size=(16, 48, 3))
                    .astype(np.uint8)).save(p)
    ckpt = save_tiny_siglip2(tmp_path / "ckpt")
    tokens = tmp_path / "tokens.json"
    tokens.write_text(json.dumps({"ant": [1, 2], "bee": [3, 4]}))
    rc = main(["classify", str(p), "--ckpt", str(ckpt), "--model", "siglip",
               "--naflex", "--tokens-file", str(tokens),
               "--platform", "cpu"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 2
    for line in out:
        assert 0.0 < float(line.split()[0]) < 1.0


def test_classify_naflex_requires_siglip(tmp_path, image_file):
    from hf_util import save_tiny_clip
    ckpt = save_tiny_clip(tmp_path / "ckpt")
    tokens = tmp_path / "tokens.json"
    tokens.write_text(json.dumps({"cat": [1, 63]}))
    with pytest.raises(SystemExit, match="naflex"):
        main(["classify", image_file, "--ckpt", str(ckpt), "--model", "clip",
              "--naflex", "--tokens-file", str(tokens), "--platform", "cpu"])


def test_zero_shot_ensemble_weights_math(tmp_path, rng):
    """classifier_weights == normalize(mean(normalize(per-prompt)))."""
    import jax.numpy as jnp

    from hf_util import save_tiny_siglip
    from jimm_tpu import SigLIP
    from jimm_tpu.utils.zero_shot import classifier_weights
    model = SigLIP.from_pretrained(save_tiny_siglip(tmp_path / "ckpt"))
    L = model.config.text.context_length
    rows = jnp.asarray(rng.randint(1, 90, size=(6, L)), jnp.int32)  # 2cls x3
    w = np.asarray(classifier_weights(model, rows, 2))
    emb = np.asarray(model.encode_text(rows))
    emb = emb / np.linalg.norm(emb, axis=-1, keepdims=True)
    ref = emb.reshape(2, 3, -1).mean(axis=1)
    ref = ref / np.linalg.norm(ref, axis=-1, keepdims=True)
    np.testing.assert_allclose(w, ref, atol=1e-6)
    np.testing.assert_allclose(np.linalg.norm(w, axis=-1), 1.0, atol=1e-6)


def _clip_ckpt_with_vocab(tmp_path):
    import json as _json

    from jimm_tpu.data.clip_tokenizer import bytes_to_unicode

    alphabet = list(bytes_to_unicode().values())
    merges = [("c", "a"), ("ca", "t</w>"), ("d", "o"), ("do", "g</w>")]
    vocab_tokens = (alphabet + [c + "</w>" for c in alphabet]
                    + ["".join(m) for m in merges]
                    + ["<|startoftext|>", "<|endoftext|>"])
    ckpt = save_tiny_clip(tmp_path / "ckpt", vocab_size=len(vocab_tokens))
    (tmp_path / "ckpt" / "vocab.json").write_text(_json.dumps(
        {tok: i for i, tok in enumerate(vocab_tokens)}))
    (tmp_path / "ckpt" / "merges.txt").write_text(
        "#version: 0.2\n" + "\n".join(f"{a} {b}" for a, b in merges) + "\n")
    return ckpt


def test_classify_ensemble_clip(tmp_path, image_file, capsys):
    """--ensemble with a custom "|" template set through the built-in CLIP
    BPE tokenizer; scores still softmax-normalize over the labels."""
    ckpt = _clip_ckpt_with_vocab(tmp_path)
    rc = main(["classify", image_file, "--ckpt", str(ckpt), "--model",
               "clip", "--labels", "cat,dog", "--ensemble",
               "--template", "a photo of a {}|a drawing of a {}",
               "--platform", "cpu"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 2
    assert abs(sum(float(l.split()[0]) for l in out) - 1.0) < 1e-3


def test_classify_ensemble_rejects_tokens_file(tmp_path, image_file):
    ckpt = save_tiny_clip(tmp_path / "ckpt")
    tokens = tmp_path / "tokens.json"
    tokens.write_text(json.dumps({"cat": [1, 63]}))
    with pytest.raises(SystemExit, match="ensemble"):
        main(["classify", image_file, "--ckpt", str(ckpt), "--model",
              "clip", "--ensemble", "--tokens-file", str(tokens),
              "--platform", "cpu"])
