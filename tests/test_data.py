"""Input pipeline tests: prefetch ordering, termination, error propagation,
device placement."""

import numpy as np
import pytest

from jimm_tpu.data import PrefetchIterator, blob_classification, contrastive_pairs
from jimm_tpu.parallel import DATA_PARALLEL, make_mesh


def test_prefetch_preserves_order_and_stops():
    src = iter([np.full((2, 2), i, np.float32) for i in range(5)])
    it = PrefetchIterator(src)
    got = [int(b[0, 0]) for b in it]
    assert got == [0, 1, 2, 3, 4]
    with pytest.raises(StopIteration):
        next(it)


def test_prefetch_propagates_producer_error():
    def bad():
        yield np.zeros((1,), np.float32)
        raise RuntimeError("producer exploded")

    it = PrefetchIterator(bad())
    next(it)
    with pytest.raises(RuntimeError, match="producer exploded"):
        next(it)


def test_prefetch_places_on_mesh(eight_devices):
    mesh = make_mesh({"data": 8})
    src = (x for x in [(np.zeros((16, 4, 4, 3), np.float32),
                        np.zeros((16,), np.int32))])
    it = PrefetchIterator(src, mesh=mesh, rules=DATA_PARALLEL)
    images, labels = next(it)
    assert images.sharding.spec == DATA_PARALLEL.spec("batch", None, None, None)
    it.close()


def test_blob_dataset_shapes_and_labels():
    gen = blob_classification(8, image_size=16)
    images, labels = next(gen)
    assert images.shape == (8, 16, 16, 3) and labels.shape == (8,)
    assert images.dtype == np.float32 and labels.dtype == np.int32
    assert set(np.unique(labels)).issubset({0, 1, 2, 3})


def test_contrastive_pairs_encode_class_in_text():
    gen = contrastive_pairs(8, image_size=16, vocab_size=32, seq_len=4)
    _, text = next(gen)
    assert text.shape == (8, 4)
    assert (text[:, 0] < 4).all()  # class token leads the caption
