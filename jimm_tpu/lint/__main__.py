import sys

from jimm_tpu.lint.cli import main

sys.exit(main())
