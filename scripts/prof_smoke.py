"""CI drill for continuous profiling + HBM observability (ISSUE 18).

Four legs, all through shipped code paths:

**Ring leg.** ``jimm-tpu train --prof-ring`` at an aggressive cadence
(``--prof-every 5``) so a short run commits several real window captures;
asserts the ring holds >= 2 committed captures, stays under its byte
budget, and that every capture journaled a ``prof_capture_started`` /
``prof_capture_committed`` pair.

**Diff leg.** ``jimm-tpu obs prof diff`` over the two newest ring
captures — run in a SUBPROCESS that asserts ``jax`` was never imported,
proving the analysis path works on a dev box against rsynced artifacts.

**Incident leg.** The elastic kill-drill (2-replica x 2-way engine, one
replica's forward replaced with a raiser) with a capture manager
configured: the heal path must auto-trigger a deep capture tagged with
the incident's correlation id, and the journal chain for that cid must
include ``prof_capture_committed``.

**Overhead leg.** Interleaved ring-on / ring-off tiny-train pairs; the
minimum over pairs of (median on-step time / median off-step time) must
be <= 1.01 — the <=1% overhead budget the ring ships under. Appends a
``phase=prof_overhead`` row to MEASUREMENTS.jsonl.

Exits nonzero with a JSON error line on any violation.

Usage:
    JAX_PLATFORMS=cpu python -m scripts.prof_smoke
"""

from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys
import tempfile
import time
from pathlib import Path

RING_STEPS = 14
RING_EVERY = 5
RING_BUDGET = 32 << 20
OVERHEAD_PAIRS = 3
OVERHEAD_STEPS = 24
OVERHEAD_GATE = 1.01


def fail(msg: str) -> int:
    print(json.dumps({"metric": "prof_smoke", "value": 0.0, "error": msg}),
          flush=True)
    return 1


def _train(tmp: Path, tag: str, steps: int, prof_ring: Path | None,
           every: int = 200) -> tuple[int, Path]:
    from jimm_tpu import cli
    metrics = tmp / f"metrics_{tag}.jsonl"
    argv = ["train", "--preset", "vit-tiny-patch16-224", "--tiny",
            "--batch-size", "4", "--steps", str(steps), "--seed", "7",
            "--log-every", "0", "--metrics-file", str(metrics)]
    if prof_ring is not None:
        argv += ["--prof-ring", str(prof_ring),
                 "--prof-every", str(every), "--prof-window", "1",
                 "--prof-ring-bytes", str(RING_BUDGET)]
    rc = cli.main(argv)
    return rc, metrics


def _step_times(metrics: Path, skip: int = 2) -> list[float]:
    """Per-step times from the metrics JSONL, skipping compile/warmup."""
    times = []
    for line in metrics.read_text().splitlines():
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        t = rec.get("step_time_s")
        if isinstance(t, (int, float)) and rec.get("step", 0) >= skip:
            times.append(float(t))
    return times


def ring_leg(tmp: Path) -> tuple[str | None, dict, list[dict]]:
    from jimm_tpu.obs.journal import get_journal
    from jimm_tpu.obs.prof.capture import list_captures, reset_capture

    ring = tmp / "ring"
    rc, _ = _train(tmp, "ring", RING_STEPS, ring, every=RING_EVERY)
    reset_capture()
    if rc:
        return f"train --prof-ring exited {rc}", {}, []
    metas = list_captures(ring)
    if len(metas) < 2:
        return f"expected >= 2 ring captures, got {len(metas)}", {}, []
    total = sum(m["bytes"] for m in metas)
    if total > RING_BUDGET:
        return f"ring over budget: {total} > {RING_BUDGET}", {}, []
    events = [e["event"] for e in get_journal().tail(200)]
    started = events.count("prof_capture_started")
    committed = events.count("prof_capture_committed")
    if committed < len(metas) or started < committed:
        return (f"journal pairs off: {started} started, {committed} "
                f"committed, {len(metas)} on disk"), {}, []
    return None, {"captures": len(metas), "ring_bytes": total,
                  "kinds": [m["kind"] for m in metas]}, metas


def diff_leg(metas: list[dict]) -> tuple[str | None, dict]:
    newest = [str(m["path"]) for m in metas[-2:]]
    # jax-free proof: diff in a subprocess and assert jax never imported
    code = (
        "import sys\n"
        "from jimm_tpu.obs.cli import main\n"
        "rc = main(['obs', 'prof', 'diff', '--json', sys.argv[1], "
        "sys.argv[2]])\n"
        "assert 'jax' not in sys.modules, 'diff path imported jax'\n"
        "sys.exit(0 if rc in (0, 1) else 2)\n"
    )
    env = dict(os.environ)
    env.pop("JIMM_PROF_DIR", None)
    proc = subprocess.run([sys.executable, "-c", code, *newest],
                          capture_output=True, text=True, env=env,
                          timeout=120)
    if proc.returncode not in (0, 1):
        return (f"jax-free diff failed rc={proc.returncode}: "
                f"{proc.stderr[-400:]}"), {}
    d = json.loads(proc.stdout)
    if d.get("verdict") not in ("ok", "regression"):
        return f"diff produced no verdict: {d}", {}
    return None, {"verdict": d["verdict"],
                  "total_delta_frac": d["total_delta_frac"],
                  "jax_free": True}


def incident_leg(tmp: Path) -> tuple[str | None, dict]:
    import asyncio

    import numpy as np
    from flax import nnx

    from jimm_tpu import CLIP, preset
    from jimm_tpu.aot import ArtifactStore
    from jimm_tpu.cli import _tiny_override
    from jimm_tpu.obs.journal import chain, get_journal
    from jimm_tpu.obs.prof.capture import configure_capture, reset_capture
    from jimm_tpu.serve import (BucketTable, InferenceEngine,
                                build_replica_forwards, plan_topology)

    mgr = configure_capture(tmp / "incident_ring", deep_window_s=0.3,
                            min_trigger_interval_s=0.0)
    cfg = _tiny_override(preset("clip-vit-base-patch16"))
    model = CLIP(cfg, rngs=nnx.Rngs(0))
    size = cfg.vision.image_size
    plan = plan_topology(2, 2)
    try:
        with tempfile.TemporaryDirectory(prefix="prof-smoke-") as root:
            store = ArtifactStore(root)

            def build():
                return build_replica_forwards(
                    model, plan, method="encode_image",
                    item_shape=(size, size, 3), store=store,
                    label="prof_smoke")

            forwards, traces = build()
            engine = InferenceEngine(forwards, item_shape=(size, size, 3),
                                     buckets=BucketTable((1, 4)),
                                     max_delay_ms=2.0, trace_count=traces)
            engine.warmup_blocking()
            engine.set_heal(build)
            x = np.random.RandomState(0).rand(size, size, 3) \
                .astype(np.float32)

            class Raiser:
                def __call__(self, _):
                    raise RuntimeError("injected: replica device lost")

            async def drive():
                await engine.start()
                try:
                    for _ in range(4):
                        await engine.submit(x)
                    engine._replicas[1].forward = Raiser()
                    for _ in range(400):
                        try:
                            await engine.submit(x)
                        except RuntimeError:
                            pass
                        if engine.metrics.count("replans_total") >= 1:
                            return None
                        await asyncio.sleep(0.01)
                    return "no replan happened"
                finally:
                    await engine.stop()

            err = asyncio.run(drive())
            if err:
                return f"kill-drill: {err}", {}
            deadline = time.monotonic() + 10.0
            while not mgr.ls() and time.monotonic() < deadline:
                time.sleep(0.05)
            mgr.flush()
            captures = mgr.ls()
            events = list(get_journal().tail(400))
            faults = [e for e in events if e["event"] == "replica_fault"
                      and e.get("cid")]
            if not faults:
                return "no correlated replica_fault", {}
            cid = faults[-1]["cid"]
            tagged = [c for c in captures if c.get("cid") == cid]
            if not tagged:
                return (f"no deep capture on incident cid {cid}: "
                        f"{[c.get('cid') for c in captures]}"), {}
            incident = [e["event"] for e in chain(events, cid)]
            if "prof_capture_committed" not in incident:
                return (f"prof_capture_committed missing from chain: "
                        f"{incident}"), {}
            return None, {"cid": cid, "deep_capture": tagged[0]["name"],
                          "capture_bytes": tagged[0]["bytes"],
                          "reason": tagged[0]["reason"]}
    finally:
        reset_capture()


def overhead_leg(tmp: Path) -> tuple[str | None, dict]:
    from jimm_tpu.obs.prof.capture import reset_capture

    ratios = []
    for pair in range(OVERHEAD_PAIRS):
        # interleave on/off so in-process warmup and machine drift hit
        # both sides of every pair equally
        rc, m_on = _train(tmp, f"on{pair}", OVERHEAD_STEPS,
                          tmp / f"ovh_ring{pair}")
        reset_capture()
        if rc:
            return f"ring-on run {pair} exited {rc}", {}
        rc, m_off = _train(tmp, f"off{pair}", OVERHEAD_STEPS, None)
        if rc:
            return f"ring-off run {pair} exited {rc}", {}
        on = _step_times(m_on)
        off = _step_times(m_off)
        if len(on) < 8 or len(off) < 8:
            return f"too few step times (on={len(on)}, off={len(off)})", {}
        ratios.append(statistics.median(on) / statistics.median(off))
    best = min(ratios)
    if best > OVERHEAD_GATE:
        return (f"ring overhead over budget: min ratio {best:.4f} > "
                f"{OVERHEAD_GATE} (pairs: "
                f"{[round(r, 4) for r in ratios]})"), {}
    return None, {"min_ratio": round(best, 4),
                  "ratios": [round(r, 4) for r in ratios],
                  "gate": OVERHEAD_GATE, "steps": OVERHEAD_STEPS,
                  "prof_every_default": 200}


def hbm_leg() -> tuple[str | None, dict]:
    import jax.numpy as jnp

    from jimm_tpu.obs.prof.memory import MemoryMonitor

    # a pinned live array the sampler must see, whatever the earlier legs
    # left resident (CPU backends report via jax.live_arrays fallback)
    anchor = jnp.ones((256, 256), jnp.float32)
    anchor.block_until_ready()
    mon = MemoryMonitor()
    report = mon.sample()
    del anchor
    if not report["devices"]:
        return "device_memory_rows returned no devices", {}
    sources = {r["source"] for r in report["devices"]}
    if report["total_bytes_in_use"] < 256 * 256 * 4:
        return (f"live bytes not attributed: "
                f"{report['total_bytes_in_use']} (sources={sources})"), {}
    return None, {"devices": len(report["devices"]),
                  "sources": sorted(sources),
                  "total_bytes_in_use": report["total_bytes_in_use"]}


def main() -> int:
    # must land before jax initializes its backends (incident leg is 2x2)
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    import jax
    if jax.device_count() < 8:
        return fail(f"need 8 virtual devices, have {jax.device_count()}")

    tmp = Path(tempfile.mkdtemp(prefix="prof_smoke_"))
    err, ring_summary, metas = ring_leg(tmp)
    if err:
        return fail(f"ring leg: {err}")
    err, diff_summary = diff_leg(metas)
    if err:
        return fail(f"diff leg: {err}")
    err, incident_summary = incident_leg(tmp)
    if err:
        return fail(f"incident leg: {err}")
    err, overhead_summary = overhead_leg(tmp)
    if err:
        return fail(f"overhead leg: {err}")
    err, hbm_summary = hbm_leg()
    if err:
        return fail(f"hbm leg: {err}")

    row = {"ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
           "phase": "prof_overhead",
           "metric": "prof_ring_overhead (cpu smoke)",
           "value": overhead_summary["min_ratio"],
           "unit": "x step time vs ring off (min over pairs of medians)",
           "workload": "vit_tiny_train", "backend": "cpu",
           **{k: v for k, v in overhead_summary.items()
              if k != "min_ratio"}}
    measurements = Path(__file__).resolve().parent.parent \
        / "MEASUREMENTS.jsonl"
    with open(measurements, "a") as f:
        f.write(json.dumps(row) + "\n")

    print(json.dumps({"metric": "prof_smoke", "value": 1.0,
                      "ring": ring_summary, "diff": diff_summary,
                      "incident": incident_summary,
                      "overhead": overhead_summary,
                      "hbm": hbm_summary}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
