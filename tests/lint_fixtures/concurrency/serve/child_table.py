"""Cross-file JL014 waiver child: writes a request-keyed entry with no
eviction in THIS file — per-file JL014 fires, the graph waives it because
the inherited ``_evict_if_full`` (base_table.py) bounds the table."""

from tests.lint_fixtures.concurrency.serve.base_table import BoundedTable


class TenantView(BoundedTable):
    def record(self, tenant_id: str, value: float):
        self._table[tenant_id] = value  # JL014 per-file; waived via base
        self._evict_if_full()
