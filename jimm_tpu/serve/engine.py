"""Async micro-batching inference engine.

Single requests arrive on an asyncio loop; a batcher task coalesces them
under a max-latency/max-batch policy, pads each micro-batch up to one of the
pre-declared :mod:`~jimm_tpu.serve.buckets`, and dispatches through a warm
pre-compiled jitted forward. The coalescing policy:

1. take the first queued request, open a ``max_delay_ms`` window;
2. drain whatever else is already queued (no await, no added latency);
3. wait out the remainder of the window for stragglers — unless the queue
   depth is past the admission policy's shed watermark, in which case
   dispatch immediately at the largest already-full bucket (graceful
   degradation: shed latency, not requests);
4. stop early the moment the largest bucket fills.

Device compute runs on a single-thread executor so the event loop keeps
accepting and coalescing while a batch is in flight (continuous batching:
batch N+1 forms while batch N computes). Host syncs (``np.asarray`` on the
result) happen only inside that executor — the ``*_blocking`` functions —
never on the loop; the JL006 lint rule enforces exactly this split for every
``async def`` in this package.
"""

from __future__ import annotations

import asyncio
import functools
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Callable

import numpy as np

from jimm_tpu.obs.spans import new_trace_id, span
from jimm_tpu.serve.admission import (AdmissionController, AdmissionPolicy,
                                      DeadlineExceededError, EngineClosedError,
                                      RequestError, ServeMetrics)
from jimm_tpu.serve.buckets import BucketTable, default_buckets, pad_batch

_STOP = object()


def counting_forward(model, method: str = "encode_image"
                     ) -> tuple[Callable, Callable[[], int]]:
    """A jitted ``model.<method>`` plus a trace-count getter.

    Same explicit-module-argument spelling as ``utils/jit.py``'s
    ``jit_forward``; the counter increments inside the traced Python body,
    which runs once per compilation — so the getter IS the compile count the
    zero-recompiles-after-warmup acceptance check reads.
    """
    from flax import nnx

    state = {"traces": 0}

    @nnx.jit
    def _fwd(m, x):
        state["traces"] += 1
        return getattr(m, method)(x)

    return functools.partial(_fwd, model), lambda: state["traces"]


class _Request:
    __slots__ = ("item", "future", "deadline", "t0", "rid")

    def __init__(self, item: np.ndarray, future: asyncio.Future,
                 deadline: float, t0: float, rid: str):
        self.item = item
        self.future = future
        self.deadline = deadline
        self.t0 = t0
        self.rid = rid


class InferenceEngine:
    """Coalesces single-item requests into bucketed micro-batches.

    Args:
        forward: callable over a ``(B, *item_shape)`` array returning an
            array-like whose row ``i`` answers input row ``i`` (e.g. the
            pair from :func:`counting_forward`).
        item_shape: per-request input shape (no batch axis); submissions
            with any other shape are rejected with a typed
            :class:`~jimm_tpu.serve.admission.RequestError`.
        dtype: dtype batches are assembled in (requests are cast).
        buckets: allowed batch sizes (default: the platform table).
        max_delay_ms: coalescing window — the latency each request may
            spend waiting for batch-mates.
        policy: admission policy (queue bound, default deadline, shed
            watermark).
        metrics: shared :class:`ServeMetrics` (one per server).
        trace_count: optional compile-count getter, exported as the
            ``compile_count`` gauge.
    """

    def __init__(self, forward: Callable, *, item_shape: tuple[int, ...],
                 dtype=np.float32, buckets: BucketTable | None = None,
                 max_delay_ms: float = 5.0,
                 policy: AdmissionPolicy | None = None,
                 metrics: ServeMetrics | None = None,
                 trace_count: Callable[[], int] | None = None):
        self.forward = forward
        self.item_shape = tuple(item_shape)
        self.dtype = np.dtype(dtype)
        self.buckets = buckets if buckets is not None else default_buckets()
        self.max_delay_s = max_delay_ms / 1e3
        self.metrics = metrics or ServeMetrics()
        self.admission = AdmissionController(policy, self.metrics)
        self.trace_count = trace_count
        if trace_count is not None:
            self.metrics.bind_gauge("compile_count", trace_count)
        self.metrics.bind_gauge("queue_depth_now",
                                lambda: float(self._queue.qsize())
                                if self._queue is not None else 0.0)
        self._queue: asyncio.Queue | None = None
        self._task: asyncio.Task | None = None
        self._pool = ThreadPoolExecutor(max_workers=1,
                                        thread_name_prefix="jimm-serve-fwd")
        self._running = False
        # Per-request phase decomposition (trace id -> phase seconds),
        # newest last; read by /healthz debugging and tests.
        self.recent_traces: deque[dict] = deque(maxlen=64)
        # bucket -> {"seconds", "source"} filled by warmup_blocking;
        # source is "compile" (plain forward) or the AOT outcome
        # ("aot"/"miss"/"fallback") when the forward is store-backed.
        self.warmup_report: dict = {}

    # -- lifecycle --------------------------------------------------------

    def warmup_blocking(self) -> dict:
        """Compile every bucket before traffic (call off the event loop).
        Returns {bucket: seconds}; after this, steady-state traffic hits
        only warm executables.

        Store-first forwards (jimm_tpu.aot.AotForward) are consulted via
        their ``prepare_bucket(size)`` hook before the priming call: on an
        AOT hit the forward installs a deserialized executable, so the
        priming run below is a device warm-up, not a fresh trace+compile.
        The per-bucket outcome lands in ``self.warmup_report``."""
        prepare = getattr(self.forward, "prepare_bucket", None)
        times = {}
        self.warmup_report = {}
        for size in self.buckets.sizes:
            source = prepare(size) if prepare is not None else "compile"
            zeros = np.zeros((size,) + self.item_shape, self.dtype)
            t0 = time.monotonic()
            with span("serve_warmup_aot" if source == "aot"
                      else "serve_warmup_compile"):
                self._forward_blocking(zeros)
            times[size] = round(time.monotonic() - t0, 4)
            self.warmup_report[size] = {"seconds": times[size],
                                        "source": source}
        return times

    async def start(self) -> None:
        if self._running:
            return
        self._queue = asyncio.Queue()
        self._running = True
        self._task = asyncio.get_running_loop().create_task(
            self._batcher(), name="jimm-serve-batcher")

    async def stop(self) -> None:
        if not self._running:
            return
        self._running = False
        assert self._queue is not None
        self._queue.put_nowait(_STOP)
        if self._task is not None:
            await self._task
            self._task = None
        self._pool.shutdown(wait=True)

    # -- submission -------------------------------------------------------

    async def submit(self, item: np.ndarray,
                     timeout_s: float | None = None,
                     trace_id: str | None = None) -> np.ndarray:
        """One request in, one output row out. Raises
        :class:`QueueFullError` (backpressure), :class:`RequestError`
        (shape mismatch), or :class:`DeadlineExceededError` (deadline hit
        while queued or in flight). ``trace_id`` (admission-assigned, or
        generated here) follows the request into bucket dispatch and keys
        its phase decomposition in ``recent_traces``."""
        if not self._running or self._queue is None:
            raise EngineClosedError("engine is not running; call start()")
        item = self._coerce(item)
        self.metrics.inc("requests_total")
        self.admission.admit(self._queue.qsize())
        now = time.monotonic()
        deadline = self.admission.deadline_for(timeout_s, now)
        future = asyncio.get_running_loop().create_future()
        self._queue.put_nowait(_Request(item, future, deadline, now,
                                        trace_id or new_trace_id()))
        self.metrics.set_queue_depth(self._queue.qsize())
        try:
            return await asyncio.wait_for(future, timeout=deadline - now)
        except asyncio.TimeoutError:
            self.metrics.inc("timeouts_total")
            raise DeadlineExceededError(
                f"request deadline ({deadline - now:.3f}s) exceeded") \
                from None

    def _coerce(self, item) -> np.ndarray:
        """Validate and cast one request payload (host-side, cheap)."""
        arr = np.asarray(item, self.dtype)
        if arr.shape != self.item_shape:
            self.metrics.inc("errors_total")
            raise RequestError(f"item shape {arr.shape} != engine shape "
                               f"{self.item_shape}")
        return arr

    # -- batching loop ----------------------------------------------------

    async def _batcher(self) -> None:
        assert self._queue is not None
        queue = self._queue
        while True:
            first = await queue.get()
            if first is _STOP:
                break
            batch = [first]
            window_end = time.monotonic() + self.max_delay_s
            max_size = self.buckets.max_size
            stop = False
            shed = False
            while len(batch) < max_size:
                # drain what is already here — free batch-mates
                try:
                    nxt = queue.get_nowait()
                except asyncio.QueueEmpty:
                    nxt = None
                if nxt is _STOP:
                    stop = True
                    break
                if nxt is not None:
                    batch.append(nxt)
                    continue
                if self.admission.under_pressure(len(batch) + queue.qsize()):
                    # graceful degradation: dispatch the largest already-
                    # full smaller bucket instead of waiting out the window
                    shed = True
                    break
                remaining = window_end - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    nxt = await asyncio.wait_for(queue.get(),
                                                 timeout=remaining)
                except asyncio.TimeoutError:
                    break
                if nxt is _STOP:
                    stop = True
                    break
                batch.append(nxt)
            self.metrics.set_queue_depth(queue.qsize())
            await self._dispatch(batch, shed=shed)
            if stop:
                break

    async def _dispatch(self, batch: list[_Request], *,
                        shed: bool = False) -> None:
        now = time.monotonic()
        live = []
        for req in batch:
            if req.future.cancelled():
                # submit()'s wait_for already gave the client its timeout
                self.metrics.inc("cancelled_total")
            elif req.deadline <= now:
                self.metrics.inc("cancelled_total")
                if not req.future.done():
                    req.future.set_exception(DeadlineExceededError(
                        "deadline expired before dispatch"))
            else:
                live.append(req)
        if not live:
            return
        n = len(live)
        # queue phase ends here: time from submit to the start of dispatch
        for req in live:
            self.metrics.observe_phase("queue", now - req.t0)
        bucket = self.buckets.select(n) or self.buckets.max_size
        t_pad = time.perf_counter()
        with span("serve_pad"):
            padded = pad_batch([req.item for req in live], bucket)
        pad_s = time.perf_counter() - t_pad
        self.metrics.observe_phase("pad", pad_s)
        loop = asyncio.get_running_loop()
        try:
            out, device_s, readback_s = await loop.run_in_executor(
                self._pool, self._forward_blocking_timed, padded)
        except Exception as e:  # noqa: BLE001 — surface to every waiter
            self.metrics.inc("errors_total")
            for req in live:
                if not req.future.done():
                    req.future.set_exception(e)
            return
        self.metrics.observe_phase("device", device_s)
        self.metrics.observe_phase("readback", readback_s)
        self.metrics.observe_batch(n, bucket, shed=shed)
        done = time.monotonic()
        for i, req in enumerate(live):
            if not req.future.done():
                req.future.set_result(out[i])
                self.metrics.inc("responses_total")
                self.metrics.observe_latency(done - req.t0)
                self.recent_traces.append({
                    "trace_id": req.rid,
                    "bucket": bucket,
                    "queue_s": round(now - req.t0, 6),
                    "pad_s": round(pad_s, 6),
                    "device_s": round(device_s, 6),
                    "readback_s": round(readback_s, 6),
                    "total_s": round(done - req.t0, 6),
                })

    # -- device side (executor thread, never the event loop) --------------

    def _forward_blocking(self, padded: np.ndarray) -> np.ndarray:
        """Runs the warm forward and materializes the result on host. The
        only place in the engine that blocks on the device."""
        return self._forward_blocking_timed(padded)[0]

    def _forward_blocking_timed(
            self, padded: np.ndarray) -> tuple[np.ndarray, float, float]:
        """`_forward_blocking` plus the device/readback split: seconds the
        device spent computing (dispatch + ``block_until_ready``) vs.
        copying the result back to host memory (``np.asarray``)."""
        t0 = time.perf_counter()
        with span("serve_device"):
            out = self.forward(padded)
            if hasattr(out, "block_until_ready"):
                out.block_until_ready()
        t1 = time.perf_counter()
        with span("serve_readback"):
            host = np.asarray(out)
        return host, t1 - t0, time.perf_counter() - t1
