"""Pallas TPU fp8 matmul with an e4m3-forward / e5m2-gradient custom VJP.

The low-precision *training* fast path's workhorse, pairing the serving
int8 kernel (``ops/int8_matmul.py``): forward operands quantize to
``float8_e4m3fn`` (3 mantissa bits — resolution matters more than range
for activations and weights), gradients quantize to ``float8_e5m2``
(5 exponent bits — the backward's dynamic range dwarfs its precision
needs). Both run as fp8 x fp8 -> f32 MXU dots
(``preferred_element_type=jnp.float32``), with the rank-0 dequantizing
rescale fused into the same grid cell's epilogue.

Scaling is per-tensor and **explicit**: every public entry point takes the
fp32 scales as arguments and the custom VJP carries them as residual
state, so the caller decides the strategy —

- **dynamic** (:func:`dynamic_scale`): scale from this tensor's own amax.
  The backward always uses it for the incoming gradient (the cotangent's
  magnitude is unknowable ahead of time).
- **delayed** (:func:`delayed_scale` + :func:`update_amax_history`): scale
  from a rolling amax history, one matmul pass behind. The training
  policy (``jimm_tpu.quant.policy``) keeps the history as module state so
  forward quantization costs no extra reduction over the live tensor.

Quantization (the only sanctioned fp8 casts — lint rule JL016 bans bare
``.astype(jnp.float8_*)`` elsewhere in ops/ and train/) saturates at the
format max instead of overflowing to inf. Shape robustness and block
resolution mirror ``int8_matmul``: rows pad to the fp8 32-sublane tile,
K/N pad to 128 lanes, blocks resolve through
``tune.best_config("fp8_matmul")`` (lookup-only; explicit ints win so the
tuner's bench closures cannot recurse). Off-TPU the kernel runs in the
Pallas interpreter so CPU parity tests exercise the same code path.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from jimm_tpu.utils.compat import pallas_tpu_compiler_params

DEFAULT_BLOCK_M = 256
DEFAULT_BLOCK_N = 256
_LANES = 128
#: fp8 Mosaic tiles are (32, 128) — row blocks align to 32 sublanes
_FP8_SUBLANES = 32

#: saturation bounds of the two formats (jnp.finfo(...).max)
E4M3_MAX = 448.0
E5M2_MAX = 57344.0

_SEMANTICS = pallas_tpu_compiler_params(
    dimension_semantics=("parallel", "parallel"))

#: VMEM budget for one grid cell's resident tiles (mirrors the int8 /
#: flash kernels' budget; sync-tested against tune.space)
_VMEM_BUDGET = 8 * 1024 * 1024


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _ceil_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _per_cell_vmem_bytes(block_m: int, block_n: int, k: int) -> int:
    """Resident working set of one (block_m, block_n) grid cell: the fp8
    a/b tiles at the 128-padded K, the lane-broadcast per-tensor scale,
    the bias, and the f32 accumulator / out tiles. Mirrored jax-free in
    ``tune.space.fp8_matmul_vmem_bytes`` (sync-tested)."""
    kp = _ceil_to(k, _LANES)
    return (block_m * kp                  # a fp8 tile
            + kp * block_n                # b fp8 tile
            + _LANES * 4                  # lane-broadcast tensor scale
            + block_n * 4                 # bias
            + 2 * block_m * block_n * 4)  # f32 acc + out tile


def _dequant(acc: jax.Array, scale: jax.Array) -> jax.Array:
    """f32 accumulator -> dequantized f32 via the combined per-tensor
    scale (rank-0 rescale; both operands' scales fold into one scalar)."""
    return acc * scale


def _matmul_kernel(aq_ref, bq_ref, s_ref, b_ref, o_ref):
    acc = jax.lax.dot_general(
        aq_ref[...], bq_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    # the combined scale arrives lane-broadcast (1, 128); every lane holds
    # the same scalar
    y = _dequant(acc, s_ref[0, 0])
    o_ref[...] = (y + b_ref[...][None, :]).astype(o_ref.dtype)


def _resolve_blocks(a_shape, b_shape, dtypes, block_m, block_n):
    """Trace-time (host-side) block resolution through the tune cache —
    lookup only, never a measurement. Explicit ints win (the tuner's bench
    closures pass them, so tuning cannot recurse)."""
    if block_m is not None and block_n is not None:
        return int(block_m), int(block_n)
    from jimm_tpu.tune import best_config
    cfg = best_config("fp8_matmul", (tuple(a_shape), tuple(b_shape)),
                      tuple(dtypes),
                      default={"block_m": DEFAULT_BLOCK_M,
                               "block_n": DEFAULT_BLOCK_N})
    return (int(block_m if block_m is not None else cfg["block_m"]),
            int(block_n if block_n is not None else cfg["block_n"]))


def _pad2(x: jax.Array, rows: int, cols: int) -> jax.Array:
    pr, pc = rows - x.shape[0], cols - x.shape[1]
    return x if pr == 0 and pc == 0 else jnp.pad(x, ((0, pr), (0, pc)))


def _fp8_gemm(a_q: jax.Array, b_q: jax.Array, scale: jax.Array,
              bias: jax.Array | None, block_m: int | None,
              block_n: int | None) -> jax.Array:
    """One fp8 x fp8 -> f32 Pallas matmul ``(M, K) @ (K, N)`` with the
    fused dequant + bias epilogue. Operand formats may differ (the
    backward contracts e5m2 gradients against e4m3 residuals)."""
    m, k = a_q.shape
    kb, n = b_q.shape
    if kb != k:
        raise ValueError(f"a_q K {k} != b_q K {kb}")
    bm, bn = _resolve_blocks(a_q.shape, b_q.shape,
                             (a_q.dtype, b_q.dtype), block_m, block_n)
    bm = max(_FP8_SUBLANES,
             min(_ceil_to(bm, _FP8_SUBLANES), _ceil_to(m, _FP8_SUBLANES)))
    bn = max(_LANES, min(_ceil_to(bn, _LANES), _ceil_to(n, _LANES)))
    mp, np_, kp = _ceil_to(m, bm), _ceil_to(n, bn), _ceil_to(k, _LANES)
    s = jnp.broadcast_to(
        jnp.asarray(scale, jnp.float32).reshape(1, 1), (1, _LANES))
    b = (jnp.zeros((np_,), jnp.float32) if bias is None
         else jnp.pad(bias.astype(jnp.float32), ((0, np_ - bias.shape[0]),)))
    # zero padding contributes zero products to the fp8 dot
    out = pl.pallas_call(
        _matmul_kernel,
        grid=(mp // bm, np_ // bn),
        in_specs=[
            pl.BlockSpec((bm, kp), lambda i, j: (i, 0)),
            pl.BlockSpec((kp, bn), lambda i, j: (0, j)),
            pl.BlockSpec((1, _LANES), lambda i, j: (0, 0)),
            pl.BlockSpec((bn,), lambda i, j: (j,)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), jnp.float32),
        compiler_params=_SEMANTICS,
        interpret=_interpret(),
    )(_pad2(a_q, mp, kp), _pad2(b_q, kp, np_), s, b)
    return out[:m, :n]


# ---------------------------------------------------------------------------
# scaling helpers — the sanctioned homes for every fp8 cast (JL016)
# ---------------------------------------------------------------------------

def quantize_tensor(x: jax.Array, scale: jax.Array, dtype) -> jax.Array:
    """Per-tensor symmetric fp8 quantization at an explicit fp32 scale,
    saturating at the format max (no infs from a stale delayed scale)."""
    fmax = float(jnp.finfo(dtype).max)
    xf = x.astype(jnp.float32) / scale
    return jnp.clip(xf, -fmax, fmax).astype(dtype)


def tensor_amax(x: jax.Array) -> jax.Array:
    """The per-tensor amax observation feeding delayed scaling."""
    return jnp.max(jnp.abs(x.astype(jnp.float32)))


def dynamic_scale(x: jax.Array, dtype) -> jax.Array:
    """Per-tensor scale from this tensor's own amax: ``amax / format_max``
    (1.0 for all-zero tensors, so dequantization stays finite)."""
    amax = tensor_amax(x)
    fmax = float(jnp.finfo(dtype).max)
    return jnp.where(amax > 0, amax / fmax, 1.0)


def delayed_scale(amax_history: jax.Array, dtype) -> jax.Array:
    """Per-tensor scale from a rolling amax history (max over the window,
    one matmul pass behind the live tensor — Transformer-Engine-style
    delayed scaling)."""
    amax = jnp.max(amax_history)
    fmax = float(jnp.finfo(dtype).max)
    return jnp.where(amax > 0, amax / fmax, 1.0)


def update_amax_history(amax_history: jax.Array,
                        amax: jax.Array) -> jax.Array:
    """Roll the delayed-scaling window: drop the oldest observation,
    append the newest."""
    return jnp.concatenate(
        [amax_history[1:], jnp.reshape(amax, (1,)).astype(jnp.float32)])


# ---------------------------------------------------------------------------
# the custom VJP: e4m3 forward, e5m2 backward
# ---------------------------------------------------------------------------

@partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def _fp8_matmul(x, w, bias, x_scale, w_scale, block_m, block_n):
    x_q = quantize_tensor(x, x_scale, jnp.float8_e4m3fn)
    w_q = quantize_tensor(w, w_scale, jnp.float8_e4m3fn)
    return _fp8_gemm(x_q, w_q, x_scale * w_scale, bias, block_m, block_n)


def _fp8_matmul_fwd(x, w, bias, x_scale, w_scale, block_m, block_n):
    x_q = quantize_tensor(x, x_scale, jnp.float8_e4m3fn)
    w_q = quantize_tensor(w, w_scale, jnp.float8_e4m3fn)
    y = _fp8_gemm(x_q, w_q, x_scale * w_scale, bias, block_m, block_n)
    # residuals are the fp8 tensors themselves — the backward contracts
    # against exactly what the forward multiplied (straight-through
    # estimator through the quantizer), at 1 byte/element
    # zero-size sentinels carry the primal dtypes to the backward (dtype
    # objects are not valid pytree leaves for traced residuals)
    return y, (x_q, w_q, x_scale, w_scale,
               jnp.zeros((0,), x.dtype), jnp.zeros((0,), w.dtype),
               None if bias is None else jnp.zeros((0,), bias.dtype))


def _fp8_matmul_bwd(block_m, block_n, res, dy):
    x_q, w_q, x_scale, w_scale, x_sent, w_sent, b_sent = res
    x_dtype, w_dtype = x_sent.dtype, w_sent.dtype
    b_dtype = None if b_sent is None else b_sent.dtype
    dy_scale = dynamic_scale(dy, jnp.float8_e5m2)
    dy_q = quantize_tensor(dy, dy_scale, jnp.float8_e5m2)
    # dx = dy @ w^T : e5m2 x e4m3 contraction, dequant by both scales.
    # Cotangents land back in the primal dtypes — a bf16 model under remat
    # would otherwise see f32 cotangents meet bf16 recomputed values and
    # fail stablehlo verification at lowering.
    dx = _fp8_gemm(dy_q, w_q.T, dy_scale * w_scale, None, block_m,
                   block_n).astype(x_dtype)
    # dw = x^T @ dy
    dw = _fp8_gemm(x_q.T, dy_q, x_scale * dy_scale, None, block_m,
                   block_n).astype(w_dtype)
    dbias = (None if b_dtype is None
             else jnp.sum(dy.astype(jnp.float32), axis=0).astype(b_dtype))
    # scales are statistics, not parameters — no gradient flows to them
    return (dx, dw, dbias,
            jnp.zeros_like(x_scale), jnp.zeros_like(w_scale))


_fp8_matmul.defvjp(_fp8_matmul_fwd, _fp8_matmul_bwd)


def fp8_matmul(x: jax.Array, w: jax.Array, bias: jax.Array | None = None,
               *, x_scale: jax.Array | None = None,
               w_scale: jax.Array | None = None,
               block_m: int | None = None,
               block_n: int | None = None) -> jax.Array:
    """Differentiable fp8 matmul ``x @ w + bias`` (f32 output).

    Forward quantizes both operands to e4m3 at the given per-tensor
    scales; the backward quantizes the incoming gradient to e5m2 with a
    dynamic scale and contracts it against the saved fp8 residuals.

    Args:
        x: ``(M, K)`` activations (any float dtype).
        w: ``(K, N)`` weights (any float dtype).
        bias: optional ``(N,)`` bias added in f32 after dequantization.
        x_scale, w_scale: fp32 per-tensor scales; ``None`` falls back to
            dynamic scaling from the live tensor (the policy module passes
            delayed scales here instead).
        block_m, block_n: grid tile extents; ``None`` resolves through
            ``tune.best_config("fp8_matmul", ...)``.
    """
    xs = (dynamic_scale(x, jnp.float8_e4m3fn) if x_scale is None
          else jnp.asarray(x_scale, jnp.float32))
    ws = (dynamic_scale(w, jnp.float8_e4m3fn) if w_scale is None
          else jnp.asarray(w_scale, jnp.float32))
    return _fp8_matmul(x, w, bias, xs, ws, block_m, block_n)
