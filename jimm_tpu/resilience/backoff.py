"""Bounded, optionally-jittered exponential backoff.

One policy object shared by every retry loop in the tree — the hub download
retry (``weights/resolve.py``), the serve client's stale-socket retry
(``serve/client.py``), and the training supervisor (``supervisor.py``) —
so "how long do we wait after failure N" has exactly one definition.

Jitter exists to de-synchronize restart herds: when a maintenance event
preempts every worker of a pod at once, identical backoff schedules would
slam the coordinator in lockstep. It is seeded so drills and tests replay
the same delays.
"""

from __future__ import annotations

import random

__all__ = ["BackoffPolicy"]


class BackoffPolicy:
    """Delays of ``base_s * 2**attempt``, capped at ``max_s``.

    ``jitter`` is a fraction in [0, 1]: each delay is scaled by a uniform
    factor in ``[1 - jitter, 1 + jitter]`` drawn from a ``seed``-determined
    stream. ``jitter=0`` (the default) gives the exact exponential sequence
    — the hub-retry path relies on that to keep its measured delays stable.

    ``retries`` is carried for callers that bound their loop by the policy
    (the serve client); :meth:`delay` itself accepts any attempt index.
    """

    def __init__(self, *, retries: int = 3, base_s: float = 0.5,
                 max_s: float = float("inf"), jitter: float = 0.0,
                 seed: int | None = None):
        if retries < 0:
            raise ValueError(f"retries must be >= 0, got {retries}")
        if base_s < 0:
            raise ValueError(f"base_s must be >= 0, got {base_s}")
        if not 0.0 <= jitter <= 1.0:
            raise ValueError(f"jitter must be in [0, 1], got {jitter}")
        self.retries = retries
        self.base_s = base_s
        self.max_s = max_s
        self.jitter = jitter
        self._rng = random.Random(seed)

    def delay(self, attempt: int) -> float:
        """Seconds to wait after failed attempt ``attempt`` (0-based)."""
        d = min(self.max_s, self.base_s * (2 ** max(0, attempt)))
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        return max(0.0, d)
