"""grain-backed input pipeline over the zero-dependency TFRecord codec.

`jimm_tpu.data.records` is a plain-python generator pipeline (decode ->
native preprocess -> batch). This module offers the same batches through
`grain` (installed in the target environment; SURVEY App B): a random-access
record source + `grain.python.DataLoader`, which adds what a generator
cannot —

- **parallel workers** (``worker_count``): decode/resize in subprocesses,
  overlapping host preprocessing with device steps,
- **global shuffle** (index-level, not a buffer) with per-epoch reshuffling,
- **deterministic, checkpointable iteration**: the iterator's
  ``get_state()/set_state()`` captures the exact position (grain's
  ``PyGrainCheckpointHandler`` plugs into orbax for the same thing), a
  stronger resume story than the records-path ``skip_examples``
  fast-forward,
- **multi-host sharding** via ``ShardOptions`` (equivalent to the records
  path's ``shard_index/shard_count``).

The on-disk format and the decoded batches are identical to
`jimm_tpu.data.records` (reference anchor for the data story: the
reference's only input path is a network tfds call,
ref `examples/vit_training.py:205-212`).
"""

from __future__ import annotations

import os
from typing import Iterator, Sequence

import numpy as np

from jimm_tpu.data.preprocess import (SIGLIP_MEAN, SIGLIP_STD,
                                      to_float_normalized)
from jimm_tpu.data.records import pad_tokens, prep_image, resolve_paths
from jimm_tpu.data.tfrecord import decode_example

_LEN_BYTES = 8
_CRC_BYTES = 4


def _scan_offsets(path: str) -> list[tuple[int, int]]:
    """(payload_offset, payload_length) of every record in one shard —
    header-only scan (seeks past payloads), so indexing is IO-light.
    Truncated shards (interrupted copy/write) fail HERE with a clear error,
    like `read_tfrecord` — not later with a confusing worker decode error."""
    out = []
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        while True:
            head = f.read(_LEN_BYTES)
            if not head:
                break
            if len(head) != _LEN_BYTES:
                raise ValueError(f"truncated tfrecord length in {path}")
            n = int.from_bytes(head, "little")
            f.seek(_CRC_BYTES, 1)  # length crc
            off = f.tell()
            end = off + n + _CRC_BYTES
            if end > size:
                raise ValueError(
                    f"truncated tfrecord payload in {path}: record at "
                    f"offset {off} claims {n} bytes but the file ends at "
                    f"{size}")
            out.append((off, n))
            f.seek(end)
    return out


class TFRecordDataSource:
    """Random-access view over tfrecord shards (grain's
    ``RandomAccessDataSource`` protocol: ``len`` + ``getitem`` -> payload
    bytes). Builds a per-record offset index at construction. Reads use
    ``os.pread`` on a per-path fd: positionless, so grain's multithreaded
    readers (``ReadOptions.num_threads`` is 16 by default) can hit one
    source concurrently without interleaving seeks. The source pickles to
    worker processes; fds reopen lazily there."""

    def __init__(self, data: str | Sequence[str]):
        self._paths = resolve_paths(data)
        self._index: list[tuple[int, int, int]] = []  # (path_i, off, len)
        for pi, path in enumerate(self._paths):
            self._index.extend((pi, off, n)
                               for off, n in _scan_offsets(path))
        self._fds: dict[int, int] = {}

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_fds"] = {}  # fds don't pickle; workers reopen
        return state

    def __repr__(self) -> str:
        # stable across processes: grain embeds repr(data_source) in the
        # iterator state and refuses to restore when it differs (the default
        # object repr contains the memory address, which never matches)
        return (f"TFRecordDataSource(paths={self._paths!r}, "
                f"records={len(self._index)})")

    def __len__(self) -> int:
        return len(self._index)

    def __getitem__(self, i: int) -> bytes:
        pi, off, n = self._index[int(i)]
        fd = self._fds.get(pi)
        if fd is None:
            new = os.open(self._paths[pi], os.O_RDONLY)
            fd = self._fds.setdefault(pi, new)  # GIL-atomic; lose the race
            if fd is not new:                   # -> close the extra fd
                os.close(new)
        data = os.pread(fd, n, off)
        if len(data) != n:
            raise ValueError(f"short read at offset {off} of "
                             f"{self._paths[pi]} (file changed underfoot?)")
        return data

    def close(self) -> None:
        while self._fds:
            os.close(self._fds.popitem()[1])

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:
            pass


def _prep_image(ex: dict, image_size: int, mean, std) -> np.ndarray:
    return to_float_normalized(prep_image(ex, image_size)[None], mean, std)[0]


def make_grain_loader(data: str | Sequence[str], batch_size: int, *,
                      task: str = "contrastive", image_size: int,
                      seq_len: int | None = None, pad_id: int = 0,
                      mean=SIGLIP_MEAN, std=SIGLIP_STD, seed: int = 0,
                      num_epochs: int | None = None, shuffle: bool = True,
                      worker_count: int = 0, shard_index: int = 0,
                      shard_count: int = 1):
    """Build a ``grain.python.DataLoader`` yielding the same batch tuples as
    `jimm_tpu.data.records`:

    - ``task="contrastive"``: ``(images f32 [B,S,S,3], tokens i32 [B,L])``
      (requires ``seq_len``)
    - ``task="classification"``: ``(images f32 [B,S,S,3], labels i32 [B])``

    Iterate it directly, or grab ``iter(loader)`` and use
    ``get_state()/set_state()`` for exact checkpointable resume.
    """
    import grain.python as pg

    if task == "contrastive" and seq_len is None:
        raise ValueError("contrastive task needs seq_len")
    if task not in ("contrastive", "classification"):
        raise ValueError(f"unknown task {task!r}")

    class _Parse(pg.MapTransform):
        def map(self, payload: bytes):
            ex = decode_example(payload)
            image = _prep_image(ex, image_size, mean, std)
            if task == "classification":
                return image, np.int32(ex["label"][0])
            return image, pad_tokens(ex["tokens"], seq_len, pad_id)

    source = TFRecordDataSource(data)
    sampler = pg.IndexSampler(
        num_records=len(source),
        shuffle=shuffle,
        seed=seed,
        num_epochs=num_epochs,
        shard_options=pg.ShardOptions(shard_index=shard_index,
                                      shard_count=shard_count,
                                      drop_remainder=True))
    return pg.DataLoader(
        data_source=source,
        sampler=sampler,
        operations=[_Parse(), pg.Batch(batch_size, drop_remainder=True)],
        worker_count=worker_count)


def grain_batches(loader) -> Iterator[tuple[np.ndarray, np.ndarray]]:
    """Adapter: a grain DataLoader -> the plain ``(images, aux)`` tuple
    stream the trainer consumes (`jimm_tpu.cli.cmd_train`). Per-batch
    production time lands in the ``jimm_train`` registry
    (``grain_produce_seconds``) so input-bound runs show up in the unified
    dump, not just as mysteriously slow steps."""
    import time

    from jimm_tpu.obs.registry import enabled as _obs_enabled, get_registry
    it = iter(loader)
    while True:
        t0 = time.perf_counter()
        try:
            batch = next(it)
        except StopIteration:
            return
        if _obs_enabled():
            get_registry("jimm_train").histogram(
                "grain_produce_seconds").observe(time.perf_counter() - t0)
        yield tuple(np.asarray(b) for b in batch)


class CheckpointableGrainStream:
    """Exact resume under prefetch: pairs every produced batch with the
    grain iterator state captured right after pulling it, and exposes
    ``consumed_state`` — the state as of the last batch the *training loop*
    received, not the producer's read-ahead position.

    A ``PrefetchIterator`` runs the producer in a worker thread up to
    ``prefetch`` batches ahead, so checkpointing ``grain_iter.get_state()``
    directly skips those in-flight batches on resume (they were produced,
    never trained on). Iterate ``.batches()`` as the producer, wrap the
    consumer side with ``.track()``, and checkpoint ``consumed_state``.

    Thread-safety: the producer appends and the consumer pops on a
    ``deque`` — both operations are atomic, and batch order is preserved
    end-to-end (the prefetch queue is FIFO), so state i always pairs with
    batch i.
    """

    def __init__(self, grain_iter):
        from collections import deque
        self._it = grain_iter
        self._produced: "deque[bytes]" = deque()
        #: state to checkpoint; resumes at the batch AFTER the last consumed
        self.consumed_state: bytes = grain_iter.get_state()

    def batches(self) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        """Producer side: (images, aux) tuples off the grain iterator."""
        for batch in self._it:
            self._produced.append(self._it.get_state())
            yield tuple(np.asarray(b) for b in batch)

    def track(self, iterator: Iterator) -> Iterator:
        """Consumer side: pass batches through, advancing consumed_state."""
        for batch in iterator:
            if not self._produced:
                # a batch this stream never produced would silently mispair
                # state i with batch i+1 from here on — fail loudly instead
                raise RuntimeError(
                    "track() received a batch not produced by batches(): "
                    "the consumer iterator must be fed (possibly via "
                    "prefetch) from this stream's batches() only")
            self.consumed_state = self._produced.popleft()
            yield batch
