"""``jimm_tpu.lint`` — TPU-correctness static analyzer.

Layer 1 (always on) is pure-``ast`` rules JL001–JL016 and JL021 over
the source tree, plus the JL020 suppression-hygiene meta-rule. ``--concurrency``
builds a project-wide symbol table and call graph (``lint.graph``) and
runs the lock-discipline race detector (JL017–JL019), the tiered-
retrieval request-path IO rule (JL023), and interprocedural
escalations of JL006/JL008/JL013. ``--jaxpr`` is layer
1.5: abstract traces of registered entry points checked for promotion
drift, baked constants, and collective drift (JLT104–JLT106).
``--trace`` (layer 2) lowers entry points and asserts program-text
properties JLT101–JLT103. See ``docs/static_analysis.md`` for the rule
catalog and suppression syntax (``# jaxlint: disable=<rule> <why>``).
"""

from jimm_tpu.lint.core import ERROR, WARNING, Finding, lint_file, lint_paths

__all__ = ["ERROR", "WARNING", "Finding", "lint_file", "lint_paths"]
