"""Command-line interface: ``python -m jimm_tpu.lint [paths] [--trace]
[--json] [--vmem-budget BYTES]``.

Exit status is 1 when any **error**-severity finding survives suppression;
warnings are reported but never block. ``--json`` emits a machine-readable
report (one object per finding: rule, severity, path, line, message) for CI.
"""

from __future__ import annotations

import argparse
import json
import sys

from jimm_tpu.lint.core import ERROR, Finding, lint_paths
from jimm_tpu.lint.rules_ast import DEFAULT_VMEM_BUDGET


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m jimm_tpu.lint",
        description="TPU-correctness static analyzer for jimm_tpu "
                    "(AST rules JL0xx; --trace adds lowered-HLO checks "
                    "JLT1xx)")
    parser.add_argument("paths", nargs="*", default=["jimm_tpu", "tests"],
                        help="files or directories to lint "
                             "(default: jimm_tpu tests)")
    parser.add_argument("--trace", action="store_true",
                        help="also lower registered model entry points on "
                             "tiny shapes and check donation aliasing, FSDP "
                             "gather behavior, and batch-bucket stability "
                             "(imports JAX, takes ~a minute)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit findings as a JSON array on stdout")
    parser.add_argument("--vmem-budget", type=int,
                        default=DEFAULT_VMEM_BUDGET, metavar="BYTES",
                        help="VMEM budget for the JL005 block-size estimate "
                             f"(default {DEFAULT_VMEM_BUDGET})")
    return parser


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    findings: list[Finding] = lint_paths(args.paths,
                                         vmem_budget=args.vmem_budget)
    if args.trace:
        from jimm_tpu.lint.trace import run_trace_checks
        findings.extend(run_trace_checks())

    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    if args.as_json:
        print(json.dumps([f.to_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
        errors = sum(f.severity == ERROR for f in findings)
        warnings = len(findings) - errors
        print(f"{errors} error(s), {warnings} warning(s)")
    return 1 if any(f.severity == ERROR for f in findings) else 0


if __name__ == "__main__":
    sys.exit(main())
