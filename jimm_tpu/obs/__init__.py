"""jimm_tpu.obs — unified observability: one registry, spans, goodput.

Public surface::

    from jimm_tpu import obs

    reg = obs.get_registry("jimm_train")        # namespaced registry
    reg.counter("steps_total").inc()
    with obs.span("checkpoint_save"): ...        # host timing + TraceAnnotation
    acct = obs.GoodputAccounter()
    with acct.measure("data_wait"): batch = next(it)
    obs.snapshot()                               # unified {prefix_name: value}
    obs.render_prometheus()                      # one text dump, all namespaces

Disable all optional instrumentation with ``JIMM_OBS=0`` (or
``obs.set_enabled(False)``): spans and goodput measures become no-ops;
registries keep counting (serve counters are product behavior).
"""

from jimm_tpu.obs.baseline import (BaselineStore, check_rows, is_fallback,
                                   row_key)
from jimm_tpu.obs.exporters import (JsonlExporter, console_table,
                                    diff_snapshots, parse_prometheus_text,
                                    render_prometheus_text)
from jimm_tpu.obs.goodput import BUCKETS, GoodputAccounter
from jimm_tpu.obs.journal import (EventJournal, chain, configure_journal,
                                  correlate, current_cid, get_journal,
                                  new_correlation_id, read_events,
                                  reset_journal)
from jimm_tpu.obs.prof import (CaptureManager, MemoryMonitor,
                               configure_capture, get_capture_manager,
                               maybe_trigger, reset_capture)
from jimm_tpu.obs.registry import (Counter, DuplicateMetricError, Gauge,
                                   Histogram, MetricRegistry, enabled,
                                   get_registry, percentile, publish,
                                   registries, render_prometheus,
                                   set_enabled, snapshot, unpublish)
from jimm_tpu.obs.slo import SloEngine, SloObjective
from jimm_tpu.obs.spans import new_trace_id, span
from jimm_tpu.obs.timeline import (export_timeline, validate_chrome_trace,
                                   write_timeline)

__all__ = [
    "BUCKETS", "BaselineStore", "CaptureManager", "Counter",
    "DuplicateMetricError", "EventJournal", "Gauge", "GoodputAccounter",
    "Histogram", "JsonlExporter", "MemoryMonitor", "MetricRegistry",
    "SloEngine", "SloObjective", "chain", "check_rows", "configure_capture",
    "configure_journal", "console_table", "correlate", "current_cid",
    "diff_snapshots", "enabled", "export_timeline", "get_capture_manager",
    "get_journal", "get_registry", "is_fallback", "maybe_trigger",
    "new_correlation_id", "new_trace_id", "parse_prometheus_text",
    "percentile", "publish", "read_events", "registries",
    "render_prometheus", "render_prometheus_text", "reset_capture",
    "reset_journal", "row_key", "set_enabled", "snapshot", "span",
    "unpublish", "validate_chrome_trace", "write_timeline",
]
