"""On-TPU embedding & top-k retrieval platform.

- :mod:`~jimm_tpu.retrieval.store` — persistent, incrementally-updatable
  vector store (content-addressed segments + atomic manifests) with the
  prompt-embedding LRU as its hot tier.
- :mod:`~jimm_tpu.retrieval.topk` — exact streaming top-k scoring on
  device (blocked matmul + running ``lax.top_k`` merge, corpus sharded
  over the serving topology), AOT-warm and tune-resolved.
- :mod:`~jimm_tpu.retrieval.ann` — IVF two-stage approximate search
  (k-means coarse quantizer + runtime-``nprobe`` cluster probe + exact
  rescore of candidate spans), same AOT/tune/sharding contracts.
- :mod:`~jimm_tpu.retrieval.tier` — tiered residency over the same
  cluster-major layout: budgeted hot arena on device, warm host RAM, cold
  disk segments, PQ residual codec, and the autonomous ``IndexDaemon``.
- :mod:`~jimm_tpu.retrieval.api` — the service facade ``serve --index``
  and ``/v1/search`` ride, plus the ``jimm_retrieval`` metric namespace.
- :mod:`~jimm_tpu.retrieval.cli` — ``jimm-tpu index build|add|ls|verify``
  (jax-free, like the aot/tune/obs CLIs).

Importing this package never imports jax (the device program materializes
inside function bodies), so the index CLI stays a pure-host tool.
"""

from jimm_tpu.retrieval.ann import (DEFAULT_NPROBE, IvfIndexSearcher,
                                    IvfSearcher, assign_clusters,
                                    train_centroids)
from jimm_tpu.retrieval.api import RetrievalService, retrieval_metrics
from jimm_tpu.retrieval.store import (LoadedIndex, PersistentEmbeddingCache,
                                      RetrievalStoreError, VectorStore,
                                      normalize_rows)
from jimm_tpu.retrieval.tier import (IndexDaemon, PqCodec, TieredSearcher,
                                     TierPlan, plan_tiers, train_pq)
from jimm_tpu.retrieval.topk import (DEFAULT_BLOCK_N, IndexSearcher,
                                     Searcher, merge_partials,
                                     streaming_topk)

__all__ = ["DEFAULT_BLOCK_N", "DEFAULT_NPROBE", "IndexDaemon",
           "IndexSearcher", "IvfIndexSearcher", "IvfSearcher",
           "LoadedIndex", "PersistentEmbeddingCache", "PqCodec",
           "RetrievalService", "RetrievalStoreError", "Searcher",
           "TierPlan", "TieredSearcher", "VectorStore", "assign_clusters",
           "merge_partials", "normalize_rows", "plan_tiers",
           "retrieval_metrics", "streaming_topk", "train_centroids",
           "train_pq"]
