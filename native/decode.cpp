// Native JPEG/PNG decode for the input pipeline (libjpeg + libpng, both
// ubiquitous system libraries). The pure-python path decodes through PIL —
// a heavyweight optional dependency and the usual ingestion bottleneck; this
// gives the data loaders a zero-python decode for the common cases (baseline
// /progressive JPEG in grayscale/YCbCr/RGB, 8-bit gray/RGB PNG) and reports
// "not mine" for everything else (alpha, palette, 16-bit, CMYK), which
// falls back to PIL in `jimm_tpu/data/preprocess.py:decode_image_native`.
//
// Built into libjimm_preprocess.so when the codec headers exist (the
// Makefile probes); otherwise the stubs below report unavailability and the
// python wrapper never calls in.

#include <cstdint>
#include <cstring>

#ifndef JIMM_NO_IMAGE_CODECS

#include <csetjmp>
#include <cstdio>

#include <jpeglib.h>
#include <png.h>

namespace {

struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jmp;
};

void jimm_jpeg_abort(j_common_ptr cinfo) {
  std::longjmp(reinterpret_cast<JpegErr*>(cinfo->err)->jmp, 1);
}

bool is_jpeg(const uint8_t* d, int64_t n) {
  return n >= 2 && d[0] == 0xFF && d[1] == 0xD8;
}

bool is_png(const uint8_t* d, int64_t n) {
  static const uint8_t sig[8] = {0x89, 'P', 'N', 'G', '\r', '\n', 0x1a, '\n'};
  // full signature AND the IHDR chunk tag where it must sit: dimensions are
  // read straight from these bytes, so a garbled header must not pass
  return n >= 26 && std::memcmp(d, sig, 8) == 0 &&
         std::memcmp(d + 12, "IHDR", 4) == 0;
}

// Same spirit as PIL's decompression-bomb guard (MAX_IMAGE_PIXELS):
// anything bigger goes to the python path, where PIL enforces its limit.
constexpr int64_t kMaxPixels = 178956970;

uint32_t be32(const uint8_t* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

}  // namespace

extern "C" {

// Probe: 0 = this library can decode it (fills h/w), 1 = recognized but
// needs the python fallback, 2 = not a JPEG/PNG at all.
int jimm_image_info(const uint8_t* data, int64_t n, int64_t* h, int64_t* w) {
  if (is_jpeg(data, n)) {
    jpeg_decompress_struct cinfo;
    JpegErr err;
    cinfo.err = jpeg_std_error(&err.mgr);
    err.mgr.error_exit = jimm_jpeg_abort;
    if (setjmp(err.jmp)) {
      jpeg_destroy_decompress(&cinfo);
      return 1;
    }
    jpeg_create_decompress(&cinfo);
    jpeg_mem_src(&cinfo, data, static_cast<unsigned long>(n));
    jpeg_read_header(&cinfo, TRUE);
    // CMYK/YCCK can't convert to RGB in libjpeg: python fallback
    bool ok = cinfo.jpeg_color_space == JCS_GRAYSCALE ||
              cinfo.jpeg_color_space == JCS_YCbCr ||
              cinfo.jpeg_color_space == JCS_RGB;
    *h = cinfo.image_height;
    *w = cinfo.image_width;
    jpeg_destroy_decompress(&cinfo);
    if (*h <= 0 || *w <= 0 || *h * *w > kMaxPixels) return 1;
    return ok ? 0 : 1;
  }
  if (is_png(data, n)) {
    // IHDR is always first: length(4) "IHDR"(4) width(4) height(4)
    // bit_depth(1) color_type(1) at fixed offsets 8..26
    *w = be32(data + 16);
    *h = be32(data + 20);
    int bit_depth = data[24];
    int color = data[25];
    // bound each dimension BEFORE multiplying: h/w come straight from
    // attacker-controlled IHDR bytes (up to 2^32-1 each) and the int64
    // product can overflow, wrapping negative and slipping past the guard
    if (*h <= 0 || *w <= 0 || *h > kMaxPixels || *w > kMaxPixels ||
        *h * *w > kMaxPixels)
      return 1;
    // 0 = gray, 2 = truecolor RGB; everything else (palette, alpha,
    // 16-bit) takes the python path
    return (bit_depth == 8 && (color == 0 || color == 2)) ? 0 : 1;
  }
  return 2;
}

// Decode into caller-allocated uint8 [h, w, 3] RGB. Returns 0 on success,
// 1 when the image decoded but libjpeg warned (caller should prefer a
// tolerant decoder's judgement), -1 on hard failure.
int jimm_decode_image(const uint8_t* data, int64_t n, uint8_t* out,
                      int64_t h, int64_t w) {
  if (is_jpeg(data, n)) {
    jpeg_decompress_struct cinfo;
    JpegErr err;
    cinfo.err = jpeg_std_error(&err.mgr);
    err.mgr.error_exit = jimm_jpeg_abort;
    if (setjmp(err.jmp)) {
      jpeg_destroy_decompress(&cinfo);
      return -1;
    }
    jpeg_create_decompress(&cinfo);
    jpeg_mem_src(&cinfo, data, static_cast<unsigned long>(n));
    jpeg_read_header(&cinfo, TRUE);
    cinfo.out_color_space = JCS_RGB;
    jpeg_start_decompress(&cinfo);
    if (static_cast<int64_t>(cinfo.output_height) != h ||
        static_cast<int64_t>(cinfo.output_width) != w ||
        cinfo.output_components != 3) {
      jpeg_destroy_decompress(&cinfo);
      return -1;
    }
    while (cinfo.output_scanline < cinfo.output_height) {
      JSAMPROW row = out + int64_t(cinfo.output_scanline) * w * 3;
      jpeg_read_scanlines(&cinfo, &row, 1);
    }
    // libjpeg WARNS (rather than erroring) on recoverable oddities —
    // truncated bodies it pads, but also harmless junk like "extraneous
    // bytes before marker" that is common in real-world corpora and that
    // PIL decodes fine. Warnings raised during header/scanline decode mean
    // the pixels may differ from a tolerant decoder's: report 1
    // (decoded-but-suspect) so the python wrapper re-decodes through PIL.
    // Warnings first raised at finish (trailing junk AFTER every scanline
    // was produced) cannot change pixels already decoded — keep those a
    // clean 0 and spare the double decode on dirty-but-complete files.
    bool warned_during_scan = cinfo.err->num_warnings > 0;
    jpeg_finish_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return warned_during_scan ? 1 : 0;
  }
  if (is_png(data, n)) {
    png_image image;
    std::memset(&image, 0, sizeof(image));
    image.version = PNG_IMAGE_VERSION;
    if (!png_image_begin_read_from_memory(&image, data,
                                          static_cast<size_t>(n)))
      return -1;
    image.format = PNG_FORMAT_RGB;
    if (static_cast<int64_t>(image.height) != h ||
        static_cast<int64_t>(image.width) != w) {
      png_image_free(&image);
      return -1;
    }
    if (!png_image_finish_read(&image, nullptr, out, 0, nullptr)) {
      png_image_free(&image);
      return -1;
    }
    return 0;
  }
  return -1;
}

// 1 when this build carries the codecs (python checks before trusting info)
int jimm_has_image_codecs(void) { return 1; }

}  // extern "C"

#else  // JIMM_NO_IMAGE_CODECS

extern "C" {
int jimm_image_info(const uint8_t*, int64_t, int64_t*, int64_t*) { return 2; }
int jimm_decode_image(const uint8_t*, int64_t, uint8_t*, int64_t, int64_t) {
  return -1;
}
int jimm_has_image_codecs(void) { return 0; }
}

#endif  // JIMM_NO_IMAGE_CODECS
