"""Orbax-based sharded checkpoint save/restore — the reference is load-only
(SURVEY §5): no save path, no optimizer state, no resume.

Saves the full training state (model params + optimizer state + step) with
async, sharded orbax writes; restores onto the *current* mesh sharding (so a
run can resume on a different topology). HF-interoperable safetensors export
lives in `jimm_tpu/weights/export.py`.
"""

from __future__ import annotations

import os
import warnings
from pathlib import Path
from typing import Any

import numpy as np
import orbax.checkpoint as ocp
from flax import nnx

import jimm_tpu.utils.compat  # noqa: F401  (nnx backfills: to_flat_state, set_value)


def _split_state(obj) -> Any:
    return nnx.state(obj)


def _storage_layout(model: nnx.Module) -> dict[str, Any] | None:
    """Fingerprint of any baked pipeline placement (`nn/transformer.py`
    pp_stages): layer rows are stored in circular schedule order, so a
    restore into a DIFFERENT placement would permute layers silently —
    shapes all match. Recorded at save, validated at restore."""
    cfg = getattr(model, "config", None)
    if cfg is None:
        return None
    layout: dict[str, Any] = {}
    for tower in ("vision", "text"):
        t = getattr(cfg, tower, None)
        if (t is not None and getattr(t, "pipeline", False)
                and t.pp_virtual > 1 and t.pp_stages):
            layout[tower] = {"pp_stages": t.pp_stages,
                             "pp_virtual": t.pp_virtual, "depth": t.depth}
    return layout or None


def _mesh_layout(mesh) -> dict[str, Any] | None:
    """JSON-able fingerprint of the mesh a state was saved under (axis
    sizes + device count). Orbax's ``StandardRestore`` already reshards
    every array onto the *target* state's shardings, so a mesh change needs
    no data movement here — the layout is recorded so restore can tell an
    elastic topology change apart from a same-shape resume and count it."""
    if mesh is None:
        return None
    return {"axes": {str(k): int(v) for k, v in dict(mesh.shape).items()},
            "n_devices": int(mesh.devices.size)}


def _relayout(state, saved: dict | None, current: dict | None):
    """Re-permute stacked layer rows from a checkpoint's baked pipeline
    placement to the target model's (either may be canonical=None). Applies
    to every leaf under a tower's ``blocks`` whose leading dim is the layer
    count — model params and mirrored optimizer moments alike."""
    from jimm_tpu.parallel.pipeline import circular_layer_order

    perms: dict[str, np.ndarray] = {}
    for tower in ("vision", "text"):
        s = (saved or {}).get(tower)
        c = (current or {}).get(tower)
        if s == c:
            continue
        if s and c and s["depth"] != c["depth"]:
            raise ValueError(f"{tower} depth changed between checkpoint "
                             f"({s['depth']}) and model ({c['depth']})")
        depth = (s or c)["depth"]

        def order(layout):
            if not layout:
                return np.arange(depth)
            return circular_layer_order(depth, layout["pp_stages"],
                                        layout["pp_virtual"])

        o_saved, o_cur = order(s), order(c)
        inv_saved = np.empty(depth, np.int64)
        inv_saved[o_saved] = np.arange(depth)
        perm = inv_saved[o_cur]  # saved-storage -> canonical -> cur-storage
        if not np.array_equal(perm, np.arange(depth)):
            perms[tower] = perm
    if not perms:
        return state

    out = []
    for path, leaf in nnx.to_flat_state(state):
        keys = tuple(str(k) for k in path)
        tower = next((t for t in perms if t in keys), None)
        if tower is not None and "blocks" in keys:
            perm = perms[tower]
            # get_value(): flax 0.12 deprecates .value access on Variables
            val = (leaf.get_value() if hasattr(leaf, "get_value")
                   else leaf)
            if getattr(val, "ndim", 0) >= 1 and val.shape[0] == len(perm):
                new = val[perm]
                if getattr(val, "sharding", None) is not None:
                    # the gather's output sharding is XLA's choice; pin it
                    # back so restore keeps its onto-current-sharding
                    # contract (stage-sharded pipelined params especially)
                    import jax
                    new = jax.device_put(new, val.sharding)
                leaf = leaf.replace(new) if hasattr(leaf, "replace") else new
        out.append((path, leaf))
    return nnx.from_flat_state(out)


def _pin_unannotated(state, mesh):
    """Leaves the model never annotated (optimizer scalars like the Adam
    step count) restore *committed to a single device*: orbax reshards
    onto the target's sharding, and an unannotated target array means
    SingleDeviceSharding. A later jit mixing them with mesh-committed
    params then refuses placement outright. Re-pin such leaves replicated
    over the live mesh — exactly where jit would have put them before the
    restore committed them."""
    if mesh is None:
        return state
    import jax
    from jax.sharding import NamedSharding, PartitionSpec
    rep = NamedSharding(mesh, PartitionSpec())
    out = []
    for path, leaf in nnx.to_flat_state(state):
        val = leaf.get_value() if hasattr(leaf, "get_value") else leaf
        sh = getattr(val, "sharding", None)
        if sh is not None and not isinstance(sh, NamedSharding):
            new = jax.device_put(val, rep)
            leaf = leaf.replace(new) if hasattr(leaf, "replace") else new
        out.append((path, leaf))
    return nnx.from_flat_state(out)


class CheckpointManager:
    """Thin nnx-aware wrapper over ``orbax.checkpoint.CheckpointManager``."""

    def __init__(self, directory: str | Path, *, max_to_keep: int = 3,
                 save_interval_steps: int = 1, mesh=None):
        self._dir = Path(directory).absolute()
        #: mesh the live model is sharded over (None = unsharded). Saves
        #: record its layout; restore compares it against the checkpoint's
        #: and counts a topology change when they differ (elastic restarts
        #: that lost or gained devices land here).
        self.mesh = mesh
        #: ``{"saved": ..., "current": ...}`` of the last restore that
        #: crossed a mesh change, else None
        self.last_topology_change: dict[str, Any] | None = None
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                create=True))
        # orbax's step scan ignores hidden dirs, so the marker and
        # quarantine sidecars can live inside the checkpoint root
        self._markers = self._dir / ".jimm_markers"
        #: steps whose async save was initiated but not yet known committed
        self._pending: list[int] = []
        #: user-supplied ``extra`` metadata of the last restored step
        #: (e.g. the grain data-iterator state) — populated by `restore`
        self.last_restored_extra: dict[str, Any] = {}

    @property
    def directory(self) -> Path:
        return self._dir

    def save(self, step: int, model: nnx.Module,
             optimizer: nnx.Optimizer | None = None, *,
             extra: dict[str, Any] | None = None, force: bool = False) -> bool:
        """Async-save model (+ optimizer) state at ``step``."""
        from jimm_tpu.obs import get_registry, span
        with span("checkpoint_save"):
            items: dict[str, Any] = {
                "model": ocp.args.StandardSave(nnx.state(model, nnx.Param))}
            if optimizer is not None:
                items["opt"] = ocp.args.StandardSave(
                    nnx.state(optimizer, nnx.optimizer.OptState))
            meta = dict(extra or {})
            layout = _storage_layout(model)
            if layout is not None:
                meta["_storage_layout"] = layout
            mesh_layout = _mesh_layout(self.mesh)
            if mesh_layout is not None:
                meta["_mesh_layout"] = mesh_layout
            if meta:
                items["extra"] = ocp.args.JsonSave(meta)
            saved = self._mgr.save(step, args=ocp.args.Composite(**items),
                                   force=force)
        if saved:
            # entering an actual save waits out the previous async write
            # (orbax serializes them), so every earlier pending step is
            # committed by now — the new step stays pending until the next
            # save/wait/close proves its own write finished
            self._flush_markers()
            self._pending.append(step)
            get_registry("jimm_train").counter("checkpoint_saves_total").inc()
        return saved

    # -- completion markers -------------------------------------------------
    # orbax's latest_step()/all_steps() scan bare step directories, so a
    # partially-written dir left by a mid-save kill looks identical to a
    # committed checkpoint and silently wins the "latest" race. A marker
    # file is dropped (atomic tmp + rename) only once a step's async write
    # is known finished; restore trusts markers, not directory listings.

    def _write_marker(self, step: int) -> None:
        self._markers.mkdir(exist_ok=True)
        tmp = self._markers / f".{step}.tmp"
        tmp.write_text("complete\n")
        os.replace(tmp, self._markers / str(step))

    def _flush_markers(self) -> None:
        if not self._pending:
            return
        for step in self._pending:
            self._write_marker(step)
        self._pending.clear()
        from jimm_tpu.resilience.supervisor import note_checkpoint_completed
        note_checkpoint_completed()

    def _marked_steps(self) -> set[int] | None:
        """Steps with a completion marker, or None when this checkpoint
        tree predates markers entirely (then orbax's listing is all we
        have, the historical behavior)."""
        if not self._markers.is_dir():
            return None
        marked = {int(p.name) for p in self._markers.iterdir()
                  if p.name.isdigit()}
        return marked or None

    def _steps_on_disk(self) -> set[int]:
        # a direct listing, not self._mgr.all_steps(): orbax caches its
        # step scan at manager creation, which would miss dirs that appear
        # or vanish (quarantine) while this process runs
        if not self._dir.is_dir():
            return set()
        return {int(p.name) for p in self._dir.iterdir()
                if p.is_dir() and p.name.isdigit()}

    def completed_steps(self) -> list[int]:
        """Ascending steps that are both on disk and marked complete."""
        existing = self._steps_on_disk()
        marked = self._marked_steps()
        if marked is None:
            return sorted(existing)
        return sorted(existing & marked)

    def quarantine_step(self, step: int, reason: str) -> Path | None:
        """Move a bad step directory into ``.quarantine/`` — never delete,
        so the bytes stay available for a post-mortem. Returns the new
        location, or None when the move lost a race."""
        from jimm_tpu.obs import get_registry
        src = self._dir / str(step)
        qdir = self._dir / ".quarantine"
        try:
            qdir.mkdir(exist_ok=True)
            dest = qdir / str(step)
            n = 0
            while dest.exists():
                n += 1
                dest = qdir / f"{step}-{n}"
            os.replace(src, dest)
            (dest / ".jimm_quarantine_reason.txt").write_text(reason + "\n")
        except OSError:
            return None
        (self._markers / str(step)).unlink(missing_ok=True)
        get_registry("jimm_train").counter(
            "checkpoint_quarantined_total").inc()
        from jimm_tpu.obs.journal import get_journal
        get_journal().emit("checkpoint_quarantined", step=step,
                           reason=reason, dest=str(dest))
        self._mgr.reload()  # drop the manager's cached view of the tree
        return dest

    def _sweep_partial_dirs(self, *, newer_than: int) -> None:
        """Quarantine unmarked step dirs newer than the newest completed
        step — the torso a mid-save kill leaves behind — so orbax's own
        step scan can never resurrect them."""
        marked = self._marked_steps()
        if marked is None:
            return
        for step in self._steps_on_disk():
            if (step > newer_than and step not in marked
                    and step not in self._pending):
                self.quarantine_step(
                    step, "partial write (no completion marker)")

    def restore(self, model: nnx.Module,
                optimizer: nnx.Optimizer | None = None,
                *, step: int | None = None) -> int:
        """Restore in place (onto each param's current sharding); returns the
        restored step.

        With ``step=None`` the newest *completed* checkpoint is used —
        partial step dirs (no completion marker) are swept aside, and a
        step whose restore fails (corrupted bytes) is quarantined, never
        deleted, before falling back to the previous good step. An explicit
        ``step`` restores exactly that step and propagates its errors.

        Baked pipeline placement (`nn/transformer.py` pp_stages) stores
        layer rows in circular schedule order. When the checkpoint's layout
        differs from the model's, the stacked layer arrays are re-permuted
        through canonical order (saved-storage -> canonical -> current-
        storage), so a pipelined run can be evaluated or fine-tuned with any
        other placement — including none."""
        if step is not None:
            return self._restore_step(step, model, optimizer)
        candidates = self.completed_steps()
        if not candidates:
            raise FileNotFoundError("no checkpoint found")
        self._sweep_partial_dirs(newer_than=candidates[-1])
        for cand in reversed(candidates):
            try:
                return self._restore_step(cand, model, optimizer)
            except Exception as e:
                dest = self.quarantine_step(
                    cand, f"restore failed: {type(e).__name__}: {e}")
                warnings.warn(
                    f"checkpoint step {cand} failed to restore "
                    f"({type(e).__name__}: {e}); quarantined to {dest}, "
                    f"falling back to the previous good step",
                    RuntimeWarning, stacklevel=2)
        raise FileNotFoundError(
            f"no restorable checkpoint: all {len(candidates)} candidate "
            f"step(s) failed and were quarantined")

    def _restore_step(self, step: int, model: nnx.Module,
                      optimizer: nnx.Optimizer | None = None) -> int:
        from jimm_tpu.obs import get_registry, span
        from jimm_tpu.obs.journal import get_journal
        get_registry("jimm_train").counter("checkpoint_restores_total").inc()
        # inherits the ambient incident cid when the supervisor is
        # restarting around a failure — the restore joins that chain
        get_journal().emit("checkpoint_restored", step=step)
        with span("checkpoint_restore"):
            model_state = nnx.state(model, nnx.Param)
            items: dict[str, Any] = {
                "model": ocp.args.StandardRestore(model_state)}
            if optimizer is not None:
                items["opt"] = ocp.args.StandardRestore(
                    nnx.state(optimizer, nnx.optimizer.OptState))
            # probe for the optional extra/ item by its committed directory
            # (the manager uses default step naming) instead of
            # catch-and-retry: a corrupt/unreadable extra must FAIL the
            # restore, not silently skip the placement guard below, and a
            # genuine model-state error must not trigger a pointless second
            # multi-GB restore attempt
            has_extra = (self._mgr.directory / str(step) / "extra").exists()
            if has_extra:
                items["extra"] = ocp.args.JsonRestore()
            restored = self._mgr.restore(step,
                                         args=ocp.args.Composite(**items))
            saved_meta = (restored.get("extra") or {}) if has_extra else {}
            self.last_restored_extra = {
                k: v for k, v in saved_meta.items()
                if k not in ("_storage_layout", "_mesh_layout")}
            self._note_mesh_change(step, saved_meta.get("_mesh_layout"))
            saved = saved_meta.get("_storage_layout")
            current = _storage_layout(model)
            model_state = restored["model"]
            opt_state = restored.get("opt")
            if saved != current:
                model_state = _relayout(model_state, saved, current)
                if opt_state is not None:
                    # optimizer moments live under opt.model mirroring the
                    # param tree; same stacked rows, same re-permutation
                    opt_state = _relayout(opt_state, saved, current)
            model_state = _pin_unannotated(model_state, self.mesh)
            if opt_state is not None:
                opt_state = _pin_unannotated(opt_state, self.mesh)
            nnx.update(model, model_state)
            if optimizer is not None:
                nnx.update(optimizer, opt_state)
        return step

    def _note_mesh_change(self, step: int, saved: dict | None) -> None:
        """Detect restore-onto-a-different-mesh (elastic shrink/grow).

        The actual resharding is free: ``StandardRestore`` targets the live
        model's NamedShardings, so the arrays land distributed over
        whatever mesh the model was rebuilt on. What a topology change
        still needs is to be *visible* — the counter is what drills and
        dashboards assert on."""
        current = _mesh_layout(self.mesh)
        if saved is None or current is None or saved == current:
            return
        self.last_topology_change = {"step": step, "saved": saved,
                                     "current": current}
        from jimm_tpu.obs import get_registry
        from jimm_tpu.obs.journal import get_journal
        get_registry("jimm_train").counter(
            "checkpoint_topology_changes_total").inc()
        get_journal().emit("mesh_resharded", step=step, saved=saved,
                           current=current)
        print(  # jaxlint: disable=JL007 — one-shot operator narration of an elastic restore, mirrors the supervisor's restart lines
            f"[checkpoint] step {step} saved on mesh {saved['axes']} "
            f"({saved['n_devices']} devices), restored onto "
            f"{current['axes']} ({current['n_devices']} devices) — "
            f"resharded onto the current topology")

    def latest_step(self) -> int | None:
        """Newest *completed* step (marker-verified) — unlike raw orbax,
        a partially-written step directory can never be "latest"."""
        steps = self.completed_steps()
        return steps[-1] if steps else None

    def wait(self) -> None:
        self._mgr.wait_until_finished()
        self._flush_markers()

    def close(self) -> None:
        self._mgr.close()  # waits out in-flight async saves
        self._flush_markers()
