"""``python -m jimm_tpu`` entry point."""

import sys

from jimm_tpu.cli import main

sys.exit(main())
