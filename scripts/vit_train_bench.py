"""Metric of record #2 (BASELINE.md: "ViT-L/16 ImageNet train MFU").

Thin entry point the measurement watcher queues: execs ``bench.py --model
vit_l16_384`` so the ViT-L/16-384 classifier train-MFU bench shares every
piece of bench.py's outage hardening (probe/compile watchdogs, budget-aware
retry, CPU-smoke fallback, analytic-vs-XLA MFU cross-check) and its
measurement fields — including the ``step_time_p50_ms``/``step_time_p99_ms``
spread computed with the shared `jimm_tpu.obs.percentile` helper, the same
nearest-rank math the serve stack reports. Extra argv is forwarded, so e.g.
``python -m scripts.vit_train_bench --batch-size 64`` works.
"""

from __future__ import annotations

import os
import pathlib
import sys

BENCH = pathlib.Path(__file__).resolve().parent.parent / "bench.py"


def main() -> None:
    # compile watchdog 300s (not the 240 default): the 24-layer full-unroll
    # ViT-L step compiles noticeably slower than SigLIP-B's 12+12 towers on
    # this single-core host; user argv still overrides
    os.execv(sys.executable, [sys.executable, str(BENCH),
                              "--model", "vit_l16_384",
                              "--compile-timeout", "300"] + sys.argv[1:])


if __name__ == "__main__":
    main()
