"""JL013 fixture: silently swallowed broad exceptions in serving code."""


def dispatch(replica, batch):
    try:
        return replica.forward(batch)
    except Exception:                         # JL013: watchdog never sees it
        pass


def drain(conn):
    try:
        conn.close()
    except:                                   # JL013: bare except, same hole
        pass


def close_quietly(sock):
    # ok: narrow except — a best-effort close is allowed to ignore OSError
    try:
        sock.close()
    except OSError:
        pass


def snapshot_gauge(fn):
    # ok: broad but justified best-effort swallow
    try:
        return float(fn())
    except Exception:  # jaxlint: disable=JL013 — a gauge must not kill the scrape
        pass


def report(err, metrics):
    # ok: broad except that HANDLES the failure instead of eating it
    try:
        metrics.flush()
    except Exception as e:
        metrics.inc("errors_total")
        raise RuntimeError("flush failed") from e
