"""Multi-model residency: several warm engines behind one server.

A :class:`ModelPool` keeps N checkpoints (e.g. the f32 and int8 twins, or
B/16 next to So400m) resident on one topology, each wrapped in its own
:class:`~jimm_tpu.serve.engine.InferenceEngine` whose forward carries its
own AOT fingerprint — the artifact store keys on the aggregated param
dtype and config, so the twins can never adopt each other's executables
and every model restarts warm independently. Requests pick a model with
the ``model=`` field (or ``X-Jimm-Model`` header); absent means the
default model, so single-model deployments are unchanged.

Weight hot-swap is :meth:`swap`: stage a fresh warmed engine under an
existing name and the pool atomically re-routes new requests to it,
returning the old engine for the caller to drain and stop. The pool's
table is operator-configured and every entry is removable
(:meth:`remove` is the eviction path JL014 looks for) — request traffic
can route to models but never create them.
"""

from __future__ import annotations

import threading

from jimm_tpu.serve.admission import RequestError

__all__ = ["ModelPool", "param_nbytes"]


def param_nbytes(tree) -> int:
    """Total parameter bytes of a (possibly nested) param container —
    dict/list/tuple of arrays, an ``nnx.State`` (any ``.items()``
    mapping, with ``VariableState.value`` leaves), or a flax module with
    ``.params``. Duck-typed on ``size``/``dtype.itemsize`` so numpy and
    jax arrays both count without this module importing jax."""
    params = getattr(tree, "params", tree)
    if isinstance(params, (list, tuple)):
        return sum(param_nbytes(v) for v in params)
    size = getattr(params, "size", None)
    itemsize = getattr(getattr(params, "dtype", None), "itemsize", None)
    if size is not None and itemsize is not None:
        return int(size) * int(itemsize)
    items = getattr(params, "items", None)  # dict / nnx.State / FrozenDict
    if callable(items):
        return sum(param_nbytes(v) for _, v in items())
    value = getattr(params, "value", None)  # nnx VariableState leaf
    if value is not None:
        return param_nbytes(value)
    return 0


class ModelPool:
    """Named engines sharing one server, one metrics surface, one loop.

    Args:
        engines: ``{name: InferenceEngine}`` — all resident models. Build
            them with a **shared** :class:`ServeMetrics` so the pool reads
            as one ``jimm_serve`` namespace; the pool adds per-model
            dispatch counters on top.
        default: name routed when a request names no model.
    """

    def __init__(self, engines: dict, *, default: str):
        if default not in engines:
            raise ValueError(f"default model {default!r} not in pool "
                             f"({sorted(engines)})")
        self._lock = threading.Lock()
        self._engines = dict(engines)
        self.default_name = default
        self._resident_bytes: dict[str, int] = {}
        metrics = engines[default].metrics
        for name, engine in engines.items():
            metrics.inc(f"model_{name}_requests_total", 0)
            self._track_bytes(name, engine)
        metrics.bind_gauge(
            "pool_resident_bytes",
            lambda: float(sum(self._resident_bytes.values())))

    def _track_bytes(self, name: str, engine) -> None:
        """Record a model's resident parameter bytes (from the engine's
        ``resident_param_bytes`` attribute, stamped at build time or via
        :meth:`set_resident_bytes`) and expose the
        ``pool_resident_bytes_{model}`` gauge. The gauge closure reads the
        dict, so swap/remove update the scrape without rebinding."""
        self._resident_bytes[name] = int(
            getattr(engine, "resident_param_bytes", 0) or 0)
        self.metrics.bind_gauge(
            f"pool_resident_bytes_{name}",
            lambda n=name: float(self._resident_bytes.get(n, 0)))

    # -- routing ----------------------------------------------------------

    @property
    def metrics(self):
        """The pool's shared metrics surface (the default engine's)."""
        return self._engines[self.default_name].metrics

    @property
    def default(self):
        return self._engines[self.default_name]

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._engines)

    def engines(self) -> list:
        with self._lock:
            return list(self._engines.values())

    def get(self, model: str | None):
        """The engine serving ``model`` (None -> default). Unknown names
        are a client error, not a server fault."""
        with self._lock:
            if model is None:
                engine = self._engines[self.default_name]
                name = self.default_name
            else:
                engine = self._engines.get(model)
                name = model
            if engine is None:
                raise RequestError(
                    f"unknown model {model!r} (resident: "
                    f"{sorted(self._engines)})")
        engine.metrics.inc(f"model_{name}_requests_total")
        return engine

    # -- residency management (operator plane) ----------------------------

    def add(self, name: str, engine) -> None:
        """Make a warmed, started engine resident under a new name."""
        with self._lock:
            if name in self._engines:
                raise ValueError(f"model {name!r} already resident; "
                                 "use swap()")
            self._engines[name] = engine
        engine.metrics.inc(f"model_{name}_requests_total", 0)
        self._track_bytes(name, engine)

    def swap(self, name: str, engine):
        """Weight hot-swap: atomically route ``name`` to ``engine`` and
        return the previous engine (caller drains/stops it). The new
        engine must already be warm — the swap itself never compiles."""
        with self._lock:
            if name not in self._engines:
                raise ValueError(f"model {name!r} not resident; use add()")
            old = self._engines[name]
            self._engines[name] = engine
        self._track_bytes(name, engine)
        return old

    def remove(self, name: str):
        """Evict a resident model (the default cannot be evicted) and
        return its engine for the caller to stop."""
        with self._lock:
            if name == self.default_name:
                raise ValueError("cannot remove the default model")
            if name not in self._engines:
                raise ValueError(f"model {name!r} not resident")
            self._resident_bytes.pop(name, None)
            return self._engines.pop(name)

    def set_resident_bytes(self, name: str, nbytes: int) -> None:
        """Operator override for a model's resident parameter bytes (for
        engines built before byte stamping, or quantized twins whose
        packed layout the builder can't see)."""
        with self._lock:
            if name not in self._engines:
                raise ValueError(f"model {name!r} not resident")
            self._resident_bytes[name] = int(nbytes)

    def resident_bytes(self) -> dict[str, int]:
        """Per-model resident parameter bytes (autoscaler residency input)."""
        with self._lock:
            return dict(self._resident_bytes)

    # -- surfaces ---------------------------------------------------------

    def describe(self) -> dict:
        """healthz ``models`` block: per-model buckets/dtype/warm-start
        provenance and dispatch counts."""
        with self._lock:
            items = sorted(self._engines.items())
        out = {}
        for name, engine in items:
            row = {"default": name == self.default_name,
                   "buckets": list(engine.buckets.sizes),
                   # serving precision rides the bucket table, not the
                   # engine (whose dtype is batch assembly, always f32)
                   "dtype": engine.buckets.dtype,
                   "resident_param_bytes": self._resident_bytes.get(name, 0),
                   "requests": engine.metrics.count(
                       f"model_{name}_requests_total")}
            report = getattr(engine, "warmup_report", None)
            if report:
                row["warmup"] = {str(k): v["source"]
                                 for k, v in sorted(report.items())}
            out[name] = row
        return out
