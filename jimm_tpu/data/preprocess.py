"""Host-side image preprocessing: native C++ fast path + numpy fallback.

The TPU compute path is XLA; this is the *host* runtime in front of it. The
C++ library (`native/preprocess.cpp`, built by `make -C native`) multithreads
the per-batch CPU work (uint8->float32 normalize, bilinear resize, center
crop) so input prep overlaps device compute instead of serializing with it
(the reference's input path is single-threaded numpy,
ref `examples/vit_training.py:45-57`). If the .so is absent every function
transparently falls back to an equivalent numpy implementation — results are
identical to ~1e-6.

Conventions: C-contiguous NHWC float32/uint8; resize uses half-pixel centers
(PIL / ``tf.image.resize`` semantics, not ``jax.image.resize``'s default).
"""

from __future__ import annotations

import ctypes
import os
from pathlib import Path

import numpy as np

#: CLIP / SigLIP standard normalization constants.
IMAGENET_MEAN = np.asarray([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.asarray([0.229, 0.224, 0.225], np.float32)
CLIP_MEAN = np.asarray([0.48145466, 0.4578275, 0.40821073], np.float32)
CLIP_STD = np.asarray([0.26862954, 0.26130258, 0.27577711], np.float32)
SIGLIP_MEAN = np.asarray([0.5, 0.5, 0.5], np.float32)
SIGLIP_STD = np.asarray([0.5, 0.5, 0.5], np.float32)

_I64 = ctypes.c_int64
_F32P = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
_U8P = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")


def _load_library() -> ctypes.CDLL | None:
    override = os.environ.get("JIMM_PREPROCESS_LIB")
    candidates = [override] if override else [
        str(Path(__file__).resolve().parents[2] / "native"
            / "libjimm_preprocess.so"),
    ]
    for path in candidates:
        if path and Path(path).exists():
            try:
                lib = ctypes.CDLL(path)
            except OSError:
                continue
            lib.jimm_u8_to_f32_normalize.argtypes = [
                _U8P, _F32P, _I64, _I64, _I64, _I64, _F32P, _F32P,
                ctypes.c_int]
            lib.jimm_f32_normalize.argtypes = [
                _F32P, _I64, _I64, _I64, _I64, _F32P, _F32P, ctypes.c_int]
            lib.jimm_resize_bilinear_f32.argtypes = [
                _F32P, _F32P, _I64, _I64, _I64, _I64, _I64, _I64,
                ctypes.c_int]
            lib.jimm_center_crop_f32.argtypes = [
                _F32P, _F32P, _I64, _I64, _I64, _I64, _I64, _I64,
                ctypes.c_int]
            if hasattr(lib, "jimm_image_info"):  # newer .so: image codecs
                lib.jimm_image_info.argtypes = [
                    ctypes.c_char_p, _I64, ctypes.POINTER(_I64),
                    ctypes.POINTER(_I64)]
                lib.jimm_image_info.restype = ctypes.c_int
                lib.jimm_decode_image.argtypes = [
                    ctypes.c_char_p, _I64, _U8P, _I64, _I64]
                lib.jimm_decode_image.restype = ctypes.c_int
                lib.jimm_has_image_codecs.restype = ctypes.c_int
            return lib
    return None


_LIB = _load_library()
_THREADS = int(os.environ.get("JIMM_PREPROCESS_THREADS",
                              min(8, os.cpu_count() or 1)))


def native_available() -> bool:
    return _LIB is not None


def native_codecs_available() -> bool:
    return (_LIB is not None and hasattr(_LIB, "jimm_has_image_codecs")
            and bool(_LIB.jimm_has_image_codecs()))


def decode_image_native(data: bytes) -> np.ndarray | None:
    """Decode JPEG/PNG bytes to uint8 [H, W, 3] RGB via the native library
    (libjpeg/libpng). Returns None whenever the native path can't or
    shouldn't take it — library not built, codecs absent, an image class the
    C side doesn't handle (alpha/palette/16-bit PNG, CMYK JPEG,
    decompression-bomb sizes), libjpeg warnings raised during header or
    scanline decode (truncated/padded bodies whose pixels are suspect), or
    outright corrupt bodies — so callers fall back to PIL, which makes the
    final accept/reject call. Warnings first raised at finish (e.g.
    'extraneous bytes before marker' from trailing junk, AFTER every
    scanline was produced) keep the native pixels: they are bit-identical
    to PIL's and skipping the re-decode is the point of the native path.
    Files PIL would also reject then raise in PIL, keeping existing
    skip-bad-record handlers working."""
    if not native_codecs_available():
        return None
    h, w = _I64(0), _I64(0)
    status = _LIB.jimm_image_info(data, len(data), ctypes.byref(h),
                                  ctypes.byref(w))
    if status != 0:
        return None  # needs-PIL (1) or not an image (2: caller will raise)
    out = np.empty((h.value, w.value, 3), np.uint8)
    if _LIB.jimm_decode_image(data, len(data), out, h.value, w.value) != 0:
        return None  # suspect (1) or corrupt (-1): let PIL decide
    return out


def _chanwise(arr: np.ndarray, c: int) -> np.ndarray:
    out = np.ascontiguousarray(np.broadcast_to(
        np.asarray(arr, np.float32), (c,)))
    return out


def to_float_normalized(images: np.ndarray, mean=SIGLIP_MEAN,
                        std=SIGLIP_STD) -> np.ndarray:
    """uint8 or float [B,H,W,C] -> float32, ``(x/255 - mean) / std`` (uint8)
    or ``(x - mean) / std`` (float input, assumed already in [0,1])."""
    b, h, w, c = images.shape
    mean = _chanwise(mean, c)
    std = _chanwise(std, c)
    if images.dtype == np.uint8:
        images = np.ascontiguousarray(images)
        out = np.empty(images.shape, np.float32)
        if _LIB is not None:
            _LIB.jimm_u8_to_f32_normalize(images, out, b, h, w, c, mean, std,
                                          _THREADS)
        else:
            out[...] = (images.astype(np.float32) / 255.0 - mean) / std
        return out
    out = np.array(images, np.float32, order="C")  # always a fresh copy
    if _LIB is not None:
        _LIB.jimm_f32_normalize(out, b, h, w, c, mean, std, _THREADS)
    else:
        out[...] = (out - mean) / std
    return out


def resize_bilinear(images: np.ndarray, size: tuple[int, int]) -> np.ndarray:
    """float32 [B,H,W,C] -> [B,size[0],size[1],C], half-pixel bilinear."""
    images = np.ascontiguousarray(images, np.float32)
    b, sh, sw, c = images.shape
    dh, dw = size
    if (sh, sw) == (dh, dw):
        return images
    out = np.empty((b, dh, dw, c), np.float32)
    if _LIB is not None:
        _LIB.jimm_resize_bilinear_f32(images, out, b, sh, sw, dh, dw, c,
                                      _THREADS)
        return out
    # numpy fallback: gather the four corners with precomputed weights
    ys = np.maximum((np.arange(dh, dtype=np.float32) + 0.5) * (sh / dh) - 0.5,
                    0.0)
    xs = np.maximum((np.arange(dw, dtype=np.float32) + 0.5) * (sw / dw) - 0.5,
                    0.0)
    y0 = np.minimum(ys.astype(np.int64), sh - 1)
    x0 = np.minimum(xs.astype(np.int64), sw - 1)
    y1 = np.minimum(y0 + 1, sh - 1)
    x1 = np.minimum(x0 + 1, sw - 1)
    wy = (ys - y0).astype(np.float32)[None, :, None, None]
    wx = (xs - x0).astype(np.float32)[None, None, :, None]
    rows0, rows1 = images[:, y0], images[:, y1]
    top = rows0[:, :, x0] * (1 - wx) + rows0[:, :, x1] * wx
    bot = rows1[:, :, x0] * (1 - wx) + rows1[:, :, x1] * wx
    out[...] = top * (1 - wy) + bot * wy
    return out


def center_crop(images: np.ndarray, size: tuple[int, int]) -> np.ndarray:
    """float32 [B,H,W,C] -> centered [B,size[0],size[1],C]."""
    images = np.ascontiguousarray(images, np.float32)
    b, h, w, c = images.shape
    ch, cw = size
    if (h, w) == (ch, cw):
        return images
    if ch > h or cw > w:
        raise ValueError(f"crop {size} larger than image {(h, w)}")
    if _LIB is not None:
        out = np.empty((b, ch, cw, c), np.float32)
        _LIB.jimm_center_crop_f32(images, out, b, h, w, ch, cw, c, _THREADS)
        return out
    y0, x0 = (h - ch) // 2, (w - cw) // 2
    return np.ascontiguousarray(images[:, y0:y0 + ch, x0:x0 + cw])


def preprocess_batch(images: np.ndarray, *, image_size: int,
                     mean=SIGLIP_MEAN, std=SIGLIP_STD,
                     crop: bool = False) -> np.ndarray:
    """Full inference-style pipeline: resize (shorter side or direct) ->
    optional center crop -> normalize. Input uint8/float [B,H,W,C]."""
    b, h, w, c = images.shape
    if images.dtype == np.uint8:
        if not crop and (h, w) == (image_size, image_size):
            # single fused multithreaded pass: u8 -> normalized f32
            return to_float_normalized(images, mean, std)
        # multithreaded u8 -> [0,1] f32 (mean 0 / std 1), then resize
        images = to_float_normalized(images, 0.0, 1.0)
    if crop and (h != w):
        scale = image_size / min(h, w)
        images = resize_bilinear(images, (round(h * scale), round(w * scale)))
        images = center_crop(images, (image_size, image_size))
    else:
        images = resize_bilinear(images, (image_size, image_size))
    return to_float_normalized(images, mean, std)
