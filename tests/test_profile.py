"""Trace capture + offline per-op analysis (no TensorBoard)."""

from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jimm_tpu.train.profile import op_stats, summarize, trace

FIXTURE_DIR = Path(__file__).parent / "fixtures" / "profile"


def test_trace_capture_and_analysis(tmp_path):
    x = jnp.asarray(np.random.RandomState(0).randn(256, 256), jnp.float32)

    @jax.jit
    def f(x):
        return jnp.tanh(x @ x) @ x

    f(x).block_until_ready()
    with trace(tmp_path):
        for _ in range(3):
            out = f(x)
        out.block_until_ready()

    stats = op_stats(tmp_path)
    assert stats, "no ops aggregated from the capture"
    assert sum(s.total_us for s in stats) > 0
    text = summarize(stats, top=5, steps=3)
    assert "device op time" in text and "by category" in text


class TestOpStatsFixture:
    """Offline analyzer over the checked-in tiny.trace.json.gz: two device
    pids (/device:TPU:0 and :1) each with an "XLA Ops" lane, a non-op
    "Steps" lane, a host python process, real ops (fusion.1 x2, copy.2) and
    one of every _NON_OP container-event shape."""

    def test_per_op_aggregation_on_default_device(self):
        stats = op_stats(FIXTURE_DIR)
        by_name = {s.name: s for s in stats}
        assert set(by_name) == {"fusion.1", "copy.2"}
        fu = by_name["fusion.1"]
        # both device-0 occurrences aggregated; the "Steps"-lane, device-1,
        # and host-process events with the same name do not leak in
        assert fu.count == 2
        assert fu.total_us == pytest.approx(200.0)
        assert fu.bytes_accessed == 2_000_000
        assert fu.category == "fusion"
        assert "fused_matmul" in fu.long_name
        # 2 MB in 200 us = 10 GB/s
        assert fu.gbps == pytest.approx(10.0)
        cp = by_name["copy.2"]
        assert (cp.count, cp.total_us, cp.category) == (1, 50.0, "copy")
        # sorted by descending total time
        assert [s.name for s in stats] == ["fusion.1", "copy.2"]

    def test_non_op_container_events_filtered(self):
        names = {s.name for s in op_stats(FIXTURE_DIR)}
        for filtered in ("jit_train_step", "while.4", "12345",
                         "SyncOnDone", "VitModule"):
            assert filtered not in names

    def test_device_selection(self):
        # device=1 sees only the second pid's single occurrence
        by_name = {s.name: s for s in op_stats(FIXTURE_DIR, device=1)}
        assert by_name["fusion.1"].total_us == pytest.approx(40.0)
        # device=None sums across devices (40 + 200), still no host events
        all_dev = {s.name: s for s in op_stats(FIXTURE_DIR, device=None)}
        assert all_dev["fusion.1"].total_us == pytest.approx(240.0)

    def test_summarize_renders(self):
        text = summarize(op_stats(FIXTURE_DIR), top=5)
        assert "device op time" in text
        assert "fusion.1" in text

    def test_missing_dir_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            op_stats(tmp_path)


def test_metrics_logger_tensorboard(tmp_path):
    """Scalar events written through the tensorboard package (no TF) read
    back with the right tags and values."""
    pytest.importorskip("tensorboard")
    from jimm_tpu.train.metrics import MetricsLogger

    logger = MetricsLogger(tensorboard_dir=tmp_path, print_every=0)
    logger.log(0, loss=2.5, note="skipped-non-numeric")
    logger.log(1, loss=1.25)
    logger.close()

    from tensorboard.backend.event_processing.event_file_loader import (
        EventFileLoader)
    from tensorboard.util.tensor_util import make_ndarray
    files = list(tmp_path.glob("events.out.tfevents.*"))
    assert len(files) == 1
    got = {}
    for ev in EventFileLoader(str(files[0])).Load():
        for v in getattr(ev.summary, "value", []):
            # the event-processing layer migrates simple_value -> tensor
            val = (float(make_ndarray(v.tensor))
                   if v.WhichOneof("value") == "tensor" else v.simple_value)
            got[(ev.step, v.tag)] = val
    assert got == {(0, "loss"): 2.5, (1, "loss"): 1.25}
