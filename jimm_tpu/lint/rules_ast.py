"""Layer-1 lint rules — pure ``ast``, no JAX import.

Rule catalog (see ``docs/static_analysis.md`` for the narrative version):

- **JL001** version-gated ``jax.config.update`` key used without a guard
  (the exact bug that bricked the seed suite's collection on JAX 0.4.x).
- **JL002** host-device sync inside jitted code: ``.item()``,
  ``float()``/``int()``/``bool()``/``np.asarray()`` on traced values, and
  Python ``if`` on a traced value (shape/dtype/``is None`` tests are static
  and exempt).
- **JL003** train-step-shaped jit (carries optimizer state) without
  ``donate_argnums``, and train-step builder calls without ``donate=`` in
  library code (tests are exempt — they exercise the default).
- **JL004** ``PartitionSpec`` axis names outside the canonical mesh-axis
  vocabulary (a typo'd axis silently shards nothing).
- **JL005** Pallas block/VMEM shapes that violate the TPU (8, 128)
  sublane/lane tiling or exceed the VMEM budget estimate.
- **JL006** blocking host sync (``.block_until_ready()``, ``np.asarray``,
  ``jax.device_get``, ``.item()``) inside an ``async def`` in serving code —
  it stalls the event loop that is supposed to keep coalescing batches;
  device waits belong in sync ``*_blocking`` helpers run via an executor.
- **JL007** bare ``print(`` in ``jimm_tpu/`` library code — telemetry
  belongs in the ``jimm_tpu.obs`` registry / ``MetricsLogger`` where it is
  structured, rate-limited, and exportable; CLI entry points
  (``cli.py``/``__main__.py``/``launch.py``) and scripts are exempt.
- **JL008** ``jax.jit`` / ``nnx.jit`` invoked (or a jit-decorated function
  defined) inside a loop body or per-request handler — every pass builds a
  fresh jit wrapper with an empty compile cache, so the work recompiles
  per iteration/request and defeats both bucket warmup and the AOT
  artifact store. Hoist the jit to module/init scope; tests are exempt.
- **JL009** hardcoded Pallas block-size literal (``block_q=128`` /
  ``block_k=...`` / ``block_rows=...``) at a call site outside
  ``jimm_tpu/ops/`` and ``jimm_tpu/tune/`` — a pinned int overrides the
  persistent autotuner (``jimm_tpu.tune.best_config``) for every shape and
  backend; leave the kwarg off (or pass ``None``) so tuned configs apply,
  or tune offline with ``jimm-tpu tune``. Tests are exempt; deliberate
  pins carry a ``# jaxlint: disable=JL009`` justification.
- **JL010** ``jax.device_put`` without an explicit placement (no second
  positional argument and no ``device=``/``sharding=`` kwarg) in
  ``serve/`` or ``parallel/`` code — an unplaced put lands the array
  replicated on the default device, silently undoing the submesh layout
  every replica forward depends on (mismatched-layout retrace or a wrong-
  device transfer per call). Pass the target ``NamedSharding`` (or
  device); deliberate default placements carry a
  ``# jaxlint: disable=JL010`` justification.
- **JL011** host-side full sort over array data (``np.argsort`` /
  ``np.sort`` / ``jnp.sort`` variants, or ``sorted()`` over a value that
  came off a device) in ``serve/`` or ``retrieval/`` hot paths — an O(N
  log N) host sort over a corpus-sized array is the exact anti-pattern
  the streaming top-k exists to avoid: score selection belongs on device
  via ``jax.lax.top_k``; host-side *final merges* over bounded candidate
  sets use ``np.lexsort`` (which is why lexsort is not banned).
  Deliberate host sorts carry a ``# jaxlint: disable=JL011``
  justification.
- **JL012** silent float32/float64 upcast (``.astype(jnp.float32)`` /
  ``jax.lax.convert_element_type(x, jnp.float32)``) in quantized ops
  code outside a ``*dequant*``/``*quantize*``-named function — the int8
  fast path wins by keeping operands int8 until the one fused dequant
  at the accumulator; a stray upcast anywhere else re-materializes f32
  tiles in VMEM and silently hands the MXU a f32 matmul. Rescales live
  in ``_dequant``-style helpers (docs/quantization.md); deliberate
  upcasts carry a ``# jaxlint: disable=JL012`` justification.
- **JL013** broad exception swallowed silently (``except Exception:
  pass``, bare ``except:``, or ``except BaseException:`` with a
  pass-only body) in ``serve/``, ``train/``, or ``resilience/`` library
  code — these are the paths whose failures the supervisor, the replica
  watchdog, and the preemption handler exist to SEE; a silent swallow
  turns worker death into a hang and a corrupt checkpoint into a cold
  start. Handle it, log it, or narrow the except; deliberate best-effort
  swallows carry a ``# jaxlint: disable=JL013`` justification. Tests are
  exempt.
- **JL014** unbounded request-keyed table growth in ``serve/`` library
  code: a ``self.<table>[<param>] = ...`` (or ``.setdefault(<param>,
  ...)``) where the key comes from a caller-supplied parameter and the
  class never evicts from that table (``.pop``/``.popitem``/``.clear``/
  ``del``). A per-tenant/per-model dict keyed by whatever clients send is
  a memory leak an adversary controls — one request per invented name
  grows the table forever. Key runtime state by *configuration* (the
  policy file's tenant names, the pool's operator-built model table) and
  map unknown ids onto one shared default slot, or give the table an
  eviction path; deliberate bounded tables carry a
  ``# jaxlint: disable=JL014`` justification. Tests are exempt.
- **JL015** structured event emitted as a bare ``print(json.dumps(...))``
  (or a print concatenating/formatting a ``json.dumps`` result) in
  ``serve/``, ``train/``, or ``resilience/`` code — ad-hoc JSON on stdout
  has no sequence number, no timestamp, no correlation id, and no
  crash-safe file behind it, so the incident chain the flight recorder
  reconstructs (fault → fence → heal → replan) silently loses the event.
  Emit through ``jimm_tpu.obs.journal`` instead; CLI entry points
  (``cli.py``/``__main__.py``/``launch.py``) keep their sanctioned
  parseable ready-lines, and deliberate console sinks carry a
  ``# jaxlint: disable=JL015`` justification. Tests are exempt.
- **JL016** bare low-precision cast (``.astype(jnp.float8_e4m3fn)`` /
  ``.astype(jnp.float8_e5m2)`` / ``.astype(jnp.int8)`` or the
  ``convert_element_type`` spelling) in ``ops/`` or ``train/`` code
  outside a ``*quantize*``/``*scale*``-named function — a narrow-format
  cast without an explicit scale silently saturates (e4m3 tops out at
  448, int8 at 127): nothing crashes, the tensor just loses its top
  octaves and training quality decays untraceably. Quantization lives in
  the scaling helpers (``quantize_tensor`` / ``quantize_rows`` /
  ``dynamic_scale``, docs/quantization.md) where amax -> scale -> clip
  -> cast travel together; expression-derived dtypes
  (``x.astype(k.dtype)``) stay legal, and deliberate unscaled casts
  carry a ``# jaxlint: disable=JL016`` justification. Tests are exempt.
- **JL021** numeric confidence-threshold literal in ``serve/cascade/``
  code outside the calibration module — a threshold hardcoded into a
  router or autoscaler (``threshold = 0.92``, ``confidence=0.9``,
  ``conf >= 0.95``) silently overrides whatever was *fit* on a holdout
  set for the contracted disagreement rate, and drifts the moment the
  model, dtype twin, or traffic changes. Thresholds are data: fit them
  with ``jimm-tpu cascade calibrate`` and load the content-addressed
  artifact (``load_calibration``); only ``calibrate.py`` (where fitting
  lives) and tests may spell threshold numbers. Deliberate literals
  carry a ``# jaxlint: disable=JL021`` justification.
- **JL022** direct ``jax.profiler.start_trace`` / ``stop_trace`` call
  outside ``jimm_tpu/obs/prof/`` — the runtime supports ONE active
  profiler session per process, and the continuous capture ring
  (``--prof-ring`` / ``--prof-dir``) may be holding it at any moment: a
  second ``start_trace`` raises mid-incident, exactly when the capture
  mattered. All session control lives behind the ring's session lock —
  one-shot traces go through
  ``jimm_tpu.obs.prof.capture.profiler_session`` (or
  ``train.profile.trace``), anomaly captures through
  ``CaptureManager.trigger``. Tests are exempt; deliberate direct calls
  carry a ``# jaxlint: disable=JL022`` justification.
- **JL024** dense score materialization or full-KV ``all_gather`` inside
  ``parallel/seqpar`` — the sequence-parallel ring's contract is that no
  device ever holds the full sequence: KV chunks move peer-to-peer via
  ``ppermute`` (O(local) memory per hop) and scores exist only one
  chunk-pair tile at a time inside per-hop helpers. An ``all_gather``
  reassembles the full KV on every device (memory scales with S again,
  exactly what the seq axis was bought to avoid), and a score-shaped
  ``einsum`` (output keeping a free sequence letter from each operand)
  outside a ``*hop*``-named function is the full ``(S, S)`` matrix.
  Deliberate gathers carry a ``# jaxlint: disable=JL024`` justification.
"""

from __future__ import annotations

import ast

from jimm_tpu.lint.core import ERROR, WARNING, Finding

#: jax.config keys that only exist on some JAX lines — using one unguarded
#: makes the import/startup path crash on the other lines. Extend this table
#: as new gated keys enter the codebase.
VERSION_GATED_CONFIG_KEYS: dict[str, str] = {
    "jax_num_cpu_devices": "JAX >= 0.5 (0.4.x: XLA_FLAGS "
                           "--xla_force_host_platform_device_count)",
}

#: canonical physical mesh-axis vocabulary. Mirrors
#: ``jimm_tpu.parallel.mesh.MESH_AXES`` — duplicated here so layer 1 never
#: imports JAX; ``tests/test_lint.py`` asserts the two stay in sync.
CANONICAL_MESH_AXES = frozenset({"data", "model", "replica", "seq", "stage"})

#: parameter names that mark a jitted function as a train step carrying
#: optimizer state (JL003)
OPTIMIZER_PARAM_NAMES = frozenset({"optimizer", "opt", "opt_state",
                                   "optimizer_state"})

TRAIN_STEP_BUILDERS = frozenset({"make_classifier_train_step",
                                 "make_contrastive_train_step"})

#: attribute reads on a traced value that are static at trace time (inspect
#: metadata, not data) — branching on them is fine
STATIC_ATTRS = frozenset({"shape", "ndim", "dtype", "size", "sharding"})

DEFAULT_VMEM_BUDGET = 16 * 1024 * 1024  # bytes; ~v5e per-core VMEM

_DTYPE_BYTES = {"float64": 8, "int64": 8, "float32": 4, "int32": 4,
                "uint32": 4, "bfloat16": 2, "float16": 2, "int16": 2,
                "int8": 1, "uint8": 1, "bool_": 1}


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _annotate_parents(tree: ast.AST) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._jaxlint_parent = node  # type: ignore[attr-defined]


def _parent(node: ast.AST) -> ast.AST | None:
    return getattr(node, "_jaxlint_parent", None)


def _dotted(node: ast.AST) -> str | None:
    """``jax.config.update``-style dotted name for Name/Attribute chains."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jit_expr(node: ast.AST) -> bool:
    name = _dotted(node)
    if name is None:
        return False
    return name == "jit" or name.endswith(".jit")


def _jit_decorator(dec: ast.expr) -> ast.expr | None:
    """The decorator expression if it jit-wraps the function: ``@jit``,
    ``@jax.jit`` / ``@nnx.jit``, ``@jit(...)``, ``@partial(jit, ...)``."""
    if _is_jit_expr(dec):
        return dec
    if isinstance(dec, ast.Call):
        if _is_jit_expr(dec.func):
            return dec
        fname = _dotted(dec.func)
        if fname in ("partial", "functools.partial") and dec.args \
                and _is_jit_expr(dec.args[0]):
            return dec
    return None


def _decorator_keywords(dec: ast.expr) -> set[str]:
    if isinstance(dec, ast.Call):
        return {kw.arg for kw in dec.keywords if kw.arg}
    return set()


def _jitted_functions(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                jd = _jit_decorator(dec)
                if jd is not None:
                    yield node, jd
                    break


# ---------------------------------------------------------------------------
# JL001 — version-gated config key without a guard
# ---------------------------------------------------------------------------

def _is_guarded(node: ast.AST) -> bool:
    """True when an ancestor try/except catches AttributeError (or broader),
    or an ancestor ``if`` gates on ``hasattr``/``__version__``."""
    cur: ast.AST | None = node
    while cur is not None:
        parent = _parent(cur)
        if isinstance(parent, ast.Try) and cur in parent.body:
            for handler in parent.handlers:
                if handler.type is None:
                    return True
                names = [_dotted(t) for t in (
                    handler.type.elts if isinstance(handler.type, ast.Tuple)
                    else [handler.type])]
                if any(n in ("AttributeError", "Exception") for n in names):
                    return True
        if isinstance(parent, ast.If):
            test_src = ast.dump(parent.test)
            if "hasattr" in test_src or "__version__" in test_src:
                return True
        cur = parent
    return False


def check_version_gated_config(tree: ast.AST, path: str) -> list[Finding]:
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fname = _dotted(node.func)
        if fname is None or not fname.endswith("config.update"):
            continue
        if not node.args or not isinstance(node.args[0], ast.Constant):
            continue
        key = node.args[0].value
        if key not in VERSION_GATED_CONFIG_KEYS:
            continue
        if _is_guarded(node):
            continue
        findings.append(Finding(
            "JL001", ERROR, path, node.lineno,
            f"jax.config.update({key!r}, ...) is version-gated "
            f"({VERSION_GATED_CONFIG_KEYS[key]}) but has no "
            f"try/except AttributeError or hasattr guard"))
    return findings


# ---------------------------------------------------------------------------
# JL002 — host-device sync inside jitted code
# ---------------------------------------------------------------------------

def _only_static_uses(value: ast.expr, tainted: set[str]) -> bool:
    """True when every tainted name in ``value`` is reached only through a
    static-metadata attribute (``x.dtype``, ``x.shape``, ...) or
    ``len``/``isinstance`` — such an expression is trace-time static, so
    a local assigned from it (``dtype = x.dtype``) must NOT be tainted:
    branching on it later is as legal as branching on ``x.dtype``
    directly."""
    found_any = False
    for node in ast.walk(value):
        if not (isinstance(node, ast.Name) and node.id in tainted):
            continue
        found_any = True
        parent = _parent(node)
        if isinstance(parent, ast.Attribute) and parent.attr in STATIC_ATTRS:
            continue
        if isinstance(parent, ast.Call) and _dotted(parent.func) in (
                "len", "isinstance"):
            continue
        return False
    return found_any


def _tainted_names(fn: ast.FunctionDef) -> set[str]:
    """Function parameters plus locals assigned from expressions that use
    them — a one-pass, forward-only approximation of 'traced value'.
    Locals assigned purely from static metadata of traced values
    (``dtype = x.dtype``; ``n = len(x)``) stay untainted."""
    args = fn.args
    tainted = {a.arg for a in (args.posonlyargs + args.args + args.kwonlyargs)
               if a.arg not in ("self", "cls")}
    for a in (args.vararg, args.kwarg):
        if a is not None:
            tainted.add(a.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and any(
                isinstance(n, ast.Name) and n.id in tainted
                for n in ast.walk(node.value)):
            if _only_static_uses(node.value, tainted):
                continue
            for target in node.targets:
                for t in ast.walk(target):
                    if isinstance(t, ast.Name):
                        tainted.add(t.id)
    return tainted


def _mentions_tainted(node: ast.AST, tainted: set[str]) -> bool:
    return any(isinstance(n, ast.Name) and n.id in tainted
               for n in ast.walk(node))


def _branch_is_static(test: ast.expr, tainted: set[str]) -> bool:
    """True for trace-time-static branch tests: ``is (not) None``,
    ``isinstance``, and tests that touch traced values only through static
    metadata attributes (``.shape``/``.ndim``/``.dtype``/``len()``)."""
    if isinstance(test, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
        return True
    for node in ast.walk(test):
        if not (isinstance(node, ast.Name) and node.id in tainted):
            continue
        parent = _parent(node)
        if isinstance(parent, ast.Attribute) and parent.attr in STATIC_ATTRS:
            continue
        if isinstance(parent, ast.Call) and _dotted(parent.func) in (
                "len", "isinstance"):
            continue
        # raw traced value in the test
        return False
    return True


def check_host_sync_in_jit(tree: ast.AST, path: str) -> list[Finding]:
    findings = []
    for fn, _dec in _jitted_functions(tree):
        tainted = _tainted_names(fn)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                fname = _dotted(node.func)
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "item" and not node.args:
                    findings.append(Finding(
                        "JL002", ERROR, path, node.lineno,
                        f".item() inside jitted `{fn.name}` forces a "
                        f"host-device sync"))
                elif fname in ("float", "int", "bool") and node.args \
                        and _mentions_tainted(node.args[0], tainted):
                    findings.append(Finding(
                        "JL002", ERROR, path, node.lineno,
                        f"{fname}() on a traced value inside jitted "
                        f"`{fn.name}` forces a host-device sync"))
                elif fname in ("np.asarray", "np.array", "numpy.asarray",
                               "numpy.array", "onp.asarray") and node.args \
                        and _mentions_tainted(node.args[0], tainted):
                    findings.append(Finding(
                        "JL002", ERROR, path, node.lineno,
                        f"{fname}() on a traced value inside jitted "
                        f"`{fn.name}` copies device data to host"))
            elif isinstance(node, ast.If) \
                    and _mentions_tainted(node.test, tainted) \
                    and not _branch_is_static(node.test, tainted):
                findings.append(Finding(
                    "JL002", ERROR, path, node.lineno,
                    f"Python `if` on a traced value inside jitted "
                    f"`{fn.name}` — use jnp.where/lax.cond"))
    return findings


# ---------------------------------------------------------------------------
# JL003 — train-step jit without donation
# ---------------------------------------------------------------------------

def _path_is_test(path: str) -> bool:
    base = path.replace("\\", "/").rsplit("/", 1)[-1]
    return base.startswith("test_") or base == "conftest.py"


def check_train_step_donation(tree: ast.AST, path: str) -> list[Finding]:
    findings = []
    for fn, dec in _jitted_functions(tree):
        params = {a.arg for a in fn.args.posonlyargs + fn.args.args
                  + fn.args.kwonlyargs}
        if not params & OPTIMIZER_PARAM_NAMES:
            continue
        if not _decorator_keywords(dec) & {"donate_argnums", "donate",
                                           "donate_argnames"}:
            findings.append(Finding(
                "JL003", ERROR, path, fn.lineno,
                f"jitted train step `{fn.name}` carries optimizer state "
                f"({sorted(params & OPTIMIZER_PARAM_NAMES)}) without "
                f"donate_argnums — params/m/v double-buffer in HBM"))
    if not _path_is_test(path):
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fname = _dotted(node.func)
            if fname is None:
                continue
            if fname.rsplit(".", 1)[-1] not in TRAIN_STEP_BUILDERS:
                continue
            if any(kw.arg == "donate" for kw in node.keywords):
                continue
            findings.append(Finding(
                "JL003", ERROR, path, node.lineno,
                f"{fname}(...) without donate= leaves donation off on a "
                f"training hot path; pass donate=True (or donate=False "
                f"with a reason)"))
    return findings


# ---------------------------------------------------------------------------
# JL004 — PartitionSpec axis vocabulary
# ---------------------------------------------------------------------------

def _spec_strings(args: list[ast.expr]):
    for arg in args:
        for node in ast.walk(arg):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                yield node


def check_partition_spec_axes(tree: ast.AST, path: str) -> list[Finding]:
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fname = _dotted(node.func)
        if fname is None:
            continue
        if fname != "P" and fname.rsplit(".", 1)[-1] != "PartitionSpec":
            continue
        for s in _spec_strings(list(node.args)):
            if s.value not in CANONICAL_MESH_AXES:
                findings.append(Finding(
                    "JL004", ERROR, path, s.lineno,
                    f"PartitionSpec axis {s.value!r} is not a canonical "
                    f"mesh axis {sorted(CANONICAL_MESH_AXES)} — typo'd "
                    f"axes silently shard nothing"))
    return findings


# ---------------------------------------------------------------------------
# JL005 — Pallas tiling / VMEM budget
# ---------------------------------------------------------------------------

def _module_int_constants(tree: ast.AST) -> dict[str, int]:
    consts: dict[str, int] = {}
    body = getattr(tree, "body", [])
    for node in body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            val = _resolve_int(node.value, consts)
            if val is not None:
                consts[node.targets[0].id] = val
    return consts


def _resolve_int(node: ast.expr, consts: dict[str, int]) -> int | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = _resolve_int(node.operand, consts)
        return None if v is None else -v
    if isinstance(node, ast.BinOp):
        left = _resolve_int(node.left, consts)
        right = _resolve_int(node.right, consts)
        if left is None or right is None:
            return None
        try:
            if isinstance(node.op, ast.Add):
                return left + right
            if isinstance(node.op, ast.Sub):
                return left - right
            if isinstance(node.op, ast.Mult):
                return left * right
            if isinstance(node.op, ast.FloorDiv):
                return left // right
            if isinstance(node.op, ast.Mod):
                return left % right
            if isinstance(node.op, ast.Pow):
                return left ** right
        except (ZeroDivisionError, OverflowError, ValueError):
            return None
    return None


def _dtype_bytes(node: ast.expr | None) -> int:
    name = (_dotted(node) or "") if node is not None else ""
    leaf = name.rsplit(".", 1)[-1]
    return _DTYPE_BYTES.get(leaf, 4)


def _check_shape(dims: list[int | None], bytes_per_elem: int, budget: int,
                 path: str, lineno: int, what: str) -> list[Finding]:
    findings = []
    if dims and dims[-1] is not None and dims[-1] != 1 \
            and dims[-1] % 128 != 0:
        findings.append(Finding(
            "JL005", ERROR, path, lineno,
            f"{what} last dim {dims[-1]} is not a multiple of the 128-lane "
            f"TPU tile — the Mosaic pad wastes VMEM and VPU lanes"))
    if len(dims) >= 2 and dims[-2] is not None and dims[-2] != 1 \
            and dims[-2] % 8 != 0:
        findings.append(Finding(
            "JL005", ERROR, path, lineno,
            f"{what} second-minor dim {dims[-2]} is not a multiple of the "
            f"8-sublane TPU tile"))
    if all(d is not None for d in dims) and dims:
        total = bytes_per_elem
        for d in dims:
            total *= d  # type: ignore[operator]
        if total > budget:
            findings.append(Finding(
                "JL005", ERROR, path, lineno,
                f"{what} is {total / 2**20:.1f} MiB, over the "
                f"{budget / 2**20:.1f} MiB VMEM budget (tune with "
                f"--vmem-budget)"))
    return findings


def check_pallas_tiling(tree: ast.AST, path: str,
                        vmem_budget: int | None = None) -> list[Finding]:
    budget = vmem_budget if vmem_budget is not None else DEFAULT_VMEM_BUDGET
    consts = _module_int_constants(tree)
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fname = _dotted(node.func)
        leaf = fname.rsplit(".", 1)[-1] if fname else None
        if leaf == "BlockSpec":
            for arg in node.args:
                if isinstance(arg, ast.Tuple):
                    dims = [_resolve_int(e, consts) for e in arg.elts]
                    findings.extend(_check_shape(
                        dims, 4, budget, path, node.lineno,
                        "BlockSpec block shape"))
                    break  # one shape tuple per BlockSpec
        elif leaf in ("VMEM", "SMEM") and fname and "." in fname:
            if node.args and isinstance(node.args[0], ast.Tuple):
                dims = [_resolve_int(e, consts)
                        for e in node.args[0].elts]
                dtype = node.args[1] if len(node.args) > 1 else None
                if leaf == "VMEM":
                    findings.extend(_check_shape(
                        dims, _dtype_bytes(dtype), budget, path,
                        node.lineno, "VMEM scratch shape"))
    return findings


# ---------------------------------------------------------------------------
# JL006 — blocking host sync on the serve event loop
# ---------------------------------------------------------------------------

#: dotted call names that materialize device data on host (block the caller)
HOST_SYNC_CALLS = frozenset({"np.asarray", "np.array", "numpy.asarray",
                             "numpy.array", "onp.asarray", "jax.device_get",
                             "device_get"})


def _path_is_serve(path: str) -> bool:
    parts = path.replace("\\", "/").split("/")
    return "serve" in parts or parts[-1] == "serve.py"


def check_async_host_sync(tree: ast.AST, path: str) -> list[Finding]:
    """JL006: in serving code, ``async def`` bodies run on the engine's
    event loop — the thing that must stay free to coalesce batches. A
    blocking host sync there stalls every in-flight request. Sync helper
    functions (run via ``run_in_executor``) are the sanctioned home for
    device waits, so nested sync ``def``/``lambda`` bodies are exempt."""
    if not _path_is_serve(path):
        return []
    findings = []
    for fn in ast.walk(tree):
        if isinstance(fn, ast.AsyncFunctionDef):
            findings += _scan_async_body(fn, path)
    return findings


def _scan_async_body(fn: ast.AsyncFunctionDef, path: str) -> list[Finding]:
    findings = []
    stack: list[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue  # different execution context (executor helpers / the
            #           outer walk already visits nested async defs)
        if isinstance(node, ast.Call):
            fname = _dotted(node.func)
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "block_until_ready":
                findings.append(Finding(
                    "JL006", ERROR, path, node.lineno,
                    f".block_until_ready() inside async `{fn.name}` blocks "
                    f"the serve event loop — move the device wait into a "
                    f"sync *_blocking helper run via run_in_executor"))
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "item" and not node.args:
                findings.append(Finding(
                    "JL006", ERROR, path, node.lineno,
                    f".item() inside async `{fn.name}` forces a host sync "
                    f"on the serve event loop — read results in a sync "
                    f"*_blocking helper run via run_in_executor"))
            elif fname in HOST_SYNC_CALLS:
                findings.append(Finding(
                    "JL006", ERROR, path, node.lineno,
                    f"{fname}() inside async `{fn.name}` can block the "
                    f"serve event loop on a device transfer — do host "
                    f"materialization in a sync *_blocking helper run via "
                    f"run_in_executor"))
        stack.extend(ast.iter_child_nodes(node))
    return findings


# ---------------------------------------------------------------------------
# JL007 — bare print() in library code
# ---------------------------------------------------------------------------

#: basenames where print IS the product (user-facing command entry points)
PRINT_EXEMPT_BASENAMES = frozenset({"cli.py", "__main__.py", "launch.py"})


def _path_is_library(path: str) -> bool:
    """True for files inside the ``jimm_tpu`` package that are not command
    entry points (scripts/ and tests/ are outside the package entirely)."""
    parts = path.replace("\\", "/").split("/")
    return "jimm_tpu" in parts[:-1] \
        and parts[-1] not in PRINT_EXEMPT_BASENAMES


def check_bare_print(tree: ast.AST, path: str) -> list[Finding]:
    """JL007: library code must not ``print`` — a stray print per step is
    unstructured, unrateable console spam that bypasses every exporter.
    Route output through ``jimm_tpu.obs`` (registry/span) or
    ``train.metrics.MetricsLogger``; a deliberate console sink carries a
    ``# jaxlint: disable=JL007`` justification."""
    if not _path_is_library(path):
        return []
    findings = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id == "print":
            findings.append(Finding(
                "JL007", ERROR, path, node.lineno,
                "bare print() in library code — log through the "
                "jimm_tpu.obs registry or MetricsLogger (CLI modules and "
                "scripts are exempt; suppress deliberate console sinks "
                "with # jaxlint: disable=JL007)"))
    return findings


# ---------------------------------------------------------------------------
# JL008 — jit inside a loop body or per-request handler
# ---------------------------------------------------------------------------

#: method names that handle one network request per call
#: (http.server's do_VERB convention; add as serving grows transports)
REQUEST_HANDLER_NAMES = frozenset({"do_GET", "do_POST", "do_PUT",
                                   "do_DELETE", "do_HEAD"})


def _enclosing_loop(node: ast.AST) -> ast.AST | None:
    """The innermost For/While/AsyncFor whose *body* (not iter/test)
    contains ``node``, without crossing a function boundary — a jit inside
    a ``def`` that merely sits in a loop runs once per call, not per
    iteration of the outer loop."""
    cur: ast.AST | None = node
    while cur is not None:
        parent = _parent(cur)
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda)):
            return None
        if isinstance(parent, (ast.For, ast.AsyncFor, ast.While)) \
                and cur not in (getattr(parent, "iter", None),
                                getattr(parent, "test", None)):
            return parent
        cur = parent
    return None


def _enclosing_handler(node: ast.AST, path: str) -> str | None:
    """Name of the per-request handler ``node`` sits in, if any: a
    ``do_VERB`` method anywhere, or any ``async def`` in serving code
    (the engine's event-loop coroutines each run per request/batch)."""
    cur: ast.AST | None = _parent(node)
    while cur is not None:
        if isinstance(cur, ast.FunctionDef) \
                and cur.name in REQUEST_HANDLER_NAMES:
            return cur.name
        if isinstance(cur, ast.AsyncFunctionDef) and _path_is_serve(path):
            return cur.name
        cur = _parent(cur)
    return None


def check_jit_in_loop(tree: ast.AST, path: str) -> list[Finding]:
    """JL008: a ``jit`` call in a loop body or request handler makes a new
    wrapper (and a cold compile cache) every pass — the exact recompile
    hazard bucket warmup and the AOT store exist to eliminate. Tests are
    exempt: they intentionally construct jits per-case."""
    if _path_is_test(path):
        return []
    findings = []
    seen_lines: set[int] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and _is_jit_expr(node.func)):
            continue
        where = None
        if _enclosing_loop(node) is not None:
            where = "a loop body"
        else:
            handler = _enclosing_handler(node, path)
            if handler is not None:
                where = f"per-request handler `{handler}`"
        if where is None or node.lineno in seen_lines:
            continue
        seen_lines.add(node.lineno)
        fname = _dotted(node.func) or "jit"
        findings.append(Finding(
            "JL008", ERROR, path, node.lineno,
            f"{fname}(...) inside {where} builds a fresh wrapper (and "
            f"recompiles) every pass, defeating bucket warmup and the AOT "
            f"artifact store — hoist the jit to module or __init__ scope"))
    for fn, dec in _jitted_functions(tree):
        if _enclosing_loop(fn) is not None and fn.lineno not in seen_lines:
            seen_lines.add(fn.lineno)
            findings.append(Finding(
                "JL008", ERROR, path, fn.lineno,
                f"jit-decorated `{fn.name}` is defined inside a loop body "
                f"— each iteration makes a new function object with a cold "
                f"compile cache; define it once outside the loop"))
    return findings


# ---------------------------------------------------------------------------
# JL009 — hardcoded block-size literal bypasses the autotuner
# ---------------------------------------------------------------------------

#: kernel block kwargs the tune cache owns (``jimm_tpu.tune.api.KERNELS``)
TUNABLE_BLOCK_KWARGS = frozenset({"block_q", "block_k", "block_rows"})

#: package directories where explicit int blocks are the mechanism itself:
#: ops modules define the safe defaults, and the tuner's bench closures MUST
#: pass explicit ints (that is the no-recursion contract with best_config)
_BLOCK_LITERAL_EXEMPT_DIRS = frozenset({"ops", "tune"})


def _path_is_block_exempt(path: str) -> bool:
    parts = path.replace("\\", "/").split("/")
    if "jimm_tpu" in parts[:-1]:
        rel = parts[parts.index("jimm_tpu") + 1:-1]
        if _BLOCK_LITERAL_EXEMPT_DIRS & set(rel):
            return True
    return _path_is_test(path)


def check_block_size_literal(tree: ast.AST, path: str) -> list[Finding]:
    """JL009: a literal ``block_q=128``-style kwarg at a call site pins one
    block size for every shape, dtype, and TPU generation, silently masking
    whatever ``jimm_tpu.tune`` has measured as best. Call sites should omit
    the kwarg (ops resolve it through ``tune.best_config`` with a safe
    default); genuinely deliberate pins take a
    ``# jaxlint: disable=JL009`` with a reason."""
    if _path_is_block_exempt(path):
        return []
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        for kw in node.keywords:
            if kw.arg not in TUNABLE_BLOCK_KWARGS:
                continue
            if isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, int) \
                    and not isinstance(kw.value.value, bool):
                findings.append(Finding(
                    "JL009", ERROR, path, kw.value.lineno,
                    f"hardcoded {kw.arg}={kw.value.value} bypasses the "
                    f"persistent autotuner for every shape/backend — omit "
                    f"the kwarg so jimm_tpu.tune.best_config resolves it "
                    f"(tune offline with `jimm-tpu tune`), or justify the "
                    f"pin with # jaxlint: disable=JL009"))
    return findings


# ---------------------------------------------------------------------------
# JL010 — unplaced device_put in sharding-sensitive code
# ---------------------------------------------------------------------------

def _path_is_parallel(path: str) -> bool:
    parts = path.replace("\\", "/").split("/")
    return "parallel" in parts or parts[-1] == "parallel.py"


def check_device_put_placement(tree: ast.AST, path: str) -> list[Finding]:
    """JL010: in ``serve/`` and ``parallel/`` code every ``jax.device_put``
    must say *where* — a second positional argument or a ``device=``/
    ``sharding=`` kwarg. A bare put places the array on the default device,
    which in a multi-replica topology is some other replica's submesh: the
    sharded executable then either retraces for the mismatched layout or
    pays a cross-device transfer on every batch. Deliberate default
    placements carry a ``# jaxlint: disable=JL010`` justification."""
    if not (_path_is_serve(path) or _path_is_parallel(path)):
        return []
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fname = _dotted(node.func)
        if fname is None or fname.rsplit(".", 1)[-1] != "device_put":
            continue
        if len(node.args) >= 2:
            continue  # positional device/sharding
        if any(kw.arg in ("device", "sharding") for kw in node.keywords):
            continue
        findings.append(Finding(
            "JL010", ERROR, path, node.lineno,
            f"{fname}(...) without a device/sharding places the array on "
            f"the default device — in sharded serving that is the wrong "
            f"submesh (layout retrace or per-batch cross-device copy); "
            f"pass the replica's NamedSharding, or justify the default "
            f"placement with # jaxlint: disable=JL010"))
    return findings


# ---------------------------------------------------------------------------
# JL011 — host-side full sort in serving/retrieval hot paths
# ---------------------------------------------------------------------------

#: dotted sort calls that rank an entire array on host — O(N log N) on the
#: request path where the device's O(N) ``lax.top_k`` (plus a bounded-set
#: ``np.lexsort`` merge, deliberately absent from this list) is the contract
HOST_SORT_CALLS = frozenset({
    "np.argsort", "np.sort", "numpy.argsort", "numpy.sort",
    "jnp.argsort", "jnp.sort", "jax.numpy.argsort", "jax.numpy.sort",
})

#: calls whose results are host copies of (potentially corpus-sized) device
#: or numpy array data — seeds the taint that makes ``sorted()`` suspicious
ARRAY_SOURCE_CALLS = frozenset({"np.asarray", "np.array", "numpy.asarray",
                                "numpy.array", "jnp.asarray", "jnp.array",
                                "jax.device_get", "device_get"})


def _path_is_retrieval(path: str) -> bool:
    parts = path.replace("\\", "/").split("/")
    return "retrieval" in parts or parts[-1] == "retrieval.py"


def _array_tainted_names(scope: ast.AST) -> set[str]:
    """Names assigned (directly or transitively) from array-materializing
    calls inside ``scope`` — one forward pass, JL002-style."""
    tainted: set[str] = set()
    for node in ast.walk(scope):
        if not isinstance(node, ast.Assign):
            continue
        from_array = any(
            isinstance(n, ast.Call) and _dotted(n.func) in ARRAY_SOURCE_CALLS
            for n in ast.walk(node.value))
        if from_array or _mentions_tainted(node.value, tainted):
            for target in node.targets:
                # plain names (incl. tuple unpacking) only: a subscript or
                # attribute store does not make its container an array
                if isinstance(target, (ast.Tuple, ast.List)):
                    tainted.update(t.id for t in target.elts
                                   if isinstance(t, ast.Name))
                elif isinstance(target, ast.Name):
                    tainted.add(target.id)
    return tainted


def check_host_sort(tree: ast.AST, path: str) -> list[Finding]:
    """JL011: serving/retrieval hot paths must not full-sort on host. The
    banned calls rank every element of their input; over a device array
    that also forces the whole corpus through a transfer first. Selection
    runs on device (``jax.lax.top_k`` per block + streaming merge); only
    the bounded per-partition candidate merge belongs on host, and that is
    ``np.lexsort``'s job. Tests are exempt (oracles *should* argsort)."""
    if not (_path_is_serve(path) or _path_is_retrieval(path)) \
            or _path_is_test(path):
        return []
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fname = _dotted(node.func)
        if fname in HOST_SORT_CALLS:
            findings.append(Finding(
                "JL011", ERROR, path, node.lineno,
                f"{fname}() full-sorts on host in a serving/retrieval hot "
                f"path — rank on device with jax.lax.top_k (streaming "
                f"merge for big corpora); np.lexsort over a bounded "
                f"candidate set is the sanctioned host-side final merge, "
                f"or justify with # jaxlint: disable=JL011"))
    seen: set[int] = set()
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        tainted = _array_tainted_names(fn)
        if not tainted:
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Name) \
                    and node.func.id == "sorted" and node.args \
                    and _mentions_tainted(node.args[0], tainted) \
                    and node.lineno not in seen:
                seen.add(node.lineno)
                findings.append(Finding(
                    "JL011", ERROR, path, node.lineno,
                    f"sorted() over array-derived data in `{fn.name}` "
                    f"full-sorts on host in a serving/retrieval hot path "
                    f"— use jax.lax.top_k on device (np.lexsort for "
                    f"bounded final merges), or justify with "
                    f"# jaxlint: disable=JL011"))
    return findings


# ---------------------------------------------------------------------------
# JL012 — silent f32 upcast in quantized ops code
# ---------------------------------------------------------------------------

#: dtype leaf names whose appearance as an astype/convert target undoes the
#: int8 storage win (bf16 stays allowed: mixed-precision epilogues are fine)
_WIDE_FLOAT_DTYPES = frozenset({"float32", "float64"})

#: substrings that sanction an enclosing function as THE dequant site — the
#: one place per kernel where the int32 accumulator meets its scales
_DEQUANT_NAME_MARKS = ("dequant", "quantize")


def _path_is_quant_ops(path: str) -> bool:
    """Quantization code: anything under a ``quant/`` package, plus ops
    modules whose basename marks them as int8/quantized kernels."""
    parts = path.replace("\\", "/").split("/")
    if "quant" in parts[:-1]:
        return True
    base = parts[-1]
    return "ops" in parts[:-1] and ("int8" in base or "quant" in base)


def _wide_float_target(node: ast.expr) -> str | None:
    """The float32/float64 name if ``node`` denotes one (dotted name like
    ``jnp.float32`` or a ``"float32"`` string constant), else None."""
    name = _dotted(node)
    if name is not None:
        leaf = name.rsplit(".", 1)[-1]
        return leaf if leaf in _WIDE_FLOAT_DTYPES else None
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and node.value in _WIDE_FLOAT_DTYPES:
        return node.value
    return None


def _in_dequant_function(node: ast.AST) -> bool:
    cur: ast.AST | None = _parent(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)) and any(
                mark in cur.name for mark in _DEQUANT_NAME_MARKS):
            return True
        cur = _parent(cur)
    return False


def check_quant_upcast(tree: ast.AST, path: str) -> list[Finding]:
    """JL012: quantized ops keep everything int8 until the single fused
    dequant — that is the whole bandwidth/MXU win. An ``.astype(f32)`` or
    ``convert_element_type(x, f32)`` sprinkled anywhere else silently
    rebuilds full-width tiles, and nothing crashes: the kernel just stops
    being an int8 kernel. The sanctioned home for the rescale is a
    function whose name says so (``_dequant*`` / ``*quantize*``);
    deliberate upcasts elsewhere carry ``# jaxlint: disable=JL012``."""
    if not _path_is_quant_ops(path) or _path_is_test(path):
        return []
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        target = None
        how = None
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "astype" and node.args:
            target = _wide_float_target(node.args[0])
            how = f".astype({target})"
        else:
            fname = _dotted(node.func)
            if fname is not None \
                    and fname.rsplit(".", 1)[-1] == "convert_element_type" \
                    and len(node.args) >= 2:
                target = _wide_float_target(node.args[1])
                how = f"convert_element_type(..., {target})"
        if target is None or _in_dequant_function(node):
            continue
        findings.append(Finding(
            "JL012", ERROR, path, node.lineno,
            f"{how} in quantized ops code outside a dequant/quantize "
            f"helper silently re-materializes wide tiles and forfeits the "
            f"int8 MXU path — keep the rescale in the fused _dequant "
            f"epilogue (docs/quantization.md), or justify with "
            f"# jaxlint: disable=JL012"))
    return findings


# ---------------------------------------------------------------------------
# JL013 — silently swallowed broad exception in resilience-critical paths
# ---------------------------------------------------------------------------

def _path_is_resilient(path: str) -> bool:
    parts = path.replace("\\", "/").split("/")
    return bool({"serve", "train", "resilience"} & set(parts))


def check_swallowed_exception(tree: ast.AST, path: str) -> list[Finding]:
    """JL013: a broad except with a pass-only body in serve/train/
    resilience library code. The whole resilience design rests on failures
    being *observable* — the supervisor restarts on worker death, the
    replica watchdog fences a failing lane, the checkpoint fallback
    quarantines corrupt steps — and every one of those signals dies at an
    ``except Exception: pass``. Narrow excepts (``except OSError: pass``
    around a close()) stay legal: the rule targets the handlers broad
    enough to eat the failures the machinery above must see."""
    if not _path_is_resilient(path) or _path_is_test(path):
        return []
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        broad = node.type is None or (
            isinstance(node.type, ast.Name)
            and node.type.id in ("Exception", "BaseException"))
        if broad and all(isinstance(s, ast.Pass) for s in node.body):
            findings.append(Finding(
                "JL013", ERROR, path, node.lineno,
                "broad exception swallowed silently — in serve/train/"
                "resilience paths this hides worker death from the "
                "supervisor and the watchdog; handle, log, or narrow it "
                "(deliberate best-effort swallows carry a "
                "# jaxlint: disable=JL013 justification)"))
    return findings


# ---------------------------------------------------------------------------
# JL014 — unbounded request-keyed table growth in serving state
# ---------------------------------------------------------------------------

_EVICTION_METHODS = frozenset({"pop", "popitem", "popleft", "clear"})


def _self_attr_name(node: ast.AST) -> str | None:
    """``self.<attr>`` -> attr name (None for anything else)."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _evicted_attrs(cls: ast.ClassDef) -> set[str]:
    """Attributes of ``cls`` that have SOME eviction path anywhere in the
    class body: ``self.x.pop/popitem/popleft/clear(...)`` or
    ``del self.x[...]``. Presence of any eviction op is the evidence the
    table is managed, so every write to it stays legal."""
    evicted: set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _EVICTION_METHODS:
            attr = _self_attr_name(node.func.value)
            if attr is not None:
                evicted.add(attr)
        elif isinstance(node, ast.Delete):
            for target in node.targets:
                if isinstance(target, ast.Subscript):
                    attr = _self_attr_name(target.value)
                    if attr is not None:
                        evicted.add(attr)
    return evicted


def _names_in(node: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def check_unbounded_tenant_table(tree: ast.AST, path: str) -> list[Finding]:
    """JL014: serving state keyed by caller-supplied identifiers with no
    eviction. The QoS discipline is that runtime tables are bounded by
    *configuration* (policy-file tenants, the operator's model pool), not
    by traffic: anonymous/unknown ids share one default slot. This rule
    catches the regression where a handler quietly grows
    ``self.per_tenant[tenant_id]`` per request — unbounded memory an
    adversary can drive by inventing names."""
    if not _path_is_serve(path) or _path_is_test(path):
        return []
    findings = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        evicted = _evicted_attrs(cls)
        for fn in ast.walk(cls):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            params = {a.arg for a in (fn.args.posonlyargs + fn.args.args
                                      + fn.args.kwonlyargs)} - {"self"}
            if not params:
                continue
            for node in ast.walk(fn):
                attr = key = None
                if isinstance(node, (ast.Assign, ast.AnnAssign)):
                    targets = (node.targets
                               if isinstance(node, ast.Assign)
                               else [node.target])
                    for target in targets:
                        if isinstance(target, ast.Subscript):
                            a = _self_attr_name(target.value)
                            if a is not None:
                                attr, key = a, target.slice
                elif isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "setdefault" and node.args:
                    a = _self_attr_name(node.func.value)
                    if a is not None:
                        attr, key = a, node.args[0]
                if attr is None or attr in evicted \
                        or not (_names_in(key) & params):
                    continue
                findings.append(Finding(
                    "JL014", ERROR, path, node.lineno,
                    f"self.{attr} grows per caller-supplied key with no "
                    f"eviction anywhere in {cls.name} — a request-keyed "
                    f"table is memory an adversary controls (one invented "
                    f"tenant/model name per request, forever). Key state "
                    f"by configuration and map unknown ids to a shared "
                    f"default slot, add an eviction path, or justify with "
                    f"# jaxlint: disable=JL014"))
    return findings


# ---------------------------------------------------------------------------
# JL015 — journal bypass: print(json.dumps(...)) structured-event emission
# ---------------------------------------------------------------------------

def _is_json_dumps_call(node: ast.AST) -> bool:
    """``json.dumps(...)`` / ``_json.dumps(...)`` / bare ``dumps(...)``."""
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr == "dumps"
    return isinstance(fn, ast.Name) and fn.id == "dumps"


def check_journal_bypass(tree: ast.AST, path: str) -> list[Finding]:
    """JL015: in serve/train/resilience code, a structured event printed
    as ad-hoc JSON bypasses the flight recorder. The journal exists so an
    incident reads back as ONE correlated chain — seq, timestamps, cid —
    from a crash-safe rotating file; a ``print(json.dumps({...}))`` emits
    the same fact as an orphan line only a console scraper can find.
    Walking the print's argument subtrees catches the concatenation and
    f-string spellings too (``print("x: " + json.dumps(d))``)."""
    if not _path_is_resilient(path) or _path_is_test(path):
        return []
    base = path.replace("\\", "/").rsplit("/", 1)[-1]
    if base in PRINT_EXEMPT_BASENAMES:
        return []
    findings = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id == "print"):
            continue
        if any(_is_json_dumps_call(sub) for arg in node.args
               for sub in ast.walk(arg)):
            findings.append(Finding(
                "JL015", ERROR, path, node.lineno,
                "structured event printed as ad-hoc JSON — this bypasses "
                "the flight-recorder journal (no seq/ts/cid, not crash-"
                "safe), orphaning the event from its incident chain; emit "
                "via jimm_tpu.obs.journal (get_journal().emit(...)) or "
                "justify with # jaxlint: disable=JL015"))
    return findings


# ---------------------------------------------------------------------------
# JL016 — bare low-precision cast outside a scaling/quantization helper
# ---------------------------------------------------------------------------

#: dtype leaf names whose appearance as a cast target narrows precision on
#: the training fast path — each has a sanctioned scaled home
_LOWP_DTYPES = frozenset({"float8_e4m3fn", "float8_e5m2", "int8"})

#: substrings that sanction an enclosing function as a scaling-aware
#: quantization site (quantize_tensor / quantize_rows / _quantize_heads /
#: dynamic_scale / delayed_scale ...)
_SCALING_NAME_MARKS = ("quantize", "scale")


def _path_is_precision_critical(path: str) -> bool:
    """Kernel and trainer code: the two trees where a low-precision cast
    is a numerics decision, not a storage format."""
    parts = path.replace("\\", "/").split("/")
    return bool({"ops", "train"} & set(parts[:-1]))


def _lowp_target(node: ast.expr) -> str | None:
    """The low-precision dtype name if ``node`` denotes one (dotted name
    like ``jnp.float8_e4m3fn`` or an ``"int8"`` string constant), else
    None. Expression-derived dtypes (``k.dtype``) resolve to the leaf
    ``dtype`` and stay legal by construction."""
    name = _dotted(node)
    if name is not None:
        leaf = name.rsplit(".", 1)[-1]
        return leaf if leaf in _LOWP_DTYPES else None
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and node.value in _LOWP_DTYPES:
        return node.value
    return None


def _in_scaling_function(node: ast.AST) -> bool:
    cur: ast.AST | None = _parent(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)) and any(
                mark in cur.name for mark in _SCALING_NAME_MARKS):
            return True
        cur = _parent(cur)
    return False


def check_bare_lowp_cast(tree: ast.AST, path: str) -> list[Finding]:
    """JL016: fp8/int8 casts in ops/train code must travel with a scale.
    A bare ``.astype(jnp.float8_e4m3fn)`` saturates everything past 448
    (int8 past 127) — no crash, no NaN guard trips, the tensor just loses
    its top octaves and the loss curve quietly degrades. The sanctioned
    homes are functions whose names say they scale (``quantize_tensor``,
    ``quantize_rows``, ``dynamic_scale``, ...) where the amax reduction,
    the scale division, the clip, and the cast are one auditable unit."""
    if not _path_is_precision_critical(path) or _path_is_test(path):
        return []
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        target = None
        how = None
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "astype" and node.args:
            target = _lowp_target(node.args[0])
            how = f".astype({target})"
        else:
            fname = _dotted(node.func)
            if fname is not None \
                    and fname.rsplit(".", 1)[-1] == "convert_element_type" \
                    and len(node.args) >= 2:
                target = _lowp_target(node.args[1])
                how = f"convert_element_type(..., {target})"
        if target is None or _in_scaling_function(node):
            continue
        findings.append(Finding(
            "JL016", ERROR, path, node.lineno,
            f"bare {how} outside a quantize/scale helper saturates at the "
            f"format max with no scale to absorb the range — route the "
            f"cast through a scaling helper (quantize_tensor / "
            f"quantize_rows, docs/quantization.md) so amax -> scale -> "
            f"clip -> cast stay together, or justify with "
            f"# jaxlint: disable=JL016"))
    return findings


# ---------------------------------------------------------------------------
# JL021 — hardcoded confidence-threshold literal in cascade routing code
# ---------------------------------------------------------------------------

#: name substrings that mark a binding/comparison as a confidence threshold
_THRESHOLD_NAME_MARKS = ("threshold", "confidence")

#: the one cascade module where threshold numbers legitimately live:
#: the fitter/loader itself
_CALIBRATION_BASENAMES = frozenset({"calibrate.py"})


def _path_is_cascade(path: str) -> bool:
    parts = path.replace("\\", "/").split("/")
    return "cascade" in parts[:-1] and "serve" in parts


def _is_threshold_name(node: ast.AST) -> bool:
    """True when ``node`` names something threshold-like (``threshold``,
    ``self.confidence``, ``escalation_threshold`` ...)."""
    if isinstance(node, ast.Name):
        name = node.id
    elif isinstance(node, ast.Attribute):
        name = node.attr
    else:
        return False
    name = name.lower()
    return any(mark in name for mark in _THRESHOLD_NAME_MARKS)


def _numeric_literal(node: ast.AST) -> bool:
    """A bare int/float constant, possibly under a unary +/- (``0.92``,
    ``-1.5``). Deliberately NOT any-literal-in-subtree: ``round(conf, 6)``
    carries a 6 but decides nothing."""
    if isinstance(node, ast.UnaryOp) \
            and isinstance(node.op, (ast.UAdd, ast.USub)):
        node = node.operand
    return (isinstance(node, ast.Constant)
            and isinstance(node.value, (int, float))
            and not isinstance(node.value, bool))


def check_cascade_thresholds(tree: ast.AST, path: str) -> list[Finding]:
    """JL021: in ``serve/cascade/`` (outside ``calibrate.py`` and tests),
    no numeric literal may bind to or compare against a threshold-named
    value — routers load calibration artifacts, they never ship
    thresholds."""
    if not _path_is_cascade(path) or _path_is_test(path):
        return []
    if path.replace("\\", "/").rsplit("/", 1)[-1] in _CALIBRATION_BASENAMES:
        return []

    def finding(node: ast.AST, how: str) -> Finding:
        return Finding(
            "JL021", ERROR, path, node.lineno,
            f"hardcoded confidence-threshold literal ({how}) in cascade "
            "routing code — thresholds are fit on a holdout set "
            "(jimm-tpu cascade calibrate) and loaded from the "
            "content-addressed store (load_calibration), never spelled "
            "in code; justify deliberate literals with "
            "# jaxlint: disable=JL021")

    findings = []
    for node in ast.walk(tree):
        # threshold = 0.92 / self.confidence_floor: float = 0.9
        if isinstance(node, ast.Assign) and _numeric_literal(node.value):
            if any(_is_threshold_name(t) for t in node.targets):
                findings.append(finding(node, "assignment"))
        elif isinstance(node, ast.AnnAssign) and node.value is not None \
                and _numeric_literal(node.value) \
                and _is_threshold_name(node.target):
            findings.append(finding(node, "assignment"))
        # fn(threshold=0.92)
        elif isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg is not None and any(
                        mark in kw.arg.lower()
                        for mark in _THRESHOLD_NAME_MARKS) \
                        and _numeric_literal(kw.value):
                    findings.append(finding(kw.value, f"{kw.arg}= keyword"))
        # def route(..., threshold=0.92)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            args = node.args
            all_args = args.posonlyargs + args.args + args.kwonlyargs
            all_defaults = args.defaults + args.kw_defaults
            for arg, default in zip(all_args[-len(all_defaults):]
                                    if all_defaults else [], all_defaults):
                if default is not None and _numeric_literal(default) \
                        and any(mark in arg.arg.lower()
                                for mark in _THRESHOLD_NAME_MARKS):
                    findings.append(finding(default,
                                            f"{arg.arg}= default"))
        # conf >= 0.95  /  0.95 < confidence
        elif isinstance(node, ast.Compare):
            operands = [node.left] + list(node.comparators)
            if any(_is_threshold_name(op) for op in operands) and any(
                    _numeric_literal(op) for op in operands):
                findings.append(finding(node, "comparison"))
    return findings


# ---------------------------------------------------------------------------
# JL022 — direct profiler session control outside obs/prof/
# ---------------------------------------------------------------------------

#: the two jax.profiler calls that claim/release THE process profiler
#: session (TraceAnnotation etc. are session-agnostic and stay legal)
_PROFILER_SESSION_FNS = frozenset({"start_trace", "stop_trace"})


def _path_is_prof_home(path: str) -> bool:
    """Inside ``jimm_tpu/obs/prof/`` — the sanctioned session owner."""
    parts = path.replace("\\", "/").split("/")
    return "prof" in parts[:-1] and "obs" in parts


def check_profiler_bypass(tree: ast.AST, path: str) -> list[Finding]:
    """JL022: ``jax.profiler.start_trace``/``stop_trace`` called outside
    ``obs/prof/`` — the process has ONE profiler session and the capture
    ring may be holding it; direct session control races the ring instead
    of serializing on its lock. Catches both the attribute spelling
    (``jax.profiler.start_trace(...)``) and names imported from
    ``jax.profiler`` directly."""
    if _path_is_prof_home(path) or _path_is_test(path):
        return []
    imported: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) \
                and node.module == "jax.profiler":
            for alias in node.names:
                if alias.name in _PROFILER_SESSION_FNS:
                    imported.add(alias.asname or alias.name)
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fname = _dotted(node.func)
        if fname is None:
            continue
        leaf = fname.rsplit(".", 1)[-1]
        if fname in imported or (leaf in _PROFILER_SESSION_FNS
                                 and fname.endswith(f"profiler.{leaf}")):
            findings.append(Finding(
                "JL022", ERROR, path, node.lineno,
                f"direct jax.profiler.{leaf} outside obs/prof — the "
                "process has ONE profiler session and the continuous "
                "capture ring may be holding it (a second start_trace "
                "raises mid-incident, exactly when the capture mattered). "
                "Use jimm_tpu.obs.prof.capture.profiler_session (or "
                "train.profile.trace) so sessions serialize on the ring's "
                "lock, or justify with # jaxlint: disable=JL022"))
    return findings


# ---------------------------------------------------------------------------
# JL024 — sequence-parallel discipline inside parallel/seqpar
# ---------------------------------------------------------------------------

def _path_is_seqpar(path: str) -> bool:
    """Non-test files named ``seqpar*`` under ``parallel/`` — the modules
    whose whole point is never holding the full sequence on one device."""
    parts = path.replace("\\", "/").split("/")
    return (not _path_is_test(path) and "parallel" in parts
            and parts[-1].startswith("seqpar"))


def _einsum_is_dense_scores(equation: str) -> bool:
    """True for ``"bqnd,bknd->bnqk"``-shaped equations: each operand
    contributes exactly one free letter to the output and those two
    letters are the output's trailing pair — the ``(..., Sq, Sk)`` outer
    product over two sequence axes, i.e. materialized attention scores.
    The trailing-pair requirement keeps ``p @ V`` contractions
    (``"bnqk,bknd->bqnd"``) and plain projections clean."""
    try:
        ins, out = equation.replace(" ", "").split("->")
        a, b = ins.split(",")
    except ValueError:
        return False
    free_a = (set(a) - set(b)) & set(out)
    free_b = (set(b) - set(a)) & set(out)
    if len(free_a) != 1 or len(free_b) != 1 or len(out) < 2:
        return False
    return set(out[-2:]) == free_a | free_b


def _enclosing_function_name(node: ast.AST) -> str:
    cur = _parent(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur.name
        cur = _parent(cur)
    return ""


def check_seqpar_discipline(tree: ast.AST, path: str) -> list[Finding]:
    """JL024: dense ``(S, S)`` score materialization or unpermuted full-KV
    gathers inside ``parallel/seqpar``.

    The ring's contract is that no device ever holds more than one
    sequence chunk of K/V or one chunk-pair tile of scores: KV moves by
    ``ppermute`` (peer-to-peer, O(local) memory) and scores exist only
    per hop. Two AST shapes break that contract mechanically:

    - ``jax.lax.all_gather`` — reassembles the full sequence on every
      device, turning the ring into replicated attention with extra
      steps (memory scales with S again, exactly what the seq axis was
      bought to avoid);
    - a score-shaped ``einsum`` (output carrying a free sequence letter
      from each operand) outside a per-hop helper (function name
      containing ``hop``) — at module scope that outer product is the
      full (S, S) score matrix, not a chunk tile.

    ``tests/lint_fixtures/jimm_tpu/parallel/`` keeps the living fixture."""
    if not _path_is_seqpar(path):
        return []
    imported_gather: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module in (
                "jax.lax", "jax._src.lax.parallel"):
            for alias in node.names:
                if alias.name == "all_gather":
                    imported_gather.add(alias.asname or alias.name)
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fname = _dotted(node.func)
        if fname is None:
            continue
        leaf = fname.rsplit(".", 1)[-1]
        if fname in imported_gather or (leaf == "all_gather"
                                        and fname.endswith("lax.all_gather")):
            findings.append(Finding(
                "JL024", ERROR, path, node.lineno,
                "all_gather inside parallel/seqpar reassembles the full "
                "KV sequence on every device — per-device memory scales "
                "with S again, defeating the seq axis. Rotate chunks with "
                "jax.lax.ppermute (see _rotate), or justify with "
                "# jaxlint: disable=JL024"))
            continue
        if leaf == "einsum" and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str) \
                and _einsum_is_dense_scores(node.args[0].value) \
                and "hop" not in _enclosing_function_name(node):
            findings.append(Finding(
                "JL024", ERROR, path, node.lineno,
                "score-shaped einsum outside a per-hop helper "
                "materializes the dense (S, S) score matrix — seqpar "
                "scores may only exist one chunk-pair tile at a time "
                "inside *hop* functions, or justify with "
                "# jaxlint: disable=JL024"))
    return findings


# ---------------------------------------------------------------------------

def run_all(tree: ast.AST, path: str,
            vmem_budget: int | None = None) -> list[Finding]:
    _annotate_parents(tree)
    findings: list[Finding] = []
    findings += check_version_gated_config(tree, path)
    findings += check_host_sync_in_jit(tree, path)
    findings += check_train_step_donation(tree, path)
    findings += check_partition_spec_axes(tree, path)
    findings += check_pallas_tiling(tree, path, vmem_budget)
    findings += check_async_host_sync(tree, path)
    findings += check_bare_print(tree, path)
    findings += check_jit_in_loop(tree, path)
    findings += check_block_size_literal(tree, path)
    findings += check_device_put_placement(tree, path)
    findings += check_host_sort(tree, path)
    findings += check_quant_upcast(tree, path)
    findings += check_swallowed_exception(tree, path)
    findings += check_unbounded_tenant_table(tree, path)
    findings += check_journal_bypass(tree, path)
    findings += check_bare_lowp_cast(tree, path)
    findings += check_cascade_thresholds(tree, path)
    findings += check_profiler_bypass(tree, path)
    findings += check_seqpar_discipline(tree, path)
    return findings
