"""Exact on-TPU top-k retrieval: blocked scoring + streaming merge.

The scoring kernel follows FlashAttention's IO-aware blocking (PAPERS.md):
the ``(B, N)`` score matrix is never materialized. The corpus lives on
device as ``(shards, nblocks, block_n, D)``; a ``lax.scan`` streams one
``(block_n, D)`` block at a time through the MXU — ``(B, D) @ (D,
block_n)`` — and folds each block's ``jax.lax.top_k`` into a running
``(B, k)`` carry. Peak live intermediate is ``(B, block_n)`` scores plus
the ``(B, 2k)`` merge buffer, independent of corpus size N.

Exactness (recall@k == 1.0 vs a NumPy oracle) is by construction, not
approximation: every row is scored, and ``lax.top_k``'s stable
lowest-index-first tie order is preserved end to end — the running carry
(earlier, lower global indices) is concatenated *before* each block's
candidates, and the host-side merge of per-shard partials re-sorts the
bounded ``shards * k`` candidate set with an explicit (score desc, index
asc) key. Host code never full-sorts anything corpus-sized; lint rule
JL011 makes that an error in this package.

Sharding rides the PR 6 topology: the corpus splits on the ``model`` mesh
axis (shard axis of the 4-D layout), each replica scores its contiguous
row partition into a ``(shards, B, k)`` partial, and the final merge is
host-side over ``replicas * shards * k`` candidates. Block offsets and the
live-row count are *runtime* arguments, so every equally-padded partition
shares one compiled program — and one AOT fingerprint: the forward is
registered in the :mod:`jimm_tpu.aot` store exactly like a serve bucket
(``method="retrieval_topk"``), so a warm restart deserializes the scoring
program instead of re-tracing it. Block sizes resolve through
``tune.best_config("retrieval_topk", ...)``; an explicit ``block_n`` wins,
like the ops kernels.
"""

from __future__ import annotations

import math
import threading
from typing import Any, Callable, Sequence

import numpy as np

from jimm_tpu.retrieval.store import LoadedIndex, normalize_rows

__all__ = ["DEFAULT_BLOCK_N", "IndexSearcher", "Searcher", "corpus_layout",
           "make_topk_fn", "merge_partials", "streaming_topk"]

#: safe fallback block: lane-aligned, small enough that a (64, block_n)
#: f32 score tile + the (block_n, D) corpus block sit comfortably in VMEM
#: at ViT-scale D; tune.best_config refines it per (N, D, dtype)
DEFAULT_BLOCK_N = 1024

_LANES = 128


def _ceil_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


# ---------------------------------------------------------------------------
# device program
# ---------------------------------------------------------------------------

def make_topk_fn(k: int) -> Callable:
    """The traceable scoring program for one ``k``.

    Signature: ``fn(corpus (S, nb, bn, D), offsets (S, nb) i32,
    valid () i32, queries (B, D) f32) -> (values (S, B, k) f32,
    indices (S, B, k) i32)`` where ``indices`` are *global* corpus rows
    (``offsets`` already carry any partition base) and rows at or beyond
    ``valid`` are masked to ``-inf`` / left as padding candidates.
    """
    import jax
    import jax.numpy as jnp

    k = int(k)

    def fn(corpus, offsets, valid, queries):
        qf = queries.astype(jnp.float32)
        batch = qf.shape[0]

        def per_shard(shard_blocks, shard_offsets):
            block_n = shard_blocks.shape[1]
            kk = min(k, block_n)

            def body(carry, xs):
                carry_vals, carry_idx = carry
                block, offset = xs
                # the MXU step: (B, D) @ (D, block_n); never (B, N)
                scores = qf @ block.astype(jnp.float32).T
                cols = offset + jax.lax.iota(jnp.int32, block_n)
                scores = jnp.where(cols[None, :] < valid, scores,
                                   -jnp.inf)
                block_vals, block_pos = jax.lax.top_k(scores, kk)
                block_idx = jnp.take(cols, block_pos)
                # carry first: on equal scores top_k keeps the earlier
                # position, i.e. the lower global index — matching a
                # stable NumPy argsort oracle
                merged_vals, merged_pos = jax.lax.top_k(
                    jnp.concatenate([carry_vals, block_vals], axis=1), k)
                merged_idx = jnp.take_along_axis(
                    jnp.concatenate([carry_idx, block_idx], axis=1),
                    merged_pos, axis=1)
                return (merged_vals, merged_idx), None

            init = (jnp.full((batch, k), -jnp.inf, jnp.float32),
                    jnp.full((batch, k), -1, jnp.int32))
            (vals, idx), _ = jax.lax.scan(body, init,
                                          (shard_blocks, shard_offsets))
            return vals, idx

        return jax.vmap(per_shard)(corpus, offsets)

    return fn


# ---------------------------------------------------------------------------
# host-side layout and merge
# ---------------------------------------------------------------------------

def corpus_layout(corpus: np.ndarray, *, shards: int = 1,
                  block_n: int = DEFAULT_BLOCK_N, base: int = 0,
                  pad_rows: int | None = None
                  ) -> tuple[np.ndarray, np.ndarray, int]:
    """Pack an ``(N, D)`` corpus into the device layout.

    Returns ``(blocks (S, nb, bn, D), offsets (S, nb) i32, valid)``.
    ``pad_rows`` pads the partition to a common row count so every replica
    partition of one index shares shapes (and therefore one compiled
    program and one AOT fingerprint); ``base`` shifts offsets so indices
    stay global across partitions.
    """
    corpus = np.asarray(corpus)
    if corpus.ndim != 2:
        raise ValueError(f"corpus must be (N, D); got {corpus.shape}")
    n, dim = corpus.shape
    shards = max(1, int(shards))
    block_n = max(1, int(block_n))
    target = max(int(pad_rows) if pad_rows is not None else n, 1)
    if target < n:
        raise ValueError(f"pad_rows={target} < corpus rows {n}")
    per_shard = _ceil_to(math.ceil(target / shards), block_n)
    nblocks = per_shard // block_n
    padded = np.zeros((shards * per_shard, dim), corpus.dtype)
    padded[:n] = corpus
    blocks = padded.reshape(shards, nblocks, block_n, dim)
    offsets = (base
               + np.arange(shards, dtype=np.int32)[:, None] * per_shard
               + np.arange(nblocks, dtype=np.int32)[None, :] * block_n)
    return blocks, np.ascontiguousarray(offsets), base + n


def merge_partials(values: np.ndarray, indices: np.ndarray,
                   k: int) -> tuple[np.ndarray, np.ndarray]:
    """Fold ``(P, B, k)`` per-shard/per-replica partials into the global
    ``(B, k)`` result. The candidate set is ``P * k`` per query — bounded
    by the merge fan-in, never by corpus size — so an explicit
    (score desc, global index asc) lexicographic sort here is O(Pk log Pk)
    and reproduces the stable-argsort oracle's tie order exactly. This is
    the sanctioned host-merge idiom JL011 points at.
    """
    values = np.asarray(values, np.float32)
    indices = np.asarray(indices, np.int64)
    partials, batch, kk = values.shape
    flat_v = values.transpose(1, 0, 2).reshape(batch, partials * kk)
    flat_i = indices.transpose(1, 0, 2).reshape(batch, partials * kk)
    if flat_v.shape[1] < k:
        # k exceeds the total candidate fan-in (k > live rows, or every
        # segment tombstoned): keep the (B, k) shape contract and let
        # (-inf, -1) padding mark the underfill explicitly
        pad = k - flat_v.shape[1]
        flat_v = np.pad(flat_v, ((0, 0), (0, pad)),
                        constant_values=-np.inf)
        flat_i = np.pad(flat_i, ((0, 0), (0, pad)), constant_values=-1)
    # padding candidates (idx -1, val -inf) must lose every comparison,
    # including against real -inf scores, so push their index to +inf-ish
    sort_i = np.where(flat_i < 0, np.iinfo(np.int64).max, flat_i)
    order = np.lexsort((sort_i, -flat_v), axis=-1)[:, :k]
    return (np.take_along_axis(flat_v, order, axis=1),
            np.take_along_axis(flat_i, order, axis=1))


def streaming_topk(queries: np.ndarray, corpus: np.ndarray, k: int, *,
                   block_n: int = DEFAULT_BLOCK_N
                   ) -> tuple[np.ndarray, np.ndarray]:
    """Single-device convenience: exact top-k of ``queries`` against
    ``corpus`` via the streaming program. Returns host ``(values (B, k),
    indices (B, k))`` with ``-1``/``-inf`` rows past the corpus size when
    ``k > N``. The parity tests drive this directly."""
    import jax

    queries = np.asarray(queries, np.float32)
    blocks, offsets, valid = corpus_layout(corpus, shards=1,
                                           block_n=block_n)
    vals, idx = jax.jit(make_topk_fn(int(k)))(
        blocks, offsets, np.int32(valid), queries)
    return np.asarray(vals)[0], np.asarray(idx, np.int64)[0]


# ---------------------------------------------------------------------------
# warm searchers (AOT + tune integration)
# ---------------------------------------------------------------------------

def _resolve_block_n(n: int, dim: int, dtype, batch: int,
                     block_n: int | None) -> int:
    """Explicit block wins (tuner bench closures must not recurse);
    otherwise consult the persistent tune cache, falling back to the
    pruned-space default — same contract as the ops kernels."""
    if block_n is not None:
        return int(block_n)
    from jimm_tpu import tune
    config = tune.best_config(
        "retrieval_topk",
        shapes=[(int(batch), int(dim)), (int(n), int(dim))],
        dtypes=[np.dtype(dtype)])
    return int(config["block_n"])


class Searcher:
    """One partition's warm scoring forward: device-resident corpus blocks
    plus a store-first compiled program per query bucket.

    Mirrors :class:`~jimm_tpu.aot.warmup.AotForward`'s dispatch contract —
    ``prepare(bucket)`` consults the artifact store under an ``aot_load``
    span and returns ``"aot"``/``"miss"``/``"fallback"`` (counted in the
    ``jimm_aot`` registry), the fresh path is a counting jit whose getter
    feeds the zero-recompile checks, and a loaded executable that raises
    at call time quarantines itself and degrades to fresh.
    """

    def __init__(self, corpus: np.ndarray, *, k: int,
                 buckets: Sequence[int] = (1,), block_n: int | None = None,
                 mesh: Any = None, base: int = 0,
                 pad_rows: int | None = None, aot_store: Any = None,
                 label: str = "retrieval", write_through: bool = True):
        import jax

        corpus = np.ascontiguousarray(np.asarray(corpus))
        self.k = int(k)
        self.buckets = tuple(sorted(int(b) for b in buckets))
        self.dim = int(corpus.shape[1])
        self.n_rows = int(corpus.shape[0])
        self.mesh = mesh
        self.store = aot_store
        self.label = label
        self.write_through = write_through
        shards = int(dict(mesh.shape).get("model", 1)) if mesh is not None \
            else 1
        self.block_n = _resolve_block_n(self.n_rows, self.dim,
                                        corpus.dtype, self.buckets[-1],
                                        block_n)
        blocks, offsets, valid = corpus_layout(
            corpus, shards=shards, block_n=self.block_n, base=base,
            pad_rows=pad_rows)
        self.shards = shards
        self.nblocks = int(blocks.shape[1])
        self._corpus_dtype = str(blocks.dtype)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            self._corpus_sharding = NamedSharding(
                mesh, PartitionSpec("model", None, None, None))
            self._offsets_sharding = NamedSharding(
                mesh, PartitionSpec("model", None))
            self._blocks = jax.device_put(blocks, self._corpus_sharding)
            self._offsets = jax.device_put(offsets,
                                           self._offsets_sharding)
        else:
            self._corpus_sharding = self._offsets_sharding = None
            self._blocks = jax.device_put(blocks)
            self._offsets = jax.device_put(offsets)
        self._valid = np.int32(valid)
        self._traces = {"count": 0}
        fn = make_topk_fn(self.k)

        def counting(blocks_, offsets_, valid_, queries):
            self._traces["count"] += 1
            return fn(blocks_, offsets_, valid_, queries)

        self._fn = fn
        self._fresh = jax.jit(counting)
        self._loaded: dict[int, Callable] = {}
        #: bucket -> "aot" | "miss" | "fallback" | "compile"
        self.sources: dict[int, str] = {}

    def trace_count(self) -> int:
        return self._traces["count"]

    def resident_bytes(self) -> int:
        """Device-resident corpus bytes (padded blocks + offset table) —
        the same accounting the tiered searcher reports, so serve_bench
        rows compare across index modes."""
        return int(self._blocks.nbytes) + int(self._offsets.nbytes)

    # -- AOT keys ---------------------------------------------------------

    def key_for(self, bucket: int):
        from jimm_tpu.aot.keys import serve_forward_key
        return serve_forward_key(
            {"kind": "retrieval_topk", "shards": self.shards,
             "nblocks": self.nblocks, "block_n": self.block_n,
             "dim": self.dim, "k": self.k,
             "corpus_dtype": self._corpus_dtype},
            method="retrieval_topk", bucket=int(bucket),
            item_shape=(self.dim,), in_dtype=np.float32,
            param_dtype=self._corpus_dtype, mesh=self.mesh)

    def _arg_specs(self, bucket: int):
        import jax
        return (
            jax.ShapeDtypeStruct(
                (self.shards, self.nblocks, self.block_n, self.dim),
                self._blocks.dtype, sharding=self._corpus_sharding),
            jax.ShapeDtypeStruct((self.shards, self.nblocks), np.int32,
                                 sharding=self._offsets_sharding),
            jax.ShapeDtypeStruct((), np.int32),
            jax.ShapeDtypeStruct((int(bucket), self.dim), np.float32),
        )

    # -- warm-start -------------------------------------------------------

    def prepare(self, bucket: int) -> str:
        """Store-first warm-start for one query bucket; never raises."""
        bucket = int(bucket)
        if bucket in self.sources:
            return self.sources[bucket]
        if self.store is None:
            self.sources[bucket] = "compile"
            return "compile"
        from jimm_tpu import obs
        from jimm_tpu.aot.warmup import _runtime_versions, aot_metrics
        hit, miss, fallback = aot_metrics()
        key = self.key_for(bucket)
        fp = key.fingerprint()
        existed = self.store.contains(fp)
        source = "miss"
        with obs.span("aot_load"):
            payload = self.store.get(fp,
                                     expect_versions=_runtime_versions())
            if payload is not None:
                try:
                    self._loaded[bucket] = self._bind(payload)
                    source = "aot"
                except Exception as e:  # noqa: BLE001 — degrade, never die
                    self.store.quarantine(fp,
                                          f"deserialize/bind failed: {e}")
                    source = "fallback"
            elif existed:
                source = "fallback"  # store.get already quarantined it
        if source == "aot":
            hit.inc()
        elif source == "fallback":
            fallback.inc()
        else:
            miss.inc()
            if self.write_through:
                self._export_and_put(bucket, key, fp)
        self.sources[bucket] = source
        return source

    def _bind(self, payload: bytes) -> Callable:
        import jax
        from jax import export as jax_export
        exported = jax_export.deserialize(bytearray(payload))
        flat_avals = jax.tree.flatten(exported.in_avals)[0] \
            if hasattr(exported, "in_avals") else []
        if flat_avals and len(flat_avals) != 4:
            raise ValueError(f"artifact expects {len(flat_avals)} input "
                             f"leaves, retrieval_topk provides 4")
        return jax.jit(exported.call)

    def _export_and_put(self, bucket: int, key, fp: str) -> None:
        """Write-through on a miss so the next process (and every sibling
        replica — same shapes, same fingerprint) starts warm. Failure to
        serialize must not break search."""
        try:
            import jax
            from jax import export as jax_export

            from jimm_tpu.aot.keys import AOT_FORMAT_VERSION
            exported = jax_export.export(jax.jit(self._fn))(
                *self._arg_specs(bucket))
            self.store.put(fp, exported.serialize(),
                           meta={"label": self.label, **key.describe(),
                                 "format_version": AOT_FORMAT_VERSION})
        except Exception:  # noqa: BLE001
            pass

    def warmup(self) -> dict[int, str]:
        """Prepare + prime every bucket; returns {bucket: source}."""
        zeros = None
        for bucket in self.buckets:
            self.prepare(bucket)
            zeros = np.zeros((bucket, self.dim), np.float32)
            self.search_partial(zeros)
        return dict(self.sources)

    # -- dispatch ---------------------------------------------------------

    def _bucket_for(self, batch: int) -> int:
        for bucket in self.buckets:
            if batch <= bucket:
                return bucket
        raise ValueError(f"query batch {batch} exceeds largest retrieval "
                         f"bucket {self.buckets[-1]}")

    def search_partial(self, queries: np.ndarray
                       ) -> tuple[np.ndarray, np.ndarray]:
        """Score a ``(B, D)`` f32 query batch; returns host partials
        ``(values (S, B, k), indices (S, B, k))`` with global indices.
        Batches past the largest bucket run as chunks of it — no new
        program shapes, so no recompiles."""
        queries = np.asarray(queries, np.float32)
        batch = queries.shape[0]
        top = self.buckets[-1]
        if batch > top:
            outs = [self.search_partial(queries[i:i + top])
                    for i in range(0, batch, top)]
            return (np.concatenate([o[0] for o in outs], axis=1),
                    np.concatenate([o[1] for o in outs], axis=1))
        bucket = self._bucket_for(batch)
        if batch < bucket:
            padded = np.zeros((bucket, self.dim), np.float32)
            padded[:batch] = queries
            queries = padded
        fn = self._loaded.get(bucket)
        if fn is not None:
            try:
                vals, idx = fn(self._blocks, self._offsets, self._valid,
                               queries)
            except Exception:  # noqa: BLE001 — a bad artifact must not
                # fail the query: quarantine, recompile fresh
                from jimm_tpu.aot.warmup import aot_metrics
                aot_metrics()[2].inc()
                del self._loaded[bucket]
                self.sources[bucket] = "fallback"
                if self.store is not None:
                    self.store.quarantine(
                        self.key_for(bucket).fingerprint(),
                        "loaded executable raised at call time")
                vals, idx = self._fresh(self._blocks, self._offsets,
                                        self._valid, queries)
        else:
            vals, idx = self._fresh(self._blocks, self._offsets,
                                    self._valid, queries)
        return (np.asarray(vals)[:, :batch],
                np.asarray(idx, np.int64)[:, :batch])


class IndexSearcher:
    """Search one :class:`LoadedIndex` across the serving topology.

    On a trivial (or absent) plan this is a single :class:`Searcher` on
    the default device. On an ``R x k`` plan the corpus splits into R
    contiguous, equally-padded row partitions — one per replica submesh,
    further sharded ``model``-axis-wise inside each — so all partitions
    share one compiled program and one AOT fingerprint (offsets and the
    live-row count are runtime arguments). ``search`` merges the
    ``R * shards`` partial top-k sets host-side and maps global row
    indices back to string ids.
    """

    def __init__(self, index: LoadedIndex, *, k: int = 10,
                 buckets: Sequence[int] = (1,),
                 block_n: int | None = None, plan: Any = None,
                 aot_store: Any = None, label: str | None = None):
        if len(index) == 0:
            raise ValueError(f"index {index.name!r} is empty")
        self.index = index
        self.k = int(k)
        self.buckets = tuple(sorted(int(b) for b in buckets))
        label = label or f"retrieval:{index.name}"
        corpus = index.vectors
        if plan is not None and not plan.is_trivial:
            replicas = plan.replicas
            chunk = math.ceil(len(index) / replicas)
            meshes = plan.meshes()
            self.searchers = [
                Searcher(corpus[r * chunk:(r + 1) * chunk], k=self.k,
                         buckets=self.buckets, block_n=block_n,
                         mesh=meshes[r], base=r * chunk, pad_rows=chunk,
                         aot_store=aot_store, label=label)
                for r in range(replicas)]
        else:
            self.searchers = [
                Searcher(corpus, k=self.k, buckets=self.buckets,
                         block_n=block_n, aot_store=aot_store,
                         label=label)]
        #: {bucket: "aot"|"miss"|"compile"|"fallback"|"mixed"} after warmup
        self.warmup_report: dict[int, str] = {}
        self._dispatch_lock = threading.Lock()

    @property
    def block_n(self) -> int:
        return self.searchers[0].block_n

    def trace_count(self) -> int:
        return sum(s.trace_count() for s in self.searchers)

    def resident_bytes(self) -> int:
        return sum(s.resident_bytes() for s in self.searchers)

    def prepare(self, bucket: int) -> str:
        sources = {s.prepare(bucket) for s in self.searchers}
        return sources.pop() if len(sources) == 1 else "mixed"

    def warmup(self) -> dict[int, str]:
        """Warm every (replica, bucket); returns the aggregated
        {bucket: source} map the serve ready line reports."""
        for searcher in self.searchers:
            searcher.warmup()
        report: dict[int, str] = {}
        for bucket in self.buckets:
            sources = {s.sources.get(bucket) for s in self.searchers}
            report[bucket] = (sources.pop() if len(sources) == 1
                              else "mixed")
        self.warmup_report = report
        return report

    def search(self, queries: np.ndarray
               ) -> tuple[np.ndarray, np.ndarray, list[list[str]]]:
        """Top-k over the whole index for a ``(B, D)`` (or ``(D,)``) query
        batch. Queries are unit-normalized host-side (cosine metric).
        Returns ``(values (B, k'), indices (B, k'), ids)`` with
        ``k' = min(k, N)``."""
        queries = np.asarray(queries, np.float32)
        if queries.ndim == 1:
            queries = queries[None, :]
        if queries.ndim != 2 or queries.shape[1] != self.index.dim:
            raise ValueError(
                f"queries must be (B, {self.index.dim}); got "
                f"{queries.shape}")
        queries = normalize_rows(queries)
        # one search on the device at a time: handler threads all land
        # here, and concurrently launched collective programs interleave
        # their rendezvous on the shared replica submeshes and deadlock
        with self._dispatch_lock:
            partials = [s.search_partial(queries) for s in self.searchers]
        values = np.concatenate([p[0] for p in partials], axis=0)
        indices = np.concatenate([p[1] for p in partials], axis=0)
        k_eff = min(self.k, len(self.index))
        vals, idx = merge_partials(values, indices, k_eff)
        ids = [[self.index.ids[j] for j in row] for row in idx]
        return vals, idx, ids
