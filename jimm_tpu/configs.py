"""Model configuration dataclasses and named presets.

The reference (`/root/reference`) derives hyperparameters ad-hoc from HF
`config.json` keys or shape inference scattered through each model's
`from_pretrained` (e.g. `src/jimm/models/vit.py:131-164`). Here every model is
driven by one frozen dataclass so presets, checkpoint inference, and CLI flags
all land in the same place.

Parity-critical defaults are documented per field with the reference citation.
"""

from __future__ import annotations

import dataclasses
import json
import pathlib
from dataclasses import dataclass, field
from typing import Any, Literal

Pooling = Literal["cls", "map", "last", "eot", "none"]
Activation = Literal["gelu", "gelu_tanh", "quick_gelu"]
AttnImpl = Literal["auto", "xla", "flash", "flash_masked", "flash_bias",
                   "flash_int8", "sigmoid", "ring", "ulysses", "saveable"]
#: Training precision policy (`jimm_tpu/quant/policy.py`): "bf16" trains
#: as built, "fp8_hybrid" swaps eligible Linears for e4m3-fwd/e5m2-grad
#: fp8 matmuls, "int8_qk" switches attention to the int8-QK flash kernel.
Precision = Literal["bf16", "fp8_hybrid", "int8_qk"]
#: "dots" + optional "+ln"/"+act"/"+attn" save-list extensions
RematPolicy = str


def remat_policy_parts(policy: str) -> list[str]:
    """Validate a remat policy string; return its ``+``-separated parts.
    Canonical validator shared by the CLI/bench parse layer and the
    execution point (`nn/transformer.py:_remat_policy`)."""
    parts = policy.split("+")
    if policy != "none" and (parts[0] != "dots"
                             or not set(parts[1:]) <= {"ln", "act", "attn"}):
        raise ValueError(f"unknown remat_policy {policy!r}; expected 'none' "
                         "or 'dots' with optional '+ln', '+act', '+attn' "
                         "suffixes (e.g. 'dots+ln+act')")
    return parts


def parse_remat(spec: str) -> dict[str, Any]:
    """CLI ``--remat`` spec -> `with_runtime` kwargs. ``none`` = remat off,
    ``full`` = remat with full recompute, ``dots[+ln][+act][+attn]`` = remat
    with that save-list. Raises ValueError on a malformed spec, so tools can
    fail at parse time instead of deep inside the first jit trace."""
    if spec in ("none", "full"):
        return {"remat": spec != "none", "remat_policy": "none"}
    remat_policy_parts(spec)
    return {"remat": True, "remat_policy": spec}


def check_pp_schedule(M: int, V: int, *, n_stages: int | None = None,
                      local_batch: int | None = None,
                      prefix: str = "") -> None:
    """Microbatch scheduling constraints — the ONE implementation behind
    both the parse-time validation (``validate_pipeline``) and the
    trace-time checks in `parallel/pipeline.py`, so semantics and messages
    cannot drift apart."""
    if M < 1:
        raise ValueError(prefix + f"n_microbatches must be >= 1, got {M}")
    if V < 1:
        raise ValueError(prefix + f"n_virtual must be >= 1, got {V}")
    if n_stages is not None and V > 1 and M % n_stages:
        raise ValueError(prefix + f"interleaved schedule needs microbatches "
                         f"{M} divisible by {n_stages} stages")
    if local_batch is not None and local_batch % M:
        raise ValueError(prefix + f"local batch {local_batch} not divisible "
                         f"by {M} microbatches")


def validate_pipeline(tower, *, n_stages: int, local_batch: int | None = None,
                      tower_name: str | None = None) -> None:
    """Surface the pipeline constraints at config/CLI parse time (VERDICT r3
    weak #6: a user used to reach them minutes into a compile). The same
    function runs inside `nn/transformer.py`'s pipeline dispatch, and the
    microbatch checks are shared with `parallel/pipeline.py` via
    ``check_pp_schedule`` — one implementation, both paths."""
    if not getattr(tower, "pipeline", False):
        return
    M, V = tower.pp_microbatches, tower.pp_virtual
    prefix = f"{tower_name} tower: " if tower_name else ""
    check_pp_schedule(M, V, prefix=prefix)
    if n_stages < 1:
        raise ValueError(prefix + "pipeline=True needs an ambient mesh with "
                         "a 'stage' axis (use use_sharding(mesh, PIPELINE))")
    if tower.depth % (n_stages * V):
        raise ValueError(prefix + f"depth {tower.depth} not divisible by "
                         f"{n_stages} stages x {V} virtual chunks")
    if V > 1 and tower.pp_stages and tower.pp_stages != n_stages:
        raise ValueError(prefix + f"model was built for "
                         f"pp_stages={tower.pp_stages} but the mesh has "
                         f"{n_stages} stages")
    check_pp_schedule(M, V, n_stages=n_stages, local_batch=local_batch,
                      prefix=prefix)


def normalize_act(name: str | None, default: str = "gelu") -> str:
    """HF ``hidden_act`` -> canonical Activation name."""
    if name is None:
        return default
    return {"gelu": "gelu", "gelu_new": "gelu_tanh",
            "gelu_pytorch_tanh": "gelu_tanh",
            "quick_gelu": "quick_gelu"}.get(name, name)


def act_to_hf(name: str) -> str:
    """Canonical Activation name -> HF ``hidden_act``."""
    return {"gelu": "gelu", "gelu_tanh": "gelu_pytorch_tanh",
            "quick_gelu": "quick_gelu"}.get(name, name)


#: Tower fields that select execution strategy, not architecture — safe to
#: override when loading a checkpoint (`from_pretrained(..., runtime=...)`)
RUNTIME_FIELDS = frozenset({
    "attn_impl", "ln_impl", "fused_qkv", "remat", "remat_policy", "scan_unroll",
    "dropout", "pipeline", "pp_microbatches", "pp_virtual", "pp_stages",
    "precision",
})


def with_runtime(cfg, **fields):
    """Return ``cfg`` with runtime (non-architecture) fields replaced in the
    vision — and, if present, text — tower. Rejects architecture fields so a
    checkpoint's shapes can never be silently contradicted.

    Flat fields apply to both towers; ``vision=dict(...)`` / ``text=dict(...)``
    target one tower (needed when the towers' depths admit different
    pipeline splits, e.g. CLIP-L's 24-deep vision vs 12-deep text)."""
    per_tower = {t: dict(fields.pop(t, None) or {})
                 for t in ("vision", "text")}
    bad = (set(fields) | set(per_tower["vision"]) | set(per_tower["text"])
           ) - RUNTIME_FIELDS
    if bad:
        raise ValueError(f"not runtime-overridable: {sorted(bad)} "
                         f"(allowed: {sorted(RUNTIME_FIELDS)})")
    cfg = dataclasses.replace(cfg, vision=dataclasses.replace(
        cfg.vision, **fields, **per_tower["vision"]))
    if hasattr(cfg, "text"):
        cfg = dataclasses.replace(cfg, text=dataclasses.replace(
            cfg.text, **fields, **per_tower["text"]))
    elif per_tower["text"]:
        raise ValueError("config has no text tower to override")
    return cfg


@dataclass(frozen=True)
class TransformerConfig:
    """Shared encoder-stack hyperparameters (vision or text tower)."""

    width: int = 768
    depth: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072  # read from config, NOT hardcoded 4x (ref limitation, SURVEY §2.4)
    act: Activation = "gelu"
    ln_eps: float = 1e-6
    dropout: float = 0.0
    causal: bool = False
    attn_impl: AttnImpl = "auto"
    #: Pipeline-parallel forward: shard the stacked ``layers`` axis over a
    #: ``stage`` mesh axis and circulate microbatches via ppermute
    #: (`jimm_tpu/parallel/pipeline.py`). Requires depth % n_stages == 0 and
    #: (local) batch % pp_microbatches == 0.
    pipeline: bool = False
    pp_microbatches: int = 4
    #: Interleaved pipeline schedule: each stage holds this many
    #: NON-contiguous layer chunks (circular placement) and microbatches lap
    #: the ring pp_virtual times — bubble shrinks ~pp_virtual-fold
    #: (`jimm_tpu/parallel/pipeline.py`). Needs depth % (stages*virtual) == 0
    #: and (for >1) pp_microbatches % stages == 0.
    pp_virtual: int = 1
    #: Known pipeline-stage count. With ``pp_virtual > 1`` and this set, the
    #: stacked blocks are STORED in circular schedule order from
    #: construction (loaders/exporters reorder at the stacking edge), so the
    #: forward avoids re-permuting — a cross-stage all-to-all — every step.
    #: 0 = unknown: the forward permutes per call (correct, slower).
    pp_stages: int = 0
    remat: bool = False
    #: What the backward pass may keep from the forward when ``remat`` is on:
    #: "none" recomputes everything (min memory, ~1/3 extra FLOPs); "dots"
    #: saves matmul outputs and recomputes only cheap elementwise ops
    #: (ln/act/softmax) — the usual best MFU/memory trade on TPU.
    remat_policy: RematPolicy = "none"
    #: LayerNorm kernel: "xla" (nnx.LayerNorm) or "fused" (one-pass Pallas
    #: fwd/bwd, `jimm_tpu/ops/layer_norm.py`).
    ln_impl: Literal["xla", "fused"] = "xla"
    #: Compute q/k/v as one (H, 3H) matmul (call-time kernel concat;
    #: checkpoints unchanged).
    fused_qkv: bool = False
    #: `lax.scan` unroll factor for the layer loop. >1 trades compile time
    #: for schedule freedom: XLA turns the per-layer stacked-gradient
    #: dynamic-update-slices into statically-indexed updates it can fuse.
    scan_unroll: int = 1
    #: Training precision policy, applied to the built model by
    #: `quant.policy.apply_precision_policy` (trainer/CLI plumbing) — the
    #: config field records intent so measurements and adopted runtimes
    #: carry it; construction itself never reads it.
    precision: Precision = "bf16"

    @property
    def head_dim(self) -> int:
        return self.width // self.num_heads


@dataclass(frozen=True)
class VisionConfig:
    """Vision tower. Mirrors `src/jimm/common/vit.py:104-248` behavior.

    - ``pre_norm``: CLIP applies an extra LayerNorm after embeddings and skips
      embedding dropout (ref `common/vit.py:181-190,238-241`).
    - ``patch_bias``: CLIP's patch conv has no bias (ref `models/clip.py:66`).
    - ``pooling``: "cls" (ViT/CLIP) or "map" (SigLIP MAP head,
      ref `common/vit.py:12-101`) or "none" (return full sequence).
    """

    image_size: int = 224
    patch_size: int = 16
    channels: int = 3
    #: frames per clip for temporal (video) towers: each frame patchifies
    #: independently and the T * grid^2 tokens flatten into ONE sequence
    #: (pos table covers the full flattened length) — long-sequence work
    #: that the seq-parallel mesh axis shards across chips. 1 = image.
    num_frames: int = 1
    width: int = 768
    depth: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    act: Activation = "gelu"
    ln_eps: float = 1e-6
    dropout: float = 0.0
    pooling: Pooling = "cls"
    pre_norm: bool = False
    patch_bias: bool = True
    attn_impl: AttnImpl = "auto"
    pipeline: bool = False
    pp_microbatches: int = 4
    pp_virtual: int = 1
    pp_stages: int = 0
    remat: bool = False
    remat_policy: RematPolicy = "none"
    ln_impl: Literal["xla", "fused"] = "xla"
    fused_qkv: bool = False
    scan_unroll: int = 1
    precision: Precision = "bf16"

    @property
    def grid(self) -> int:
        return self.image_size // self.patch_size

    @property
    def num_patches(self) -> int:
        return self.grid * self.grid * self.num_frames

    @property
    def seq_len(self) -> int:
        return self.num_patches + (1 if self.pooling == "cls" else 0)

    def encoder(self) -> TransformerConfig:
        return TransformerConfig(
            width=self.width, depth=self.depth, num_heads=self.num_heads,
            mlp_dim=self.mlp_dim, act=self.act, ln_eps=self.ln_eps,
            dropout=self.dropout, causal=False, attn_impl=self.attn_impl,
            pipeline=self.pipeline, pp_microbatches=self.pp_microbatches,
            pp_virtual=self.pp_virtual, pp_stages=self.pp_stages,
            remat=self.remat, remat_policy=self.remat_policy,
            ln_impl=self.ln_impl, fused_qkv=self.fused_qkv,
            scan_unroll=self.scan_unroll, precision=self.precision,
        )


@dataclass(frozen=True)
class TextConfig:
    """Text tower. CLIP: causal + EOT-argmax pooling (ref `models/clip.py:92-104,
    164-166`). SigLIP: bidirectional + last-token pooling (ref
    `models/siglip.py:79-91,151-152`)."""

    vocab_size: int = 49408
    context_length: int = 77
    width: int = 512
    depth: int = 12
    num_heads: int = 8
    mlp_dim: int = 2048
    act: Activation = "quick_gelu"
    ln_eps: float = 1e-5
    dropout: float = 0.0
    causal: bool = True
    pooling: Pooling = "eot"
    proj_bias: bool = False  # CLIP text_projection is bias-free; SigLIP head has bias
    # recorded at load, re-emitted at export; HF CLIP pools at this token's
    # first occurrence (argmax-equivalent when EOT is the max id)
    eos_token_id: int | None = None
    attn_impl: AttnImpl = "auto"
    pipeline: bool = False
    pp_microbatches: int = 4
    pp_virtual: int = 1
    pp_stages: int = 0
    remat: bool = False
    remat_policy: RematPolicy = "none"
    ln_impl: Literal["xla", "fused"] = "xla"
    fused_qkv: bool = False
    scan_unroll: int = 1
    precision: Precision = "bf16"

    def encoder(self) -> TransformerConfig:
        return TransformerConfig(
            width=self.width, depth=self.depth, num_heads=self.num_heads,
            mlp_dim=self.mlp_dim, act=self.act, ln_eps=self.ln_eps,
            dropout=self.dropout, causal=self.causal, attn_impl=self.attn_impl,
            pipeline=self.pipeline, pp_microbatches=self.pp_microbatches,
            pp_virtual=self.pp_virtual, pp_stages=self.pp_stages,
            remat=self.remat, remat_policy=self.remat_policy,
            ln_impl=self.ln_impl, fused_qkv=self.fused_qkv,
            scan_unroll=self.scan_unroll, precision=self.precision,
        )


@dataclass(frozen=True)
class ViTConfig:
    """ViT image classifier (ref `models/vit.py:16-103`): post-norm backbone,
    CLS pooling, LN eps 1e-12 (ref `models/vit.py:73`), optional linear head."""

    vision: VisionConfig = field(default_factory=lambda: VisionConfig(ln_eps=1e-12))
    num_classes: int = 1000
    do_classification: bool = True


@dataclass(frozen=True)
class CLIPConfig:
    """CLIP dual tower (ref `models/clip.py:15-188`): pre-norm QuickGELU vision
    tower without patch bias, causal text tower, bias-free projections,
    learned ``logit_scale``."""

    vision: VisionConfig = field(default_factory=lambda: VisionConfig(
        width=768, depth=12, num_heads=12, mlp_dim=3072, act="quick_gelu",
        ln_eps=1e-5, pooling="cls", pre_norm=True, patch_bias=False,
        patch_size=32))
    text: TextConfig = field(default_factory=TextConfig)
    projection_dim: int = 512
    logit_scale_init: float = 2.6592  # ln(1/0.07), OpenAI CLIP init


@dataclass(frozen=True)
class SigLIPConfig:
    """SigLIP dual tower (ref `models/siglip.py:15-174`): MAP-pooled vision
    tower (gelu_tanh, eps 1e-6), bidirectional text tower with last-token
    pooling and biased projection, ``logit_scale`` AND ``logit_bias``."""

    vision: VisionConfig = field(default_factory=lambda: VisionConfig(
        image_size=256, patch_size=16, width=768, depth=12, num_heads=12,
        mlp_dim=3072, act="gelu_tanh", ln_eps=1e-6, pooling="map",
        pre_norm=False, patch_bias=True))
    text: TextConfig = field(default_factory=lambda: TextConfig(
        vocab_size=32000, context_length=64, width=768, depth=12, num_heads=12,
        mlp_dim=3072, act="gelu_tanh", ln_eps=1e-6, causal=False,
        pooling="last", proj_bias=True))
    # SigLIP projects both towers to the (shared) text width, not a separate dim
    projection_dim: int = 768
    logit_scale_init: float = 2.3026  # ln(10), SigLIP paper init
    logit_bias_init: float = -10.0


def _vit(size: str, patch: int, image: int, classes: int = 1000) -> ViTConfig:
    w, d, h, m = {
        "T": (192, 12, 3, 768),
        "S": (384, 12, 6, 1536),
        "B": (768, 12, 12, 3072),
        "L": (1024, 24, 16, 4096),
        "H": (1280, 32, 16, 5120),
        "g": (1408, 40, 16, 6144),
        "G": (1664, 48, 16, 8192),
    }[size]
    return ViTConfig(
        vision=VisionConfig(image_size=image, patch_size=patch, width=w,
                            depth=d, num_heads=h, mlp_dim=m, ln_eps=1e-12),
        num_classes=classes)


def _vit_temporal(size: str, patch: int, image: int, frames: int,
                  classes: int = 1000) -> ViTConfig:
    """Temporal ViT: frames flattened into one sequence (T * grid^2
    tokens) — the video workload the sequence-parallel mesh axis exists
    for. No architectural surgery beyond the longer pos table; attention
    is full spatio-temporal. MAP pooling on purpose: a CLS token would
    make the sequence odd and lock out every even ring size, while
    T * grid^2 divides cleanly across the ``seq`` axis."""
    base = _vit(size, patch, image, classes)
    return dataclasses.replace(
        base, vision=dataclasses.replace(base.vision, num_frames=frames,
                                         pooling="map"))


def _siglip(size: str, patch: int, image: int, vocab: int = 32000,
            ctx: int = 64) -> SigLIPConfig:
    w, d, h, m = {
        "B": (768, 12, 12, 3072),
        "L": (1024, 24, 16, 4096),
        "So400m": (1152, 27, 16, 4304),  # non-4x MLP: loadable here, not in ref
    }[size]
    return SigLIPConfig(
        vision=VisionConfig(image_size=image, patch_size=patch, width=w, depth=d,
                            num_heads=h, mlp_dim=m, act="gelu_tanh", ln_eps=1e-6,
                            pooling="map"),
        text=TextConfig(vocab_size=vocab, context_length=ctx, width=w, depth=d,
                        num_heads=h, mlp_dim=m, act="gelu_tanh", ln_eps=1e-6,
                        causal=False, pooling="last", proj_bias=True),
        projection_dim=w)


def _clip(vision_size: str, patch: int, image: int = 224) -> CLIPConfig:
    vw, vd, vh, vm, proj = {
        "B": (768, 12, 12, 3072, 512),
        "L": (1024, 24, 16, 4096, 768),
    }[vision_size]
    tw, td, th, tm = {"B": (512, 12, 8, 2048), "L": (768, 12, 12, 3072)}[vision_size]
    return CLIPConfig(
        vision=VisionConfig(image_size=image, patch_size=patch, width=vw,
                            depth=vd, num_heads=vh, mlp_dim=vm, act="quick_gelu",
                            ln_eps=1e-5, pooling="cls", pre_norm=True,
                            patch_bias=False),
        text=TextConfig(vocab_size=49408, context_length=77, width=tw, depth=td,
                        num_heads=th, mlp_dim=tm, act="quick_gelu", ln_eps=1e-5,
                        causal=True, pooling="eot", proj_bias=False),
        projection_dim=proj)


#: Named presets covering the BASELINE.json tracked configs.
PRESETS: dict[str, Any] = {
    # ViT
    "vit-tiny-patch16-224": _vit("T", 16, 224),
    "vit-small-patch16-224": _vit("S", 16, 224),
    "vit-base-patch16-224": _vit("B", 16, 224),
    "vit-base-patch32-384": _vit("B", 32, 384),
    "vit-large-patch16-384": _vit("L", 16, 384),
    "vit-huge-patch14-224": _vit("H", 14, 224),
    # Temporal ViT (video: frames flattened into sequence — 8 * 196 + 1 =
    # 1569 tokens; train/serve these across a seq-parallel mesh axis)
    "vit-temporal-small-patch16-224-f8": _vit_temporal("S", 16, 224, 8),
    "vit-temporal-base-patch16-224-f8": _vit_temporal("B", 16, 224, 8),
    # CLIP
    "clip-vit-base-patch32": _clip("B", 32),
    "clip-vit-base-patch16": _clip("B", 16),
    "clip-vit-large-patch14": _clip("L", 14),
    "clip-vit-large-patch14-336": _clip("L", 14, 336),
    # SigLIP
    "siglip-base-patch16-224": _siglip("B", 16, 224),
    "siglip-base-patch16-256": _siglip("B", 16, 256),
    "siglip-base-patch16-384": _siglip("B", 16, 384),
    "siglip-large-patch16-256": _siglip("L", 16, 256),
    "siglip-large-patch16-384": _siglip("L", 16, 384),
    "siglip-so400m-patch14-384": _siglip("So400m", 14, 384),
    "siglip2-base-patch16-256": _siglip("B", 16, 256, vocab=256000),
    "siglip2-large-patch16-512": _siglip("L", 16, 512, vocab=256000),
    # So400m towers are dimensionally identical to the v1 So400m release
    # (verified against google/siglip-so400m-patch14-384); v2 swaps the
    # tokenizer/vocab (Gemma 256k) and training recipe, not the shapes.
    # (giant-opt is deliberately absent: its asymmetric text tower can't be
    # verified offline — from_pretrained still loads it from the HF config.)
    "siglip2-so400m-patch14-384": _siglip("So400m", 14, 384, vocab=256000),
    "siglip2-so400m-patch16-256": _siglip("So400m", 16, 256, vocab=256000),
}


def preset(name: str, **overrides: Any):
    """Fetch a named preset, optionally overriding top-level fields."""
    cfg = PRESETS[name]
    return dataclasses.replace(cfg, **overrides) if overrides else cfg


# ---------------------------------------------------------------------------
# Adopted runtime: measured-best execution config per preset
# ---------------------------------------------------------------------------

#: Written by `scripts/adopt_sweep.py --apply` from real TPU sweep records
#: (committed with provenance); consumed by the CLI train path and bench.py
#: so presets run the measured-best execution config by default.
ADOPTED_RUNTIME_PATH = (pathlib.Path(__file__).resolve().parent
                        / "adopted_runtime.json")


def _check_runtime_fields(fields: Any) -> None:
    """Raise on anything `with_runtime` would reject or a jit trace would
    choke on minutes in: unknown field names, or out-of-domain values."""
    if not isinstance(fields, dict):
        raise TypeError(f"runtime entry must be a dict, got {type(fields)}")
    bad = set(fields) - RUNTIME_FIELDS
    if bad:
        raise ValueError(f"non-runtime fields {sorted(bad)}")
    def _int_ge(v: Any, lo: int) -> bool:
        return isinstance(v, int) and not isinstance(v, bool) and v >= lo
    for k, v in fields.items():
        ok = True
        if k == "attn_impl":
            from typing import get_args
            ok = v in get_args(AttnImpl)
        elif k == "ln_impl":
            ok = v in ("xla", "fused")
        elif k in ("fused_qkv", "remat", "pipeline"):
            ok = isinstance(v, bool)
        elif k == "remat_policy":
            remat_policy_parts(str(v))  # raises on malformed spec
            ok = isinstance(v, str)
        elif k in ("scan_unroll", "pp_microbatches", "pp_virtual"):
            ok = _int_ge(v, 1)
        elif k == "pp_stages":
            ok = _int_ge(v, 0)
        elif k == "dropout":
            ok = isinstance(v, (int, float)) and 0.0 <= v <= 1.0
        elif k == "precision":
            from typing import get_args
            ok = v in get_args(Precision)
        if not ok:
            raise ValueError(f"bad value for runtime field {k!r}: {v!r}")


def adopted_runtime(preset_name: str) -> dict[str, Any]:
    """Measured-best `with_runtime` kwargs for ``preset_name`` ({} when no
    sweep result has been adopted). Field names are checked against
    RUNTIME_FIELDS and values against their domains; a file that fails
    validation degrades to {} with a warning, so a corrupted or hand-edited
    adopted_runtime.json can neither crash the CLI nor burn a TPU window
    failing deep inside the first jit trace."""
    try:
        data = json.loads(ADOPTED_RUNTIME_PATH.read_text())
        fields = (data.get("presets", {}).get(preset_name, {})
                  .get("runtime", {}))
    except (OSError, json.JSONDecodeError):
        return {}
    except (AttributeError, TypeError) as e:  # valid JSON, wrong containers
        import warnings
        warnings.warn(f"ignoring malformed adopted_runtime.json: {e}",
                      stacklevel=2)
        return {}
    try:
        _check_runtime_fields(fields)
    except (TypeError, ValueError) as e:
        import warnings
        warnings.warn(f"ignoring adopted runtime for {preset_name!r}: {e}",
                      stacklevel=2)
        return {}
    return dict(fields)
