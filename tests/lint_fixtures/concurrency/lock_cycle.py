"""JL018 seed: two locks acquired in opposite orders across methods (the
replan-vs-scheduler deadlock precursor shape) — plus a pair that always
nests in one global order, which must stay clean."""

import threading


class DeadlockPair:
    """`rebalance` takes _plan_lock then _stats_lock; `report` takes
    _stats_lock then _plan_lock: a cycle — two threads entering from
    opposite ends freeze forever. JL018."""

    def __init__(self):
        self._plan_lock = threading.Lock()
        self._stats_lock = threading.Lock()
        self.plan = {}
        self.stats = {}

    def rebalance(self):
        with self._plan_lock:
            with self._stats_lock:
                self.stats["rebalance"] = len(self.plan)

    def report(self):
        with self._stats_lock:
            with self._plan_lock:
                self.plan["reported"] = dict(self.stats)


class OrderedPair:
    """Same two locks, always plan -> stats: clean."""

    def __init__(self):
        self._plan_lock = threading.Lock()
        self._stats_lock = threading.Lock()
        self.plan = {}
        self.stats = {}

    def rebalance(self):
        with self._plan_lock:
            with self._stats_lock:
                self.stats["rebalance"] = len(self.plan)

    def report(self):
        with self._plan_lock:
            with self._stats_lock:
                self.stats["reported"] = len(self.plan)
