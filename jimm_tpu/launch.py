"""Multi-process launcher — the torchrun/mpirun counterpart for jimm_tpu.

The reference scales out via externally-launched MPI/NCCL ranks; the
TPU-native equivalent is one process per host plus `jax.distributed`
(SURVEY §2.3 "collective communication backend"). Cloud TPU pods need no
launcher at all — the TPU runtime starts one process per host and
``initialize_distributed()`` auto-detects. This covers the cases where
nothing spawns those processes for you:

- **local simulation**: N processes x M virtual CPU devices on one machine
  (the exact topology `tests/test_distributed.py` exercises),
- **manual multi-node**: run the same command on every node with its
  ``--node-rank``; node 0's address is the coordinator.

Usage::

    # 2 local processes x 2 virtual CPU devices each (4-device cluster)
    python -m jimm_tpu.launch --nproc 2 --platform cpu --host-devices 2 -- \
        python -m jimm_tpu train --preset siglip-base-patch16-256 ...

    # manual 2-node cluster, one process per node
    python -m jimm_tpu.launch --nnodes 2 --node-rank 0 \
        --coordinator node0:12345 -- python train.py   # on node 0
    python -m jimm_tpu.launch --nnodes 2 --node-rank 1 \
        --coordinator node0:12345 -- python train.py   # on node 1

Children receive ``JIMM_COORDINATOR`` / ``JIMM_NUM_PROCESSES`` /
``JIMM_PROCESS_ID`` (plus ``JIMM_PLATFORM`` / ``JIMM_HOST_DEVICES``
passthrough); a bare ``initialize_distributed()`` — which the CLI calls
automatically — picks them up. Child output is line-prefixed with its
global rank; the first failing child terminates the rest.
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import threading


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _pump(stream, rank: int, out) -> None:
    for line in iter(stream.readline, ""):
        out.write(f"[rank {rank}] {line}")
        out.flush()
    stream.close()


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m jimm_tpu.launch",
        description="Spawn a jax.distributed process group and run CMD in "
                    "every process (everything after `--`).")
    p.add_argument("--nproc", type=int, default=1,
                   help="processes to spawn on THIS node")
    p.add_argument("--nnodes", type=int, default=1,
                   help="total nodes in the cluster (run this launcher on "
                        "each, with its --node-rank)")
    p.add_argument("--node-rank", type=int, default=0)
    p.add_argument("--coordinator", default=None,
                   help="host:port of global process 0 (required when "
                        "--nnodes > 1; defaults to 127.0.0.1:<free port>)")
    p.add_argument("--platform", default=None,
                   help="JIMM_PLATFORM for children (e.g. cpu)")
    p.add_argument("--host-devices", type=int, default=None,
                   help="virtual CPU devices per process (JIMM_HOST_DEVICES)")
    p.add_argument("--restarts", type=int, default=0,
                   help="relaunch the whole group up to N times after a "
                        "failure (preemption, crash); the command should "
                        "be resumable — e.g. include --ckpt-dir and "
                        "--resume, which cold-starts cleanly on the first "
                        "attempt")
    p.add_argument("--restart-backoff-s", type=float, default=1.0,
                   help="base of the jittered exponential backoff between "
                        "group relaunches")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="command to run in every process, after `--`")
    args = p.parse_args(argv)

    cmd = args.cmd[1:] if args.cmd[:1] == ["--"] else args.cmd
    if not cmd:
        p.error("no command given (put it after `--`)")
    if args.nnodes < 1 or not 0 <= args.node_rank < args.nnodes:
        p.error(f"--node-rank {args.node_rank} outside [0, {args.nnodes})")
    if args.nnodes > 1 and not args.coordinator:
        p.error("--coordinator host:port is required with --nnodes > 1")
    if args.nproc < 1:
        p.error("--nproc must be >= 1")
    if args.restarts < 0:
        p.error("--restarts must be >= 0")
    world = args.nnodes * args.nproc
    if world < 2:
        p.error("a 1-process world needs no launcher; run the command "
                "directly")

    from jimm_tpu.resilience import BackoffPolicy
    backoff = BackoffPolicy(base_s=args.restart_backoff_s, max_s=60.0,
                            jitter=0.5)
    import time

    rc = 0
    for attempt in range(args.restarts + 1):
        # a fresh auto-coordinator port per attempt: the previous group's
        # listener may still be in TIME_WAIT
        coordinator = args.coordinator or f"127.0.0.1:{_free_port()}"
        rc = _run_group(args, cmd, coordinator)
        if rc == 0 or rc == 130:  # success, or operator stop — don't retry
            break
        if attempt < args.restarts:
            delay = backoff.delay(attempt)
            print(f"[launch] group failed (rc {rc}); restart "
                  f"{attempt + 1}/{args.restarts} in {delay:.1f}s",
                  file=sys.stderr)
            time.sleep(delay)
    return rc


def _run_group(args, cmd: list[str], coordinator: str) -> int:
    """Spawn one process group, wait it out, and return its exit code
    (first failure wins; 130 = interrupted by the operator)."""
    world = args.nnodes * args.nproc
    procs: list[subprocess.Popen] = []
    pumps: list[threading.Thread] = []
    for local in range(args.nproc):
        rank = args.node_rank * args.nproc + local
        env = dict(os.environ,
                   JIMM_COORDINATOR=coordinator,
                   JIMM_NUM_PROCESSES=str(world),
                   JIMM_PROCESS_ID=str(rank))
        if args.platform:
            env["JIMM_PLATFORM"] = args.platform
        if args.host_devices:
            env["JIMM_HOST_DEVICES"] = str(args.host_devices)
        proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True,
                                bufsize=1)
        procs.append(proc)
        t = threading.Thread(target=_pump, args=(proc.stdout, rank,
                                                 sys.stdout), daemon=True)
        t.start()
        pumps.append(t)

    import time

    state = {"interrupted": False, "kill_at": None}

    def terminate_all(signum=None, frame=None):
        if signum is not None:
            state["interrupted"] = True
        if state["kill_at"] is None:
            # SIGTERM now; escalate to SIGKILL if anything survives 10 s
            # (a rank wedged in uninterruptible I/O or a blocking handler
            # must not hang the launcher forever)
            state["kill_at"] = time.monotonic() + 10
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()

    signal.signal(signal.SIGINT, terminate_all)
    signal.signal(signal.SIGTERM, terminate_all)

    # wait for all; the first failure tears the group down (a dead rank
    # would otherwise hang the rest inside a collective forever)
    rc = 0
    pending = set(range(args.nproc))
    while pending:
        for i in sorted(pending):
            code = procs[i].poll()
            if code is None:
                continue
            pending.discard(i)
            if code and not rc:
                # subprocess reports signal deaths as -signum; shells use
                # 128+signum — keep that convention for CI legibility
                rc = 128 - code if code < 0 else code
                if not state["interrupted"]:
                    print(f"[launch] rank "
                          f"{args.node_rank * args.nproc + i} exited "
                          f"{code}; terminating the group", file=sys.stderr)
                    terminate_all()
            break
        else:
            if state["kill_at"] and time.monotonic() > state["kill_at"]:
                for proc in procs:
                    if proc.poll() is None:
                        proc.kill()
                state["kill_at"] = time.monotonic() + 10
            time.sleep(0.2)
    for t in pumps:
        t.join(timeout=5)
    if state["interrupted"] and not rc:
        return 130  # operator stop, not a rank failure (a failure that
        # preceded the interrupt keeps its code)
    return rc


if __name__ == "__main__":
    sys.exit(main())
