"""int8 quantization: checkpoint rewrite (weights/quantize.py), live model
surgery (jimm_tpu.quant), the serve dtype axis, and the AOT param-dtype
fingerprint that keeps int8 and f32 artifacts apart."""

import json
import pathlib

import numpy as np
import pytest
from flax import nnx

from jimm_tpu import CLIP, preset
from jimm_tpu.cli import _tiny_override
from jimm_tpu.weights.quantize import (SCALE_SUFFIX, default_predicate,
                                       dequantize_state_dict,
                                       dequantize_tensor, is_quantized_state,
                                       load_dequantized, quantize_state_dict,
                                       quantize_tensor, save_quantized)


@pytest.fixture(scope="module")
def tiny_clip():
    cfg = _tiny_override(preset("clip-vit-base-patch16"))
    return cfg, CLIP(cfg, rngs=nnx.Rngs(0))


class TestQuantizeTensor:
    def test_scheme_properties(self):
        rng = np.random.default_rng(0)
        w = rng.normal(size=(16, 33)).astype(np.float32)
        q, scale = quantize_tensor(w)
        assert q.dtype == np.int8 and scale.dtype == np.float32
        assert scale.shape == (16,)
        # symmetric max-abs: each channel's extreme lands exactly on +-127
        assert np.all(np.max(np.abs(q), axis=1) == 127)
        np.testing.assert_allclose(scale, np.max(np.abs(w), axis=1) / 127.0)

    def test_zero_channel_stays_finite(self):
        w = np.zeros((3, 8), np.float32)
        w[1] = 2.0
        q, scale = quantize_tensor(w)
        assert scale[0] == 1.0 and scale[2] == 1.0
        assert np.all(np.isfinite(dequantize_tensor(q, scale)))

    def test_roundtrip_error_bounded_by_half_step(self):
        rng = np.random.default_rng(1)
        w = rng.normal(size=(8, 64)).astype(np.float32)
        q, scale = quantize_tensor(w)
        err = np.abs(dequantize_tensor(q, scale) - w)
        assert np.all(err <= scale[:, None] / 2 + 1e-7)

    def test_requantize_is_bit_stable(self):
        # the max element quantizes to exactly +-127, so a dequantized
        # tensor re-quantizes to the SAME bits and bit-identical scales —
        # repeated rewrite passes cannot drift
        rng = np.random.default_rng(2)
        w = rng.normal(size=(5, 40)).astype(np.float32)
        q1, s1 = quantize_tensor(w)
        q2, s2 = quantize_tensor(dequantize_tensor(q1, s1))
        assert np.array_equal(q1, q2)
        assert np.array_equal(s1, s2)


class TestStateDict:
    def test_predicate_excludes_non_matmul_tensors(self):
        mat = np.ones((4, 4), np.float32)
        assert default_predicate("vision_model.mlp.fc1.weight", mat)
        assert not default_predicate("bias", np.ones((4,), np.float32))
        assert not default_predicate("layer_norm.weight", mat)
        assert not default_predicate(
            "embeddings.position_embedding.weight", mat)
        assert not default_predicate("logit_scale", mat)
        assert not default_predicate("k", np.ones((4, 4), np.int32))

    def test_quantize_dequantize_round_trip(self):
        rng = np.random.default_rng(0)
        state = {"a.weight": rng.normal(size=(8, 16)).astype(np.float32),
                 "a.bias": rng.normal(size=(8,)).astype(np.float32),
                 "norm.weight": rng.normal(size=(16, 16))
                 .astype(np.float32)}
        qstate = quantize_state_dict(state)
        assert is_quantized_state(qstate)
        assert qstate["a.weight"].dtype == np.int8
        assert ("a.weight" + SCALE_SUFFIX) in qstate
        # pass-throughs untouched
        assert np.array_equal(qstate["a.bias"], state["a.bias"])
        assert np.array_equal(qstate["norm.weight"], state["norm.weight"])
        back = dequantize_state_dict(qstate)
        assert set(back) == set(state)
        assert back["a.weight"].dtype == np.float32

    def test_quantize_state_dict_idempotent(self):
        rng = np.random.default_rng(0)
        state = {"w.weight": rng.normal(size=(4, 8)).astype(np.float32)}
        once = quantize_state_dict(state)
        twice = quantize_state_dict(once)
        assert all(np.array_equal(twice[k], once[k]) for k in once)

    def test_safetensors_round_trip_bit_stable(self, tmp_path, tiny_clip):
        from jimm_tpu.weights.safetensors_io import load_file
        _, model = tiny_clip
        save_quantized(model, tmp_path)
        raw = load_file(tmp_path / "model.safetensors")
        assert is_quantized_state(raw)
        assert any(v.dtype == np.int8 for v in raw.values())
        # re-quantizing the dequantized checkpoint reproduces every int8
        # tensor and every scale bit for bit
        requant = quantize_state_dict(dequantize_state_dict(raw))
        assert set(requant) == set(raw)
        assert all(np.array_equal(requant[k], raw[k]) for k in raw)

    def test_save_quantized_stamps_config(self, tmp_path, tiny_clip):
        _, model = tiny_clip
        save_quantized(model, tmp_path)
        config = json.loads(
            pathlib.Path(tmp_path, "config.json").read_text())
        assert config["jimm_quant"]["format"] == "int8-v1"
        assert config["jimm_quant"]["scale_suffix"] == SCALE_SUFFIX
        full = load_dequantized(tmp_path / "model.safetensors")
        assert not is_quantized_state(full)
        assert all(v.dtype != np.int8 for v in full.values())


class TestQuantizeModel:
    def test_counts_and_stays_close(self, tiny_clip):
        from jimm_tpu.quant import QuantLinear, quantize_model
        cfg, model_f32 = tiny_clip
        model_q = CLIP(cfg, rngs=nnx.Rngs(0))
        n = quantize_model(model_q)
        # per tower stack: q/k/v/out + fc1/fc2, plus the two projections
        assert n == 14
        assert isinstance(model_q.visual_projection, QuantLinear)
        x = np.random.RandomState(0).randn(
            2, cfg.vision.image_size, cfg.vision.image_size, 3
        ).astype(np.float32)
        a = np.asarray(model_q.encode_image(x))
        b = np.asarray(model_f32.encode_image(x))
        cos = (a * b).sum(-1) / (np.linalg.norm(a, axis=-1)
                                 * np.linalg.norm(b, axis=-1))
        assert cos.min() > 0.999

    def test_fused_qkv_projections_are_skipped(self):
        from jimm_tpu.nn.transformer import Attention
        from jimm_tpu.quant import QuantLinear, quantize_model
        attn = Attention(64, 2, nnx.Rngs(0), fused_qkv=True)
        n = quantize_model(attn)
        # fused_qkv reads raw .kernel params for the (H, 3H) concat: q/k/v
        # must stay Linear; only the out projection quantizes
        assert n == 1
        assert isinstance(attn.out, QuantLinear)
        assert all(isinstance(getattr(attn, p), nnx.Linear)
                   for p in ("q", "k", "v"))
        x = np.random.RandomState(0).randn(1, 8, 64).astype(np.float32)
        assert np.asarray(attn(x)).shape == (1, 8, 64)


class TestPrecisionPolicy:
    """Training-precision surgery (quant/policy.py): the bf16 / fp8_hybrid
    / int8_qk axis the train CLI exposes as --precision."""

    def test_policy_literal_matches_policies(self):
        from typing import get_args

        from jimm_tpu.configs import Precision
        from jimm_tpu.quant.policy import POLICIES
        assert tuple(get_args(Precision)) == POLICIES

    def test_bf16_is_identity(self):
        from jimm_tpu.nn.transformer import Attention
        from jimm_tpu.quant.policy import apply_precision_policy
        attn = Attention(64, 2, nnx.Rngs(0))
        assert apply_precision_policy(attn, "bf16") == 0
        assert isinstance(attn.q, nnx.Linear)

    def test_fp8_hybrid_shares_master_weights(self):
        from jimm_tpu.nn.transformer import Attention
        from jimm_tpu.quant.policy import Fp8Linear, apply_precision_policy
        attn = Attention(64, 2, nnx.Rngs(0))
        kernel = attn.q.kernel
        n = apply_precision_policy(attn, "fp8_hybrid")
        assert n == 4  # q/k/v/out
        assert isinstance(attn.q, Fp8Linear)
        # the optimizer keeps updating the ORIGINAL Param — surgery must
        # share it, never copy
        assert attn.q.kernel is kernel
        assert attn.q.x_amax[...].shape == (16,)
        x = np.random.RandomState(0).randn(1, 8, 64).astype(np.float32)
        out = np.asarray(attn(x))
        assert out.shape == (1, 8, 64) and np.all(np.isfinite(out))
        # the forward rolled the delayed-scaling histories
        assert float(attn.q.w_amax[...][-1]) > 0

    def test_fused_qkv_projections_stay_linear(self):
        from jimm_tpu.nn.transformer import Attention
        from jimm_tpu.quant.policy import Fp8Linear, apply_precision_policy
        attn = Attention(64, 2, nnx.Rngs(0), fused_qkv=True)
        n = apply_precision_policy(attn, "fp8_hybrid")
        # fused_qkv reads raw .kernel params for the (H, 3H) concat —
        # same eligibility rule as quantize_model
        assert n == 1
        assert isinstance(attn.out, Fp8Linear)
        assert all(isinstance(getattr(attn, p), nnx.Linear)
                   for p in ("q", "k", "v"))

    def test_int8_qk_flips_attention_impl_only(self):
        from jimm_tpu.nn.transformer import Attention
        from jimm_tpu.quant.policy import apply_precision_policy
        attn = Attention(64, 2, nnx.Rngs(0))
        n = apply_precision_policy(attn, "int8_qk")
        assert n == 1 and attn.impl == "flash_int8"
        assert isinstance(attn.q, nnx.Linear)  # linears untouched

    def test_unknown_policy_raises(self):
        from jimm_tpu.quant.policy import apply_precision_policy
        with pytest.raises(ValueError, match="unknown precision policy"):
            apply_precision_policy(nnx.Linear(4, 4, rngs=nnx.Rngs(0)),
                                   "fp4")


class TestServeDtypeAxis:
    def test_bucket_table_carries_dtype(self):
        from jimm_tpu.serve import SERVE_DTYPES, BucketTable
        assert BucketTable((1, 2)).dtype == "float32"
        assert BucketTable((1, 2), dtype="int8").dtype == "int8"
        assert set(SERVE_DTYPES) == {"float32", "bfloat16", "int8"}

    def test_unknown_dtype_rejected(self):
        from jimm_tpu.serve import BucketTable
        with pytest.raises(ValueError, match="serve dtype"):
            BucketTable((1, 2), dtype="fp8")

    def test_default_buckets_pass_dtype_through(self):
        from jimm_tpu.serve import default_buckets
        assert default_buckets("cpu", dtype="int8").dtype == "int8"


class TestAotParamDtype:
    def test_mixed_precision_fingerprint(self, tiny_clip):
        from jimm_tpu.aot.warmup import _model_param_dtype
        from jimm_tpu.quant import quantize_model
        cfg, _ = tiny_clip
        model = CLIP(cfg, rngs=nnx.Rngs(0))
        # plain model: single dtype, same string as the old first-leaf
        # probe — existing store fingerprints stay valid
        assert _model_param_dtype(model) == "float32"
        quantize_model(model)
        # quantized model: aggregated signature, so an int8 serve can
        # never adopt (or be adopted by) the f32 twin's artifacts
        assert _model_param_dtype(model) == "float32+int8"
