"""Pallas TPU flash attention (placeholder: XLA fallback until the kernel
lands)."""

from __future__ import annotations

import jax


def flash_attention(q, k, v, *, is_causal=False):
    return jax.nn.dot_product_attention(q, k, v, is_causal=is_causal)
