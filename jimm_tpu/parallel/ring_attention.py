"""Ring attention: exact attention over sequences sharded across devices.

Long-context sequence/context parallelism (absent from the reference — max
sequence there is 577 vision tokens, SURVEY §2.3). The sequence axis is
sharded over a mesh axis; each device keeps its local query block while
key/value blocks travel around the ring via ``jax.lax.ppermute``. Online
(flash-style) softmax accumulation in fp32 makes the result exact — identical
to full attention — while no device ever materializes the full sequence or
the full attention matrix. Differentiable end-to-end through the
``lax.scan``-of-``ppermute`` (JAX AD transposes the permutes).

Complements the Pallas flash kernel (`jimm_tpu/ops/flash_attention.py`):
flash blocks *within* a chip, the ring blocks *across* chips; compose them by
passing ``impl="flash"`` so each local block product uses the kernel.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _block(q, k, v, mask):
    """One (q-block x kv-block) partial attention: returns unnormalized
    accumulator pieces (m, p_sum, pv) in fp32. Shapes (B, Sq, N, D)."""
    d = q.shape[-1]
    qf = q.astype(jnp.float32) / jnp.sqrt(d)
    s = jnp.einsum("bqnd,bknd->bnqk", qf, k.astype(jnp.float32))
    s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1)  # (B, N, Sq)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    pv = jnp.einsum("bnqk,bknd->bqnd", p, v.astype(jnp.float32))
    return m, l, pv


def _ring_local_flash(q, k, v, *, axis_name: str, causal: bool = False):
    """Ring step where each local (q x kv-chunk) product is the Pallas flash
    kernel (`flash_attention_lse`); chunk results are merged by logsumexp
    reweighting.

    Causal decomposes per chunk pair (block-causal ring attention): the OWN
    chunk is a causal flash call (q/k positions align), chunks from EARLIER
    ring owners attend in full, and later owners' chunks are skipped
    entirely (``lax.cond`` keeps the carry) — no masked flops, and the skip
    halves the average work like the dense causal case."""
    from jimm_tpu.ops.flash_attention import flash_attention_lse

    n_dev = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, sq, n, d = q.shape
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    def combine(k_cur, v_cur, lse, acc, *, is_causal=False):
        o_blk, lse_blk = flash_attention_lse(q, k_cur, v_cur,
                                             is_causal=is_causal)
        lse_new = jnp.logaddexp(lse, lse_blk)
        w_old = jnp.exp(lse - lse_new).transpose(0, 2, 1)[..., None]
        w_blk = jnp.exp(lse_blk - lse_new).transpose(0, 2, 1)[..., None]
        return lse_new, acc * w_old + o_blk.astype(jnp.float32) * w_blk

    # own chunk first (the only causal-masked pair), then n_dev-1
    # permute+combine steps — no wasted final permute
    lse0 = jnp.full((b, n, sq), NEG_INF, jnp.float32)
    acc0 = jnp.zeros((b, sq, n, d), jnp.float32)
    lse, acc = combine(k, v, lse0, acc0, is_causal=causal)

    def step(carry, j):
        k_cur, v_cur, lse, acc = carry
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        if causal:
            src = (idx - j) % n_dev  # ring owner of this kv chunk
            lse, acc = jax.lax.cond(
                src < idx,  # strictly earlier positions: full attention
                lambda args: combine(k_cur, v_cur, *args),
                lambda args: args,
                (lse, acc))
        else:
            lse, acc = combine(k_cur, v_cur, lse, acc)
        return (k_cur, v_cur, lse, acc), None

    (_, _, _, acc), _ = jax.lax.scan(step, (k, v, lse, acc),
                                     jnp.arange(1, n_dev))
    return acc.astype(q.dtype)


def _ring_local(q, k, v, *, axis_name: str, causal: bool):
    n_dev = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, sq, n, d = q.shape
    sk = k.shape[1]

    q_pos = idx * sq + jnp.arange(sq)

    def combine(j, k_cur, v_cur, m, l, acc):
        src = (idx - j) % n_dev  # ring owner of the current kv chunk
        k_pos = src * sk + jnp.arange(sk)
        mask = jnp.ones((sq, sk), bool)
        if causal:
            mask = k_pos[None, :] <= q_pos[:, None]
        m_blk, l_blk, pv_blk = _block(q, k_cur, v_cur,
                                      mask[None, None])  # (B,N,Sq[,D])
        m_new = jnp.maximum(m, m_blk)
        c_old = jnp.exp(m - m_new)
        c_blk = jnp.exp(m_blk - m_new)
        l_new = l * c_old + l_blk * c_blk
        acc_new = (acc * c_old.transpose(0, 2, 1)[..., None]
                   + pv_blk * c_blk.transpose(0, 2, 1)[..., None])
        return m_new, l_new, acc_new

    def step(carry, j):
        k_cur, v_cur, m, l, acc = carry
        m, l, acc = combine(j, k_cur, v_cur, m, l, acc)
        perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, m, l, acc), None

    m0 = jnp.full((b, n, sq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, n, sq), jnp.float32)
    acc0 = jnp.zeros((b, sq, n, d), jnp.float32)
    # n_dev-1 permuting steps, then the final chunk without the last permute
    (k, v, m, l, acc), _ = jax.lax.scan(step, (k, v, m0, l0, acc0),
                                        jnp.arange(n_dev - 1))
    m, l, acc = combine(n_dev - 1, k, v, m, l, acc)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = acc / l_safe.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   mesh: Mesh | None = None, axis_name: str = "seq",
                   is_causal: bool = False, impl: str = "einsum") -> jax.Array:
    """Exact attention over ``(B, S, N, D)`` q/k/v whose sequence dim is
    sharded over ``axis_name``. Equals full (unsharded) attention to fp32
    accuracy.

    ``mesh=None`` uses the ambient mesh installed by
    ``jimm_tpu.parallel.use_sharding`` / ``jax.set_mesh``.

    ``impl="flash"`` runs each local (q x kv-chunk) product through the
    Pallas flash kernel and merges chunks by logsumexp reweighting — flash
    blocks within the chip, the ring blocks across chips; causal runs
    block-causally (own chunk causal, earlier chunks full, later skipped).
    ``impl="auto"`` picks flash on TPU, einsum otherwise.
    """
    if mesh is None:
        # Works both outside and inside jit: the abstract mesh mirrors the
        # ambient concrete mesh installed by use_sharding/jax.set_mesh, and
        # shard_map binds the concrete one itself when no mesh is passed.
        ambient = jax.sharding.get_abstract_mesh()
        if ambient is None or ambient.empty:
            raise ValueError("ring_attention: no mesh given and no ambient "
                             "mesh installed (use use_sharding(mesh, ...))")
        if axis_name not in ambient.shape:
            raise ValueError(f"ambient mesh {dict(ambient.shape)} has no "
                             f"{axis_name!r} axis")
    elif axis_name not in mesh.shape:
        raise ValueError(f"mesh {dict(mesh.shape)} has no {axis_name!r} axis")
    if impl == "auto":
        # Same shape gate as dot_product_attention's auto path: the Pallas
        # kernel is validated for head_dim 64/128/256 and per-chip chunks
        # worth blocking; everything else takes the einsum path.
        shape = dict((mesh or jax.sharding.get_abstract_mesh()).shape)
        local_seq = q.shape[1] // shape[axis_name]
        flash_ok = (jax.default_backend() == "tpu"
                    and q.shape[-1] in (64, 128, 256) and local_seq >= 128)
        impl = "flash" if flash_ok else "einsum"
    if impl == "flash":
        local = partial(_ring_local_flash, axis_name=axis_name,
                        causal=is_causal)
    elif impl == "einsum":
        local = partial(_ring_local, axis_name=axis_name, causal=is_causal)
    else:
        raise ValueError(f"unknown ring attention impl {impl!r}")
    kwargs = {} if mesh is None else {"mesh": mesh}  # None -> ambient mesh
    fn = shard_map(
        local,
        in_specs=(P(None, axis_name), P(None, axis_name), P(None, axis_name)),
        out_specs=P(None, axis_name),
        check_vma=False, **kwargs)
    return fn(q, k, v)
