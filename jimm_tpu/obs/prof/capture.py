"""Windowed ``jax.profiler`` capture manager: continuous ring + deep capture.

Two capture kinds, one on-disk ring:

- **window** — the always-on continuous profiler. ``on_step(step)`` (train)
  opens a short capture every ``every_steps`` steps and commits it after
  ``window_steps``; between captures the hook is one integer compare, which
  is how the ring holds its ≤1% step-time overhead budget.
- **deep** — anomaly-triggered. ``trigger(cid=...)`` opens a longer capture
  tagged with the incident's flight-recorder correlation id and commits it
  on a timer (serve incidents have no step boundary), emitting
  ``prof_capture_started`` / ``prof_capture_committed`` journal events on
  that cid so the capture joins the incident chain.

Ring discipline (journal-style rotation, AOT-store atomicity):

- a capture records into ``cap-NNNNNN-<kind>.tmp/``; commit writes
  ``meta.json`` (tmp file + ``os.replace``) then renames the whole dir to
  ``cap-NNNNNN-<kind>/`` — readers only ever see complete captures;
- committed captures are evicted oldest-first once the ring exceeds its
  hard byte budget;
- a capture that fails to stop, or a leftover ``.tmp`` dir from a crash,
  is moved under ``quarantine/`` with a reason file — **never deleted** —
  so evidence of a broken profiler run survives for a human.

Only this module (and the :func:`profiler_session` primitive below) may
call ``jax.profiler.start_trace``/``stop_trace`` — lint rule JL022 fences
every other call site, because a bypass would race the process-wide
profiler session and escape the byte budget. jax is imported lazily so the
``obs prof ls/show/diff`` CLI stays jax-free.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
from contextlib import contextmanager
from pathlib import Path

from jimm_tpu.obs.journal import get_journal, new_correlation_id
from jimm_tpu.obs.registry import get_registry

__all__ = [
    "CaptureManager", "configure_capture", "get_capture_manager",
    "list_captures", "maybe_trigger", "profiler_session", "reset_capture",
]

META_NAME = "meta.json"
_PREFIX = "cap-"
_TMP_SUFFIX = ".tmp"

#: process-wide profiler session lock: jax allows exactly one active trace,
#: so every sanctioned entry point serializes on this.
_SESSION_LOCK = threading.Lock()


class _JaxProfiler:
    """Default backend: the real ``jax.profiler`` (imported lazily so the
    module itself stays importable without jax)."""

    def start(self, log_dir: str) -> None:
        import jax
        jax.profiler.start_trace(log_dir)  # jaxlint: disable=JL022 — the sanctioned home: CaptureManager/profiler_session route every capture here

    def stop(self) -> None:
        import jax
        jax.profiler.stop_trace()  # jaxlint: disable=JL022 — sanctioned home (see start)


@contextmanager
def profiler_session(log_dir: str | Path):
    """The ONE raw trace primitive outside :class:`CaptureManager`: capture
    the enclosed region into ``log_dir``, holding the process-wide session
    lock so a one-shot ``--profile-dir`` trace and the continuous ring can
    never double-start the profiler. Library code goes through this (or a
    manager) — never ``jax.profiler.start_trace`` directly (JL022)."""
    Path(log_dir).mkdir(parents=True, exist_ok=True)
    prof = _JaxProfiler()
    with _SESSION_LOCK:
        prof.start(str(log_dir))
        try:
            yield
        finally:
            prof.stop()


def _dir_bytes(root: Path) -> int:
    total = 0
    for base, _dirs, files in os.walk(root):
        for name in files:
            try:
                total += os.path.getsize(os.path.join(base, name))
            except OSError:
                pass
    return total


def _read_meta(cap_dir: Path) -> dict | None:
    try:
        with open(cap_dir / META_NAME) as f:
            meta = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    return meta if isinstance(meta, dict) else None


def list_captures(root: str | Path) -> list[dict]:
    """Committed capture metas under ``root``, oldest first. jax-free —
    this is what ``obs prof ls`` and the timeline exporter read."""
    root = Path(root)
    out = []
    if not root.is_dir():
        return out
    for entry in sorted(root.iterdir()):
        if not entry.name.startswith(_PREFIX) \
                or entry.name.endswith(_TMP_SUFFIX) or not entry.is_dir():
            continue
        meta = _read_meta(entry)
        if meta is not None:
            meta = dict(meta, path=str(entry))
            out.append(meta)
    out.sort(key=lambda m: m.get("seq", 0))
    return out


class CaptureManager:
    """Owns one capture ring rooted at ``root``.

    Args:
        root: ring directory (created; ``quarantine/`` lives under it).
        max_ring_bytes: hard byte budget for committed captures — commit
            evicts oldest-first past this.
        every_steps: continuous mode — open a window capture every N steps
            (0 disables the ring; ``trigger`` still works).
        window_steps: steps per window capture.
        deep_window_s: wall-clock length of a triggered deep capture
            (committed by a timer thread — serve incidents have no steps).
        min_trigger_interval_s: deep-capture rate limit; triggers inside
            the interval are counted as suppressed, not captured.
        journal: explicit :class:`EventJournal` (default: process global).
        profiler: injectable start/stop backend (tests); default jax.
    """

    def __init__(self, root: str | Path, *, max_ring_bytes: int = 64 << 20,
                 every_steps: int = 200, window_steps: int = 2,
                 deep_window_s: float = 1.5,
                 min_trigger_interval_s: float = 10.0,
                 journal=None, profiler=None):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.quarantine_dir = self.root / "quarantine"
        self.max_ring_bytes = int(max_ring_bytes)
        self.every_steps = int(every_steps)
        self.window_steps = max(1, int(window_steps))
        self.deep_window_s = float(deep_window_s)
        self.min_trigger_interval_s = float(min_trigger_interval_s)
        self._journal = journal
        self._profiler = profiler or _JaxProfiler()
        self._lock = threading.RLock()
        self._active: dict | None = None
        self._timer: threading.Timer | None = None
        self._last_trigger_mono: float | None = None
        self._triggered_cids: set[str] = set()
        reg = get_registry("jimm_prof")
        self._captures_total = reg.counter("captures_total")
        self._deep_total = reg.counter("deep_captures_total")
        self._evicted_total = reg.counter("evicted_total")
        self._quarantined_total = reg.counter("quarantined_total")
        self._suppressed_total = reg.counter("trigger_suppressed_total")
        self._failed_total = reg.counter("capture_failures_total")
        self._overhead = reg.counter("overhead_seconds_total")
        reg.gauge("ring_bytes", self.ring_bytes)
        reg.gauge("capture_active",
                  lambda: 1.0 if self._active is not None else 0.0)
        # crash recovery: count what already committed, quarantine
        # leftover .tmp dirs (a crash mid-capture), never delete them
        self._entries: list[dict] = [
            {"seq": m.get("seq", 0), "path": Path(m["path"]),
             "bytes": int(m.get("bytes", 0))}
            for m in list_captures(self.root)]
        self._seq = max([e["seq"] for e in self._entries], default=0)
        for entry in sorted(self.root.iterdir()):
            if entry.name.startswith(_PREFIX) \
                    and entry.name.endswith(_TMP_SUFFIX):
                self._quarantine(entry, "incomplete capture (crash?)")

    # -- journal/metrics helpers ------------------------------------------

    def _emit(self, event: str, *, cid: str | None = None, **fields):
        journal = self._journal if self._journal is not None \
            else get_journal()
        return journal.emit(event, cid=cid, **fields)

    def ring_bytes(self) -> float:
        """Committed bytes currently in the ring (quarantine excluded)."""
        with self._lock:
            return float(sum(e["bytes"] for e in self._entries))

    # -- capture lifecycle ------------------------------------------------

    def start(self, kind: str, *, cid: str | None = None,
              reason: str | None = None, step: int | None = None,
              window_s: float | None = None) -> dict | None:
        """Open a capture. Returns its (in-progress) meta, or None when a
        capture is already active or the profiler session is held
        elsewhere (a one-shot ``profiler_session`` in flight)."""
        t0 = time.perf_counter()
        with self._lock:
            if self._active is not None:
                return None
            if not _SESSION_LOCK.acquire(blocking=False):
                return None
            self._seq += 1
            name = f"{_PREFIX}{self._seq:06d}-{kind}"
            tmp = self.root / (name + _TMP_SUFFIX)
            try:
                tmp.mkdir(parents=True, exist_ok=True)
                self._profiler.start(str(tmp))
            except Exception as e:  # noqa: BLE001 — a broken profiler must never take down the serving/training process; the failure is counted, journaled, and quarantined
                _SESSION_LOCK.release()
                self._failed_total.inc()
                self._emit("prof_capture_failed", cid=cid, kind=kind,
                           error=f"{type(e).__name__}: {e}")
                if tmp.exists():
                    self._quarantine(tmp, f"start failed: {e}")
                return None
            meta = {"seq": self._seq, "name": name, "kind": kind,
                    "cid": cid, "reason": reason, "step": step,
                    "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
                    "start_mono": round(time.monotonic(), 6)}
            if window_s is not None:
                meta["window_s"] = window_s
            self._active = dict(meta, _dir=tmp)
            self._emit("prof_capture_started", cid=cid, kind=kind,
                       capture=name, reason=reason, step=step)
            if kind == "deep":
                self._deep_total.inc()
                self._timer = threading.Timer(
                    window_s if window_s is not None else self.deep_window_s,
                    self.commit)
                self._timer.daemon = True
                self._timer.start()
        self._overhead.inc(time.perf_counter() - t0)
        return meta

    def commit(self) -> dict | None:
        """Stop the active capture, finalize it atomically into the ring,
        journal ``prof_capture_committed`` (with ``dur_s`` so the timeline
        renders the window), and enforce the byte budget."""
        t0 = time.perf_counter()
        with self._lock:
            act = self._active
            if act is None:
                return None
            self._active = None
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            tmp = act.pop("_dir")
            try:
                self._profiler.stop()
            except Exception as e:  # noqa: BLE001 — see start(): a failed stop quarantines the evidence instead of crashing the host process
                _SESSION_LOCK.release()
                self._failed_total.inc()
                self._emit("prof_capture_failed", cid=act.get("cid"),
                           kind=act["kind"], capture=act["name"],
                           error=f"{type(e).__name__}: {e}")
                self._quarantine(tmp, f"stop failed: {e}")
                return None
            _SESSION_LOCK.release()
            end = time.monotonic()
            meta = {k: v for k, v in act.items()}
            meta["end_mono"] = round(end, 6)
            meta["dur_s"] = round(end - meta["start_mono"], 6)
            meta["bytes"] = _dir_bytes(tmp)
            final = self.root / meta["name"]
            try:
                tmp_meta = tmp / (META_NAME + _TMP_SUFFIX)
                with open(tmp_meta, "w") as f:
                    json.dump(meta, f, indent=2, sort_keys=True)
                    f.write("\n")
                os.replace(tmp_meta, tmp / META_NAME)
                os.replace(tmp, final)
            except OSError as e:
                self._failed_total.inc()
                self._quarantine(tmp, f"commit failed: {e}")
                return None
            self._entries.append({"seq": meta["seq"], "path": final,
                                  "bytes": meta["bytes"]})
            self._captures_total.inc()
            self._emit("prof_capture_committed", cid=meta.get("cid"),
                       kind=meta["kind"], capture=meta["name"],
                       bytes=meta["bytes"], dur_s=meta["dur_s"],
                       step=meta.get("step"))
            self._enforce_budget()
        self._overhead.inc(time.perf_counter() - t0)
        return meta

    def _enforce_budget(self) -> None:
        # oldest-first eviction, always keeping the newest capture even
        # when it alone exceeds the budget (a ring that can hold nothing
        # is useless; the budget bounds accumulation, not one artifact)
        total = sum(e["bytes"] for e in self._entries)
        while total > self.max_ring_bytes and len(self._entries) > 1:
            old = self._entries.pop(0)
            shutil.rmtree(old["path"], ignore_errors=True)
            total -= old["bytes"]
            self._evicted_total.inc()

    def _quarantine(self, path: Path, reason: str) -> None:
        """Corrupt/incomplete capture: move aside, never delete."""
        self.quarantine_dir.mkdir(parents=True, exist_ok=True)
        dest = self.quarantine_dir / path.name
        i = 0
        while dest.exists():
            i += 1
            dest = self.quarantine_dir / f"{path.name}.{i}"
        try:
            os.replace(path, dest)
            with open(dest / "QUARANTINE_REASON.txt", "w") as f:
                f.write(reason + "\n")
        except OSError:
            return
        self._quarantined_total.inc()

    # -- continuous mode (train step hook) --------------------------------

    def on_step(self, step: int) -> None:
        """Per-step hook for the continuous ring. Fast path (no capture
        active, not a capture step) is one modulo + compares."""
        act = self._active
        if act is not None:
            if act["kind"] == "window" \
                    and step - (act.get("step") or 0) >= self.window_steps:
                self.commit()
            return
        if self.every_steps <= 0:
            return
        # offset 2 into each period: past the compile step and the first
        # post-restore step, matching the --profile-dir window choice
        if step % self.every_steps == 2 % self.every_steps and step > 0:
            self.start("window", step=step)

    def flush(self) -> dict | None:
        """Commit whatever is active (end-of-run / engine shutdown)."""
        return self.commit()

    # -- anomaly trigger --------------------------------------------------

    def trigger(self, cid: str | None = None, reason: str | None = None,
                *, window_s: float | None = None) -> dict | None:
        """Deep capture on an incident. Rate-limited (one per
        ``min_trigger_interval_s``) and deduped per cid — heal, replan, and
        SLO burn often fire on the same incident within milliseconds, and
        one deep capture per incident is the useful artifact. An active
        *window* capture is committed first; an active *deep* capture
        suppresses the trigger."""
        with self._lock:
            now = time.monotonic()
            if cid is not None and cid in self._triggered_cids:
                self._suppressed_total.inc()
                return None
            if self._last_trigger_mono is not None and \
                    now - self._last_trigger_mono \
                    < self.min_trigger_interval_s:
                self._suppressed_total.inc()
                return None
            if self._active is not None:
                if self._active["kind"] == "deep":
                    self._suppressed_total.inc()
                    return None
                self.commit()
            cid = cid or new_correlation_id()
            meta = self.start("deep", cid=cid, reason=reason,
                              window_s=window_s)
            if meta is not None:
                self._last_trigger_mono = now
                self._triggered_cids.add(cid)
                if len(self._triggered_cids) > 1024:
                    # cid dedup is per recent incident, not forever
                    self._triggered_cids = set(list(
                        self._triggered_cids)[-256:])
            return meta

    def ls(self) -> list[dict]:
        return list_captures(self.root)

    def close(self) -> None:
        self.flush()


# ---------------------------------------------------------------------------
# process-global manager (env: JIMM_PROF_DIR) — the wiring surface the
# serve engine / SLO listener / goodput advisor hang their triggers on
# ---------------------------------------------------------------------------

_global_manager: CaptureManager | None = None
_env_checked = False


def configure_capture(root: str | Path, **kwargs) -> CaptureManager:
    """Install the process-global capture manager (``--prof-dir`` flags and
    smokes call this; ``JIMM_PROF_DIR`` configures it implicitly)."""
    global _global_manager, _env_checked
    _global_manager = CaptureManager(root, **kwargs)
    _env_checked = True
    return _global_manager


def get_capture_manager() -> CaptureManager | None:
    """The global manager, auto-configured from ``JIMM_PROF_DIR`` on first
    call; None when profiling is not enabled (the common case — every
    trigger site must tolerate it)."""
    global _env_checked, _global_manager
    if _global_manager is None and not _env_checked:
        _env_checked = True
        root = os.environ.get("JIMM_PROF_DIR")
        if root:
            _global_manager = CaptureManager(root)
    return _global_manager


def maybe_trigger(cid: str | None = None, reason: str | None = None,
                  *, window_s: float | None = None) -> dict | None:
    """Trigger a deep capture iff a global manager is configured — the
    no-op-by-default hook incident paths call unconditionally."""
    mgr = get_capture_manager()
    if mgr is None:
        return None
    try:
        return mgr.trigger(cid, reason, window_s=window_s)
    except Exception:  # noqa: BLE001 — profiling is observability: it must never convert an incident into a crash
        return None


def reset_capture() -> None:
    """Drop the global manager (tests)."""
    global _global_manager, _env_checked
    if _global_manager is not None:
        try:
            _global_manager.flush()
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass
    _global_manager = None
    _env_checked = False
