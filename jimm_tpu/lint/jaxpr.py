"""Layer 1.5 (``--jaxpr``): jaxpr-level invariants between the AST rules
and the lowered-HLO checks.

``jax.make_jaxpr`` abstractly traces each registered entry point — no
compile, no execution, seconds not minutes — and asserts properties the
AST can't see and program text makes awkward:

- **JLT104** f32 promotion drift in low-precision paths: inside the
  ``fp8_hybrid`` / ``int8_qk`` policy-rewritten forwards, count
  ``convert_element_type`` equations promoting a low-precision operand
  (int8 / fp8 / bf16) to f32, plus weak-typed f32 results (a Python
  scalar leaking into the traced graph). Each entry commits a budget in
  the goldens file; drift above it means the quantized path silently
  re-materializes wide tiles — the dynamic complement of JL012/JL016.
- **JLT105** trace-time-baked host constants: a serve forward whose
  closed jaxpr carries a large ndarray const re-embeds that array in
  every process's compile — the recompile-per-process hazard the AOT
  store cannot fingerprint away, because the bytes live in the program.
  State must enter as arguments.
- **JLT106** collective count drift: the number of ``psum`` /
  ``all_gather`` / ``reduce_scatter`` / ... equations per entry point is
  compared to the committed golden (``jaxpr_goldens.json``). A collective
  appearing (or vanishing) without the golden being updated is a sharding
  regression, not a refactor.

Entry points and goldens are injectable for tests; exceptions surface as
JLT000 findings like the trace layer's.
"""

from __future__ import annotations

import json
import pathlib

from jimm_tpu.lint.core import ERROR, WARNING, Finding

__all__ = ["ENTRY_POINTS", "GOLDENS_PATH", "run_jaxpr_checks",
           "collective_counts", "f32_promotions", "update_goldens"]

GOLDENS_PATH = pathlib.Path(__file__).resolve().parent \
    / "jaxpr_goldens.json"

#: dtypes whose promotion to f32 JLT104 counts against the budget
LOWP_DTYPES = frozenset({"int8", "float8_e4m3fn", "float8_e5m2", "bfloat16"})

#: cross-device collective primitives JLT106 tracks
COLLECTIVE_PRIMITIVES = frozenset({
    "psum", "psum2", "all_gather", "reduce_scatter", "ppermute",
    "all_to_all", "pmax", "pmin", "axis_index"})

#: a const bigger than this (bytes) is "baked", not a tolerable epsilon
CONST_BUDGET_BYTES = 1024

_TINY = dict(image_size=16, patch_size=8, width=32, depth=2, num_heads=2,
             mlp_dim=64)


# ---------------------------------------------------------------------------
# registered entry points: name -> () -> (fn, args) for jax.make_jaxpr
# ---------------------------------------------------------------------------

def _vit_state_forward(policy: str):
    """Tiny ViT forward with state passed as an ARGUMENT (the shape every
    serve forward must have), optionally policy-rewritten."""
    import jax.numpy as jnp
    from flax import nnx

    from jimm_tpu import VisionTransformer, ViTConfig, VisionConfig
    from jimm_tpu.quant.policy import apply_precision_policy

    cfg = ViTConfig(vision=VisionConfig(**_TINY), num_classes=4)
    model = VisionTransformer(cfg, rngs=nnx.Rngs(0))
    if policy != "bf16":
        apply_precision_policy(model, policy)
    graphdef, state = nnx.split(model)

    def forward(state, images):
        return nnx.merge(graphdef, state)(images)

    return forward, (state, jnp.zeros((2, 16, 16, 3), jnp.float32))


def _entry_serve_forward():
    return _vit_state_forward("bf16")


def _entry_fp8_hybrid():
    return _vit_state_forward("fp8_hybrid")


def _entry_int8_qk():
    return _vit_state_forward("int8_qk")


def _entry_data_parallel_psum():
    """shard_map data-parallel loss: the one entry that SHOULD carry a
    collective — exactly one psum — so JLT106 pins the count from both
    sides."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    try:
        from jax.experimental.shard_map import shard_map
    except ImportError:  # newer jax: promoted out of experimental
        from jax.sharding import shard_map  # type: ignore[attr-defined]

    devices = jax.devices()
    mesh = Mesh(np.array(devices).reshape(len(devices)), ("data",))

    def mean_loss(x):
        def shard_loss(xs):
            local = jnp.sum(xs * xs)
            return jax.lax.psum(local, "data")

        return shard_map(shard_loss, mesh=mesh, in_specs=P("data"),
                         out_specs=P())(x)

    return mean_loss, (jnp.zeros((len(devices) * 2, 4), jnp.float32),)


ENTRY_POINTS = {
    "serve_forward_vit": _entry_serve_forward,
    "precision_fp8_hybrid": _entry_fp8_hybrid,
    "precision_int8_qk": _entry_int8_qk,
    "data_parallel_psum": _entry_data_parallel_psum,
}


def _jaxpr_path(entry: str) -> str:
    return f"<jaxpr:{entry}>"


# ---------------------------------------------------------------------------
# jaxpr walkers
# ---------------------------------------------------------------------------

def _sub_jaxprs(eqn):
    for v in eqn.params.values():
        for item in (v if isinstance(v, (list, tuple)) else [v]):
            if hasattr(item, "eqns"):  # raw Jaxpr (e.g. shard_map body)
                yield item
            elif hasattr(item, "jaxpr"):  # ClosedJaxpr (e.g. pjit body)
                yield item.jaxpr

def _walk_eqns(jaxpr):
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn):
            yield from _walk_eqns(sub)


def collective_counts(closed_jaxpr) -> dict[str, int]:
    """Histogram of collective primitives, recursing into sub-jaxprs
    (pjit/shard_map/scan bodies)."""
    counts: dict[str, int] = {}
    for eqn in _walk_eqns(closed_jaxpr.jaxpr):
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMITIVES:
            counts[name] = counts.get(name, 0) + 1
    return counts


def f32_promotions(closed_jaxpr) -> tuple[int, int]:
    """(low-precision -> f32 convert count, weak-typed f32 result count)
    across the whole jaxpr."""
    promos = 0
    weak = 0
    for eqn in _walk_eqns(closed_jaxpr.jaxpr):
        if eqn.primitive.name == "convert_element_type":
            src = getattr(getattr(eqn.invars[0], "aval", None), "dtype", None)
            dst = eqn.params.get("new_dtype")
            if str(dst) == "float32" and str(src) in LOWP_DTYPES:
                promos += 1
        for out in eqn.outvars:
            aval = getattr(out, "aval", None)
            if getattr(aval, "weak_type", False) \
                    and str(getattr(aval, "dtype", "")) == "float32":
                weak += 1
    return promos, weak


def _big_consts(closed_jaxpr) -> list[tuple]:
    out = []
    for const in closed_jaxpr.consts:
        nbytes = getattr(const, "nbytes", 0)
        if nbytes and nbytes > CONST_BUDGET_BYTES:
            out.append((tuple(getattr(const, "shape", ())),
                        str(getattr(const, "dtype", "?")), int(nbytes)))
    return out


# ---------------------------------------------------------------------------
# checks
# ---------------------------------------------------------------------------

def _load_goldens(path=None) -> dict:
    p = pathlib.Path(path) if path is not None else GOLDENS_PATH
    try:
        return json.loads(p.read_text())
    except (OSError, ValueError):
        return {}


def _check_entry(entry: str, make, golden: dict | None) -> list[Finding]:
    import jax

    fn, args = make()
    closed = jax.make_jaxpr(fn)(*args)
    findings: list[Finding] = []
    path = _jaxpr_path(entry)

    # JLT104 — promotion drift vs committed budget
    promos, weak = f32_promotions(closed)
    if golden is not None and "f32_promotions" in golden:
        budget = int(golden["f32_promotions"])
        if promos + weak > budget:
            findings.append(Finding(
                "JLT104", ERROR, path, 0,
                f"{promos} low-precision->f32 promotions + {weak} "
                f"weak-typed f32 results exceed the committed budget of "
                f"{budget} — the quantized path is re-materializing wide "
                f"values (or a Python scalar leaked into the trace); fix "
                f"the promotion or update jaxpr_goldens.json with the "
                f"reviewed new budget"))

    # JLT105 — trace-time-baked host constants
    for shape, dtype, nbytes in _big_consts(closed):
        findings.append(Finding(
            "JLT105", ERROR, path, 0,
            f"trace-time constant {dtype}{list(shape)} ({nbytes} bytes) "
            f"is baked into the jaxpr — closed-over host arrays recompile "
            f"per process and defeat AOT-store fingerprinting; pass the "
            f"array as an argument (donated state), not a closure"))

    # JLT106 — collective count drift vs golden
    counts = collective_counts(closed)
    if golden is None or "collectives" not in golden:
        findings.append(Finding(
            "JLT106", WARNING, path, 0,
            f"no committed collective golden for entry `{entry}` "
            f"(observed {counts or '{}'}) — run `python -m jimm_tpu.lint "
            f"--jaxpr --update-goldens` and commit jaxpr_goldens.json"))
    elif counts != dict(golden["collectives"]):
        findings.append(Finding(
            "JLT106", ERROR, path, 0,
            f"collective counts drifted: observed {counts or '{}'} vs "
            f"committed {golden['collectives']} — a collective appeared or "
            f"vanished without review; fix the sharding or update "
            f"jaxpr_goldens.json deliberately"))
    return findings


def run_jaxpr_checks(entry_points: dict | None = None,
                     goldens: dict | None = None) -> list[Finding]:
    """Run JLT104–JLT106 over every entry point (default: the registered
    set, with goldens from :data:`GOLDENS_PATH`). Exceptions become JLT000
    findings — a broken trace is a finding, not a linter crash."""
    from jimm_tpu.utils.env import set_host_device_count

    try:  # must land before the XLA backend initializes; no-op after
        set_host_device_count(8)
    except RuntimeError:
        pass

    entries = ENTRY_POINTS if entry_points is None else entry_points
    all_goldens = _load_goldens() if goldens is None else goldens
    findings: list[Finding] = []
    for entry, make in entries.items():
        try:
            findings.extend(_check_entry(entry, make,
                                         all_goldens.get(entry)))
        except Exception as e:  # noqa: BLE001 — surface, don't crash
            findings.append(Finding(
                "JLT000", ERROR, _jaxpr_path(entry), 0,
                f"jaxpr check raised {type(e).__name__}: {e}"))
    return findings


def update_goldens(path=None) -> dict:
    """Re-trace every registered entry point and write the observed
    collective counts and promotion budgets to the goldens file. Returns
    the written mapping."""
    import jax

    from jimm_tpu.utils.env import set_host_device_count

    try:
        set_host_device_count(8)
    except RuntimeError:
        pass
    out: dict[str, dict] = {}
    for entry, make in ENTRY_POINTS.items():
        fn, args = make()
        closed = jax.make_jaxpr(fn)(*args)
        promos, weak = f32_promotions(closed)
        out[entry] = {"collectives": collective_counts(closed),
                      "f32_promotions": promos + weak}
    p = pathlib.Path(path) if path is not None else GOLDENS_PATH
    p.write_text(json.dumps(out, indent=2, sort_keys=True) + "\n")
    return out
